"""Epoch-level training loop shared by the CIFAR and ImageNet harnesses.

The framework equivalent of ``run_batches`` / ``train_epoch`` / ``train``
(`CIFAR10/core.py:303-341`): the per-batch body is entirely inside the jitted
train step, so the host loop only feeds batches and accumulates the already
globally-reduced metrics.
"""

from __future__ import annotations

import contextlib
import os
from typing import Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_compressed_dp.train.state import TrainState
from tpu_compressed_dp.utils.loggers import MetricAccumulator
from tpu_compressed_dp.utils.timer import Timer

__all__ = ["pad_batch", "run_train_epoch", "run_eval", "train_epoch",
           "comm_summary", "guard_summary", "control_summary",
           "fabric_gauges",
           "add_robustness_args", "add_adaptive_args", "add_topology_args",
           "add_telemetry_args", "job_scoped", "prom_labels",
           "add_checkpoint_args", "add_stream_args", "build_robustness",
           "build_control", "build_elastic", "elastic_distributed_init",
           "make_heartbeat", "make_event_stream", "make_flight_recorder",
           "make_stream", "stream_join_seq", "stream_rejoin_params",
           "flight_update", "make_preemption",
           "preempt_exit", "profile_trace"]


@contextlib.contextmanager
def profile_trace(trace_dir: Optional[str]):
    """``jax.profiler`` trace capture with a guaranteed stop.

    The harnesses used to copy-paste ``start_trace``/``stop_trace`` around
    the profiled epoch with no try/finally — an exception mid-epoch (e.g.
    ``GuardExceeded``) leaked a running trace, which keeps buffering
    profiler events for the rest of the process AND makes the next
    ``start_trace`` raise.  One context manager, used by all three
    harnesses; no-op (yields False) when ``trace_dir`` is falsy."""
    if not trace_dir:
        yield False
        return
    jax.profiler.start_trace(trace_dir)
    try:
        yield True
    finally:
        jax.profiler.stop_trace()


def add_telemetry_args(p) -> None:
    """The shared ``--events`` / ``--prom`` / ``--job_id`` CLI surface
    (obs/export.py)."""
    p.add_argument("--events", type=str, default=None,
                   help="JSONL telemetry event stream path (schema-versioned;"
                        " one record per step/epoch/guard event — feed to "
                        "tools/trace_report.py)")
    p.add_argument("--prom", type=str, default=None,
                   help="Prometheus textfile path, rewritten atomically at "
                        "each epoch/log window with the latest metrics")
    p.add_argument("--job_id", type=str,
                   default=os.environ.get("TCDP_JOB_ID") or None,
                   help="fleet job id (default: $TCDP_JOB_ID, exported by "
                        "tools/fleet.py): prefixes the --events/--prom/"
                        "--heartbeat file names (obs.export.job_scoped_path) "
                        "and labels the Prometheus exposition job=\"<id>\", "
                        "so jobs sharing one collector dir never clobber "
                        "each other")
    p.add_argument("--events_max_mb", type=float, default=0.0,
                   help="rotate the --events JSONL when the live file "
                        "would cross this many MB (atomic rename to "
                        "<path>.<seg>; records carry their segment index; "
                        "0 = unbounded)")
    p.add_argument("--flight_dir", type=str, default=None,
                   help="shared dir for the per-rank flight recorder "
                        "(obs/flight.py): ring-buffered telemetry, "
                        "blackbox.rank<R>.json dumps on failure paths, "
                        "live straggler/* gauges — feed the dir to "
                        "tools/postmortem.py after a crash")
    p.add_argument("--flight_capacity", type=int, default=256,
                   help="flight-recorder ring capacity per channel "
                        "(memory is O(channels x capacity))")


def add_topology_args(p) -> None:
    """The shared ``--dp_pods`` / ``--hier_route_factor_*`` CLI surface for
    ``--transport hierarchical`` (the dp_pods x dp_chips virtual mesh of
    parallel/dp.py)."""
    p.add_argument("--dp_pods", type=int, default=1,
                   help="hierarchical transport: pod count P of the "
                        "dp_pods x dp_chips virtual mesh (must divide the "
                        "data axis; 1 = flat).  Also splits the billed "
                        "comm arithmetic per fabric (net/dcn_* gauges)")
    p.add_argument("--hier_route_factor_ici", type=float, default=1.25,
                   help="hierarchical transport: intra-pod union capacity "
                        "in units of k (clips fold into EF)")
    p.add_argument("--hier_route_factor_dcn", type=float, default=1.25,
                   help="hierarchical transport: inter-pod bucket capacity "
                        "in units of slab/P (clips fold into EF)")


def fabric_gauges(comm_means: Dict[str, float], world: int, pods: int,
                  steps: int, seconds: float) -> Dict[str, float]:
    """Per-fabric ``net/`` gauges (obs/registry.py) from an epoch's mean
    ``comm/*`` metrics: DCN MB per step per chip, and the per-chip Gb/s
    each fabric must sustain at the measured step rate.  Empty on a flat
    mesh (``pods <= 1``) or when comm metrics are absent — the DCN split
    only means something on a 2-level topology."""
    from tpu_compressed_dp.utils.meters import per_fabric_comm_bytes

    if pods <= 1:
        return {}
    fabric = per_fabric_comm_bytes(comm_means, world, pods)
    if fabric is None:
        return {}
    ici_b, dcn_b = fabric
    out = {"net/dcn_mb_per_step": dcn_b / 1e6}
    if seconds > 0 and steps > 0:
        rate = steps / seconds
        out["net/dcn_gbps_per_chip"] = dcn_b * rate * 8 / 1e9
        out["net/ici_gbps_per_chip"] = ici_b * rate * 8 / 1e9
    return out


def job_scoped(args, path):
    """Apply the ``--job_id`` namespace to one telemetry path (no-op for
    single-job runs)."""
    from tpu_compressed_dp.obs.export import job_scoped_path

    return job_scoped_path(path, getattr(args, "job_id", None))


def prom_labels(args, **labels) -> Dict[str, str]:
    """The harness's Prometheus label set: the caller's labels plus
    ``job="<id>"`` under a fleet job id."""
    job = getattr(args, "job_id", None)
    if job:
        labels["job"] = job
    return labels


def make_event_stream(args, **meta):
    """The harnesses' ``--events`` setup: a started
    :class:`~tpu_compressed_dp.obs.export.EventStream` on the master rank
    (metrics are globally reduced, every rank would write identical
    records), or None.  The path and metadata are job-scoped under
    ``--job_id``."""
    if not getattr(args, "events", None) or jax.process_index() != 0:
        return None
    from tpu_compressed_dp.obs.export import EventStream

    if getattr(args, "job_id", None):
        meta = dict(meta, job=args.job_id)
    max_mb = getattr(args, "events_max_mb", 0.0) or 0.0
    return EventStream(job_scoped(args, args.events), meta=dict(meta),
                       max_bytes=int(max_mb * 1e6) if max_mb > 0 else None)


def make_flight_recorder(args, **meta):
    """The harnesses' ``--flight_dir`` setup: a per-rank
    :class:`~tpu_compressed_dp.obs.flight.FlightRecorder` (or None).  EVERY
    rank gets one — unlike the event stream, the whole point is per-rank
    evidence — writing bundles/profiles into the job-scoped shared dir."""
    if not getattr(args, "flight_dir", None):
        return None
    from tpu_compressed_dp.obs.flight import FlightRecorder

    directory = getattr(args, "flight_dir")
    if getattr(args, "job_id", None):
        directory = os.path.join(directory, args.job_id)
        meta = dict(meta, job=args.job_id)
    return FlightRecorder(rank=jax.process_index(),
                          capacity=getattr(args, "flight_capacity", 256),
                          directory=directory, meta=dict(meta))


def flight_update(flight, *, step=None, metrics=None, spans=None):
    """Per-epoch/window flight upkeep: feed the drained timeline spans and
    the window's fetched metrics into the rings, publish this rank's phase
    profile, and return the gauges (``flight/*`` counters + the live
    cross-rank ``straggler/*``) for the heartbeat/Prometheus payloads.
    ``{}`` when the recorder is off — callers can merge unconditionally."""
    if flight is None:
        return {}
    if spans:
        flight.note_spans(spans)
    if step is not None:
        flight.note_step(step, metrics or {})
    gauges = dict(flight.metrics())
    gauges.update(flight.publish())
    return gauges


def add_robustness_args(p, *, check_note: str) -> None:
    """The shared ``--guard*`` / ``--chaos`` / ``--heartbeat`` CLI surface
    (one definition for all three harnesses; ``check_note`` names the
    harness's wedge-check cadence in the --guard_max_skips help)."""
    p.add_argument("--guard", action="store_true",
                   help="arm the in-graph step guard: cross-worker "
                        "finiteness vote skips nonfinite steps, holds "
                        "params/ef/comp bitwise, dynamic loss scaling on "
                        "16-bit dtypes (train/guard.py)")
    p.add_argument("--guard_init_scale", type=float, default=2.0 ** 15)
    p.add_argument("--guard_backoff", type=float, default=0.5)
    p.add_argument("--guard_growth_interval", type=int, default=200)
    p.add_argument("--guard_max_skips", type=int, default=25,
                   help="raise GuardExceeded past this many CONSECUTIVE "
                        f"skipped steps ({check_note})")
    p.add_argument("--chaos", type=str, default=None,
                   help="deterministic fault injection, e.g. "
                        "'nan,target=grads,steps=3+7,worker=1' or "
                        "'crash=120' (utils/chaos.py; in-graph injection "
                        "auto-arms --guard)")
    p.add_argument("--heartbeat", type=str, default=None,
                   help="liveness JSON path (utils/resilience.Heartbeat); "
                        "payload carries step + last_good_step")
    p.add_argument("--heartbeat_interval", type=float, default=10.0)
    p.add_argument("--elastic", action="store_true",
                   help="survive peer death without a full-job restart: "
                        "detect (heartbeat gossip + bounded fetches), "
                        "remesh to W-1 with EF/PowerSGD migration, retry "
                        "(train/elastic.py)")
    p.add_argument("--elastic_dir", type=str, default=None,
                   help="shared per-rank heartbeat gossip directory "
                        "(omit = no gossip plane; chaos/fetch detection "
                        "still active)")
    p.add_argument("--peer_timeout", type=float, default=60.0,
                   help="seconds without a fresh peer heartbeat (or a "
                        "blocked metrics fetch) before declaring the peer "
                        "dead; --chaos peer_timeout=<s> overrides")
    p.add_argument("--elastic_ef", type=str, default="fold",
                   choices=("fold", "drop"),
                   help="departing worker's EF residual: fold into a "
                        "survivor (mass-conserving) or drop and count it "
                        "in elastic/dropped_ef_norm")
    p.add_argument("--elastic_min_world", type=int, default=2,
                   help="refuse to remesh below this many workers")


def add_adaptive_args(p) -> None:
    """The shared ``--adaptive*`` CLI surface: the closed-loop compression
    controller (tpu_compressed_dp/control/).  Decision cadence is the
    harness's metric-fetch window (epoch for CIFAR/ImageNet, log window for
    the LM harness) — the controller's own ``--adaptive_window`` counts
    APPLIED updates inside those fetches."""
    p.add_argument("--adaptive", action="store_true",
                   help="arm the closed-loop compression controller: retune "
                        "the compression knob (Top-K/Random-K ratio, "
                        "PowerSGD rank) along a precompiled rung ladder to "
                        "fit comm under the hideable-compute budget "
                        "(control/controller.py)")
    p.add_argument("--adaptive_window", type=int, default=8,
                   help="applied updates per control decision window")
    p.add_argument("--adaptive_deadband", type=float, default=0.25,
                   help="relative comm/budget deadband before a rung move")
    p.add_argument("--adaptive_rungs", type=str, default=None,
                   help="comma-separated explicit rung ladder (strictly "
                        "descending knob values; rung 0 is the static "
                        "baseline).  Default: halve the configured "
                        "ratio/rank per rung, 5 rungs deep")
    p.add_argument("--adaptive_budget_ms", type=float, default=0.0,
                   help="explicit per-update hideable-comm budget in ms; "
                        "0 = derive from measured compute x the overlap "
                        "schedule's hideable byte fraction")
    p.add_argument("--adaptive_bw_mbps", type=float, default=100.0,
                   help="modeled interconnect bandwidth (Mbit/s) used to "
                        "turn analytic sent-bits into comm ms under "
                        "--adaptive_signal modeled")
    p.add_argument("--adaptive_signal", type=str, default="modeled",
                   choices=("modeled", "measured"),
                   help="'modeled' prices comm from analytic sent-bits / "
                        "--adaptive_bw_mbps (bitwise replay-deterministic); "
                        "'measured' uses harness-observed wall times "
                        "(NOT replay-deterministic)")
    p.add_argument("--adaptive_model", type=str, default="flat",
                   choices=("flat", "twin"),
                   help="how 'modeled' prices bits: 'flat' divides by "
                        "--adaptive_bw_mbps; 'twin' prices the transport's "
                        "collective schedule through the calibrated "
                        "per-fabric digital twin (tpu_compressed_dp/twin/, "
                        "fitted from --twin_records).  Both are pure "
                        "functions of billed bits (replay-deterministic)")
    p.add_argument("--twin_records", type=str, default=".",
                   help="directory holding the BENCH_r*/MULTICHIP_r* "
                        "records the twin calibrates from "
                        "(--adaptive_model twin)")


def build_control(args, comp_cfg):
    """Resolve the ``--adaptive*`` CLI surface into a
    :class:`~tpu_compressed_dp.control.ControlConfig` (or None).

    Raises on a non-tunable compression method — silently running static
    under an --adaptive flag would invalidate any adaptive-vs-static
    comparison the run was launched for."""
    if not getattr(args, "adaptive", False):
        return None
    from tpu_compressed_dp.control import ControlConfig, build_ladder
    from tpu_compressed_dp.control.config import TUNABLE_METHODS
    from tpu_compressed_dp.control.rungs import ladder_knob
    from tpu_compressed_dp.ops.compressors import canonical_name

    method = (canonical_name(comp_cfg.method)
              if comp_cfg is not None and comp_cfg.method else None)
    if method not in TUNABLE_METHODS:
        raise SystemExit(
            f"--adaptive requires a tunable compression method "
            f"{TUNABLE_METHODS}, got {method!r}")
    if args.adaptive_rungs:
        knob = ladder_knob(method)
        cast = float if knob == "ratio" else int
        rungs = tuple(cast(v) for v in args.adaptive_rungs.split(","))
    else:
        rungs = build_ladder(method, comp_cfg.ratio, comp_cfg.rank)
    return ControlConfig(
        method=method, rungs=rungs,
        window=args.adaptive_window, deadband=args.adaptive_deadband,
        signal=args.adaptive_signal,
        model=getattr(args, "adaptive_model", "flat"),
        bandwidth_mbps=args.adaptive_bw_mbps,
        budget_ms=args.adaptive_budget_ms)


def build_twin_pricer(args, comp_cfg, *, world: int):
    """Fit the digital twin from ``--twin_records`` and wrap it as the
    Controller's bit pricer — None unless ``--adaptive_model twin``.

    The fit happens once at harness start (a least-squares over the
    committed artifacts, milliseconds of host work); from then on every
    decision window prices its billed bits through the frozen result, so
    the control loop stays replay-deterministic."""
    if getattr(args, "adaptive_model", "flat") != "twin":
        return None
    from tpu_compressed_dp.control.signals import TwinPricer
    from tpu_compressed_dp.twin import calibration_rows, fit

    rows = calibration_rows(args.twin_records)
    calib = fit(rows)
    mode = getattr(comp_cfg, "mode", "simulate") if comp_cfg else "simulate"
    transport = getattr(comp_cfg, "transport", None) if comp_cfg else None
    if mode != "wire" or not transport:
        transport = "psum"   # simulate bills compressed payloads on psum
    elif transport == "allgather":
        transport = "all_gather"
    return TwinPricer(
        model=calib.model, world=max(int(world), 1),
        pods=int(getattr(args, "dp_pods", 1) or 1),
        transport=transport,
        calib_rows=len(rows))


def control_summary(controller, control) -> Dict[str, float]:
    """Epoch adaptive-control accounting for the harness summary line:
    the live rung index and knob value.  Empty when the controller is off."""
    if controller is None or control == ():
        return {}
    m = controller.metrics(control)
    return {"rung": m["control/rung"], controller.knob: m["control/value"]}


def make_heartbeat(args):
    """The harnesses' ``--heartbeat`` setup: a started Heartbeat, or None.
    The path is job-scoped under ``--job_id`` (two pool-sharing jobs must
    not clobber one liveness file) and the payload names the job so a
    fleet poll can attribute the verdict."""
    if not args.heartbeat:
        return None
    from tpu_compressed_dp.utils.resilience import Heartbeat

    payload = {"rank": jax.process_index()}
    if getattr(args, "job_id", None):
        payload["job"] = args.job_id
    return Heartbeat(job_scoped(args, args.heartbeat),
                     interval_s=args.heartbeat_interval, payload=payload)


def add_checkpoint_args(p, *, cadence_help: str) -> None:
    """The shared ``--checkpoint_dir`` / ``--resume`` / ``--ckpt_every`` CLI
    surface (``cadence_help`` names the harness's save cadence unit)."""
    p.add_argument("--checkpoint_dir", type=str, default=None,
                   help="Orbax checkpoint directory (async saves, "
                        "checksummed manifests, preemption emergency saves "
                        "— utils/checkpoint.py)")
    p.add_argument("--resume", type=str, default=None,
                   help="restore the newest verifiable checkpoint from this "
                        "directory before training")
    p.add_argument("--ckpt_every", type=int, default=1, help=cadence_help)


def add_stream_args(p, *, cadence_help: str) -> None:
    """The shared ``--stream*`` CLI surface: delta-compressed state
    streaming (stream/ — incremental checkpoints, warm rejoin, model
    push).  ``cadence_help`` names the harness's append cadence unit."""
    p.add_argument("--stream_dir", type=str, default=None,
                   help="delta state-stream directory (keyframe + Top-K "
                        "drift segments, manifest-checksummed; feeds warm "
                        "rejoin and tools/stream_serve.py consumers)")
    p.add_argument("--stream_every", type=int, default=1, help=cadence_help)
    p.add_argument("--stream_keyframe_every", type=int, default=8,
                   help="segments per stream window (one full keyframe, "
                        "Top-K deltas, one window-closing flush; the flush "
                        "makes keyframe+deltas == params bitwise)")
    p.add_argument("--stream_ratio", type=float, default=0.01,
                   help="Top-K density of each delta segment (fraction of "
                        "model coordinates)")
    p.add_argument("--stream_rejoin", action="store_true",
                   help="on a watchdog relaunch, catch up from the delta "
                        "stream instead of the survivors' full params "
                        "broadcast (falls back automatically when the "
                        "stream is absent or corrupt); requires "
                        "--stream_dir armed fleet-wide")


def make_stream(args, *, flight=None, events=None, log=print):
    """Resolve ``--stream_dir`` into a started
    :class:`~tpu_compressed_dp.stream.writer.StreamWriter` (or None).
    Single-writer discipline: only process 0 appends — every process
    holds the replicated params, and two writers would race the segment
    sequence."""
    if not getattr(args, "stream_dir", None):
        return None
    if jax.process_index() != 0:
        return None
    from tpu_compressed_dp.stream import StreamWriter

    return StreamWriter(args.stream_dir,
                        ratio=getattr(args, "stream_ratio", 0.01),
                        keyframe_every=getattr(args, "stream_keyframe_every",
                                               8),
                        flight=flight, events=events, log=log)


def stream_join_seq(args):
    """The joiner's pre-admission stream probe: the segment seq it can
    catch up to, or None when warm rejoin is off/unavailable.  Passed as
    ``stream_seq`` into the rendezvous join record so survivors take the
    params-skipping barrier (``ElasticRuntime.rejoin_barrier``) only for
    joiners that really can adopt from the stream — the probe runs a full
    verification catch-up, not just a head read."""
    if not (getattr(args, "stream_rejoin", False)
            and getattr(args, "stream_dir", None)):
        return None
    from tpu_compressed_dp.stream import (StreamCorrupt, StreamReader,
                                          is_stream_dir)

    if not is_stream_dir(args.stream_dir):
        return None
    try:
        reader = StreamReader(args.stream_dir)
        reader.catch_up()
    except StreamCorrupt as e:
        print(f"stream: rejoin probe failed ({e}); joining cold")
        return None
    return int(reader.applied_seq) if reader.applied_seq >= 0 else None


def stream_rejoin_params(args, state, decision=None, *, flight=None,
                         log=print):
    """Joiner-side warm rejoin: ``(adopted_params, info)`` for
    ``ElasticRuntime.join_world``, or ``(None, None)`` to take the
    survivors' full broadcast.  Runs AFTER admission, so the survivors'
    barrier flush (``StreamWriter.sync``) is already on disk and the
    reconstruction is bitwise the live params.  ``decision`` is the
    :class:`~tpu_compressed_dp.train.rendezvous.EpochDecision` the join
    returned: its committed ``warm`` bit is the fleet-wide agreement on
    the broadcast layout, so when it says cold the catch-up is skipped
    outright (``join_world`` would discard it anyway)."""
    if not (getattr(args, "stream_rejoin", False)
            and getattr(args, "stream_dir", None)):
        return None, None
    if decision is not None and not getattr(decision, "warm", False):
        log("stream: epoch committed a cold admission — skipping the "
            "warm-rejoin catch-up")
        return None, None
    from tpu_compressed_dp.stream import warm_rejoin

    adopted, info = warm_rejoin(state, args.stream_dir, log=log,
                                flight=flight)
    if info is None:
        return None, None
    return adopted.params, info


def make_preemption(log=print):
    """Install the SIGTERM/SIGINT preemption flag for a harness run.  Always
    pair with ``handler.uninstall()`` in the run's ``finally``."""
    from tpu_compressed_dp.utils.resilience import PreemptionHandler

    return PreemptionHandler(log=log).install()


def preempt_exit(err, *, ckpt=None, state=None, meta=None, events=None,
                 flight=None, log=print):
    """The harnesses' common preemption epilogue: drain any in-flight async
    checkpoint write (ignoring its failure — the emergency save is about to
    supersede it), cut a SYNCHRONOUS emergency checkpoint, emit a
    ``preempt`` event, dump the flight-recorder black box, and return the
    ``SystemExit`` carrying
    :data:`~tpu_compressed_dp.utils.resilience.PREEMPT_EXIT` for the caller
    to raise — the distinct code ``tools/watchdog.py --relaunch`` respawns
    immediately on (no backoff burn)."""
    from tpu_compressed_dp.utils.resilience import PREEMPT_EXIT

    saved = None
    if ckpt is not None and state is not None:
        try:
            ckpt.drain(raise_error=False)
            saved = ckpt.save(state, {**(meta or {}), "emergency": True})
        except Exception as save_err:
            log(f"preempt: emergency checkpoint FAILED: {save_err!r}")
    if flight is not None:
        # last write before the process dies: the postmortem's only
        # evidence that this rank exited on a reclaim, not a crash
        flight.observe(err, step=getattr(err, "step", None),
                       saved_step=saved)
    if events is not None:
        try:
            events.emit("preempt", step=getattr(err, "step", None),
                        signum=getattr(err, "signum", None), saved_step=saved)
        except Exception:
            pass
    log("preempt: "
        + (f"emergency checkpoint committed at step {saved}" if saved is not None
           else "no checkpoint directory — progress since the last save is lost")
        + f"; exiting {PREEMPT_EXIT} for immediate relaunch")
    return SystemExit(PREEMPT_EXIT)


def build_robustness(args, dtype):
    """Resolve the shared ``--guard*`` / ``--chaos`` CLI surface (all three
    harnesses) into ``(guard_cfg, chaos, crash_injector)``.

    In-graph chaos injection auto-arms the guard: injecting NaN without the
    guard poisons EF/compressor state permanently, which is only ever wanted
    as the explicit control arm of a drill (tools/chaos_drill.py constructs
    that case directly).  Loss scaling activates per ``dtype``
    (``GuardConfig.for_dtype``): dynamic on 16-bit floats, identity on fp32.
    """
    from tpu_compressed_dp.train.guard import GuardConfig, init_guard_state  # noqa: F401
    from tpu_compressed_dp.utils.chaos import ChaosConfig, maybe_crash_injector

    chaos = ChaosConfig.parse(args.chaos) if args.chaos else None
    want_guard = args.guard or (chaos is not None and chaos.injects_in_graph)
    if want_guard and not args.guard and jax.process_index() == 0:
        print("chaos: in-graph injection requested — arming the step guard")
    guard_cfg = GuardConfig.for_dtype(
        dtype,
        init_scale=args.guard_init_scale,
        backoff=args.guard_backoff,
        growth_interval=args.guard_growth_interval,
        max_consecutive_skips=args.guard_max_skips,
    ) if want_guard else None
    return guard_cfg, chaos, maybe_crash_injector(chaos)


def build_elastic(args, mesh, *, chaos=None, crash=None, events=None,
                  place=None, flight=None, stream=None, ef_axes=("data",)):
    """Resolve the ``--elastic*`` CLI surface into a started
    :class:`~tpu_compressed_dp.train.elastic.ElasticRuntime` (or None).

    The gossip plane only arms when ``--elastic_dir`` names the shared
    directory; the chaos-conversion and bounded-fetch detection planes are
    always on.  ``--chaos peer_timeout=<s>`` (the drill's knob) overrides
    ``--peer_timeout``.  ``crash`` (the armed CrashInjector) lets the
    runtime probe the ``during_remesh`` chaos phase so cascading failures
    are drillable; ``ef_axes`` names the mesh axes the gradient sync spans
    (the LM harness passes ``('data', 'seq')``).  Under a real
    multi-process run the rendezvous plane arms too (same shared
    directory), enabling the coordinated ``jax.distributed`` re-init on
    peer death (train/rendezvous.py).
    """
    if not getattr(args, "elastic", False):
        return None
    from tpu_compressed_dp.train.elastic import (ElasticConfig,
                                                 ElasticRuntime, PeerGossip)

    timeout = args.peer_timeout
    if chaos is not None and chaos.peer_timeout > 0:
        timeout = chaos.peer_timeout
    cfg = ElasticConfig(
        gossip_dir=args.elastic_dir, rank=jax.process_index(),
        peer_timeout_s=timeout, min_world=args.elastic_min_world,
        ef_policy=args.elastic_ef)
    gossip = None
    rendezvous = None
    if cfg.gossip_dir:
        # gossip is a PROCESS-level plane: one rank per host process, each
        # writing its own liveness file (ElasticRuntime.poll beats it).
        # Under the single-process simulation world == 1 — the simulated
        # per-device workers have no writers, so peer death there is the
        # chaos plane's job (drills simulate gossip peers directly).
        gossip = PeerGossip(cfg.gossip_dir, cfg.rank, jax.process_count(),
                            peer_timeout_s=cfg.peer_timeout_s)
        if jax.process_count() > 1:
            from tpu_compressed_dp.train.rendezvous import Rendezvous
            rendezvous = Rendezvous(cfg.gossip_dir, cfg.rank)
    # stream_armed is the FLEET-WIDE fact (--stream_dir is the same CLI on
    # every process); self.stream is held by process 0 only (make_stream),
    # so the warm-rejoin barrier layout must key on the former
    return ElasticRuntime(cfg, mesh, chaos=chaos, gossip=gossip,
                          events=events, place=place, crash=crash,
                          rendezvous=rendezvous, flight=flight,
                          stream=stream,
                          stream_armed=bool(getattr(args, "stream_dir",
                                                    None)),
                          ef_axes=tuple(ef_axes))


def elastic_distributed_init(args):
    """Multi-host rendezvous with elastic rejoin, replacing the harnesses'
    bare ``distributed_init`` call.

    A watchdog-relaunched host carries the running world's epoch in its
    environment (``TCDP_RENDEZVOUS_EPOCH``, exported by ``tools/watchdog.py
    --relaunch --elastic_dir``): instead of forming a fresh world from its
    stale ``--coordinator/--num_processes`` flags, it parks in the
    rendezvous join barrier until the survivors commit an epoch that
    readmits it, then initialises against the re-elected coordinator.
    Returns the :class:`~tpu_compressed_dp.train.rendezvous.EpochDecision`
    it joined under (the harness hands it to ``ElasticRuntime.join_world``
    to adopt the survivors' replicated state), or None on a fresh launch.
    A blown join deadline raises — the process exits nonzero and the
    watchdog's backoff is the park-and-retry loop.
    """
    from tpu_compressed_dp.parallel.mesh import distributed_init
    from tpu_compressed_dp.train.rendezvous import maybe_rejoin_from_env

    rank = getattr(args, "process_id", None)
    decision = maybe_rejoin_from_env(
        getattr(args, "elastic_dir", None),
        0 if rank is None else int(rank),
        deadline_s=4 * getattr(args, "peer_timeout", 60.0),
        stream_seq=stream_join_seq(args))
    if decision is not None:
        distributed_init(decision.address, decision.num_processes,
                         decision.process_id)
        return decision
    distributed_init(getattr(args, "coordinator", None),
                     getattr(args, "num_processes", None),
                     getattr(args, "process_id", None))
    return None


def comm_summary(acc: "MetricAccumulator") -> Dict[str, float]:
    """Epoch comm accounting (analytic bytes-on-wire, SURVEY.md §5): 'sent
    frac' = elements that travel; 'wire frac' = bits that travel vs a dense
    fp32 allreduce (catches quantizers, whose element count is dense but whose
    width is 2-9 bits).  Empty when compression metrics are absent."""
    if "comm/sent_elems" not in acc.sums:
        return {}
    dense = max(acc.mean("comm/dense_elems"), 1.0)
    return {
        "sent frac": acc.mean("comm/sent_elems") / dense,
        "wire frac": acc.mean("comm/sent_bits") / (32.0 * dense),
    }


def guard_summary(acc: "MetricAccumulator") -> Dict[str, float]:
    """Epoch step-guard accounting: 'skipped' = cumulative vetoed steps
    (end-of-epoch value of the monotone counter), 'loss scale' = the live
    dynamic loss scale.  Empty when the guard is off."""
    if "guard/nonfinite" not in acc.sums:
        return {}
    return {
        "skipped": acc.last.get("guard/skipped", 0.0),
        "loss scale": acc.last.get("guard/loss_scale", 1.0),
    }


def pad_batch(batch: Dict[str, np.ndarray], size: int) -> Dict[str, np.ndarray]:
    """Pad a (possibly short) final batch to a static ``size`` with a 0/1 mask,
    so every eval step sees one shape (no per-shape recompiles)."""
    n = len(batch["target"])
    if n == size and "mask" in batch:
        return batch
    mask = np.zeros((size,), np.float32)
    mask[:n] = 1.0
    if n == size:
        return {**batch, "mask": mask}
    pad_n = size - n
    x = np.concatenate([batch["input"], np.zeros((pad_n,) + batch["input"].shape[1:],
                                                 batch["input"].dtype)])
    y = np.concatenate([batch["target"], np.full((pad_n,), -1, batch["target"].dtype)])
    return {"input": x, "target": y, "mask": mask}


def run_train_epoch(train_step, state: TrainState, batches: Iterable[Dict],
                    *, crash=None, step_offset: int = 0, guard_cfg=None,
                    timeline=None, elastic=None, preempt=None, flight=None,
                    ) -> Tuple[TrainState, MetricAccumulator]:
    # Metrics stay on device until the epoch ends: a per-step float() would
    # block host batch prep on the device and serialize the pipeline (JAX's
    # async dispatch is the overlap the reference engineered with side
    # streams).  The final device_get blocks, so epoch wall-times stay honest.
    #
    # ``crash`` (utils/chaos.CrashInjector) fires the host-side chaos fault
    # before dispatching the matching global step (= step_offset + i, the
    # attempted-step counter — the same numbering the in-graph injection
    # reads from TrainState.step).  ``guard_cfg`` arms the wedge check: the
    # consecutive-skip streak is inspected on the fetched metrics at epoch
    # end (per-step checks would force a device sync each step and
    # serialize the pipeline; detection latency here is one epoch, and the
    # raise lands inside run_with_recovery's retry loop like any failure).
    #
    # ``timeline`` (obs/trace.StepTimeline) splits each step's host time
    # into input-pipeline wait (the `next()` inside the for statement) and
    # dispatch; it never syncs the device unless configured to sample.
    #
    # ``elastic`` (train/elastic.ElasticRuntime) adds the per-batch gossip
    # poll and the second crash check AFTER dispatch (phase
    # 'mid_collective': the step's collectives are in flight — the
    # deterministic stand-in for a peer dying inside an allreduce), and
    # bounds the epoch-end metrics fetch so a dead peer raises PeerFailed
    # instead of stalling the fetch forever.
    #
    # ``preempt`` (utils/resilience.PreemptionHandler) raises Preempted at
    # the first step boundary after SIGTERM/SIGINT landed; checked AFTER
    # crash.check so chaos' crash=preempt self-SIGTERM at step N is
    # observed within the same iteration, and the except below still rides
    # the live state out for the emergency save.
    acc = MetricAccumulator()
    step_metrics = []
    if timeline is not None:
        # exclude whatever happened since the previous epoch's last dispatch
        # (eval, checkpoint saves, loader swaps) from step 0's data wait
        timeline.resume()
    try:
        for i, batch in enumerate(batches):
            if timeline is not None:
                timeline.batch_ready()
            if crash is not None:
                crash.check(step_offset + i)
            if preempt is not None:
                preempt.check(step_offset + i)
            if elastic is not None:
                elastic.poll(step_offset + i)
            state, metrics = train_step(state, {k: jnp.asarray(v) for k, v in batch.items()})
            if crash is not None:
                crash.check(step_offset + i, phase="mid_collective")
            if timeline is not None:
                timeline.step_dispatched()
            step_metrics.append(metrics)
    except Exception as err:
        # donation consumed the caller's pre-epoch buffers at step 0, so
        # the only live TrainState is this frame's local — ride it out on
        # the exception for the elastic remesh handler (steps dispatched
        # before the failure drain to completion during state migration
        # under the single-process simulation; the rest of the epoch
        # re-runs on the surviving mesh)
        err.elastic_state = state
        raise
    if elastic is not None:
        fetched = elastic.bounded_get(step_metrics,
                                      step=step_offset + len(step_metrics))
    else:
        fetched = jax.device_get(step_metrics)
    for metrics in fetched:
        acc.update(metrics)
    if flight is not None:
        # ring the fetched (host) metrics BEFORE the guard inspects them:
        # when the wedge check raises, the streak history that tripped it is
        # already in the black box (O(capacity) host dicts, no device work)
        for j, metrics in enumerate(fetched):
            flight.note_step(step_offset + j, metrics)
    if guard_cfg is not None and fetched:
        from tpu_compressed_dp.train.guard import check_guard_metrics

        check_guard_metrics(fetched[-1], guard_cfg, flight=flight)
    return state, acc


def run_eval(eval_step, state: TrainState, batches: Iterable[Dict], batch_size: int) -> Dict[str, float]:
    sums = {"loss_sum": 0.0, "correct": 0.0, "correct5": 0.0, "count": 0.0}
    for batch in batches:
        padded = pad_batch(batch, batch_size)
        m = eval_step(state, {k: jnp.asarray(v) for k, v in padded.items()})
        for k in sums:
            sums[k] += float(m[k])
    n = max(sums["count"], 1.0)
    return {
        "loss": sums["loss_sum"] / n,
        "acc": sums["correct"] / n,
        "acc5": sums["correct5"] / n,
        "count": sums["count"],
    }


def train_epoch(
    train_step,
    eval_step,
    state: TrainState,
    train_batches,
    test_batches,
    timer: Timer,
    batch_size: int,
    test_time_in_total: bool = False,
    crash=None,
    step_offset: int = 0,
    guard_cfg=None,
    timeline=None,
    world: Optional[int] = None,
    pods: int = 1,
    elastic=None,
    preempt=None,
    flight=None,
) -> Tuple[TrainState, Dict[str, float], MetricAccumulator]:
    """One train + eval pass with the reference's epoch-summary shape
    (`core.py:324-331`).  ``crash``/``step_offset``/``guard_cfg``/
    ``timeline`` pass through to :func:`run_train_epoch`; with ``world``
    the summary gains the analytic per-chip comm rate ('comm MB/s', the
    transport-split arithmetic of ``utils.meters.per_chip_comm_bytes``).
    Also returns the epoch's :class:`MetricAccumulator` so callers can
    export raw metric means (event stream, Prometheus) without re-running
    the reduction."""
    state, train_acc = run_train_epoch(
        train_step, state, train_batches, crash=crash,
        step_offset=step_offset, guard_cfg=guard_cfg, timeline=timeline,
        elastic=elastic, preempt=preempt, flight=flight)
    train_time = timer()
    test_stats = run_eval(eval_step, state, test_batches, batch_size)
    test_time = timer(test_time_in_total)
    summary = {
        "train time": train_time,
        "train loss": train_acc.mean("loss"),
        "train acc": train_acc.mean("correct"),
        "test time": test_time,
        "test loss": test_stats["loss"],
        "test acc": test_stats["acc"],
        "total time": timer.total_time,
    }
    summary.update(comm_summary(train_acc))
    summary.update(guard_summary(train_acc))
    if world:
        from tpu_compressed_dp.utils.meters import per_chip_comm_bytes

        comm_means = {k: train_acc.mean(k) for k in train_acc.sums
                      if k.startswith("comm/")}
        comm_b = per_chip_comm_bytes(comm_means, world, pods)
        if comm_b is not None and train_time > 0:
            summary["comm MB/s"] = comm_b * train_acc.steps / train_time / 1e6
        gauges = fabric_gauges(comm_means, world, pods, train_acc.steps,
                               train_time)
        if gauges:
            summary["dcn MB/s"] = (gauges.get("net/dcn_gbps_per_chip", 0.0)
                                   * 1e3 / 8)
    return state, summary, train_acc
