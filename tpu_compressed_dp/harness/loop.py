"""Epoch-level training loop shared by the CIFAR and ImageNet harnesses.

The framework equivalent of ``run_batches`` / ``train_epoch`` / ``train``
(`CIFAR10/core.py:303-341`): the per-batch body is entirely inside the jitted
train step, so the host loop only feeds batches and accumulates the already
globally-reduced metrics.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from tpu_compressed_dp.train.state import TrainState
from tpu_compressed_dp.utils.loggers import MetricAccumulator
from tpu_compressed_dp.utils.timer import Timer

__all__ = ["pad_batch", "run_train_epoch", "run_eval", "train_epoch", "comm_summary"]


def comm_summary(acc: "MetricAccumulator") -> Dict[str, float]:
    """Epoch comm accounting (analytic bytes-on-wire, SURVEY.md §5): 'sent
    frac' = elements that travel; 'wire frac' = bits that travel vs a dense
    fp32 allreduce (catches quantizers, whose element count is dense but whose
    width is 2-9 bits).  Empty when compression metrics are absent."""
    if "comm/sent_elems" not in acc.sums:
        return {}
    dense = max(acc.mean("comm/dense_elems"), 1.0)
    return {
        "sent frac": acc.mean("comm/sent_elems") / dense,
        "wire frac": acc.mean("comm/sent_bits") / (32.0 * dense),
    }


def pad_batch(batch: Dict[str, np.ndarray], size: int) -> Dict[str, np.ndarray]:
    """Pad a (possibly short) final batch to a static ``size`` with a 0/1 mask,
    so every eval step sees one shape (no per-shape recompiles)."""
    n = len(batch["target"])
    if n == size and "mask" in batch:
        return batch
    mask = np.zeros((size,), np.float32)
    mask[:n] = 1.0
    if n == size:
        return {**batch, "mask": mask}
    pad_n = size - n
    x = np.concatenate([batch["input"], np.zeros((pad_n,) + batch["input"].shape[1:],
                                                 batch["input"].dtype)])
    y = np.concatenate([batch["target"], np.full((pad_n,), -1, batch["target"].dtype)])
    return {"input": x, "target": y, "mask": mask}


def run_train_epoch(train_step, state: TrainState, batches: Iterable[Dict]) -> Tuple[TrainState, MetricAccumulator]:
    # Metrics stay on device until the epoch ends: a per-step float() would
    # block host batch prep on the device and serialize the pipeline (JAX's
    # async dispatch is the overlap the reference engineered with side
    # streams).  The final device_get blocks, so epoch wall-times stay honest.
    acc = MetricAccumulator()
    step_metrics = []
    for batch in batches:
        state, metrics = train_step(state, {k: jnp.asarray(v) for k, v in batch.items()})
        step_metrics.append(metrics)
    for metrics in jax.device_get(step_metrics):
        acc.update(metrics)
    return state, acc


def run_eval(eval_step, state: TrainState, batches: Iterable[Dict], batch_size: int) -> Dict[str, float]:
    sums = {"loss_sum": 0.0, "correct": 0.0, "correct5": 0.0, "count": 0.0}
    for batch in batches:
        padded = pad_batch(batch, batch_size)
        m = eval_step(state, {k: jnp.asarray(v) for k, v in padded.items()})
        for k in sums:
            sums[k] += float(m[k])
    n = max(sums["count"], 1.0)
    return {
        "loss": sums["loss_sum"] / n,
        "acc": sums["correct"] / n,
        "acc5": sums["correct5"] / n,
        "count": sums["count"],
    }


def train_epoch(
    train_step,
    eval_step,
    state: TrainState,
    train_batches,
    test_batches,
    timer: Timer,
    batch_size: int,
    test_time_in_total: bool = False,
) -> Tuple[TrainState, Dict[str, float]]:
    """One train + eval pass with the reference's epoch-summary shape
    (`core.py:324-331`)."""
    state, train_acc = run_train_epoch(train_step, state, train_batches)
    train_time = timer()
    test_stats = run_eval(eval_step, state, test_batches, batch_size)
    test_time = timer(test_time_in_total)
    summary = {
        "train time": train_time,
        "train loss": train_acc.mean("loss"),
        "train acc": train_acc.mean("correct"),
        "test time": test_time,
        "test loss": test_stats["loss"],
        "test acc": test_stats["acc"],
        "total time": timer.total_time,
    }
    summary.update(comm_summary(train_acc))
    return state, summary
