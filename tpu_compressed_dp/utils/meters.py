"""Timing and communication meters.

Re-implements the reference's `IMAGENET/training/meter.py`:
  * ``TimeMeter`` (`meter.py:49-60`) — data-wait vs step time.  Under JAX's
    async dispatch the device step time is not observable per-step without
    stalling the pipeline, so the meter tracks what the host can honestly
    see: input-pipeline wait and dispatch time; whole-epoch device time comes
    from the epoch barrier (`harness/loop.py`).
  * ``NetworkMeter`` (`meter.py:24-47,66-86`) — real NIC Gbit/s from
    /proc/net/dev deltas.  On a TPU pod this sees only DCN (host-to-host)
    traffic; ICI bytes never cross the NIC, which is why the framework also
    accounts payloads analytically (``CommMeter``).
  * ``CommMeter`` — analytic bytes-on-wire accumulated from the train step's
    ``comm/*`` metrics; the TPU-native replacement for measuring compression
    payloads off the NIC.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

__all__ = ["TimeMeter", "NetworkMeter", "CommMeter", "GuardMeter",
           "network_bytes", "per_chip_traffic_bytes", "per_chip_comm_bytes",
           "per_fabric_traffic_bytes", "per_fabric_comm_bytes"]


def per_chip_traffic_bytes(psum_bytes: float, allgather_bytes: float,
                           world: int, alltoall_bytes: float = 0.0) -> float:
    """Per-chip link traffic for one gradient sync at ``world`` chips.

    The single source of the method-aware transport arithmetic (VERDICT r2
    #2), shared by bench/sweep.py, the ImageNet harness and
    tools/validate_transport.py so they can never report different numbers
    for the same run: a ring psum moves ``2(W-1)/W x payload`` through each
    chip's links; an all_gather of worker-distinct payloads moves
    ``(W-1) x payload`` per chip (every worker's packet visits every other
    chip); an all_to_all moves ``(W-1)/W x payload`` per chip (each worker
    keeps its own ``1/W`` bucket locally and sends one bucket to each peer
    — the sharded transport's route stage, whose shard-return all_gather
    bills in the allgather bucket).  The sync engines report the split as
    ``comm/sent_bits_psum`` / ``comm/sent_bits_allgather`` /
    ``comm/sent_bits_alltoall``.  This is the analytic analog of the
    reference's NIC-byte measurement (`IMAGENET/training/meter.py:24-47`).
    """
    ring = 2 * (world - 1) / max(world, 1)
    return (ring * psum_bytes + (world - 1) * allgather_bytes
            + (world - 1) / max(world, 1) * alltoall_bytes)


def per_fabric_traffic_bytes(psum_bytes: float, allgather_bytes: float,
                             world: int, alltoall_bytes: float = 0.0,
                             ici_bytes: float = 0.0,
                             dcn_route_bytes: float = 0.0,
                             dcn_return_bytes: float = 0.0,
                             pods: int = 1) -> Tuple[float, float]:
    """Per-chip link traffic split ``(ici_bytes, dcn_bytes)`` for one sync
    on a ``pods x (world/pods)`` virtual mesh.

    The hierarchical transport's group collectives bill per fabric
    directly: the dense pod psums ride the ``C = world/pods``-chip
    intra-pod ring (``2(C-1)/C x`` their summed payload); the inter-pod
    route is an all_to_all over ``pods`` participants (``(P-1)/P x``) and
    the shard return an all_gather (``(P-1) x``).  Whole-world collectives
    (the flat psum/allgather/alltoall buckets from non-hierarchical
    groups) span BOTH fabrics; they bill to DCN when ``pods > 1`` — the
    slow fabric is the binding constraint a whole-world ring is limited by
    — and to ICI on a flat mesh (``pods == 1``), where they are the whole
    story and ``dcn == 0``.
    """
    pods = max(pods, 1)
    chips = max(world // pods, 1)
    flat = per_chip_traffic_bytes(psum_bytes, allgather_bytes, world,
                                  alltoall_bytes)
    ici = 2 * (chips - 1) / chips * ici_bytes
    dcn = ((pods - 1) / pods * dcn_route_bytes
           + (pods - 1) * dcn_return_bytes)
    if pods > 1:
        dcn += flat
    else:
        ici += flat
    return ici, dcn


def per_chip_comm_bytes(m: Dict[str, float], world: int,
                        pods: int = 1) -> Optional[float]:
    """Per-chip link bytes of ONE step from a ``comm/*`` metrics dict
    (per-step values or epoch means), applying the transport split through
    :func:`per_chip_traffic_bytes` (plus the hierarchical transport's
    per-fabric terms when present).  None when comm metrics are absent
    (compression off).  The single epilogue all three harnesses use for
    their comm-bytes/s column, so they can never disagree on the
    arithmetic."""
    fabric = per_fabric_comm_bytes(m, world, pods)
    if fabric is None:
        return None
    return fabric[0] + fabric[1]


def per_fabric_comm_bytes(m: Dict[str, float], world: int,
                          pods: int = 1) -> Optional[Tuple[float, float]]:
    """``(ici_bytes, dcn_bytes)`` per chip for ONE step from a ``comm/*``
    metrics dict — :func:`per_fabric_traffic_bytes` fed from the engines'
    billed split.  None when comm metrics are absent."""
    if "comm/sent_bits" not in m:
        return None
    psum_b = float(m.get("comm/sent_bits_psum", m["comm/sent_bits"])) / 8
    ag_b = float(m.get("comm/sent_bits_allgather", 0.0)) / 8
    a2a_b = float(m.get("comm/sent_bits_alltoall", 0.0)) / 8
    ici_b = float(m.get("comm/sent_bits_ici", 0.0)) / 8
    dcn_b = float(m.get("comm/sent_bits_dcn", 0.0)) / 8
    rt_b = float(m.get("comm/sent_bits_dcn_route", 0.0)) / 8
    return per_fabric_traffic_bytes(
        psum_b, ag_b, world, a2a_b, ici_b, rt_b, max(dcn_b - rt_b, 0.0),
        pods)


class TimeMeter:
    """Host-side split of the train loop: data wait vs dispatch."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.data_time = 0.0
        self.dispatch_time = 0.0
        self.batches = 0
        self._t = time.perf_counter()

    def batch_loaded(self):
        now = time.perf_counter()
        self.data_time += now - self._t
        self._t = now

    def batch_dispatched(self):
        now = time.perf_counter()
        self.dispatch_time += now - self._t
        self._t = now
        self.batches += 1

    def summary(self) -> Dict[str, float]:
        n = max(self.batches, 1)
        return {
            "data ms/batch": self.data_time / n * 1e3,
            "dispatch ms/batch": self.dispatch_time / n * 1e3,
        }


def network_bytes() -> Tuple[int, int]:
    """Total (recv, transmit) bytes across non-loopback NICs
    (`meter.py:66-86`)."""
    recv = transmit = 0
    try:
        with open("/proc/net/dev") as f:
            lines = f.read().splitlines()
    except OSError:
        return 0, 0
    for line in lines[2:]:
        iface, _, rest = line.partition(":")
        if iface.strip() == "lo" or not rest:
            continue
        cols = rest.split()
        recv += int(cols[0])
        transmit += int(cols[8])
    return recv, transmit


class NetworkMeter:
    """Real NIC bandwidth over the interval since the last call
    (`meter.py:24-47`)."""

    def __init__(self):
        self.last_t = time.perf_counter()
        self.last_recv, self.last_transmit = network_bytes()

    def update_bandwidth(self) -> Tuple[float, float]:
        """Returns (recv_gbit/s, transmit_gbit/s) since the previous call."""
        now = time.perf_counter()
        recv, transmit = network_bytes()
        dt = max(now - self.last_t, 1e-9)
        rg = (recv - self.last_recv) * 8 / 1e9 / dt
        tg = (transmit - self.last_transmit) * 8 / 1e9 / dt
        self.last_t, self.last_recv, self.last_transmit = now, recv, transmit
        return rg, tg


class GuardMeter:
    """Step-guard bookkeeping from the train step's ``guard/*`` metrics
    (:mod:`tpu_compressed_dp.train.guard`).

    ``update(metrics, step)`` takes a fetched metrics dict at global step
    ``step`` — any cadence works, because the skip rate comes from the
    DELTA of the cumulative ``guard/skipped`` counter over the step delta,
    not from sampling per-step verdicts (sampling at the log cadence
    aliases against periodic faults: a 10% skip rate observed every 10th
    step reads as 0% or 100%).  ``summary`` reports the latest guard
    scalars plus ``guard/skip_rate`` over the window since the previous
    update.  No-ops (empty summary) when the guard is off.
    """

    def __init__(self):
        self.last: Dict[str, float] = {}
        self._prev_skipped = 0.0
        self._prev_step = 0.0
        self._seeded = False
        self._rate = 0.0

    def update(self, metrics: Dict[str, float], step: float) -> None:
        if "guard/skipped" not in metrics:
            return
        self.last = {k: float(v) for k, v in metrics.items()
                     if k.startswith("guard/")}
        cur = float(metrics["guard/skipped"])
        if self._seeded and step > self._prev_step:
            self._rate = (cur - self._prev_skipped) / (step - self._prev_step)
        # the first observation only SEEDS the window: on a resumed run the
        # restored cumulative counter and step are both nonzero, and rating
        # them against (0, 0) would bill every historical skip to a window
        # that saw none
        self._prev_skipped, self._prev_step = cur, float(step)
        self._seeded = True

    def summary(self) -> Dict[str, float]:
        if not self.last:
            return {}
        return {**self.last, "guard/skip_rate": self._rate}


class CommMeter:
    """Analytic gradient-sync traffic accumulated from ``comm/*`` metrics.

    ``update`` takes one step's metrics dict; ``gbps`` converts the payload
    accumulated since the last call into ring-allreduce GB/s per chip.
    """

    def __init__(self, world: int):
        self.world = max(world, 1)
        self.reset()

    def reset(self):
        self.payload_bytes = 0.0
        self.dense_bytes = 0.0
        self.steps = 0
        self._t = time.perf_counter()

    def update(self, metrics: Dict[str, float]) -> None:
        if "comm/sent_bits" not in metrics:
            return
        self.payload_bytes += float(metrics["comm/sent_bits"]) / 8
        self.dense_bytes += float(metrics["comm/dense_elems"]) * 4
        self.steps += 1

    def gbps(self) -> Dict[str, float]:
        dt = max(time.perf_counter() - self._t, 1e-9)
        ring = 2 * (self.world - 1) / self.world
        out = {
            "net/payload_mb_per_step": self.payload_bytes / max(self.steps, 1) / 1e6,
            "net/allreduce_gbps_per_chip": ring * self.payload_bytes / 1e9 / dt,
            "net/compression_frac": self.payload_bytes / max(self.dense_bytes, 1e-9),
        }
        self.reset()
        return out
