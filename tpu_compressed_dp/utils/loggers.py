"""Training loggers: console table, TSV, tensorboard, files, metric averaging.

Covers the reference's logging stack — CIFAR ``TableLogger``
(`CIFAR10/core.py:31-37`), ``TSVLogger`` (`dawn.py:89-96`, the DAWNBench
submission format), ``StatsLogger`` (`core.py:161-173`), the ImageNet
``AverageMeter`` (`IMAGENET/training/meter.py:4-22`), the master-only
``TensorboardLogger`` with scalar JSON export and an examples-count x-axis
(`logger.py:13-68`; the wandb mirror is not reproduced — zero-egress), and
the three-file ``FileLogger`` (verbose/event/debug, rank-prefixed console,
`logger.py:74-121`).
"""

from __future__ import annotations

import json
import logging
import os
import sys
from typing import Dict, Iterable, List, Optional

from tpu_compressed_dp.obs import registry as obs_registry

__all__ = [
    "TableLogger",
    "TSVLogger",
    "AverageMeter",
    "MetricAccumulator",
    "TensorboardLogger",
    "FileLogger",
    "NoOp",
]


class TableLogger:
    """Fixed-width console table; columns locked to the first row's keys."""

    def append(self, output: Dict) -> None:
        if not hasattr(self, "keys"):
            self.keys = list(output.keys())
            print(*(f"{k:>12s}" for k in self.keys))
        filtered = [output.get(k) for k in self.keys]
        print(*(f"{v:12.4f}" if isinstance(v, float) else f"{v!s:>12}" for v in filtered))


class TSVLogger:
    """DAWNBench `epoch\\thours\\ttop1Accuracy` log (`dawn.py:89-96`)."""

    def __init__(self):
        self.log: List[str] = ["epoch\thours\ttop1Accuracy"]

    def append(self, output: Dict) -> None:
        epoch = output["epoch"]
        hours = output["total time"] / 3600
        acc = output["test acc"] * 100
        self.log.append(f"{epoch}\t{hours:.8f}\t{acc:.2f}")

    def save(self, log_dir: str, name: str = "logs.tsv") -> str:
        log_dir = os.path.expanduser(log_dir)
        os.makedirs(log_dir, exist_ok=True)
        path = os.path.join(log_dir, name)
        with open(path, "w") as f:
            f.write(str(self))
        return path

    def __str__(self) -> str:
        return "\n".join(self.log)


class AverageMeter:
    """Running value/average/smoothed view (`meter.py:4-22`)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.val = 0.0
        self.avg = 0.0
        self.smooth_avg = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val: float, n: int = 1):
        self.val = val
        self.sum += val * n
        self.count += n
        self.smooth_avg = val if self.count == n else self.smooth_avg * 0.9 + val * 0.1
        self.avg = self.sum / self.count


class NoOp:
    """Absorbing sink for non-master ranks (`logger.py:124-127`)."""

    def __getattr__(self, name):
        def noop(*args, **kwargs):
            return None

        return noop


class TensorboardLogger:
    """Master-only tensorboard writer, x-axis in cumulative *examples*
    (`logger.py:24-34`: "Tensorboard is easier to parse if global_step is
    examples seen"); scalars mirrored to a JSON file on close
    (`logger.py:36-38`).  Instantiate on every rank — non-master ranks get a
    no-op (the reference gated identically)."""

    def __new__(cls, output_dir: Optional[str], is_master: bool = True):
        if not output_dir or not is_master:
            return NoOp()
        return super().__new__(cls)

    def __init__(self, output_dir: str, is_master: bool = True):
        from torch.utils.tensorboard import SummaryWriter

        os.makedirs(output_dir, exist_ok=True)
        self.writer = SummaryWriter(output_dir)
        self.output_dir = output_dir
        self.examples = 0
        self.scalars: Dict[str, List] = {}

    def update_examples_count(self, n: int) -> None:
        self.examples += int(n)

    def log_scalar(self, tag: str, value: float, step: Optional[int] = None) -> None:
        step = self.examples if step is None else step
        self.writer.add_scalar(tag, value, step)
        self.scalars.setdefault(tag, []).append([step, float(value)])

    def log_metrics(self, metrics: Dict[str, float], prefix: str = "") -> None:
        for k, v in metrics.items():
            if isinstance(v, (int, float)):
                self.log_scalar(prefix + k, v)

    def close(self) -> None:
        with open(os.path.join(self.output_dir, "scalars.json"), "w") as f:
            json.dump(self.scalars, f)
        self.writer.close()


class FileLogger:
    """Three-file logger + rank-prefixed console (`logger.py:74-121`):
    ``verbose.log`` (INFO+), ``event.log`` (WARN+ — the ``~~epoch`` summary
    lines go here via :meth:`event`), ``debug.log`` (DEBUG+ with
    timestamps).  Only the master rank writes files; every rank prints."""

    def __init__(self, output_dir: Optional[str], rank: int = 0,
                 is_master: bool = True):
        self.rank = rank
        self.logger = logging.getLogger(f"tpu_compressed_dp.r{rank}")
        self.logger.setLevel(logging.DEBUG)
        self.logger.handlers = []
        self.logger.propagate = False
        console = logging.StreamHandler(sys.stdout)
        console.setLevel(logging.DEBUG)
        console.setFormatter(logging.Formatter(f"{rank}: %(message)s"))
        self.logger.addHandler(console)
        if output_dir and is_master:
            os.makedirs(output_dir, exist_ok=True)
            for fname, level, fmt in [
                ("verbose.log", logging.INFO, "%(message)s"),
                ("event.log", logging.WARNING, "%(message)s"),
                ("debug.log", logging.DEBUG, "%(asctime)s %(levelname)s %(message)s"),
            ]:
                h = logging.FileHandler(os.path.join(output_dir, fname))
                h.setLevel(level)
                h.setFormatter(logging.Formatter(fmt))
                self.logger.addHandler(h)

    def debug(self, msg: str) -> None:
        self.logger.debug(msg)

    def info(self, msg: str) -> None:
        self.logger.info(msg)

    def event(self, msg: str) -> None:
        """Epoch-summary channel (reference logs these at WARN so they land
        in event.log, `train_imagenet_nv.py:232,243`)."""
        self.logger.warning(msg)


class MetricAccumulator:
    """Accumulates per-step metric dicts into epoch means/sums.

    The framework-native replacement for ``StatsLogger`` (`core.py:161-173`):
    metrics arrive already globally reduced from the train step, so this is
    pure host-side bookkeeping.
    """

    #: keys that are global sums per step (everything else is a per-example
    #: or per-step value, averaged with the step's example count as weight).
    #: Derived from the metric registry's declared reductions
    #: (obs/registry.py) — a new sum-reduced metric joins automatically.
    SUM_KEYS = frozenset(name for name, ms in obs_registry.REGISTRY.items()
                         if ms.reduction == "sum")

    def __init__(self):
        self.sums: Dict[str, float] = {}
        self.weights: Dict[str, float] = {}
        #: most recent value per key — for cumulative/stateful metrics
        #: (guard/skipped totals, guard/loss_scale) where a weighted mean is
        #: meaningless and the end-of-epoch value is the honest summary
        self.last: Dict[str, float] = {}
        #: update() calls seen — the step count rate math needs
        self.steps: int = 0

    def update(self, metrics: Dict[str, float]) -> None:
        self.steps += 1
        w = float(metrics.get("count", 1.0))
        for k, v in metrics.items():
            v = float(v)
            self.last[k] = v
            if k in self.SUM_KEYS:
                self.sums[k] = self.sums.get(k, 0.0) + v
            else:
                self.sums[k] = self.sums.get(k, 0.0) + v * w
                self.weights[k] = self.weights.get(k, 0.0) + w

    def mean(self, key: str) -> float:
        if key in self.SUM_KEYS:
            return self.sums[key] / max(self.sums.get("count", 1.0), 1e-12)
        return self.sums[key] / max(self.weights[key], 1e-12)

    def sum(self, key: str) -> float:
        return self.sums[key]
