"""Training loggers: console table, TSV, and metric averaging.

Covers the reference's CIFAR logging stack — ``TableLogger``
(`CIFAR10/core.py:31-37`), ``TSVLogger`` (`dawn.py:89-96`, the DAWNBench
submission format), ``StatsLogger`` (`core.py:161-173`) — plus meters from the
ImageNet side (`IMAGENET/training/meter.py:4-22`).
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List

__all__ = ["TableLogger", "TSVLogger", "AverageMeter", "MetricAccumulator"]


class TableLogger:
    """Fixed-width console table; columns locked to the first row's keys."""

    def append(self, output: Dict) -> None:
        if not hasattr(self, "keys"):
            self.keys = list(output.keys())
            print(*(f"{k:>12s}" for k in self.keys))
        filtered = [output.get(k) for k in self.keys]
        print(*(f"{v:12.4f}" if isinstance(v, float) else f"{v!s:>12}" for v in filtered))


class TSVLogger:
    """DAWNBench `epoch\\thours\\ttop1Accuracy` log (`dawn.py:89-96`)."""

    def __init__(self):
        self.log: List[str] = ["epoch\thours\ttop1Accuracy"]

    def append(self, output: Dict) -> None:
        epoch = output["epoch"]
        hours = output["total time"] / 3600
        acc = output["test acc"] * 100
        self.log.append(f"{epoch}\t{hours:.8f}\t{acc:.2f}")

    def save(self, log_dir: str, name: str = "logs.tsv") -> str:
        log_dir = os.path.expanduser(log_dir)
        os.makedirs(log_dir, exist_ok=True)
        path = os.path.join(log_dir, name)
        with open(path, "w") as f:
            f.write(str(self))
        return path

    def __str__(self) -> str:
        return "\n".join(self.log)


class AverageMeter:
    """Running value/average/smoothed view (`meter.py:4-22`)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.val = 0.0
        self.avg = 0.0
        self.smooth_avg = 0.0
        self.sum = 0.0
        self.count = 0

    def update(self, val: float, n: int = 1):
        self.val = val
        self.sum += val * n
        self.count += n
        self.smooth_avg = val if self.count == n else self.smooth_avg * 0.9 + val * 0.1
        self.avg = self.sum / self.count


class MetricAccumulator:
    """Accumulates per-step metric dicts into epoch means/sums.

    The framework-native replacement for ``StatsLogger`` (`core.py:161-173`):
    metrics arrive already globally reduced from the train step, so this is
    pure host-side bookkeeping.
    """

    #: keys that are global sums per step (everything else is a per-example or
    #: per-step value, averaged with the step's example count as weight)
    SUM_KEYS = frozenset({"correct", "correct5", "count", "loss_sum"})

    def __init__(self):
        self.sums: Dict[str, float] = {}
        self.weights: Dict[str, float] = {}

    def update(self, metrics: Dict[str, float]) -> None:
        w = float(metrics.get("count", 1.0))
        for k, v in metrics.items():
            v = float(v)
            if k in self.SUM_KEYS:
                self.sums[k] = self.sums.get(k, 0.0) + v
            else:
                self.sums[k] = self.sums.get(k, 0.0) + v * w
                self.weights[k] = self.weights.get(k, 0.0) + w

    def mean(self, key: str) -> float:
        if key in self.SUM_KEYS:
            return self.sums[key] / max(self.sums.get("count", 1.0), 1e-12)
        return self.sums[key] / max(self.weights[key], 1e-12)

    def sum(self, key: str) -> float:
        return self.sums[key]
