"""Orbax checkpoint / resume.

The reference checkpoints ``{epoch, state_dict, best_top5, optimizer}`` with
rank-0 ``torch.save`` when top-5 improves past 93% and at phase boundaries
(`train_imagenet_nv.py:663-669`, `:245-253`), restoring via ``--resume``
(`:193-198`).  Here the *entire* mutable training state — including the
error-feedback residual the reference forgot (SURVEY.md §5) and the PRNG key —
is one pytree saved atomically through Orbax; under multi-host SPMD Orbax
writes each shard from its owning host, the role rank-0 gating played.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from tpu_compressed_dp.train.state import TrainState

__all__ = ["Checkpointer", "save_checkpoint", "restore_checkpoint"]


class Checkpointer:
    """Step-indexed checkpoint directory with best-metric gating.

    ``save(state, meta)`` always writes; ``save_if_best(state, top5, ...)``
    reproduces the reference's improve-only policy (`train_imagenet_nv.py:245-250`)
    minus its ``>93%`` floor (configurable) so small runs checkpoint too.
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
        )
        self.best_metric: Optional[float] = None

    def save(self, state: TrainState, meta: Optional[Dict[str, Any]] = None) -> int:
        step = int(state.step)
        if step in (self.manager.all_steps() or ()):
            # same train step already on disk (e.g. a phase-boundary save
            # immediately after resume) — identical state, nothing to write
            return step
        meta = dict(meta or {})
        if self.best_metric is not None:
            # every save carries best-so-far, so restoring from ANY latest
            # checkpoint (incl. phase-boundary saves) keeps the improve-only
            # gate intact
            meta.setdefault("best_metric", self.best_metric)
        self.manager.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(_to_saveable(state)),
                meta=ocp.args.JsonSave(dict(meta or {})),
            ),
        )
        self.manager.wait_until_finished()
        return step

    def save_if_best(
        self, state: TrainState, metric: float, *, floor: float = 0.0,
        meta: Optional[Dict[str, Any]] = None,
    ) -> bool:
        """Save when ``metric`` (e.g. top-5) beats the best so far and exceeds
        ``floor`` (the reference gated at 93%, `train_imagenet_nv.py:175,245`)."""
        if metric < floor or (self.best_metric is not None and metric <= self.best_metric):
            return False
        self.best_metric = metric
        self.save(state, {**(meta or {}), "best_metric": metric})
        return True

    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()

    def restore(self, target_state: TrainState, step: Optional[int] = None
                ) -> Tuple[TrainState, Dict[str, Any]]:
        """Restore into the structure of ``target_state`` (shapes/dtypes/
        shardings come from the target, so a restored run keeps its mesh
        placement)."""
        step = step if step is not None else self.manager.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory!r}")
        template = _to_saveable(target_state)
        try:
            payload = self.manager.restore(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(template),
                    meta=ocp.args.JsonRestore(),
                ),
            )
        except (ValueError, KeyError) as e:
            # The template can legitimately disagree with the saved tree on
            # the OPTIONAL state entries: legacy checkpoints lack 'comp'
            # (pre-PowerSGD) and/or 'guard' (pre-step-guard) entirely, and
            # toggling powersgd / --guard between save and resume flips
            # those entries between the empty marker {} and {'on': ...}
            # (Orbax raises ValueError for template-missing-saved-key and
            # KeyError for saved-missing-template-key).  Fall back to ONE
            # template-free restore (saved structure as-is) and let
            # _from_saveable reconcile guard/comp against the target — but
            # first verify every OTHER entry matches the template's
            # structure/shape/dtype exactly, so a genuine mismatch (resized
            # params, renamed keys) still surfaces as the ORIGINAL error
            # instead of silently restoring garbage into the caller's tree.
            try:
                payload = self.manager.restore(
                    step,
                    args=ocp.args.Composite(
                        state=ocp.args.StandardRestore(),
                        meta=ocp.args.JsonRestore(),
                    ),
                )
            except Exception:
                raise e
            saved = payload["state"]
            if set(saved) - set(template):
                raise e  # fields this build does not know — not our legacy case
            for k, tv in template.items():
                if k in ("guard", "comp"):
                    continue
                if k not in saved:
                    raise e
                if (jax.tree.structure(tv) != jax.tree.structure(saved[k])):
                    raise e
                for tl, sl in zip(jax.tree.leaves(tv),
                                  jax.tree.leaves(saved[k])):
                    if (tuple(np.shape(tl)) != tuple(np.shape(sl))
                            or np.asarray(tl).dtype != np.asarray(sl).dtype):
                        raise e
        state = _from_saveable(target_state, payload["state"])
        meta = dict(payload.get("meta") or {})
        if "best_metric" in meta:
            self.best_metric = float(meta["best_metric"])
        return state, meta

    def close(self):
        self.manager.close()


def _to_saveable(state: TrainState) -> Dict[str, Any]:
    from tpu_compressed_dp.train.guard import guard_to_dict

    d = {f.name: getattr(state, f.name) for f in dataclasses.fields(state)}
    # PRNG keys: store raw key data (typed keys are not serialisable)
    d["rng"] = jax.random.key_data(d["rng"])
    # ef/comp/guard == () when off; Orbax cannot round-trip an empty
    # container leaf.  GuardState serialises as a plain dict so the on-disk
    # form needs no pytree registration agreement with a future reader.
    d["ef"] = {"on": d["ef"]} if d["ef"] != () else {}
    d["comp"] = {"on": d["comp"]} if d["comp"] != () else {}
    d["guard"] = {"on": guard_to_dict(d["guard"])} if d["guard"] != () else {}
    return d


def _from_saveable(target: TrainState, d: Dict[str, Any]) -> TrainState:
    from tpu_compressed_dp.train.guard import guard_from_dict

    d = dict(d)
    d["rng"] = jax.random.wrap_key_data(np.asarray(d["rng"]))
    ef = d["ef"]
    d["ef"] = ef["on"] if "on" in ef else ()
    # comp/guard: a saved value wins; the empty marker {} (feature was OFF
    # at save time) or a missing key (checkpoint predates the field) keeps
    # the CALLER's value — a freshly-built warm start / init_guard_state
    # when resuming an old run with powersgd / the guard newly enabled,
    # () otherwise — instead of clobbering it.
    if "comp" in d and "on" in d["comp"]:
        d["comp"] = d["comp"]["on"]
    else:
        d["comp"] = target.comp
    if "guard" in d and "on" in d["guard"]:
        d["guard"] = guard_from_dict(d["guard"]["on"])
    else:
        d["guard"] = target.guard
    return dataclasses.replace(target, **d)


def save_checkpoint(directory: str, state: TrainState, meta: Optional[Dict] = None) -> int:
    """One-shot save (``save_checkpoint``, `train_imagenet_nv.py:663-669`)."""
    ckpt = Checkpointer(directory)
    try:
        return ckpt.save(state, meta)
    finally:
        ckpt.close()


def restore_checkpoint(directory: str, target_state: TrainState,
                       step: Optional[int] = None) -> Tuple[TrainState, Dict[str, Any]]:
    """One-shot restore (``--resume``, `train_imagenet_nv.py:193-198`)."""
    ckpt = Checkpointer(directory)
    try:
        return ckpt.restore(target_state, step)
    finally:
        ckpt.close()
