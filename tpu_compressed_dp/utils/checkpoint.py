"""Orbax checkpoint / resume — async, preemption-aware, self-verifying.

The reference checkpoints ``{epoch, state_dict, best_top5, optimizer}`` with
rank-0 ``torch.save`` when top-5 improves past 93% and at phase boundaries
(`train_imagenet_nv.py:663-669`, `:245-253`), restoring via ``--resume``
(`:193-198`).  Here the *entire* mutable training state — including the
error-feedback residual the reference forgot (SURVEY.md §5) and the PRNG key —
is one pytree saved atomically through Orbax; under multi-host SPMD Orbax
writes each shard from its owning host, the role rank-0 gating played.

Three layers on top of the raw Orbax manager:

  * **Async saves** — :meth:`Checkpointer.save_async` snapshots the state to
    host memory (``jax.device_get``), hands the blocking Orbax write + GC to
    a background thread, and returns to the step loop.  Any subsequent save
    / restore / ``close`` barriers on the in-flight write first; the time the
    step loop spends blocked in such a barrier accrues to ``ckpt/blocked_ms``
    while the write itself is ``ckpt/save_ms`` (both in ``metrics()``,
    declared in :mod:`tpu_compressed_dp.obs.registry`).
  * **Checksummed manifests** — every committed step gets a
    ``manifest-<step>.json`` at the directory root (per-file SHA-256 + size +
    schema version, committed atomically via tmp + ``os.replace`` like
    ``train/rendezvous.py``), so a torn or bit-flipped checkpoint is
    *detectable* offline (``tools/ckpt_fsck.py``) and at restore time.
  * **Last-known-good fallback** — :meth:`Checkpointer.restore` with no
    explicit step walks the chain newest → oldest, skipping steps that fail
    manifest verification or raise during the Orbax read, and restores the
    newest verifiable one; the walk-back distance accrues to
    ``ckpt/rollback_steps`` and emits a ``ckpt_rollback`` event.  Only when
    *no* step restores does the first error propagate.

Steps are garbage-collected by the Checkpointer itself (newest
``max_to_keep``), never evicting the pinned ``save_if_best`` step — the raw
Orbax ``max_to_keep`` would happily delete the best checkpoint after three
later periodic saves.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from tpu_compressed_dp.train.state import TrainState

__all__ = [
    "Checkpointer", "CheckpointCorrupt", "save_checkpoint",
    "restore_checkpoint", "MANIFEST_SCHEMA", "manifest_path", "read_manifest",
    "write_manifest", "verify_step_dir", "list_step_dirs", "digest_file",
]

#: manifest schema version; bump on incompatible manifest layout changes
MANIFEST_SCHEMA = 1


class CheckpointCorrupt(RuntimeError):
    """A checkpoint step failed manifest verification (missing files, size
    or digest mismatch, unreadable manifest)."""


# --------------------------------------------------------------------------
# Manifest helpers — module-level and Orbax-free so ``tools/ckpt_fsck.py``
# can verify/list/prune a directory offline without constructing a manager.

def manifest_path(directory: str, step: int) -> str:
    """``manifest-<step>.json`` lives at the directory ROOT: Orbax owns the
    step directory's contents (and deletes it wholesale), the manifest is
    ours and must survive to flag a half-deleted step."""
    return os.path.join(directory, f"manifest-{int(step)}.json")


def digest_file(path: str) -> str:
    """Chunked SHA-256 of one file — the digest every manifest entry (and
    the stream segment store, :mod:`tpu_compressed_dp.stream.store`) pins."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


_digest_file = digest_file  # internal callers / historical name


def write_manifest(directory: str, step: int,
                   meta: Optional[Dict[str, Any]] = None) -> str:
    """Hash every file under ``<directory>/<step>`` and commit the manifest
    atomically (tmp + ``os.replace``, the ``train/rendezvous.py`` idiom).
    Call only after the Orbax write has finished — the manifest IS the
    commit marker for the integrity layer."""
    step = int(step)
    step_dir = os.path.join(directory, str(step))
    files: Dict[str, Dict[str, Any]] = {}
    for root, _, names in os.walk(step_dir):
        for name in sorted(names):
            fp = os.path.join(root, name)
            rel = os.path.relpath(fp, step_dir)
            files[rel] = {"sha256": _digest_file(fp),
                          "bytes": os.path.getsize(fp)}
    rec = {"v": MANIFEST_SCHEMA, "step": step, "ts": time.time(),
           "files": files, "meta": dict(meta or {})}
    path = manifest_path(directory, step)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f)
    os.replace(tmp, path)
    return path


def read_manifest(directory: str, step: int) -> Optional[Dict[str, Any]]:
    """Parse a step's manifest; ``None`` when missing or unreadable."""
    try:
        with open(manifest_path(directory, step), "rb") as f:
            rec = json.loads(f.read().decode("utf-8"))
    except (OSError, ValueError):
        return None
    return rec if isinstance(rec, dict) else None


def verify_step_dir(directory: str, step: int) -> List[str]:
    """Verify one step against its manifest; returns problem strings
    (empty = verifiable).

    A step with *no* manifest at all is tolerated as a legacy (pre-manifest)
    checkpoint — restore must keep working on directories written by older
    builds; ``ckpt_fsck --list`` surfaces them as legacy.  A manifest that
    exists but cannot be parsed IS a problem (a torn manifest commit)."""
    step = int(step)
    step_dir = os.path.join(directory, str(step))
    if not os.path.isdir(step_dir):
        return [f"step directory missing: {step_dir}"]
    man = read_manifest(directory, step)
    if man is None:
        if os.path.exists(manifest_path(directory, step)):
            return ["manifest unreadable (torn commit?)"]
        return []  # legacy checkpoint: no manifest was ever written
    if man.get("v") != MANIFEST_SCHEMA:
        return [f"manifest schema {man.get('v')!r} != {MANIFEST_SCHEMA}"]
    problems = []
    for rel, ent in (man.get("files") or {}).items():
        fp = os.path.join(step_dir, rel)
        if not os.path.isfile(fp):
            problems.append(f"missing file: {rel}")
        elif os.path.getsize(fp) != int(ent.get("bytes", -1)):
            problems.append(
                f"size mismatch: {rel} ({os.path.getsize(fp)} != "
                f"{ent.get('bytes')})")
        elif _digest_file(fp) != ent.get("sha256"):
            problems.append(f"digest mismatch: {rel}")
    return problems


def list_step_dirs(directory: str) -> List[int]:
    """Step indices present on disk (numeric subdirectories), sorted."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return sorted(int(n) for n in names
                  if n.isdigit() and os.path.isdir(os.path.join(directory, n)))


class Checkpointer:
    """Step-indexed checkpoint directory with best-metric gating, async
    writes, checksummed manifests, and walk-back restore.

    ``save(state, meta)`` always writes (synchronously); ``save_async``
    returns once the state is snapshotted to host; ``save_if_best(state,
    top5, ...)`` reproduces the reference's improve-only policy
    (`train_imagenet_nv.py:245-250`) minus its ``>93%`` floor (configurable)
    so small runs checkpoint too — and *pins* the best step against GC.

    Not multi-writer safe: one Checkpointer owns a directory.  Internally it
    IS thread-safe — the background writer and the step loop serialise on an
    operation lock, and barriers join the writer before any new manager op.

    Set ``.events`` to an :class:`~tpu_compressed_dp.obs.export.EventStream`
    to get ``ckpt_save`` / ``ckpt_rollback`` records on the ``--events``
    stream (emission failures never propagate into the save path).  Set
    ``.flight`` to a :class:`~tpu_compressed_dp.obs.flight.FlightRecorder`
    to additionally tee the lifecycle into its ``ckpt`` ring and dump a
    blackbox bundle when a restore raises :class:`CheckpointCorrupt`.
    """

    def __init__(self, directory: str, *, max_to_keep: Optional[int] = 3,
                 events=None, flight=None):
        self.directory = os.path.abspath(directory)
        # GC is ours (best-step pinning); Orbax keeps everything
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=None, create=True),
        )
        self.max_to_keep = max_to_keep
        self.best_metric: Optional[float] = None
        #: the pinned step of the best checkpoint; GC never evicts it
        self.best_step: Optional[int] = None
        self.events = events
        self.flight = flight
        #: optional :class:`tpu_compressed_dp.stream.writer.StreamWriter`
        #: tee — each committed full checkpoint requests a stream keyframe
        #: so the next delta window re-anchors at a durably-saved state
        #: (recovery depth for a stream consumer never spans a checkpoint)
        self.stream = None
        #: last background write failure popped by a non-raising barrier
        self.last_save_error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._bg_error: Optional[BaseException] = None
        # _op serialises every manager/manifest/GC operation across the step
        # loop and the background writer; _mx guards the metric counters
        self._op = threading.RLock()
        self._mx = threading.Lock()
        self._inflight = 0
        self._save_ms = 0.0       # duration of the newest committed write
        self._blocked_ms = 0.0    # cumulative step-loop time spent in barriers
        self._rollback_steps = 0  # cumulative restore walk-back distance
        self._last_step: Optional[int] = None
        self._mark_mono = time.monotonic()  # newest commit (or open) time

    # ---------------------------------------------------------------- saves

    def save(self, state: TrainState, meta: Optional[Dict[str, Any]] = None
             ) -> int:
        """Synchronous save: barrier on any in-flight async write, then block
        until the Orbax write + manifest commit + GC finish.  This is the
        emergency-save primitive — when it returns, the step is durable."""
        self._barrier(accrue=True)
        step = int(state.step)
        if self._dedupe(step):
            return step
        meta = self._meta_with_best(meta)
        payload = _to_saveable(state)
        t0 = time.monotonic()
        self._write_payload(step, payload, meta)
        self._committed(step, (time.monotonic() - t0) * 1e3, mode="sync",
                        meta=meta)
        return step

    def save_async(self, state: TrainState,
                   meta: Optional[Dict[str, Any]] = None) -> int:
        """Hand the write to a background thread and return to the step loop.

        The state is snapshotted to host memory *before* returning (the
        caller may donate/overwrite the device buffers on the very next
        step), so the write is consistent no matter what the loop does.  If
        a previous async write is still in flight this call barriers on it
        first — that wait is the only blocking and accrues to
        ``ckpt/blocked_ms``.  A background failure is re-raised at the next
        barrier (save/save_async/drain); the emergency path uses
        ``drain(raise_error=False)`` to save what it can anyway.
        """
        self._barrier(accrue=True)
        step = int(state.step)
        if self._dedupe(step):
            return step
        meta = self._meta_with_best(meta)
        payload = jax.device_get(_to_saveable(state))
        with self._mx:
            self._inflight = 1

        def _bg():
            t0 = time.monotonic()
            try:
                self._write_payload(step, payload, meta)
            except BaseException as e:  # surfaced at the next barrier
                with self._mx:
                    self._bg_error = e
            else:
                self._committed(step, (time.monotonic() - t0) * 1e3,
                                mode="async", meta=meta)
            finally:
                with self._mx:
                    self._inflight = 0

        self._thread = threading.Thread(
            target=_bg, name=f"ckpt-save-{step}", daemon=True)
        self._thread.start()
        return step

    def save_if_best(
        self, state: TrainState, metric: float, *, floor: float = 0.0,
        meta: Optional[Dict[str, Any]] = None,
    ) -> bool:
        """Save when ``metric`` (e.g. top-5) beats the best so far and exceeds
        ``floor`` (the reference gated at 93%, `train_imagenet_nv.py:175,245`).
        The saved step is pinned: periodic-save GC never evicts it (a new
        best moves the pin)."""
        if metric < floor or (self.best_metric is not None
                              and metric <= self.best_metric):
            return False
        self.best_metric = metric
        self.best_step = int(state.step)
        self.save(state, {**(meta or {}), "best_metric": metric})
        return True

    def _dedupe(self, step: int) -> bool:
        """Same train step already on disk AND verifiable (e.g. a
        phase-boundary save immediately after resume) — identical state,
        nothing to write.  A step that exists but fails verification is
        deleted so the re-save (a replay overwriting a torn write) goes
        through."""
        if step not in self._steps_on_disk():
            return False
        if not verify_step_dir(self.directory, step):
            return True
        with self._op:
            try:
                self.manager.delete(step)
            except Exception:
                pass
            self._rm_manifest(step)
        return False

    def _meta_with_best(self, meta: Optional[Dict[str, Any]]) -> Dict[str, Any]:
        # every save carries best-so-far, so restoring from ANY latest
        # checkpoint (incl. phase-boundary saves) keeps the improve-only
        # gate — and the GC pin — intact
        meta = dict(meta or {})
        if self.best_metric is not None:
            meta.setdefault("best_metric", self.best_metric)
        if self.best_step is not None:
            meta.setdefault("best_step", int(self.best_step))
        return meta

    def _write_payload(self, step: int, payload: Dict[str, Any],
                       meta: Dict[str, Any]) -> None:
        """The blocking write seam for ONE step: Orbax save + manifest commit
        + GC.  Runs on the caller's thread (sync save) or the background
        writer (async).  Tests inject a slow/failing replacement here."""
        with self._op:
            self.manager.save(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(payload),
                    meta=ocp.args.JsonSave(dict(meta)),
                ),
            )
            self.manager.wait_until_finished()
            write_manifest(self.directory, step, meta=meta)
            self._gc()

    def _committed(self, step: int, ms: float, *, mode: str,
                   meta: Dict[str, Any]) -> None:
        with self._mx:
            self._save_ms = ms
            self._last_step = step
            self._mark_mono = time.monotonic()
        fields = {"step": step, "ms": round(ms, 3), "mode": mode}
        if meta.get("emergency"):
            fields["emergency"] = True
        self._emit("ckpt_save", **fields)
        st = self.stream
        if st is not None:
            try:
                st.request_keyframe()  # re-anchor the delta window here
            except Exception:
                pass  # the stream tee must never fail a save

    def _gc(self) -> None:
        """Keep the newest ``max_to_keep`` steps plus the pinned best step.
        Called with ``_op`` held, after each commit."""
        if not self.max_to_keep or self.max_to_keep <= 0:
            return
        steps = sorted(self.manager.all_steps() or ())
        keep = set(steps[-self.max_to_keep:])
        if self.best_step is not None:
            keep.add(int(self.best_step))
        for s in steps:
            if s in keep:
                continue
            try:
                self.manager.delete(s)
            except Exception:
                continue  # a survivor is harmless; next GC retries
            self._rm_manifest(s)

    def _rm_manifest(self, step: int) -> None:
        try:
            os.remove(manifest_path(self.directory, step))
        except OSError:
            pass

    # ------------------------------------------------------------- barriers

    def _barrier(self, *, accrue: bool, raise_error: bool = True) -> None:
        t = self._thread
        if t is not None:
            was_alive = t.is_alive()
            t0 = time.monotonic()
            t.join()
            if accrue and was_alive:
                with self._mx:
                    self._blocked_ms += (time.monotonic() - t0) * 1e3
            self._thread = None
        err, self._bg_error = self._bg_error, None
        if err is not None:
            self.last_save_error = err
            if raise_error:
                raise err

    def drain(self, *, raise_error: bool = True) -> None:
        """Block until any in-flight async write commits.  With
        ``raise_error=False`` (the emergency path) a background failure is
        recorded in ``last_save_error`` instead of raised, so the caller can
        still cut its own save."""
        self._barrier(accrue=True, raise_error=raise_error)

    # -------------------------------------------------------------- restore

    def latest_step(self) -> Optional[int]:
        with self._op:
            return self.manager.latest_step()

    def verify_step(self, step: int) -> List[str]:
        return verify_step_dir(self.directory, step)

    def restore(self, target_state: TrainState, step: Optional[int] = None
                ) -> Tuple[TrainState, Dict[str, Any]]:
        """Restore into the structure of ``target_state`` (shapes/dtypes/
        shardings come from the target, so a restored run keeps its mesh
        placement).

        With an explicit ``step`` the manifest must verify — corruption
        raises :class:`CheckpointCorrupt` (the caller asked for THAT step).
        With ``step=None`` the chain is walked newest → oldest past corrupt
        or unreadable steps to the newest verifiable one; the walk-back
        accrues to ``ckpt/rollback_steps`` and emits ``ckpt_rollback``.
        Only when nothing restores does the first error propagate (so a
        genuine template mismatch on the only checkpoint still surfaces
        as the original Orbax error)."""
        # never let a failed *periodic* save block a restore; the failure
        # stays visible in last_save_error
        self._barrier(accrue=False, raise_error=False)
        template = _to_saveable(target_state)
        if step is not None:
            problems = verify_step_dir(self.directory, int(step))
            if problems:
                err = CheckpointCorrupt(
                    f"checkpoint step {int(step)} failed verification: "
                    + "; ".join(problems))
                self._observe_corrupt(err, step=int(step))
                raise err
            payload = self._restore_payload(int(step), template)
            return self._finish_restore(target_state, payload)

        steps = sorted(self._steps_on_disk(), reverse=True)
        if not steps:
            raise FileNotFoundError(f"no checkpoints under {self.directory!r}")
        newest = steps[0]
        first_err: Optional[BaseException] = None
        skipped: List[Dict[str, Any]] = []
        for s in steps:
            problems = verify_step_dir(self.directory, s)
            if problems:
                if first_err is None:
                    first_err = CheckpointCorrupt(
                        f"checkpoint step {s} failed verification: "
                        + "; ".join(problems))
                skipped.append({"step": s, "problems": problems})
                continue
            try:
                payload = self._restore_payload(s, template)
            except Exception as e:
                if first_err is None:
                    first_err = e
                skipped.append({"step": s, "problems": [repr(e)]})
                continue
            if s != newest:
                rollback = newest - s
                with self._mx:
                    self._rollback_steps += rollback
                self._emit("ckpt_rollback", from_step=newest, to_step=s,
                           rollback_steps=rollback, skipped=skipped)
            return self._finish_restore(target_state, payload)
        assert first_err is not None
        if isinstance(first_err, CheckpointCorrupt):
            # the walk-back exhausted the chain: NOTHING on disk verifies
            self._observe_corrupt(first_err, step=newest)
        raise first_err

    def _restore_payload(self, step: int, template: Dict[str, Any]
                         ) -> Dict[str, Any]:
        with self._op:
            try:
                return self.manager.restore(
                    step,
                    args=ocp.args.Composite(
                        state=ocp.args.StandardRestore(template),
                        meta=ocp.args.JsonRestore(),
                    ),
                )
            except (ValueError, KeyError) as e:
                # The template can legitimately disagree with the saved tree
                # on the OPTIONAL state entries: legacy checkpoints lack
                # 'comp' (pre-PowerSGD), 'guard' (pre-step-guard) and/or
                # 'control' (pre-adaptive-compression) entirely, and toggling
                # powersgd / --guard / --adaptive between save and
                # resume flips those entries between the empty marker {} and
                # {'on': ...} (Orbax raises ValueError for
                # template-missing-saved-key and KeyError for
                # saved-missing-template-key).  Fall back to ONE
                # template-free restore (saved structure as-is) and let
                # _from_saveable reconcile guard/comp against the target —
                # but first verify every OTHER entry matches the template's
                # structure/shape/dtype exactly, so a genuine mismatch
                # (resized params, renamed keys) still surfaces as the
                # ORIGINAL error instead of silently restoring garbage into
                # the caller's tree.
                try:
                    payload = self.manager.restore(
                        step,
                        args=ocp.args.Composite(
                            state=ocp.args.StandardRestore(),
                            meta=ocp.args.JsonRestore(),
                        ),
                    )
                except Exception:
                    raise e
                saved = payload["state"]
                if set(saved) - set(template):
                    raise e  # fields this build does not know — not legacy
                for k, tv in template.items():
                    if k in ("guard", "comp", "control"):
                        continue
                    if k not in saved:
                        raise e
                    if jax.tree.structure(tv) != jax.tree.structure(saved[k]):
                        raise e
                    for tl, sl in zip(jax.tree.leaves(tv),
                                      jax.tree.leaves(saved[k])):
                        if (tuple(np.shape(tl)) != tuple(np.shape(sl))
                                or np.asarray(tl).dtype
                                != np.asarray(sl).dtype):
                            raise e
                return payload

    def _finish_restore(self, target_state: TrainState,
                        payload: Dict[str, Any]
                        ) -> Tuple[TrainState, Dict[str, Any]]:
        state = _from_saveable(target_state, payload["state"])
        meta = dict(payload.get("meta") or {})
        if "best_metric" in meta:
            self.best_metric = float(meta["best_metric"])
        if "best_step" in meta:
            self.best_step = int(meta["best_step"])
        return state, meta

    def _steps_on_disk(self):
        with self._op:
            return set(self.manager.all_steps() or ())

    # ---------------------------------------------------------- observability

    def metrics(self) -> Dict[str, float]:
        """Host-emitter gauges/counters for Prometheus export; keys are
        declared in ``obs/registry.py``."""
        with self._mx:
            return {
                "ckpt/save_ms": self._save_ms,
                "ckpt/blocked_ms": self._blocked_ms,
                "ckpt/inflight": float(self._inflight),
                "ckpt/last_step": float(
                    -1 if self._last_step is None else self._last_step),
                "ckpt/age_s": time.monotonic() - self._mark_mono,
                "ckpt/rollback_steps": float(self._rollback_steps),
            }

    def heartbeat_fields(self) -> Dict[str, float]:
        """The two fields the watchdog's ``--max_ckpt_age`` check reads out
        of the heartbeat payload."""
        with self._mx:
            return {
                "last_ckpt_step": int(
                    -1 if self._last_step is None else self._last_step),
                "ckpt_age_s": time.monotonic() - self._mark_mono,
            }

    def _emit(self, kind: str, **fields) -> None:
        fl = self.flight
        if fl is not None:
            try:
                fl.record("ckpt", kind, **fields)
            except Exception:
                pass  # telemetry must never fail a save/restore
        ev = self.events
        if ev is None:
            return
        try:
            ev.emit(kind, **fields)
        except Exception:
            pass  # telemetry must never fail a save/restore

    def _observe_corrupt(self, err: BaseException, *, step: int) -> None:
        fl = self.flight
        if fl is None:
            return
        try:
            fl.observe(err, step=step)
        except Exception:
            pass  # forensics must never mask the corruption itself

    # ----------------------------------------------------------------- close

    def close(self):
        """Drain the background writer (never raising — close runs in
        ``finally`` blocks) and close the Orbax manager."""
        self._barrier(accrue=False, raise_error=False)
        with self._op:
            self.manager.close()


def _to_saveable(state: TrainState) -> Dict[str, Any]:
    from tpu_compressed_dp.control.state import control_to_dict
    from tpu_compressed_dp.train.guard import guard_to_dict

    d = {f.name: getattr(state, f.name) for f in dataclasses.fields(state)}
    # PRNG keys: store raw key data (typed keys are not serialisable)
    d["rng"] = jax.random.key_data(d["rng"])
    # ef/comp/guard/control == () when off; Orbax cannot round-trip an empty
    # container leaf.  GuardState/ControlState serialise as plain dicts so
    # the on-disk form needs no pytree registration agreement with a future
    # reader.
    d["ef"] = {"on": d["ef"]} if d["ef"] != () else {}
    d["comp"] = {"on": d["comp"]} if d["comp"] != () else {}
    d["guard"] = {"on": guard_to_dict(d["guard"])} if d["guard"] != () else {}
    d["control"] = ({"on": control_to_dict(d["control"])}
                    if d["control"] != () else {})
    return d


def _from_saveable(target: TrainState, d: Dict[str, Any]) -> TrainState:
    from tpu_compressed_dp.control.state import control_from_dict
    from tpu_compressed_dp.train.guard import guard_from_dict

    d = dict(d)
    d["rng"] = jax.random.wrap_key_data(np.asarray(d["rng"]))
    ef = d["ef"]
    d["ef"] = ef["on"] if "on" in ef else ()
    # comp/guard/control: a saved value wins; the empty marker {} (feature
    # was OFF at save time) or a missing key (checkpoint predates the field)
    # keeps the CALLER's value — a freshly-built warm start /
    # init_guard_state / init_control_state when resuming an old run with
    # powersgd / the guard / adaptive control newly enabled, () otherwise —
    # instead of clobbering it.
    if "comp" in d and "on" in d["comp"]:
        d["comp"] = d["comp"]["on"]
    else:
        d["comp"] = target.comp
    if "guard" in d and "on" in d["guard"]:
        d["guard"] = guard_from_dict(d["guard"]["on"])
    else:
        d["guard"] = target.guard
    if "control" in d and isinstance(d["control"], dict) \
            and "on" in d["control"]:
        d["control"] = control_from_dict(d["control"]["on"])
    else:
        d["control"] = target.control
    return dataclasses.replace(target, **d)


def save_checkpoint(directory: str, state: TrainState, meta: Optional[Dict] = None) -> int:
    """One-shot save (``save_checkpoint``, `train_imagenet_nv.py:663-669`)."""
    ckpt = Checkpointer(directory)
    try:
        return ckpt.save(state, meta)
    finally:
        ckpt.close()


def restore_checkpoint(directory: str, target_state: TrainState,
                       step: Optional[int] = None) -> Tuple[TrainState, Dict[str, Any]]:
    """One-shot restore (``--resume``, `train_imagenet_nv.py:193-198`)."""
    ckpt = Checkpointer(directory)
    try:
        return ckpt.restore(target_state, step)
    finally:
        ckpt.close()
