"""Wall-clock timing with device synchronisation.

Equivalent of the reference ``Timer`` (`CIFAR10/core.py:14-27`), which was
instantiated with ``torch.cuda.synchronize`` (`dawn.py:129`); on JAX the sync
is ``block_until_ready`` on a sentinel device value.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import jax

__all__ = ["Timer", "device_sync"]


def device_sync() -> None:
    """Block until all enqueued device work is complete.

    Implemented as a value fetch of a fresh sentinel computation: device
    queues are FIFO, so fetching the sentinel drains everything enqueued
    before it.  (``block_until_ready`` alone is not a reliable barrier on
    remote-tunneled backends — observed on axon to return pre-completion.)
    """
    import jax.numpy as jnp

    jax.device_get(jnp.zeros(()) + 0.0)


class Timer:
    """Split timer: each call returns the delta since the previous call and
    (optionally) accumulates it into ``total_time`` (`core.py:21-27`).

    Unlike the reference (which appended every split timestamp to a list
    forever — unbounded memory on long runs), only the LAST timestamp is
    kept; the split/total semantics are unchanged."""

    def __init__(self, synch: Optional[Callable[[], None]] = None):
        self.synch = synch or (lambda: None)
        self.synch()
        self.last_time = time.time()
        self.total_time = 0.0

    def __call__(self, include_in_total: bool = True) -> float:
        self.synch()
        now = time.time()
        delta_t = now - self.last_time
        self.last_time = now
        if include_in_total:
            self.total_time += delta_t
        return delta_t
