"""Deterministic fault injection ("chaos") for the robustness stack.

Two injection planes, both driven by the *step counter* rather than wall
clock or RNG, so a replay after checkpoint restore reproduces the exact same
faults (resume-safe by construction — the property the chaos drill's
crash-recovery case depends on):

  * **in-graph** (:func:`inject`, traced into the jitted step): overwrite a
    chosen worker's gradients or loss with NaN/Inf at chosen steps.  This is
    the adversary the step guard (:mod:`tpu_compressed_dp.train.guard`) must
    beat: one poisoned worker, everyone must skip identically and the
    EF/compressor state must stay clean.
  * **host-side** (:class:`CrashInjector`): raise :class:`ChaosCrash` out of
    the training loop at a chosen global step, exercising
    ``run_with_recovery``'s restore-and-replay path.  Fires once per
    process (a restored replay walking back through the crash step must not
    re-crash, or recovery could never make progress).

CLI surface: every harness takes ``--chaos SPEC`` where SPEC is
comma-separated ``key=value`` tokens (a bare ``nan``/``inf`` sets ``kind``):

    --chaos "nan,target=grads,steps=3+7,worker=1"
    --chaos "inf,target=loss,every=50"
    --chaos "crash=120"                  # host crash only, no in-graph fault
    --chaos "crash=mid_collective,crash_at_step=12,worker=3"
    --chaos "crash=during_remesh,crash_at_step=12,worker=3"
    --chaos "crash=preempt,crash_at_step=12"   # self-SIGTERM at step 12
    --chaos "peer_timeout=0.5"           # elastic: tighten gossip staleness

``crash=mid_collective`` arms the host crash in the **collective phase**:
the injector fires *after* the step has been dispatched (its collectives
are genuinely in flight under async dispatch) instead of before — the
deterministic stand-in for a worker dying inside an allreduce, consumed by
the elastic runtime (:mod:`tpu_compressed_dp.train.elastic`) as a simulated
peer failure of ``worker``.  ``crash=during_remesh`` arms the **remesh
phase**: the injector fires while survivors are inside
``ElasticRuntime.handle_failure`` — a SECOND worker dying during the
recovery from the first, the cascading-failure case the runtime must
re-enter failure handling for (unioned dead set, shrink restarted) rather
than committing a world that is already stale.  ``crash=preempt`` does not
raise at all: the injector sends the process a real ``SIGTERM``
(``os.kill(os.getpid(), ...)``) at the armed step — the deterministic
stand-in for a spot/preemptible VM reclaim, observed by
:class:`~tpu_compressed_dp.utils.resilience.PreemptionHandler` and turned
into the emergency-checkpoint-and-exit path.  Like every other fault here
all are keyed off the step counter, so a restored replay reproduces them
exactly.

``tools/chaos_drill.py`` runs the full injection matrix and asserts the
guard's invariants.
"""

from __future__ import annotations

import dataclasses
import os
import signal
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

__all__ = ["ChaosConfig", "ChaosCrash", "CrashInjector", "fires_at", "inject"]


class ChaosCrash(RuntimeError):
    """The injected host-side failure (plays the role of a preempted VM or a
    killed worker; anything but KeyboardInterrupt/SystemExit, which
    ``run_with_recovery`` deliberately re-raises)."""


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """One fault-injection scenario.

    kind:           'nan' | 'inf' — the poison value
    target:         'grads' (every element of the worker's local gradient) |
                    'loss' (the worker's scalar loss)
    steps:          global step indices (0-based, pre-increment — the value
                    of ``TrainState.step`` going *into* the step) at which
                    the in-graph fault fires
    every:          also fire whenever ``step % every == 0`` (0 = off)
    worker:         linearised data-parallel worker index to poison (over
                    (data,) or (data, seq) — see ``guard.worker_index``)
    crash_at_step:  host-side: raise :class:`ChaosCrash` before dispatching
                    this global step (-1 = off); fires once per process
    crash_mode:     'step' (raise before dispatch — the classic dead-process
                    crash) | 'mid_collective' (raise after dispatch, while
                    the step's collectives are in flight; the elastic
                    runtime interprets it as ``worker`` dying mid-allreduce)
                    | 'during_remesh' (raise inside the elastic failure
                    handler — a second worker dying while survivors are
                    already remeshing; the runtime unions the dead set and
                    re-enters failure handling)
                    | 'preempt' (no raise: send this process a real SIGTERM
                    before dispatching the step — the deterministic spot-VM
                    reclaim, handled by PreemptionHandler as an emergency
                    checkpoint + exit)
    peer_timeout:   elastic failure-detection budget in seconds: a peer
                    heartbeat older than this counts as dead, and a blocked
                    device fetch longer than this raises PeerFailed
                    (0 = use the runtime default)
    """

    kind: str = "nan"
    target: str = "grads"
    steps: Tuple[int, ...] = ()
    every: int = 0
    worker: int = 0
    crash_at_step: int = -1
    crash_mode: str = "step"
    peer_timeout: float = 0.0

    def __post_init__(self):
        if self.kind not in ("nan", "inf"):
            raise ValueError(f"chaos kind must be nan|inf, got {self.kind!r}")
        if self.target not in ("grads", "loss"):
            raise ValueError(
                f"chaos target must be grads|loss, got {self.target!r}")
        if self.every < 0 or self.worker < 0:
            raise ValueError("chaos every/worker must be >= 0")
        if self.crash_mode not in ("step", "mid_collective", "during_remesh",
                                   "preempt"):
            raise ValueError("chaos crash_mode must be step|mid_collective|"
                             f"during_remesh|preempt, got {self.crash_mode!r}")
        if self.peer_timeout < 0:
            raise ValueError("chaos peer_timeout must be >= 0")

    @property
    def injects_in_graph(self) -> bool:
        return bool(self.steps) or self.every > 0

    @classmethod
    def parse(cls, spec: str) -> "ChaosConfig":
        """Parse the ``--chaos`` CLI string (see module docstring)."""
        kw: dict = {}
        for tok in filter(None, (t.strip() for t in spec.split(","))):
            if "=" not in tok:
                if tok not in ("nan", "inf"):
                    raise ValueError(
                        f"bad --chaos token {tok!r}: bare tokens must be "
                        "nan|inf; everything else is key=value")
                kw["kind"] = tok
                continue
            k, v = tok.split("=", 1)
            k = k.strip()
            v = v.strip()
            if k in ("kind", "target"):
                kw[k] = v
            elif k == "steps":
                kw["steps"] = tuple(int(s) for s in v.split("+") if s)
            elif k in ("every", "worker"):
                kw[k] = int(v)
            elif k == "crash" and v in ("mid_collective", "during_remesh",
                                        "preempt"):
                # mode selector rides the crash key; the step itself comes
                # from a separate crash_at_step=N token
                kw["crash_mode"] = v
            elif k in ("crash", "crash_at_step"):
                kw["crash_at_step"] = int(v)
            elif k == "crash_mode":
                kw["crash_mode"] = v
            elif k == "peer_timeout":
                kw["peer_timeout"] = float(v)
            else:
                raise ValueError(
                    f"unknown --chaos key {k!r} (kind|target|steps|every|"
                    "worker|crash|crash_mode|peer_timeout)")
        return cls(**kw)

    def to_spec(self) -> str:
        """The canonical ``--chaos`` string: ``parse(c.to_spec()) == c`` for
        every config (the round-trip the elastic drill and replay tooling
        rely on to re-arm an identical scenario after a relaunch)."""
        toks = [self.kind]
        if self.target != "grads":
            toks.append(f"target={self.target}")
        if self.steps:
            toks.append("steps=" + "+".join(str(s) for s in self.steps))
        if self.every:
            toks.append(f"every={self.every}")
        if self.worker:
            toks.append(f"worker={self.worker}")
        if self.crash_at_step >= 0:
            toks.append(f"crash_at_step={self.crash_at_step}")
        if self.crash_mode != "step":
            toks.append(f"crash={self.crash_mode}")
        if self.peer_timeout:
            toks.append(f"peer_timeout={self.peer_timeout:g}")
        return ",".join(toks)


def fires_at(chaos: ChaosConfig, step: Array) -> Array:
    """Traced predicate: does the in-graph fault fire at ``step``?  Pure
    function of the step counter — replay-deterministic."""
    fire = jnp.asarray(False)
    for s in chaos.steps:
        fire = fire | (step == s)
    if chaos.every > 0:
        fire = fire | (step % chaos.every == 0)
    return fire


def inject(chaos: ChaosConfig, step: Array, widx: Array, loss: Array,
           grads: Any) -> Tuple[Array, Any]:
    """Poison ``loss`` or ``grads`` on the targeted worker at firing steps
    (identity everywhere else).  Runs inside the jitted step, *before* the
    guard's finiteness vote."""
    fire = fires_at(chaos, step) & (widx == chaos.worker)
    bad = float("nan") if chaos.kind == "nan" else float("inf")
    if chaos.target == "loss":
        loss = jnp.where(fire, jnp.asarray(bad, loss.dtype), loss)
    else:
        grads = jax.tree.map(
            lambda g: jnp.where(fire, jnp.asarray(bad, g.dtype), g), grads)
    return loss, grads


class CrashInjector:
    """Host-side crash at a global step, once per process.

    >>> crash = CrashInjector(chaos.crash_at_step)
    >>> crash.check(global_step)   # raises ChaosCrash at/after the step

    ``mode='mid_collective'`` defers the raise to the post-dispatch check:
    the loop calls ``check(step)`` before dispatch (phase ``'step'``, a
    no-op for this mode) and ``check(step, phase='mid_collective')`` right
    after, when the step's collectives are in flight.  The raised
    :class:`ChaosCrash` carries ``step``/``mode``/``worker`` so the elastic
    runtime can translate it into the simulated peer failure.
    """

    def __init__(self, crash_at_step: int, mode: str = "step",
                 worker: int = 0):
        self.crash_at_step = int(crash_at_step)
        self.mode = mode
        self.worker = int(worker)
        self.fired = False
        #: optional FlightRecorder (obs/flight.py): the injector notes the
        #: injection into the chaos ring the instant it fires — for the
        #: preempt mode this is the ONLY record the dying process gets to
        #: make before the SIGTERM lands
        self.flight = None

    def _note_fired(self, step: int) -> None:
        if self.flight is None:
            return
        try:
            self.flight.record("chaos", "crash_fired", step=int(step),
                               mode=self.mode, worker=self.worker,
                               crash_at_step=self.crash_at_step)
        except Exception:
            pass  # forensics must never alter the injected failure

    def check(self, step: int, phase: str = "step") -> None:
        if self.mode == "preempt":
            # no raise: deliver a REAL SIGTERM to this process, exactly what
            # a spot-VM reclaim does.  PreemptionHandler's flag (checked by
            # the loop right after) turns it into the emergency-save path.
            if (not self.fired and phase == "step"
                    and self.crash_at_step >= 0
                    and int(step) >= self.crash_at_step):
                self.fired = True
                self._note_fired(step)
                os.kill(os.getpid(), signal.SIGTERM)
            return
        # >= not ==: epoch-granular callers (the CNN harnesses check once
        # per batch with the attempted-step counter) must not miss the mark
        # when a skip/resume lands the counter past it
        if (not self.fired and phase == self.mode
                and self.crash_at_step >= 0
                and int(step) >= self.crash_at_step):
            self.fired = True
            self._note_fired(step)
            err = ChaosCrash(
                f"chaos: injected host crash at step {int(step)}"
                + (" (mid-collective)" if self.mode == "mid_collective"
                   else ""))
            err.step = int(step)
            err.mode = self.mode
            err.worker = self.worker
            raise err


def maybe_crash_injector(chaos: Optional[ChaosConfig]) -> Optional[CrashInjector]:
    """Convenience for the harnesses: an armed injector, or None."""
    if chaos is None or chaos.crash_at_step < 0:
        return None
    return CrashInjector(chaos.crash_at_step, mode=chaos.crash_mode,
                         worker=chaos.worker)
