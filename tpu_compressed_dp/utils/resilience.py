"""Failure detection and crash recovery.

The reference has neither (SURVEY.md §5): membership is fixed at launch,
crashes print a traceback (`train_imagenet_nv.py:704-716`), and spot-instance
recovery is "relaunch by hand" (`train.py:49`).  Net-new here:

  * ``Heartbeat`` — a background thread that writes ``{ts, step, payload}``
    to a JSON file at an interval; an external watchdog (or another host)
    reads it with :func:`read_heartbeat` / :func:`is_stale` to detect hung or
    dead workers.  Pure files, no control plane to operate.
  * ``run_with_recovery`` — wraps an epoch-style loop: on an exception it
    restores the latest checkpoint and replays from there, up to
    ``max_retries`` consecutive failures (progress between checkpoints
    resets the budget).  With Orbax checkpoints carrying the full
    ``TrainState`` (EF residual and RNG included), a replayed epoch is
    bitwise the run that would have happened without the crash.
  * ``PreemptionHandler`` — SIGTERM/SIGINT set a step-granularity flag; the
    harness loops poll it via :meth:`PreemptionHandler.check`, which raises
    :class:`Preempted` so the harness can drain any in-flight async
    checkpoint write, cut an emergency save, and exit with
    :data:`PREEMPT_EXIT` — the code ``tools/watchdog.py --relaunch``
    respawns immediately on (no backoff, no retry-budget burn).
  * ``spawn_supervised`` — the supervisor-side child launch shared by the
    watchdog and the fleet scheduler: composes the incarnation
    (``TCDP_RESTART_COUNT``) and elastic-rejoin (``TCDP_ELASTIC_DIR``,
    ``TCDP_RENDEZVOUS_*``) environment over the operator's own without
    clobbering it.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import threading
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

__all__ = ["Heartbeat", "read_heartbeat", "is_stale", "check_heartbeat",
           "run_with_recovery", "Preempted", "PreemptionHandler",
           "PREEMPT_EXIT", "spawn_supervised"]

#: exit code of a preempted-and-checkpointed harness (EX_TEMPFAIL: "try
#: again") — distinct from both clean exit (0) and crash (1), so the
#: watchdog can relaunch immediately without burning backoff or budget
PREEMPT_EXIT = 75


class Preempted(Exception):
    """The preemption flag was observed at a step boundary.  An ``Exception``
    (not ``BaseException``) so ``run_train_epoch``'s handler still attaches
    the live ``elastic_state`` on the way out — but ``run_with_recovery``
    re-raises it explicitly: a preemption must trigger the emergency-save
    path, never a restore-and-replay retry."""

    def __init__(self, msg: str, *, step: Optional[int] = None,
                 signum: Optional[int] = None):
        super().__init__(msg)
        self.step = step
        self.signum = signum


class PreemptionHandler:
    """Signal-flag bridge between the platform's preemption notice and the
    step loop.

    >>> handler = PreemptionHandler().install()
    >>> handler.check(step)     # raises Preempted once SIGTERM/SIGINT landed
    >>> handler.uninstall()     # ALWAYS, in finally: restore prior handlers

    The Python-level signal handler only sets a :class:`threading.Event` —
    async-signal-safe, no I/O, no raise from arbitrary bytecode — and the
    loop converts it to :class:`Preempted` at the next step boundary, so the
    interrupted state is always a consistent between-steps ``TrainState``.

    ``signal.signal`` only works on the main thread; off it (a harness
    driven from a test runner's worker thread) ``install`` degrades to an
    inert handler (``installed`` False, ``check`` never raises) rather than
    crashing the run.
    """

    def __init__(self, *, signals=(signal.SIGTERM, signal.SIGINT),
                 log: Callable[[str], None] = print):
        self.signals = tuple(signals)
        self.log = log
        self.installed = False
        self._event = threading.Event()
        self.signum: Optional[int] = None
        self._prev: Dict[int, Any] = {}

    def install(self) -> "PreemptionHandler":
        try:
            for s in self.signals:
                self._prev[s] = signal.signal(s, self._on_signal)
            self.installed = True
        except ValueError:
            # not on the main thread: leave the process default in place
            self._prev.clear()
            self.installed = False
        return self

    def _on_signal(self, signum, frame) -> None:
        self.signum = signum
        self._event.set()

    @property
    def triggered(self) -> bool:
        return self._event.is_set()

    def check(self, step: Optional[int] = None) -> None:
        """Raise :class:`Preempted` if the flag is set (call once per step)."""
        if self._event.is_set():
            try:
                name = signal.Signals(self.signum).name
            except (ValueError, TypeError):
                name = str(self.signum)
            self.log(f"preempt: {name} received; stopping at step {step}")
            raise Preempted(f"preempted by {name}", step=step,
                            signum=self.signum)

    def uninstall(self) -> None:
        """Restore the previous handlers (mandatory in ``finally`` — a leaked
        handler would swallow the next process's Ctrl-C)."""
        if not self.installed:
            return
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, TypeError, OSError):
                pass
        self._prev.clear()
        self.installed = False


class Heartbeat:
    """Background liveness file writer.

    >>> hb = Heartbeat(path, interval_s=10)
    >>> hb.update(step=123)   # cheap; call from the train loop
    >>> hb.stop()

    Every record carries an ``incarnation`` — monotonically increasing
    across process restarts, seeded from ``TCDP_RESTART_COUNT`` (exported
    by ``tools/watchdog.py --relaunch``).  A restarted worker's first
    heartbeat therefore carries a HIGHER incarnation than any file its
    previous life left behind, so elastic peers can tell "this rank came
    back" from "this is the stale file of a dead prior life".
    """

    def __init__(self, path: str, interval_s: float = 10.0,
                 payload: Optional[Dict[str, Any]] = None,
                 incarnation: Optional[int] = None):
        self.path = path
        self.interval_s = interval_s
        self.payload = dict(payload or {})
        if incarnation is None:
            incarnation = int(os.environ.get("TCDP_RESTART_COUNT", "0") or 0)
        self.incarnation = int(incarnation)
        self._step = 0
        # update() runs on the train loop thread while _write() iterates the
        # payload on the writer thread: unsynchronised, json.dump raises
        # "dict changed size during iteration" intermittently (and the
        # writer thread died silently, turning a live worker into a
        # stale-heartbeat false positive).  The lock guards the mutation;
        # _write snapshots under it and serialises/writes outside it, so
        # the train loop never blocks on disk.
        self._lock = threading.Lock()
        #: first exception the writer thread hit (None = healthy); surfaced
        #: rather than swallowed so tests and watchdog wrappers can assert
        self.last_error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._write()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def update(self, step: int, **payload) -> None:
        with self._lock:
            self._step = int(step)
            self.payload.update(payload)

    def _write(self) -> None:
        with self._lock:
            rec = {"ts": time.time(), "step": self._step,
                   "incarnation": self.incarnation, **self.payload}
        # pid-unique tmp name: two lives of a relaunched worker racing on
        # the same heartbeat path must not interleave writes into one tmp
        # file (the os.replace itself is atomic either way)
        tmp = f"{self.path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, self.path)  # atomic: readers never see partial JSON

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._write()
            except Exception as e:  # e.g. disk full: record, keep beating
                with self._lock:
                    self.last_error = e

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=self.interval_s + 1)
        self._write()


def spawn_supervised(cmd: Sequence[str], *,
                     restart_count: int,
                     elastic_dir: Optional[str] = None,
                     env: Optional[Dict[str, str]] = None,
                     extra_env: Optional[Dict[str, str]] = None,
                     popen: Callable[..., "subprocess.Popen"] = subprocess.Popen,
                     log: Callable[[str], None] = print):
    """Launch one supervised child with the incarnation/rejoin environment
    — the spawn path shared by ``tools/watchdog.py --relaunch`` and the
    fleet's subprocess controller (``tools/fleet.py``).

    The child environment is a COPY of ``env`` (default ``os.environ``)
    with only the supervision keys layered on top — an operator-set
    variable is never clobbered unless the supervisor owns it:

    * ``TCDP_RESTART_COUNT`` — supervisor-owned, always written: the
      child Heartbeat's incarnation must be strictly larger each respawn.
    * ``TCDP_ELASTIC_DIR`` + (when the rendezvous directory holds a
      committed world epoch) ``TCDP_RENDEZVOUS_EPOCH``/``..._ADDR`` —
      only with ``elastic_dir``: the rejoin hint that lands a restarted
      host in the RUNNING world's join barrier
      (``train/rendezvous.maybe_rejoin_from_env``) instead of forming a
      fresh one.  Without a committed epoch the rendezvous keys are left
      exactly as the operator set them.
    * ``extra_env`` — caller-owned additions (the fleet's ``TCDP_JOB_ID``
      and world/device assignment); applied last, so they win.

    ``popen`` is injectable so unit tests capture the composed
    environment without forking (tests/test_fleet.py)."""
    child_env = dict(os.environ if env is None else env)
    child_env["TCDP_RESTART_COUNT"] = str(int(restart_count))
    if elastic_dir:
        from tpu_compressed_dp.train.rendezvous import (DIR_ENV, export_env,
                                                        read_epoch)
        child_env[DIR_ENV] = elastic_dir
        rec = read_epoch(elastic_dir)
        if rec is not None:
            export_env(child_env, rec)
            log(f"spawn: rejoin hint: world epoch {rec['epoch']} "
                f"@ {rec.get('address')}")
    if extra_env:
        child_env.update({str(k): str(v) for k, v in extra_env.items()})
    return popen(list(cmd), env=child_env)


def read_heartbeat(path: str) -> Optional[Dict[str, Any]]:
    """Parse a heartbeat file; ``None`` on ANY unreadable content.

    The writer's atomic-replace means a well-behaved filesystem never
    shows a torn record, but elastic gossip reads peers' files over shared
    storage where torn/truncated reads DO happen (NFS close-to-open,
    object-store gateways) — so every decode failure (truncated JSON,
    garbage bytes, a non-object payload) degrades to "no heartbeat", never
    an exception out of the failure detector."""
    try:
        with open(path, "rb") as f:
            rec = json.loads(f.read().decode("utf-8"))
    except (OSError, ValueError):
        # ValueError covers json.JSONDecodeError and UnicodeDecodeError
        return None
    return rec if isinstance(rec, dict) else None


def is_stale(path: str, max_age_s: float) -> bool:
    """True when the heartbeat is missing, unreadable, lacks a numeric
    ``ts``, or is older than ``max_age_s``."""
    hb = read_heartbeat(path)
    if hb is None:
        return True
    ts = hb.get("ts")
    if not isinstance(ts, (int, float)) or isinstance(ts, bool):
        return True
    return (time.time() - ts) > max_age_s


def check_heartbeat(path: str, *, max_age_s: float = 60.0,
                    max_wedge_steps: Optional[int] = None,
                    min_steps_per_sec: Optional[float] = None,
                    max_step_p95_ms: Optional[float] = None,
                    max_ckpt_age_s: Optional[float] = None,
                    max_stream_lag_s: Optional[float] = None,
                    max_straggler_skew_s: Optional[float] = None,
                    now: Optional[float] = None,
                    hb: Optional[Dict[str, Any]] = None) -> list:
    """Health-check a heartbeat file; returns a list of problem strings
    (empty = healthy) — the check-only half of the ROADMAP watchdog,
    consumed by ``tools/watchdog.py --check``.

    Three independent failure modes, each reading a different part of the
    payload the harnesses write:

    * **dead/stale** — file missing, unreadable, or ``ts`` older than
      ``max_age_s``: the writer thread (and so the process) is gone.
    * **wedged** — the process is alive and ``step`` advances, but
      ``last_good_step`` (the step-guard's applied-update watermark) has
      fallen more than ``max_wedge_steps`` behind: every step is being
      vetoed — exactly the wedge a liveness check alone cannot see.
    * **stalled** — the telemetry snapshot's ``steps_per_sec`` (from the
      :class:`~tpu_compressed_dp.obs.trace.StepTimeline` window) has
      dropped below ``min_steps_per_sec``: alive, applying updates, but
      crawling (data stall, thrashing input pipeline).
    * **slow tail** — the telemetry snapshot's ``step_p95_ms`` (the
      timeline window's tail latency) exceeds ``max_step_p95_ms``: the
      MEAN rate still looks fine but the tail regressed — the perf-gate
      bound (``benchmarks/perf_pins.json``) enforced live instead of at
      test time, and the first symptom of a degrading interconnect or a
      periodic stall the mean averages away.
    * **checkpoint-stale** — ``ckpt_age_s`` (written from
      ``Checkpointer.heartbeat_fields``) plus the heartbeat's own age
      exceeds ``max_ckpt_age_s``: training advances but nothing durable is
      landing — a wedged async writer or a full/readonly checkpoint disk,
      the failure a crash would silently amplify into lost work.
    * **stream-stale** — ``stream_lag_s`` (written from
      ``StreamWriter.heartbeat_fields``, or by ``tools/stream_serve.py``
      on the consumer side) plus the heartbeat's own age exceeds
      ``max_stream_lag_s``: the delta state stream has stopped advancing —
      warm rejoin and the model-push channel are serving stale parameters.
    * **straggler** — ``straggler_skew_s`` (the flight recorder's live
      cross-rank skew of the mean host step time, from
      ``FlightRecorder.publish``) exceeds ``max_straggler_skew_s``: one
      rank is pacing every collective for the whole world — the failure
      mode worth catching BEFORE it becomes a peer-timeout remesh.

    Wedge/stall/checkpoint checks are skipped when their payload fields are
    absent (guard/telemetry/checkpointing off) — absence of optional
    telemetry is not a fault.
    Pass ``hb`` (an already-parsed record) to check a single consistent
    read — callers that also inspect the payload should read once and
    share it, not race a concurrent ``os.replace`` between two reads.
    """
    now = time.time() if now is None else now
    if hb is None:
        hb = read_heartbeat(path)
    if hb is None:
        return [f"heartbeat missing or unreadable: {path}"]
    problems = []
    age = now - float(hb.get("ts", 0.0))
    if age > max_age_s:
        problems.append(
            f"stale: heartbeat is {age:.1f}s old (> {max_age_s:g}s) — "
            "worker dead or hung")
    if max_wedge_steps is not None and "last_good_step" in hb:
        lag = int(hb.get("step", 0)) - int(hb["last_good_step"])
        if lag > max_wedge_steps:
            problems.append(
                f"wedged: last applied update is {lag} steps behind the "
                f"attempt counter (> {max_wedge_steps}) — every step is "
                "being skipped")
    tele = hb.get("telemetry") or {}
    if (min_steps_per_sec is not None
            and tele.get("steps_per_sec") is not None
            and float(tele["steps_per_sec"]) < min_steps_per_sec):
        problems.append(
            f"stalled: step rate {float(tele['steps_per_sec']):.4g}/s "
            f"below the {min_steps_per_sec:g}/s floor")
    if (max_step_p95_ms is not None
            and tele.get("step_p95_ms") is not None
            and float(tele["step_p95_ms"]) > max_step_p95_ms):
        problems.append(
            f"slow tail: p95 step time {float(tele['step_p95_ms']):.4g}ms "
            f"exceeds the {max_step_p95_ms:g}ms bound — the tail regressed "
            "past the run's modeled/pinned budget")
    if max_ckpt_age_s is not None and hb.get("ckpt_age_s") is not None:
        # the payload's age was computed when the heartbeat was written;
        # add the heartbeat's own age so a dying writer cannot freeze the
        # checkpoint clock at a healthy-looking value
        ckpt_age = float(hb["ckpt_age_s"]) + max(age, 0.0)
        if ckpt_age > max_ckpt_age_s:
            problems.append(
                f"checkpoint stale: last durable save {ckpt_age:.1f}s ago "
                f"(> {max_ckpt_age_s:g}s, last_ckpt_step="
                f"{hb.get('last_ckpt_step')}) — a crash now loses that much "
                "work")
    if max_stream_lag_s is not None and hb.get("stream_lag_s") is not None:
        # same heartbeat-age correction as the checkpoint clock: a dying
        # writer must not freeze the stream lag at a healthy value
        lag = float(hb["stream_lag_s"]) + max(age, 0.0)
        if lag > max_stream_lag_s:
            problems.append(
                f"stream stale: last delta segment {lag:.1f}s ago "
                f"(> {max_stream_lag_s:g}s, stream_last_step="
                f"{hb.get('stream_last_step')}) — warm rejoin and serving "
                "consumers are falling behind the run")
    skew = hb.get("straggler_skew_s", tele.get("straggler_skew_s"))
    if max_straggler_skew_s is not None and skew is not None:
        if float(skew) > max_straggler_skew_s:
            rank = hb.get("straggler_rank")
            problems.append(
                f"straggler: cross-rank step-time skew {float(skew):.4g}s "
                f"exceeds the {max_straggler_skew_s:g}s bound"
                + (f" (slowest rank {int(rank)})"
                   if isinstance(rank, (int, float)) and rank >= 0 else "")
                + " — one rank is pacing the whole world's collectives")
    return problems


def run_with_recovery(
    epoch_fn: Callable[[Any, int], Any],
    state: Any,
    epochs: int,
    *,
    checkpointer=None,
    start_epoch: int = 0,
    max_retries: int = 3,
    on_restore: Optional[Callable[[Any], Any]] = None,
    flight=None,
) -> Tuple[Any, Dict[str, int]]:
    """Run ``state = epoch_fn(state, epoch)`` for each epoch, restoring from
    ``checkpointer`` (latest step) and retrying after exceptions.

    ``on_restore`` re-places a restored state onto the mesh (e.g.
    ``TrainState.with_mesh_sharding`` / ``place_lm_state``).  Epoch indices
    re-run after a restore are derived from the checkpoint meta's ``epoch``
    (saved by the harnesses), falling back to restarting the failed epoch.
    Returns ``(state, {'failures': n, 'restores': m})``.

    :class:`Preempted` is re-raised untouched (the harness's emergency-save
    path owns it, not the retry budget).  Restore-time *corruption* never
    consumes a retry either: ``Checkpointer.restore`` walks back to the
    newest verifiable checkpoint internally, so a torn latest write costs a
    rollback (accounted in ``ckpt/rollback_steps``), not a failure.

    ``flight`` (a :class:`~tpu_compressed_dp.obs.flight.FlightRecorder`)
    dumps a blackbox bundle when the retry budget is exhausted — the
    TERMINAL error, the one the process dies with; per-retry failures are
    recoverable by construction and stay out of the shared dir.
    """
    failures = restores = 0
    epoch = start_epoch
    while epoch < epochs:
        try:
            state = epoch_fn(state, epoch)
            failures = 0  # progress resets the retry budget
            epoch += 1
        except (KeyboardInterrupt, SystemExit, Preempted):
            raise
        except Exception as train_err:
            failures += 1
            if checkpointer is None or failures > max_retries:
                if flight is not None:
                    flight.observe(train_err, retries=failures - 1,
                                   terminal=True)
                raise
            try:
                state, meta = checkpointer.restore(state)
            except FileNotFoundError:
                # crashed before the FIRST checkpoint existed: there is
                # nothing to replay from, and letting the restore's
                # FileNotFoundError propagate would mask the actual
                # training failure the operator needs to see
                if flight is not None:
                    flight.observe(train_err, retries=failures - 1,
                                   terminal=True)
                raise train_err
            if on_restore is not None:
                state = on_restore(state)
            restores += 1
            epoch = int(meta.get("epoch", epoch - 1)) + 1
    return state, {"failures": failures, "restores": restores}
