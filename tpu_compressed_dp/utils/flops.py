"""Analytic model FLOPs and MFU (model-FLOPs utilisation).

The reference's telemetry stopped at images/sec (`IMAGENET/training/
logger.py:66-68`); MFU normalises that to chip capability so throughput
claims transfer across hardware (VERDICT r2 #3).

Conventions (the standard ones, cf. PaLM appendix B):
  * model FLOPs = the FLOPs of the MODEL's forward+backward only — the
    compression/comm machinery is deliberately excluded (that overhead
    showing up as lost MFU is exactly what the metric is for);
  * backward = 2x forward (two matmuls per forward matmul), so
    ``train = 3 x forward``;
  * MFU is quoted against the chip's peak dense-matmul rate in its native
    matmul precision (bf16 for TPUs) regardless of the activation dtype in
    use — fp32 compute then simply shows as lower MFU.

Forward FLOPs come from XLA's own cost model (``compiled.cost_analysis()``)
of the jitted single-device forward — exact for any architecture (graph nets
included) with no hand-maintained per-layer walk; transformers at sharded
scale use the closed-form ``6N + 12*L*d*s`` per token instead.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

__all__ = [
    "fwd_flops_xla",
    "train_flops_per_step",
    "transformer_train_flops_per_token",
    "chip_peak_flops",
    "mfu",
    "throughput_record",
    "cnn_mfu_record",
    "PEAK_FLOPS_BF16",
]

# Peak dense-matmul TFLOP/s per chip, bf16 (public spec sheets).  Keyed by
# `device_kind` prefix; unknown kinds return None and MFU is omitted rather
# than quoted against a guessed peak.
PEAK_FLOPS_BF16: Dict[str, float] = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,        # v5p (after the more-specific v5-lite keys)
    "TPU v6 lite": 918e12,   # v6e (Trillium)
    "TPU v6e": 918e12,
}


def fwd_flops_xla(fn: Callable, *args: Any) -> Optional[float]:
    """FLOPs of one call of ``fn(*args)`` per XLA's compiled cost model.

    ``fn`` should be the bare model forward (apply_fn closed over
    hyperparams), NOT the train step — cost analysis of the step would count
    compression, optimizer, and collective work as "model" FLOPs.  Returns
    None where the backend doesn't expose an estimate.
    """
    # lower on abstract shapes: works with donated/deleted buffers and
    # moves no data to the device.  Tracing errors in `fn` propagate — only
    # a missing backend cost model degrades to None.
    abstract = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(jnp.shape(a), jnp.result_type(a)),
        args)
    compiled = jax.jit(fn).lower(*abstract).compile()
    try:
        cost = compiled.cost_analysis()
    except Exception:  # pragma: no cover - backend without cost model
        return None
    if isinstance(cost, list):  # older jax returns one dict per device
        cost = cost[0] if cost else {}
    val = float((cost or {}).get("flops", 0.0))
    return val if val > 0 else None


def train_flops_per_step(fwd_flops: float) -> float:
    """fwd + bwd = 3x fwd (bwd re-derives two matmuls per forward matmul)."""
    return 3.0 * fwd_flops


def transformer_train_flops_per_token(
    n_params: int, n_layers: int, d_model: int, seq_len: int
) -> float:
    """The standard decoder LM accounting (PaLM appendix B): ``6N`` for the
    parameter matmuls (2N fwd, 4N bwd) plus ``12 L d s`` for the attention
    score/value matmuls (QK^T and AV, fwd+bwd, causal factor ignored —
    matching common MFU practice, which makes causal models look slightly
    better, not worse)."""
    return 6.0 * n_params + 12.0 * n_layers * d_model * seq_len


def chip_peak_flops(device=None) -> Optional[float]:
    """Peak bf16 TFLOP/s of ``device`` (default: first local device)."""
    if device is None:
        devs = jax.local_devices()
        if not devs:
            return None
        device = devs[0]
    kind = getattr(device, "device_kind", "") or ""
    # longest-prefix match so "TPU v5 lite" doesn't resolve to "TPU v5"
    best = None
    for prefix, peak in PEAK_FLOPS_BF16.items():
        if kind.startswith(prefix) and (best is None or len(prefix) > best[0]):
            best = (len(prefix), peak)
    return best[1] if best else None


def mfu(model_flops_per_sec: float, device=None) -> Optional[float]:
    """``model_flops_per_sec / chip_peak`` — None off-TPU / unknown chip."""
    peak = chip_peak_flops(device)
    if not peak or model_flops_per_sec <= 0:
        return None
    return model_flops_per_sec / peak


def throughput_record(fwd_flops: Optional[float], steps_per_sec: float,
                      *, examples_per_sec: Optional[float] = None,
                      tokens_per_sec: Optional[float] = None
                      ) -> Dict[str, float]:
    """The registry-named throughput/MFU telemetry for one window.

    ``fwd_flops`` is the PER-CHIP forward cost of one step (from
    :func:`fwd_flops_xla` at the per-chip batch shape, or a closed form
    divided by chip count); shared by all three harness epilogues so
    examples/s, tokens/s, TFLOP/s-per-chip and MFU are computed the same
    way everywhere.  MFU is omitted off-TPU (unknown peak), TFLOPs when the
    backend exposes no cost model."""
    rec: Dict[str, float] = {}
    if examples_per_sec is not None:
        rec["throughput/examples_per_sec"] = examples_per_sec
    if tokens_per_sec is not None:
        rec["throughput/tokens_per_sec"] = tokens_per_sec
    if fwd_flops is None or steps_per_sec <= 0:
        return rec
    per_chip = train_flops_per_step(fwd_flops) * steps_per_sec
    rec["throughput/model_tflops_per_chip"] = per_chip / 1e12
    u = mfu(per_chip)
    if u is not None:
        rec["throughput/mfu"] = u
    return rec


def cnn_mfu_record(apply_fn, params, batch_stats, input_shape,
                   steps_per_sec: float) -> Dict[str, float]:
    """The benchmark-record MFU fields for a CNN-style ``apply_fn`` (the
    shared epilogue of bench.py and bench/sweep.py): forward FLOPs from the
    XLA cost model at the given per-chip input shape, train = 3x fwd at the
    measured step rate, ``mfu`` vs the chip's bf16 peak.  Empty dict where
    the backend exposes no cost model; ``mfu`` omitted off-TPU."""
    fwd = fwd_flops_xla(
        lambda p, s, x: apply_fn(p, s, x, True, {}),
        params, batch_stats,
        jnp.zeros(input_shape, jnp.float32))
    if fwd is None:
        return {}
    per_chip = train_flops_per_step(fwd) * steps_per_sec
    rec = {"model_tflops_per_sec_per_chip": round(per_chip / 1e12, 3)}
    u = mfu(per_chip)
    if u is not None:
        rec["mfu"] = round(u, 4)
    return rec
