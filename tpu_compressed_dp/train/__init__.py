from tpu_compressed_dp.train import optim, schedules, state, step  # noqa: F401
