"""SGD with schedule-valued hyper-parameters, as pure JAX.

Functional re-design of the reference's ``TorchOptimiser``/``SGD`` pair
(`CIFAR10/torch_backend.py:122-143`): hyper-parameters may be callables of the
step number, re-evaluated every step inside the jitted train step (the
reference re-evaluated them in Python and poked ``param_groups``).  Update
rule matches ``torch.optim.SGD`` (including Nesterov), which is what both
reference harnesses use (`dawn.py:146-148`, `train_imagenet_nv.py:185-191`).

Also provides the BatchNorm weight-decay exclusion of
`IMAGENET/training/experimental_utils.py:3-22` (``--no-bn-wd``) as a pytree
mask.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Union

import jax
import jax.numpy as jnp

Array = jax.Array
Schedule = Callable[[Array], Array]
ScalarOrSchedule = Union[float, Schedule]

__all__ = ["SGD", "bn_wd_mask"]


def _value(v: ScalarOrSchedule, step: Array) -> Array:
    """Evaluate a hyper-parameter: callable-of-step or constant.

    Mirrors ``TorchOptimiser.param_values`` (`torch_backend.py:129-130`).
    """
    return v(step) if callable(v) else jnp.asarray(v, jnp.float32)


@dataclasses.dataclass(frozen=True)
class SGD:
    """torch-semantics SGD: ``d = g + wd*p; buf = mu*buf + (1-damp)*d;
    d = d + mu*buf if nesterov; p -= lr*d``.

    ``wd_mask`` is a pytree of bools (or a predicate applied via
    ``bn_wd_mask``) selecting which params receive weight decay.
    """

    lr: ScalarOrSchedule = 0.0
    momentum: ScalarOrSchedule = 0.0
    weight_decay: ScalarOrSchedule = 0.0
    dampening: float = 0.0
    nesterov: bool = False
    wd_mask: Optional[Any] = None

    def init(self, params: Any) -> Any:
        """Momentum buffers, zero-initialised.

        torch seeds the buffer with the first gradient rather than
        ``mu*0 + (1-damp)*g``; with ``dampening=0`` (the only value the
        reference uses) zero-init is identical from step one onward.
        """
        return {"momentum": jax.tree.map(jnp.zeros_like, params)}

    def apply(self, params: Any, grads: Any, opt_state: Any, step: Array):
        lr = _value(self.lr, step)
        mu = _value(self.momentum, step)
        wd = _value(self.weight_decay, step)
        mask = self.wd_mask if self.wd_mask is not None else jax.tree.map(lambda _: True, params)

        def upd(p, g, buf, use_wd):
            g = g.astype(jnp.float32)
            d = g + wd * p if use_wd else g
            buf = mu * buf + (1.0 - self.dampening) * d
            d = d + mu * buf if self.nesterov else buf
            return p - lr * d, buf

        flat_p, treedef = jax.tree.flatten(params)
        flat_g = jax.tree.leaves(grads)
        flat_b = jax.tree.leaves(opt_state["momentum"])
        flat_m = jax.tree.leaves(mask)
        new_p, new_b = [], []
        for p, g, b, m in zip(flat_p, flat_g, flat_b, flat_m):
            np_, nb = upd(p, g, b, bool(m))
            new_p.append(np_)
            new_b.append(nb)
        return (
            jax.tree.unflatten(treedef, new_p),
            {"momentum": jax.tree.unflatten(treedef, new_b)},
        )


def bn_wd_mask(params: Any, is_excluded: Optional[Callable[[tuple], bool]] = None) -> Any:
    """True where weight decay applies; False for BatchNorm params.

    Equivalent of ``bnwd_optim_params``/``split_bn_params``
    (`experimental_utils.py:5-22`), which exclude all parameters belonging to
    BatchNorm modules.  By default a leaf is excluded when any path component
    mentions batch-norm (flax modules named ``bn*`` / ``BatchNorm*``).
    """

    def default_excluded(path: tuple) -> bool:
        return any(("bn" in str(k).lower() or "batchnorm" in str(k).lower()) for k in path)

    pred = is_excluded or default_excluded
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree.structure(params)
    vals = [not pred(tuple(_key_str(k) for k in path)) for path, _ in flat]
    return jax.tree.unflatten(treedef, vals)


def _key_str(k) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)
