"""Jitted LM pretrain step over a (data, seq, tensor) mesh.

Composes the three parallelism axes the Llama stretch config needs
(BASELINE.json; none exist in the reference, SURVEY.md §2.2):

  * ``data`` — batch sharding; gradients compress-then-psum across it (and
    across ``seq``), via the same sync engine as the CNN harnesses
    (:func:`tpu_compressed_dp.parallel.dp.make_grad_sync` — layerwise or
    entire-model, all six methods, simulate or wire, error feedback).
  * ``seq`` — sequence sharding; attention runs as a ring
    (:mod:`tpu_compressed_dp.ops.ring_attention`).  A (data, seq) pair is one
    "compression worker": each holds a distinct micro-slice of tokens, so the
    gradient reduction spans the combined ``("data", "seq")`` axes.
  * ``tensor`` — megatron-style sharded layers inside the model
    (:mod:`tpu_compressed_dp.models.transformer`); TP-internal reductions
    (attention/MLP output psums, vocab-parallel loss, replicated-param
    cotangents) are exact and uncompressed, mirroring how the reference
    compressed only the *data-parallel* gradient exchange.

Everything is one ``shard_map`` over the full mesh: tensor-sharded params
arrive as local shards, replicated params are marked device-varying over
(data, seq) (same pcast trick as train/step.py) so the compressed sync — not
shard_map's AD — owns the data-axis reduction.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from tpu_compressed_dp import compat
from tpu_compressed_dp.compat import shard_map

from tpu_compressed_dp.models.transformer import (
    LlamaConfig,
    apply_llama,
    fused_head_xent,
    param_specs,
    use_fused_head_xent,
    vocab_parallel_xent,
)
from tpu_compressed_dp.obs import trace as obs_trace
from tpu_compressed_dp.parallel.dp import (
    CompressionConfig,
    make_grouped_grad_sync,
    make_sharded_clip,
)
from tpu_compressed_dp.train import guard as guard_mod
from tpu_compressed_dp.train.guard import GuardConfig
from tpu_compressed_dp.train.optim import SGD
from tpu_compressed_dp.train.state import TrainState
from tpu_compressed_dp.train.step import optimizer_lr
from tpu_compressed_dp.utils import chaos as chaos_mod

Array = jax.Array

__all__ = ["make_lm_train_step", "init_lm_ef_state", "init_lm_comp_state",
           "lm_state_specs", "make_lm_mesh"]

LM_AXES = ("data", "seq", "tensor")


def make_lm_mesh(data: int, seq: int = 1, tensor: int = 1) -> Mesh:
    from tpu_compressed_dp.parallel.mesh import make_mesh

    return make_mesh((data, seq, tensor), LM_AXES)


def init_lm_ef_state(cfg: LlamaConfig, params: Any, comp: CompressionConfig,
                     mesh: Mesh) -> Any:
    """EF residual with a leading (data*seq) worker axis; tensor-sharded dims
    follow the param's own sharding (each tensor shard keeps its own
    residual slice)."""
    if not comp.error_feedback:
        return ()
    workers = mesh.shape["data"] * mesh.shape["seq"]
    return jax.tree.map(
        lambda p: jnp.zeros((workers,) + p.shape, jnp.float32), params
    )


def _ef_specs(pspecs: Any) -> Any:
    return jax.tree.map(
        lambda s: P(("data", "seq"), *s), pspecs,
        is_leaf=lambda x: isinstance(x, P),
    )


def _lm_is_sharded(cfg: LlamaConfig):
    pspec_leaves = jax.tree.leaves(
        param_specs(cfg), is_leaf=lambda x: isinstance(x, P))
    return [any(ax == "tensor" for ax in spec) for spec in pspec_leaves]


def init_lm_comp_state(cfg: LlamaConfig, params: Any, comp: CompressionConfig,
                       mesh: Mesh) -> Any:
    """Compressor state (PowerSGD warm-start Q) for the LM step, with the
    same signature grouping ``make_lm_train_step``'s grouped sync uses and a
    leading (data*seq) worker axis like :func:`init_lm_ef_state`.

    Tensor-sharded parameter groups sync on per-shard flats whose sizes this
    (global-shape) init cannot see, so stateful compression currently
    requires ``tensor == 1``; replicated-signature groups are what the DP
    sync engine compresses anyway.
    """
    from tpu_compressed_dp.ops.compressors import canonical_name
    from tpu_compressed_dp.parallel.dp import init_comp_state_grouped

    if canonical_name(comp.method) != "powersgd":
        return ()
    if mesh.shape.get("tensor", 1) > 1:
        raise NotImplementedError(
            "powersgd over tensor-sharded params needs shard-local warm "
            "starts; run it on a (data[, seq]) mesh (tensor=1)")
    workers = mesh.shape["data"] * mesh.shape["seq"]
    return init_comp_state_grouped(
        params, comp, _lm_is_sharded(cfg), "tensor", workers)


def lm_state_specs(cfg: LlamaConfig, comp: CompressionConfig) -> TrainState:
    """PartitionSpec pytree for the LM TrainState (shard_map in/out specs)."""
    pspecs = param_specs(cfg)
    return TrainState(
        step=P(),
        params=pspecs,
        batch_stats=P(),
        opt_state={"momentum": pspecs},
        ef=_ef_specs(pspecs) if comp.error_feedback else P(),
        rng=P(),
        # compressor state (powersgd warm-start Q): leading (data, seq)
        # worker axis, inner dims unsharded — build with
        # init_comp_state_grouped(..., num_devices=data*seq)
        comp=P(("data", "seq")),
        # step-guard state: replicated (the finiteness vote makes it
        # identical on every worker)
        guard=P(),
        # adaptive-compression control state: replicated, host-mutated only
        control=P(),
    )


def place_lm_state(state: TrainState, cfg: LlamaConfig, comp: CompressionConfig,
                   mesh: Mesh) -> TrainState:
    """Shard a (restored) TrainState onto the 3-D mesh per lm_state_specs —
    the LM analog of ``TrainState.with_mesh_sharding`` (checkpoint restore
    lands everything on one device)."""
    return state.place_with_specs(lm_state_specs(cfg, comp), mesh)


def make_lm_train_step(
    cfg: LlamaConfig,
    optimizer: SGD,
    comp_cfg: CompressionConfig,
    mesh: Mesh,
    *,
    clip_norm: float = 0.0,
    clip_sent_norm: float = 0.0,
    donate: bool = True,
    guard_cfg: Optional[GuardConfig] = None,
    chaos: Optional["chaos_mod.ChaosConfig"] = None,
):
    """Build ``train_step(state, batch) -> (state, metrics)``.

    ``batch``: ``{'input': [B, T] int32, 'target': [B, T] int32}``, ``B``
    divisible by the data axis, ``T`` by the seq axis.

    ``clip_norm`` / ``clip_sent_norm``: the EF-with-momentum stabilisers of
    :func:`tpu_compressed_dp.train.step.make_train_step` (local-gradient /
    post-aggregation L2 clip).  Norms span the FULL model gradient: squared
    norms of tensor-SHARDED leaves psum over the tensor axis; replicated
    leaves (already psum'd by shard_map AD) count once.

    ``guard_cfg`` / ``chaos``: the step guard and fault injection of
    :func:`tpu_compressed_dp.train.step.make_train_step`.  The finiteness
    vote spans the WHOLE mesh (data, seq, tensor): a NaN on one tensor
    shard's gradient slice must veto the update on every replica, or the
    tensor-sharded params would de-synchronise.  Chaos targets one
    (data, seq) compression worker across all its tensor shards.

    ``comp_cfg.sync_overlap > 1`` chunk-pipelines each replication
    signature's sync (the grouped wrapper's base engines dispatch through
    :mod:`tpu_compressed_dp.parallel.overlap`): K reverse-topological chunk
    collectives per signature, interleavable with the remaining backward.
    The per-chunk optimizer interleave stays a pure-DP
    (:func:`~tpu_compressed_dp.train.step.make_train_step`) optimisation —
    signature groups interleave leaves across chunk boundaries here, so the
    update runs whole-tree after the chunked sync.
    """
    cfg.validate_mesh(mesh.shape["tensor"])
    from tpu_compressed_dp.ops.compressors import canonical_name

    if (canonical_name(comp_cfg.method) == "powersgd"
            and mesh.shape["tensor"] > 1):
        # same limitation init_lm_comp_state documents, guarded at the
        # factory so direct API users get the real reason, not a generic
        # missing-warm-start error for state no init can build
        raise NotImplementedError(
            "powersgd over tensor-sharded params needs shard-local warm "
            "starts; run it on a (data[, seq]) mesh (tensor=1)")
    sync_axes = ("data", "seq")
    n_workers = mesh.shape["data"] * mesh.shape["seq"]

    # Tensor-sharded and tensor-replicated leaves sync as separate groups so
    # data-dependent compression masks cannot de-synchronise replicated
    # params across tensor shards (see make_grouped_grad_sync); the same
    # grouping drives init_lm_comp_state so warm-start state lines up.
    is_sharded = _lm_is_sharded(cfg)
    grad_sync = make_grouped_grad_sync(comp_cfg, sync_axes, is_sharded, "tensor")

    clip_tree = make_sharded_clip(is_sharded, "tensor")
    guarded = guard_cfg is not None
    inject = chaos is not None and chaos.injects_in_graph
    if inject and chaos.worker >= n_workers:
        # silently-never-firing injection would fake a passing drill
        raise ValueError(
            f"chaos worker {chaos.worker} out of range for {n_workers} "
            "(data x seq) workers")

    def local_step(state: TrainState, x: Array, y: Array):
        comp_key = jax.random.fold_in(state.rng, state.step)
        ls_scale = (state.guard.loss_scale if guarded
                    else jnp.asarray(1.0, jnp.float32))

        def loss_fn(params):
            # per-worker logits buffer: local tokens x vocab shard (V/tp)
            # at the config's logits width (bf16 OR fp32 — ADVICE r5)
            if use_fused_head_xent(x.shape[0] * x.shape[1],
                                   cfg.vocab_size // mesh.shape["tensor"],
                                   jnp.dtype(cfg.dtype).itemsize):
                # head matmul + softmax-xent fused through a chunked running
                # logsumexp: the [B,T,V] logits (and AD's saved softmax
                # inputs) never materialise in HBM
                h, aux = apply_llama(cfg, params, x, tensor_axis="tensor",
                                     seq_axis="seq", with_aux=True,
                                     return_hidden=True)
                xent = fused_head_xent(
                    h, params["lm_head"].astype(cfg.dtype), y, "tensor")
            else:
                logits, aux = apply_llama(cfg, params, x,
                                          tensor_axis="tensor",
                                          seq_axis="seq", with_aux=True)
                xent = vocab_parallel_xent(logits, y, tensor_axis="tensor")
            # backprop at loss_scale x (identity unguarded/fp32); the raw
            # xent rides along for metrics/vote
            return (xent + cfg.moe_aux_weight * aux) * ls_scale, xent

        varying = jax.tree.map(
            lambda p: compat.pcast(p, sync_axes, to="varying"), state.params
        )
        with obs_trace.phase("grad"):
            (_, loss), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(varying)
        if inject:
            loss, grads = chaos_mod.inject(
                chaos, state.step, guard_mod.worker_index(sync_axes), loss,
                grads)
        ok = None
        if guarded:
            # vote over the FULL mesh: tensor-sharded gradient slices differ
            # per shard, and every replica must take the identical branch
            ok = guard_mod.finite_vote(
                guard_mod.tree_all_finite(loss, grads), LM_AXES)
            grads = jax.tree.map(lambda g: g / ls_scale, grads)
        if clip_norm > 0.0:
            grads = clip_tree(grads, clip_norm)

        ef_local = jax.tree.map(lambda e: e[0], state.ef)
        comp_local = jax.tree.map(lambda c: c[0], state.comp)
        synced, new_ef, new_comp, comm = grad_sync(
            grads, ef_local, comp_local, comp_key, ok=ok)
        new_ef = jax.tree.map(lambda e: e[None], new_ef)
        new_comp = jax.tree.map(lambda c: c[None], new_comp)
        if clip_sent_norm > 0.0:
            synced = clip_tree(synced, clip_sent_norm)

        new_step = state.step + 1
        # guard-aware LR rewind: schedules key off the applied-update count
        sched_step = guard_mod.schedule_step(guard_cfg, state.guard, new_step)
        with obs_trace.phase("update"):
            new_params, new_opt = optimizer.apply(state.params, synced,
                                                  state.opt_state, sched_step)
        new_guard = state.guard
        if guarded:
            new_params = guard_mod.select_tree(ok, new_params, state.params)
            new_opt = guard_mod.select_tree(ok, new_opt, state.opt_state)
            new_guard = guard_mod.update_guard(guard_cfg, state.guard, ok,
                                               new_step)
            loss = jnp.where(ok, loss, 0.0)
        ntok = jnp.asarray(x.shape[0] * x.shape[1], jnp.float32)
        metrics = {
            "loss": jax.lax.pmean(loss, sync_axes),
            "tokens": jax.lax.psum(ntok, sync_axes),
            "lr": optimizer_lr(optimizer, sched_step),
        }
        if guarded:
            metrics.update(guard_mod.guard_metrics(new_guard))
        for k, v in comm.items():
            metrics[k if k.startswith("guard/") else f"comm/{k}"] = (
                jax.lax.pmean(v, sync_axes))

        return dataclasses.replace(
            state, step=new_step, params=new_params, opt_state=new_opt,
            ef=new_ef, comp=new_comp, guard=new_guard,
        ), metrics

    state_spec = lm_state_specs(cfg, comp_cfg)
    data_spec = P("data", "seq")
    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(state_spec, data_spec, data_spec),
        out_specs=(state_spec, P()),
    )
    jitted = partial(jax.jit, donate_argnums=(0,) if donate else ())(
        lambda state, x, y: sharded(state, x, y)
    )

    def train_step(state: TrainState, batch: Dict[str, Array]):
        for leaf in jax.tree.leaves(state.ef):
            if leaf.ndim < 1 or leaf.shape[0] != n_workers:
                raise ValueError(
                    f"LM EF residual needs leading axis {n_workers} "
                    f"(data x seq workers); got {leaf.shape} — build with "
                    "init_lm_ef_state(cfg, params, comp, mesh)"
                )
        if guarded and state.guard == ():
            raise ValueError(
                "guard_cfg set but state.guard is empty; build it with "
                "init_guard_state(guard_cfg)")
        return jitted(state, batch["input"], batch["target"])

    return train_step


def make_lm_eval_step(cfg: LlamaConfig, mesh: Mesh):
    """``eval_step(state, batch) -> {'loss': mean nll, 'tokens': count}``."""
    cfg.validate_mesh(mesh.shape["tensor"])

    def local_eval(params, x: Array, y: Array):
        logits = apply_llama(cfg, params, x, tensor_axis="tensor", seq_axis="seq")
        loss = vocab_parallel_xent(logits, y, tensor_axis="tensor")
        return {
            "loss": jax.lax.pmean(loss, ("data", "seq")),
            "tokens": jax.lax.psum(
                jnp.asarray(x.shape[0] * x.shape[1], jnp.float32), ("data", "seq")
            ),
        }

    pspecs = param_specs(cfg)
    sharded = shard_map(
        local_eval, mesh=mesh,
        in_specs=(pspecs, P("data", "seq"), P("data", "seq")),
        out_specs=P(),
    )

    @jax.jit
    def eval_step(state: TrainState, batch: Dict[str, Array]):
        return sharded(state.params, batch["input"], batch["target"])

    return eval_step
