"""The jitted train/eval step: forward, backward, compress, psum, update.

This one compiled function replaces the reference's entire per-batch control
flow — ``run_batches`` body (`CIFAR10/core.py:306-321`), the compression comm
calls (`core.py:175-301`), the DDP hook/bucket machinery (`ddp.py:394-488`),
and the optimizer step (`torch_backend.py:132-135`).  It runs under
``shard_map`` over a ``('data',)`` mesh: parameters and optimizer state are
replicated, the batch is sharded on its leading axis, gradients are
compressed locally and reduced with ``lax.psum`` — XLA schedules the
collectives to overlap with compute, which is the TPU-native answer to the
reference's reverse-order bucket overlap (`sparsified_ddp.py:279-281`).

Gradient scale protocol: each reference worker compresses the gradient of a
*summed* loss over its own full batch (512 for CIFAR) and the results are
allreduce-averaged (`core.py:217-222`).  We compute the local *mean* gradient
and multiply by ``grad_scale`` before compression.  The default is 1.0
(mean-gradient scale); to reproduce the paper protocol — in particular for the
scale-sensitive Threshold-V operator — the harnesses pass
``grad_scale=<global batch size>``, pairing it with
``lr = schedule/batch_size, wd = 5e-4*batch_size`` exactly as `dawn.py:142-148`,
so the synced gradient equals the global summed-loss gradient when
compression is off.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from tpu_compressed_dp import compat
from tpu_compressed_dp.compat import shard_map

from tpu_compressed_dp.obs import trace as obs_trace
from tpu_compressed_dp.parallel.dp import CompressionConfig, make_grad_sync
from tpu_compressed_dp.train import guard as guard_mod
from tpu_compressed_dp.train.guard import GuardConfig
from tpu_compressed_dp.train.optim import SGD
from tpu_compressed_dp.train.state import TrainState
from tpu_compressed_dp.utils import chaos as chaos_mod

Array = jax.Array

# Adapter each model family provides:
#   apply_fn(params, batch_stats, x, train, rngs) -> (logits, new_batch_stats)
ApplyFn = Callable[[Any, Any, Array, bool, Dict[str, Array]], Tuple[Array, Any]]

__all__ = ["make_train_step", "make_eval_step", "cross_entropy_sum"]


def cross_entropy_per_example(logits: Array, labels: Array) -> Array:
    """Per-example softmax cross-entropy (`nn.CrossEntropyLoss(reduction='none')`,
    `dawn.py:85`).  Out-of-range labels (eval padding) contribute 0."""
    logz = jax.nn.log_softmax(logits.astype(jnp.float32))
    safe = jnp.clip(labels, 0, logits.shape[-1] - 1)
    ll = jnp.take_along_axis(logz, safe[:, None], axis=1)[:, 0]
    return jnp.where((labels >= 0) & (labels < logits.shape[-1]), -ll, 0.0)


def cross_entropy_sum(logits: Array, labels: Array) -> Array:
    """Summed softmax cross-entropy (`core.py:310`)."""
    return jnp.sum(cross_entropy_per_example(logits, labels))


def make_train_step(
    apply_fn: ApplyFn,
    optimizer: SGD,
    comp_cfg: CompressionConfig,
    mesh: Mesh,
    *,
    grad_scale: float = 1.0,
    clip_norm: float = 0.0,
    clip_sent_norm: float = 0.0,
    axis_name: str = "data",
    donate: bool = True,
    guard_cfg: Optional[GuardConfig] = None,
    chaos: Optional["chaos_mod.ChaosConfig"] = None,
):
    """Build ``train_step(state, batch) -> (state, metrics)``, jitted over ``mesh``.

    ``batch`` is ``{'input': [B, ...], 'target': [B]}`` with ``B`` divisible by
    the mesh's data-axis size; metrics are global (psum-reduced) scalars.

    ``clip_norm`` (mean-loss units; 0 = off) clips each worker's local
    gradient by L2 norm *before* error-feedback accumulation — the DGC-style
    stabiliser for sparsified training with momentum.  Root-cause analysis
    (`tools/ef_bisect.py`, `benchmarks/ef_momentum_bisect_r2.txt`): EF defers
    ~1/k steps of gradient mass per coordinate, and that delay times the
    momentum gain 1/(1-mu) diverges under the dawn protocol's peak lr — for
    the reference's own update rule too (torch repro of
    `sparsified_ddp.py:408-413` + momentum SGD NaNs identically).  Clipping
    bounds the re-injected residual and restores stable training.

    ``clip_sent_norm`` (same units; 0 = off) clips the *synced* gradient
    after aggregation, which bounds the ~1/k-step residual spike itself —
    local clipping cannot (the residual accumulates clipped inflow for 1/k
    steps and still releases it at once).  For Random-K + EF + momentum the
    bisect shows clip-sent ~20x lower final loss than clip-local alone;
    combine both for the most robust protocol.

    ``guard_cfg`` (None = off) arms the in-graph step guard
    (:mod:`tpu_compressed_dp.train.guard`): a cross-worker finiteness vote
    over loss + gradients gates the whole update — on a bad step
    params/opt_state/batch_stats/ef/comp are held bitwise, the dynamic loss
    scale backs off, and the skip counters advance; ``state.guard`` must be
    built with ``init_guard_state(guard_cfg)``.  The loss is multiplied by
    the live scale before backprop and the gradients divided by it after
    the vote (so a scale overflow is itself caught by the vote).

    ``chaos`` (None = off) traces deterministic fault injection into the
    step (:mod:`tpu_compressed_dp.utils.chaos`): NaN/Inf into one worker's
    gradients or loss at step-counter-chosen steps — the adversary the
    guard is tested against (tools/chaos_drill.py).

    ``comp_cfg.sync_overlap > 1`` chunk-pipelines the gradient sync
    (:mod:`tpu_compressed_dp.parallel.overlap`): the sync decomposes into K
    reverse-topological chunk collectives the scheduler interleaves with
    the remaining backward pass, and — when ``clip_sent_norm`` is off —
    each chunk's slice of the optimizer update is traced right after its
    reduce so it can run while the next chunk's collective is in flight.
    Bitwise-identical numerics either way; ``clip_sent_norm > 0`` needs the
    global synced-gradient norm (a barrier over all chunks), so that path
    keeps the whole-tree update after the chunked sync.
    """
    grad_sync = make_grad_sync(comp_cfg, axis_name)
    fused_overlap = None
    if (comp_cfg.sync_overlap > 1 and clip_sent_norm == 0.0
            and isinstance(optimizer, SGD)):
        # the per-chunk interleave slices the optimizer leaf-for-leaf and
        # reaches into opt_state["momentum"]/wd_mask — SGD's shape; any
        # other optimizer keeps chunked sync + whole-tree apply
        from tpu_compressed_dp.parallel import overlap as overlap_mod

        fused_overlap = overlap_mod.make_overlap_sync_apply(
            comp_cfg, optimizer, axis_name)
    guarded = guard_cfg is not None
    inject = chaos is not None and chaos.injects_in_graph
    if inject and chaos.worker >= mesh.shape[axis_name]:
        # an out-of-range worker would silently never fire — the drill
        # would then "pass" against faults that never happened
        raise ValueError(
            f"chaos worker {chaos.worker} out of range for "
            f"{mesh.shape[axis_name]} data-parallel workers")

    def local_step(state: TrainState, x: Array, y: Array):
        step_key = jax.random.fold_in(state.rng, state.step)
        comp_key, drop_key = jax.random.split(step_key)
        drop_key = jax.random.fold_in(drop_key, jax.lax.axis_index(axis_name))
        ls_scale = (state.guard.loss_scale if guarded
                    else jnp.asarray(1.0, jnp.float32))

        def loss_fn(params):
            logits, new_bs = apply_fn(params, state.batch_stats, x, True, {"dropout": drop_key})
            loss = cross_entropy_sum(logits, y) / x.shape[0]  # local mean
            # backprop the SCALED loss (identity when unguarded/fp32): the
            # whole backward pass runs at loss_scale x, keeping tiny
            # half-precision cotangents above the representable floor
            return loss * ls_scale, (new_bs, logits, loss)

        # shard_map's AD would transparently psum gradients of replicated
        # params — but the whole point of this framework is to compress each
        # worker's gradient *before* the reduction.  Mark the params as
        # device-varying so jax.grad yields the per-worker local gradient and
        # the (possibly compressed) psum stays under our control in grad_sync.
        varying_params = jax.tree.map(lambda p: _to_varying(p, axis_name), state.params)
        with obs_trace.phase("grad"):
            (_, (new_bs, logits, loss)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(varying_params)

        scaled = jax.tree.map(lambda g: g.astype(jnp.float32) * grad_scale, grads)
        if inject:
            loss, scaled = chaos_mod.inject(
                chaos, state.step, guard_mod.worker_index(axis_name), loss,
                scaled)
        ok = None
        if guarded:
            # vote BEFORE unscaling: an inf that the loss scale itself
            # manufactured is exactly what dynamic backoff must see
            ok = guard_mod.finite_vote(
                guard_mod.tree_all_finite(loss, scaled), axis_name)
            scaled = jax.tree.map(lambda g: g / ls_scale, scaled)
        if clip_norm > 0.0:
            # local-gradient clip at mean-loss scale: ||scaled|| / grad_scale
            # <= clip_norm after this (threshold stays protocol-invariant
            # under the summed-loss grad_scale pairing)
            gnorm = jnp.sqrt(sum(jnp.sum(g * g) for g in jax.tree.leaves(scaled)))
            factor = jnp.minimum(1.0, clip_norm * grad_scale / jnp.maximum(gnorm, 1e-20))
            scaled = jax.tree.map(lambda g: g * factor, scaled)
        # EF residual and compressor state are per-worker state (the
        # reference's per-rank epsilon, sparsified_ddp.py:222; PowerSGD's
        # warm-start Q): stored with a leading device axis, sharded over the
        # mesh; squeeze the local slice here.
        ef_local = jax.tree.map(lambda e: e[0], state.ef)
        comp_local = jax.tree.map(lambda c: c[0], state.comp)
        new_step = state.step + 1
        # guard-aware LR rewind: schedules see the applied-update count, so
        # vetoed steps don't fast-forward the schedule clock
        sched_step = guard_mod.schedule_step(guard_cfg, state.guard, new_step)
        if fused_overlap is not None:
            # chunk-pipelined sync + per-chunk optimizer interleave: chunk
            # i's update slice runs while chunk i+1's collective is in
            # flight (the vote `ok` was computed once, above, before any
            # chunk dispatches)
            new_params, new_opt, new_ef, new_comp, comm = fused_overlap(
                state.params, scaled, ef_local, comp_local, state.opt_state,
                comp_key, sched_step, ok=ok)
        else:
            synced, new_ef, new_comp, comm = grad_sync(
                scaled, ef_local, comp_local, comp_key, ok=ok)
            if clip_sent_norm > 0.0:
                snorm = jnp.sqrt(
                    sum(jnp.sum(g * g) for g in jax.tree.leaves(synced)))
                sfactor = jnp.minimum(
                    1.0,
                    clip_sent_norm * grad_scale / jnp.maximum(snorm, 1e-20))
                synced = jax.tree.map(lambda g: g * sfactor, synced)
            with obs_trace.phase("update"):
                new_params, new_opt = optimizer.apply(
                    state.params, synced, state.opt_state, sched_step)
        new_ef = jax.tree.map(lambda e: e[None], new_ef)
        new_comp = jax.tree.map(lambda c: c[None], new_comp)

        # BN running stats are computed from the local shard; average them so
        # the replicated state stays consistent.  Normalisation itself still
        # used local batch statistics, matching the reference's non-synced BN
        # (SURVEY.md §7 "BatchNorm under DP").
        new_bs = jax.lax.pmean(new_bs, axis_name) if new_bs else new_bs

        new_guard = state.guard
        if guarded:
            # the vetoed branch holds EVERYTHING the step would have mutated
            # (ef/comp were held inside grad_sync); only the step counter,
            # the RNG stream (derived from it) and the guard's own
            # bookkeeping advance
            new_params = guard_mod.select_tree(ok, new_params, state.params)
            new_opt = guard_mod.select_tree(ok, new_opt, state.opt_state)
            new_bs = guard_mod.select_tree(ok, new_bs, state.batch_stats)
            new_guard = guard_mod.update_guard(guard_cfg, state.guard, ok,
                                               new_step)
            # a nonfinite loss would poison the epoch mean; report 0 for the
            # skipped step (its count still contributes — honest step totals)
            loss = jnp.where(ok, loss, 0.0)

        local_bs = jnp.asarray(x.shape[0], jnp.float32)
        correct = jnp.sum(jnp.argmax(logits, axis=1) == y).astype(jnp.float32)
        metrics = {
            "loss": jax.lax.psum(loss * local_bs, axis_name) / jax.lax.psum(local_bs, axis_name),
            "correct": jax.lax.psum(correct, axis_name),
            "count": jax.lax.psum(local_bs, axis_name),
            "lr": optimizer_lr(optimizer, sched_step),
        }
        if guarded:
            metrics.update(guard_mod.guard_metrics(new_guard))
        for k, v in comm.items():
            # guard/* stats are already-global diagnostics, not comm volumes
            metrics[k if k.startswith("guard/") else f"comm/{k}"] = (
                jax.lax.pmean(v, axis_name))

        new_state = dataclasses.replace(
            state,
            step=new_step,
            params=new_params,
            batch_stats=new_bs,
            opt_state=new_opt,
            ef=new_ef,
            comp=new_comp,
            guard=new_guard,
        )
        return new_state, metrics

    state_spec = TrainState(
        step=P(), params=P(), batch_stats=P(), opt_state=P(), ef=P(axis_name),
        rng=P(), comp=P(axis_name), guard=P(), control=P(),
    )
    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(state_spec, P(axis_name), P(axis_name)),
        out_specs=(state_spec, P()),
    )

    jitted = partial(jax.jit, donate_argnums=(0,) if donate else ())(
        lambda state, x, y: sharded(state, x, y)
    )
    n_dev = mesh.shape[axis_name]

    def train_step(state: TrainState, batch: Dict[str, Array]):
        if comp_cfg.error_feedback and state.ef == ():
            raise ValueError(
                "error_feedback=True but state.ef is empty; build it with "
                f"init_ef_state(params, cfg, num_devices={n_dev})")
        if guarded and state.guard == ():
            raise ValueError(
                "guard_cfg set but state.guard is empty; build it with "
                "init_guard_state(guard_cfg)")
        for field, hint in (("ef", "init_ef_state(params, cfg"),
                            ("comp", "init_comp_state(params, cfg")):
            for leaf in jax.tree.leaves(getattr(state, field)):
                if leaf.ndim < 1 or leaf.shape[0] != n_dev:
                    raise ValueError(
                        f"{field} leaves need a leading device axis of size "
                        f"{n_dev} (got shape {leaf.shape}); build them with "
                        f"{hint}, num_devices={n_dev})"
                    )
        return jitted(state, batch["input"], batch["target"])

    return train_step


def _to_varying(x: Array, axis_name: str) -> Array:
    """Mark a replicated value as device-varying (identity on the forward pass,
    blocks the automatic psum on the backward pass)."""
    return compat.pcast(x, axis_name, to="varying")


def optimizer_lr(optimizer: SGD, step: Array) -> Array:
    lr = optimizer.lr
    return lr(step) if callable(lr) else jnp.asarray(lr, jnp.float32)


def make_eval_step(apply_fn: ApplyFn, mesh: Mesh, *, axis_name: str = "data"):
    """Build ``eval_step(state, batch) -> {loss_sum, correct, correct5, count}``
    (global sums).

    Equivalent of the reference's eval pass (`core.py:326`) and the global
    metric reduction of ``distributed_predict`` (`train_imagenet_nv.py:523-542`).
    ``batch`` may carry a ``'mask'`` array (1.0 = real example, 0.0 = padding);
    padded examples contribute to no metric — the TPU answer to the
    reference's uneven-final-batch problem (`DistValSampler`,
    `dataloader.py:133-161`, hands ranks possibly-empty batches; we pad to a
    static shape instead so XLA sees one shape per image size).
    """

    def local_eval(state: TrainState, x: Array, y: Array, mask: Array):
        logits, _ = apply_fn(state.params, state.batch_stats, x, False, {})
        loss = jnp.sum(cross_entropy_per_example(logits, y) * mask)
        correct1 = jnp.sum((jnp.argmax(logits, axis=1) == y) * mask)
        top5 = jax.lax.top_k(logits, min(5, logits.shape[-1]))[1]
        correct5 = jnp.sum(jnp.any(top5 == y[:, None], axis=1) * mask)
        return {
            "loss_sum": jax.lax.psum(loss, axis_name),
            "correct": jax.lax.psum(correct1, axis_name),
            "correct5": jax.lax.psum(correct5, axis_name),
            "count": jax.lax.psum(jnp.sum(mask), axis_name),
        }

    state_spec = TrainState(
        step=P(), params=P(), batch_stats=P(), opt_state=P(), ef=P(axis_name),
        rng=P(), comp=P(axis_name), guard=P(), control=P(),
    )
    sharded = shard_map(
        local_eval,
        mesh=mesh,
        in_specs=(state_spec, P(axis_name), P(axis_name), P(axis_name)),
        out_specs=P(),
    )

    @jax.jit
    def eval_step(state: TrainState, batch: Dict[str, Array]):
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones((batch["target"].shape[0],), jnp.float32)
        return sharded(state, batch["input"], batch["target"], mask)

    return eval_step
