"""Elastic data-parallel training: failure detection, coordinated abort,
W-1 remesh with EF/PowerSGD state migration, and scale-up re-admission.

The robustness stack up to here survives nonfinite steps (the step guard)
and single-process death (checkpoint + watchdog relaunch) — but only by
restarting the WHOLE job: a dead host stalls every collective until the
supervisor kills the world.  This module is the other half: survivors
detect the failure, abort coherently, shrink the mesh by the dead worker,
and keep training.

Failure model (three detection planes, all raising :class:`PeerFailed`):

  * **heartbeat gossip** (:class:`PeerGossip`) — every worker process
    writes its own liveness file (:meth:`PeerGossip.beat`, same atomic
    record shape as :class:`~tpu_compressed_dp.utils.resilience.Heartbeat`)
    into a shared ``--elastic_dir``; every worker reads its peers' files
    each poll.  A peer whose record stays older than ``peer_timeout_s`` is
    dead.  Records carry an ``incarnation`` (seeded from
    ``TCDP_RESTART_COUNT``, exported by ``tools/watchdog.py --relaunch``):
    a restarted peer's fresh file has a HIGHER incarnation, so it reads as
    "this rank died and came back" (a rejoin candidate), never as
    continuity of the dead life.
  * **bounded collective fetch** (:func:`fetch_with_timeout`) — a
    ``device_get`` on results of an in-flight step normally returns in
    step time; when a peer died mid-collective it blocks forever.  The
    fetch runs in a worker thread with a deadline; blowing it raises
    ``PeerFailed`` instead of stalling silently.  (Honest limitation: an
    in-process XLA computation cannot be cancelled — on real multi-host
    deployments the abort is a process exit and the watchdog relaunches
    into the next remesh barrier; under the single-process simulation the
    deterministic ``crash=mid_collective`` chaos plays the dying peer.)
  * **deterministic chaos** — ``--chaos crash=mid_collective,...`` raises
    after step dispatch, while the step's collectives are in flight;
    :meth:`ElasticRuntime.failure_from` translates it into the same
    ``PeerFailed`` the real detectors raise, which is what lets the chaos
    drill prove the whole remesh path bitwise.

Remesh semantics (what the departing worker owes the run):

  * ``params`` / ``opt_state`` / ``batch_stats`` / ``guard`` are replicated
    — survivors already hold them; they are preserved **bitwise**.
  * ``TrainState.ef`` is per-worker unsent gradient mass (the memory of
    "Sparsified SGD with Memory"): the lost worker's residual row is either
    **folded** into a survivor's residual (an exact fp32 add — total EF
    mass is conserved, and the folded mass re-enters the very next step's
    gradients like any EF carry) or **dropped** and accounted in the
    ``elastic/dropped_ef_norm`` metric (the L2 norm of the gradient mass
    the run will never apply).
  * ``TrainState.comp`` (PowerSGD warm-start factors) is identical on
    every worker by construction (the P/Q psums average factors), so the
    dead worker's rows are simply deleted; on re-admission the returning
    worker's factors are re-warmed from a broadcast of a survivor's row —
    re-agreement is what keeps the power iteration meaningful.
  * The sharded transport's owner partition (``ops/wire_sharded.py``) is a
    pure function of the static world size read off the mesh at trace
    time, so rebuilding the train step over the W-1 mesh recomputes the
    shard boundaries automatically (tests/test_wire_sharded.py asserts the
    W -> W-1 partition keeps covering the flat unit space exactly).

Scale-up: a returning host rejoins at the next remesh barrier
(:meth:`ElasticRuntime.readmit`): the mesh is extended with the parked
device, the live (in-process) state plays the role of the live checkpoint,
the new EF row starts at zero (a fresh worker has not withheld anything)
and the comp rows are broadcast-re-warmed.

``tools/chaos_drill.py`` (``elastic_remesh`` / ``elastic_readmit`` /
``elastic_matrix``) proves the invariants end to end.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from tpu_compressed_dp.parallel.mesh import DATA_AXIS, make_data_mesh
from tpu_compressed_dp.utils.resilience import read_heartbeat

__all__ = [
    "PeerFailed", "ElasticConfig", "PeerGossip", "ElasticRuntime",
    "heartbeat_path", "write_peer_heartbeat", "fetch_with_timeout",
    "surviving_mesh", "extended_mesh", "migrate_ef", "migrate_comp",
    "expand_ef", "expand_comp", "shrink_state", "expand_state",
    "TrimBatches",
]

#: Default failure-detection budget: a peer heartbeat older than this (and
#: a collective fetch blocked longer than this) counts as a dead peer.
DEFAULT_PEER_TIMEOUT_S = 60.0


class PeerFailed(RuntimeError):
    """Coordinated abort signal: one or more peers are gone.

    ``failed`` — worker indices (mesh positions / gossip ranks) declared
    dead; may be empty when a collective timeout fired before the gossip
    named a culprit (the runtime then consults gossip to fill it in).
    ``step`` — the attempted global step, when known.  Every survivor
    raises the same verdict from the same evidence (stale files age out at
    the same wall-clock deadline; the chaos injection is step-keyed), which
    is what makes the abort coordinated rather than a stampede.
    """

    def __init__(self, failed: Iterable[int] = (), *,
                 step: Optional[int] = None, reason: str = "peer failure"):
        self.failed: Tuple[int, ...] = tuple(sorted(int(f) for f in failed))
        self.step = None if step is None else int(step)
        self.reason = reason
        who = list(self.failed) if self.failed else "unknown peer(s)"
        at = f" at step {self.step}" if self.step is not None else ""
        super().__init__(f"elastic: {who} failed{at}: {reason}")


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """Knobs of the elastic runtime (CLI surface: ``--elastic*``).

    gossip_dir:      shared directory of per-rank heartbeat files (None =
                     no gossip plane; chaos / fetch timeouts still work)
    rank:            this worker's gossip rank
    peer_timeout_s:  staleness/fetch deadline before a peer counts as dead
    min_world:       refuse to shrink below this many workers (the job is
                     better off dying and relaunching than limping on a
                     mesh too small to be worth the lr/batch mismatch)
    ef_policy:       'fold' (conserve the lost EF mass into a survivor) |
                     'drop' (discard it; counted in elastic/dropped_ef_norm)
    """

    gossip_dir: Optional[str] = None
    rank: int = 0
    peer_timeout_s: float = DEFAULT_PEER_TIMEOUT_S
    min_world: int = 2
    ef_policy: str = "fold"

    def __post_init__(self):
        if self.ef_policy not in ("fold", "drop"):
            raise ValueError(
                f"ef_policy must be fold|drop, got {self.ef_policy!r}")
        if self.peer_timeout_s <= 0:
            raise ValueError("peer_timeout_s must be > 0")
        if self.min_world < 1:
            raise ValueError("min_world must be >= 1")


# ------------------------------------------------------------------ gossip

def heartbeat_path(gossip_dir: str, rank: int) -> str:
    return os.path.join(gossip_dir, f"rank{int(rank)}.json")


def write_peer_heartbeat(gossip_dir: str, rank: int, step: int, *,
                         incarnation: int = 0,
                         ts: Optional[float] = None) -> str:
    """One atomic heartbeat write into the gossip directory — the
    thread-free form the harness step loops and the drill's simulated
    peers use (same record shape and atomic tmp+replace as
    :class:`~tpu_compressed_dp.utils.resilience.Heartbeat`)."""
    os.makedirs(gossip_dir, exist_ok=True)
    path = heartbeat_path(gossip_dir, rank)
    rec = {"ts": time.time() if ts is None else float(ts),
           "step": int(step), "rank": int(rank),
           "incarnation": int(incarnation)}
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f)
    os.replace(tmp, path)
    return path


class PeerGossip:
    """Decentralised failure detector over a shared heartbeat directory.

    Each worker runs one instance: it reads every peer's file per
    :meth:`check` and votes a peer dead once no FRESH record (recent ``ts``
    AND the admitted incarnation) has been seen for ``peer_timeout_s``.
    Incarnation rules:

      * the first record seen for a rank admits its incarnation;
      * a record with a LOWER incarnation than admitted is a stale file of
        a dead prior life — it never refreshes liveness;
      * a record with a HIGHER incarnation means the peer process was
        replaced: the admitted life is declared dead (its in-memory EF row
        is gone regardless of how alive the new process looks) and the new
        incarnation becomes a rejoin candidate for the next barrier.

    Construction starts every peer's grace clock at "now", so a cold start
    where peers appear over ``peer_timeout_s`` does not false-positive.
    """

    def __init__(self, gossip_dir: str, rank: int, world: int, *,
                 peer_timeout_s: float = DEFAULT_PEER_TIMEOUT_S,
                 incarnation: Optional[int] = None,
                 now: Callable[[], float] = time.time):
        self.gossip_dir = gossip_dir
        self.rank = int(rank)
        self.world = int(world)
        self.peer_timeout_s = float(peer_timeout_s)
        if incarnation is None:
            try:
                incarnation = int(os.environ.get("TCDP_RESTART_COUNT", "0"))
            except ValueError:
                incarnation = 0
        self.incarnation = int(incarnation)
        self._last_beat = float("-inf")
        self._now = now
        t0 = now()
        self._last_fresh: Dict[int, float] = {
            r: t0 for r in range(self.world)}
        self._admitted: Dict[int, Optional[int]] = {
            r: None for r in range(self.world)}
        self._dead: Dict[int, str] = {}          # rank -> reason
        self._rejoin: Dict[int, int] = {}        # rank -> new incarnation

    @property
    def dead(self) -> Tuple[int, ...]:
        return tuple(sorted(self._dead))

    def beat(self, step: int = 0) -> None:
        """Write THIS rank's own liveness file (rate-limited to a quarter of
        the timeout — peers need several fresh observations per window, and
        an atomic replace per step would be pure filesystem churn)."""
        now = self._now()
        if now - self._last_beat >= self.peer_timeout_s / 4:
            write_peer_heartbeat(self.gossip_dir, self.rank, step,
                                 incarnation=self.incarnation, ts=now)
            self._last_beat = now

    def note_dead(self, ranks: Iterable[int], reason: str = "declared dead"
                  ) -> None:
        """Record an externally-detected failure (chaos conversion, a peer
        named by another detector) so rejoin tracking stays consistent."""
        for r in ranks:
            self._dead.setdefault(int(r), reason)

    def check(self, now: Optional[float] = None) -> Dict[int, str]:
        """One gossip sweep; returns the NEWLY dead peers ``{rank: why}``
        (already-known dead peers are only re-reported via :attr:`dead`)."""
        now = self._now() if now is None else now
        newly: Dict[int, str] = {}
        for r in range(self.world):
            if r == self.rank:
                continue
            hb = read_heartbeat(heartbeat_path(self.gossip_dir, r))
            inc = None
            if hb is not None:
                inc = int(hb.get("incarnation", 0) or 0)
                ts = hb.get("ts")
                fresh_ts = (isinstance(ts, (int, float))
                            and not isinstance(ts, bool)
                            and (now - ts) <= self.peer_timeout_s)
            if r in self._dead:
                dead_inc = self._admitted.get(r)
                if (hb is not None and fresh_ts and inc is not None
                        and (dead_inc is None or inc > dead_inc)):
                    self._rejoin[r] = inc
                continue
            if hb is not None:
                if self._admitted[r] is None:
                    self._admitted[r] = inc
                if inc > self._admitted[r]:
                    # the process we were tracking is gone; its replacement
                    # may rejoin, but the tracked life's state died with it
                    why = (f"incarnation advanced {self._admitted[r]} -> "
                           f"{inc} (peer restarted)")
                    self._dead[r] = why
                    newly[r] = why
                    self._rejoin[r] = inc
                    continue
                if fresh_ts and inc == self._admitted[r]:
                    self._last_fresh[r] = max(self._last_fresh[r], float(ts))
            age = now - self._last_fresh[r]
            if age > self.peer_timeout_s:
                why = (f"no fresh heartbeat for {age:.1f}s "
                       f"(> {self.peer_timeout_s:g}s)")
                self._dead[r] = why
                newly[r] = why
        return newly

    def raise_if_dead(self, step: Optional[int] = None,
                      now: Optional[float] = None) -> None:
        newly = self.check(now)
        if newly:
            reason = "; ".join(f"rank {r}: {why}"
                               for r, why in sorted(newly.items()))
            raise PeerFailed(newly, step=step, reason=reason)

    def rejoin_candidates(self, now: Optional[float] = None
                          ) -> Dict[int, int]:
        """Dead ranks whose directory now shows a fresh, newer incarnation
        — ready for re-admission at the next barrier."""
        self.check(now)
        return dict(self._rejoin)

    def readmit(self, rank: int) -> None:
        """Move a rank back to the tracked set under its new incarnation."""
        rank = int(rank)
        inc = self._rejoin.pop(rank, None)
        self._dead.pop(rank, None)
        self._admitted[rank] = inc
        self._last_fresh[rank] = self._now()


# ------------------------------------------------- bounded collective fetch

def fetch_with_timeout(thunk: Callable[[], Any], timeout_s: float, *,
                       step: Optional[int] = None,
                       what: str = "collective fetch") -> Any:
    """Run a blocking device fetch with a deadline.

    ``jax.device_get`` on an in-flight step's outputs normally completes in
    step time; with a peer dead mid-collective it blocks forever.  The
    thunk runs in a daemon thread; exceeding ``timeout_s`` raises
    :class:`PeerFailed` (with no culprit — gossip names the rank).  The
    thunk's own exception, if any, is re-raised on the caller's thread.
    """
    box: Dict[str, Any] = {}
    done = threading.Event()

    def runner():
        try:
            box["value"] = thunk()
        except BaseException as e:  # surfaced on the caller's thread
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=runner, daemon=True)
    t.start()
    if not done.wait(timeout_s):
        raise PeerFailed((), step=step, reason=(
            f"{what} still blocked after {timeout_s:g}s — "
            "a peer died mid-collective"))
    if "error" in box:
        raise box["error"]
    return box.get("value")


# ------------------------------------------------------------ mesh surgery

def _data_devices(mesh) -> List:
    """Devices along the data axis, requiring a data-parallel-ONLY mesh:
    either the 1-D ``('data',)`` mesh or a multi-axis mesh whose non-data
    axes are all size 1 (the LM harness's dp-only configuration).  Losing
    one data worker of a sheared dp x tp mesh would orphan a whole model
    shard — that is a job restart, not a remesh."""
    names = tuple(mesh.axis_names)
    if DATA_AXIS not in names:
        raise ValueError(
            f"elastic remesh needs a '{DATA_AXIS}' axis; got axes {names}")
    extra = {n: int(mesh.shape[n]) for n in names if n != DATA_AXIS}
    if any(s != 1 for s in extra.values()):
        raise ValueError(
            "elastic remesh supports data-parallel-only meshes; got "
            f"model axes {extra}")
    return list(mesh.devices.reshape(-1))


def _rebuild_mesh(mesh, devices: Sequence):
    """A mesh over ``devices`` with the template mesh's axis names (data
    axis resized, unit model axes preserved so the harness's specs keep
    resolving)."""
    names = tuple(mesh.axis_names)
    if names == (DATA_AXIS,):
        return make_data_mesh(devices=list(devices))
    shape = tuple(len(devices) if n == DATA_AXIS else 1 for n in names)
    return jax.sharding.Mesh(
        np.asarray(devices, dtype=object).reshape(shape), names)


def surviving_mesh(mesh, failed: Sequence[int]):
    """The W-1 (or W-F) mesh over the survivors, order preserved; returns
    ``(new_mesh, removed_devices)`` with the dead workers' devices parked
    for later re-admission."""
    devices = _data_devices(mesh)
    failed_set = {int(f) for f in failed}
    bad = [f for f in failed_set if not 0 <= f < len(devices)]
    if bad:
        raise ValueError(f"failed worker index {bad} outside world "
                         f"{len(devices)}")
    survivors = [d for i, d in enumerate(devices) if i not in failed_set]
    removed = [devices[i] for i in sorted(failed_set)]
    if not survivors:
        raise ValueError("no survivors to remesh over")
    return _rebuild_mesh(mesh, survivors), removed


def extended_mesh(mesh, new_devices: Sequence):
    """The mesh with returning devices appended (rejoiners take the tail
    positions — survivor worker indices, and with them the EF rows and the
    owner partition prefix, stay stable)."""
    devices = _data_devices(mesh)
    return _rebuild_mesh(mesh, devices + list(new_devices))


# -------------------------------------------------------- state migration

def migrate_ef(ef: Any, failed: Sequence[int], *, policy: str = "fold",
               fold_into: int = 0) -> Tuple[Any, float]:
    """Shrink the EF residual's leading worker axis by ``failed``.

    ``fold``: the lost rows are added into survivor row ``fold_into``
    (survivor order) with one exact fp32 add per leaf — total residual mass
    is conserved and re-enters the next step's gradients like any EF carry.
    ``drop``: the lost rows are discarded; returns their global L2 norm
    (root of the summed squares across all leaves, fp64 accumulate) so the
    caller can account the abandoned gradient mass.

    Host-side numpy on fetched arrays; returns ``(new_ef, dropped_norm)``.
    """
    if policy not in ("fold", "drop"):
        raise ValueError(f"ef policy must be fold|drop, got {policy!r}")
    if ef == ():
        return (), 0.0
    failed = sorted({int(f) for f in failed})
    dropped_sq = 0.0

    def one(a):
        nonlocal dropped_sq
        a = np.asarray(a)
        if a.ndim < 1 or a.shape[0] <= max(failed):
            raise ValueError(
                f"EF leaf with leading axis {a.shape} cannot lose "
                f"worker(s) {failed}")
        lost = a[failed]
        kept = np.delete(a, failed, axis=0)
        if policy == "fold":
            kept = kept.copy()
            kept[fold_into] = kept[fold_into] + lost.sum(axis=0)
        else:
            dropped_sq += float(np.sum(lost.astype(np.float64) ** 2))
        return kept

    new_ef = jax.tree.map(one, ef)
    return new_ef, float(np.sqrt(dropped_sq))


def migrate_comp(comp: Any, failed: Sequence[int]) -> Any:
    """Shrink the compressor state's leading worker axis: the PowerSGD
    warm-start rows are identical across workers (psum-averaged), so the
    dead rows are deleted with nothing to fold."""
    if comp == ():
        return ()
    failed = sorted({int(f) for f in failed})
    return jax.tree.map(
        lambda a: np.delete(np.asarray(a), failed, axis=0), comp)


def expand_ef(ef: Any, n_new: int = 1) -> Any:
    """Append zero rows for rejoining workers (a fresh worker has not
    withheld any gradient mass yet)."""
    if ef == () or n_new <= 0:
        return ef
    return jax.tree.map(
        lambda a: np.concatenate(
            [np.asarray(a),
             np.zeros((n_new,) + np.asarray(a).shape[1:],
                      np.asarray(a).dtype)], axis=0), ef)


def expand_comp(comp: Any, n_new: int = 1) -> Any:
    """Append broadcast copies of survivor row 0 for rejoining workers —
    the PowerSGD re-warm: every worker must iterate in the same basis, so
    the newcomer adopts the survivors' converged factors instead of a cold
    random restart."""
    if comp == () or n_new <= 0:
        return comp
    return jax.tree.map(
        lambda a: np.concatenate(
            [np.asarray(a)]
            + [np.asarray(a)[:1]] * n_new, axis=0), comp)


def shrink_state(state, failed: Sequence[int], *, policy: str = "fold",
                 fold_into: int = 0):
    """Migrate a TrainState off the dead workers: fetch ef/comp to host,
    shrink their leading axes, keep every replicated field bitwise.
    Returns ``(new_state, dropped_ef_norm)`` — still host-side; the caller
    places it on the new mesh (``with_mesh_sharding``)."""
    ef = jax.device_get(state.ef) if state.ef != () else ()
    comp = jax.device_get(state.comp) if state.comp != () else ()
    new_ef, dropped = migrate_ef(ef, failed, policy=policy,
                                 fold_into=fold_into)
    new_comp = migrate_comp(comp, failed)
    return dataclasses.replace(state, ef=new_ef, comp=new_comp), dropped


def expand_state(state, n_new: int = 1):
    """Extend a TrainState for ``n_new`` rejoining workers (zero EF rows,
    broadcast-re-warmed comp rows); host-side, caller re-places."""
    ef = jax.device_get(state.ef) if state.ef != () else ()
    comp = jax.device_get(state.comp) if state.comp != () else ()
    return dataclasses.replace(state, ef=expand_ef(ef, n_new),
                               comp=expand_comp(comp, n_new))


class TrimBatches:
    """Iterable view trimming each batch dict to at most ``size`` rows —
    the remeshed world divides a smaller global batch, so after W -> W-1
    each batch is cut to ``(bs // W') * W'`` rows (short final batches pass
    through untouched for the eval padding to handle)."""

    def __init__(self, inner, size: int):
        self.inner = inner
        self.size = int(size)

    def __iter__(self):
        for batch in self.inner:
            yield {k: v[:self.size] for k, v in batch.items()}

    def __len__(self):
        return len(self.inner)


# ----------------------------------------------------------------- runtime

class ElasticRuntime:
    """The harness-facing elastic driver: owns the current mesh, converts
    failures, performs the remesh, and keeps the ``elastic/*`` counters.

    Typical harness shape::

        el = ElasticRuntime(cfg, mesh, chaos=chaos, events=events)
        while epoch < epochs:
            try:
                state, ... = train_epoch(step_for(el.mesh), state, ...)
            except Exception as e:
                failure = el.failure_from(e)
                if failure is None:
                    raise
                state = el.handle_failure(state, failure)
                continue        # retry the epoch on the W-1 mesh
            epoch += 1
    """

    def __init__(self, cfg: ElasticConfig, mesh, *, chaos=None,
                 gossip: Optional[PeerGossip] = None, events=None,
                 place: Optional[Callable[[Any, Any], Any]] = None,
                 log: Callable[[str], None] = print):
        _data_devices(mesh)  # validates the mesh shape up front
        self.cfg = cfg
        self.mesh = mesh
        self.chaos = chaos
        self.gossip = gossip
        self.events = events
        # how to re-place a migrated state on a new mesh; the CNN default
        # is the TrainState's own sharding rule, the LM harness passes its
        # place_lm_state closure
        self._place = place or (lambda s, m: s.with_mesh_sharding(m))
        self._log = log
        self._parked: List = []            # (rank, device) of removed peers
        self.peer_failures = 0
        self.remesh_count = 0
        self.readmit_count = 0
        self.dropped_ef_norm = 0.0
        self.remesh_latency_ms = 0.0       # latest remesh's host latency

    @property
    def world(self) -> int:
        return int(self.mesh.shape[DATA_AXIS])

    # -- detection -------------------------------------------------------
    def poll(self, step: Optional[int] = None) -> None:
        """Write our own gossip heartbeat, then sweep the peers'; raises
        :class:`PeerFailed` on newly-dead peers."""
        if self.gossip is not None:
            self.gossip.beat(0 if step is None else step)
            self.gossip.raise_if_dead(step)

    def bounded_get(self, x, *, step: Optional[int] = None,
                    what: str = "step metrics fetch"):
        """``jax.device_get`` with the peer-timeout deadline."""
        return fetch_with_timeout(lambda: jax.device_get(x),
                                  self.cfg.peer_timeout_s, step=step,
                                  what=what)

    def failure_from(self, exc: BaseException) -> Optional[PeerFailed]:
        """Translate an exception into the coordinated failure it signals,
        or None for faults that are not elastic's to handle.

        * :class:`PeerFailed` passes through; an empty culprit list (a
          fetch timeout) is filled in from the gossip's dead set.
        * A ``mid_collective`` :class:`~tpu_compressed_dp.utils.chaos.ChaosCrash`
          becomes the simulated death of ``chaos.worker`` — the same
          handler path real survivors reach through gossip/timeouts.
        """
        from tpu_compressed_dp.utils.chaos import ChaosCrash

        if isinstance(exc, PeerFailed):
            if not exc.failed and self.gossip is not None:
                dead = self.gossip.dead or tuple(self.gossip.check())
                if dead:
                    return PeerFailed(dead, step=exc.step,
                                      reason=f"{exc.reason}; gossip names "
                                             f"{list(dead)}")
            return exc
        if (isinstance(exc, ChaosCrash)
                and getattr(exc, "mode", "step") == "mid_collective"):
            return PeerFailed((getattr(exc, "worker", 0),),
                              step=getattr(exc, "step", None),
                              reason="chaos mid-collective kill")
        return None

    # -- remesh ----------------------------------------------------------
    def handle_failure(self, state, failure: PeerFailed, *,
                       fold_into: int = 0):
        """Coordinated abort + remesh: shrink the mesh by the dead workers,
        migrate EF/comp per the configured policy, re-place the state, and
        account the event.  Returns the state ON the new mesh; the caller
        must rebuild its jitted steps against :attr:`mesh` (which is how
        the sharded transport's owner partition gets recomputed)."""
        if not failure.failed:
            raise failure
        new_world = self.world - len(set(failure.failed))
        if new_world < self.cfg.min_world:
            raise PeerFailed(
                failure.failed, step=failure.step,
                reason=(f"{failure.reason}; surviving world {new_world} "
                        f"below min_world {self.cfg.min_world} — "
                        "not remeshing"))
        t0 = time.monotonic()
        new_mesh, removed = surviving_mesh(self.mesh, failure.failed)
        state, dropped = shrink_state(state, failure.failed,
                                      policy=self.cfg.ef_policy,
                                      fold_into=fold_into)
        state = self._place(state, new_mesh)
        self._parked.extend(zip(sorted(set(failure.failed)), removed))
        self.mesh = new_mesh
        if self.gossip is not None:
            self.gossip.note_dead(failure.failed, failure.reason)
        self.peer_failures += len(set(failure.failed))
        self.remesh_count += 1
        self.dropped_ef_norm += dropped
        self.remesh_latency_ms = (time.monotonic() - t0) * 1e3
        self._log(f"elastic: remeshed {new_world + len(set(failure.failed))}"
                  f" -> {new_world} workers after {failure.reason} "
                  f"(ef={self.cfg.ef_policy}"
                  + (f", dropped ‖ef‖={dropped:.3e}" if dropped else "")
                  + f", {self.remesh_latency_ms:.0f} ms)")
        if self.events is not None:
            self.events.emit(
                "remesh", step=failure.step, failed=list(failure.failed),
                world=new_world, ef_policy=self.cfg.ef_policy,
                dropped_ef_norm=float(dropped),
                latency_ms=self.remesh_latency_ms)
        return state

    # -- re-admission ----------------------------------------------------
    def readmit(self, state, n: Optional[int] = None):
        """Scale back up at a remesh barrier: append up to ``n`` parked
        devices (all, by default) back onto the mesh tail, zero their EF
        rows, broadcast-re-warm their comp rows, and re-place the live
        state (the "live checkpoint" — in-process survivors already hold
        the replicated fields the rejoiner needs)."""
        n = len(self._parked) if n is None else min(int(n), len(self._parked))
        if n <= 0:
            return state
        back, self._parked = self._parked[:n], self._parked[n:]
        ranks = [r for r, _ in back]
        new_mesh = extended_mesh(self.mesh, [d for _, d in back])
        state = self._place(expand_state(state, n_new=n), new_mesh)
        self.mesh = new_mesh
        self.readmit_count += n
        if self.gossip is not None:
            for r in ranks:
                self.gossip.readmit(r)
        self._log(f"elastic: readmitted {n} worker(s) {ranks} -> "
                  f"world {self.world}")
        if self.events is not None:
            self.events.emit("readmit", ranks=ranks, world=self.world)
        return state

    @property
    def parked(self) -> Tuple[int, ...]:
        """Ranks currently removed from the mesh (readmission pool)."""
        return tuple(r for r, _ in self._parked)

    # -- accounting ------------------------------------------------------
    def metrics(self) -> Dict[str, float]:
        """The declared ``elastic/*`` keys (obs/registry.py) for the
        harness exporters (Prometheus textfile, heartbeat payload)."""
        return {
            "elastic/peer_failures": float(self.peer_failures),
            "elastic/remesh_count": float(self.remesh_count),
            "elastic/dropped_ef_norm": float(self.dropped_ef_norm),
            "elastic/remesh_latency_ms": float(self.remesh_latency_ms),
        }
