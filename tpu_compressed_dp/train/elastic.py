"""Elastic data-parallel training: failure detection, coordinated abort,
W-1 remesh with EF/PowerSGD state migration, and scale-up re-admission.

The robustness stack up to here survives nonfinite steps (the step guard)
and single-process death (checkpoint + watchdog relaunch) — but only by
restarting the WHOLE job: a dead host stalls every collective until the
supervisor kills the world.  This module is the other half: survivors
detect the failure, abort coherently, shrink the mesh by the dead worker,
and keep training.

Failure model (three detection planes, all raising :class:`PeerFailed`):

  * **heartbeat gossip** (:class:`PeerGossip`) — every worker process
    writes its own liveness file (:meth:`PeerGossip.beat`, same atomic
    record shape as :class:`~tpu_compressed_dp.utils.resilience.Heartbeat`)
    into a shared ``--elastic_dir``; every worker reads its peers' files
    each poll.  A peer whose record stays older than ``peer_timeout_s`` is
    dead.  Records carry an ``incarnation`` (seeded from
    ``TCDP_RESTART_COUNT``, exported by ``tools/watchdog.py --relaunch``):
    a restarted peer's fresh file has a HIGHER incarnation, so it reads as
    "this rank died and came back" (a rejoin candidate), never as
    continuity of the dead life.
  * **bounded collective fetch** (:func:`fetch_with_timeout`) — a
    ``device_get`` on results of an in-flight step normally returns in
    step time; when a peer died mid-collective it blocks forever.  The
    fetch runs in a worker thread with a deadline; blowing it raises
    ``PeerFailed`` instead of stalling silently.  (Honest limitation: an
    in-process XLA computation cannot be cancelled — on real multi-host
    deployments the abort is a process exit and the watchdog relaunches
    into the next remesh barrier; under the single-process simulation the
    deterministic ``crash=mid_collective`` chaos plays the dying peer.)
  * **deterministic chaos** — ``--chaos crash=mid_collective,...`` raises
    after step dispatch, while the step's collectives are in flight;
    :meth:`ElasticRuntime.failure_from` translates it into the same
    ``PeerFailed`` the real detectors raise, which is what lets the chaos
    drill prove the whole remesh path bitwise.

Remesh semantics (what the departing worker owes the run):

  * ``params`` / ``opt_state`` / ``batch_stats`` / ``guard`` are replicated
    — survivors already hold them; they are preserved **bitwise**.
  * ``TrainState.ef`` is per-worker unsent gradient mass (the memory of
    "Sparsified SGD with Memory"): the lost worker's residual row is either
    **folded** into a survivor's residual (an exact fp32 add — total EF
    mass is conserved, and the folded mass re-enters the very next step's
    gradients like any EF carry) or **dropped** and accounted in the
    ``elastic/dropped_ef_norm`` metric (the L2 norm of the gradient mass
    the run will never apply).
  * ``TrainState.comp`` (PowerSGD warm-start factors) is identical on
    every worker by construction (the P/Q psums average factors), so the
    dead worker's rows are simply deleted; on re-admission the returning
    worker's factors are re-warmed from a broadcast of a survivor's row —
    re-agreement is what keeps the power iteration meaningful.
  * The sharded transport's owner partition (``ops/wire_sharded.py``) is a
    pure function of the static world size read off the mesh at trace
    time, so rebuilding the train step over the W-1 mesh recomputes the
    shard boundaries automatically (tests/test_wire_sharded.py asserts the
    W -> W-1 partition keeps covering the flat unit space exactly).

Scale-up: a returning host rejoins at the next remesh barrier
(:meth:`ElasticRuntime.readmit`): the mesh is extended with the parked
device, the live (in-process) state plays the role of the live checkpoint,
the new EF row starts at zero (a fresh worker has not withheld anything)
and the comp rows are broadcast-re-warmed.

``tools/chaos_drill.py`` (``elastic_remesh`` / ``elastic_readmit`` /
``elastic_matrix``) proves the invariants end to end.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import numpy as np

from tpu_compressed_dp.parallel.mesh import DATA_AXIS, make_data_mesh
from tpu_compressed_dp.utils.resilience import read_heartbeat

__all__ = [
    "PeerFailed", "ElasticConfig", "PeerGossip", "ElasticRuntime",
    "heartbeat_path", "write_peer_heartbeat", "fetch_with_timeout",
    "abandoned_fetch_count",
    "surviving_mesh", "extended_mesh", "migrate_ef", "migrate_comp",
    "expand_ef", "expand_comp", "shrink_state", "expand_state",
    "TrimBatches",
]

#: Default failure-detection budget: a peer heartbeat older than this (and
#: a collective fetch blocked longer than this) counts as a dead peer.
DEFAULT_PEER_TIMEOUT_S = 60.0


class PeerFailed(RuntimeError):
    """Coordinated abort signal: one or more peers are gone.

    ``failed`` — worker indices (mesh positions / gossip ranks) declared
    dead; may be empty when a collective timeout fired before the gossip
    named a culprit (the runtime then consults gossip to fill it in).
    ``step`` — the attempted global step, when known.  Every survivor
    raises the same verdict from the same evidence (stale files age out at
    the same wall-clock deadline; the chaos injection is step-keyed), which
    is what makes the abort coordinated rather than a stampede.
    """

    def __init__(self, failed: Iterable[int] = (), *,
                 step: Optional[int] = None, reason: str = "peer failure"):
        self.failed: Tuple[int, ...] = tuple(sorted(int(f) for f in failed))
        self.step = None if step is None else int(step)
        self.reason = reason
        who = list(self.failed) if self.failed else "unknown peer(s)"
        at = f" at step {self.step}" if self.step is not None else ""
        super().__init__(f"elastic: {who} failed{at}: {reason}")


@dataclasses.dataclass(frozen=True)
class ElasticConfig:
    """Knobs of the elastic runtime (CLI surface: ``--elastic*``).

    gossip_dir:      shared directory of per-rank heartbeat files (None =
                     no gossip plane; chaos / fetch timeouts still work)
    rank:            this worker's gossip rank
    peer_timeout_s:  staleness/fetch deadline before a peer counts as dead
    min_world:       refuse to shrink below this many workers (the job is
                     better off dying and relaunching than limping on a
                     mesh too small to be worth the lr/batch mismatch)
    ef_policy:       'fold' (conserve the lost EF mass into a survivor) |
                     'drop' (discard it; counted in elastic/dropped_ef_norm)
    """

    gossip_dir: Optional[str] = None
    rank: int = 0
    peer_timeout_s: float = DEFAULT_PEER_TIMEOUT_S
    min_world: int = 2
    ef_policy: str = "fold"

    def __post_init__(self):
        if self.ef_policy not in ("fold", "drop"):
            raise ValueError(
                f"ef_policy must be fold|drop, got {self.ef_policy!r}")
        if self.peer_timeout_s <= 0:
            raise ValueError("peer_timeout_s must be > 0")
        if self.min_world < 1:
            raise ValueError("min_world must be >= 1")


# ------------------------------------------------------------------ gossip

def heartbeat_path(gossip_dir: str, rank: int) -> str:
    return os.path.join(gossip_dir, f"rank{int(rank)}.json")


def write_peer_heartbeat(gossip_dir: str, rank: int, step: int, *,
                         incarnation: int = 0,
                         ts: Optional[float] = None,
                         wall: Callable[[], float] = time.time) -> str:
    """One atomic heartbeat write into the gossip directory — the
    thread-free form the harness step loops and the drill's simulated
    peers use (same record shape and atomic tmp+replace as
    :class:`~tpu_compressed_dp.utils.resilience.Heartbeat`).  ``ts``
    overrides the record timestamp outright; ``wall`` is the injectable
    clock it defaults to (peer staleness is judged on LOCAL monotonic
    freshness, never on this field — see :class:`PeerGossip`)."""
    os.makedirs(gossip_dir, exist_ok=True)
    path = heartbeat_path(gossip_dir, rank)
    rec = {"ts": wall() if ts is None else float(ts),
           "step": int(step), "rank": int(rank),
           "incarnation": int(incarnation)}
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f)
    os.replace(tmp, path)
    return path


class PeerGossip:
    """Decentralised failure detector over a shared heartbeat directory.

    Each worker runs one instance: it reads every peer's file per
    :meth:`check` and votes a peer dead once no FRESH record (a changed
    record under the admitted incarnation) has been seen for
    ``peer_timeout_s`` of local monotonic time.  Incarnation rules:

      * the first record seen for a rank admits its incarnation;
      * a record with a LOWER incarnation than admitted is a stale file of
        a dead prior life — it never refreshes liveness;
      * a record with a HIGHER incarnation means the peer process was
        replaced: the admitted life is declared dead (its in-memory EF row
        is gone regardless of how alive the new process looks) and the new
        incarnation becomes a rejoin candidate for the next barrier.

    Construction starts every peer's grace clock at "now", so a cold start
    where peers appear over ``peer_timeout_s`` does not false-positive.

    Clock discipline: staleness is measured on THIS process's monotonic
    clock, and a peer is fresh when its record *changed* since the last
    sweep — the writer's wall-clock ``ts`` is never compared against local
    time.  An NTP step (or plain cross-host clock skew) therefore cannot
    mass-declare live peers dead: as long as a peer keeps rewriting its
    file (``beat`` rewrites at least every ``peer_timeout_s / 4``), it
    keeps reading as alive no matter what its timestamps claim.  The one
    cost is that a pre-existing stale file buys its dead writer a single
    extra timeout window at first observation (it reads as a change) —
    the same grace a cold start already grants.
    """

    def __init__(self, gossip_dir: str, rank: int, world: int, *,
                 peer_timeout_s: float = DEFAULT_PEER_TIMEOUT_S,
                 incarnation: Optional[int] = None,
                 now: Callable[[], float] = time.monotonic):
        self.gossip_dir = gossip_dir
        self.rank = int(rank)
        self.world = int(world)
        self.peer_timeout_s = float(peer_timeout_s)
        if incarnation is None:
            try:
                incarnation = int(os.environ.get("TCDP_RESTART_COUNT", "0"))
            except ValueError:
                incarnation = 0
        self.incarnation = int(incarnation)
        self._last_beat = float("-inf")
        self._now = now
        t0 = now()
        self._last_fresh: Dict[int, float] = {
            r: t0 for r in range(self.world)}
        self._last_rec: Dict[int, Tuple] = {}    # rank -> last observed record
        self._admitted: Dict[int, Optional[int]] = {
            r: None for r in range(self.world)}
        self._dead: Dict[int, str] = {}          # rank -> reason
        self._rejoin: Dict[int, int] = {}        # rank -> new incarnation

    @property
    def dead(self) -> Tuple[int, ...]:
        return tuple(sorted(self._dead))

    def beat(self, step: int = 0) -> None:
        """Write THIS rank's own liveness file (rate-limited to a quarter of
        the timeout — peers need several fresh observations per window, and
        an atomic replace per step would be pure filesystem churn)."""
        now = self._now()
        if now - self._last_beat >= self.peer_timeout_s / 4:
            write_peer_heartbeat(self.gossip_dir, self.rank, step,
                                 incarnation=self.incarnation, ts=now)
            self._last_beat = now

    def note_dead(self, ranks: Iterable[int], reason: str = "declared dead"
                  ) -> None:
        """Record an externally-detected failure (chaos conversion, a peer
        named by another detector) so rejoin tracking stays consistent."""
        for r in ranks:
            self._dead.setdefault(int(r), reason)

    def check(self, now: Optional[float] = None) -> Dict[int, str]:
        """One gossip sweep; returns the NEWLY dead peers ``{rank: why}``
        (already-known dead peers are only re-reported via :attr:`dead`)."""
        now = self._now() if now is None else now
        newly: Dict[int, str] = {}
        for r in range(self.world):
            if r == self.rank:
                continue
            hb = read_heartbeat(heartbeat_path(self.gossip_dir, r))
            inc = None
            changed = False
            if hb is not None:
                inc = int(hb.get("incarnation", 0) or 0)
                rec = (hb.get("ts"), hb.get("step"), inc)
                changed = rec != self._last_rec.get(r)
                if changed:
                    self._last_rec[r] = rec
            if r in self._dead:
                dead_inc = self._admitted.get(r)
                if (hb is not None and changed and inc is not None
                        and (dead_inc is None or inc > dead_inc)):
                    self._rejoin[r] = inc
                continue
            if hb is not None:
                if self._admitted[r] is None:
                    self._admitted[r] = inc
                if inc > self._admitted[r]:
                    # the process we were tracking is gone; its replacement
                    # may rejoin, but the tracked life's state died with it
                    why = (f"incarnation advanced {self._admitted[r]} -> "
                           f"{inc} (peer restarted)")
                    self._dead[r] = why
                    newly[r] = why
                    self._rejoin[r] = inc
                    continue
                if changed and inc == self._admitted[r]:
                    # liveness = "the record is still being rewritten",
                    # stamped with OUR clock — never the writer's wall ts
                    self._last_fresh[r] = max(self._last_fresh[r],
                                              float(now))
            age = now - self._last_fresh[r]
            if age > self.peer_timeout_s:
                why = (f"no fresh heartbeat for {age:.1f}s "
                       f"(> {self.peer_timeout_s:g}s)")
                self._dead[r] = why
                newly[r] = why
        return newly

    def raise_if_dead(self, step: Optional[int] = None,
                      now: Optional[float] = None) -> None:
        newly = self.check(now)
        if newly:
            reason = "; ".join(f"rank {r}: {why}"
                               for r, why in sorted(newly.items()))
            raise PeerFailed(newly, step=step, reason=reason)

    def rejoin_candidates(self, now: Optional[float] = None
                          ) -> Dict[int, int]:
        """Dead ranks whose directory now shows a fresh, newer incarnation
        — ready for re-admission at the next barrier."""
        self.check(now)
        return dict(self._rejoin)

    def readmit(self, rank: int) -> None:
        """Move a rank back to the tracked set under its new incarnation."""
        rank = int(rank)
        inc = self._rejoin.pop(rank, None)
        self._dead.pop(rank, None)
        self._admitted[rank] = inc
        self._last_fresh[rank] = self._now()


# ------------------------------------------------- bounded collective fetch

# Timed-out fetch threads cannot be killed (a device_get blocked inside the
# runtime has no cancellation point), but they must not LEAK: each abandoned
# runner is tracked here and reaped (dropped from the list) as soon as it
# finishes, and its discard flag makes it drop the fetched buffer instead of
# pinning it in a result box nobody will ever read.
_ABANDONED_FETCHES: List[threading.Thread] = []
_ABANDONED_LOCK = threading.Lock()


def abandoned_fetch_count() -> int:
    """Live runner threads whose deadline expired (reaps finished ones
    first).  Steady state is 0 once their blocking fetches drain — the
    hammer test pins that repeated timeouts do not accumulate threads."""
    with _ABANDONED_LOCK:
        _ABANDONED_FETCHES[:] = [t for t in _ABANDONED_FETCHES
                                 if t.is_alive()]
        return len(_ABANDONED_FETCHES)


def fetch_with_timeout(thunk: Callable[[], Any], timeout_s: float, *,
                       step: Optional[int] = None,
                       what: str = "collective fetch") -> Any:
    """Run a blocking device fetch with a deadline.

    ``jax.device_get`` on an in-flight step's outputs normally completes in
    step time; with a peer dead mid-collective it blocks forever.  The
    thunk runs in a daemon thread; exceeding ``timeout_s`` raises
    :class:`PeerFailed` (with no culprit — gossip names the rank).  The
    thunk's own exception, if any, is re-raised on the caller's thread.

    On timeout the caller marks the runner DISCARDED before abandoning it:
    whenever the blocked fetch eventually returns, the runner drops the
    value on the floor (no reference survives the function) instead of
    parking a dead world's device buffers in a result box forever.  The
    abandoned thread itself is tracked and reaped once it exits
    (:func:`abandoned_fetch_count`).
    """
    box: Dict[str, Any] = {}
    done = threading.Event()
    lock = threading.Lock()
    discarded = [False]

    def runner():
        try:
            value = thunk()
            with lock:
                if not discarded[0]:
                    box["value"] = value
            del value
        except BaseException as e:
            with lock:
                if not discarded[0]:  # nobody is left to re-raise it to
                    box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=runner, daemon=True,
                         name=f"tcdp-elastic-fetch({what})")
    t.start()
    if not done.wait(timeout_s):
        with lock:
            discarded[0] = True
        with _ABANDONED_LOCK:
            _ABANDONED_FETCHES[:] = [a for a in _ABANDONED_FETCHES
                                     if a.is_alive()]
            _ABANDONED_FETCHES.append(t)
        raise PeerFailed((), step=step, reason=(
            f"{what} still blocked after {timeout_s:g}s — "
            "a peer died mid-collective"))
    if "error" in box:
        raise box["error"]
    return box.get("value")


# ------------------------------------------------------------ mesh surgery

def _mesh_grid(mesh) -> np.ndarray:
    """The mesh's devices as a ``(data_rows, model_cols)`` object grid:
    row ``i`` is data worker ``i``'s devices across every model axis
    (tensor/sequence/pipe), flattened in axis order.  Elastic membership
    changes are DATA-row changes — a dying host takes one data row (its
    model shards are replicated across data rows, so survivors still hold
    a full copy of the model); losing a model COLUMN would orphan model
    state and stays a job restart.  The grid view is what lets the surgery
    below work unchanged on ('data',), dp x tp, dp x sp, ... meshes."""
    names = tuple(mesh.axis_names)
    if DATA_AXIS not in names:
        raise ValueError(
            f"elastic remesh needs a '{DATA_AXIS}' axis; got axes {names}")
    dev = np.asarray(mesh.devices, dtype=object)
    i = names.index(DATA_AXIS)
    rows = int(dev.shape[i])
    return np.moveaxis(dev, i, 0).reshape(rows, -1)


def _rebuild_mesh(mesh, grid: np.ndarray):
    """A mesh over the ``(data_rows, model_cols)`` grid with the template
    mesh's axis names and model-axis sizes (only the data axis resizes, so
    the harness's PartitionSpecs keep resolving on the new mesh)."""
    names = tuple(mesh.axis_names)
    grid = np.asarray(grid, dtype=object)
    if names == (DATA_AXIS,):
        return make_data_mesh(devices=list(grid.reshape(-1)))
    i = names.index(DATA_AXIS)
    model_shape = [int(mesh.shape[n]) for n in names if n != DATA_AXIS]
    dev = grid.reshape([grid.shape[0]] + model_shape)
    dev = np.moveaxis(dev, 0, i)
    return jax.sharding.Mesh(dev, names)


def _as_rows(new_devices: Sequence, model_cols: int) -> np.ndarray:
    """Normalise readmitted devices to grid rows: a flat device list on a
    dp-only mesh (one device per row), or per-row device sequences on a
    sheared mesh."""
    rows = []
    for entry in new_devices:
        row = [entry] if not isinstance(entry, (list, tuple, np.ndarray)) \
            else list(entry)
        if len(row) != model_cols:
            raise ValueError(
                f"readmitted row has {len(row)} device(s); the mesh's "
                f"model axes need {model_cols} per data row")
        rows.append(row)
    return np.asarray(rows, dtype=object).reshape(len(rows), model_cols)


def surviving_mesh(mesh, failed: Sequence[int]):
    """The W-1 (or W-F) mesh over the surviving data rows, order
    preserved; returns ``(new_mesh, removed_rows)`` with each dead
    worker's devices (a full model row) parked for later re-admission."""
    grid = _mesh_grid(mesh)
    failed_set = {int(f) for f in failed}
    bad = [f for f in failed_set if not 0 <= f < grid.shape[0]]
    if bad:
        raise ValueError(f"failed worker index {bad} outside world "
                         f"{grid.shape[0]}")
    keep = [i for i in range(grid.shape[0]) if i not in failed_set]
    if not keep:
        raise ValueError("no survivors to remesh over")
    removed = [list(grid[i]) if grid.shape[1] > 1 else grid[i, 0]
               for i in sorted(failed_set)]
    return _rebuild_mesh(mesh, grid[keep]), removed


def extended_mesh(mesh, new_devices: Sequence):
    """The mesh with returning data rows appended (rejoiners take the tail
    positions — survivor worker indices, and with them the EF rows and the
    owner partition prefix, stay stable)."""
    grid = _mesh_grid(mesh)
    rows = _as_rows(new_devices, grid.shape[1])
    return _rebuild_mesh(mesh, np.concatenate([grid, rows], axis=0))


# -------------------------------------------------------- state migration

def migrate_ef(ef: Any, failed: Sequence[int], *, policy: str = "fold",
               fold_into: int = 0) -> Tuple[Any, float]:
    """Shrink the EF residual's leading worker axis by ``failed``.

    ``fold``: the lost rows are added into survivor row ``fold_into``
    (survivor order) with one exact fp32 add per leaf — total residual mass
    is conserved and re-enters the next step's gradients like any EF carry.
    ``drop``: the lost rows are discarded; returns their global L2 norm
    (root of the summed squares across all leaves, fp64 accumulate) so the
    caller can account the abandoned gradient mass.

    Host-side numpy on fetched arrays; returns ``(new_ef, dropped_norm)``.
    """
    if policy not in ("fold", "drop"):
        raise ValueError(f"ef policy must be fold|drop, got {policy!r}")
    if ef == ():
        return (), 0.0
    failed = sorted({int(f) for f in failed})
    dropped_sq = 0.0

    def one(a):
        nonlocal dropped_sq
        a = np.asarray(a)
        if a.ndim < 1 or a.shape[0] <= max(failed):
            raise ValueError(
                f"EF leaf with leading axis {a.shape} cannot lose "
                f"worker(s) {failed}")
        lost = a[failed]
        kept = np.delete(a, failed, axis=0)
        if policy == "fold":
            kept = kept.copy()
            kept[fold_into] = kept[fold_into] + lost.sum(axis=0)
        else:
            dropped_sq += float(np.sum(lost.astype(np.float64) ** 2))
        return kept

    new_ef = jax.tree.map(one, ef)
    return new_ef, float(np.sqrt(dropped_sq))


def migrate_comp(comp: Any, failed: Sequence[int]) -> Any:
    """Shrink the compressor state's leading worker axis: the PowerSGD
    warm-start rows are identical across workers (psum-averaged), so the
    dead rows are deleted with nothing to fold."""
    if comp == ():
        return ()
    failed = sorted({int(f) for f in failed})
    return jax.tree.map(
        lambda a: np.delete(np.asarray(a), failed, axis=0), comp)


def expand_ef(ef: Any, n_new: int = 1) -> Any:
    """Append zero rows for rejoining workers (a fresh worker has not
    withheld any gradient mass yet)."""
    if ef == () or n_new <= 0:
        return ef
    return jax.tree.map(
        lambda a: np.concatenate(
            [np.asarray(a),
             np.zeros((n_new,) + np.asarray(a).shape[1:],
                      np.asarray(a).dtype)], axis=0), ef)


def expand_comp(comp: Any, n_new: int = 1) -> Any:
    """Append broadcast copies of survivor row 0 for rejoining workers —
    the PowerSGD re-warm: every worker must iterate in the same basis, so
    the newcomer adopts the survivors' converged factors instead of a cold
    random restart."""
    if comp == () or n_new <= 0:
        return comp
    return jax.tree.map(
        lambda a: np.concatenate(
            [np.asarray(a)]
            + [np.asarray(a)[:1]] * n_new, axis=0), comp)


def _rows_per_data_row(tree: Any, data_world: Optional[int]) -> int:
    """How many leading-axis rows one DATA row owns in an EF/comp tree.

    The leading worker axis counts SYNC workers, which on a sheared mesh
    is the product of every axis the gradient sync spans — e.g. the LM
    harness's EF is ``P(('data', 'seq'), ...)``, so a dp x sp mesh has
    ``sp`` EF rows per data row, laid out data-major (data row ``d`` owns
    rows ``[d*sp, (d+1)*sp)``).  Derived from the leaves' actual leading
    dim against the mesh's data extent so no extra configuration can
    drift from the real layout."""
    if tree == () or data_world is None:
        return 1
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return 1
    lead = int(np.asarray(leaves[0]).shape[0])
    if data_world <= 0 or lead % data_world:
        raise ValueError(
            f"EF/comp leading axis {lead} does not divide into "
            f"{data_world} data rows")
    return lead // data_world


def _worker_rows(data_rows: Sequence[int], m: int) -> List[int]:
    """Expand failed DATA-row indices into leading-axis row indices
    (identity when ``m == 1``, the dp-only layout)."""
    return [int(d) * m + j for d in sorted({int(x) for x in data_rows})
            for j in range(m)]


def shrink_state(state, failed: Sequence[int], *, policy: str = "fold",
                 fold_into: int = 0, data_world: Optional[int] = None):
    """Migrate a TrainState off the dead workers: fetch ef/comp to host,
    shrink their leading axes, keep every replicated field bitwise.
    ``failed`` are DATA-row indices; ``data_world`` (the data extent of
    the mesh being shrunk) translates them to leading-axis rows when the
    sync world is wider than the data axis (dp x sp — see
    :func:`_rows_per_data_row`); omitted, rows map 1:1.  Returns
    ``(new_state, dropped_ef_norm)`` — still host-side; the caller places
    it on the new mesh (``with_mesh_sharding`` / ``place_lm_state``)."""
    ef = jax.device_get(state.ef) if state.ef != () else ()
    comp = jax.device_get(state.comp) if state.comp != () else ()
    ef_rows = _worker_rows(failed, _rows_per_data_row(ef, data_world))
    comp_rows = _worker_rows(failed, _rows_per_data_row(comp, data_world))
    new_ef, dropped = migrate_ef(ef, ef_rows, policy=policy,
                                 fold_into=fold_into)
    new_comp = migrate_comp(comp, comp_rows)
    return dataclasses.replace(state, ef=new_ef, comp=new_comp), dropped


def expand_state(state, n_new: int = 1, *,
                 data_world: Optional[int] = None):
    """Extend a TrainState for ``n_new`` rejoining DATA rows (zero EF
    rows, broadcast-re-warmed comp rows — ``m`` leading-axis rows per data
    row, see :func:`_rows_per_data_row` with ``data_world`` the CURRENT
    pre-extension data extent); host-side, caller re-places."""
    ef = jax.device_get(state.ef) if state.ef != () else ()
    comp = jax.device_get(state.comp) if state.comp != () else ()
    m_ef = _rows_per_data_row(ef, data_world)
    m_comp = _rows_per_data_row(comp, data_world)
    return dataclasses.replace(state, ef=expand_ef(ef, n_new * m_ef),
                               comp=expand_comp(comp, n_new * m_comp))


class TrimBatches:
    """Iterable view trimming each batch dict to at most ``size`` rows —
    the remeshed world divides a smaller global batch, so after W -> W-1
    each batch is cut to ``(bs // W') * W'`` rows (short final batches pass
    through untouched for the eval padding to handle)."""

    def __init__(self, inner, size: int):
        self.inner = inner
        self.size = int(size)

    def __iter__(self):
        for batch in self.inner:
            yield {k: v[:self.size] for k, v in batch.items()}

    def __len__(self):
        return len(self.inner)


# ----------------------------------------------------------------- runtime

class ElasticRuntime:
    """The harness-facing elastic driver: owns the current mesh, converts
    failures, performs the remesh, and keeps the ``elastic/*`` counters.

    Typical harness shape::

        el = ElasticRuntime(cfg, mesh, chaos=chaos, events=events)
        while epoch < epochs:
            try:
                state, ... = train_epoch(step_for(el.mesh), state, ...)
            except Exception as e:
                failure = el.failure_from(e)
                if failure is None:
                    raise
                state = el.handle_failure(state, failure)
                continue        # retry the epoch on the W-1 mesh
            epoch += 1
    """

    def __init__(self, cfg: ElasticConfig, mesh, *, chaos=None,
                 gossip: Optional[PeerGossip] = None, events=None,
                 place: Optional[Callable[[Any, Any], Any]] = None,
                 crash=None, rendezvous=None,
                 ef_axes: Tuple[str, ...] = (DATA_AXIS,),
                 flight=None, stream=None, stream_armed=None,
                 log: Callable[[str], None] = print):
        _mesh_grid(mesh)  # validates the mesh shape up front
        self.cfg = cfg
        self.mesh = mesh
        self.chaos = chaos
        self.gossip = gossip
        self.events = events
        # flight recorder (obs/flight.py): peer failures dump a blackbox
        # bundle, remesh/cascade/readmit transitions land in its elastic
        # ring — observation only, never load-bearing
        self.flight = flight
        # how to re-place a migrated state on a new mesh; the CNN default
        # is the TrainState's own sharding rule, the LM harness passes its
        # place_lm_state closure
        self._place = place or (lambda s, m: s.with_mesh_sharding(m))
        # the armed CrashInjector (utils/chaos.py): handle_failure probes
        # its 'during_remesh' phase so a second death INSIDE the failure
        # handler cascades instead of wedging
        self.crash = crash
        # the rendezvous handle (train/rendezvous.py) — arms the
        # multi-process coordinated re-init path; None keeps every remesh
        # in-process (the single-process simulation and all the drills)
        self.rendezvous = rendezvous
        # the delta StreamWriter (stream/writer.py): every committed world
        # transition requests a keyframe (the delta window re-anchors on
        # the new membership) and the rejoin barrier flushes the stream so
        # a joiner catching up from it adopts the live params bitwise
        self.stream = stream
        # whether the delta stream is armed FLEET-WIDE (``--stream_dir``
        # on every process).  The writer itself lives only on process 0,
        # so the warm-rejoin barrier layout must key on this flag — a
        # value every survivor shares — never on ``self.stream`` (which
        # would make process 0 pick a different collective pytree than
        # the other survivors).  Defaults to following ``stream`` for
        # single-writer setups constructed directly (tests, drills).
        self.stream_armed = (stream is not None if stream_armed is None
                             else bool(stream_armed))
        self.stream_rejoin_bytes = 0.0     # newest warm rejoin's byte cost
        # which mesh axes the gradient sync spans — the EF leading axis
        # layout (the LM harness passes ('data', 'seq'))
        self.ef_axes = tuple(ef_axes)
        self._log = log
        self._parked: List = []            # (rank, device row) of removed peers
        self._proc_ranks: Tuple[int, ...] = tuple(
            range(jax.process_count()))    # surviving ORIGINAL process ranks
        self.epoch = 0                     # last committed rendezvous epoch
        self.peer_failures = 0
        self.remesh_count = 0
        self.cascade_count = 0             # failures converted DURING a remesh
        self.readmit_count = 0
        self.dropped_ef_norm = 0.0
        self.remesh_latency_ms = 0.0       # latest remesh's host latency
        self.remesh_ms = 0.0               # cumulative remesh downtime

    @property
    def world(self) -> int:
        return int(self.mesh.shape[DATA_AXIS])

    # -- detection -------------------------------------------------------
    def poll(self, step: Optional[int] = None) -> None:
        """Write our own gossip heartbeat, then sweep the peers'; raises
        :class:`PeerFailed` on newly-dead peers."""
        if self.gossip is not None:
            self.gossip.beat(0 if step is None else step)
            self.gossip.raise_if_dead(step)

    def bounded_get(self, x, *, step: Optional[int] = None,
                    what: str = "step metrics fetch"):
        """``jax.device_get`` with the peer-timeout deadline."""
        return fetch_with_timeout(lambda: jax.device_get(x),
                                  self.cfg.peer_timeout_s, step=step,
                                  what=what)

    def failure_from(self, exc: BaseException) -> Optional[PeerFailed]:
        """Translate an exception into the coordinated failure it signals,
        or None for faults that are not elastic's to handle.

        * :class:`PeerFailed` passes through; an empty culprit list (a
          fetch timeout) is filled in from the gossip's dead set.
        * A ``mid_collective`` :class:`~tpu_compressed_dp.utils.chaos.ChaosCrash`
          becomes the simulated death of ``chaos.worker`` — the same
          handler path real survivors reach through gossip/timeouts.
        """
        from tpu_compressed_dp.utils.chaos import ChaosCrash

        if isinstance(exc, PeerFailed):
            if not exc.failed and self.gossip is not None:
                dead = self.gossip.dead or tuple(self.gossip.check())
                if dead:
                    return PeerFailed(dead, step=exc.step,
                                      reason=f"{exc.reason}; gossip names "
                                             f"{list(dead)}")
            return exc
        if (isinstance(exc, ChaosCrash)
                and getattr(exc, "mode", "step") in ("mid_collective",
                                                     "during_remesh")):
            return PeerFailed((getattr(exc, "worker", 0),),
                              step=getattr(exc, "step", None),
                              reason=("chaos kill during remesh"
                                      if exc.mode == "during_remesh"
                                      else "chaos mid-collective kill"))
        return None

    # -- remesh ----------------------------------------------------------
    def handle_failure(self, state, failure: PeerFailed, *,
                       fold_into: int = 0):
        """Coordinated abort + remesh: shrink the mesh by the dead workers,
        migrate EF/comp per the configured policy, re-place the state, and
        account the event.  Returns the state ON the new mesh; the caller
        must rebuild its jitted steps against :attr:`mesh` (which is how
        the sharded transport's owner partition gets recomputed).

        Cascading failures: a peer dying while survivors are INSIDE this
        handler (the ``crash=during_remesh`` chaos phase plays it
        deterministically) re-enters failure handling — the dead set is
        unioned and the shrink restarts from the still-uncommitted
        original mesh/state, down to ``min_world``, instead of committing
        a world that is already stale.

        Under ``jax.process_count() > 1`` with a rendezvous armed, the
        commit goes through the coordinated re-init path
        (:meth:`_handle_failure_multiprocess`) — survivors agree on a new
        epoch, tear down and re-run ``jax.distributed.initialize`` over
        the reduced process set, then rebuild the mesh and state on the
        new runtime."""
        from tpu_compressed_dp.utils.chaos import ChaosCrash

        if not failure.failed:
            raise failure
        # dump the blackbox NOW, while the evidence is fresh: even though
        # this handler usually recovers, the dead peer's why/when must
        # survive a cascade that kills us mid-remesh
        if self.flight is not None:
            self.flight.observe(failure, step=failure.step)
        if self.rendezvous is not None and jax.process_count() > 1:
            return self._handle_failure_multiprocess(state, failure)
        failed = {int(f) for f in failure.failed}
        reason = failure.reason
        t0 = time.monotonic()
        while True:
            new_world = self.world - len(failed)
            if new_world < self.cfg.min_world:
                err = PeerFailed(
                    sorted(failed), step=failure.step,
                    reason=(f"{reason}; surviving world {new_world} "
                            f"below min_world {self.cfg.min_world} — "
                            "not remeshing"))
                if self.flight is not None:
                    self.flight.observe(err, step=failure.step)
                raise err
            new_mesh, removed = surviving_mesh(self.mesh, sorted(failed))
            new_state, dropped = shrink_state(
                state, sorted(failed), policy=self.cfg.ef_policy,
                fold_into=fold_into, data_world=self.world)
            # a second death while we are mid-remesh: probe the chaos
            # injector's during_remesh phase BEFORE committing — the
            # shrink restarts with the union against the original mesh
            if self.crash is not None:
                try:
                    probe = (failure.step if failure.step is not None
                             else getattr(self.crash, "crash_at_step", 0))
                    self.crash.check(probe, phase="during_remesh")
                except ChaosCrash as e:
                    more = self.failure_from(e)
                    if more is not None and more.failed:
                        extra = set(more.failed) - failed
                        failed |= set(more.failed)
                        self.cascade_count += 1
                        self.peer_failures += len(extra)
                        reason = f"{reason}; then {more.reason}"
                        self._log("elastic: peer(s) "
                                  f"{sorted(more.failed)} died during the "
                                  "remesh — re-entering failure handling "
                                  f"over {sorted(failed)}")
                        if self.events is not None:
                            self.events.emit(
                                "remesh_cascade", step=failure.step,
                                failed=sorted(failed),
                                added=sorted(extra))
                        if self.flight is not None:
                            self.flight.record(
                                "elastic", "remesh_cascade",
                                step=failure.step, failed=sorted(failed),
                                added=sorted(extra))
                        continue
            break
        state = self._place(new_state, new_mesh)
        self._parked.extend(zip(sorted(failed), removed))
        old_world = self.world
        self.mesh = new_mesh
        if self.gossip is not None:
            self.gossip.note_dead(failed, reason)
        self.peer_failures += len(set(failure.failed))
        self.remesh_count += 1
        self.dropped_ef_norm += dropped
        self.remesh_latency_ms = (time.monotonic() - t0) * 1e3
        self.remesh_ms += self.remesh_latency_ms
        self._log(f"elastic: remeshed {old_world}"
                  f" -> {new_world} workers after {reason} "
                  f"(ef={self.cfg.ef_policy}"
                  + (f", dropped ‖ef‖={dropped:.3e}" if dropped else "")
                  + f", {self.remesh_latency_ms:.0f} ms)")
        if self.events is not None:
            self.events.emit(
                "remesh", step=failure.step, failed=sorted(failed),
                world=new_world, ef_policy=self.cfg.ef_policy,
                dropped_ef_norm=float(dropped),
                latency_ms=self.remesh_latency_ms,
                remesh_ms=self.remesh_ms)
        if self.flight is not None:
            self.flight.record(
                "elastic", "remesh", step=failure.step,
                failed=sorted(failed), world=new_world,
                ef_policy=self.cfg.ef_policy,
                dropped_ef_norm=float(dropped),
                latency_ms=self.remesh_latency_ms)
        self._stream_keyframe()
        return state

    # -- re-admission ----------------------------------------------------
    def readmit(self, state, n: Optional[int] = None):
        """Scale back up at a remesh barrier: append up to ``n`` parked
        devices (all, by default) back onto the mesh tail, zero their EF
        rows, broadcast-re-warm their comp rows, and re-place the live
        state (the "live checkpoint" — in-process survivors already hold
        the replicated fields the rejoiner needs)."""
        n = len(self._parked) if n is None else min(int(n), len(self._parked))
        if n <= 0:
            return state
        t0 = time.monotonic()
        back, self._parked = self._parked[:n], self._parked[n:]
        ranks = [r for r, _ in back]
        new_mesh = extended_mesh(self.mesh, [d for _, d in back])
        state = self._place(
            expand_state(state, n_new=n, data_world=self.world), new_mesh)
        self.mesh = new_mesh
        self.remesh_ms += (time.monotonic() - t0) * 1e3
        self.readmit_count += n
        if self.gossip is not None:
            for r in ranks:
                self.gossip.readmit(r)
        self._log(f"elastic: readmitted {n} worker(s) {ranks} -> "
                  f"world {self.world}")
        if self.events is not None:
            self.events.emit("readmit", ranks=ranks, world=self.world)
        if self.flight is not None:
            self.flight.record("elastic", "readmit", ranks=ranks,
                               world=self.world)
        self._stream_keyframe()
        return state

    def _stream_keyframe(self) -> None:
        """Re-anchor the delta stream after a committed world transition —
        a consumer must never need segments that straddle a membership
        change to reconstruct the post-transition state."""
        st = self.stream
        if st is not None:
            try:
                st.request_keyframe()
            except Exception:
                pass  # the stream tee must never fail a remesh

    @property
    def parked(self) -> Tuple[int, ...]:
        """Ranks currently removed from the mesh (readmission pool)."""
        return tuple(r for r, _ in self._parked)

    # -- multi-process world transitions ---------------------------------
    # These paths only run under jax.process_count() > 1 with a rendezvous
    # armed; they are exercised by the HAS_CPU_MULTIPROCESS-gated 2-process
    # drills (tests/test_elastic_multiprocess.py).  The pure pieces (rank ->
    # row maps, local-shard gathers) are unit tested single-process.

    def _proc_data_rows(self, ranks: Iterable[int]) -> List[int]:
        """The mesh data rows owned by the given ORIGINAL process ranks
        (contiguous blocks in surviving-rank order)."""
        per = self.world // max(len(self._proc_ranks), 1)
        pos = {r: i for i, r in enumerate(self._proc_ranks)}
        return [pos[int(r)] * per + j for r in ranks for j in range(per)
                if int(r) in pos]

    def _host_snapshot(self, state):
        """Fetch what THIS process can still read before the distributed
        runtime is torn down: replicated fields in full (every process
        holds a replica shard), EF/comp as the locally-addressable leading
        rows.  Never touches non-addressable shards — those live(d) on
        peers and fetching them is exactly the hang we are escaping."""
        def full(x):
            if isinstance(x, jax.Array) and not x.is_fully_addressable:
                return np.asarray(x.addressable_data(0))
            return jax.device_get(x)

        def local_rows(x):
            x_arr = x
            shards = sorted(x_arr.addressable_shards,
                            key=lambda s: s.index[0].start or 0)
            rows = [np.asarray(s.data) for s in shards]
            if any(r.shape[1:] != tuple(x_arr.shape[1:]) for r in rows):
                raise NotImplementedError(
                    "multi-process elastic re-init supports EF/comp "
                    "sharded on the leading worker axis only; trailing "
                    "model-axis shards (dp x tp multi-host) need a full "
                    "restart")
            return np.concatenate(rows, axis=0)

        repl = jax.tree.map(full, dataclasses.replace(state, ef=(), comp=()))
        ef = (jax.tree.map(local_rows, state.ef)
              if state.ef != () else ())
        comp = (jax.tree.map(local_rows, state.comp)
                if state.comp != () else ())
        return repl, ef, comp

    def _assemble_multiprocess(self, repl, local_ef, local_comp, mesh):
        """Rebuild a global TrainState on a freshly re-initialised runtime:
        replicated fields place through the harness's place callback (every
        process holds the full value), EF/comp reassemble from each
        process's local rows (``make_array_from_process_local_data``)."""
        from jax.sharding import NamedSharding, PartitionSpec

        state = self._place(repl, mesh)
        sharding = NamedSharding(mesh, PartitionSpec(self.ef_axes))
        lead = int(mesh.shape[DATA_AXIS]) * int(
            np.prod([mesh.shape[a] for a in self.ef_axes
                     if a != DATA_AXIS] or [1]))

        def assemble(rows):
            rows = np.asarray(rows)
            return jax.make_array_from_process_local_data(
                sharding, rows, (lead,) + rows.shape[1:])

        ef = (jax.tree.map(assemble, local_ef) if local_ef != () else ())
        comp = (jax.tree.map(assemble, local_comp)
                if local_comp != () else ())
        return dataclasses.replace(state, ef=ef, comp=comp)

    def _handle_failure_multiprocess(self, state, failure: PeerFailed):
        """Coordinated multi-process shrink: snapshot local state, agree on
        the surviving world through the rendezvous, re-init
        ``jax.distributed`` over it, rebuild mesh + state.

        ``failure.failed`` are ORIGINAL process ranks (the gossip plane's
        currency).  The dead processes' EF rows lived only in their memory
        and are unrecoverable — multi-process death always behaves like
        the ``drop`` policy with an unknowable norm (logged, and flagged
        on the remesh event), whatever ``ef_policy`` says."""
        from tpu_compressed_dp.train.rendezvous import reinit_distributed

        t0 = time.monotonic()
        dead = {int(f) for f in failure.failed}
        live = [r for r in self._proc_ranks if r not in dead]
        if self.cfg.rank not in live:
            raise PeerFailed(sorted(dead), step=failure.step,
                             reason=f"{failure.reason}; this rank is among "
                                    "the declared dead — exiting for the "
                                    "watchdog")
        grid = _mesh_grid(self.mesh)
        dead_rows = self._proc_data_rows(dead)
        new_world = self.world - len(dead_rows)
        if new_world < self.cfg.min_world:
            raise PeerFailed(
                sorted(dead), step=failure.step,
                reason=(f"{failure.reason}; surviving world {new_world} "
                        f"below min_world {self.cfg.min_world} — "
                        "not remeshing"))
        repl, local_ef, local_comp = self._host_snapshot(state)
        decision = self.rendezvous.propose(
            live, deadline_s=self.cfg.peer_timeout_s * 4)
        reinit_distributed(decision, log=self._log)
        new_grid = np.asarray(jax.devices(), dtype=object).reshape(
            -1, grid.shape[1])
        new_mesh = _rebuild_mesh(self.mesh, new_grid)
        state = self._assemble_multiprocess(repl, local_ef, local_comp,
                                            new_mesh)
        self.mesh = new_mesh
        self._proc_ranks = decision.ranks
        self.epoch = decision.epoch
        if self.gossip is not None:
            self.gossip.note_dead(dead, failure.reason)
        self.peer_failures += len(dead)
        self.remesh_count += 1
        self.remesh_latency_ms = (time.monotonic() - t0) * 1e3
        self.remesh_ms += self.remesh_latency_ms
        self._log(f"elastic: epoch {decision.epoch}: re-initialised "
                  f"{len(live) + len(dead)} -> {len(live)} processes "
                  f"(world {new_world}) after {failure.reason}; dead "
                  "peers' EF rows unrecoverable (dropped, norm unknown); "
                  f"{self.remesh_latency_ms:.0f} ms")
        if self.events is not None:
            self.events.emit(
                "remesh", step=failure.step, failed=sorted(dead),
                world=new_world, epoch=decision.epoch,
                ef_policy="drop", ef_unrecoverable=True,
                dropped_ef_norm=float("nan"),
                latency_ms=self.remesh_latency_ms,
                remesh_ms=self.remesh_ms)
        if self.flight is not None:
            self.flight.record(
                "elastic", "remesh", step=failure.step,
                failed=sorted(dead), world=new_world,
                epoch=decision.epoch, ef_policy="drop",
                latency_ms=self.remesh_latency_ms)
        return state

    def rejoin_barrier(self, state):
        """Survivor half of multi-process scale-up, called at an epoch
        boundary: fold pending join requests (watchdog-relaunched hosts
        waiting in :meth:`Rendezvous.join`) into a new world epoch,
        re-init, and rebuild with zero EF rows for the joiners (their rows
        arrive via each process's local contribution — the joiner's own
        :meth:`join_world` supplies zeros).  Returns ``(state, changed)``;
        the caller rebuilds its jitted steps when ``changed``.

        Warm rejoin: when the delta stream is armed FLEET-WIDE
        (``stream_armed`` — ``--stream_dir`` on every process) and EVERY
        pending joiner's join record carries the ``stream`` flag (it
        caught up from the delta stream —
        :func:`tpu_compressed_dp.stream.rejoin.warm_rejoin`), the barrier
        flushes the stream first (:meth:`StreamWriter.sync`, on the one
        process that holds the writer — the head now reconstructs to the
        live params bitwise), publishes the warm bit in the epoch commit,
        and the broadcast SKIPS the params tree: the joiners already hold
        it, and the dominant rejoin byte cost moves from the full dense
        params onto the compressed delta wire.  Every participant —
        survivor or joiner, writer-holding or not — picks the collective
        layout from the COMMITTED ``decision.warm`` bit, so the pytree
        structures agree by construction."""
        if self.rendezvous is None or jax.process_count() <= 1:
            return state, False
        joins = self.rendezvous.pending_joins()
        ready = sorted(set(joins) - set(self._proc_ranks))
        if not ready:
            return state, False
        t0 = time.monotonic()
        # derived ONLY from fleet-shared state: the immutable join records
        # plus the fleet-wide armed flag — never from self.stream, which
        # only process 0 holds (harness/loop.py make_stream)
        want_warm = (self.stream_armed
                     and all(joins[r].get("stream") is not None
                             for r in ready))
        repl, local_ef, local_comp = self._host_snapshot(state)
        if want_warm and self.stream is not None:
            # pin stream == live params before the epoch commit: the
            # joiners' adopted reconstruction is bitwise what the
            # survivors hold, so skipping the params broadcast is safe
            self.stream.sync(repl.params, step=int(repl.step))
        new_ranks = sorted(set(self._proc_ranks) | set(ready))
        from jax.experimental import multihost_utils

        from tpu_compressed_dp.train.rendezvous import reinit_distributed
        # only survivors vote (the joiners are parked in Rendezvous.join);
        # the coordinator is therefore a survivor — the broadcast source
        # of the replicated state the joiners are missing
        decision = self.rendezvous.propose(
            new_ranks, voters=self._proc_ranks, warm=want_warm,
            deadline_s=self.cfg.peer_timeout_s * 4)
        reinit_distributed(decision, log=self._log)
        warm = decision.warm
        src = decision.ranks.index(decision.coordinator)
        if warm:
            params_local = repl.params
            bx = multihost_utils.broadcast_one_to_all(
                dataclasses.replace(repl, params=()),
                is_source=decision.process_id == src)
            repl = dataclasses.replace(bx, params=params_local)
        else:
            repl = multihost_utils.broadcast_one_to_all(
                repl, is_source=decision.process_id == src)
        if local_comp != ():
            # comp rows are identical across workers by construction, so
            # the coordinator's local rows re-warm the joiners' too
            local_comp = multihost_utils.broadcast_one_to_all(
                local_comp, is_source=decision.process_id == src)
        grid_cols = _mesh_grid(self.mesh).shape[1]
        new_grid = np.asarray(jax.devices(), dtype=object).reshape(
            -1, grid_cols)
        new_mesh = _rebuild_mesh(self.mesh, new_grid)
        state = self._assemble_multiprocess(repl, local_ef, local_comp,
                                            new_mesh)
        self.mesh = new_mesh
        self._proc_ranks = tuple(decision.ranks)
        self.epoch = decision.epoch
        self.readmit_count += len(ready)
        if self.gossip is not None:
            for r in ready:
                self.gossip.readmit(r)
        self.remesh_ms += (time.monotonic() - t0) * 1e3
        self._log(f"elastic: epoch {decision.epoch}: readmitted process(es) "
                  f"{ready} -> world {self.world}")
        if self.events is not None:
            self.events.emit("readmit", ranks=ready, world=self.world,
                             epoch=decision.epoch, warm=warm)
        if self.flight is not None:
            self.flight.record("elastic", "readmit", ranks=ready,
                               world=self.world, epoch=decision.epoch,
                               warm=warm)
        self._stream_keyframe()
        return state, True

    def join_world(self, state, decision, *, adopted_params=None,
                   adopted_info=None):
        """Joiner half of multi-process scale-up: called by a relaunched
        harness right after init, with the :class:`EpochDecision` its
        rendezvous join returned.  The fresh-init state supplies shapes;
        replicated values are adopted from the survivors' broadcast and
        the EF rows start at zero (a rejoiner has withheld nothing).

        ``adopted_params`` is the warm-rejoin reconstruction
        (:func:`tpu_compressed_dp.stream.rejoin.warm_rejoin`).  The
        broadcast layout follows the COMMITTED ``decision.warm`` bit —
        the same record the survivors read — never the local adoption
        outcome, so the collective's pytree structure cannot diverge
        across the fleet.  When the commit says warm the params tree is
        taken from the stream (the survivors skipped it); a warm commit
        with NO adoption in hand raises — joining the params-skipping
        collective with fresh-init params would silently train from
        garbage, so the safe move is to exit for the watchdog and retry
        (the next probe joins cold and the survivors commit accordingly).
        When the commit says cold, any stream catch-up is discarded and
        the full broadcast is taken.  ``adopted_info`` is the rejoin's
        accounting dict (bytes/segments/step)."""
        from jax.experimental import multihost_utils

        repl, local_ef, local_comp = self._host_snapshot(state)
        # the re-elected coordinator (a survivor) is the source of truth
        # for every replicated field and the comp re-warm; our fresh-init
        # values are discarded
        src = decision.ranks.index(decision.coordinator)
        warm = bool(getattr(decision, "warm", False))
        if warm and adopted_params is None:
            from tpu_compressed_dp.train.rendezvous import RendezvousError
            raise RendezvousError(
                f"epoch {decision.epoch} committed warm (survivors skip the "
                "params broadcast) but this joiner holds no stream "
                "reconstruction to adopt — exiting for the watchdog to "
                "relaunch; the next join probe re-decides warm vs cold")
        if warm:
            repl = dataclasses.replace(repl, params=adopted_params)
            bx = multihost_utils.broadcast_one_to_all(
                dataclasses.replace(repl, params=()),
                is_source=decision.process_id == src)
            repl = dataclasses.replace(bx, params=repl.params)
            self.stream_rejoin_bytes = float(
                (adopted_info or {}).get("bytes", 0))
            if self.flight is not None:
                self.flight.record("stream", "warm_join",
                                   epoch=decision.epoch,
                                   **dict(adopted_info or {}))
        else:
            if adopted_params is not None:
                self._log("elastic: stream catch-up unused — epoch "
                          f"{decision.epoch} committed a cold (full "
                          "broadcast) admission")
            repl = multihost_utils.broadcast_one_to_all(
                repl, is_source=decision.process_id == src)
        if local_comp != ():
            local_comp = multihost_utils.broadcast_one_to_all(
                local_comp, is_source=decision.process_id == src)
        local_ef = jax.tree.map(np.zeros_like, local_ef)
        grid_cols = _mesh_grid(self.mesh).shape[1]
        new_grid = np.asarray(jax.devices(), dtype=object).reshape(
            -1, grid_cols)
        new_mesh = _rebuild_mesh(self.mesh, new_grid)
        state = self._assemble_multiprocess(repl, local_ef, local_comp,
                                            new_mesh)
        self.mesh = new_mesh
        self._proc_ranks = tuple(decision.ranks)
        self.epoch = decision.epoch
        self._log(f"elastic: rejoined world epoch {decision.epoch} as "
                  f"process {decision.process_id}/{decision.num_processes}")
        return state

    # -- accounting ------------------------------------------------------
    def metrics(self) -> Dict[str, float]:
        """The declared ``elastic/*`` keys (obs/registry.py) for the
        harness exporters (Prometheus textfile, heartbeat payload)."""
        return {
            "elastic/peer_failures": float(self.peer_failures),
            "elastic/remesh_count": float(self.remesh_count),
            "elastic/dropped_ef_norm": float(self.dropped_ef_norm),
            "elastic/remesh_latency_ms": float(self.remesh_latency_ms),
            "elastic/remesh_ms": float(self.remesh_ms),
            "stream/rejoin_bytes": float(self.stream_rejoin_bytes),
        }
