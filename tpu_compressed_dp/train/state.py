"""Train state: the one pytree that is stepped, replicated, and checkpointed.

Unlike the reference — where momentum lives in torch.optim, BN stats inside
modules, and the error-feedback residual in a wrapper that is *not*
checkpointed (SURVEY.md §5) — everything mutable is explicit here and goes
through Orbax as a unit: ``{step, params, batch_stats, opt_state, ef, rng}``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

__all__ = ["TrainState"]


@struct.dataclass
class TrainState:
    step: jax.Array            # int32 global step counter
    params: Any                # model parameters (fp32 master copy)
    batch_stats: Any           # BatchNorm running stats ({} for stat-free models)
    opt_state: Any             # optimizer buffers (momentum, ...)
    ef: Any                    # error-feedback residual pytree, or () when off
    rng: jax.Array             # base PRNG key; per-step keys are folded from it

    @classmethod
    def create(cls, params: Any, batch_stats: Any, opt_state: Any, ef: Any, rng: jax.Array):
        return cls(
            step=jnp.asarray(0, jnp.int32),
            params=params,
            batch_stats=batch_stats,
            opt_state=opt_state,
            ef=ef,
            rng=rng,
        )
