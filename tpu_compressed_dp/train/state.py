"""Train state: the one pytree that is stepped, replicated, and checkpointed.

Unlike the reference — where momentum lives in torch.optim, BN stats inside
modules, and the error-feedback residual in a wrapper that is *not*
checkpointed (SURVEY.md §5) — everything mutable is explicit here and goes
through Orbax as a unit: ``{step, params, batch_stats, opt_state, ef, rng,
comp}``.  ``comp`` is the persistent compressor state (PowerSGD warm-start
factors, :func:`tpu_compressed_dp.parallel.dp.init_comp_state`): it shards
and checkpoints exactly like the EF residual, so a resumed run keeps the
power iteration's converged subspace instead of re-warming from random.
``guard`` is the step guard's carry (dynamic loss scale + skip counters,
:func:`tpu_compressed_dp.train.guard.init_guard_state`): replicated — the
cross-worker finiteness vote makes every field identical on every worker —
and checkpointed, so a restored run resumes with the loss scale it had
found, not the (possibly overflowing) init.
``control`` is the adaptive-compression controller's carry (current rung,
open decision window, :func:`tpu_compressed_dp.control.state.init_control_state`):
replicated and checkpointed like ``guard``, but mutated only by the HOST
controller between steps — the jitted step threads it through untouched.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["TrainState"]


@struct.dataclass
class TrainState:
    step: jax.Array            # int32 global step counter
    params: Any                # model parameters (fp32 master copy)
    batch_stats: Any           # BatchNorm running stats ({} for stat-free models)
    opt_state: Any             # optimizer buffers (momentum, ...)
    ef: Any                    # error-feedback residual pytree, or () when off
    rng: jax.Array             # base PRNG key; per-step keys are folded from it
    comp: Any = ()             # compressor state (PowerSGD warm-start Q), or ()
    guard: Any = ()            # step-guard state (GuardState), or () when off
    control: Any = ()          # adaptive-compression state (ControlState), or ()

    @classmethod
    def create(cls, params: Any, batch_stats: Any, opt_state: Any, ef: Any,
               rng: jax.Array, comp: Any = (), guard: Any = (),
               control: Any = ()):
        return cls(
            step=jnp.asarray(0, jnp.int32),
            params=params,
            batch_stats=batch_stats,
            opt_state=opt_state,
            ef=ef,
            rng=rng,
            comp=comp,
            guard=guard,
            control=control,
        )

    def with_mesh_sharding(self, mesh: Mesh, axis_name: str = "data") -> "TrainState":
        """Place the state on ``mesh``: everything replicated except the
        per-worker EF residual and compressor state, sharded on their
        leading device axis.  Needed after a checkpoint restore (which lands
        arrays on one device) before the shard_map'd step will accept the
        state."""
        rep = NamedSharding(mesh, P())
        dat = NamedSharding(mesh, P(axis_name))
        placed = {
            f.name: jax.device_put(getattr(self, f.name), rep)
            for f in dataclasses.fields(self)
            if f.name not in ("ef", "comp")
        }
        ef = self.ef if self.ef == () else jax.device_put(self.ef, dat)
        comp = self.comp if self.comp == () else jax.device_put(self.comp, dat)
        return dataclasses.replace(self, ef=ef, comp=comp, **placed)

    def place_with_specs(self, specs: "TrainState", mesh: Mesh) -> "TrainState":
        """Place every field per a specs-TrainState (fields are PartitionSpecs
        or pytrees of them, e.g. ``lm_state_specs`` / ``pp_state_specs``).
        Needed after a checkpoint restore (which lands arrays on one device)
        before a shard_map'd step will accept the state."""

        def place(x, spec):
            return jax.device_put(x, NamedSharding(mesh, spec))

        placed = {}
        for f in dataclasses.fields(self):
            val, spec = getattr(self, f.name), getattr(specs, f.name)
            if f.name in ("ef", "comp", "guard", "control") and val == ():
                placed[f.name] = ()
            elif isinstance(spec, P):
                placed[f.name] = jax.tree.map(lambda v: place(v, spec), val)
            else:
                spec_leaves = jax.tree.leaves(
                    spec, is_leaf=lambda x: isinstance(x, P))
                val_leaves = jax.tree.leaves(val)
                placed[f.name] = jax.tree.unflatten(
                    jax.tree.structure(val),
                    [place(v, s) for v, s in zip(val_leaves, spec_leaves)],
                )
        return TrainState(**placed)
