"""File-based rendezvous: how a multi-process elastic world agrees to change.

The elastic runtime (:mod:`tpu_compressed_dp.train.elastic`) can already
shrink a mesh and migrate EF/compressor state — but under
``jax.process_count() > 1`` that is not enough: the dead peer's process is
wired into the jax.distributed client/coordinator, and every collective
over the old world hangs until the runtime is torn down and re-initialised
over the survivors.  This module is the agreement protocol for that
teardown, built from the same primitives as the gossip plane (atomic
tmp+``os.replace`` JSON files over the shared ``--elastic_dir``, the
``TCDP_RESTART_COUNT`` incarnation scheme):

  * **epoch file** (``epoch.json``) — the committed world: monotone
    ``epoch`` counter, the surviving original ranks, the re-elected
    coordinator (lowest surviving rank) and its ``host:port``.  One atomic
    replace per transition; readers never see a torn record.
  * **vote files** (``vote.e<E>.rank<R>.json``) — rank R's proposal for
    epoch E: the survivor set it believes in, plus its advertised host.
    The transition commits only when every proposed survivor has voted the
    SAME set (conflicting membership views raise — a split-brain world is
    worse than a dead one); the lowest surviving rank then writes the
    epoch file and everyone else adopts it.
  * **join files** (``join.rank<R>.json``) — a watchdog-relaunched host
    announcing itself (with its new incarnation) to the running world;
    survivors fold pending joins into the next epoch at a readmit barrier,
    and the joiner waits on the epoch file with a bounded deadline,
    falling back to park-and-retry (exit; the watchdog's backoff is the
    retry loop).

The coordinator port is ``base_port + epoch`` — deterministic, so every
survivor derives the same address without another round of agreement, and
a re-elected coordinator on the same host never collides with the dead
world's listener.

Everything here is plain files + injectable clocks: the protocol is unit
tested single-process and deterministic (tier-1); the 2-process drills
that exercise it against a real ``jax.distributed`` runtime are gated on
``HAS_CPU_MULTIPROCESS`` in the slow tier.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import re
import time
from typing import Callable, Dict, Iterable, Optional, Tuple

__all__ = [
    "EPOCH_ENV", "ADDR_ENV", "DIR_ENV",
    "RendezvousError", "RendezvousTimeout", "EpochDecision", "Rendezvous",
    "epoch_path", "read_epoch", "write_epoch", "export_env",
    "maybe_rejoin_from_env", "reinit_distributed",
]

#: Env vars ``tools/watchdog.py --relaunch --elastic_dir`` exports so a
#: restarted host rejoins the RUNNING world instead of forming a fresh one.
EPOCH_ENV = "TCDP_RENDEZVOUS_EPOCH"
ADDR_ENV = "TCDP_RENDEZVOUS_ADDR"
DIR_ENV = "TCDP_ELASTIC_DIR"

#: Coordinator port for epoch E is ``base_port + E`` (see module docstring).
DEFAULT_BASE_PORT = 51300


class RendezvousError(RuntimeError):
    """Unrecoverable disagreement (conflicting membership votes, a commit
    that excludes this rank): the safe move is a full restart, not a limp."""


class RendezvousTimeout(RendezvousError):
    """A bounded wait (vote quorum, join admission) expired.  For a joiner
    this is the park-and-retry exit: the join file stays behind and the
    watchdog's backoff schedules the next attempt."""


def _read_json(path: str) -> Optional[dict]:
    """Tolerant read: None for missing/torn/foreign content (same contract
    as ``utils.resilience.read_heartbeat`` — a reader never crashes on a
    writer's in-flight state, it just retries next poll)."""
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    return rec if isinstance(rec, dict) else None


def _write_json(path: str, rec: dict) -> str:
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f)
    os.replace(tmp, path)
    return path


def epoch_path(rdzv_dir: str) -> str:
    return os.path.join(rdzv_dir, "epoch.json")


def read_epoch(rdzv_dir: str) -> Optional[dict]:
    """The committed world record, or None before the first transition."""
    rec = _read_json(epoch_path(rdzv_dir))
    if rec is None or "epoch" not in rec or "ranks" not in rec:
        return None
    return rec


def write_epoch(rdzv_dir: str, rec: dict) -> str:
    os.makedirs(rdzv_dir, exist_ok=True)
    return _write_json(epoch_path(rdzv_dir), rec)


@dataclasses.dataclass(frozen=True)
class EpochDecision:
    """One committed world transition, as seen by one process.

    ``ranks`` are the surviving ORIGINAL launch ranks (sorted) — gossip
    files, gossip ranks, and parked-worker bookkeeping keep using them.
    ``process_id`` is this process's CONTIGUOUS index within ``ranks`` (the
    id ``jax.distributed.initialize`` needs), or None when the commit
    excludes this process (it must park and wait to be readmitted).

    ``warm`` is the committed warm-rejoin bit of a readmission epoch: the
    survivors decided (once, at the barrier) that the params tree is
    SKIPPED in the admission broadcast because every admitted joiner
    adopts it from the delta stream.  Survivors and joiners alike pick
    the broadcast layout from THIS bit — never from local state — so the
    collective's pytree structure agrees fleet-wide by construction
    (``ElasticRuntime.rejoin_barrier`` / ``join_world``).
    """

    epoch: int
    ranks: Tuple[int, ...]
    coordinator: int
    address: str
    process_id: Optional[int]
    warm: bool = False

    @property
    def num_processes(self) -> int:
        return len(self.ranks)


class Rendezvous:
    """One process's handle on the shared rendezvous directory.

    All waits poll with an injectable ``now``/``sleep`` pair (monotonic by
    default — wall-clock steps must not expire agreement deadlines), so
    unit tests script multi-rank interleavings deterministically from a
    single thread.
    """

    def __init__(self, rdzv_dir: str, rank: int, *,
                 host: str = "127.0.0.1",
                 base_port: int = DEFAULT_BASE_PORT,
                 now: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 wall: Callable[[], float] = time.time,
                 poll_s: float = 0.05):
        self.dir = rdzv_dir
        self.rank = int(rank)
        self.host = host
        self.base_port = int(base_port)
        self._now = now
        self._sleep = sleep
        # informational ts fields on shared-dir records; injectable so a
        # replayed transition writes byte-identical files (tcdp-lint TCDP101)
        self._wall = wall
        self.poll_s = float(poll_s)
        os.makedirs(rdzv_dir, exist_ok=True)

    # -- committed world -------------------------------------------------
    def current(self) -> Optional[dict]:
        return read_epoch(self.dir)

    def decision_from(self, rec: dict) -> EpochDecision:
        ranks = tuple(sorted(int(r) for r in rec["ranks"]))
        pid = ranks.index(self.rank) if self.rank in ranks else None
        return EpochDecision(
            epoch=int(rec["epoch"]), ranks=ranks,
            coordinator=int(rec.get("coordinator", ranks[0])),
            address=str(rec["address"]), process_id=pid,
            warm=bool(rec.get("warm", False)))

    # -- votes -----------------------------------------------------------
    def _vote_path(self, epoch: int, rank: int) -> str:
        return os.path.join(self.dir, f"vote.e{int(epoch)}.rank{int(rank)}.json")

    def vote(self, epoch: int, survivors: Iterable[int]) -> None:
        _write_json(self._vote_path(epoch, self.rank), {
            "epoch": int(epoch), "rank": self.rank,
            "survivors": sorted(int(s) for s in survivors),
            "host": self.host, "ts": self._wall()})

    def read_votes(self, epoch: int) -> Dict[int, dict]:
        votes: Dict[int, dict] = {}
        pattern = os.path.join(self.dir, f"vote.e{int(epoch)}.rank*.json")
        for path in glob.glob(pattern):
            m = re.search(r"rank(\d+)\.json$", path)
            rec = _read_json(path) if m else None
            if m and rec is not None and int(rec.get("epoch", -1)) == int(epoch):
                votes[int(m.group(1))] = rec
        return votes

    def _gc_votes(self, committed_epoch: int) -> None:
        # best-effort: stale votes of already-committed epochs are noise,
        # never consulted (read_votes keys on the exact epoch)
        for path in glob.glob(os.path.join(self.dir, "vote.e*.rank*.json")):
            m = re.search(r"vote\.e(\d+)\.", path)
            if m and int(m.group(1)) <= int(committed_epoch):
                try:
                    os.remove(path)
                except OSError:
                    pass

    # -- the transition --------------------------------------------------
    def propose(self, members: Iterable[int], *,
                voters: Optional[Iterable[int]] = None,
                warm: bool = False,
                deadline_s: float = 60.0) -> EpochDecision:
        """Agree on the next epoch over ``members`` (which must include
        this rank).  Every VOTER calls this with the same member set (they
        all derived it from the same coordinated :class:`PeerFailed` or
        the same join files); the lowest voting rank commits the epoch
        file once all votes agree, everyone returns the committed
        decision.  ``voters`` defaults to the members — a readmission
        barrier passes the SURVIVOR subset, because pending joiners are
        parked in :meth:`join` and cannot vote (and the re-elected
        coordinator must be a survivor: it is the broadcast source for
        the replicated state the joiner is missing).  ``warm`` is the
        readmission barrier's warm-rejoin bit; every voter derives it
        from the same immutable join records, the leader publishes it in
        the commit, and BOTH sides of the admission broadcast take their
        layout from the committed record (:class:`EpochDecision`).  A
        commit that lands with a HIGHER epoch than proposed (a cascade
        won the race) is adopted as long as it still names this rank."""
        members = tuple(sorted({int(s) for s in members}))
        voters = (members if voters is None
                  else tuple(sorted({int(v) for v in voters})))
        if self.rank not in members:
            raise RendezvousError(
                f"rank {self.rank} proposing a world {members} that "
                "excludes itself")
        if self.rank not in voters or not set(voters) <= set(members):
            raise RendezvousError(
                f"voters {voters} must include this rank and be a subset "
                f"of the members {members}")
        cur = self.current()
        epoch = (int(cur["epoch"]) if cur else 0) + 1
        self.vote(epoch, members)
        leader = voters[0]
        deadline = self._now() + float(deadline_s)
        while True:
            rec = self.current()
            if rec is not None and int(rec["epoch"]) >= epoch:
                if self.rank not in [int(r) for r in rec["ranks"]]:
                    raise RendezvousError(
                        f"epoch {rec['epoch']} committed without rank "
                        f"{self.rank}: {sorted(rec['ranks'])}")
                return self.decision_from(rec)
            votes = self.read_votes(epoch)
            if set(votes) >= set(voters):
                worlds = {tuple(v.get("survivors", ())) for r, v in
                          votes.items() if r in voters}
                if worlds != {members}:
                    raise RendezvousError(
                        f"conflicting membership votes for epoch {epoch}: "
                        f"{sorted(worlds)} — split-brain, not committing")
                if self.rank == leader:
                    host = str(votes[leader].get("host", self.host))
                    rec = {"epoch": epoch, "ranks": list(members),
                           "coordinator": leader,
                           "address": f"{host}:{self.base_port + epoch}",
                           "warm": bool(warm), "ts": self._wall()}
                    write_epoch(self.dir, rec)
                    self._gc_votes(epoch)
                    return self.decision_from(rec)
            if self._now() >= deadline:
                missing = sorted(set(voters) - set(votes))
                raise RendezvousTimeout(
                    f"epoch {epoch} vote quorum not reached in "
                    f"{deadline_s:g}s (missing votes from {missing})")
            self._sleep(self.poll_s)

    # -- joins -----------------------------------------------------------
    def _join_path(self, rank: int) -> str:
        return os.path.join(self.dir, f"join.rank{int(rank)}.json")

    def request_join(self, *, incarnation: int = 0,
                     stream_seq: Optional[int] = None) -> None:
        rec = {"rank": self.rank, "incarnation": int(incarnation),
               "host": self.host, "ts": self._wall()}
        if stream_seq is not None:
            # warm rejoin: this joiner caught up from the delta stream
            # through segment `stream_seq` — survivors reading the flag
            # flush the stream and skip the params broadcast
            # (ElasticRuntime.rejoin_barrier)
            rec["stream"] = int(stream_seq)
        _write_json(self._join_path(self.rank), rec)

    def pending_joins(self) -> Dict[int, dict]:
        """Relaunched hosts waiting for admission (rank -> join record)."""
        joins: Dict[int, dict] = {}
        for path in glob.glob(os.path.join(self.dir, "join.rank*.json")):
            m = re.search(r"rank(\d+)\.json$", path)
            rec = _read_json(path) if m else None
            if m and rec is not None:
                joins[int(m.group(1))] = rec
        return joins

    def clear_join(self, rank: int) -> None:
        try:
            os.remove(self._join_path(rank))
        except OSError:
            pass

    def join(self, *, incarnation: int = 0,
             stale_epoch: Optional[int] = None,
             deadline_s: float = 60.0,
             stream_seq: Optional[int] = None) -> Optional[EpochDecision]:
        """A relaunched host's admission wait: announce, then poll for a
        commit that names this rank.  ``stale_epoch`` is the epoch the
        relaunch env advertised — the world this process DIED out of; only
        a strictly newer commit admits (the stale epoch file may still
        list us).  ``stream_seq`` advertises a warm rejoin (see
        :meth:`request_join`).  Returns None on deadline (park-and-retry:
        the join file stays behind, the caller exits, the watchdog
        retries)."""
        self.request_join(incarnation=incarnation, stream_seq=stream_seq)
        deadline = self._now() + float(deadline_s)
        while True:
            rec = self.current()
            if (rec is not None
                    and self.rank in [int(r) for r in rec["ranks"]]
                    and (stale_epoch is None
                         or int(rec["epoch"]) > int(stale_epoch))):
                self.clear_join(self.rank)
                return self.decision_from(rec)
            if self._now() >= deadline:
                return None
            self._sleep(self.poll_s)


# -------------------------------------------------- relaunch env plumbing

def export_env(env: dict, rec: dict) -> dict:
    """Stamp the committed epoch into a child environment (the watchdog's
    half of rejoin): the relaunched harness reads these back through
    :func:`maybe_rejoin_from_env`."""
    env[EPOCH_ENV] = str(int(rec["epoch"]))
    env[ADDR_ENV] = str(rec.get("address", ""))
    return env


def maybe_rejoin_from_env(rdzv_dir: Optional[str], rank: int, *,
                          deadline_s: float = 300.0,
                          env: Optional[dict] = None,
                          stream_seq: Optional[int] = None,
                          **rdzv_kw) -> Optional[EpochDecision]:
    """The relaunched harness's entry: if the environment carries a
    rendezvous epoch (the watchdog saw a running world when it respawned
    us), wait in the join barrier for admission and return the decision to
    initialise against.  Returns None when there is nothing to rejoin (a
    fresh launch).  Raises :class:`RendezvousTimeout` when the deadline
    expires — the caller exits nonzero and the watchdog's backoff is the
    retry (park-and-retry)."""
    env = os.environ if env is None else env
    if EPOCH_ENV not in env:
        return None
    rdzv_dir = rdzv_dir or env.get(DIR_ENV)
    if not rdzv_dir:
        return None
    try:
        stale_epoch = int(env[EPOCH_ENV])
    except ValueError:
        stale_epoch = None
    try:
        incarnation = int(env.get("TCDP_RESTART_COUNT", "0") or 0)
    except ValueError:
        incarnation = 0
    rdzv = Rendezvous(rdzv_dir, rank, **rdzv_kw)
    decision = rdzv.join(incarnation=incarnation, stale_epoch=stale_epoch,
                         deadline_s=deadline_s, stream_seq=stream_seq)
    if decision is None:
        raise RendezvousTimeout(
            f"rank {rank} not admitted within {deadline_s:g}s — parking "
            "(join request left behind; the watchdog retries)")
    return decision


def reinit_distributed(decision: EpochDecision, *,
                       shutdown: Optional[Callable[[], None]] = None,
                       initialize: Optional[Callable[..., None]] = None,
                       log: Callable[[str], None] = print) -> None:
    """Tear down the dead world's ``jax.distributed`` runtime and bring up
    the committed one: shutdown (tolerating a client already wedged on the
    dead coordinator), then ``initialize`` against the re-elected
    coordinator with this process's new contiguous id.  Injectable for the
    single-process unit tests; the real wiring is exercised by the
    ``HAS_CPU_MULTIPROCESS``-gated drills."""
    import jax

    if decision.process_id is None:
        raise RendezvousError(
            f"cannot re-initialise into epoch {decision.epoch}: this "
            "process is not in the committed world")
    shutdown = jax.distributed.shutdown if shutdown is None else shutdown
    initialize = (jax.distributed.initialize if initialize is None
                  else initialize)
    try:
        shutdown()
    except Exception as e:  # a client wedged on the dead coordinator
        log(f"rendezvous: distributed shutdown raised {e!r} (continuing "
            "into re-init)")
    if decision.num_processes <= 1:
        return
    initialize(coordinator_address=decision.address,
               num_processes=decision.num_processes,
               process_id=decision.process_id)
