"""Pipeline-parallel LM train step over a (data[, seq], pipe[, tensor]) mesh.

Completes the parallelism families (DP/TP/SP/EP elsewhere): GPipe-style
microbatch pipelining of the transformer stack, TPU-native formulation —

  * layer parameters are stacked on a leading layer axis and sharded over
    the ``pipe`` mesh axis, so stage ``s`` physically holds layers
    ``[s*L/S, (s+1)*L/S)`` (embedding / LM head / final norm are replicated;
    only the boundary stages read them);
  * the schedule is a single differentiable loop of ``M + S - 1`` ticks: at
    tick ``t`` stage ``s`` runs its layers on microbatch ``t - s`` and hands
    the activation to its right neighbor with one ``ppermute`` — reverse-mode
    AD transposes the loop into the backward pipeline automatically (the
    transpose of ppermute is the reverse ppermute), so there is no
    hand-written backward schedule;
  * ramp/drain ticks compute on zero activations and are masked out of the
    loss (compute is wasted in the bubble, as in GPipe; fraction
    ``(S-1)/(M+S-1)``);
  * gradient sync (with any compression config) runs over the ``data`` axis
    exactly as in the other steps: stage-local layer gradients sync across
    their data replicas; pipe-replicated leaves (embed/head/norm) are
    psum'd over ``pipe`` by shard_map AD before the compressed data-axis
    sync sees them.

Composability note: this step owns the FULL (data, seq, pipe, tensor)
composition — ``make_pp_mesh(data, pipe, tensor, seq)``: megatron sharding
inside each stage with ``tensor > 1`` (column-parallel qkv/gate/up,
row-parallel wo/w_down, vocab-parallel head/loss, expert-parallel MoE),
ring attention over ``seq`` inside each stage tick with ``seq > 1``
(positions offset per shard, EF workers span data x seq).  The
non-pipelined (data, seq, tensor) step lives in
:mod:`tpu_compressed_dp.train.lm_step`.  The reference had exactly one
axis (SURVEY.md §2.2) — every composition here is net-new capability.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from tpu_compressed_dp import compat
from tpu_compressed_dp.compat import shard_map

from tpu_compressed_dp.models.transformer import (
    LlamaConfig,
    _moe_ffn,
    _psum_if,
    _rms_norm,
    _rope,
    fused_head_xent,
    use_fused_head_xent,
    vocab_parallel_xent,
)
from tpu_compressed_dp.obs import trace as obs_trace
from tpu_compressed_dp.ops.ring_attention import ring_attention
from tpu_compressed_dp.parallel.dp import (
    CompressionConfig,
    make_partitioned_clip,
    make_partitioned_grad_sync,
)
from tpu_compressed_dp.train import guard as guard_mod
from tpu_compressed_dp.train.guard import GuardConfig
from tpu_compressed_dp.train.optim import SGD
from tpu_compressed_dp.train.state import TrainState
from tpu_compressed_dp.train.step import optimizer_lr
from tpu_compressed_dp.utils import chaos as chaos_mod

Array = jax.Array

__all__ = ["make_pp_mesh", "stack_layer_params", "pp_state_specs",
           "make_pp_train_step", "init_pp_ef_state", "place_pp_state"]


def place_pp_state(state: TrainState, cfg: "LlamaConfig",
                   comp: CompressionConfig, mesh: Mesh) -> TrainState:
    """Re-place a (restored) stacked-layer TrainState onto the pipeline
    mesh per ``pp_state_specs`` — checkpoint restore lands every array on one
    device, and the pipelined step needs layer stacks sharded over ``pipe``
    and EF residuals over ``data`` (`train_imagenet_nv.py:193-198` is the
    reference's resume)."""
    return state.place_with_specs(
        pp_state_specs(cfg, comp, tensor=mesh.shape.get("tensor", 1) > 1,
                       seq=mesh.shape.get("seq", 1) > 1),
        mesh)


def make_pp_mesh(data: int, pipe: int, tensor: int = 1, seq: int = 1) -> Mesh:
    from tpu_compressed_dp.parallel.mesh import make_mesh

    sizes, names = [data], ["data"]
    if seq > 1:
        sizes.append(seq)
        names.append("seq")
    sizes.append(pipe)
    names.append("pipe")
    if tensor > 1:
        sizes.append(tensor)
        names.append("tensor")
    return make_mesh(tuple(sizes), tuple(names))


def stack_layer_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """``layers: [ {k: arr} x L ] -> {k: arr[L, ...]}`` so the layer dim can
    shard over the pipe axis.  Requires a homogeneous stack (dense FFN or
    MoE-every-layer)."""
    layers = params["layers"]
    keys = set(layers[0])
    if any(set(l) != keys for l in layers):
        raise ValueError(
            "pipeline stages need homogeneous layers (use moe_every=1 or "
            "a dense FFN config)"
        )
    stacked = {k: jnp.stack([l[k] for l in layers]) for k in sorted(keys)}
    return {**{k: v for k, v in params.items() if k != "layers"},
            "layers": stacked}


def init_pp_ef_state(cfg: LlamaConfig, stacked_params: Dict[str, Any],
                     comp: CompressionConfig, mesh: Mesh) -> Any:
    if not comp.error_feedback:
        return ()
    workers = mesh.shape["data"] * mesh.shape.get("seq", 1)
    return jax.tree.map(
        lambda p: jnp.zeros((workers,) + p.shape, jnp.float32), stacked_params
    )


def pp_state_specs(cfg: LlamaConfig, comp: CompressionConfig,
                   tensor: bool = False, seq: bool = False) -> TrainState:
    """Specs for the stacked-layer state; with ``tensor`` the megatron
    sharding of :func:`transformer.param_specs` composes onto the stacked
    arrays (layer dim over ``pipe``, weight dims over ``tensor``); with
    ``seq`` the EF residual's worker axis spans (data, seq)."""
    if not tensor:
        layer_specs = {k: P("pipe") for k in (
            ["attn_norm", "wq", "wk", "wv", "wo", "mlp_norm",
             "w_gate", "w_up", "w_down"] + (["router"] if cfg.n_experts else [])
        )}
        pspecs = {"embed": P(), "final_norm": P(), "lm_head": P(),
                  "layers": layer_specs}
    else:
        t = "tensor"
        if cfg.n_experts:
            # stacked expert weights: [L, e, ...] — experts over tensor,
            # mirroring param_specs' expert-parallel layout
            ffn = {"router": P("pipe"),
                   "w_gate": P("pipe", t), "w_up": P("pipe", t),
                   "w_down": P("pipe", t)}
        else:
            # column-parallel gate/up, row-parallel down ([L, in, out])
            ffn = {"w_gate": P("pipe", None, t), "w_up": P("pipe", None, t),
                   "w_down": P("pipe", t, None)}
        layer_specs = {
            "attn_norm": P("pipe"), "mlp_norm": P("pipe"),
            "wq": P("pipe", None, t), "wk": P("pipe", None, t),
            "wv": P("pipe", None, t), "wo": P("pipe", t, None),
            **ffn,
        }
        pspecs = {"embed": P(), "final_norm": P(),
                  "lm_head": P(None, t), "layers": layer_specs}
    worker_ax = ("data", "seq") if seq else "data"
    ef_specs = jax.tree.map(lambda s: P(worker_ax, *s), pspecs,
                            is_leaf=lambda x: isinstance(x, P))
    return TrainState(
        step=P(), params=pspecs, batch_stats=P(),
        opt_state={"momentum": pspecs},
        ef=ef_specs if comp.error_feedback else P(),
        rng=P(),
        # compressor state (powersgd warm-start Q): leading worker axis only
        comp=P(worker_ax),
        # step-guard state: replicated (global finiteness vote)
        guard=P(),
        # adaptive-compression control state: replicated, host-mutated only
        control=P(),
    )


def _decoder_layer(cfg: LlamaConfig, lp: Dict[str, Array], h: Array,
                   pos: Array, tensor_axis=None, seq_axis=None) -> Array:
    """One pre-norm decoder layer from unstacked per-layer params (the
    single-device body of apply_llama, factored for reuse by the stages).
    With ``tensor_axis``, qkv/gate/up are column-sharded and wo/w_down
    row-sharded — the same megatron layout as apply_llama, composed with
    the pipe stacking."""
    dt = cfg.dtype
    hd = cfg.head_dim
    x = _rms_norm(h, lp["attn_norm"], cfg.norm_eps)
    b, t = x.shape[:2]
    q = (x @ lp["wq"].astype(dt)).reshape(b, t, -1, hd).transpose(0, 2, 1, 3)
    k = (x @ lp["wk"].astype(dt)).reshape(b, t, -1, hd).transpose(0, 2, 1, 3)
    v = (x @ lp["wv"].astype(dt)).reshape(b, t, -1, hd).transpose(0, 2, 1, 3)
    q, k = _rope(q, pos, cfg.rope_theta), _rope(k, pos, cfg.rope_theta)
    o = ring_attention(q, k, v, axis_name=seq_axis)
    attn = o.transpose(0, 2, 1, 3).reshape(b, t, -1) @ lp["wo"].astype(dt)
    h = h + _psum_if(attn, tensor_axis)
    x = _rms_norm(h, lp["mlp_norm"], cfg.norm_eps)
    if cfg.n_experts:
        mlp, _ = _moe_ffn(cfg, lp, x, tensor_axis)
    else:
        mlp = _psum_if(
            (jax.nn.silu(x @ lp["w_gate"].astype(dt))
             * (x @ lp["w_up"].astype(dt))) @ lp["w_down"].astype(dt),
            tensor_axis)
    return h + mlp


def make_pp_train_step(
    cfg: LlamaConfig,
    optimizer: SGD,
    comp_cfg: CompressionConfig,
    mesh: Mesh,
    *,
    microbatches: int,
    clip_norm: float = 0.0,
    clip_sent_norm: float = 0.0,
    donate: bool = True,
    guard_cfg: Optional[GuardConfig] = None,
    chaos: Optional["chaos_mod.ChaosConfig"] = None,
):
    """Build ``train_step(state, batch) -> (state, metrics)``.

    ``state.params`` must be in stacked form (:func:`stack_layer_params`).
    ``batch['input'|'target']``: [B, T] with ``B`` divisible by
    ``data_size * microbatches`` and ``T`` by the seq axis size.

    ``clip_norm`` / ``clip_sent_norm``: the EF-with-momentum stabilisers
    (see :func:`tpu_compressed_dp.train.step.make_train_step`); norms span
    the full model — pipe-sharded layer stacks psum their squared norms
    over ``pipe``, replicated embed/head/norm leaves count once.

    ``guard_cfg`` / ``chaos``: the step guard and fault injection of
    :func:`tpu_compressed_dp.train.step.make_train_step`.  The finiteness
    vote spans EVERY mesh axis (data[, seq], pipe[, tensor]): a NaN in one
    stage's layer-stack gradient must veto the update on all stages, or the
    pipeline's replicated embed/head params would de-synchronise from the
    stage-local layers.

    ``comp_cfg.sync_overlap > 1`` chunk-pipelines each replication
    signature's data-axis sync (the partitioned wrapper's base engines
    dispatch through :mod:`tpu_compressed_dp.parallel.overlap`); the
    optimizer update stays whole-tree, as in
    :func:`~tpu_compressed_dp.train.lm_step.make_lm_train_step`.
    """
    from tpu_compressed_dp.ops.compressors import canonical_name

    if canonical_name(comp_cfg.method) == "powersgd":
        # stacked-layer params shard over the pipe axis, so warm-start
        # factors would need per-stage shapes no current init can build
        raise NotImplementedError(
            "powersgd is not yet supported with pipeline parallelism; "
            "run it on a (data[, seq]) mesh")
    stages = mesh.shape["pipe"]
    tp = mesh.shape.get("tensor", 1)
    sp = mesh.shape.get("seq", 1)
    tensor_axis = "tensor" if tp > 1 else None
    seq_axis = "seq" if sp > 1 else None
    sync_axes = ("data", "seq") if sp > 1 else ("data",)
    if tp > 1:
        cfg.validate_mesh(tp)
    if cfg.n_layers % stages:
        raise ValueError(f"n_layers ({cfg.n_layers}) must divide by pipe "
                         f"size {stages}")
    if cfg.n_experts and cfg.moe_every != 1:
        raise ValueError("pipeline stages need homogeneous layers: MoE "
                         "configs require moe_every=1")
    layers_per_stage = cfg.n_layers // stages
    M = microbatches
    if M % stages:
        import warnings

        warnings.warn(
            f"microbatches ({M}) not divisible by pipe size ({stages}): the "
            "deferred LM head falls back to every stage heading the full "
            "drained batch — correct, but S x the logits memory and head "
            "FLOPs of the even-split fast path", stacklevel=2)
    # Leaves sync in one group per model-axis replication signature — four
    # at pipe x tensor: fully replicated (embed/final_norm), pipe-sharded
    # tensor-replicated (norm vectors), tensor-sharded pipe-replicated
    # (lm_head), pipe+tensor-sharded (layer weights).  Mixing signatures
    # under one data-dependent compression mask would de-synchronise
    # replicas (see make_partitioned_grad_sync).
    spec_tree = pp_state_specs(cfg, comp_cfg, tensor=tp > 1,
                               seq=sp > 1).params
    spec_leaves = jax.tree.leaves(spec_tree, is_leaf=lambda x: isinstance(x, P))
    model_axes = ("pipe", "tensor") if tp > 1 else ("pipe",)
    leaf_axes = [tuple(a for a in model_axes
                       if any(ax == a for ax in spec))
                 for spec in spec_leaves]
    grad_sync = make_partitioned_grad_sync(comp_cfg, sync_axes, leaf_axes)
    clip_tree = make_partitioned_clip(leaf_axes)
    n_workers = mesh.shape["data"] * sp
    dt = cfg.dtype
    guarded = guard_cfg is not None
    inject = chaos is not None and chaos.injects_in_graph
    if inject and chaos.worker >= n_workers:
        # silently-never-firing injection would fake a passing drill
        raise ValueError(
            f"chaos worker {chaos.worker} out of range for {n_workers} "
            "(data x seq) workers")
    vote_axes = tuple(mesh.axis_names)

    def local_step(state: TrainState, x: Array, y: Array):
        comp_key = jax.random.fold_in(state.rng, state.step)
        ls_scale = (state.guard.loss_scale if guarded
                    else jnp.asarray(1.0, jnp.float32))
        stage = jax.lax.axis_index("pipe")
        b_local, t_len = x.shape
        mb = b_local // M
        xs = x.reshape(M, mb, t_len)
        ys = y.reshape(M, mb, t_len)
        # with a seq axis, t_len is the LOCAL sequence block; positions and
        # attention follow apply_llama's sequence-parallel convention (ring
        # attention over `seq` inside each stage)
        if seq_axis is not None:
            pos = jax.lax.axis_index(seq_axis) * t_len + jnp.arange(t_len)
        else:
            pos = jnp.arange(t_len)
        perm = [(i, (i + 1) % stages) for i in range(stages)]

        def loss_fn(params):
            def stage_apply(h):
                for i in range(layers_per_stage):
                    lp = jax.tree.map(lambda a: a[i], params["layers"])
                    h = _decoder_layer(cfg, lp, h, pos, tensor_axis,
                                       seq_axis)
                return h

            def tick(h_cur, t):
                # stage 0 injects microbatch t (clamped; masked by `inject`)
                inject = (stage == 0) & (t < M)
                x_t = xs[jnp.clip(t, 0, M - 1)]
                emb = params["embed"].astype(dt)[x_t]
                emb = compat.pcast(emb, ("pipe",), to="varying")
                h_in = jnp.where(inject, emb, h_cur)
                h_out = stage_apply(h_in)
                h_next = jax.lax.ppermute(h_out, "pipe", perm)
                return h_next, h_out

            h0 = compat.pcast(jnp.zeros((mb, t_len, cfg.dim), dt),
                               sync_axes + ("pipe",), to="varying")
            _, h_ticks = jax.lax.scan(tick, h0, jnp.arange(M + stages - 1))
            # The final-norm + LM-head + loss are DEFERRED past the loop
            # (VERDICT r2 #6): the last stage emits microbatch j at tick
            # S-1+j, so its drained activations are a STATIC slice of the
            # scan's stacked outputs — no scatter in the loop, no extra
            # carry for AD to checkpoint.  In the tick loop every stage paid
            # the head M+S-1 times (ramp ticks on zero activations
            # included); here the drained activations are psum-broadcast
            # over `pipe` (activations are [*, d] — small next to [*, V]
            # logits) and each stage heads M/S microbatches, so the head
            # costs M/S passes wall-clock and the logits buffer stays S x
            # smaller than a whole-batch head pass.
            emitted = h_ticks[stages - 1:stages - 1 + M]       # [M, mb, T, d]
            emitted = jax.lax.psum(
                jnp.where(stage == stages - 1, emitted,
                          jnp.zeros_like(emitted)), "pipe")
            if M % stages == 0:
                m_s = M // stages
                my_h = jax.lax.dynamic_slice_in_dim(emitted, stage * m_s, m_s)
                my_y = jax.lax.dynamic_slice_in_dim(
                    compat.pcast(ys, ("pipe",), to="varying"),
                    stage * m_s, m_s)
                scale = 1.0 / stages
            else:  # uneven split: every stage heads the full drained set
                m_s, my_h, scale = M, emitted, 1.0 / stages
                my_y = compat.pcast(ys, ("pipe",), to="varying")
            hn = _rms_norm(my_h.reshape(m_s * mb, t_len, cfg.dim),
                           params["final_norm"], cfg.norm_eps)
            if use_fused_head_xent(m_s * mb * t_len, cfg.vocab_size // tp,
                                   jnp.dtype(cfg.dtype).itemsize):
                nll = fused_head_xent(hn, params["lm_head"].astype(dt),
                                      my_y.reshape(m_s * mb, t_len),
                                      tensor_axis)
            else:
                logits = hn @ params["lm_head"].astype(dt)  # [., T, V/tp]
                nll = vocab_parallel_xent(
                    logits, my_y.reshape(m_s * mb, t_len),
                    tensor_axis=tensor_axis)
            # equal chunks: mean of chunk-means == global mean; backprop at
            # loss_scale x (identity unguarded/fp32)
            loss = jax.lax.psum(nll * scale, "pipe")
            return loss * ls_scale

        varying = jax.tree.map(
            lambda p: compat.pcast(p, sync_axes, to="varying"), state.params
        )
        with obs_trace.phase("grad"):
            loss, grads = jax.value_and_grad(loss_fn)(varying)
        loss = loss / ls_scale  # raw loss for metrics/vote (1.0 unguarded)
        if inject:
            loss, grads = chaos_mod.inject(
                chaos, state.step, guard_mod.worker_index(sync_axes), loss,
                grads)
        ok = None
        if guarded:
            # vote over EVERY mesh axis: stage-local layer gradients differ
            # per pipe (and tensor) shard, and all replicas must branch
            # identically
            ok = guard_mod.finite_vote(
                guard_mod.tree_all_finite(loss, grads), vote_axes)
            grads = jax.tree.map(lambda g: g / ls_scale, grads)
        if clip_norm > 0.0:
            grads = clip_tree(grads, clip_norm)

        ef_local = jax.tree.map(lambda e: e[0], state.ef)
        comp_local = jax.tree.map(lambda c: c[0], state.comp)
        synced, new_ef, new_comp, comm = grad_sync(
            grads, ef_local, comp_local, comp_key, ok=ok)
        new_ef = jax.tree.map(lambda e: e[None], new_ef)
        new_comp = jax.tree.map(lambda c: c[None], new_comp)
        if clip_sent_norm > 0.0:
            synced = clip_tree(synced, clip_sent_norm)

        new_step = state.step + 1
        # guard-aware LR rewind: schedules key off the applied-update count
        sched_step = guard_mod.schedule_step(guard_cfg, state.guard, new_step)
        with obs_trace.phase("update"):
            new_params, new_opt = optimizer.apply(state.params, synced,
                                                  state.opt_state, sched_step)
        new_guard = state.guard
        if guarded:
            new_params = guard_mod.select_tree(ok, new_params, state.params)
            new_opt = guard_mod.select_tree(ok, new_opt, state.opt_state)
            new_guard = guard_mod.update_guard(guard_cfg, state.guard, ok,
                                               new_step)
            loss = jnp.where(ok, loss, 0.0)
        metrics = {
            "loss": jax.lax.pmean(loss, sync_axes),
            "tokens": jax.lax.psum(
                jnp.asarray(b_local * t_len, jnp.float32), sync_axes),
            "lr": optimizer_lr(optimizer, sched_step),
        }
        if guarded:
            metrics.update(guard_mod.guard_metrics(new_guard))
        for k, v in comm.items():
            metrics[k if k.startswith("guard/") else f"comm/{k}"] = (
                jax.lax.pmean(v, sync_axes))
        return dataclasses.replace(
            state, step=new_step, params=new_params, opt_state=new_opt,
            ef=new_ef, comp=new_comp, guard=new_guard,
        ), metrics

    state_spec = pp_state_specs(cfg, comp_cfg, tensor=tp > 1, seq=sp > 1)
    data_spec = P("data", "seq") if sp > 1 else P("data")
    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(state_spec, data_spec, data_spec),
        out_specs=(state_spec, P()),
    )
    jitted = partial(jax.jit, donate_argnums=(0,) if donate else ())(
        lambda state, x, y: sharded(state, x, y)
    )

    def train_step(state: TrainState, batch: Dict[str, Array]):
        for leaf in jax.tree.leaves(state.ef):
            if leaf.ndim < 1 or leaf.shape[0] != n_workers:
                raise ValueError(
                    f"PP EF residual needs leading axis {n_workers}; got "
                    f"{leaf.shape} — build with init_pp_ef_state"
                )
        if guarded and state.guard == ():
            raise ValueError(
                "guard_cfg set but state.guard is empty; build it with "
                "init_guard_state(guard_cfg)")
        return jitted(state, batch["input"], batch["target"])

    return train_step
