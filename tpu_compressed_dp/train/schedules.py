"""Learning-rate schedules as pure functions of the step.

Covers both reference schedule systems:
  * ``PiecewiseLinear`` (`CIFAR10/core.py:157-159`) — np.interp over knots.
  * The ImageNet phase mini-DSL (`train_imagenet_nv.py:602-651`): a list of
    ``{'ep': e | (e0, e1), 'lr': v | (v0, v1)}`` dicts, constant or linearly
    interpolated within a phase, at per-batch granularity.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

import jax.numpy as jnp

Array = jnp.ndarray
Schedule = Callable[[Array], Array]

__all__ = [
    "piecewise_linear",
    "phase_lr_schedule",
    "lr_phases_to_knots",
    "epoch_from_steps",
    "phase_lr_schedule_variable_bs",
]


def piecewise_linear(knots: Sequence[float], vals: Sequence[float]) -> Schedule:
    """``PiecewiseLinear(knots, vals)(t)`` = linear interpolation, clamped at the ends.

    ``t`` is in whatever unit the caller chooses (the CIFAR harness uses
    fractional epochs: ``step / batches_per_epoch``, `dawn.py:142`).
    """
    kn = jnp.asarray(knots, jnp.float32)
    vs = jnp.asarray(vals, jnp.float32)

    def schedule(t: Array) -> Array:
        return jnp.interp(jnp.asarray(t, jnp.float32), kn, vs)

    return schedule


def lr_phases_to_knots(phases: List[dict]) -> Tuple[List[float], List[float]]:
    """Flatten ImageNet-style lr phases into (knots, vals) for interpolation.

    A phase ``{'ep': (e0, e1), 'lr': (v0, v1)}`` ramps linearly; ``'ep': e``
    with scalar ``lr`` holds the value until the next phase starts
    (`train_imagenet_nv.py:611-634` semantics).
    """
    knots: List[float] = []
    vals: List[float] = []
    lr_phases = [p for p in phases if "lr" in p]
    for i, p in enumerate(lr_phases):
        ep = p["ep"]
        lr = p["lr"]
        if isinstance(ep, (tuple, list)):
            e0, e1 = float(ep[0]), float(ep[1])
        else:
            e0 = float(ep)
            if i + 1 < len(lr_phases):
                nxt = lr_phases[i + 1]["ep"]
                e1 = float(nxt[0] if isinstance(nxt, (tuple, list)) else nxt)
            else:
                e1 = e0 + 1.0
        if isinstance(lr, (tuple, list)):
            v0, v1 = float(lr[0]), float(lr[1])
        else:
            v0 = v1 = float(lr)
        # Nudge the start knot so back-to-back phases don't share an x value
        # (np.interp would otherwise pick an arbitrary side of the jump).
        if knots and e0 <= knots[-1]:
            e0 = knots[-1] + 1e-6
        knots += [e0, e1]
        vals += [v0, v1]
    return knots, vals


def phase_lr_schedule(phases: List[dict], batches_per_epoch: int) -> Schedule:
    """Per-batch LR from ImageNet phase dicts; input is the global step."""
    knots, vals = lr_phases_to_knots(phases)
    base = piecewise_linear(knots, vals)

    def schedule(step: Array) -> Array:
        return base(jnp.asarray(step, jnp.float32) / float(batches_per_epoch))

    return schedule


def epoch_from_steps(epoch_batches: Sequence[int]) -> Schedule:
    """Map a global step to a fractional epoch when batches-per-epoch varies.

    Progressive resizing changes the batch size mid-run
    (`train.py:60-72`: bs 512 -> 224 -> 128), so epoch ``e`` spans
    ``epoch_batches[e]`` steps; the reference's ``Scheduler`` got fractional
    epochs from ``(epoch, batch_num, batch_tot)`` at call time
    (`train_imagenet_nv.py:640-645`) — here the same piecewise-affine map is
    traced into the jitted step.
    """
    cum = [0.0]
    for n in epoch_batches:
        cum.append(cum[-1] + float(max(n, 1)))
    epochs = [float(e) for e in range(len(cum))]
    return piecewise_linear(cum, epochs)


def phase_lr_schedule_variable_bs(phases: List[dict], epoch_batches: Sequence[int]) -> Schedule:
    """Phase LR under progressive resizing: ``lr(step) = lr_by_epoch(epoch(step))``."""
    knots, vals = lr_phases_to_knots(phases)
    by_epoch = piecewise_linear(knots, vals)
    to_epoch = epoch_from_steps(epoch_batches)

    def schedule(step: Array) -> Array:
        return by_epoch(to_epoch(jnp.asarray(step, jnp.float32)))

    return schedule
