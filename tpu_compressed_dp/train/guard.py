"""In-graph step guard: cross-worker finiteness vote + dynamic loss scaling.

The stack carries persistent cross-step state on every worker — the
error-feedback residual (``TrainState.ef``) and PowerSGD's warm-started
factors (``TrainState.comp``) — so a single nonfinite gradient does not just
ruin one step: once absorbed, the poison replays out of the residual forever.
The reference had nothing here at all; `utils/resilience.py` gives the
host-level half (crash -> restore -> replay) and this module gives the
in-graph half:

  * every step, each worker reduces ``isfinite`` over its loss and local
    gradients and the workers take a **vote** (one ``psum`` of the nonfinite
    counts over the sync axes).  The psum is symmetric, so every worker —
    including ones whose own gradients were clean — computes the identical
    verdict and takes the identical branch; there is no rank-0 broadcast to
    race;
  * on a bad step the update is **skipped**: params, optimizer buffers,
    batch stats, EF residual and compressor state are all held bitwise at
    their pre-step values (the sync engines gate EF/comp internally, see
    ``parallel/dp.py``), and the **dynamic loss scale** backs off;
  * on good steps the scale regrows after ``growth_interval`` consecutive
    successes — the standard fp16 dynamic-loss-scaling protocol
    (`fp16util.py`'s static ``loss_scale=1024`` is the reference's whole
    story; bf16 rarely overflows but underflows the same 8-bit exponent as
    fp32 never would at half precision, so the fp16/bf16 paths get the full
    dynamic protocol and fp32 gets the identity scale);
  * the consecutive-skip streak lives in :class:`GuardState` (checkpointed
    with everything else); past ``max_consecutive_skips`` the host raises
    :class:`GuardExceeded` — a wedged run (e.g. corrupted data shard feeding
    NaNs every step) fails loudly into ``run_with_recovery`` instead of
    silently burning its epoch budget skipping.

Everything here runs *inside* the jitted step except
:func:`check_guard_metrics` (a host-side assertion over fetched metrics;
raising is impossible inside jit without checkify's overhead on every step).

Fault-injection counterpart: :mod:`tpu_compressed_dp.utils.chaos`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence, Union

import jax
import jax.numpy as jnp
from flax import struct

Array = jax.Array

__all__ = [
    "GuardConfig", "GuardState", "GuardExceeded", "init_guard_state",
    "tree_all_finite", "finite_vote", "select_tree", "update_guard",
    "guard_metrics", "check_guard_metrics", "worker_index",
    "guard_to_dict", "guard_from_dict", "schedule_step",
]


class GuardExceeded(RuntimeError):
    """Raised (host-side) when the consecutive-skip streak passes
    ``GuardConfig.max_consecutive_skips`` — the run is wedged, not unlucky."""


@dataclasses.dataclass(frozen=True)
class GuardConfig:
    """Step-guard knobs.

    init_scale:            starting loss scale (active only with
                           ``loss_scaling``; the classic fp16 default is a
                           large power of two so backoff finds the usable
                           range fast)
    backoff:               multiplier on a nonfinite step (0.5 = halve)
    growth:                multiplier after ``growth_interval`` good steps
    growth_interval:       consecutive good steps before the scale regrows
    max_consecutive_skips: host-side raise threshold (strictly-greater-than);
                           see :func:`check_guard_metrics`
    loss_scaling:          False pins the scale to 1.0 (the fp32 identity
                           path — the vote/skip machinery still runs)
    """

    init_scale: float = 2.0 ** 15
    backoff: float = 0.5
    growth: float = 2.0
    growth_interval: int = 200
    max_consecutive_skips: int = 25
    loss_scaling: bool = True
    # Guard-aware LR rewind (ROADMAP): schedule-valued hyper-parameters key
    # off the APPLIED-update count (step - total_skipped) instead of the raw
    # attempt counter, so N vetoed steps leave the LR exactly where an
    # unskipped run of the same good-step count would — a burst of skips no
    # longer fast-forwards warmup/anneal.  Constant hyper-parameters are
    # unaffected; the raw step still drives RNG streams and checkpointing.
    lr_rewind: bool = True

    def __post_init__(self):
        if not (0.0 < self.backoff < 1.0):
            raise ValueError(f"backoff must be in (0, 1), got {self.backoff}")
        if self.growth <= 1.0:
            raise ValueError(f"growth must be > 1, got {self.growth}")
        if self.growth_interval < 1:
            raise ValueError(
                f"growth_interval must be >= 1, got {self.growth_interval}")
        if self.init_scale < 1.0:
            raise ValueError(
                f"init_scale must be >= 1, got {self.init_scale} "
                "(the scale is clamped to >= 1 by backoff anyway)")
        if self.max_consecutive_skips < 1:
            raise ValueError(
                f"max_consecutive_skips must be >= 1, got "
                f"{self.max_consecutive_skips}")

    @classmethod
    def for_dtype(cls, dtype, **kw) -> "GuardConfig":
        """Loss scaling active on the 16-bit float paths, identity on fp32
        (a pow-2 scale would be exact there anyway; identity keeps the fp32
        guarded step equal to the unguarded one up to psum reduction order
        — the guarded program compiles separately, so XLA may pick a
        different all-reduce tree)."""
        dt = jnp.dtype(dtype)
        scaling = jnp.issubdtype(dt, jnp.floating) and dt.itemsize <= 2
        return cls(loss_scaling=kw.pop("loss_scaling", scaling), **kw)


@struct.dataclass
class GuardState:
    """The guard's cross-step carry, one more ``TrainState`` occupant: it is
    replicated (the vote makes every field identical on every worker),
    round-trips Orbax (``utils/checkpoint.py``) and therefore replays
    bit-identically through ``run_with_recovery``."""

    loss_scale: Array       # f32 scalar, >= 1.0
    good_steps: Array       # i32 consecutive good steps since last scale event
    skips: Array            # i32 CONSECUTIVE skipped steps (streak)
    total_skipped: Array    # i32 total skipped steps (monotone)
    last_good_step: Array   # i32 step counter after the last applied update


def init_guard_state(cfg: Optional[GuardConfig]) -> Any:
    """Fresh :class:`GuardState` (``()`` when the guard is off, mirroring
    ``ef``/``comp``).

    Each field gets its OWN zero array: sharing one ``jnp.asarray(0)``
    across fields aliases their device buffers, and a donating jitted step
    (``donate=True``, the harness default) then fails with "attempt to
    donate the same buffer twice".
    """
    if cfg is None:
        return ()
    scale = cfg.init_scale if cfg.loss_scaling else 1.0
    return GuardState(
        loss_scale=jnp.asarray(scale, jnp.float32),
        good_steps=jnp.zeros((), jnp.int32),
        skips=jnp.zeros((), jnp.int32),
        total_skipped=jnp.zeros((), jnp.int32),
        last_good_step=jnp.zeros((), jnp.int32),
    )


def guard_to_dict(gs: GuardState) -> Dict[str, Array]:
    """Plain-dict form for Orbax (a vanilla nested dict needs no pytree
    registration agreement between the writing and reading process)."""
    return {f.name: getattr(gs, f.name) for f in dataclasses.fields(gs)}


def guard_from_dict(d: Dict[str, Any]) -> GuardState:
    return GuardState(
        loss_scale=jnp.asarray(d["loss_scale"], jnp.float32),
        good_steps=jnp.asarray(d["good_steps"], jnp.int32),
        skips=jnp.asarray(d["skips"], jnp.int32),
        total_skipped=jnp.asarray(d["total_skipped"], jnp.int32),
        last_good_step=jnp.asarray(d["last_good_step"], jnp.int32),
    )


def tree_all_finite(*trees: Any) -> Array:
    """Scalar bool: every float leaf of every tree is finite.  Integer
    leaves are skipped (isfinite is vacuous there)."""
    ok = jnp.asarray(True)
    for tree in trees:
        for leaf in jax.tree.leaves(tree):
            if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.inexact):
                ok = ok & jnp.all(jnp.isfinite(leaf))
    return ok


def finite_vote(local_ok: Array, axis_names: Union[str, Sequence[str]]) -> Array:
    """Cross-worker vote: globally ok iff EVERY worker's local verdict is ok.

    One psum of the nonfinite counts over ``axis_names`` — symmetric, so the
    result (and hence the skip branch) is identical on every participant; a
    single poisoned worker vetoes the whole update."""
    bad = (~local_ok).astype(jnp.int32)
    return jax.lax.psum(bad, axis_names) == 0


def worker_index(axis_names: Union[str, Sequence[str]]) -> Array:
    """Linearised worker index over one or more mesh axes (row-major in the
    given order) — the coordinate chaos injection targets."""
    if isinstance(axis_names, str):
        return jax.lax.axis_index(axis_names)
    idx = jnp.asarray(0, jnp.int32)
    for ax in axis_names:
        idx = idx * jax.lax.psum(1, ax) + jax.lax.axis_index(ax)
    return idx


def schedule_step(cfg: Optional[GuardConfig], gs: Any, new_step: Array) -> Array:
    """The step value schedule-valued hyper-parameters (LR, momentum, wd)
    should be evaluated at — the guard-aware LR rewind
    (``GuardConfig.lr_rewind``).

    ``new_step - total_skipped`` counts APPLIED updates: a vetoed step
    advances the raw attempt counter (RNG stream, checkpoint naming) but
    not the schedule clock, so after N skips the LR sits exactly where an
    unskipped run of the same good-step count would put it.  On a vetoed
    step the computed update is discarded anyway, so the (one-behind) value
    it sees is irrelevant.  ``gs`` is the PRE-step :class:`GuardState`
    (``state.guard``); pass-through when the guard is off or rewind is
    disabled.
    """
    if cfg is None or not cfg.lr_rewind or gs == ():
        return new_step
    return new_step - gs.total_skipped


def select_tree(ok: Array, new: Any, old: Any) -> Any:
    """Per-leaf ``where(ok, new, old)``; the held branch is the *input* leaf
    itself so a skipped step is bitwise the pre-step state."""
    return jax.tree.map(lambda n, o: jnp.where(ok, n, o), new, old)


def update_guard(cfg: GuardConfig, gs: GuardState, ok: Array,
                 new_step: Array) -> GuardState:
    """One transition of the guard state machine.

    good: streak resets, good_steps advances, the scale grows ``x growth``
    every ``growth_interval`` consecutive good steps.  bad: streak and total
    advance, good_steps resets, the scale backs off (clamped to >= 1 — below
    1 the "scale" would start destroying fp32 mantissa instead of protecting
    half-precision exponent).  With ``loss_scaling`` off the scale is pinned.
    """
    good = jnp.where(ok, gs.good_steps + 1, 0)
    if cfg.loss_scaling:
        grow = good >= cfg.growth_interval
        scale = jnp.where(
            ok,
            jnp.where(grow, gs.loss_scale * cfg.growth, gs.loss_scale),
            jnp.maximum(gs.loss_scale * cfg.backoff, 1.0),
        )
        good = jnp.where(grow, 0, good)
    else:
        scale = gs.loss_scale
    return GuardState(
        loss_scale=scale,
        good_steps=good.astype(jnp.int32),
        skips=jnp.where(ok, 0, gs.skips + 1).astype(jnp.int32),
        total_skipped=(gs.total_skipped + (~ok).astype(jnp.int32)),
        last_good_step=jnp.where(ok, new_step,
                                 gs.last_good_step).astype(jnp.int32),
    )


def guard_metrics(gs: GuardState) -> Dict[str, Array]:
    """The post-update guard scalars for the step's metrics dict (all
    replicated — the vote made every field identical across workers).
    ``guard/nonfinite`` itself is reported by the sync engines
    (``parallel/dp.py``), which own the EF/comp hold."""
    f32 = jnp.float32
    return {
        "guard/loss_scale": gs.loss_scale.astype(f32),
        "guard/skipped": gs.total_skipped.astype(f32),
        "guard/skip_streak": gs.skips.astype(f32),
        "guard/last_good_step": gs.last_good_step.astype(f32),
    }


def check_guard_metrics(metrics: Dict[str, Any],
                        cfg: GuardConfig, *, flight=None) -> None:
    """Host-side wedge detector: raise :class:`GuardExceeded` when the
    consecutive-skip streak has passed ``max_consecutive_skips``.

    Called on *fetched* metrics (after ``device_get``), so detection latency
    is whatever cadence the caller observes metrics at — per epoch in the
    CNN harnesses (``harness/loop.py``), per ``--log_every`` in the LM
    harness.  Raising inside the jitted step would need checkify's
    every-step overhead; a wedged run burning one extra epoch of skips is
    the cheaper failure mode, and the raise still lands inside
    ``run_with_recovery``'s retry loop.

    ``flight`` (a :class:`~tpu_compressed_dp.obs.flight.FlightRecorder`)
    dumps this rank's blackbox bundle before the raise — the wedge
    evidence (the guard ring's streak history, the chaos arm) would
    otherwise die with the process.
    """
    streak = metrics.get("guard/skip_streak")
    if streak is None:
        return
    if float(streak) > cfg.max_consecutive_skips:
        err = GuardExceeded(
            f"step guard: {int(float(streak))} consecutive nonfinite steps "
            f"(> max_consecutive_skips={cfg.max_consecutive_skips}); "
            f"loss_scale={float(metrics.get('guard/loss_scale', -1.0)):g}, "
            f"last_good_step={int(float(metrics.get('guard/last_good_step', -1)))}"
        )
        if flight is not None:
            flight.observe(
                err,
                step=int(float(metrics.get("guard/last_good_step", -1))))
        raise err
