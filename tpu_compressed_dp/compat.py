"""JAX version-compatibility shims (shard_map, VMA typing, pcast).

The framework targets the modern ``jax.shard_map`` API with varying-manual-axes
(VMA) typing: replicated inputs are explicitly ``jax.lax.pcast``-ed to
device-varying before differentiation so the per-worker local gradient — not an
auto-psummed mean — reaches the compression engine, and Pallas kernels annotate
``vma=`` on their out shapes so carries typecheck under ``shard_map``.

On older releases (``jax < 0.6``, where ``shard_map`` still lives in
``jax.experimental`` and VMA typing does not exist) the same semantics are
recovered with the replication-checking rewrite DISABLED (``check_rep=False``):
without the rewrite machinery, AD inside ``shard_map`` yields the local
per-worker gradient for replicated inputs — exactly what the explicit
pcast-to-varying buys on new JAX — and ``pcast``/``vma=`` degrade to no-ops.

Every module in this package imports ``shard_map``/``pcast``/``typeof``/
``shape_dtype_struct`` from here instead of from ``jax`` directly; this is the
single place version detection happens.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

__all__ = ["shard_map", "pcast", "typeof", "shape_dtype_struct",
           "pallas_compiler_params", "pallas_interpret_params",
           "HAS_NATIVE_SHARD_MAP", "HAS_VMA", "HAS_TPU_INTERPRET",
           "HAS_CPU_MULTIPROCESS"]

# jax >= 0.6: shard_map is a top-level export with `check_vma` semantics.
HAS_NATIVE_SHARD_MAP = hasattr(jax, "shard_map")
# VMA typing (jax.typeof(...).vma, lax.pcast, ShapeDtypeStruct(vma=...))
# arrived with the new shard_map; detect each piece independently so partial
# backports keep working.
HAS_VMA = hasattr(jax.lax, "pcast")

def _version_tuple() -> tuple:
    import re

    # keep each component's leading digits so rc/dev suffixes ("0.6.0rc1")
    # parse as (0, 6, 0) instead of collapsing to an all-zero version
    parts = []
    for x in jax.__version__.split(".")[:3]:
        m = re.match(r"\d+", x)
        parts.append(int(m.group()) if m else 0)
    return tuple(parts)


# Cross-process collectives on the CPU backend (the gloo-backed path the
# 2-process rendezvous tools exercise): 0.4.x raises "Multiprocess
# computations aren't implemented on the CPU backend".
HAS_CPU_MULTIPROCESS = _version_tuple() >= (0, 5, 0)

# TPU-semantics Pallas interpreter (pltpu.InterpretParams): required to
# interpret kernels that draw from the hardware PRNG — the stock HLO
# interpreter on old releases has no prng_seed/prng_random_bits lowering.
try:
    from jax.experimental.pallas import tpu as _pltpu

    HAS_TPU_INTERPRET = hasattr(_pltpu, "InterpretParams")
except ImportError:  # pragma: no cover
    HAS_TPU_INTERPRET = False

if not HAS_NATIVE_SHARD_MAP:
    from jax.experimental.shard_map import shard_map as _experimental_shard_map


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: Optional[bool] = None,
              **kwargs):
    """``jax.shard_map`` when available, else the ``jax.experimental`` one.

    ``check_vma`` maps to the old API's ``check_rep``.  When the caller does
    not pass it, old JAX defaults to ``check_rep=False``: the rep-checking
    rewrite would auto-psum gradients of replicated inputs, defeating the
    compress-before-reduce design (new JAX expresses the same intent with
    ``pcast(..., to='varying')``, which is an identity here).
    """
    if HAS_NATIVE_SHARD_MAP:
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)
    return _experimental_shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=bool(check_vma) if check_vma is not None else False,
        **kwargs)


def pcast(x: Any, axis_name, *, to: str = "varying") -> Any:
    """``jax.lax.pcast`` under VMA typing; identity where VMA does not exist
    (old shard_map with ``check_rep=False`` already treats every value as
    potentially device-varying, so there is nothing to mark)."""
    if HAS_VMA:
        return jax.lax.pcast(x, axis_name, to=to)
    return x


def typeof(x: Any):
    """``jax.typeof`` when available, else the abstract value.  Callers read
    ``getattr(typeof(x), 'vma', frozenset())``, which degrades to the empty
    set (no VMA tracking) on old JAX."""
    if hasattr(jax, "typeof"):
        return jax.typeof(x)
    return jax.core.get_aval(x)


def shape_dtype_struct(shape, dtype, *, vma=frozenset()) -> jax.ShapeDtypeStruct:
    """``jax.ShapeDtypeStruct`` carrying a ``vma`` annotation where supported;
    the annotation is dropped on old JAX (no VMA typing to satisfy)."""
    if HAS_VMA:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def pallas_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` (new name) or ``pltpu.TPUCompilerParams``
    (old), dropping any field the installed release does not know."""
    import dataclasses

    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    known = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in kwargs.items() if k in known})


def pallas_interpret_params():
    """The TPU-semantics Pallas interpreter (``pltpu.InterpretParams``) where
    it exists; plain ``interpret=True`` (the stock HLO interpreter) on older
    releases — whose hardware-PRNG ops are a zero stub, which the quantizer
    kernel tests account for."""
    try:
        from jax.experimental.pallas import tpu as pltpu

        return pltpu.InterpretParams()
    except (ImportError, AttributeError):
        return True
