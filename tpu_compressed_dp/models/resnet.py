"""ImageNet ResNet-18/34/50/101/152 (`IMAGENET/training/resnet.py`).

Standard torchvision-era architecture (BasicBlock `resnet.py:24-56`,
Bottleneck `:59-92`, ResNet `:95-151`) in NHWC flax.  ``bn0=True`` reproduces
``init_dist_weights`` (`resnet.py:154-160` / ``--init-bn0``,
`train_imagenet_nv.py:168`): the *last* BatchNorm of every residual block is
gamma-zero-initialised and the final FC uses normal(0, 0.01) weights — the
large-batch trick that makes each block start as identity.

``dtype=jnp.bfloat16`` is the TPU-native answer to the reference's fp16
machinery (`fp16util.py`: ``network_to_half`` + fp32 master params + static
loss scale 1024, `train_imagenet_nv.py:61`): flax keeps ``param_dtype=float32``
(the master copy — gradients and updates are fp32 automatically) while the
compute graph runs in bf16 on the MXU.  bf16's fp32-sized exponent removes the
need for loss scaling.
"""

from __future__ import annotations

from typing import Any, Sequence, Type

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["ResNet", "resnet18", "resnet34", "resnet50", "resnet101", "resnet152"]

_conv_init = nn.initializers.variance_scaling(2.0, "fan_out", "truncated_normal")
_fc_bn0_init = nn.initializers.normal(0.01)


def _bn(train: bool, name: str, zero_init: bool = False, dtype: Any = jnp.float32):
    return nn.BatchNorm(
        use_running_average=not train,
        momentum=0.9,
        epsilon=1e-5,
        scale_init=nn.initializers.zeros if zero_init else nn.initializers.ones,
        dtype=dtype,
        name=name,
    )


def _conv(features: int, kernel: int, stride: int = 1, name: str = None,
          dtype: Any = jnp.float32):
    return nn.Conv(
        features,
        (kernel, kernel),
        strides=(stride, stride),
        padding=kernel // 2,
        use_bias=False,
        kernel_init=_conv_init,
        dtype=dtype,
        name=name,
    )


class BasicBlock(nn.Module):
    features: int
    stride: int = 1
    downsample: bool = False
    bn0: bool = False
    dtype: Any = jnp.float32
    expansion = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        identity = x
        out = _conv(self.features, 3, self.stride, name="conv1", dtype=self.dtype)(x)
        out = _bn(train, "bn1", dtype=self.dtype)(out)
        out = nn.relu(out)
        out = _conv(self.features, 3, name="conv2", dtype=self.dtype)(out)
        out = _bn(train, "bn2", zero_init=self.bn0, dtype=self.dtype)(out)
        if self.downsample:
            identity = _conv(self.features, 1, self.stride, name="ds_conv", dtype=self.dtype)(x)
            identity = _bn(train, "ds_bn", dtype=self.dtype)(identity)
        return nn.relu(out + identity)


class Bottleneck(nn.Module):
    features: int
    stride: int = 1
    downsample: bool = False
    bn0: bool = False
    dtype: Any = jnp.float32
    expansion = 4

    @nn.compact
    def __call__(self, x, train: bool = False):
        identity = x
        out = _conv(self.features, 1, name="conv1", dtype=self.dtype)(x)
        out = _bn(train, "bn1", dtype=self.dtype)(out)
        out = nn.relu(out)
        out = _conv(self.features, 3, self.stride, name="conv2", dtype=self.dtype)(out)
        out = _bn(train, "bn2", dtype=self.dtype)(out)
        out = nn.relu(out)
        out = _conv(self.features * 4, 1, name="conv3", dtype=self.dtype)(out)
        out = _bn(train, "bn3", zero_init=self.bn0, dtype=self.dtype)(out)
        if self.downsample:
            identity = _conv(self.features * 4, 1, self.stride, name="ds_conv", dtype=self.dtype)(x)
            identity = _bn(train, "ds_bn", dtype=self.dtype)(identity)
        return nn.relu(out + identity)


class ResNet(nn.Module):
    block: Type[nn.Module]
    layers: Sequence[int]
    num_classes: int = 1000
    bn0: bool = False
    dtype: Any = jnp.float32
    width: int = 64

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = _conv(self.width, 7, 2, name="conv1", dtype=self.dtype)(x)
        x = _bn(train, "bn1", dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        features = self.width
        in_features = self.width
        for stage, blocks in enumerate(self.layers):
            stride = 1 if stage == 0 else 2
            for b in range(blocks):
                downsample = b == 0 and (
                    stride != 1 or in_features != features * self.block.expansion
                )
                x = self.block(
                    features,
                    stride=stride if b == 0 else 1,
                    downsample=downsample,
                    bn0=self.bn0,
                    dtype=self.dtype,
                    name=f"layer{stage + 1}_{b}",
                )(x, train)
                in_features = features * self.block.expansion
            features *= 2
        x = x.mean(axis=(1, 2))  # global average pool (`resnet.py:117`)
        return nn.Dense(
            self.num_classes,
            kernel_init=_fc_bn0_init if self.bn0 else nn.initializers.lecun_normal(),
            dtype=self.dtype,
            name="fc",
        )(x).astype(jnp.float32)


def resnet18(num_classes=1000, bn0=False, dtype=jnp.float32, width=64):
    return ResNet(BasicBlock, (2, 2, 2, 2), num_classes, bn0, dtype, width)


def resnet34(num_classes=1000, bn0=False, dtype=jnp.float32, width=64):
    return ResNet(BasicBlock, (3, 4, 6, 3), num_classes, bn0, dtype, width)


def resnet50(num_classes=1000, bn0=False, dtype=jnp.float32, width=64):
    """`resnet.py:187-196` — the ImageNet harness's flagship model."""
    return ResNet(Bottleneck, (3, 4, 6, 3), num_classes, bn0, dtype, width)


def resnet101(num_classes=1000, bn0=False, dtype=jnp.float32, width=64):
    return ResNet(Bottleneck, (3, 4, 23, 3), num_classes, bn0, dtype, width)


def resnet152(num_classes=1000, bn0=False, dtype=jnp.float32, width=64):
    return ResNet(Bottleneck, (3, 8, 36, 3), num_classes, bn0, dtype, width)
