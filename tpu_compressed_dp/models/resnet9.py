"""DAWNBench CIFAR-10 models from the graph-spec family.

Re-designs of the reference's nested-dict graph networks
(`CIFAR10/dawn.py:23-82` + the `build_graph`/`Network` interpreter,
`core.py:136-141`, `torch_backend.py:107-118`) as plain flax modules: the
DAG-with-cache interpreter is exactly what a pure jitted function is, so no
graph runtime survives the port (SURVEY.md §3.5).  Layout is NHWC (TPU
native) rather than the reference's NCHW.

Architecture parity:
  * ``ResNet9``  = `resnet9()` (`dawn.py:70-77`): prep conv_bn(64);
    layer1 conv_bn(128)+pool+residual; layer2 conv_bn(256)+pool;
    layer3 conv_bn(512)+pool+residual; maxpool4; linear(10, no bias);
    logits scaled by 0.125 (`Mul(weight)`, `dawn.py:54`).
  * ``AlexNetGraph`` = `alexnet()` (`dawn.py:57-68,79-82`).
Both support the reference's knobs: channel dict, classifier weight, extra
layers, residual placement, and BN init options (`torch_backend.py:92-103`).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["ConvBN", "Residual", "ResNet9", "AlexNetGraph"]

BN_MOMENTUM = 0.9  # EMA decay == 1 - torch's BatchNorm momentum of 0.1
BN_EPS = 1e-5


class ConvBN(nn.Module):
    """conv3x3(no bias) + BatchNorm + ReLU (`dawn.py:23-28`).

    ``bn_weight_init``/``bn_bias_init`` mirror `batch_norm()` options
    (`torch_backend.py:92-103`); freezing is handled at the optimizer level.
    """

    features: int
    stride: int = 1
    bn_weight_init: float = 1.0
    bn_bias_init: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(
            self.features,
            (3, 3),
            strides=(self.stride, self.stride),
            padding=1,
            use_bias=False,
            dtype=self.dtype,
            name="conv",
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=BN_MOMENTUM,
            epsilon=BN_EPS,
            scale_init=nn.initializers.constant(self.bn_weight_init),
            bias_init=nn.initializers.constant(self.bn_bias_init),
            dtype=self.dtype,
            name="bn",
        )(x)
        return nn.relu(x)


class Residual(nn.Module):
    """x + conv_bn(conv_bn(x)) (`dawn.py:37-43`)."""

    features: int
    bn_weight_init: float = 1.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = ConvBN(self.features, bn_weight_init=self.bn_weight_init,
                   dtype=self.dtype, name="res1")(x, train)
        y = ConvBN(self.features, bn_weight_init=self.bn_weight_init,
                   dtype=self.dtype, name="res2")(y, train)
        return x + y


def _maxpool(x, window: int):
    return nn.max_pool(x, (window, window), strides=(window, window))


class ResNet9(nn.Module):
    """The 94%-in-79s DAWNBench net (`dawn.py:70-77`, `BASELINE.md`)."""

    num_classes: int = 10
    channels: Optional[Dict[str, int]] = None
    classifier_weight: float = 0.125
    res_layers: Sequence[str] = ("layer1", "layer3")
    extra_layers: Sequence[str] = ()
    bn_weight_init: float = 1.0
    # bf16 compute / fp32 params, like models/resnet.py: flax keeps
    # param_dtype=float32 masters, the MXU sees bf16 activations; logits are
    # cast back to fp32 below so the loss/softmax run full-precision.
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        ch = self.channels or {"prep": 64, "layer1": 128, "layer2": 256, "layer3": 512}
        x = x.astype(self.dtype)
        x = ConvBN(ch["prep"], bn_weight_init=self.bn_weight_init,
                   dtype=self.dtype, name="prep")(x, train)
        for layer in ("layer1", "layer2", "layer3"):
            x = ConvBN(ch[layer], bn_weight_init=self.bn_weight_init,
                       dtype=self.dtype, name=layer)(x, train)
            x = _maxpool(x, 2)
            if layer in self.extra_layers:
                x = ConvBN(ch[layer], bn_weight_init=self.bn_weight_init,
                           dtype=self.dtype, name=f"{layer}_extra")(
                    x, train
                )
            if layer in self.res_layers:
                x = Residual(ch[layer], bn_weight_init=self.bn_weight_init,
                             dtype=self.dtype, name=f"{layer}_residual")(
                    x, train
                )
        x = _maxpool(x, 4)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.num_classes, use_bias=False, dtype=self.dtype,
                     name="linear")(x)
        return (x * self.classifier_weight).astype(jnp.float32)


class AlexNetGraph(nn.Module):
    """The graph-spec AlexNet variant (`dawn.py:57-68,79-82`)."""

    num_classes: int = 10
    channels: Optional[Dict[str, int]] = None
    classifier_weight: float = 0.125
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        ch = self.channels or {"prep": 64, "layer1": 192, "layer2": 384, "layer3": 256, "layer4": 256}
        x = x.astype(self.dtype)
        x = ConvBN(ch["prep"], stride=2, dtype=self.dtype, name="prep")(x, train)
        x = _maxpool(x, 2)
        x = ConvBN(ch["layer1"], dtype=self.dtype, name="layer1")(x, train)
        x = _maxpool(x, 2)
        x = ConvBN(ch["layer2"], dtype=self.dtype, name="layer2")(x, train)
        x = ConvBN(ch["layer3"], dtype=self.dtype, name="layer3")(x, train)
        x = ConvBN(ch["layer4"], dtype=self.dtype, name="layer4")(x, train)
        x = _maxpool(x, 2)
        x = _maxpool(x, 2)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.num_classes, use_bias=False, dtype=self.dtype,
                     name="linear")(x)
        return (x * self.classifier_weight).astype(jnp.float32)
