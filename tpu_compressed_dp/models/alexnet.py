"""CIFAR-sized AlexNet, module flavour (`CIFAR10/alexnet.py:11-57`).

The reference computes loss/correct inside ``forward`` and returns a dict;
here the module returns logits and the train step owns the loss — same
capability, standard JAX factoring.  Dropout positions and the 256*2*2
flatten match the reference exactly (input 32x32 -> features 2x2x256).
"""

from __future__ import annotations

import flax.linen as nn

__all__ = ["AlexNet"]


class AlexNet(nn.Module):
    num_classes: int = 10
    dropout_rate: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = False):
        # features (`alexnet.py:14-28`)
        x = nn.Conv(64, (3, 3), strides=(2, 2), padding=1, name="conv1")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(192, (3, 3), padding=1, name="conv2")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(384, (3, 3), padding=1, name="conv3")(x)
        x = nn.relu(x)
        x = nn.Conv(256, (3, 3), padding=1, name="conv4")(x)
        x = nn.relu(x)
        x = nn.Conv(256, (3, 3), padding=1, name="conv5")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        # classifier (`alexnet.py:29-37`)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, name="fc1")(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, name="fc2")(x))
        return nn.Dense(self.num_classes, name="fc3")(x)
