"""Graph-spec network builder: nested-dict DAG -> executable flax module.

The reference builds its DAWNBench nets from nested dict specs interpreted at
runtime: ``path_iter`` flattens nested dicts to '/'-joined paths,
``build_graph`` wires each node to the previous one unless an explicit
(`RelativePath`) edge is given (`CIFAR10/core.py:123-141`), and
``Network.forward`` walks the DAG caching every node output in a dict
(`torch_backend.py:107-118`) — with ``loss``/``correct`` as ordinary graph
nodes (`dawn.py:84-87`).

TPU-native re-design: the spec is still a nested dict (same ergonomics, same
default-sequential + explicit-edge wiring), but it compiles to ONE flax
module traced once under jit — the interpreter loop exists only at trace
time, so XLA sees a flat fused graph, not a Python walk per step.  Loss
stays out of the graph (the train step owns it; `train/step.py`), and the
node vocabulary (`Identity``/``Mul``/``Flatten``/``Add``/``Concat``,
`torch_backend.py:69-90`) is plain callables on arrays.

Spec format::

    spec = {
        "prep": ConvBN(64),
        "layer1": {"conv": ConvBN(128), "pool": MaxPool(2)},
        "join": (Add(), ["prep", "layer1/pool"]),   # explicit inputs
        "logits": Mul(0.125),
    }
    net = GraphNet(spec)        # net(x) -> last node's output
    GraphNet(spec, outputs=("logits", "layer1/pool"))  # -> dict of outputs

Node values: a flax module or any callable taking ``(x, train=...)`` or
``(x)``; a tuple ``(node, [input paths])`` for explicit edges; or a nested
dict.  Paths are '/'-joined; relative references may use ``../`` (resolved
against the node's own directory, the ``RelativePath`` equivalent).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

__all__ = [
    "GraphNet", "build_graph", "path_iter",
    "Identity", "Mul", "Flatten", "Add", "Concat", "MaxPool", "Cast",
    "resnet9_spec", "alexnet_spec",
]


# ---------------------------------------------------------------------------
# node vocabulary (`torch_backend.py:69-90`)
# ---------------------------------------------------------------------------


class Identity:
    def __call__(self, x):
        return x


@dataclasses.dataclass
class Mul:
    weight: float

    def __call__(self, x):
        return x * self.weight


class Flatten:
    def __call__(self, x):
        return x.reshape((x.shape[0], -1))


class Add:
    def __call__(self, x, y):
        return x + y


class Concat:
    def __call__(self, *xs):
        return jnp.concatenate(xs, axis=-1)


@dataclasses.dataclass
class Cast:
    """Dtype boundary node (no torch analog: the reference's fp16 wrapping
    lived outside the graph, `fp16util.py`); used by the spec builders to
    enter bf16 compute at the input and exit to fp32 logits."""

    dtype: Any

    def __call__(self, x):
        return x.astype(self.dtype)


@dataclasses.dataclass
class MaxPool:
    window: int

    def __call__(self, x):
        return nn.max_pool(x, (self.window, self.window),
                           strides=(self.window, self.window))


# ---------------------------------------------------------------------------
# spec flattening and wiring (`core.py:123-141`)
# ---------------------------------------------------------------------------


def path_iter(nested, pfx: Tuple[str, ...] = ()):
    """Yield ``(path_tuple, value)`` leaves of a nested mapping
    (`core.py:123-127`).  Accepts any Mapping — flax freezes attribute dicts
    into FrozenDicts."""
    from collections.abc import Mapping

    for name, val in nested.items():
        if isinstance(val, Mapping):
            yield from path_iter(val, pfx + (str(name),))
        else:
            yield pfx + (str(name),), val


def _resolve(path: str, at: Tuple[str, ...]) -> str:
    """Resolve relative input paths against the node's directory: ``./x`` is
    a sibling, each ``../`` climbs one level (the ``RelativePath``
    equivalent); anything else is absolute."""
    if not (path.startswith("./") or path.startswith("../")):
        return path
    parts = list(at[:-1])
    while True:
        if path.startswith("./"):
            path = path[2:]
        elif path.startswith("../"):
            parts = parts[:-1]
            path = path[3:]
        else:
            break
    return "/".join(parts + ([path] if path else []))


def build_graph(spec: Dict) -> Dict[str, Tuple[Any, Tuple[str, ...]]]:
    """Flatten a nested spec to ``{path: (node, input_paths)}`` in insertion
    order, wiring each node to its predecessor unless explicit inputs are
    given (`core.py:129-141`).  The first node's input is the graph input
    (denoted by the empty tuple)."""
    graph: Dict[str, Tuple[Any, Tuple[str, ...]]] = {}
    prev: Optional[str] = None
    for path_t, val in path_iter(spec):
        path = "/".join(path_t)
        if isinstance(val, tuple):
            node, inputs = val
            inputs = tuple(_resolve(p, path_t) for p in inputs)
            for p in inputs:
                if p not in graph:
                    raise ValueError(f"node {path!r}: unknown input {p!r} "
                                     f"(known: {list(graph)})")
        else:
            node = val
            inputs = (prev,) if prev is not None else ()
        graph[path] = (node, inputs)
        prev = path
    if not graph:
        raise ValueError("empty graph spec")
    return graph


class GraphNet(nn.Module):
    """Executable DAG — the ``Network`` equivalent (`torch_backend.py:107-118`).

    ``outputs=None`` returns the final node's value; a tuple of paths returns
    ``{path: value}`` (the reference returned the full cache; request the
    paths you need so dead branches get pruned by XLA).
    """

    spec: Any  # nested dict (static; hashed by id via flax's FrozenDict wrap)
    outputs: Optional[Tuple[str, ...]] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        import inspect

        graph = build_graph(self.spec)
        cache: Dict[str, Any] = {}
        for path, (node, inputs) in graph.items():
            args = [x] if not inputs else [cache[p] for p in inputs]
            if isinstance(node, nn.Module):
                # re-construct inside this compact scope (a module built here
                # auto-binds as a child) named by path, so param trees mirror
                # the spec layout
                fields = {
                    f.name: getattr(node, f.name)
                    for f in dataclasses.fields(node)
                    if f.name not in ("parent", "name")
                }
                bound = type(node)(**fields, name=path.replace("/", "_"))
                if "train" in inspect.signature(type(node).__call__).parameters:
                    out = bound(*args, train=train)
                else:
                    out = bound(*args)
            else:
                out = node(*args)
            cache[path] = out
        if self.outputs is None:
            return cache[path]
        return {p: cache[p] for p in self.outputs}


# ---------------------------------------------------------------------------
# the reference's spec-built nets (`dawn.py:23-82`)
# ---------------------------------------------------------------------------


def resnet9_spec(num_classes: int = 10, channels: Optional[Dict[str, int]] = None,
                 classifier_weight: float = 0.125,
                 dtype: Any = jnp.float32) -> Dict:
    """`resnet9()` as a spec (`dawn.py:44-56,70-77`): residuals are explicit
    Add edges, exactly how the reference wired them.  ``dtype=bfloat16``
    wraps the graph in Cast boundary nodes (bf16 compute, fp32 params and
    logits — the models/resnet9.py policy expressed as graph edges)."""
    from tpu_compressed_dp.models.resnet9 import ConvBN

    ch = channels or {"prep": 64, "layer1": 128, "layer2": 256, "layer3": 512}

    def res_block(c):
        # `dawn.py:37-43`: residual branch + Add join back to the trunk
        return {
            "in": Identity(),
            "res1": ConvBN(c, dtype=dtype),
            "res2": ConvBN(c, dtype=dtype),
            "add": (Add(), ["./in", "./res2"]),
        }

    return {
        "cast_in": Cast(dtype),
        "prep": ConvBN(ch["prep"], dtype=dtype),
        "layer1": {"conv": ConvBN(ch["layer1"], dtype=dtype), "pool": MaxPool(2),
                   "residual": res_block(ch["layer1"])},
        "layer2": {"conv": ConvBN(ch["layer2"], dtype=dtype), "pool": MaxPool(2)},
        "layer3": {"conv": ConvBN(ch["layer3"], dtype=dtype), "pool": MaxPool(2),
                   "residual": res_block(ch["layer3"])},
        "pool": MaxPool(4),
        "flatten": Flatten(),
        "linear": nn.Dense(num_classes, use_bias=False, dtype=dtype),
        "logits": Mul(classifier_weight),
        "cast_out": Cast(jnp.float32),
    }


def alexnet_spec(num_classes: int = 10,
                 channels: Optional[Dict[str, int]] = None,
                 classifier_weight: float = 0.125,
                 dtype: Any = jnp.float32) -> Dict:
    """`alexnet()` as a spec (`dawn.py:57-68,79-82`)."""
    from tpu_compressed_dp.models.resnet9 import ConvBN

    ch = channels or {"prep": 64, "layer1": 192, "layer2": 384,
                      "layer3": 256, "layer4": 256}
    return {
        "cast_in": Cast(dtype),
        "prep": ConvBN(ch["prep"], stride=2, dtype=dtype),
        "pool0": MaxPool(2),
        "layer1": ConvBN(ch["layer1"], dtype=dtype),
        "pool1": MaxPool(2),
        "layer2": ConvBN(ch["layer2"], dtype=dtype),
        "layer3": ConvBN(ch["layer3"], dtype=dtype),
        "layer4": ConvBN(ch["layer4"], dtype=dtype),
        "pool4": MaxPool(2),
        "pool5": MaxPool(2),
        "flatten": Flatten(),
        "linear": nn.Dense(num_classes, use_bias=False, dtype=dtype),
        "logits": Mul(classifier_weight),
        "cast_out": Cast(jnp.float32),
    }
