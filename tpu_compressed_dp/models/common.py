"""Model adapter glue: flax modules -> the train step's ``apply_fn`` protocol.

Every model in the zoo is a flax module whose ``__call__`` takes
``(x, train: bool)``; this adapter normalises the batch_stats / dropout-rng
plumbing so the train step (`train/step.py`) stays model-agnostic — the role
the reference's dict-output ``Network`` interpreter played
(`CIFAR10/torch_backend.py:107-118`), minus the graph walking.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

__all__ = ["init_model", "make_apply_fn", "make_normalizing_apply_fn"]


def init_model(module, rng: jax.Array, sample_input: jax.Array) -> Tuple[Any, Any]:
    """Initialise a model; returns ``(params, batch_stats)`` (stats may be {})."""
    variables = module.init({"params": rng, "dropout": rng}, sample_input, train=False)
    return variables["params"], variables.get("batch_stats", {})


def make_normalizing_apply_fn(module, mean, std):
    """``make_apply_fn`` with on-device input normalisation.

    Loaders ship raw uint8 NHWC and the compiled step does ``(x - mean)/std``
    (``mean``/``std`` on the 0-255 scale): 1 byte/pixel crosses the
    host->device wire instead of 4 — the reference's GPU-side
    ``BatchTransformDataLoader`` trick (`IMAGENET/training/dataloader.py:76-99`)
    applied framework-wide."""
    import jax.numpy as jnp

    inner = make_apply_fn(module)
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)

    def apply_fn(params, batch_stats, x, train, rngs):
        x = (x.astype(jnp.float32) - mean) / std
        return inner(params, batch_stats, x, train, rngs)

    return apply_fn


def make_apply_fn(module):
    """Build ``apply_fn(params, batch_stats, x, train, rngs) -> (logits, new_stats)``."""

    def apply_fn(params, batch_stats, x, train: bool, rngs: Dict[str, jax.Array]):
        variables = {"params": params}
        has_stats = bool(batch_stats)
        if has_stats:
            variables["batch_stats"] = batch_stats
        rngs = {k: v for k, v in rngs.items()} if train else {}
        if train and has_stats:
            logits, updates = module.apply(
                variables, x, train=True, mutable=["batch_stats"], rngs=rngs
            )
            return logits, updates["batch_stats"]
        logits = module.apply(variables, x, train=train, rngs=rngs)
        return logits, batch_stats

    return apply_fn
