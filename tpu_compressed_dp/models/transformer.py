"""Llama-family decoder-only transformer, written for manual mesh parallelism.

Net-new model family relative to the reference (its zoo is CNNs: ResNet-9 /
AlexNet / VGG-16 / ResNet-50, SURVEY.md §2) — required by the BASELINE.json
stretch config "Llama-3-8B pretrain — entire-model Top-K grad compression
over ICI".  Architecture: RMSNorm pre-norm, rotary position embeddings,
grouped-query attention, SwiGLU MLP, untied LM head.

Parallelism design (TPU-first, megatron-style over a named mesh):
  * ``tensor`` axis — attention heads and MLP hidden are column-sharded, the
    output projections row-sharded (one ``psum`` each per layer); the LM head
    is vocab-sharded and the loss is computed vocab-parallel (no logit
    all-gather ever materialises the [B, T, V] tensor).
  * ``seq`` axis — activations are sequence-sharded; attention runs as a
    ring over the axis (:mod:`tpu_compressed_dp.ops.ring_attention`).
  * ``data`` axis — batch sharding; gradient sync (with compression) psums
    over data x seq, handled by the train step, not the model.

``apply`` is written as per-device code: it works unsharded (axis names
``None``) and inside ``shard_map`` (axis names set), so a single-device run,
a test on the virtual CPU mesh, and a pod run share one implementation.
Parameters are a plain nested dict with a parallel tree of
``PartitionSpec``s from :func:`param_specs`.
"""

from __future__ import annotations

import dataclasses
import functools
import math
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from tpu_compressed_dp import compat
from jax.sharding import PartitionSpec as P

from tpu_compressed_dp.ops.ring_attention import ring_attention

Array = jax.Array

__all__ = ["LlamaConfig", "llama3_8b", "tiny_llama", "init_llama",
           "param_specs", "apply_llama", "vocab_parallel_xent"]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 4
    ffn_hidden: Optional[int] = None  # default: SwiGLU 8/3 * dim rounded to 256
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # Mixture-of-experts (0 = dense FFN everywhere).  Experts shard over the
    # tensor axis: activations are replicated across it in this layout, so
    # expert-parallel dispatch needs no all_to_all — each tensor rank runs
    # its local experts on all tokens (Switch-style top-1, fixed capacity)
    # and one psum combines.
    n_experts: int = 0
    moe_every: int = 2            # MoE FFN on every k-th layer (1 = all)
    capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01  # load-balance aux loss weight
    # rematerialise each layer in backward (jax.checkpoint): activation
    # memory drops from O(L) to O(1) layers at ~1/3 extra FLOPs — the knob
    # that buys long-context training headroom
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def ffn(self) -> int:
        if self.ffn_hidden is not None:
            return self.ffn_hidden
        h = int(8 * self.dim / 3)
        return ((h + 255) // 256) * 256

    def validate_mesh(self, tensor_size: int) -> None:
        if self.n_kv_heads % tensor_size or self.n_heads % tensor_size:
            raise ValueError(
                f"heads ({self.n_heads}/{self.n_kv_heads}) must divide by "
                f"tensor axis size {tensor_size}"
            )
        if self.ffn % tensor_size or self.vocab_size % tensor_size:
            raise ValueError(
                f"ffn ({self.ffn}) and vocab ({self.vocab_size}) must divide "
                f"by tensor axis size {tensor_size}"
            )
        if self.n_experts and self.n_experts % tensor_size:
            raise ValueError(
                f"n_experts ({self.n_experts}) must divide by tensor axis "
                f"size {tensor_size}"
            )

    def is_moe_layer(self, i: int) -> bool:
        return bool(self.n_experts) and (i % max(self.moe_every, 1) ==
                                         max(self.moe_every, 1) - 1)


def llama3_8b() -> LlamaConfig:
    """The BASELINE.json stretch target."""
    return LlamaConfig(vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
                       n_kv_heads=8, ffn_hidden=14336, rope_theta=500000.0)


def tiny_llama(vocab: int = 256, dim: int = 64, layers: int = 2) -> LlamaConfig:
    """Smoke/test scale."""
    return LlamaConfig(vocab_size=vocab, dim=dim, n_layers=layers, n_heads=4,
                       n_kv_heads=2, ffn_hidden=128)


def init_llama(cfg: LlamaConfig, key: Array) -> Dict[str, Any]:
    """fp32 master parameters (cast to ``cfg.dtype`` at use)."""
    def dense(key, fan_in, shape):
        return (jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in))

    keys = jax.random.split(key, cfg.n_layers + 3)
    hd = cfg.head_dim
    layers = []
    for i in range(cfg.n_layers):
        k = jax.random.split(keys[i], 8)
        layer = {
            "attn_norm": jnp.ones((cfg.dim,), jnp.float32),
            "wq": dense(k[0], cfg.dim, (cfg.dim, cfg.n_heads * hd)),
            "wk": dense(k[1], cfg.dim, (cfg.dim, cfg.n_kv_heads * hd)),
            "wv": dense(k[2], cfg.dim, (cfg.dim, cfg.n_kv_heads * hd)),
            "wo": dense(k[3], cfg.n_heads * hd, (cfg.n_heads * hd, cfg.dim)),
            "mlp_norm": jnp.ones((cfg.dim,), jnp.float32),
        }
        if cfg.is_moe_layer(i):
            e = cfg.n_experts
            layer.update({
                "router": dense(k[7], cfg.dim, (cfg.dim, e)),
                "w_gate": dense(k[4], cfg.dim, (e, cfg.dim, cfg.ffn)),
                "w_up": dense(k[5], cfg.dim, (e, cfg.dim, cfg.ffn)),
                "w_down": dense(k[6], cfg.ffn, (e, cfg.ffn, cfg.dim)),
            })
        else:
            layer.update({
                "w_gate": dense(k[4], cfg.dim, (cfg.dim, cfg.ffn)),
                "w_up": dense(k[5], cfg.dim, (cfg.dim, cfg.ffn)),
                "w_down": dense(k[6], cfg.ffn, (cfg.ffn, cfg.dim)),
            })
        layers.append(layer)
    return {
        "embed": jax.random.normal(keys[-3], (cfg.vocab_size, cfg.dim), jnp.float32) * 0.02,
        "layers": layers,
        "final_norm": jnp.ones((cfg.dim,), jnp.float32),
        "lm_head": dense(keys[-2], cfg.dim, (cfg.dim, cfg.vocab_size)),
    }


def param_specs(cfg: LlamaConfig, tensor_axis: str = "tensor") -> Dict[str, Any]:
    """PartitionSpec tree matching :func:`init_llama`'s structure.

    Column-parallel: qkv, gate/up, lm_head (output dim over tensor);
    row-parallel: wo, w_down (input dim over tensor); everything else
    replicated.  No ``data``/``seq`` entries: params are replicated across
    those axes (their grads are what the compressed sync reduces).
    """
    t = tensor_axis
    layers = []
    for i in range(cfg.n_layers):
        layer = {
            "attn_norm": P(), "mlp_norm": P(),
            "wq": P(None, t), "wk": P(None, t), "wv": P(None, t),
            "wo": P(t, None),
        }
        if cfg.is_moe_layer(i):
            # expert parallelism: the leading expert dim shards over the
            # tensor axis (router replicated — every rank routes all tokens)
            layer.update({
                "router": P(),
                "w_gate": P(t, None, None), "w_up": P(t, None, None),
                "w_down": P(t, None, None),
            })
        else:
            layer.update({
                "w_gate": P(None, t), "w_up": P(None, t),
                "w_down": P(t, None),
            })
        layers.append(layer)
    return {
        "embed": P(),
        "layers": layers,
        "final_norm": P(),
        "lm_head": P(None, t),
    }


def _rms_norm(x: Array, w: Array, eps: float) -> Array:
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale * w).astype(x.dtype)


def _rope(x: Array, pos: Array, theta: float) -> Array:
    """Rotary embedding; x: [B, H, T, D], pos: [T] global positions."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    ang = pos[:, None].astype(jnp.float32) * freqs[None, :]  # [T, D/2]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., 0::2].astype(jnp.float32), x[..., 1::2].astype(jnp.float32)
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(x.shape).astype(x.dtype)


def _psum_if(x: Array, axis: Optional[str]) -> Array:
    return jax.lax.psum(x, axis) if axis is not None else x


def _moe_ffn(cfg: LlamaConfig, lp: Dict[str, Any], x: Array,
             tensor_axis: Optional[str]) -> Tuple[Array, Array]:
    """Switch-style top-1 MoE FFN, experts sharded over the tensor axis.

    Activations are replicated across the tensor axis in this layout, so
    expert parallelism needs no all_to_all: every rank routes all tokens
    (replicated router), dispatches them into its *local* experts' fixed
    ``capacity`` slots via one-hot einsums (static shapes), and the combined
    outputs psum across the axis.  Tokens over capacity fall through to the
    residual stream (Switch semantics).  Capacity is per (data, seq) shard —
    each worker's local tokens compete for ``ceil(local_tokens/E * cf)``
    slots, so drop patterns depend on the mesh (as in any expert-parallel
    system); results equal the unsharded layer exactly in the drop-free
    regime (``cf >= E``).  Returns (out, load-balance aux).
    """
    dt = cfg.dtype
    b, t, d = x.shape
    n = b * t
    e = cfg.n_experts
    xf = x.reshape(n, d)
    probs = jax.nn.softmax(
        (xf @ lp["router"].astype(dt)).astype(jnp.float32), axis=-1)  # [N, E]
    top = jnp.argmax(probs, axis=-1)
    top_p = jnp.max(probs, axis=-1)
    onehot = jax.nn.one_hot(top, e, dtype=jnp.float32)
    # load-balance aux (Switch Transformer eq. 4): E * sum_e f_e * P_e
    aux = e * jnp.sum(jnp.mean(onehot, axis=0) * jnp.mean(probs, axis=0))

    cap = max(int(math.ceil(n / e * cfg.capacity_factor)), 1)
    pos = jnp.cumsum(onehot, axis=0) * onehot            # 1-based queue rank
    within = (pos > 0) & (pos <= cap)
    disp = (within[..., None] &
            (pos[..., None] == (1.0 + jnp.arange(cap))[None, None, :])
            ).astype(dt)                                  # [N, E, cap]
    combine = disp * top_p[:, None, None].astype(dt)

    if tensor_axis is not None:
        e_local = lp["w_gate"].shape[0]  # static: the local shard size
        off = jax.lax.axis_index(tensor_axis) * e_local
        disp = jax.lax.dynamic_slice_in_dim(disp, off, e_local, axis=1)
        combine = jax.lax.dynamic_slice_in_dim(combine, off, e_local, axis=1)

    xe = jnp.einsum("nec,nd->ecd", disp, xf)             # [E_l, cap, D]
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe, lp["w_gate"].astype(dt)))
    h = h * jnp.einsum("ecd,edf->ecf", xe, lp["w_up"].astype(dt))
    ye = jnp.einsum("ecf,efd->ecd", h, lp["w_down"].astype(dt))
    out = _psum_if(jnp.einsum("ecd,nec->nd", ye, combine), tensor_axis)
    return out.reshape(b, t, d), aux


def apply_llama(
    cfg: LlamaConfig,
    params: Dict[str, Any],
    tokens: Array,
    *,
    tensor_axis: Optional[str] = None,
    seq_axis: Optional[str] = None,
    with_aux: bool = False,
    return_hidden: bool = False,
):
    """Per-device forward: ``tokens`` [B_local, T_local] -> logits
    [B_local, T_local, V_local] (vocab-sharded when ``tensor_axis`` is set).

    Feed the result to :func:`vocab_parallel_xent`; an explicit logit
    all-gather is deliberately not offered (a [B,T,V] global tensor is the
    thing this layout exists to avoid).  With ``with_aux`` the return is
    ``(logits, aux)`` where aux is the mean MoE load-balance loss (0.0 for
    dense configs).  ``return_hidden`` skips the head and yields the
    final-normed hidden states instead of logits — the input
    :func:`fused_head_xent` wants (it owns the head matmul).
    """
    dt = cfg.dtype
    hd = cfg.head_dim

    if seq_axis is not None:
        t_local = tokens.shape[1]
        pos = jax.lax.axis_index(seq_axis) * t_local + jnp.arange(t_local)
    else:
        pos = jnp.arange(tokens.shape[1])

    h = params["embed"].astype(dt)[tokens]  # [B, T, D]
    aux_total = jnp.zeros((), jnp.float32)
    n_moe = 0

    def layer_fn(h, lp, is_moe):
        x = _rms_norm(h, lp["attn_norm"], cfg.norm_eps)
        q = (x @ lp["wq"].astype(dt))  # [B, T, Hl*hd] (heads tensor-local)
        k = (x @ lp["wk"].astype(dt))
        v = (x @ lp["wv"].astype(dt))
        b, t = x.shape[:2]
        q = q.reshape(b, t, -1, hd).transpose(0, 2, 1, 3)  # [B, Hl, T, hd]
        k = k.reshape(b, t, -1, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, t, -1, hd).transpose(0, 2, 1, 3)
        q = _rope(q, pos, cfg.rope_theta)
        k = _rope(k, pos, cfg.rope_theta)
        o = ring_attention(q, k, v, axis_name=seq_axis)  # [B, Hl, T, hd]
        o = o.transpose(0, 2, 1, 3).reshape(b, t, -1)
        attn_out = _psum_if(o @ lp["wo"].astype(dt), tensor_axis)  # row-parallel
        h = h + attn_out

        x = _rms_norm(h, lp["mlp_norm"], cfg.norm_eps)
        if is_moe:
            mlp_out, aux = _moe_ffn(cfg, lp, x, tensor_axis)
        else:
            gate = jax.nn.silu(x @ lp["w_gate"].astype(dt))
            up = x @ lp["w_up"].astype(dt)
            mlp_out = _psum_if((gate * up) @ lp["w_down"].astype(dt), tensor_axis)
            aux = jnp.zeros((), jnp.float32)
        return h + mlp_out, aux

    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn, static_argnums=(2,))

    for li, lp in enumerate(params["layers"]):
        is_moe = cfg.is_moe_layer(li)
        h, aux = layer_fn(h, lp, is_moe)
        if is_moe:
            aux_total = aux_total + aux
            n_moe += 1

    h = _rms_norm(h, params["final_norm"], cfg.norm_eps)
    if return_hidden:
        out = h
    else:
        out = h @ params["lm_head"].astype(dt)  # [B, T, V_local]
    if with_aux:
        return out, aux_total / max(n_moe, 1)
    return out


# Fused head+xent defaults by SHAPE (r5).  Measured on chip:
#   * 125M / 32k vocab / seq 1024 (logits 0.5 GB): ~5% SLOWER than the
#     unfused chain (115.3k vs 120.8k tok/s) — XLA fuses the one-shot
#     logits+softmax-xent well and the scan adds recompute;
#   * llama3_8b shapes, 2 layers / 128k vocab / seq 8192 (logits 2.1 GB):
#     the unfused chain needs 21.9 GB HBM (OOM on a 16 GB v5e) while the
#     fused path runs at 14.4k tok/s / MFU 0.71 — the [N, V] logits and
#     AD's saved softmax inputs never materialise.
# So: auto-enable when the bf16 logits buffer would exceed 1 GiB (the
# crossover sits well below the OOM cliff and above the 5%-regret regime);
# TPU_CDP_FUSED_XENT=1/0 forces either way.  Numerics: slightly MORE
# precise than the unfused path at bf16 (fp32 logits inside the scan).
_FUSED_XENT = os.environ.get("TPU_CDP_FUSED_XENT", "")
_FUSED_XENT_AUTO_BYTES = 1 << 30


def use_fused_head_xent(n_tokens: int = 0, vocab: int = 0,
                        itemsize: int = 2) -> bool:
    """Whether the LM loss should take the fused chunked-logsumexp path.

    ``n_tokens``/``vocab`` are the per-worker logits dimensions at the call
    site (0 = unknown: auto resolves to off, preserving the pre-r5
    default for callers that cannot size the buffer); ``itemsize`` is the
    logits dtype width in bytes (``jnp.dtype(cfg.dtype).itemsize`` — fp32
    configs materialise a 2x larger buffer than the old hardcoded bf16
    estimate, so the crossover fired at twice the intended size, ADVICE r5).

    Requires VMA typing: the custom VJP places its cross-shard cotangent
    psums by diffing primal/cotangent varying-axes (``match_vma``), which
    old JAX cannot express — there the hand-placed psums would silently
    vanish and tp>1 gradients would be per-shard partials.  The unfused
    vocab-parallel path is correct everywhere, so old JAX always takes it
    (this is a peak-memory feature, not a correctness one).
    """
    if not compat.HAS_VMA:
        return False
    if _FUSED_XENT in ("0", "1"):
        return _FUSED_XENT == "1"
    return n_tokens * vocab * itemsize > _FUSED_XENT_AUTO_BYTES


def _fhx_chunks(v_local: int, chunk: int):
    """(chunk_size, n_chunks, v_padded) — pad the vocab up to whole chunks
    (zero weight columns; masked to -inf in the running logsumexp)."""
    c = min(chunk, v_local)
    nc = -(-v_local // c)
    return c, nc, nc * c


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_head_xent(h: Array, w: Array, targets: Array,
                    tensor_axis: Optional[str] = None,
                    chunk: int = 2048) -> Array:
    """Mean next-token cross-entropy STRAIGHT from hidden states — the LM
    head matmul and the softmax-xent fused through a running logsumexp over
    vocab chunks, so the [N, V] logits (and AD's saved probabilities — at
    the r4 LM config ~0.5-1.5 GB/step of HBM traffic) never materialise.

    ``h`` [..., D], ``w`` [D, V_local] (vocab-sharded under
    ``tensor_axis``), ``targets`` [...] global ids.  Numerically equal to
    ``vocab_parallel_xent(h @ w, targets)`` (same max-shift, same psum
    structure); the hand-written VJP recomputes each chunk's logits in the
    backward (flash-attention discipline: trade one extra matmul pass for
    the activation storage).
    """
    loss, _ = _fhx_fwd(h, w, targets, tensor_axis, chunk)
    return loss


def _fhx_scan_stats(h2, w, targets1, off, v_local, c, nc):
    """Running (m, l, zt) over vocab chunks; w pre-padded to [D, nc*c]."""
    n = h2.shape[0]
    w3 = w.reshape(w.shape[0], nc, c)

    def body(carry, xs):
        m, l, zt = carry
        w_c, ci = xs
        z = jax.lax.dot_general(
            h2, w_c, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)          # [N, c]
        col = ci * c + jnp.arange(c)
        z = jnp.where(col[None, :] < v_local, z, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(z, axis=-1))
        l = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(z - m_new[:, None]), axis=-1)
        lt = targets1 - off - ci * c
        # membership needs BOTH chunk bounds and this shard's true vocab:
        # a target owned by the next shard can alias into this shard's pad
        # window (lt in [0, c) but targets1 - off >= v_local), where the
        # masked -inf logit would poison zt through the psum
        in_chunk = (lt >= 0) & (lt < c) & (targets1 - off < v_local)
        zc = jnp.take_along_axis(
            z, jnp.clip(lt, 0, c - 1)[:, None], axis=-1)[:, 0]
        zt = zt + jnp.where(in_chunk, zc, 0.0)
        return (m_new, l, zt), None

    init = (jnp.full((n,), -jnp.inf, jnp.float32),
            jnp.zeros((n,), jnp.float32), jnp.zeros((n,), jnp.float32))
    # inside shard_map the body outputs are device-varying (they derive from
    # the varying h/w/targets — targets can vary on axes h does not, e.g.
    # pipe in the deferred-head uneven fallback); pcast the replicated init
    # so scan's carry types match
    vma = tuple(sorted(getattr(compat.typeof(h2), "vma", frozenset())
                       | getattr(compat.typeof(w), "vma", frozenset())
                       | getattr(compat.typeof(targets1), "vma", frozenset())))
    if vma:
        init = tuple(compat.pcast(v, vma, to="varying") for v in init)
    (m, l, zt), _ = jax.lax.scan(
        body, init, (w3.transpose(1, 0, 2), jnp.arange(nc)))
    return m, l, zt


def _fhx_fwd(h, w, targets, tensor_axis, chunk):
    d = h.shape[-1]
    v_local = w.shape[-1]
    h2 = h.reshape(-1, d)
    targets1 = targets.reshape(-1)
    n = h2.shape[0]
    c, nc, v_pad = _fhx_chunks(v_local, chunk)
    w_p = jnp.pad(w, ((0, 0), (0, v_pad - v_local)))
    off = (jax.lax.axis_index(tensor_axis) * v_local
           if tensor_axis is not None else 0)
    m, l, zt = _fhx_scan_stats(h2, w_p, targets1, off, v_local, c, nc)
    if tensor_axis is not None:
        m_g = jax.lax.pmax(m, tensor_axis)
        l = jax.lax.psum(l * jnp.exp(m - m_g), tensor_axis)
        zt = jax.lax.psum(zt, tensor_axis)
        m = m_g
    lse = m + jnp.log(l)
    loss = jnp.mean(lse - zt)
    return loss, (h, w, targets, lse)


def _fhx_bwd(tensor_axis, chunk, res, g):
    import numpy as np

    h, w, targets, lse = res
    d = h.shape[-1]
    v_local = w.shape[-1]
    h2 = h.reshape(-1, d)
    targets1 = targets.reshape(-1)
    n = h2.shape[0]
    c, nc, v_pad = _fhx_chunks(v_local, chunk)
    w_p = jnp.pad(w, ((0, 0), (0, v_pad - v_local)))
    off = (jax.lax.axis_index(tensor_axis) * v_local
           if tensor_axis is not None else 0)
    # pad columns need no mask: their z = h @ 0 gives p = exp(-lse) != 0,
    # but that feeds dh only through w_c == 0 (inert) and dw only in the
    # sliced-off pad columns; the onehot never lands there (targets are
    # within the true vocab)
    dnll = (g / n).astype(jnp.float32)
    w3 = w_p.reshape(d, nc, c).transpose(1, 0, 2)

    def body(dh, xs):
        w_c, ci = xs
        z = jax.lax.dot_general(
            h2, w_c, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        p = jnp.exp(z - lse[:, None])                     # [N, c]
        lt = targets1 - off - ci * c
        # same shard-membership guard as the forward (a pad-window alias
        # would subtract the onehot from a zero-weight column — inert for
        # dh/dw, but keep the two masks identical by construction)
        lt = jnp.where(targets1 - off < v_local, lt, -1)
        onehot = (jnp.arange(c)[None, :] == lt[:, None])
        dz = ((p - onehot.astype(jnp.float32)) * dnll).astype(w_c.dtype)
        dh = dh + jax.lax.dot_general(
            dz, w_c, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        dw_c = jax.lax.dot_general(
            h2, dz, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [d, c]
        return dh, dw_c

    dh0 = jnp.zeros((n, d), jnp.float32)
    vma = tuple(sorted(getattr(compat.typeof(h2), "vma", frozenset())
                       | getattr(compat.typeof(w_p), "vma", frozenset())
                       | getattr(compat.typeof(lse), "vma", frozenset())
                       | getattr(compat.typeof(targets1), "vma", frozenset())
                       | getattr(compat.typeof(dnll), "vma", frozenset())))
    if vma:
        dh0 = compat.pcast(dh0, vma, to="varying")
    dh, dw_stack = jax.lax.scan(body, dh0, (w3, jnp.arange(nc)))
    dw = dw_stack.transpose(1, 0, 2).reshape(d, v_pad)[:, :v_local]

    # A cotangent's varying-mesh-axes must match its primal's: wherever the
    # primal is REPLICATED over an axis the computation varies on (h across
    # the vocab-sharded tensor axis; lm_head across pipeline stages), the
    # true cotangent is the SUM of the per-shard partials.  The unfused path
    # gets these psums inserted automatically as transposes of the implicit
    # pvary where replicated values meet varying operands; a custom VJP must
    # place them by hand.
    def match_vma(ct, primal):
        extra = tuple(sorted(getattr(compat.typeof(ct), "vma", frozenset())
                             - getattr(compat.typeof(primal), "vma",
                                       frozenset())))
        return jax.lax.psum(ct, extra) if extra else ct

    dh = match_vma(dh, h)
    dw = match_vma(dw, w)
    dt_ct = np.zeros(targets1.shape, dtype=jax.dtypes.float0)
    return (dh.reshape(h.shape).astype(h.dtype), dw.astype(w.dtype),
            dt_ct.reshape(targets.shape))


fused_head_xent.defvjp(_fhx_fwd, _fhx_bwd)


def vocab_parallel_xent(
    local_logits: Array,
    targets: Array,
    *,
    tensor_axis: Optional[str] = None,
) -> Array:
    """Mean next-token cross-entropy from vocab-sharded logits.

    ``local_logits`` [B, T, V_local], ``targets`` [B, T] global token ids.
    The three reductions (max, sum-exp, target logit) psum over the tensor
    axis — megatron's vocab-parallel loss, sized O(B*T) on the wire instead
    of O(B*T*V).
    """
    z = local_logits.astype(jnp.float32)
    v_local = z.shape[-1]
    # the stabilising max cancels out of the gradient — stop_gradient keeps
    # AD away from pmax (which has no differentiation rule)
    if tensor_axis is not None:
        off = jax.lax.axis_index(tensor_axis) * v_local
        zmax = jax.lax.pmax(jnp.max(jax.lax.stop_gradient(z), axis=-1), tensor_axis)
    else:
        off = 0
        zmax = jnp.max(jax.lax.stop_gradient(z), axis=-1)
    sumexp = jnp.sum(jnp.exp(z - zmax[..., None]), axis=-1)
    local_t = targets - off
    in_shard = (local_t >= 0) & (local_t < v_local)
    zt = jnp.take_along_axis(
        z, jnp.clip(local_t, 0, v_local - 1)[..., None], axis=-1
    )[..., 0]
    zt = jnp.where(in_shard, zt, 0.0)
    if tensor_axis is not None:
        sumexp = jax.lax.psum(sumexp, tensor_axis)
        zt = jax.lax.psum(zt, tensor_axis)
    nll = jnp.log(sumexp) + zmax - zt
    return jnp.mean(nll)
