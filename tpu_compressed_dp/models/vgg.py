"""VGG for CIFAR-10 (`CIFAR10/vgg16.py`).

Configs A/B/D/E with optional BatchNorm, the torch-style adaptive 7x7 average
pool (which *tiles* when the input is smaller than 7x7 — exactly what happens
for 32x32 CIFAR inputs after five pools), and the reference's init scheme
(`vgg16.py:55-66`): kaiming-normal(fan_out) convs, normal(0, 0.01) linears,
zero biases.  ``vgg16()`` mirrors the module-level ``vgg16model`` singleton
(`vgg16.py:94`): config D, no BN.
"""

from __future__ import annotations

from typing import Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

__all__ = ["VGG", "vgg16", "CFGS", "adaptive_avg_pool"]

CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512, 512, "M",
          512, 512, 512, 512, "M"],
}

_conv_init = nn.initializers.variance_scaling(2.0, "fan_out", "truncated_normal")
_fc_init = nn.initializers.normal(0.01)


def adaptive_avg_pool(x, out_hw: int):
    """torch ``AdaptiveAvgPool2d`` semantics on NHWC: output bin ``i`` averages
    input rows ``floor(i*H/O) .. ceil((i+1)*H/O)-1``; tiles when H < O."""
    n, h, w, c = x.shape
    o = out_hw

    def pool_axis(arr, size, axis):
        slices = []
        for i in range(o):
            lo = (i * size) // o
            hi = -(-((i + 1) * size) // o)  # ceil
            sl = jnp.take(arr, jnp.arange(lo, hi), axis=axis)
            slices.append(jnp.mean(sl, axis=axis, keepdims=True))
        return jnp.concatenate(slices, axis=axis)

    return pool_axis(pool_axis(x, h, 1), w, 2)


class VGG(nn.Module):
    cfg: Union[str, Sequence] = "D"
    batch_norm: bool = False
    num_classes: int = 10
    dropout_rate: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = False):
        cfg = CFGS[self.cfg] if isinstance(self.cfg, str) else self.cfg
        for v in cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding=1, kernel_init=_conv_init)(x)
                if self.batch_norm:
                    x = nn.BatchNorm(use_running_average=not train, momentum=0.9, epsilon=1e-5)(x)
                x = nn.relu(x)
        x = adaptive_avg_pool(x, 7)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, kernel_init=_fc_init, name="fc1")(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, kernel_init=_fc_init, name="fc2")(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes, kernel_init=_fc_init, name="fc3")(x)


def vgg16(num_classes: int = 10, batch_norm: bool = False) -> VGG:
    return VGG(cfg="D", batch_norm=batch_norm, num_classes=num_classes)
