"""Chunk-pipelined gradient synchronization: hide compressed collectives
behind backward + optimizer compute.

The reference's ``RandomKSparsifiedDDP`` earns its wall-clock wins by
overlapping bucket reductions with the backward pass via reverse-order
autograd hooks (`sparsified_ddp.py:279-281`, `ddp.py:429-456`).  This
framework traces the whole sync into one jitted step and leaned on XLA's
latency-hiding scheduler — but the compiled evidence
(``benchmarks/overlap_hlo_r5.txt``) shows XLA's all-reduce COMBINER merges
every per-group collective into ONE late all-reduce that depends on the
entire backward pass: only 24–39 % of the step's compute is scheduled after
it, so the sync runs largely exposed at the step tail.  Pipelining the
reduce matters as much as shrinking it (Near-Optimal Sparse Allreduce,
arXiv:2201.07598).

This module is the TPU-native answer: decompose the sync into up to
``cfg.sync_overlap`` independent **chunk syncs**, issued in
reverse-topological order (the LAST parameters' gradients — produced FIRST
by the backward pass — sync first):

  * **Chunk boundaries align with reduction-group boundaries** of the
    configured granularity (the same ``make_leaf_groups`` bucket-assignment
    the engines use), and each chunk's engine gets the chunk's global
    ``group_offset`` — so per-group compression operators, RNG streams
    (``leaf_key``), PowerSGD warm-start keys (``q<gi>``) and transports are
    BITWISE identical to the single-dispatch sync.  ``sync_overlap`` changes
    the schedule, never the numerics (tests/test_overlap.py).
  * **A minimal dependency chain** (`lax.optimization_barrier`) ties chunk
    ``i+1``'s gradient inputs to one of chunk ``i``'s reduced outputs.  The
    barrier is a runtime identity (numerics unchanged) but makes the chunk
    collectives mutually dependent, which (a) defeats the all-reduce
    combiner — the K collectives stay K separate instructions — and
    (b) pins the issue order to the reverse-topological chunk order.  The
    collectives serialise on the interconnect (they share the links anyway,
    exactly like the reference's bucket queue); every OTHER edge is real
    data flow, so XLA remains free to run the rest of the backward pass and
    the other chunks' optimizer slices while a chunk's collective is in
    flight.
  * **Per-chunk optimizer interleave** (:func:`make_overlap_sync_apply`,
    used by ``train/step.py``): chunk ``i``'s slice of ``optimizer.apply``
    runs while chunk ``i+1``'s collective is in flight.  Per-leaf SGD
    updates are independent, so the sliced apply is bitwise the whole-tree
    apply.
  * **Guard composition**: the finiteness vote (``ok``) is computed ONCE in
    the step factory, before any chunk dispatches; each chunk's engine then
    applies the standard gate (zeroed inputs, EF/comp held bitwise — see
    ``parallel/dp.py:_with_guard``), preserving the bitwise-hold invariant
    of the step guard across the chunked schedule.

Measured, not asserted: ``tools/overlap_evidence.py`` AOT-compiles the real
train step for a v5e topology and reads ``compute_after_frac`` off the
scheduled module (per-chunk collectives labelled by their
``tcdp.chunk<ii>`` scopes); ``--assert-frac`` gates it.  Results land in
``benchmarks/overlap_hlo_r8.txt`` / ``BENCH_r08.json``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from tpu_compressed_dp.obs import trace as obs_trace

__all__ = ["ChunkPlan", "plan_chunks", "grad_availability", "issue_order",
           "make_chunked_grad_sync", "make_overlap_sync_apply",
           "hideable_byte_fraction"]


@dataclasses.dataclass(frozen=True)
class ChunkPlan:
    """One chunk of the gradient tree, in PARAMETER order (chunk 0 holds the
    first leaves; issue order is the reverse).  ``[leaf_lo, leaf_hi)`` is a
    contiguous leaf range whose boundaries coincide with reduction-group
    boundaries; ``group_offset`` is the global index of the chunk's first
    group (the engines' RNG / warm-start key base)."""

    index: int
    leaf_lo: int
    leaf_hi: int
    group_offset: int
    n_groups: int
    n_bytes: int


def plan_chunks(byte_sizes: Sequence[int], cfg) -> List[ChunkPlan]:
    """Partition the tree's leaves into ``<= cfg.sync_overlap`` contiguous,
    byte-balanced chunks whose boundaries align with the granularity's
    reduction-group boundaries.

    Reuses the engines' own bucket-assignment (``make_leaf_groups``) so the
    per-group structure inside each chunk reproduces the whole-tree grouping
    exactly: greedy bucket packing is Markov in the current bucket's fill,
    and every chunk starts at a group boundary (fill = 0), so re-packing the
    chunk's leaf span yields the same groups the whole-tree packing assigned
    to that span.  ``granularity='entiremodel'`` has one group and therefore
    one chunk — the knob degrades to the single-dispatch sync there.
    """
    from tpu_compressed_dp.parallel.dp import BUCKET_MB, make_leaf_groups

    byte_sizes = list(byte_sizes)
    groups = make_leaf_groups(byte_sizes, cfg.granularity,
                              cfg.bucket_mb * BUCKET_MB)
    if not groups:
        return []
    k = max(1, min(int(cfg.sync_overlap), len(groups)))
    group_bytes = [float(sum(byte_sizes[i] for i in g)) for g in groups]
    total = sum(group_bytes) or 1.0
    plans: List[ChunkPlan] = []
    gi = 0
    cum = 0.0
    leaf_lo = 0
    for c in range(k):
        start_g = gi
        target = (c + 1) * total / k
        # take at least one group; keep taking while under the proportional
        # cut AND enough groups remain to give every later chunk one
        while gi < len(groups) and (
                gi == start_g
                or (cum + group_bytes[gi] <= target
                    and len(groups) - gi > k - c - 1)):
            cum += group_bytes[gi]
            gi += 1
        leaf_hi = groups[gi - 1][-1] + 1
        plans.append(ChunkPlan(
            index=c, leaf_lo=leaf_lo, leaf_hi=leaf_hi, group_offset=start_g,
            n_groups=gi - start_g,
            n_bytes=int(sum(group_bytes[start_g:gi]))))
        leaf_lo = leaf_hi
    assert gi == len(groups) and leaf_lo == len(byte_sizes)
    return plans


def hideable_byte_fraction(plans: Sequence[ChunkPlan]) -> float:
    """Fraction of the sync's bytes the chunk schedule can bury under
    remaining compute — the adaptive controller's budget scaler
    (:func:`tpu_compressed_dp.control.signals.hideable_budget_ms`).

    Chunks issue in reverse-parameter order; the LAST-issued chunk (chunk 0,
    the first parameters) completes at the head of the optimizer tail with
    the least compute left to hide behind, so its bytes are counted exposed
    and everything else hideable.  A single-chunk plan (``sync_overlap=1``,
    or entiremodel granularity) therefore yields 0.0 — nothing pipelines,
    matching the one-late-all-reduce behaviour the overlap evidence
    measured.
    """
    plans = list(plans)
    total = float(sum(p.n_bytes for p in plans))
    if total <= 0.0 or len(plans) < 2:
        return 0.0
    exposed = float(min(plans, key=lambda p: p.index).n_bytes)
    return max(0.0, 1.0 - exposed / total)


def _comp_slice(comp: Any, plan: ChunkPlan) -> Any:
    """The chunk's slice of the persistent compressor state: the global
    ``q<gi>`` entries of its groups (PowerSGD warm starts), ``()`` when the
    chunk holds none (stateless methods, dense-fallback-only chunks)."""
    if not isinstance(comp, dict):
        return ()
    sub = {f"q{g}": comp[f"q{g}"]
           for g in range(plan.group_offset, plan.group_offset + plan.n_groups)
           if f"q{g}" in comp}
    return sub if sub else ()


#: Elementwise / metadata primitives the step factory (and the chain
#: itself) applies to gradients AFTER the backward pass produced them —
#: loss-scale division, ``astype(f32) * grad_scale``, chaos ``select_n``,
#: clipping muls, the optimization-barrier tie.  A leaf's availability is
#: its last producer that is NOT one of these: the ``tree.map`` cosmetics
#: are emitted in LEAF order (alphabetical for flax dicts), which would
#: otherwise mask the backward's true production order.
_CHEAP_OPS = frozenset({
    "convert_element_type", "mul", "div", "select_n", "broadcast_in_dim",
    "reshape", "squeeze", "expand_dims", "transpose", "copy", "neg",
    "stop_gradient", "optimization_barrier",
})


def grad_availability(leaves: Sequence[Any]) -> Optional[List[int]]:
    """Best-effort per-leaf gradient *production rank*, read off the ambient
    jit trace: the index of the equation that really produced each leaf
    (walking back through :data:`_CHEAP_OPS`), i.e. WHEN in the backward
    pass the gradient becomes available.

    Flax flattens params alphabetically, which is NOT backward-production
    order — resnet-style models put the stem (``prep``, grad ready LAST)
    after the classifier (``linear``, ready FIRST), so a leaf-order
    heuristic anchors the chunk chain's head at the very end of the
    backward pass (measured: first-collective compute_after_frac 34 % vs
    60 %+ with true availability order).  Reading the trace frame is
    version-sensitive (``jax._src``); any surprise degrades to ``None`` and
    the caller falls back to reversed leaf order.
    """
    try:
        from jax._src.core import Var
        from jax._src.interpreters import partial_eval as pe

        first = next((t for t in leaves
                      if isinstance(t, pe.DynamicJaxprTracer)), None)
        if first is None:
            return None
        frame = first._trace.frame
        producer: Dict[Any, Any] = {}
        for i, eqn in enumerate(frame.eqns):
            for v in eqn.outvars:
                producer[v] = (i, eqn)
        memo: Dict[Any, int] = {}

        def avail(v0) -> int:
            stack = [(v0, False)]
            while stack:
                u, expanded = stack.pop()
                if u in memo:
                    continue
                p = producer.get(u)
                if p is None:
                    memo[u] = -1  # trace input / constant: available at t=0
                    continue
                i, eqn = p
                if eqn.primitive.name not in _CHEAP_OPS:
                    memo[u] = i
                    continue
                ins = [w for w in eqn.invars if isinstance(w, Var)]
                # follow only the DATA path: a cheap op combining the leaf
                # with a broadcast scalar (global clip factor, loss scale)
                # must not inherit that scalar's (very late, whole-tree)
                # rank — it would collapse every leaf to one rank and
                # degrade issue_order to a tie
                same = [w for w in ins
                        if getattr(w.aval, "shape", None) == u.aval.shape]
                if same:
                    ins = same
                if expanded:
                    memo[u] = max((memo.get(w, -1) for w in ins), default=-1)
                else:
                    stack.append((u, True))
                    stack.extend((w, False) for w in ins if w not in memo)
            return memo[v0]

        ranks = []
        for t in leaves:
            v = (frame.tracer_to_var.get(id(t))
                 if isinstance(t, pe.DynamicJaxprTracer) else None)
            ranks.append(avail(v) if v is not None else -1)
        return ranks
    except Exception:
        return None


def issue_order(plans: List[ChunkPlan],
                ranks: Optional[Sequence[int]] = None) -> List[ChunkPlan]:
    """Chunk dispatch (and chain) order.

    With per-leaf production ``ranks`` (:func:`grad_availability`): sort by
    each chunk's availability — the MAX rank over its leaves, i.e. the
    moment its last gradient lands — earliest first, so the chain head's
    collective can be scheduled while most of the backward pass still runs
    and each later chunk's collective finds fresh compute to hide behind.
    Without ranks: reverse leaf order (the LAST parameters' gradients are
    produced FIRST by the backward pass), treating pytree leaf order as
    forward-topological — true for list-like layer stacks, approximate for
    alphabetically-sorted flax dicts.  Rank ties break toward the SAME
    reversed order, so degenerate rankings (e.g. every leaf behind one
    global factor) degrade to the fallback, never to forward order."""
    if ranks is not None:
        return sorted(plans,
                      key=lambda p: (max(ranks[p.leaf_lo:p.leaf_hi]),
                                     -p.index))
    return list(reversed(plans))


def _chain(token: Optional[jax.Array], sub_leaves: List[jax.Array]):
    """Tie this chunk's inputs to the previous chunk's reduced output via an
    optimization barrier (runtime identity).  The resulting dependency edge
    is what keeps the chunk collectives K separate, ordered instructions:
    XLA's all-reduce combiner only merges independent collectives, and the
    scheduler must respect the chain.  Everything else the chunk reads
    (gradient leaves, EF, warm starts) keeps its real producers, so the
    remaining backward pass and other chunks' update slices stay free to
    overlap the in-flight collective."""
    if token is None or not sub_leaves:
        return sub_leaves
    tied = jax.lax.optimization_barrier((token, *sub_leaves))
    return list(tied[1:])


def make_chunked_grad_sync(cfg, axis_name: str = "data"):
    """Chunk-pipelined ``sync(grads, ef, comp, key[, ok])`` with the exact
    contract of :func:`tpu_compressed_dp.parallel.dp.make_grad_sync` — the
    dispatch target for ``cfg.sync_overlap > 1``.

    Bitwise-identical outputs to ``sync_overlap=1`` for every method ×
    mode × transport × EF combination: only the dependency/schedule
    structure changes (see the module docstring).
    """

    def sync(grads: Any, ef: Any, comp: Any, key: jax.Array,
             ok: Optional[jax.Array] = None):
        from tpu_compressed_dp.parallel import dp

        leaves, treedef = jax.tree.flatten(grads)
        plans = plan_chunks([g.size * g.dtype.itemsize for g in leaves], cfg)
        if len(plans) <= 1:
            # single group (entiremodel / one-leaf trees) or empty tree:
            # chunking is structureless — run the plain engine
            single = dp.make_grad_sync(cfg, axis_name, chunking=False)
            return single(grads, ef, comp, key, ok=ok)
        use_ef = cfg.error_feedback
        ef_leaves = jax.tree.leaves(ef) if use_ef else None
        out_leaves: List[Any] = [None] * len(leaves)
        new_ef_leaves: List[Any] = [None] * len(leaves)
        new_comp: Dict[str, Any] = {}
        stats: Optional[Dict[str, Any]] = None
        token = None
        # availability-ordered issue: the chunk whose last gradient lands
        # earliest in the backward pass dispatches (and heads the chain)
        # first, so its collective can start while the rest of the backward
        # still runs
        ranks = grad_availability(leaves)
        for ci, pl in enumerate(issue_order(plans, ranks)):
            sub_sync = dp.make_grad_sync(cfg, axis_name,
                                         group_offset=pl.group_offset,
                                         chunking=False)
            sub = _chain(token, leaves[pl.leaf_lo:pl.leaf_hi])
            sub_ef = ef_leaves[pl.leaf_lo:pl.leaf_hi] if use_ef else ()
            with obs_trace.chunk(ci):
                o, e, c, s = sub_sync(sub, sub_ef, _comp_slice(comp, pl),
                                      key, ok=ok)
            out_leaves[pl.leaf_lo:pl.leaf_hi] = list(o)
            if use_ef:
                new_ef_leaves[pl.leaf_lo:pl.leaf_hi] = list(e)
            if isinstance(c, dict):
                new_comp.update(c)
            stats = s if stats is None else dp.merge_stat_dicts(stats, s)
            token = o[0] if len(o) else token
        out = jax.tree.unflatten(treedef, out_leaves)
        new_ef = jax.tree.unflatten(treedef, new_ef_leaves) if use_ef else ()
        return out, new_ef, new_comp if new_comp else (), stats

    return sync


def make_overlap_sync_apply(cfg, optimizer, axis_name: str = "data"):
    """Fused chunk-pipelined sync + per-chunk optimizer apply for the pure
    data-parallel train step (``train/step.py``).

    Returns ``fused(params, grads, ef, comp, opt_state, key, step[, ok]) ->
    (new_params, new_opt_state, new_ef, new_comp, stats)``.  Chunk ``i``'s
    slice of ``optimizer.apply`` is traced immediately after chunk ``i``'s
    reduce — and BEFORE chunk ``i+1``'s collective is chained in — so the
    scheduler can run it while that collective is in flight.  Per-leaf SGD
    updates are independent and the schedule-valued hyper-parameters are
    functions of ``step`` alone, so the sliced apply is bitwise the
    whole-tree ``optimizer.apply(params, synced, opt_state, step)``.

    The caller computes the guard vote ``ok`` ONCE before this runs; the
    per-chunk engines gate EF/comp and zero the collective inputs
    (``_with_guard``), and the caller still discards the returned
    params/opt via ``select_tree`` on a vetoed step — the produced updates
    are compression noise by then, exactly as in the unfused path.

    ``clip_sent_norm`` needs the GLOBAL synced-gradient norm — a barrier
    across all chunks — so the step factory falls back to chunked-sync +
    whole-tree apply when it is set.
    """

    def fused(params: Any, grads: Any, ef: Any, comp: Any, opt_state: Any,
              key: jax.Array, step: jax.Array,
              ok: Optional[jax.Array] = None):
        from tpu_compressed_dp.parallel import dp

        p_leaves, p_tree = jax.tree.flatten(params)
        g_leaves = jax.tree.leaves(grads)
        buf_leaves = jax.tree.leaves(opt_state["momentum"])
        mask_leaves = (jax.tree.leaves(optimizer.wd_mask)
                       if optimizer.wd_mask is not None
                       else [True] * len(p_leaves))
        plans = plan_chunks([g.size * g.dtype.itemsize for g in g_leaves],
                            cfg)
        if len(plans) <= 1:
            single = dp.make_grad_sync(cfg, axis_name, chunking=False)
            synced, new_ef, new_comp, stats = single(grads, ef, comp, key,
                                                     ok=ok)
            with obs_trace.phase("update"):
                new_params, new_opt = optimizer.apply(params, synced,
                                                      opt_state, step)
            return new_params, new_opt, new_ef, new_comp, stats
        use_ef = cfg.error_feedback
        ef_leaves = jax.tree.leaves(ef) if use_ef else None
        new_p: List[Any] = [None] * len(p_leaves)
        new_b: List[Any] = [None] * len(p_leaves)
        new_ef_leaves: List[Any] = [None] * len(p_leaves)
        new_comp: Dict[str, Any] = {}
        stats: Optional[Dict[str, Any]] = None
        token = None
        ranks = grad_availability(g_leaves)
        for ci, pl in enumerate(issue_order(plans, ranks)):
            lo, hi = pl.leaf_lo, pl.leaf_hi
            sub_sync = dp.make_grad_sync(cfg, axis_name,
                                         group_offset=pl.group_offset,
                                         chunking=False)
            sub = _chain(token, g_leaves[lo:hi])
            sub_ef = ef_leaves[lo:hi] if use_ef else ()
            with obs_trace.chunk(ci):
                o, e, c, s = sub_sync(sub, sub_ef, _comp_slice(comp, pl),
                                      key, ok=ok)
                with obs_trace.phase("update"):
                    # the chunk's slice of the optimizer: plain-list pytrees
                    # align leaf-for-leaf with the full flatten order
                    sub_opt = dataclasses.replace(
                        optimizer, wd_mask=list(mask_leaves[lo:hi]))
                    p_c, o_c = sub_opt.apply(
                        p_leaves[lo:hi], list(o),
                        {"momentum": buf_leaves[lo:hi]}, step)
            new_p[lo:hi] = list(p_c)
            new_b[lo:hi] = list(o_c["momentum"])
            if use_ef:
                new_ef_leaves[lo:hi] = list(e)
            if isinstance(c, dict):
                new_comp.update(c)
            stats = s if stats is None else dp.merge_stat_dicts(stats, s)
            # chain off the chunk's REDUCED gradient (not its updated
            # params): the update slices must stay off the collective chain
            # so they remain free to overlap later chunks' collectives
            token = o[0] if len(o) else token
        new_params = jax.tree.unflatten(p_tree, new_p)
        new_opt = {"momentum": jax.tree.unflatten(p_tree, new_b)}
        g_tree = jax.tree.structure(grads)
        new_ef = jax.tree.unflatten(g_tree, new_ef_leaves) if use_ef else ()
        return new_params, new_opt, new_ef, new_comp if new_comp else (), \
            stats

    return fused
