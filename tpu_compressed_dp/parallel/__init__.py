from tpu_compressed_dp.parallel import mesh, dp  # noqa: F401
