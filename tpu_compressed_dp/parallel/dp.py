"""Compressed data-parallel gradient synchronisation.

TPU-native re-design of the reference's three DP flavours (SURVEY.md §2.2):

  * dense per-layer allreduce loop            -> `method=None`
  * ``layerwise_compressed_comm``             -> ``granularity='layerwise'``
    (`CIFAR10/core.py:175-225`)
  * ``entiremodel_compressed_comm``           -> ``granularity='entiremodel'``
    (`CIFAR10/core.py:227-301`; the reference copy crashes if called —
    SURVEY.md §2.3 — ours works)
  * ``RandomKSparsifiedDDP`` error feedback   -> ``error_feedback=True``
    (`IMAGENET/training/sparsified_ddp.py:222,408-413`)

Instead of per-parameter autograd hooks driving NCCL buckets from C++
(`ddp.py:394-409`), the whole pipeline — compress, reduce, average — is traced
into the jitted train step under ``shard_map``; XLA's latency-hiding scheduler
overlaps the psums with remaining backward compute, which is what the
reference's reverse-order bucketing bought it by hand.

Two payload modes (SURVEY.md §2.3 item 6):

  * ``mode='simulate'`` — the paper's protocol: the compressed gradient is kept
    dense (zeros at dropped coordinates) and allreduced full-size.  Studies
    convergence, not bandwidth; bytes-on-wire are *accounted analytically*.
  * ``mode='wire'`` — genuinely sparse payloads (packed k values; see
    :mod:`tpu_compressed_dp.ops.wire`), the `RandomKSparsifiedDDP` equivalent.

Stateful compressors: every sync is ``sync(grads, ef, comp, key[, ok]) ->
(synced, new_ef, new_comp, stats)`` — ``comp`` is a persistent compressor
state pytree threaded through the jitted step alongside the EF residual
(``()`` for the stateless element-wise methods).

Step guard (``ok``): the optional keyword is the globally-voted finiteness
verdict from :mod:`tpu_compressed_dp.train.guard`.  When given, BOTH engines
(element-wise/wire and PowerSGD) gate themselves: local gradients are zeroed
on a bad step (every downstream collective stays finite — the wire scatter
paths have a documented finite-input precondition) and, critically, the
persistent EF residual and compressor state are held bitwise at their
pre-step values — a single poisoned gradient must not enter state that
replays across every future step.  The stats gain ``guard/nonfinite``
(1.0 = this step was vetoed).  ``ok=None`` (the default) is the exact
pre-guard behaviour.  The first occupant is
PowerSGD (``method='powersgd'``, :mod:`tpu_compressed_dp.ops.lowrank`),
whose warm-start ``Q`` factors live in ``TrainState.comp``, are sharded
like ``ef``, and round-trip through Orbax checkpoints; its payloads are
linear in the gradient, so it is the one compressor family whose wire form
always rides the psum ring rather than an all_gather.  Build the state
with :func:`init_comp_state`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from tpu_compressed_dp.obs import registry as obs_registry
from tpu_compressed_dp.obs import trace as obs_trace
from tpu_compressed_dp.ops import compressors, kernels

__all__ = ["CompressionConfig", "make_grad_sync", "make_grouped_grad_sync",
           "make_leaf_groups", "group_concat", "group_split", "init_ef_state",
           "init_comp_state", "init_comp_state_partitioned",
           "init_comp_state_grouped", "make_sharded_clip", "merge_stat_dicts",
           "wire_rides_psum", "wire_transport"]


def wire_transport(name: str, n: int, cfg: "CompressionConfig") -> str:
    """Which collective the method's WIRE form rides for an ``n``-element
    group (VERDICT r2 #2): ``'psum'`` | ``'allgather'`` | ``'sharded'`` |
    ``'hierarchical'`` — the single source of truth for the
    ``sent_bits_psum`` / ``sent_bits_allgather`` / ``sent_bits_alltoall``
    (and, hierarchical, the per-fabric ``sent_bits_ici`` /
    ``sent_bits_dcn``) split in BOTH sync engines.

    Dense and SHARED-seed Random-K psum-reduce a (packed) buffer — per-chip
    ring traffic ``2(W-1)/W x payload``; PowerSGD's P/Q factors are linear
    in the gradient and always psum; Block-Top-K keep-all groups fall back
    to a dense psum.  Every other method's payloads are worker-distinct
    (indices or quantizer scales differ): by default they ride an
    all_gather — per-chip traffic ``~(W-1) x payload``, i.e. ``O(W*k)``.
    ``cfg.transport='sharded'`` moves the index-carrying sparsifiers
    (:data:`~tpu_compressed_dp.ops.wire_sharded.SHARDED_METHODS`) onto the
    owner-sharded reduce instead: all_to_all route (``(W-1)/W x``) plus a
    shard-return all_gather — ``O(k + n/W)`` per chip.  Quantizers carry no
    indices to route and keep the all_gather regardless.  Per-rank-mask
    Random-K (simulate default, the unseeded CIFAR harness) ships
    worker-distinct indices too — all_gather, matching its own 64-bit
    accounting.  ``cfg.transport='hierarchical'`` applies to the same
    index-carrying sparsifiers: dense psum inside each ``dp_chips``-wide
    pod (ICI), re-compress the pod union, (value, index) exchange across
    the ``dp_pods`` axis (DCN) — per-chip DCN volume ``O(k + n/W_pods)``.
    """
    if name == "none" or (name == "randomk" and cfg.resolved_shared_mask):
        return "psum"
    if name == "powersgd":
        return "psum"
    if name == "blocktopk":
        kb = compressors.blocktopk_keep_blocks(n, cfg.ratio, cfg.block_size)
        if kb * cfg.block_size >= n:
            return "psum"
    if cfg.transport in ("sharded", "hierarchical"):
        from tpu_compressed_dp.ops.wire_sharded import SHARDED_METHODS

        if name in SHARDED_METHODS:
            return cfg.transport
    return "allgather"


def wire_rides_psum(name: str, n: int, cfg: "CompressionConfig") -> bool:
    """Back-compat predicate over :func:`wire_transport`."""
    return wire_transport(name, n, cfg) == "psum"


def _sharded_group_bits(name: str, n: int, world: int,
                        cfg: "CompressionConfig"):
    """Analytic ``(route_bits, return_bits)`` of the sharded wire form for
    an ``n``-element group — the per-method unit geometry feeding
    :func:`~tpu_compressed_dp.ops.wire_sharded.sharded_payload_bits` (whose
    result equals the wire engine's measured fp32 buffer bits, so simulate
    and wire accounting agree for the sharded transport too)."""
    from tpu_compressed_dp.ops import wire_sharded

    if name == "blocktopk":
        kb = compressors.blocktopk_keep_blocks(n, cfg.ratio, cfg.block_size)
        nb = -(-n // cfg.block_size)
        return wire_sharded.sharded_payload_bits(
            nb, kb, world, cfg.block_size,
            cfg.shard_route_factor, cfg.shard_return_factor)
    if name in ("thresholdv", "adaptive_threshold"):
        keep = max(1, int(round(cfg.wire_cap_ratio * n)))
    else:
        keep = compressors.topk_keep_count(n, cfg.ratio)
    return wire_sharded.sharded_payload_bits(
        n, keep, world, 1, cfg.shard_route_factor, cfg.shard_return_factor)


def _hier_group_bits(name: str, n: int, world: int,
                     cfg: "CompressionConfig"):
    """Analytic ``(ici_bits, dcn_route_bits, dcn_return_bits)`` of the
    hierarchical wire form for an ``n``-element group — feeds
    :func:`~tpu_compressed_dp.ops.wire_sharded.hier_payload_bits` (which
    equals the wire engine's measured fp32 buffer bits, keeping simulate
    and wire per-fabric accounting identical).  ``keep`` is element-granular
    here even for blocktopk: the pod union is packed per element, not per
    block."""
    from tpu_compressed_dp.ops import wire_sharded

    if name == "blocktopk":
        kb = compressors.blocktopk_keep_blocks(n, cfg.ratio, cfg.block_size)
        keep = min(kb * cfg.block_size, n)
    elif name in ("thresholdv", "adaptive_threshold"):
        keep = max(1, int(round(cfg.wire_cap_ratio * n)))
    else:
        keep = compressors.topk_keep_count(n, cfg.ratio)
    return wire_sharded.hier_payload_bits(
        n, keep, world, cfg.dp_pods,
        cfg.hier_route_factor_ici, cfg.hier_route_factor_dcn)


def make_partitioned_clip(leaf_axes):
    """Build ``clip_tree(tree, limit)`` clipping by the FULL-model L2 norm
    for gradient trees whose leaves are sharded over per-leaf model-axis
    subsets (``leaf_axes`` aligned with ``jax.tree.leaves`` order; ``()`` =
    replicated, already psum'd by shard_map AD, counts once).  Squared
    norms accumulate per signature and psum once per signature."""
    leaf_axes = [tuple(a) for a in leaf_axes]
    sigs = sorted(set(leaf_axes))

    def global_norm(tree):
        leaves = jax.tree.leaves(tree)
        total = jnp.zeros((), jnp.float32)
        for sig in sigs:
            sq = sum(jnp.sum(g.astype(jnp.float32) ** 2)
                     for g, a in zip(leaves, leaf_axes) if a == sig)
            if sig:
                sq = jax.lax.psum(sq, sig)
            total = total + sq
        return jnp.sqrt(total)

    def clip_tree(tree, limit):
        factor = jnp.minimum(1.0, limit / jnp.maximum(global_norm(tree), 1e-20))
        return jax.tree.map(lambda g: g * factor, tree)

    return clip_tree


def make_sharded_clip(is_sharded, shard_axis):
    """Binary convenience wrapper over :func:`make_partitioned_clip`."""
    axes = (shard_axis,) if isinstance(shard_axis, str) else tuple(shard_axis)
    return make_partitioned_clip([axes if s else () for s in is_sharded])


# Stats that are 0/1 diagnostics, identical across ranks (or min/max
# verdicts), NOT additive volumes: the partitioned sync must not psum them
# over model axes or sum them across signature groups.  Maps key -> the
# (cross-rank collective, cross-group combiner) pair.  Derived from the
# metric registry's declared reductions (obs/registry.py) so the engine's
# diagnostic table can never silently disagree with the declarations the
# conformance test enforces.
_DIAG_COLLECTIVES = {
    "min": (jax.lax.pmin, jnp.minimum),
    "max": (jax.lax.pmax, jnp.maximum),
}
_DIAG_STATS = {
    key: _DIAG_COLLECTIVES[red]
    for key, red in obs_registry.engine_diag_reductions().items()
}


def merge_stat_dicts(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    """Combine two engine stat dicts from disjoint slices of one sync
    (signature groups in the partitioned wrapper, chunks in the overlap
    driver): additive volumes sum; min/max diagnostics (``sync_agree``,
    ``guard/nonfinite``) combine with their registry-declared reduction,
    and survive when EITHER side reports them — a slice of diagnostic-free
    groups must not silence the other slice's divergence signal."""
    merged = {
        k: a.get(k, 0.0) + b.get(k, 0.0)
        for k in (set(a) | set(b)) - set(_DIAG_STATS)
    }
    for k, (_, combine) in _DIAG_STATS.items():
        vals = [c[k] for c in (a, b) if k in c]
        if vals:
            merged[k] = vals[0] if len(vals) == 1 else combine(*vals)
    return merged


def _with_guard(inner_sync):
    """Give a ``sync(grads, ef, comp, key)`` engine the optional step-guard
    gate (``ok`` = the globally-voted finiteness verdict,
    :func:`tpu_compressed_dp.train.guard.finite_vote`).

    On a vetoed step the engine's job is damage containment: the local
    gradients are replaced with zeros (so every collective — psum,
    all_gather, the sharded transport's scatter/all_to_all, whose index
    arithmetic has a documented finite-input precondition — computes on
    finite data), and the persistent EF residual and compressor state come
    back bitwise equal to their inputs instead of absorbing either the
    poison or the zeroed-gradient artifact (with EF on, a zero gradient
    would still rotate ``compress(ef)`` out of the residual).  The synced
    output is then compression noise the caller discards along with the
    whole update.
    """
    # lazy: a module-level `from tpu_compressed_dp.train.guard import ...`
    # would cycle (train/__init__ -> step -> this module); by factory time
    # everything is loaded
    from tpu_compressed_dp.train.guard import select_tree

    def sync(grads: Any, ef: Any, comp: Any, key: jax.Array,
             ok: Optional[jax.Array] = None):
        if ok is None:
            return inner_sync(grads, ef, comp, key)
        safe = jax.tree.map(lambda g: jnp.where(ok, g, jnp.zeros_like(g)),
                            grads)
        out, new_ef, new_comp, stats = inner_sync(safe, ef, comp, key)
        stats = dict(stats)
        stats["guard/nonfinite"] = (~ok).astype(jnp.float32)
        return out, select_tree(ok, new_ef, ef), \
            select_tree(ok, new_comp, comp), stats

    return sync


@dataclasses.dataclass(frozen=True)
class CompressionConfig:
    """Mirrors the reference CLI surface (`dawn.py:15-19`, `train_imagenet_nv.py`).

    method:        none | topk | blocktopk | randomk | thresholdv |
                   adaptive_threshold | terngrad | qsgd | powersgd
                   (reference spellings accepted; blocktopk is net-new —
                   contiguous-block Top-K by block L2 norm, the TPU-native
                   fast wire path, see :mod:`tpu_compressed_dp.ops.wire`;
                   powersgd is net-new too — warm-started rank-``rank``
                   low-rank factorisation whose P/Q payloads ride the psum
                   ring, see :mod:`tpu_compressed_dp.ops.lowrank`.  PowerSGD
                   is stateful: build ``TrainState.comp`` with
                   :func:`init_comp_state`)
    rank:          r for powersgd (default 4); per-group payload is
                   ``r·(m + n/m)`` fp32 words for an ``n``-element group
                   reshaped to ``(m, n/m)``, ``m ~ sqrt(n)``
    granularity:   'layerwise' (one op + one reduce per parameter tensor),
                   'entiremodel' (flatten the whole gradient, one op + reduce),
                   or 'bucketed' (contiguous parameter tensors concatenated
                   into <= bucket_mb groups, one op + reduce per bucket — the
                   reference DDP's 25 MB bucketing, `ddp.py:188,238-241`,
                   computed statically at trace time).  Recommendation for
                   layer-wise semantics at scale: 'bucketed' — single-chip
                   step time matches 'layerwise' (VGG-16: 42.3 vs 42.7 ms,
                   benchmarks/vgg16_bucketed_r2.tsv) while cutting the
                   collective count ~5x (32 -> 7 on VGG-16, 161 -> 5 on
                   ResNet-50), which is what matters once psums ride real
                   interconnect; 'entiremodel' pays extra whole-model
                   concat/split copies and is the slowest single-chip.
    bucket_mb:     bucket capacity for granularity='bucketed' (default 25,
                   matching the reference)
    mode:          'simulate' (dense payload, paper protocol) or 'wire'
                   (packed sparse payload)
    transport:     'allgather' (flat combine: every worker's (value, index)
                   pairs visit every chip, O(W*k) per chip) or 'sharded'
                   (owner-sharded sparse reduce, ops/wire_sharded.py:
                   all_to_all route to contiguous shard owners, owner
                   scatter-add, shard-return all_gather — O(k + n/W) per
                   chip).  Applies to the index-carrying sparsifiers
                   (topk/blocktopk/thresholdv/adaptive_threshold); psum
                   riders and the index-free quantizers are unaffected.
                   Capacity knobs: shard_route_factor/shard_return_factor
                   (x k/W slots); clips fold into EF / comm/shard_overflow.
    ratio:         K for topk/randomk (`--ratio`, default 0.5)
    threshold:     V for thresholdv (`--threshold`, default 1e-3)
    qstates:       quantisation states for qsgd (`--qstates`, default 255)
    error_feedback: keep the dropped residual and re-add next step
                   (`sparsified_ddp.py:408-413`); the reference only has this
                   in RandomKSparsifiedDDP — here it composes with any method.
                   NB: EF defers ~1/k steps of gradient mass per coordinate;
                   under momentum that delay diverges at high peak lr — for
                   the reference's own update rule too (torch repro in
                   tools/ef_bisect.py; results in
                   benchmarks/ef_momentum_bisect_r2.txt).  Stabilise with the
                   train step's ``clip_norm`` (DGC-style local-gradient
                   clipping) or momentum=0.
    shared_mask:   random masks identical across workers (shared-seed trick,
                   `sparsified_ddp.py:164`).  Defaults: False for 'simulate'
                   (the unseeded CIFAR harness draws per-rank masks), True is
                   required for 'wire' randomk so indices line up.
    check_sync:    debug guard (the ``check_reduction`` analog,
                   `ddp.py:312-327`): wire-mode Random-K verifies every
                   worker selected identical indices before the packed psum
                   (misalignment would silently corrupt gradients) and
                   reports ``comm/sync_agree`` (1.0 = agreement).
    """

    method: Optional[str] = None
    granularity: str = "layerwise"
    mode: str = "simulate"
    # sync_overlap: decompose the gradient sync into up to this many
    # independent chunk syncs issued in reverse-topological order so XLA's
    # latency-hiding scheduler can interleave each chunk's collective with
    # the remaining backward (and, in train/step.py, the other chunks'
    # optimizer-update slices).  1 = the single-dispatch behaviour; K > 1
    # routes through parallel/overlap.py.  Chunk boundaries always align
    # with the granularity's reduction-group boundaries, so per-group
    # compression, RNG and transport are BITWISE unchanged — only the
    # dependency/schedule structure differs (tests/test_overlap.py pins
    # this).  Evidence: tools/overlap_evidence.py / benchmarks/.
    sync_overlap: int = 1
    # transport: which collective carries index-carrying wire payloads.
    # 'allgather' — every worker's (value, index) pairs visit every chip:
    # per-chip volume/decode O(W*k), fine at small W.  'sharded' — the
    # owner-sharded sparse reduce (ops/wire_sharded.py): pairs route to
    # contiguous shard owners via all_to_all, owners reduce, shards return
    # via one all_gather — O(k + n/W) per chip, the scalable regime
    # (OKTopk, PAPERS.md).  'hierarchical' — two-level reduce over the
    # dp_pods x dp_chips virtual mesh (below): dense psum along the fast
    # intra-pod ICI axis, sparse (value, index) exchange across the slow
    # DCN axis only — per-chip DCN volume O(k + n/W_pods), billed per
    # fabric (sent_bits_ici / sent_bits_dcn).  Both apply to topk/
    # blocktopk/thresholdv/adaptive_threshold; psum-riding methods and the
    # index-free quantizers are unaffected (see wire_transport).
    transport: str = "allgather"
    ratio: float = 0.5
    threshold: float = 1e-3
    qstates: int = 255
    # powersgd: rank of the low-rank approximation (r in Vogels et al.);
    # wire cost per group is r*(m + n/m) fp32 words, always on the psum ring
    rank: int = 4
    error_feedback: bool = False
    shared_mask: Optional[bool] = None
    check_sync: bool = False
    block_size: int = 256  # blocktopk: elements per contiguous block
    bucket_mb: float = 25.0  # bucketed: capacity per bucket (ddp.py:188)
    # wire thresholdv/adaptive_threshold: transport capacity as a fraction of
    # elements (survivor counts are data-dependent; the wire buffer is not).
    # Overflowing survivors stay in the EF residual (or are dropped, EF off);
    # comm/threshold_overflow reports the clip count.
    wire_cap_ratio: float = 0.05
    # sharded transport capacity factors, in units of the per-shard fair
    # share k/W.  Route: per-destination bucket = route_factor * k/W slots
    # (uniform-spread assumption; skew clips into EF / shard_overflow).
    # Return: sparse-union buffer = return_factor * k/W units (worker
    # selections overlap — the premise compression rests on; the buffer is
    # clamped to its lossless bound W*cap_dest and to the shard size, and
    # the dense shard returns instead whenever that bills no bigger).
    shard_route_factor: float = 1.25
    shard_return_factor: float = 1.25
    # hierarchical transport: the W data-parallel workers form a virtual
    # dp_pods x dp_chips mesh (rank g -> pod g // dp_chips, chip g %
    # dp_chips; world must divide evenly, checked at trace time).  The
    # intra-pod ICI axis carries a dense psum of each worker's
    # compressed-dense contribution; the pod-reduced gradient is then
    # re-compressed (packed nonzero union, capacity hier_route_factor_ici
    # x keep, sliced one slab per chip) and only (value, index) pairs
    # cross the DCN axis via the sharded bucket-route machinery with
    # capacity factor hier_route_factor_dcn.  Clips on either hop refund
    # exactly into EF (comm/shard_overflow invariant).  dp_pods=1 keeps
    # the classifier/billing surface but degenerates to one dense ICI
    # psum (no DCN traffic at all).
    dp_pods: int = 1
    hier_route_factor_ici: float = 1.25
    hier_route_factor_dcn: float = 1.25
    # terngrad: elements per scale chunk (0 = single global max; -1 = auto).
    # A single max over an entire-model gradient drives keep-probabilities
    # toward zero and the estimator variance unbounded (the r2 NaN row); one
    # max per ~2M elements keeps entire-model granularity at layer-wise-like
    # statistics.  Auto resolves to 0 for layerwise (exact reference
    # per-tensor max semantics on EVERY leaf, LM embedding included — a fixed
    # 2M default silently diverged on >2M-element leaves, ADVICE r3) and to
    # 2M for entiremodel/bucketed, where the reference has no working
    # behavior to match (its path crashed, SURVEY.md §2.3.2).
    terngrad_chunk: int = -1

    def __post_init__(self):
        if self.rank < 1:
            raise ValueError(f"rank must be >= 1, got {self.rank}")
        if self.sync_overlap < 1:
            raise ValueError(
                f"sync_overlap must be >= 1, got {self.sync_overlap} "
                "(1 = single-dispatch sync; K > 1 = chunk-pipelined)")
        if self.granularity not in ("layerwise", "entiremodel", "bucketed"):
            raise ValueError(
                f"granularity must be layerwise|entiremodel|bucketed, got {self.granularity!r}")
        if self.bucket_mb <= 0:
            raise ValueError(f"bucket_mb must be positive, got {self.bucket_mb}")
        if self.mode not in ("simulate", "wire"):
            raise ValueError(f"mode must be simulate|wire, got {self.mode!r}")
        if self.transport not in ("allgather", "sharded", "hierarchical"):
            raise ValueError(
                "transport must be allgather|sharded|hierarchical, "
                f"got {self.transport!r}")
        if self.dp_pods < 1:
            raise ValueError(
                f"dp_pods must be >= 1, got {self.dp_pods} (the DCN axis of "
                "the virtual dp_pods x dp_chips mesh; world must divide "
                "evenly, checked when the mesh size is known)")
        if self.hier_route_factor_ici <= 0 or self.hier_route_factor_dcn <= 0:
            raise ValueError(
                "hier_route_factor_ici/hier_route_factor_dcn must be "
                f"positive, got {self.hier_route_factor_ici}/"
                f"{self.hier_route_factor_dcn} (they size the pod-union "
                "recompression and inter-pod route buffers)")
        if self.shard_route_factor <= 0 or self.shard_return_factor <= 0:
            raise ValueError(
                "shard_route_factor/shard_return_factor must be positive, "
                f"got {self.shard_route_factor}/{self.shard_return_factor} "
                "(they scale the fixed per-destination and return-union "
                "buffer capacities; 0 would allocate no transport at all)")
        if not (0.0 < self.wire_cap_ratio <= 1.0):
            raise ValueError(
                f"wire_cap_ratio must be in (0, 1], got {self.wire_cap_ratio} "
                "(0/negative would degrade to a 1-element transport buffer; "
                ">1 allocates a buffer larger than the tensor)")

    @property
    def resolved_shared_mask(self) -> bool:
        if self.shared_mask is not None:
            return self.shared_mask
        return self.mode == "wire"

    @property
    def resolved_terngrad_chunk(self) -> int:
        if self.terngrad_chunk >= 0:
            return self.terngrad_chunk
        return 0 if self.granularity == "layerwise" else 1 << 21


def init_ef_state(grads_like: Any, cfg: CompressionConfig, num_devices: Optional[int] = None) -> Any:
    """Zero error-feedback residual pytree (empty tuple when EF is off).

    The residual is per-worker state (the reference keeps one ``epsilon`` per
    rank, `sparsified_ddp.py:222-223`): pass ``num_devices`` to get leaves with
    a leading device axis, to be sharded over the data mesh axis.  Unlike the
    reference, this residual is part of the train state and hence checkpointed
    (SURVEY.md §5 checkpoint gap).
    """
    if not cfg.error_feedback:
        return ()
    if num_devices is None:
        # fp32 regardless of gradient dtype: sub-epsilon dropped mass must
        # accumulate across steps, not round away (see group_split)
        return jax.tree.map(
            lambda g: jnp.zeros(g.shape, dtype=jnp.float32), grads_like)
    return jax.tree.map(
        lambda g: jnp.zeros((num_devices,) + g.shape, dtype=jnp.float32), grads_like
    )


def init_comp_state(grads_like: Any, cfg: CompressionConfig,
                    num_devices: Optional[int] = None, *, seed: int = 0) -> Any:
    """Persistent compressor-state pytree (``()`` for stateless methods).

    PowerSGD: one fp32 warm-start ``Q`` of shape ``[n2, r]`` per compressed
    leaf group (the same static grouping the sync uses), keyed ``'q<gi>'``.
    Drawn from a fixed PRNG so every worker holds the IDENTICAL warm start —
    the P/Q psums average factors, which is only meaningful when all workers
    iterate in the same basis.  Dense-fallback groups (factors would cost >=
    the dense vector: biases, norm scales) carry no state.

    Like :func:`init_ef_state`, pass ``num_devices`` to get leaves with a
    leading device axis, sharded over the data mesh axis and checkpointed as
    ``TrainState.comp``.
    """
    if compressors.canonical_name(cfg.method) != "powersgd":
        return ()
    from tpu_compressed_dp.ops import lowrank

    leaves = jax.tree.leaves(grads_like)
    groups = make_leaf_groups(
        [g.size * g.dtype.itemsize for g in leaves],
        cfg.granularity, cfg.bucket_mb * BUCKET_MB)
    key = jax.random.key(seed)
    state = {}
    for gi, idxs in enumerate(groups):
        n = sum(leaves[i].size for i in idxs)
        q = lowrank.init_group_state(n, cfg.rank, jax.random.fold_in(key, gi))
        if q is None:
            continue
        if num_devices is not None:
            q = jnp.tile(q[None], (num_devices, 1, 1))
        state[f"q{gi}"] = q
    return state if state else ()


def init_comp_state_partitioned(grads_like: Any, cfg: CompressionConfig,
                                leaf_axes, num_devices: Optional[int] = None,
                                *, seed: int = 0) -> Any:
    """Compressor state for :func:`make_partitioned_grad_sync`: one
    :func:`init_comp_state` sub-pytree per replication signature, keyed
    ``'sig<i>'`` in the same sorted-signature order the partitioned sync
    iterates (``()`` when every signature is stateless)."""
    if compressors.canonical_name(cfg.method) != "powersgd":
        return ()
    leaf_axes = [tuple(a) for a in leaf_axes]
    sigs = sorted(set(leaf_axes))
    leaves = jax.tree.leaves(grads_like)
    state = {}
    for gi, sig in enumerate(sigs):
        sub = init_comp_state(
            [l for l, a in zip(leaves, leaf_axes) if a == sig], cfg,
            num_devices, seed=seed + gi)
        if sub != ():
            state[f"sig{gi}"] = sub
    return state if state else ()


def init_comp_state_grouped(grads_like: Any, cfg: CompressionConfig,
                            is_sharded, shard_axis,
                            num_devices: Optional[int] = None, *,
                            seed: int = 0) -> Any:
    """Binary convenience wrapper over :func:`init_comp_state_partitioned`
    (mirrors :func:`make_grouped_grad_sync`)."""
    axes = (shard_axis,) if isinstance(shard_axis, str) else tuple(shard_axis)
    return init_comp_state_partitioned(
        grads_like, cfg, [axes if s else () for s in is_sharded],
        num_devices, seed=seed)


# The reference's bucket unit is MiB: ``bucket_bytes_cap = bucket_cap_mb *
# 1024 * 1024`` (`ddp.py:182,188`).
BUCKET_MB = 1024.0 * 1024.0


def make_leaf_groups(byte_sizes, granularity: str, bucket_bytes: float):
    """Partition leaf indices into reduction groups, statically at trace time.

    'layerwise' -> one leaf per group (one collective per parameter,
    `core.py:176`); 'entiremodel' -> every leaf in one group (`core.py:229`);
    'bucketed' -> contiguous leaves greedily packed into <= ``bucket_bytes``
    groups by actual byte size (``size * dtype.itemsize``, like the reference
    DDP's ``_dist_bucket_tensors(..., 25MB)`` C++ bucketing,
    `ddp.py:188,238`); an oversized single leaf gets its own bucket.
    """
    n = len(byte_sizes)
    if granularity == "layerwise":
        return [[i] for i in range(n)]
    if granularity == "entiremodel":
        return [list(range(n))] if n else []
    groups, cur, cur_bytes = [], [], 0.0
    for i, b in enumerate(byte_sizes):
        if cur and cur_bytes + b > bucket_bytes:
            groups.append(cur)
            cur, cur_bytes = [], 0.0
        cur.append(i)
        cur_bytes += b
    if cur:
        groups.append(cur)
    return groups


def group_concat(leaves, idxs):
    """Flatten-and-concatenate a reduction group's leaves (single-leaf groups
    skip the concat)."""
    flats = [leaves[i].reshape(-1) for i in idxs]
    return flats[0] if len(flats) == 1 else jnp.concatenate(flats)


def group_split(flat, leaves, idxs, out, dtype=None):
    """Slice a group's flat result back into per-leaf shapes, writing into
    ``out`` at the original leaf positions.

    ``group_concat`` of a mixed-dtype group (bf16 + fp32 leaves) promotes to
    a common dtype; each output leaf is cast back to the corresponding input
    leaf's dtype — or to ``dtype`` when given (the EF residual is fp32 by
    design regardless of gradient precision: sub-epsilon dropped mass must
    accumulate, not round away)."""
    off = 0
    for i in idxs:
        n = leaves[i].size
        out[i] = (flat[off:off + n].reshape(leaves[i].shape)
                  .astype(dtype or leaves[i].dtype))
        off += n


def make_grad_sync(cfg: CompressionConfig, axis_name: str = "data", *,
                   group_offset: int = 0, chunking: bool = True):
    """Build ``sync(grads, ef, comp, key[, ok]) -> (synced, new_ef, new_comp,
    stats)`` (``ok`` is the step guard's finiteness verdict — see
    :func:`_with_guard`; omit it for ungated behaviour).

    Must be called *inside* ``shard_map`` (uses ``lax.psum`` / ``axis_index``
    over ``axis_name``).  ``grads`` are the local worker's gradients at the
    same scale the reference compresses (see train/step.py); the return value
    is the world-averaged gradient, matching `core.py:217-222`.

    ``cfg.sync_overlap > 1`` dispatches to the chunk-pipelined driver
    (:func:`tpu_compressed_dp.parallel.overlap.make_chunked_grad_sync`),
    which calls back here once per chunk with ``chunking=False`` and the
    chunk's global ``group_offset``.  The offset shifts the per-group RNG
    derivation (:func:`~tpu_compressed_dp.ops.compressors.leaf_key`) and the
    PowerSGD warm-start keys (``q<gi>``) so a chunk's groups compute
    bitwise-identically to the same groups in a single whole-tree sync.

    ``comp`` is the persistent compressor-state pytree
    (:func:`init_comp_state`): the PowerSGD warm-start factors, threaded in
    and out of the jitted step like the EF residual.  Stateless methods take
    and return ``()`` unchanged.

    ``comm_stats`` reports per-step communication analytically (SURVEY.md §5:
    the reference measured NIC bytes via /proc/net/dev; on TPU the payload is
    known at trace time for fixed-k methods and counted at run time for
    threshold methods): ``sent_elems`` is the element count the wire
    representation would carry, ``sent_bits`` its analytic bit volume
    (quantizers send every element at 2-9 bits), ``dense_elems`` the
    uncompressed size.
    """
    if chunking and cfg.sync_overlap > 1:
        if group_offset:
            raise ValueError("group_offset is only meaningful for the "
                             "per-chunk engines (chunking=False)")
        from tpu_compressed_dp.parallel import overlap

        return overlap.make_chunked_grad_sync(cfg, axis_name)
    comp = compressors.get_compressor(
        cfg.method, ratio=cfg.ratio, threshold=cfg.threshold,
        qstates=cfg.qstates, block_size=cfg.block_size,
        terngrad_chunk=cfg.resolved_terngrad_chunk, rank=cfg.rank,
    )
    if comp.name == "powersgd":
        # stateful warm-started path; the factors ARE the wire form, so
        # simulate and wire modes share it
        return _with_guard(
            _make_powersgd_sync(cfg, axis_name, group_offset=group_offset))
    if cfg.mode == "wire" and comp.name != "none":
        # Dense (method=None) has no sparse representation — the simulate
        # path's full-size psum IS its wire format, so fall through.
        from tpu_compressed_dp.ops import wire

        wire_sync = wire.make_wire_grad_sync(cfg, axis_name,
                                             group_offset=group_offset)

        def sync_wire(grads: Any, ef: Any, comp_state: Any, key: jax.Array):
            out, new_ef, stats = wire_sync(grads, ef, key)
            return out, new_ef, comp_state, stats

        return _with_guard(sync_wire)
    per_worker_rng = not cfg.resolved_shared_mask
    bits_per_elem = compressors.payload_bits_per_elem(
        comp.name, qstates=cfg.qstates, shared_mask=cfg.resolved_shared_mask,
        block_size=cfg.block_size,
    )

    def sent_count(comp_flat: jax.Array) -> jax.Array:
        # Sparsifiers transmit only surviving coordinates; quantizers
        # (terngrad/qsgd) and identity carry every element — at a reduced
        # per-element width accounted by `bits_per_elem`.
        if not comp.is_sparsifier:
            return jnp.asarray(float(comp_flat.shape[0]), jnp.float32)
        if comp.name == "randomk":
            # bill the keep count, not count_nonzero: the wire form transports
            # exactly `keep` value slots (indices implied by the shared seed,
            # sparsified_ddp.py:412) — a selected-but-zero coordinate still
            # travels.  Keeps simulate and wire accounting identical.
            return jnp.asarray(
                float(compressors.randomk_keep_count(
                    comp_flat.shape[0], cfg.ratio)), jnp.float32)
        if comp.name == "blocktopk":
            # whole blocks travel (zeros inside a selected block included);
            # capped at n — the wire path psums small/keep-all leaves dense
            kb = compressors.blocktopk_keep_blocks(
                comp_flat.shape[0], cfg.ratio, cfg.block_size)
            return jnp.asarray(
                float(min(kb * cfg.block_size, comp_flat.shape[0])), jnp.float32)
        return jnp.count_nonzero(comp_flat).astype(jnp.float32)

    def sent_bits(comp_flat: jax.Array, sent: jax.Array) -> jax.Array:
        # blocktopk's keep-all/small leaves psum dense on the wire — no
        # block indices travel — so bill them 32 bits/elem, matching the
        # wire engine's measured payload (stats agree across modes for the
        # sparsifiers; quantizer wire bits additionally carry scale/padding
        # overhead this analytic projection amortises away)
        if comp.name == "blocktopk":
            n = comp_flat.shape[0]
            kb = compressors.blocktopk_keep_blocks(n, cfg.ratio, cfg.block_size)
            width = 32.0 if kb * cfg.block_size >= n else bits_per_elem
            return sent * width
        return sent * bits_per_elem

    def compress_flat(flat: jax.Array, key: jax.Array, index: int) -> jax.Array:
        # index is the GLOBAL group index (group_offset shifts a chunk's
        # local indices), so chunked and whole-tree syncs draw identical
        # per-group randomness
        k = compressors.leaf_key(key, index + group_offset,
                                 per_worker_rng and comp.needs_rng, axis_name)
        return comp.fn(flat, k)

    def sync(grads: Any, ef: Any, comp_state: Any, key: jax.Array
             ) -> Tuple[Any, Any, Any, Dict[str, jax.Array]]:
        world = jax.lax.psum(1, axis_name)
        leaves, treedef = jax.tree.flatten(grads)
        use_ef = cfg.error_feedback
        ef_leaves = jax.tree.leaves(ef) if use_ef else [None] * len(leaves)

        # One operator application + one collective per group: layerwise =
        # per parameter tensor (`core.py:176`), entiremodel = the whole
        # flattened gradient (`core.py:229`), bucketed = the reference DDP's
        # static 25 MB buckets.  Per-group psums are left unfused; XLA
        # coalesces/schedules them.
        groups = make_leaf_groups(
            [g.size * g.dtype.itemsize for g in leaves],
            cfg.granularity, cfg.bucket_mb * BUCKET_MB)
        out_leaves = [None] * len(leaves)
        new_ef_leaves = [None] * len(leaves)
        sent_total = jnp.asarray(0.0, jnp.float32)
        bits_total = jnp.asarray(0.0, jnp.float32)
        bits_psum = jnp.asarray(0.0, jnp.float32)
        bits_ag = jnp.asarray(0.0, jnp.float32)
        bits_a2a = jnp.asarray(0.0, jnp.float32)
        bits_ici = jnp.asarray(0.0, jnp.float32)
        bits_dcn = jnp.asarray(0.0, jnp.float32)
        bits_dcn_route = jnp.asarray(0.0, jnp.float32)
        dense_total = 0.0
        for gi, idxs in enumerate(groups):
            flat = group_concat(leaves, idxs)
            with obs_trace.phase("ef"):
                acc = flat + group_concat(ef_leaves, idxs) if use_ef else flat
            n_g = flat.shape[0]
            with obs_trace.phase("compress"):
                # fused epilogue: threshold-mask + compress + residual +
                # nonzero count in ONE pass over the accumulated gradient
                # (pallas_call boundaries block XLA from fusing the
                # where/subtract/count chain around the threshold kernel).
                # fp32-gated so the psum payload dtype matches the unfused
                # path.  Every |g| >= t selection rides the same kernel:
                # top-k (histogram threshold), threshold-V (the static V),
                # adaptive (2|g| >= max ⟺ |g| >= max/2, exact in binary fp).
                fuse_t = None
                if acc.dtype == jnp.float32 and kernels.use_fused_sparsify(n_g):
                    if comp.name == "topk":
                        keep = compressors.topk_keep_count(n_g, cfg.ratio)
                        fuse_t = kernels.topk_threshold(jnp.abs(acc), keep)
                    elif comp.name == "thresholdv":
                        fuse_t = jnp.float32(cfg.threshold)
                    elif comp.name == "adaptive_threshold":
                        fuse_t = 0.5 * jnp.max(jnp.abs(acc))
                if fuse_t is not None:
                    comp_flat, new_ef_flat, group_sent = kernels.fused_sparsify(
                        acc, fuse_t, want_ef=use_ef)
                    group_bits = group_sent * bits_per_elem
                else:
                    comp_flat = compress_flat(acc, key, gi)
                    new_ef_flat = acc - comp_flat if use_ef else None
                    group_sent = sent_count(comp_flat)
                    group_bits = sent_bits(comp_flat, group_sent)
            with obs_trace.phase("reduce"):
                reduced = jax.lax.psum(comp_flat, axis_name) / world
            with obs_trace.phase("return"):
                group_split(reduced, leaves, idxs, out_leaves)
                if use_ef:
                    group_split(new_ef_flat, leaves, idxs, new_ef_leaves,
                                dtype=jnp.float32)
            transport = wire_transport(comp.name, n_g, cfg)
            if transport == "sharded" and world > 1:
                # counterfactual like the rest of simulate billing: bill the
                # fixed-capacity route/return buffers the sharded wire form
                # WOULD move (static, like the wire engine's measured bits).
                # W=1 matches the wire engine's degradation to the allgather
                # combine (below), keeping the two engines' accounting equal.
                route_b, ret_b = _sharded_group_bits(comp.name, n_g, world, cfg)
                group_bits = jnp.asarray(route_b + ret_b, jnp.float32)
                bits_a2a = bits_a2a + route_b
                bits_ag = bits_ag + ret_b
            elif transport == "hierarchical" and world > 1:
                # per-FABRIC counterfactual: the flat collective-kind buckets
                # stay whole-world-only (their (W-1)/W arithmetic would lie
                # about grouped collectives)
                ici_b, rt_b, ret_b = _hier_group_bits(comp.name, n_g, world,
                                                      cfg)
                group_bits = jnp.asarray(ici_b + rt_b + ret_b, jnp.float32)
                bits_ici = bits_ici + ici_b
                bits_dcn = bits_dcn + rt_b + ret_b
                bits_dcn_route = bits_dcn_route + rt_b
            elif transport == "psum":
                bits_psum = bits_psum + group_bits
            else:
                bits_ag = bits_ag + group_bits
            sent_total = sent_total + group_sent
            bits_total = bits_total + group_bits
            dense_total += float(n_g)

        out = jax.tree.unflatten(treedef, out_leaves)
        new_ef = jax.tree.unflatten(treedef, new_ef_leaves) if use_ef else ()
        stats = {
            "sent_elems": sent_total,
            "sent_bits": bits_total,
            "sent_bits_psum": bits_psum,
            "sent_bits_allgather": bits_ag,
            "sent_bits_alltoall": bits_a2a,
            "sent_bits_ici": bits_ici,
            "sent_bits_dcn": bits_dcn,
            "sent_bits_dcn_route": bits_dcn_route,
            "dense_elems": jnp.asarray(dense_total, jnp.float32),
            "num_collectives": jnp.asarray(float(len(groups)), jnp.float32),
        }
        return out, new_ef, comp_state, stats

    return _with_guard(sync)


def _make_powersgd_sync(cfg: CompressionConfig, axis_name, *,
                        group_offset: int = 0):
    """The stateful PowerSGD engine behind :func:`make_grad_sync`.

    Per group: one warm-started power-iteration step against the persistent
    ``Q`` (``comp['q<gi>']``), two psums (``P`` then ``Q``), reconstruct the
    worker-mean low-rank gradient, fold the local deviation into the EF
    residual.  Groups whose factors would cost >= dense psum the full vector
    instead (exact; no state).  Every payload rides the psum ring —
    ``sent_bits_allgather`` is structurally zero for this method.

    ``check_sync`` (the ``check_reduction`` analog): the factor psums are
    only meaningful when every worker iterates in the SAME basis, so the
    guard verifies the warm-start ``Q`` agrees bitwise across workers before
    compressing and reports ``comm/sync_agree`` (1.0 = agreement) — a
    diverged warm start (e.g. mis-sharded restore) would otherwise corrupt
    gradients as silently as misaligned Random-K indices.
    """
    from tpu_compressed_dp.ops import lowrank

    if not cfg.error_feedback:
        # the rank-r projection is biased and the residual carries real
        # gradient mass every step (unlike the unbiased quantizers);
        # training with it discarded silently underperforms — Vogels et al.
        # always run PowerSGD with EF.  Legitimate EF-off uses exist
        # (linearity analysis, payload benchmarking), hence a warning, not
        # an error.
        import warnings

        warnings.warn(
            "method='powersgd' without error_feedback=True discards the "
            "low-rank residual every step; training quality degrades "
            "silently — enable EF (Vogels et al. always do)",
            stacklevel=2)

    def sync(grads: Any, ef: Any, comp_state: Any, key: jax.Array
             ) -> Tuple[Any, Any, Any, Dict[str, jax.Array]]:
        world = jax.lax.psum(1, axis_name)
        leaves, treedef = jax.tree.flatten(grads)
        use_ef = cfg.error_feedback
        ef_leaves = jax.tree.leaves(ef) if use_ef else [None] * len(leaves)
        groups = make_leaf_groups(
            [g.size * g.dtype.itemsize for g in leaves],
            cfg.granularity, cfg.bucket_mb * BUCKET_MB)
        out_leaves = [None] * len(leaves)
        new_ef_leaves = [None] * len(leaves)
        new_comp = {}
        sent_total = 0.0
        bits_total = 0.0
        n_coll = 0
        dense_total = 0.0
        agrees = []
        for gi, idxs in enumerate(groups):
            flat = group_concat(leaves, idxs)
            with obs_trace.phase("ef"):
                acc = flat + group_concat(ef_leaves, idxs) if use_ef else flat
                acc = acc.astype(jnp.float32)
            n_g = flat.shape[0]
            if lowrank.powersgd_dims(n_g, cfg.rank) is None:
                # factors would cost >= the dense vector: psum dense (exact)
                with obs_trace.phase("reduce"):
                    recon = jax.lax.psum(acc, axis_name) / world
                new_ef_flat = jnp.zeros_like(acc) if use_ef else None
                group_sent, group_bits = float(n_g), 32.0 * n_g
                n_coll += 1
            else:
                qk = f"q{gi + group_offset}"  # global key: chunk-invariant
                if not isinstance(comp_state, dict) or qk not in comp_state:
                    raise ValueError(
                        f"powersgd sync needs warm-start state {qk!r}; build "
                        "TrainState.comp with init_comp_state(grads_like, "
                        "cfg[, num_devices]) for this gradient tree")
                q_in = comp_state[qk]
                if cfg.check_sync:
                    # pmax/pmin, not psum/world: summing W identical fp32
                    # values is only exact when the reduction stays on
                    # power-of-two partials (odd-count partial sums round),
                    # so a mean-based bitwise compare false-alarms; max==min
                    # is order-free and exact
                    spread = (jax.lax.pmax(q_in, axis_name)
                              - jax.lax.pmin(q_in, axis_name))
                    agrees.append(
                        (jnp.max(jnp.abs(spread)) == 0.0).astype(jnp.float32))
                # the low-rank factor iteration interleaves compression
                # (matmuls against Q) with its two psums — one scope covers
                # the compress+reduce pair (xprof still splits the psums out
                # by op name inside it)
                with obs_trace.phase("compress"):
                    recon, q_new, group_sent, group_bits = (
                        lowrank.powersgd_group_sync(
                            acc, q_in, cfg.rank, axis_name, world))
                new_comp[qk] = q_new
                new_ef_flat = acc - recon if use_ef else None
                n_coll += 2  # P-psum + Q-psum
            with obs_trace.phase("return"):
                group_split(recon, leaves, idxs, out_leaves)
                if use_ef:
                    group_split(new_ef_flat, leaves, idxs, new_ef_leaves,
                                dtype=jnp.float32)
            sent_total += group_sent
            bits_total += group_bits
            dense_total += float(n_g)

        out = jax.tree.unflatten(treedef, out_leaves)
        new_ef = jax.tree.unflatten(treedef, new_ef_leaves) if use_ef else ()
        stats = {
            "sent_elems": jnp.asarray(sent_total, jnp.float32),
            "sent_bits": jnp.asarray(bits_total, jnp.float32),
            "sent_bits_psum": jnp.asarray(bits_total, jnp.float32),
            "sent_bits_allgather": jnp.asarray(0.0, jnp.float32),
            "sent_bits_alltoall": jnp.asarray(0.0, jnp.float32),
            "sent_bits_ici": jnp.asarray(0.0, jnp.float32),
            "sent_bits_dcn": jnp.asarray(0.0, jnp.float32),
            "sent_bits_dcn_route": jnp.asarray(0.0, jnp.float32),
            "dense_elems": jnp.asarray(dense_total, jnp.float32),
            "num_collectives": jnp.asarray(float(n_coll), jnp.float32),
        }
        if agrees:
            stats["sync_agree"] = jnp.min(jnp.stack(agrees))
        return out, new_ef, new_comp if new_comp else (), stats

    return sync


def make_partitioned_grad_sync(cfg: CompressionConfig, sync_axes,
                               leaf_axes) -> Any:
    """Compressed sync for gradient trees whose leaves are sharded over
    different subsets of model axes (tensor / pipeline parallelism, and
    their composition).

    Compression masks are data-dependent, so flattening leaves with
    DIFFERENT replication signatures together would give ranks that share
    one leaf but not another different masks over the shared sections and
    silently de-synchronise replicated parameters.  ``leaf_axes`` — aligned
    with ``jax.tree.leaves`` order — gives each leaf the tuple of model
    axes it is sharded over (``()`` = fully replicated); leaves sync in one
    group PER SIGNATURE: within a group every rank pair that shares the
    group's data either shares all of it (identical inputs -> identical
    masks) or none (independent shards).  Comm stats report model-wide
    totals: each group's per-rank stats psum over exactly its signature's
    axes.

    Compressor state is per signature: a ``{'sig<i>': sub}`` dict in the
    sorted-signature order (:func:`init_comp_state_partitioned`), ``()``
    when stateless.
    """
    base_sync = make_grad_sync(cfg, axis_name=sync_axes)
    leaf_axes = [tuple(a) for a in leaf_axes]
    sigs = sorted(set(leaf_axes))  # deterministic group order
    sig_of = {s: i for i, s in enumerate(sigs)}
    group_of = [sig_of[a] for a in leaf_axes]

    def split(tree):
        leaves = jax.tree.leaves(tree)
        return [[l for l, g in zip(leaves, group_of) if g == gi]
                for gi in range(len(sigs))]

    def merge(like, groups):
        its = [iter(g) for g in groups]
        leaves = [next(its[g]) for g in group_of]
        return jax.tree.unflatten(jax.tree.structure(like), leaves)

    def sync(grads, ef, comp, key, ok=None):
        use_ef = cfg.error_feedback
        g_groups = split(grads)
        e_groups = split(ef) if use_ef else [() for _ in sigs]
        keys = jax.random.split(key, len(sigs))
        out_g, out_e, comm = [], [], None
        new_comp = {}
        for gi, sig in enumerate(sigs):
            sub_comp = (comp.get(f"sig{gi}", ())
                        if isinstance(comp, dict) else ())
            s_g, s_e, s_comp, s_comm = base_sync(
                g_groups[gi], e_groups[gi] if use_ef else (), sub_comp,
                keys[gi], ok=ok)
            if s_comp != ():
                new_comp[f"sig{gi}"] = s_comp
            out_g.append(s_g)
            out_e.append(s_e)
            if sig:
                # Diagnostics (sync_agree, guard/nonfinite) are 0/1 verdicts,
                # not additive volumes: psum over the signature axes (or
                # summing across groups below) would inflate a unanimous
                # value to the rank count — reduce them with their own
                # collective instead.
                s_comm = {k: (_DIAG_STATS[k][0](v, sig) if k in _DIAG_STATS
                              else jax.lax.psum(v, sig))
                          for k, v in s_comm.items()}
            comm = s_comm if comm is None else merge_stat_dicts(comm, s_comm)
        synced = merge(grads, out_g)
        new_ef = merge(ef, out_e) if use_ef else ()
        return synced, new_ef, new_comp if new_comp else (), comm

    return sync


def make_grouped_grad_sync(cfg: CompressionConfig, sync_axes, is_sharded,
                           shard_axis):
    """Binary convenience wrapper over :func:`make_partitioned_grad_sync`:
    leaves are either replicated or sharded over ``shard_axis`` (a name or
    tuple of names)."""
    axes = (shard_axis,) if isinstance(shard_axis, str) else tuple(shard_axis)
    leaf_axes = [axes if s else () for s in is_sharded]
    return make_partitioned_grad_sync(cfg, sync_axes, leaf_axes)
