"""Device mesh construction and multi-host rendezvous.

TPU-native replacement for the reference's process-group machinery:
``dist.init_process_group('gloo'|'nccl', ...)`` (`CIFAR10/core.py:334`,
`IMAGENET/training/train_imagenet_nv.py:161-162`) and the NCCL ring-order
tuning strings (`IMAGENET/train.py:159-203`).  On TPU there is no user-level
ring configuration: we build a `jax.sharding.Mesh` and let XLA route
collectives over ICI/DCN; the mesh axis layout *is* the tuning surface.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "distributed_init",
    "make_data_mesh",
    "make_mesh",
    "data_sharding",
    "replicated_sharding",
    "world_size",
    "force_host_devices",
    "make_global_batch",
]

DATA_AXIS = "data"


def distributed_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host rendezvous.

    Equivalent of the reference's ``env://`` NCCL rendezvous driven by
    ``MASTER_ADDR``/``RANK``/``WORLD_SIZE`` (`train_imagenet_nv.py:64-66`,
    `dist_utils.py:27-28`).  On Cloud TPU the arguments are auto-detected; on
    other platforms they map 1:1 onto the reference's flags
    (``--master_address``, ``--world_size``, ``--rank``, `dawn.py:11-13`).
    No-ops when running single-process.
    """
    # Eagerly-registered PJRT plugins force their platform into the config at
    # interpreter start and ignore JAX_PLATFORMS set later by a parent
    # process.  For processes WE spawned (the local launcher's rendezvous
    # marker is present), re-assert the launcher's platform choice through
    # the config — effective until first backend use.  Never touch the
    # platform otherwise: the ambient environment may carry the plugin's own
    # JAX_PLATFORMS, and clobbering an explicit user config with it would
    # break CPU-forced test processes.
    if "TPU_CDP_COORDINATOR" in os.environ:
        want = os.environ.get("JAX_PLATFORMS")
        if want:
            try:
                jax.config.update("jax_platforms", want)
            except Exception:
                pass
    if num_processes is not None and num_processes <= 1:
        return
    if coordinator_address is None and num_processes is None and "COORDINATOR_ADDRESS" not in os.environ:
        # Single-process (possibly multi-chip) run: nothing to rendezvous.
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def force_host_devices(n: int, env: Optional[dict] = None) -> dict:
    """Emulate an ``n``-chip mesh on CPU (the JAX-native multi-device fake).

    Must run before the first JAX backend initialisation.  This is the test
    fixture the reference lacked (SURVEY.md §4): its closest analog was N
    Gloo processes on one machine.  Replaces (never appends alongside) any
    inherited device-count flag — duplicated XLA flags are an error.
    Mutates and returns ``env`` (default ``os.environ``) so spawn sites can
    use it on a copied environment.
    """
    if env is None:
        env = os.environ
    env.setdefault("JAX_PLATFORMS", "cpu")
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env


def make_data_mesh(num_devices: Optional[int] = None, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-D ``('data',)`` mesh — the data-parallel world.

    The reference's world is the flat rank set of the process group; here it is
    a named mesh axis so the compression layer can later compose with model
    axes (tensor/pipeline/sequence) without rework (SURVEY.md §2.2).
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested a {num_devices}-device mesh but only "
                f"{len(devices)} devices are available"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def make_mesh(axis_sizes: Sequence[int], axis_names: Sequence[str]) -> Mesh:
    """General N-D mesh for composed parallelism (dp x tp x pp x sp ...)."""
    n = int(np.prod(axis_sizes))
    available = jax.devices()
    if n > len(available):
        raise ValueError(
            f"mesh {tuple(axis_sizes)} needs {n} devices but only "
            f"{len(available)} are available"
        )
    devices = np.asarray(available[:n]).reshape(tuple(axis_sizes))
    return Mesh(devices, tuple(axis_names))


def world_size(mesh: Mesh, axis: str = DATA_AXIS) -> int:
    return mesh.shape[axis]


def data_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Batch-dimension sharding over the data axis."""
    return NamedSharding(mesh, P(axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def make_global_batch(batch: dict, mesh: Mesh, axis: str = DATA_AXIS) -> dict:
    """Assemble per-process local batches into global sharded arrays.

    The multi-host equivalent of the reference's ``DistributedSampler``
    hand-off (`dataloader.py:33`): each process holds its own slice of the
    global batch; under SPMD the jitted step wants one global ``jax.Array``
    whose shards live where the local data already is.  Identity when
    single-process (the local batch *is* the global batch).
    """
    if jax.process_count() == 1:
        return batch
    sharding = NamedSharding(mesh, P(axis))
    out = {}
    for k, v in batch.items():
        v = np.asarray(v)
        global_shape = (v.shape[0] * jax.process_count(),) + v.shape[1:]
        out[k] = jax.make_array_from_process_local_data(sharding, v, global_shape)
    return out
