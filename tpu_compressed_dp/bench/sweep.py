"""Compression-sweep benchmark driver (the paper's Fig. 3/4 protocol).

The reference validated compression variants by full training runs logged to
TSV (`CIFAR10/dawn.py:152-153`) and measured real NIC bandwidth via
/proc/net/dev deltas (`IMAGENET/training/meter.py:24-47`).  On TPU the wire
payload is known analytically at trace time, so this driver measures what the
paper plots directly:

  * steady-state train-step throughput (images/sec and images/sec/chip) per
    (method, ratio, granularity) grid point, dense baseline included;
  * per-step gradient-sync payload (MB) and the analytic all-reduce traffic
    per chip under a ring schedule (``2(W-1)/W × payload``), converted to
    GB/s at the measured step rate;
  * compression fractions (``sent_elems/dense`` and wire-bit fraction).

One JSON line per grid point on stdout (progress on stderr), optional TSV.
Convergence sweeps (accuracy-vs-epoch, the other half of Fig. 3/4) are runs
of the training harnesses themselves — e.g.
``python -m tpu_compressed_dp.harness.dawn --compress layerwise --method Topk
--ratio 0.01`` — this driver covers the time/bandwidth half.

Run: ``python -m tpu_compressed_dp.bench.sweep --model resnet9 --methods
topk,randomk --ratios 0.001,0.01,0.1 --granularities layerwise,entiremodel``
"""

from __future__ import annotations

import argparse
import itertools
import json
import sys
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpu_compressed_dp.models.common import init_model, make_apply_fn
from tpu_compressed_dp.ops.compressors import canonical_name
from tpu_compressed_dp.parallel.dp import (CompressionConfig, init_comp_state,
                                           init_ef_state)
from tpu_compressed_dp.parallel.mesh import make_data_mesh
from tpu_compressed_dp.train.optim import SGD
from tpu_compressed_dp.train.state import TrainState
from tpu_compressed_dp.train.step import make_train_step

__all__ = ["run_point", "run_adaptive_point", "run_sweep", "main",
           "attach_prediction", "PREDICT_WORLDS"]

#: the flagship projection worlds the --predict table prices
PREDICT_WORLDS = (64, 256, 1024, 4096)


def attach_prediction(rec: Dict, calib, *, pod_size: int = 64) -> Dict:
    """Add twin-modeled columns to one flat sweep record, in place.

    ``pred_step_ms`` is the calibrated twin's price for the record's own
    config (context compute anchor + priced comm); ``pred_err_frac`` its
    relative miss against the measured ``step_ms``; ``pred_err_bar_ms``
    the calibration's step-row RMS error scaled to the prediction (the
    +/- bar to quote next to it).  ``pred_step_ms_w<W>`` prices the SAME
    config projected to W chips (``W // pod_size`` pods, the measured
    row's compute held fixed, comm re-laid on the scaled schedule) for
    each W in :data:`PREDICT_WORLDS` — the scale-out table
    ``tools/twin_report.py`` renders.  Records the twin cannot price
    (uncalibrated fabric/context) get ``pred_basis`` explaining why.

    A config whose fitted compute anchor comes out negative (the context
    term absorbed a comm overshoot, so the anchor is unphysical) keeps
    its own-topology prediction — the overshoot cancels there by
    construction — but refuses the W projections (``None``): projecting
    a negative anchor onto a different schedule extrapolates the fit
    artifact, not the config.
    """
    from tpu_compressed_dp.twin.model import UncalibratedFabricError
    from tpu_compressed_dp.twin.records import (context_key, scaled_schedule,
                                                step_row)

    row = step_row(rec, source="sweep", index=0)
    comm = calib.comm_ms_for(row)
    ctx = context_key(rec)
    if ctx in calib.contexts:
        rec["pred_basis"] = "context"
        compute = calib.contexts[ctx]
    else:
        # config never benchmarked: anchor compute on the measured step
        # (comm columns still carry twin information; pred_err_frac then
        # only scores the comm model, and says so)
        rec["pred_basis"] = "measured_anchor"
        compute = float(rec["step_ms"]) - comm
    pred = compute + comm
    rec["pred_step_ms"] = round(pred, 3)
    rec["pred_err_frac"] = round(
        (pred - float(rec["step_ms"])) / max(float(rec["step_ms"]), 1e-9), 5)
    rec["pred_err_bar_ms"] = round(calib.step_rms_frac * pred, 3)
    for w in PREDICT_WORLDS:
        if compute < 0.0:
            rec[f"pred_step_ms_w{w}"] = None
            continue
        pods = max(1, w // max(int(pod_size), 1))
        try:
            sched = scaled_schedule(rec, world=w, pods=pods)
            rec[f"pred_step_ms_w{w}"] = round(
                compute + calib.model.comm_ms(sched), 3)
        except UncalibratedFabricError:
            rec[f"pred_step_ms_w{w}"] = None
    return rec


def _build_model(name: str, image_size: int, num_classes: int,
                 channels_scale: float = 1.0):
    from tpu_compressed_dp.harness.dawn import MODELS as CIFAR_MODELS
    from tpu_compressed_dp.harness.imagenet import ARCHS as IMAGENET_ARCHS

    if name in CIFAR_MODELS:
        return CIFAR_MODELS[name](channels_scale), 32, 10
    if name in IMAGENET_ARCHS:
        if channels_scale != 1.0:
            # the ImageNet archs take --width, not a multiplier; building
            # full-width silently would record timings as if scaled
            raise ValueError(
                f"{name} does not support channels_scale (CIFAR-family only)")
        return (
            IMAGENET_ARCHS[name](num_classes=num_classes, dtype=jnp.bfloat16),
            image_size,
            num_classes,
        )
    raise ValueError(
        f"unknown model {name!r}; known: {sorted(CIFAR_MODELS) + sorted(IMAGENET_ARCHS)}"
    )


def _phase_breakdown_cols(cfg, mesh, n: int, keep: int, opt, params,
                          iters: int) -> Dict[str, float]:
    """Per-phase ms columns (`phase_<name>_ms`, obs/trace.py taxonomy) via
    the tools/wire_profile stage ladders at the model's FLAT gradient size:
    cumulative prefix chains, per-phase cost = rung difference, so XLA
    cannot DCE a stage out of a longer rung.  The select+pack and bucket
    rungs ride the live `kernels.pallas_mode()` dispatch — a BENCH row pair
    (--pallas off vs auto/force) prices the fused kernels on identical
    phase boundaries.  The ladders are the element Top-K wire chain, so
    callers emit these columns for topk wire points only; `update` is the
    optimizer apply, timed on the real param tree."""
    from jax.sharding import PartitionSpec as P

    from tools import wire_profile as wp
    from tpu_compressed_dp.compat import shard_map

    if cfg.transport == "sharded":
        stages = wp.SHARDED_STAGES
        build = lambda st: wp._sharded_chain(st, n, keep, cfg)
        phase_of = {"mag": "compress", "threshold": "compress",
                    "select_pack": "compress", "route": "route",
                    "reduce": "reduce", "return": "return", "ef": "ef"}
    elif cfg.transport == "hierarchical":
        stages = wp.HIER_STAGES
        build = lambda st: wp._hier_chain(st, n, keep, cfg)
        phase_of = {"mag": "compress", "threshold": "compress",
                    "pack": "compress", "ici_reduce": "ici_reduce",
                    "recompress": "recompress", "dcn_route": "route",
                    "return": "return", "ef": "ef"}
    else:
        stages = wp.DISPATCH_STAGES
        build = lambda st: wp._dispatch_chain(st, n, keep)
        phase_of = {"mag": "compress", "threshold": "compress",
                    "select_pack": "compress", "combine": "reduce",
                    "ef": "ef"}

    x = jax.device_put(jax.random.normal(jax.random.key(7), (n,),
                                         jnp.float32))
    cols: Dict[str, float] = {}
    prev = 0.0
    for st in stages:
        fn = jax.jit(shard_map(build(st), mesh=mesh, in_specs=P(),
                               out_specs=P()))
        dt = wp.time_fn(fn, x, iters, warmup_s=0.5) * 1e3
        key = f"phase_{phase_of[st]}_ms"
        cols[key] = cols.get(key, 0.0) + max(dt - prev, 0.0)
        prev = dt

    # the measured train steps donated the original param buffers; only
    # their shape/dtype metadata survives — materialize fresh ones
    params = jax.tree.map(lambda p: jnp.zeros(p.shape, p.dtype), params)
    grads = jax.tree.map(
        lambda p: jax.random.normal(jax.random.key(11), p.shape, p.dtype),
        params)
    opt_state = opt.init(params)
    step_c = jnp.zeros((), jnp.int32)

    def upd(p, g, s):
        new_p, new_s = opt.apply(p, g, s, step_c)
        return jax.tree.leaves(new_p)[0].ravel()[:8]

    fn = jax.jit(upd)
    jax.device_get(fn(params, grads, opt_state))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(params, grads, opt_state)
    jax.device_get(out)
    cols["phase_update_ms"] = (time.perf_counter() - t0) / iters * 1e3
    return {k: round(v, 4) for k, v in cols.items()}


def run_point(
    *,
    model: str = "resnet9",
    method: Optional[str] = None,
    granularity: str = "layerwise",
    mode: str = "simulate",
    transport: str = "allgather",
    ratio: float = 0.01,
    threshold: float = 1e-3,
    qstates: int = 255,
    block_size: int = 256,
    bucket_mb: float = 25.0,
    wire_cap_ratio: float = 0.05,
    shard_route_factor: float = 1.25,
    shard_return_factor: float = 1.25,
    dp_pods: int = 1,
    hier_route_factor_ici: float = 1.25,
    hier_route_factor_dcn: float = 1.25,
    rank: int = 4,
    error_feedback: bool = False,
    sync_overlap: int = 1,
    batch_size: int = 512,
    image_size: int = 128,
    num_classes: int = 1000,
    steps: int = 30,
    warmup: int = 3,
    devices: Optional[int] = None,
    project_devices: int = 32,
    channels_scale: float = 1.0,
    phase_breakdown: bool = False,
) -> Dict[str, float]:
    """Measure one grid point; returns a flat record (also JSON-serialisable).

    ``channels_scale`` shrinks the CIFAR-family nets (width multiplier) —
    for CI smoke of the record schema on slow hosts, not for real numbers.
    """
    mesh = make_data_mesh(devices)
    ndev = mesh.shape["data"]
    bs = batch_size if batch_size % ndev == 0 else (batch_size // ndev + 1) * ndev

    module, sz, ncls = _build_model(model, image_size, num_classes, channels_scale)
    params, stats = init_model(
        module, jax.random.key(0), jnp.zeros((1, sz, sz, 3), jnp.float32)
    )
    apply_fn = make_apply_fn(module)

    opt = SGD(lr=0.01, momentum=0.9, weight_decay=5e-4)
    cfg = CompressionConfig(
        method=method, granularity=granularity, mode=mode, ratio=ratio,
        threshold=threshold, transport=transport,
        qstates=qstates, block_size=block_size, bucket_mb=bucket_mb,
        wire_cap_ratio=wire_cap_ratio,
        shard_route_factor=shard_route_factor,
        shard_return_factor=shard_return_factor,
        dp_pods=dp_pods,
        hier_route_factor_ici=hier_route_factor_ici,
        hier_route_factor_dcn=hier_route_factor_dcn, rank=rank,
        error_feedback=error_feedback, sync_overlap=sync_overlap,
    )
    state = TrainState.create(
        params, stats, opt.init(params), init_ef_state(params, cfg, ndev),
        jax.random.key(1),
        comp=init_comp_state(params, cfg, ndev),
    )
    train_step = make_train_step(apply_fn, opt, cfg, mesh, grad_scale=1.0)

    rng = np.random.default_rng(0)
    batch = {
        "input": jnp.asarray(rng.standard_normal((bs, sz, sz, 3), dtype=np.float32)),
        "target": jnp.asarray(rng.integers(0, ncls, size=(bs,), dtype=np.int32)),
    }

    # Barrier = value fetch: on remote-tunneled backends (axon)
    # block_until_ready returns before execution finishes, so every timing
    # boundary must force an actual transfer.
    def sync(m):
        return float(jax.tree.leaves(m)[0])

    # Warmup is time-based, not step-based (a freshly-attached chip ramps for
    # several seconds), with a barrier per burst so no backlog leaks into the
    # timed region.  The CPU backend has no ramp — plain step-count warmup.
    min_warm_s = 2.0 if jax.default_backend() != "cpu" else 0.0
    t0 = time.perf_counter()
    done = 0
    while done < warmup or time.perf_counter() - t0 < min_warm_s:
        for _ in range(8 if min_warm_s else 1):
            state, metrics = train_step(state, batch)
            done += 1
        sync(metrics)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = train_step(state, batch)
    metrics = jax.device_get(metrics)  # true barrier: waits for the chain
    dt = time.perf_counter() - t0

    images_per_sec = steps * bs / dt
    record: Dict[str, float] = {
        "model": model,
        "method": method or "none",
        "granularity": granularity,
        "mode": mode,
        "ratio": ratio,
        **({"rank": rank} if method is not None and
           canonical_name(method) == "powersgd" else {}),
        "error_feedback": bool(error_feedback),
        **({"sync_overlap": sync_overlap} if sync_overlap != 1 else {}),
        "devices": ndev,
        "batch": bs,
        "image_size": sz,
        "step_ms": round(dt / steps * 1e3, 3),
        "images_per_sec": round(images_per_sec, 1),
        "images_per_sec_per_chip": round(images_per_sec / ndev, 1),
    }
    # MFU (VERDICT r2 #3): model-only FLOPs at the measured step rate vs the
    # chip's bf16 peak — compression/comm overhead shows as lost MFU, which
    # is what the metric is for.
    from tpu_compressed_dp.utils.flops import cnn_mfu_record

    record.update(cnn_mfu_record(
        apply_fn, params, stats, (bs // ndev, sz, sz, 3), steps / dt))
    if channels_scale != 1.0:
        record["channels_scale"] = channels_scale
    if "comm/sent_bits" in metrics:
        payload_mb = float(metrics["comm/sent_bits"]) / 8 / 1e6  # per worker, per step
        dense_mb = float(metrics["comm/dense_elems"]) * 4 / 1e6
        # Method-aware transport split (VERDICT r2 #2): the sync engines
        # report which collective each group's wire payload rides.  A ring
        # psum moves 2(W-1)/W x payload through each chip's links; an
        # all_gather of per-worker payloads moves (W-1) x payload per chip
        # (every worker's k elements visit every other chip).  Billing
        # everything at the ring factor understated all_gather methods by
        # ~W/2 — the class of error the reference avoided by measuring real
        # NIC bytes (`meter.py:24-47`).
        from tpu_compressed_dp.utils.meters import (per_chip_traffic_bytes,
                                                    per_fabric_traffic_bytes)

        psum_mb = float(metrics.get("comm/sent_bits_psum", 0.0)) / 8 / 1e6
        ag_mb = float(metrics.get("comm/sent_bits_allgather", 0.0)) / 8 / 1e6
        a2a_mb = float(metrics.get("comm/sent_bits_alltoall", 0.0)) / 8 / 1e6
        ici_mb = float(metrics.get("comm/sent_bits_ici", 0.0)) / 8 / 1e6
        dcn_mb = float(metrics.get("comm/sent_bits_dcn", 0.0)) / 8 / 1e6
        rt_mb = float(metrics.get("comm/sent_bits_dcn_route", 0.0)) / 8 / 1e6
        # the collective(s) the wire form rides: hier group bits mark the
        # two-level transport (any flat bucket alongside, e.g. keep-all
        # dense-fallback groups, is 'mixed'); a2a > 0 marks the sharded
        # route stage (its shard return bills as allgather); any psum
        # alongside it is likewise 'mixed', matching the pre-sharded
        # classifier's semantics
        flat_mb = psum_mb + ag_mb + a2a_mb
        transport_rode = (("hierarchical" if flat_mb == 0.0 else "mixed")
                          if ici_mb + dcn_mb > 0.0
                          else ("sharded" if psum_mb == 0.0 else "mixed")
                          if a2a_mb > 0.0
                          else "psum" if ag_mb == 0.0
                          else "all_gather" if psum_mb == 0.0 else "mixed")

        def fabric_mb(w: int) -> tuple:
            return per_fabric_traffic_bytes(
                psum_mb, ag_mb, w, a2a_mb, ici_mb, rt_mb,
                max(dcn_mb - rt_mb, 0.0), dp_pods)

        def gbps_per_chip(w: int) -> tuple:
            comp_gbps = sum(fabric_mb(w)) / 1e3 * (steps / dt)
            dense_gbps = per_chip_traffic_bytes(dense_mb, 0.0, w) / 1e3 * (steps / dt)
            return comp_gbps, dense_gbps

        comp_gbps, dense_gbps = gbps_per_chip(ndev)
        traffic_ici, traffic_dcn = fabric_mb(ndev)
        record.update({
            "payload_mb_per_step": round(payload_mb, 4),
            "payload_mb_psum": round(psum_mb, 4),
            "payload_mb_allgather": round(ag_mb, 4),
            "payload_mb_alltoall": round(a2a_mb, 4),
            "payload_mb_ici": round(ici_mb, 4),
            "payload_mb_dcn": round(dcn_mb, 4),
            "dense_mb_per_step": round(dense_mb, 4),
            "transport": transport_rode,
            "sent_frac": round(float(metrics["comm/sent_elems"])
                               / max(float(metrics["comm/dense_elems"]), 1.0), 5),
            "wire_frac": round(float(metrics["comm/sent_bits"])
                               / (32.0 * max(float(metrics["comm/dense_elems"]), 1.0)), 5),
            "allreduce_gbps_per_chip": round(comp_gbps, 3),
            "dense_allreduce_gbps_per_chip": round(dense_gbps, 3),
            # per-step per-chip link traffic at the RUN's device count —
            # the rate-free quantity transport comparisons (allgather vs
            # sharded, BENCH_r07; per-fabric split for hierarchical,
            # BENCH_r10) are made on
            "per_chip_traffic_mb": round(traffic_ici + traffic_dcn, 4),
            "per_chip_traffic_mb_ici": round(traffic_ici, 4),
            "per_chip_traffic_mb_dcn": round(traffic_dcn, 4),
            "num_collectives": float(metrics["comm/num_collectives"]),
        })
        if dp_pods > 1:
            record["dp_pods"] = dp_pods
        if "comm/shard_overflow" in metrics:
            record["shard_overflow"] = float(metrics["comm/shard_overflow"])
        # Analytic multi-chip projection (VERDICT r1 weak #6): single-chip
        # sweeps measure step rate but no real collective traffic, leaving
        # the headline "allreduce GB/s vs k" metric empty.  Project the
        # W-chip per-chip link traffic — method-aware factors as above — at
        # the MEASURED step rate: the link-bandwidth demand for
        # compute-bound scaling, i.e. what the fabric must sustain for
        # compression to keep hiding behind compute.  NB step time is
        # measured at ndev devices and held fixed; a single-chip measurement
        # cannot see collectives lengthen the step.
        w = int(project_devices)
        if w > 1:
            p_gbps, p_dense_gbps = gbps_per_chip(w)
            record.update({
                "projected_devices": float(w),
                "projected_allreduce_gbps_per_chip": round(p_gbps, 6),
                "projected_dense_allreduce_gbps_per_chip": round(p_dense_gbps, 6),
            })
    if phase_breakdown:
        # the stage ladders are the element Top-K wire chain — breakdown
        # columns exist for topk wire points only (other rows carry none)
        if method is not None and canonical_name(method) == "topk" \
                and mode == "wire":
            from tpu_compressed_dp.ops import kernels
            from tpu_compressed_dp.ops.compressors import topk_keep_count

            n_flat = sum(l.size for l in jax.tree.leaves(params))
            record.update(_phase_breakdown_cols(
                cfg, mesh, n_flat, topk_keep_count(n_flat, ratio), opt,
                params, max(steps, 2)))
            record["pallas_mode"] = kernels.pallas_mode()
        else:
            print(f"# phase_breakdown: skipped for {method}/{mode} (ladder "
                  "covers topk wire points)", file=sys.stderr)
    return record


def run_adaptive_point(
    *,
    model: str = "resnet9",
    method: str = "topk",
    granularity: str = "layerwise",
    mode: str = "simulate",
    transport: str = "allgather",
    ratio: float = 0.25,
    rank: int = 4,
    error_feedback: bool = False,
    sync_overlap: int = 1,
    batch_size: int = 512,
    image_size: int = 128,
    num_classes: int = 1000,
    windows: int = 6,
    window: int = 2,
    rungs: Optional[tuple] = None,
    budget_ms: float = 0.0,
    bw_mbps: float = 100.0,
    deadband: float = 0.25,
    devices: Optional[int] = None,
    channels_scale: float = 1.0,
) -> Dict:
    """Run the closed-loop controller on one (method, granularity) point and
    bill it against every static rung — the adaptive-vs-best-static record
    (BENCH_r09 protocol).

    The measured half runs ``windows`` decision windows of ``window`` steps
    each through the real rung-switching loop (trace-cached step variant per
    visited rung, ``Controller.tick`` keyed to applied updates, PowerSGD
    warm-column migration on rank switches — the ``harness/dawn.py`` loop
    minus dataset/checkpoint plumbing).  The comparison half then times
    ``window`` steps at EVERY rung from a fresh state and picks the best
    static point: the least-compressed rung whose modeled comm time fits the
    hideable budget — the oracle the controller is supposed to converge to
    without being told the answer.

    Returns one nested record: ``window_trace`` (per-window rung / step-time
    / billed-bits trajectory), ``static_rungs``, ``best_static`` and the
    billed-bits comparison.  ``budget_ms=0`` derives the budget from the
    measured step wall time scaled by the overlap schedule's hideable byte
    fraction, exactly as the harnesses do.
    """
    from tpu_compressed_dp.control import (ControlConfig, Controller,
                                           build_ladder, comp_for_rung,
                                           init_control_state, ladder_knob,
                                           migrate_comp_state)
    from tpu_compressed_dp.parallel.overlap import (hideable_byte_fraction,
                                                    plan_chunks)

    mesh = make_data_mesh(devices)
    ndev = mesh.shape["data"]
    bs = batch_size if batch_size % ndev == 0 else (batch_size // ndev + 1) * ndev

    module, sz, ncls = _build_model(model, image_size, num_classes, channels_scale)
    params, stats = init_model(
        module, jax.random.key(0), jnp.zeros((1, sz, sz, 3), jnp.float32)
    )
    apply_fn = make_apply_fn(module)
    opt = SGD(lr=0.01, momentum=0.9, weight_decay=5e-4)
    base = CompressionConfig(
        method=method, granularity=granularity, mode=mode, ratio=ratio,
        transport=transport, rank=rank, error_feedback=error_feedback,
        sync_overlap=sync_overlap,
    )
    canon = canonical_name(method)
    ctrl = ControlConfig(
        method=canon,
        rungs=tuple(rungs) if rungs else build_ladder(canon, ratio, rank),
        window=window, deadband=deadband, signal="modeled",
        bandwidth_mbps=bw_mbps, budget_ms=budget_ms,
    )
    controller = Controller(ctrl)
    knob = ladder_knob(canon)
    hide_frac = hideable_byte_fraction(plan_chunks(
        [leaf.size * 4 for leaf in jax.tree_util.tree_leaves(params)], base))

    rng = np.random.default_rng(0)
    batch = {
        "input": jnp.asarray(rng.standard_normal((bs, sz, sz, 3), dtype=np.float32)),
        "target": jnp.asarray(rng.integers(0, ncls, size=(bs,), dtype=np.int32)),
    }

    step_cache: Dict[int, object] = {}

    def step_for(rung: int):
        if rung not in step_cache:
            # donate=False: the static half rebuilds fresh states from the
            # same `params` tree after the adaptive half has stepped, so
            # the buffers must survive the calls
            step_cache[rung] = make_train_step(
                apply_fn, opt, comp_for_rung(base, ctrl, rung), mesh,
                grad_scale=1.0, donate=False)
        return step_cache[rung]

    def fresh_state(rung: int):
        rcfg = comp_for_rung(base, ctrl, rung)
        return TrainState.create(
            params, stats, opt.init(params), init_ef_state(params, rcfg, ndev),
            jax.random.key(1), comp=init_comp_state(params, rcfg, ndev),
            control=init_control_state(ctrl),
        )

    # ---------------------------------------------------- adaptive half
    state = fresh_state(0)
    window_trace: List[Dict] = []
    adaptive_bits = 0.0
    for w in range(windows):
        rung = int(np.asarray(state.control.rung))
        train_step = step_for(rung)
        if len(window_trace) == 0 or window_trace[-1]["rung"] != rung:
            # first entry into this rung: one untimed step eats the compile
            # (it still counts as an applied update for the tick below)
            state, metrics = train_step(state, batch)
            jax.device_get(metrics)
        t0 = time.perf_counter()
        for _ in range(window):
            state, metrics = train_step(state, batch)
        metrics = jax.device_get(metrics)
        step_ms = (time.perf_counter() - t0) / window * 1e3
        bits = float(metrics.get("comm/sent_bits", 0.0))
        signals = controller.window_signals(
            mean_bits=bits, compute_ms=step_ms,
            hideable_fraction=hide_frac)
        new_control, decisions = controller.tick(
            state.control, applied=int(state.step), signals=signals)
        state = state.replace(control=new_control)
        new_rung = int(np.asarray(new_control.rung))
        if new_rung != rung and knob == "rank":
            state = state.replace(comp=migrate_comp_state(
                state.comp, params, comp_for_rung(base, ctrl, rung),
                comp_for_rung(base, ctrl, new_rung), ndev))
        dec = decisions[0] if decisions else None
        updates = window + (1 if len(window_trace) == 0
                            or window_trace[-1]["rung"] != rung else 0)
        adaptive_bits += bits * updates
        window_trace.append({
            "window": w, "rung": rung,
            "value": ctrl.rungs[rung],
            "step_ms": round(step_ms, 3),
            "bits_per_update": bits,
            "comm_ms": round(signals.comm_ms, 4),
            "budget_ms": round(signals.budget_ms, 4),
            "direction": dec.direction if dec else None,
            "rung_to": new_rung,
        })
    # ------------------------------------------------------ static half
    static_rungs: List[Dict] = []
    for rung in range(len(ctrl.rungs)):
        s = fresh_state(rung)
        train_step = step_for(rung)
        s, m = train_step(s, batch)
        jax.device_get(m)  # compile + warm
        t0 = time.perf_counter()
        for _ in range(window):
            s, m = train_step(s, batch)
        m = jax.device_get(m)
        step_ms = (time.perf_counter() - t0) / window * 1e3
        bits = float(m.get("comm/sent_bits", 0.0))
        sig = controller.window_signals(
            mean_bits=bits, compute_ms=step_ms, hideable_fraction=hide_frac)
        static_rungs.append({
            "rung": rung, "value": ctrl.rungs[rung],
            "step_ms": round(step_ms, 3),
            "bits_per_update": bits,
            "comm_ms": round(sig.comm_ms, 4),
            "budget_ms": round(sig.budget_ms, 4),
            "fits_budget": sig.comm_ms <= sig.budget_ms,
        })
    fitting = [r for r in static_rungs if r["fits_budget"]]
    best = fitting[0] if fitting else static_rungs[-1]
    n_updates = sum(window + (1 if i == 0 or window_trace[i - 1]["rung"]
                              != t["rung"] else 0)
                    for i, t in enumerate(window_trace))
    best_static_bits = best["bits_per_update"] * n_updates
    record: Dict = {
        "model": model, "method": canon, "granularity": granularity,
        "mode": mode, "adaptive": True, "knob": knob,
        "rungs": list(ctrl.rungs), "window": window, "windows": windows,
        "deadband": deadband, "bw_mbps": bw_mbps,
        "budget_ms": budget_ms,
        "error_feedback": bool(error_feedback),
        "devices": ndev, "batch": bs,
        "window_trace": window_trace,
        "static_rungs": static_rungs,
        "best_static": {"rung": best["rung"], "value": best["value"]},
        "final_rung": int(np.asarray(state.control.rung)),
        "final_value": ctrl.rungs[int(np.asarray(state.control.rung))],
        "decisions": int(np.asarray(state.control.decisions)),
        "updates": n_updates,
        "adaptive_billed_bits": adaptive_bits,
        "best_static_billed_bits": best_static_bits,
        "billed_bits_ratio": round(
            adaptive_bits / best_static_bits, 4) if best_static_bits else None,
        "converged_to_best_static": (
            int(np.asarray(state.control.rung)) == best["rung"]),
    }
    if channels_scale != 1.0:
        record["channels_scale"] = channels_scale
    return record


def run_sweep(args) -> List[Dict[str, float]]:
    methods = [m.strip() for m in args.methods.split(",") if m.strip()]
    ratios = [float(r) for r in args.ratios.split(",")]
    grans = [g.strip() for g in args.granularities.split(",") if g.strip()]
    transports = [t.strip() for t in args.transports.split(",") if t.strip()]
    records = []

    calib = None
    if getattr(args, "predict", False):
        from tpu_compressed_dp.twin import calibration_rows, fit

        calib = fit(calibration_rows(args.twin_records))
        print(f"# twin: fitted {calib.n_step_rows} step + "
              f"{calib.n_phase_rows} phase rows from {args.twin_records} "
              f"(step rms {calib.step_rms_frac:.1%})", file=sys.stderr)

    def emit(rec):
        if calib is not None and "step_ms" in rec and "transport" in rec:
            attach_prediction(rec, calib, pod_size=args.twin_pod_size)
        records.append(rec)
        print(json.dumps(rec), flush=True)

    if getattr(args, "adaptive", False):
        # closed-loop comparison instead of the static grid: one nested
        # record per (method, granularity) — per-window rung trajectory +
        # per-rung static baselines + the best-static pick (BENCH_r09)
        from tpu_compressed_dp.control.config import TUNABLE_METHODS

        ranks = [int(r) for r in args.ranks.split(",") if r.strip()]
        rungs = None
        if args.adaptive_rungs:
            vals = [float(v) for v in args.adaptive_rungs.split(",")]
            rungs = tuple(vals)
        for method, gran in itertools.product(methods, grans):
            canon = canonical_name(method)
            if canon not in TUNABLE_METHODS:
                print(f"# skipping {method}: no ladder knob (tunable: "
                      f"{','.join(TUNABLE_METHODS)})", file=sys.stderr)
                continue
            print(f"# adaptive: {method}/{gran}", file=sys.stderr)
            emit(run_adaptive_point(
                model=args.model, method=method, granularity=gran,
                mode=args.mode, transport=transports[0], ratio=ratios[0],
                rank=ranks[0], error_feedback=args.error_feedback,
                sync_overlap=args.overlap, batch_size=args.batch_size,
                image_size=args.image_size, num_classes=args.num_classes,
                windows=args.adaptive_windows, window=args.adaptive_window,
                rungs=rungs, budget_ms=args.adaptive_budget_ms,
                bw_mbps=args.adaptive_bw_mbps,
                deadband=args.adaptive_deadband, devices=args.devices,
                channels_scale=args.channels_scale))
        if args.tsv:
            print("# --tsv skipped: adaptive records are nested "
                  "(window_trace/static_rungs); use the JSON lines",
                  file=sys.stderr)
        return records

    common = dict(
        model=args.model, batch_size=args.batch_size, image_size=args.image_size,
        num_classes=args.num_classes, steps=args.steps, warmup=args.warmup,
        devices=args.devices, project_devices=args.project_devices,
        channels_scale=args.channels_scale,
        wire_cap_ratio=args.wire_cap_ratio,
        shard_route_factor=args.shard_route_factor,
        shard_return_factor=args.shard_return_factor,
        dp_pods=args.dp_pods,
        hier_route_factor_ici=args.hier_route_factor_ici,
        hier_route_factor_dcn=args.hier_route_factor_dcn,
        mode=args.mode, threshold=args.threshold, qstates=args.qstates,
        block_size=args.block_size,
        bucket_mb=args.bucket_mb,
        error_feedback=args.error_feedback,
        sync_overlap=args.overlap,
        phase_breakdown=args.phase_breakdown,
    )
    print(f"# dense baseline: {args.model}", file=sys.stderr)
    emit(run_point(method=None, **{**common, "error_feedback": False,
                                   "phase_breakdown": False}))

    ranks = [int(r) for r in args.ranks.split(",") if r.strip()]
    for method, gran in itertools.product(methods, grans):
        canon = canonical_name(method)
        # the sweep axis is method-specific: k-ratios for the sparsifiers,
        # the low-rank r for powersgd, a single point for everything else
        if canon in ("topk", "randomk", "blocktopk"):
            pts = [("ratio", r) for r in ratios]
        elif canon == "powersgd":
            pts = [("rank", r) for r in ranks]
        else:
            pts = [(None, None)]
        # EF composes with sparsifiers only; quantizers are unbiased with no
        # dropped coordinates (wire mode rejects the combination) — sweep
        # them with EF off instead of crashing a mixed-method grid.
        kw = common
        if canon in ("terngrad", "qsgd") and args.error_feedback:
            kw = {**common, "error_feedback": False}
        # the transports axis only differentiates the index-carrying
        # sparsifiers (wire_transport falls back everywhere else) — other
        # methods run once, at the first transport
        from tpu_compressed_dp.ops.wire_sharded import SHARDED_METHODS

        m_transports = (transports if canon in SHARDED_METHODS
                        else transports[:1])
        for axis, val in pts:
            for tr in m_transports:
                label = f"{method}/{gran}" + (
                    f"/k={val}" if axis == "ratio"
                    else f"/r={val}" if axis == "rank" else "") + (
                    f"/{tr}" if len(m_transports) > 1 else "")
                print(f"# {label}", file=sys.stderr)
                emit(run_point(method=method, granularity=gran, transport=tr,
                               **({axis: val} if axis else {}), **kw))
    if args.tsv:
        import os

        os.makedirs(os.path.dirname(os.path.abspath(args.tsv)), exist_ok=True)
        keys = sorted({k for r in records for k in r})
        with open(args.tsv, "w") as f:
            # Column caveats (VERDICT r3 #7) — `#` comment lines, skip on parse:
            f.write(
                "# transport: the collective the method's WIRE form rides; for"
                " mode=simulate rows this is COUNTERFACTUAL — simulate psums"
                " full-size dense tensors and the column names what the wire"
                " payload WOULD ride (payload/wire_frac columns likewise bill"
                " the wire form).  mode=wire rows bill measured payload bytes.\n"
                "# projected_*: W-chip per-chip link traffic at the MEASURED"
                " step rate (compute-bound-scaling assumption: step time held"
                " at its measured value; collectives lengthening the step are"
                " invisible to a single-chip measurement).\n")
            f.write("\t".join(keys) + "\n")
            for r in records:
                f.write("\t".join(str(r.get(k, "")) for k in keys) + "\n")
        print(f"# wrote {args.tsv}", file=sys.stderr)
    if calib is not None:
        # the scale-out table, human-shaped (same numbers as the
        # pred_step_ms_w* columns on each JSON line)
        print(f"# twin projection, modeled step ms "
              f"(pods = W // {args.twin_pod_size}):", file=sys.stderr)
        cols = "".join(f"{f'W={w}':>12s}" for w in PREDICT_WORLDS)
        print(f"# {'config':40s}{cols}", file=sys.stderr)
        for r in records:
            if f"pred_step_ms_w{PREDICT_WORLDS[0]}" not in r:
                continue
            name = (f"{r.get('method')}/{r.get('granularity')}"
                    f"/{r.get('transport')}")
            vals = "".join(
                f"{r.get(f'pred_step_ms_w{w}'):>12.1f}"
                if r.get(f"pred_step_ms_w{w}") is not None else
                f"{'n/a':>12s}" for w in PREDICT_WORLDS)
            print(f"# {name:40s}{vals}", file=sys.stderr)
    return records


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="compression sweep benchmark")
    p.add_argument("--model", default="resnet9")
    p.add_argument("--methods", default="topk,randomk",
                   help="comma list; full set: topk,blocktopk,randomk,"
                        "thresholdv,adaptive_threshold,terngrad,qsgd")
    p.add_argument("--ratios", default="0.001,0.01,0.1",
                   help="k values for topk/blocktopk/randomk (paper: 0.1%%,1%%,10%%)")
    p.add_argument("--ranks", default="1,2,4",
                   help="r values for powersgd (its sweep axis instead of k)")
    p.add_argument("--granularities", default="layerwise,entiremodel")
    p.add_argument("--transports", default="allgather",
                   help="comma list of allgather,sharded,hierarchical — the"
                        " index-carrying sparsifiers run once per transport"
                        " (sharded = the owner-sharded sparse reduce, O(k +"
                        " n/W) per chip vs allgather's O(W*k); hierarchical ="
                        " the two-level dense-ICI + sparse-DCN reduce over a"
                        " dp_pods x dp_chips virtual mesh, O(k + n/W_pods)"
                        " billed DCN bytes; other methods are unaffected)")
    p.add_argument("--mode", default="simulate", choices=["simulate", "wire"])
    p.add_argument("--threshold", type=float, default=1e-3,
                   help="V for thresholdv")
    p.add_argument("--qstates", type=int, default=255)
    p.add_argument("--block_size", type=int, default=256)
    p.add_argument("--bucket_mb", type=float, default=25.0)
    p.add_argument("--error_feedback", action="store_true")
    p.add_argument("--overlap", type=int, default=1,
                   help="sync_overlap chunk count for every grid point "
                        "(parallel/overlap.py; 1 = single-dispatch sync)")
    p.add_argument("--batch_size", type=int, default=512)
    p.add_argument("--image_size", type=int, default=128,
                   help="input size for the ImageNet archs (CIFAR models fix 32)")
    p.add_argument("--num_classes", type=int, default=1000)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--devices", type=int, default=None)
    p.add_argument("--project_devices", type=int, default=32,
                   help="W for the analytic W-chip ring allreduce GB/s "
                        "projection columns (0 disables)")
    p.add_argument("--channels_scale", type=float, default=1.0,
                   help="width multiplier for the CIFAR-family nets (CI "
                        "smoke only; real numbers want 1.0)")
    p.add_argument("--wire_cap_ratio", type=float, default=0.05,
                   help="wire thresholdv/adaptive_threshold transport "
                        "capacity (fraction of elements)")
    p.add_argument("--shard_route_factor", type=float, default=1.25,
                   help="sharded transport per-destination bucket capacity, "
                        "in units of k/W")
    p.add_argument("--shard_return_factor", type=float, default=1.25,
                   help="sharded transport return-union buffer capacity, "
                        "in units of k/W")
    p.add_argument("--dp_pods", type=int, default=1,
                   help="hierarchical transport: pod count P of the "
                        "dp_pods x dp_chips virtual mesh (must divide the "
                        "device count; 1 = flat)")
    p.add_argument("--hier_route_factor_ici", type=float, default=1.25,
                   help="hierarchical transport intra-pod union capacity, "
                        "in units of k")
    p.add_argument("--hier_route_factor_dcn", type=float, default=1.25,
                   help="hierarchical transport inter-pod bucket capacity, "
                        "in units of slab/P")
    p.add_argument("--tsv", type=str, default=None)
    p.add_argument("--phase_breakdown", action="store_true",
                   help="add per-phase ms columns (phase_compress_ms / "
                        "route / reduce / return / ef / update, plus "
                        "ici_reduce+recompress for hierarchical) to topk "
                        "wire grid points via the tools/wire_profile stage "
                        "ladders at the model's flat gradient size; the "
                        "compress/route rungs ride the live --pallas "
                        "dispatch, so an off-vs-auto row pair prices the "
                        "fused kernels")
    p.add_argument("--pallas", default=None,
                   choices=["auto", "off", "force"],
                   help="pin ops/kernels.pallas_mode() for the whole sweep "
                        "(default: leave the process default, auto); "
                        "recorded as the pallas_mode column on breakdown "
                        "rows")
    p.add_argument("--adaptive", action="store_true",
                   help="closed-loop controller comparison instead of the "
                        "static grid: per (method, granularity), run the "
                        "rung-switching control loop for --adaptive_windows "
                        "decision windows and bill it against every static "
                        "rung (control/ subsystem; BENCH_r09 protocol)")
    p.add_argument("--adaptive_windows", type=int, default=6,
                   help="decision windows to run the control loop for")
    p.add_argument("--adaptive_window", type=int, default=2,
                   help="steps (applied updates) per decision window")
    p.add_argument("--adaptive_rungs", type=str, default=None,
                   help="explicit comma ladder (ratios, or ranks for "
                        "powersgd); default build_ladder anchored at "
                        "--ratios[0] / --ranks[0]")
    p.add_argument("--adaptive_budget_ms", type=float, default=0.0,
                   help="pinned hideable-comm budget per update; 0 derives "
                        "it from measured step time x the overlap "
                        "schedule's hideable byte fraction")
    p.add_argument("--adaptive_bw_mbps", type=float, default=100.0,
                   help="modeled-signal link bandwidth (MB/s) for billed-"
                        "bits -> comm-ms conversion")
    p.add_argument("--adaptive_deadband", type=float, default=0.25,
                   help="controller hysteresis band around the budget")
    p.add_argument("--predict", action="store_true",
                   help="price every row through the calibrated digital "
                        "twin (tpu_compressed_dp/twin/): adds pred_step_ms/"
                        "pred_err_frac (+/- pred_err_bar_ms) next to the "
                        "measured columns and a pred_step_ms_w{64,256,1024,"
                        "4096} scale-out projection per row")
    p.add_argument("--twin_records", type=str, default=".",
                   help="directory with the BENCH_r*/MULTICHIP_r* records "
                        "the twin calibrates from (--predict)")
    p.add_argument("--twin_pod_size", type=int, default=64,
                   help="chips per pod assumed by the W-projection columns "
                        "(pods = W // pod_size)")
    return p


def main(argv: Optional[list] = None):
    args = build_parser().parse_args(argv)
    if args.pallas:
        from tpu_compressed_dp.ops import kernels

        kernels.set_pallas_mode(args.pallas)
    return run_sweep(args)


if __name__ == "__main__":
    main()
