"""Rendezvous protocol unit tests (train/rendezvous.py).

The protocol is plain files + injectable clocks, so every multi-rank
interleaving here is scripted deterministically from a single thread: a
follower's ``sleep`` callback runs the leader's ``propose`` (or writes the
epoch file directly), and the follower's next poll observes the commit.
The real ``jax.distributed`` wiring is exercised by the
``HAS_CPU_MULTIPROCESS``-gated drills in tests/test_elastic_multiprocess.py.
"""

import json
import os

import pytest

from tpu_compressed_dp.train import rendezvous as rdzv
from tpu_compressed_dp.train.rendezvous import (
    ADDR_ENV, DIR_ENV, EPOCH_ENV, EpochDecision, Rendezvous,
    RendezvousError, RendezvousTimeout, epoch_path, export_env,
    maybe_rejoin_from_env, read_epoch, reinit_distributed, write_epoch)

pytestmark = pytest.mark.quick


class FakeClock:
    """Injectable now/sleep pair: sleeping advances virtual time and runs
    an optional callback — the single-thread interleaving hook."""

    def __init__(self, on_sleep=None):
        self.t = 0.0
        self.on_sleep = on_sleep
        self.sleeps = 0

    def now(self):
        return self.t

    def sleep(self, s):
        self.t += s
        self.sleeps += 1
        if self.on_sleep is not None:
            self.on_sleep()


def make(rdzv_dir, rank, clock, **kw):
    return Rendezvous(str(rdzv_dir), rank, now=clock.now, sleep=clock.sleep,
                      **kw)


# ------------------------------------------------------------- epoch file

class TestEpochFile:
    def test_round_trip(self, tmp_path):
        rec = {"epoch": 3, "ranks": [0, 2, 5], "coordinator": 0,
               "address": "10.0.0.1:51303"}
        write_epoch(str(tmp_path), rec)
        got = read_epoch(str(tmp_path))
        assert got["epoch"] == 3 and got["ranks"] == [0, 2, 5]
        assert got["address"] == "10.0.0.1:51303"

    def test_missing_dir_reads_none(self, tmp_path):
        assert read_epoch(str(tmp_path / "nowhere")) is None

    def test_torn_or_foreign_content_reads_none(self, tmp_path):
        path = epoch_path(str(tmp_path))
        with open(path, "w") as f:
            f.write('{"epoch": 1, "ranks"')  # torn mid-write
        assert read_epoch(str(tmp_path)) is None
        with open(path, "w") as f:
            json.dump([1, 2, 3], f)  # wrong shape
        assert read_epoch(str(tmp_path)) is None
        with open(path, "w") as f:
            json.dump({"epoch": 1}, f)  # missing ranks
        assert read_epoch(str(tmp_path)) is None

    def test_decision_from_contiguous_process_id(self, tmp_path):
        clock = FakeClock()
        r5 = make(tmp_path, 5, clock)
        rec = {"epoch": 2, "ranks": [5, 0, 2], "address": "h:51302"}
        d = r5.decision_from(rec)
        assert d.ranks == (0, 2, 5)          # sorted original ranks
        assert d.process_id == 2             # contiguous index, not rank
        assert d.coordinator == 0            # defaults to lowest rank
        assert d.num_processes == 3
        # a rank outside the commit gets no process id (must park)
        assert make(tmp_path, 3, clock).decision_from(rec).process_id is None


# ----------------------------------------------------------- vote/propose

class TestPropose:
    def test_two_rank_commit(self, tmp_path):
        """Follower proposes first; its sleep hook runs the leader's
        propose, which sees both votes and commits; the follower's next
        poll adopts the commit."""
        done = {}
        c0 = FakeClock()
        r0 = make(tmp_path, 0, c0, host="leader-host")

        def leader_turn():
            if "d0" not in done:
                done["d0"] = r0.propose([0, 1])

        c1 = FakeClock(on_sleep=leader_turn)
        r1 = make(tmp_path, 1, c1)
        d1 = r1.propose([0, 1])
        d0 = done["d0"]
        # same committed world; process_id is each process's own index
        assert (d0.epoch, d0.ranks, d0.coordinator, d0.address) == \
            (d1.epoch, d1.ranks, d1.coordinator, d1.address)
        assert d1.epoch == 1 and d1.ranks == (0, 1) and d1.coordinator == 0
        assert d1.address == f"leader-host:{rdzv.DEFAULT_BASE_PORT + 1}"
        assert d0.process_id == 0 and d1.process_id == 1
        # committed-epoch votes are garbage-collected by the leader
        assert r0.read_votes(1) == {}

    def test_second_transition_bumps_epoch(self, tmp_path):
        write_epoch(str(tmp_path), {"epoch": 4, "ranks": [0, 1],
                                    "coordinator": 0, "address": "h:51304"})
        clock = FakeClock()
        r0 = make(tmp_path, 0, clock)
        d = r0.propose([0])  # sole survivor: quorum of one, commits alone
        assert d.epoch == 5 and d.ranks == (0,) and d.process_id == 0

    def test_voters_subset_quorum(self, tmp_path):
        """A readmission barrier: members include a parked joiner (rank 2)
        that CANNOT vote — the survivor subset alone reaches quorum."""
        done = {}
        c0 = FakeClock()
        r0 = make(tmp_path, 0, c0)

        def leader_turn():
            if "d0" not in done:
                done["d0"] = r0.propose([0, 1, 2], voters=[0, 1])

        c1 = FakeClock(on_sleep=leader_turn)
        r1 = make(tmp_path, 1, c1)
        d1 = r1.propose([0, 1, 2], voters=[0, 1])
        d0 = done["d0"]
        assert (d0.epoch, d0.ranks) == (d1.epoch, d1.ranks)
        assert d1.ranks == (0, 1, 2) and d1.coordinator == 0
        # rank 2 never voted, yet is in the committed world
        assert 2 not in r0.read_votes(1)

    def test_warm_bit_is_committed_and_defaults_false(self, tmp_path):
        """The readmission barrier's warm-rejoin layout bit rides in the
        epoch COMMIT: every participant — voter or parked joiner — reads
        the same bit back and picks the broadcast layout from it, and a
        record without one (pre-stream epochs, the failure path) decodes
        cold."""
        clock = FakeClock()
        r0 = make(tmp_path, 0, clock)
        d = r0.propose([0], warm=True)
        assert d.warm
        # a parked joiner decodes the committed record the same way
        assert r0.decision_from(read_epoch(str(tmp_path))).warm
        assert not r0.propose([0]).warm          # cold is the default
        write_epoch(str(tmp_path), {"epoch": 9, "ranks": [0],
                                    "coordinator": 0, "address": "h:1"})
        assert not r0.decision_from(read_epoch(str(tmp_path))).warm

    def test_conflicting_votes_are_split_brain(self, tmp_path):
        clock = FakeClock()
        r0 = make(tmp_path, 0, clock)
        r1 = make(tmp_path, 1, clock)
        r1.vote(1, [0, 1, 2])  # rank 1 believes in a different world
        with pytest.raises(RendezvousError, match="split-brain"):
            r0.propose([0, 1])
        assert read_epoch(str(tmp_path)) is None  # nothing committed

    def test_higher_epoch_commit_is_adopted(self, tmp_path):
        """A cascade won the race: the commit lands with a higher epoch
        than proposed, and is adopted as long as it names this rank."""
        def cascade_commit():
            if read_epoch(str(tmp_path)) is None:
                write_epoch(str(tmp_path),
                            {"epoch": 3, "ranks": [0, 1], "coordinator": 0,
                             "address": "h:51303"})

        clock = FakeClock(on_sleep=cascade_commit)
        r1 = make(tmp_path, 1, clock)
        d = r1.propose([0, 1])
        assert d.epoch == 3 and d.process_id == 1

    def test_commit_excluding_this_rank_raises(self, tmp_path):
        def hostile_commit():
            write_epoch(str(tmp_path),
                        {"epoch": 2, "ranks": [0, 2], "coordinator": 0,
                         "address": "h:51302"})

        clock = FakeClock(on_sleep=hostile_commit)
        r1 = make(tmp_path, 1, clock)
        with pytest.raises(RendezvousError, match="without rank 1"):
            r1.propose([0, 1])

    def test_proposing_a_world_without_self_raises(self, tmp_path):
        clock = FakeClock()
        r1 = make(tmp_path, 1, clock)
        with pytest.raises(RendezvousError, match="excludes itself"):
            r1.propose([0, 2])
        with pytest.raises(RendezvousError, match="voters"):
            r1.propose([0, 1], voters=[0])       # this rank cannot vote
        with pytest.raises(RendezvousError, match="voters"):
            r1.propose([0, 1], voters=[0, 1, 5])  # voter outside members

    def test_timeout_lists_missing_voters(self, tmp_path):
        clock = FakeClock()
        r0 = make(tmp_path, 0, clock)
        r1 = make(tmp_path, 1, clock)
        r1.vote(1, [0, 1, 2])  # rank 2 never shows up
        with pytest.raises(RendezvousTimeout, match=r"missing votes from \[2\]"):
            r0.propose([0, 1, 2], deadline_s=1.0)
        assert clock.sleeps > 0  # it actually polled before expiring

    def test_torn_vote_file_is_ignored(self, tmp_path):
        clock = FakeClock()
        r0 = make(tmp_path, 0, clock)
        with open(os.path.join(str(tmp_path), "vote.e1.rank7.json"), "w") as f:
            f.write('{"epoch": 1,')  # a writer died mid-replace-free write
        assert r0.read_votes(1) == {}


# ----------------------------------------------------------------- joins

class TestJoin:
    def test_admitted_by_a_commit_naming_this_rank(self, tmp_path):
        write_epoch(str(tmp_path), {"epoch": 2, "ranks": [0, 1, 2],
                                    "coordinator": 0, "address": "h:51302"})
        clock = FakeClock()
        r2 = make(tmp_path, 2, clock)
        d = r2.join(incarnation=3)
        assert d is not None and d.process_id == 2 and d.epoch == 2
        assert r2.pending_joins() == {}  # admission consumed the join file

    def test_stale_epoch_blocks_until_newer_commit(self, tmp_path):
        """The relaunch env advertised epoch 2 — the world this process
        DIED out of.  Even though the stale epoch file still names it,
        only a strictly newer commit admits."""
        write_epoch(str(tmp_path), {"epoch": 2, "ranks": [0, 1, 2],
                                    "coordinator": 0, "address": "h:51302"})

        def readmit_barrier():
            if clock.t > 0.5:
                write_epoch(str(tmp_path),
                            {"epoch": 3, "ranks": [0, 1, 2],
                             "coordinator": 0, "address": "h:51303"})

        clock = FakeClock(on_sleep=readmit_barrier)
        r2 = make(tmp_path, 2, clock)
        d = r2.join(incarnation=1, stale_epoch=2, deadline_s=30.0)
        assert d is not None and d.epoch == 3

    def test_deadline_parks_and_leaves_join_file(self, tmp_path):
        clock = FakeClock()
        r2 = make(tmp_path, 2, clock)
        d = r2.join(incarnation=1, stale_epoch=2, deadline_s=1.0)
        assert d is None  # park-and-retry: the watchdog's backoff retries
        joins = r2.pending_joins()
        assert joins[2]["incarnation"] == 1  # announcement left behind

    def test_pending_joins_and_clear(self, tmp_path):
        clock = FakeClock()
        r1 = make(tmp_path, 1, clock)
        r1.request_join(incarnation=2)
        make(tmp_path, 4, clock).request_join()
        with open(os.path.join(str(tmp_path), "join.rank9.json"), "w") as f:
            f.write("not json")  # torn announcement: ignored, not fatal
        joins = r1.pending_joins()
        assert sorted(joins) == [1, 4]
        assert joins[1]["incarnation"] == 2
        r1.clear_join(1)
        r1.clear_join(9)  # clearing a non-record is a no-op
        assert sorted(r1.pending_joins()) == [4]


# ----------------------------------------------- relaunch env + re-init

class TestRelaunchEnv:
    def test_export_then_rejoin_round_trip(self, tmp_path):
        """The watchdog's half (export_env) feeds the harness's half
        (maybe_rejoin_from_env) through a plain env dict."""
        env = {"TCDP_RESTART_COUNT": "2"}
        export_env(env, {"epoch": 2, "ranks": [0, 1, 2],
                         "address": "h:51302"})
        assert env[EPOCH_ENV] == "2" and env[ADDR_ENV] == "h:51302"
        env[DIR_ENV] = str(tmp_path)
        # the running world readmits at epoch 3 while we wait in the barrier
        write_epoch(str(tmp_path), {"epoch": 3, "ranks": [0, 1, 2],
                                    "coordinator": 0, "address": "h:51303"})
        clock = FakeClock()
        d = maybe_rejoin_from_env(None, 2, env=env, deadline_s=5.0,
                                  now=clock.now, sleep=clock.sleep)
        assert d is not None and d.epoch == 3 and d.process_id == 2

    def test_fresh_launch_returns_none(self, tmp_path):
        assert maybe_rejoin_from_env(str(tmp_path), 0, env={}) is None
        # an epoch with no directory anywhere is equally a fresh launch
        assert maybe_rejoin_from_env(None, 0, env={EPOCH_ENV: "2"}) is None

    def test_not_admitted_raises_timeout(self, tmp_path):
        env = {EPOCH_ENV: "2", DIR_ENV: str(tmp_path)}
        clock = FakeClock()
        with pytest.raises(RendezvousTimeout, match="parking"):
            maybe_rejoin_from_env(None, 2, env=env, deadline_s=1.0,
                                  now=clock.now, sleep=clock.sleep)


class TestReinitDistributed:
    def _decision(self, ranks, rank):
        ranks = tuple(sorted(ranks))
        pid = ranks.index(rank) if rank in ranks else None
        return EpochDecision(epoch=2, ranks=ranks, coordinator=ranks[0],
                             address="h:51302", process_id=pid)

    def test_excluded_process_refuses(self):
        with pytest.raises(RendezvousError, match="not in the committed"):
            reinit_distributed(self._decision([0, 1], rank=3),
                               shutdown=lambda: None,
                               initialize=lambda **kw: None)

    def test_teardown_then_init_against_new_coordinator(self):
        calls = []
        reinit_distributed(
            self._decision([0, 2, 5], rank=5),
            shutdown=lambda: calls.append("shutdown"),
            initialize=lambda **kw: calls.append(("init", kw)))
        assert calls[0] == "shutdown"
        assert calls[1] == ("init", {"coordinator_address": "h:51302",
                                     "num_processes": 3, "process_id": 2})

    def test_wedged_shutdown_is_tolerated(self):
        """A client wedged on the dead coordinator raises out of shutdown;
        re-init must proceed anyway."""
        calls, logs = [], []

        def wedged():
            raise RuntimeError("coordinator unreachable")

        reinit_distributed(self._decision([0, 1], rank=1), shutdown=wedged,
                           initialize=lambda **kw: calls.append(kw),
                           log=logs.append)
        assert len(calls) == 1 and calls[0]["process_id"] == 1
        assert any("shutdown raised" in m for m in logs)

    def test_single_process_world_skips_init(self):
        calls = []
        reinit_distributed(self._decision([3], rank=3),
                           shutdown=lambda: calls.append("shutdown"),
                           initialize=lambda **kw: calls.append("init"))
        assert calls == ["shutdown"]  # nothing to coordinate with
