"""Fixture: stat-key literal not declared in obs/registry.py (TCDP103).

The docstring may mention comm/undeclared_fixture_key without firing.
"""


def emit(stats):
    stats["comm/undeclared_fixture_key"] = 1.0  # VIOLATION
    stats["comm/sent_bits"] = 2.0  # declared — passes
    stats["not_a/family_key"] = 3.0  # unknown family — out of scope
    return stats
