"""Fixture: named_scope strings outside the tcdp.<phase> taxonomy (TCDP104)."""
import jax

from tpu_compressed_dp.obs import trace as obs_trace


def bad_scopes(x):
    with jax.named_scope("my_random_scope"):  # VIOLATION: no tcdp. prefix
        x = x + 1
    with jax.named_scope("tcdp.not_a_phase"):  # VIOLATION: unknown phase
        x = x + 1
    with obs_trace.phase("not_a_phase"):  # VIOLATION: undeclared phase
        x = x + 1
    return x


def good_scopes(x):
    with jax.named_scope("tcdp.compress"):  # declared phase — passes
        x = x + 1
    with jax.named_scope("tcdp.chunk3"):  # overlap chunk scope — passes
        x = x + 1
    with obs_trace.phase("reduce"):  # declared phase — passes
        x = x + 1
    return x
