# tcdp-lint: roles=replay
"""Fixture: wall-clock reads in a replay-deterministic module (TCDP101).

One violation per flagged call form; the module-default *reference* at the
end must NOT fire (injection seams pass)."""
import time
from datetime import datetime
from typing import Callable


def stamp_record(rec):
    rec["ts"] = time.time()  # VIOLATION: direct wall-clock call
    rec["when"] = datetime.now().isoformat()  # VIOLATION
    return rec


def good_stamp(rec, wall: Callable[[], float] = time.time):
    # the injection seam: referencing time.time as a default is fine,
    # calling the injected callable is fine
    rec["ts"] = wall()
    return rec
