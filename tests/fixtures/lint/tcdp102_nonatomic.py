# tcdp-lint: roles=shared_dir
"""Fixture: in-place write to a shared-dir record (TCDP102)."""
import json
import os


def bad_write(path, rec):
    with open(path, "w") as f:  # VIOLATION: readers can see a torn record
        json.dump(rec, f)


def good_write(path, rec):
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:  # tmp sibling — passes
        json.dump(rec, f)
    os.replace(tmp, path)


def good_append(path, line):
    with open(path, "a") as f:  # append (JSONL event stream) — exempt
        f.write(line + "\n")
