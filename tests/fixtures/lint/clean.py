# tcdp-lint: roles=replay,shared_dir
"""Fixture: near-miss patterns that must produce ZERO findings even with
every role enabled."""
import os
import time
from typing import Callable


def monotonic_ok():
    # monotonic clocks are replay-safe (durations, not wall time)
    return time.monotonic()


def injected(now: Callable[[], float] = time.time):
    return now()


def atomic(path, payload):
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(payload)
    os.replace(tmp, path)


def reader(path):
    with open(path) as f:
        return f.read()
