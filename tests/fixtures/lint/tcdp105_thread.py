"""Fixture: thread-target attribute write without the class lock (TCDP105)."""
import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.last_error = None
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        while True:
            try:
                self.count += 1  # VIOLATION: unguarded write from the thread
            except Exception as e:
                with self._lock:
                    self.last_error = e  # guarded — passes


class CleanWorker:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        with self._lock:
            self.n += 1
