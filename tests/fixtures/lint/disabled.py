# tcdp-lint: roles=replay
"""Fixture: disable-pragma round trip.  The justified disable suppresses its
finding; the bare disable suppresses but earns a TCDP100."""
import time


def justified(rec):
    rec["ts"] = time.time()  # tcdp-lint: disable=TCDP101 -- operator-facing log stamp, never replayed
    return rec


def unjustified(rec):
    rec["ts"] = time.time()  # tcdp-lint: disable=TCDP101
    return rec
