"""Integration tests: the jitted shard_map train step on an 8-device CPU mesh.

Covers the reference's hot loop semantics (`core.py:303-322`): forward,
backward, compression comm, optimizer step — for dense and compressed DP,
both granularities, with and without error feedback.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_compressed_dp.models.common import init_model, make_apply_fn
from tpu_compressed_dp.parallel.dp import (CompressionConfig, init_comp_state,
                                           init_ef_state)
from tpu_compressed_dp.train.optim import SGD
from tpu_compressed_dp.train.schedules import piecewise_linear
from tpu_compressed_dp.train.state import TrainState
from tpu_compressed_dp.train.step import make_eval_step, make_train_step


class TinyCNN(nn.Module):
    """Small conv+BN net exercising batch_stats plumbing."""

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(8, (3, 3), use_bias=False, name="conv1")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9, name="bn1")(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(4, name="head")(x)
        return x


class TinyMLP(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(32)(x))
        return nn.Dense(4)(x)


def make_batch(n=64, seed=0, img=(8, 8, 3), classes=4):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, *img).astype(np.float32)
    y = (x.reshape(n, -1).sum(axis=1) > 0).astype(np.int32) + 2 * (x[:, 0, 0, 0] > 0).astype(np.int32)
    return {"input": jnp.asarray(x), "target": jnp.asarray(y % classes)}


def build(mesh, module, cfg, *, bs=64, lr=0.05, momentum=0.9, ef=False):
    params, stats = init_model(module, jax.random.key(0), jnp.zeros((1, 8, 8, 3), jnp.float32))
    opt = SGD(lr=lr, momentum=momentum, nesterov=True, weight_decay=1e-4)
    ef_state = init_ef_state(params, cfg, num_devices=mesh.shape["data"])
    comp_state = init_comp_state(params, cfg, num_devices=mesh.shape["data"])
    state = TrainState.create(params, stats, opt.init(params), ef_state,
                              jax.random.key(1), comp=comp_state)
    apply_fn = make_apply_fn(module)
    step = make_train_step(apply_fn, opt, cfg, mesh, grad_scale=1.0, donate=False)
    ev = make_eval_step(apply_fn, mesh)
    return state, step, ev


CONFIGS = [
    CompressionConfig(method=None),
    CompressionConfig(method="topk", ratio=0.25),
    CompressionConfig(method="topk", ratio=0.25, granularity="entiremodel"),
    CompressionConfig(method="randomk", ratio=0.5, error_feedback=True),
    CompressionConfig(method="qsgd", qstates=255),
    CompressionConfig(method="terngrad"),
    CompressionConfig(method="adaptive_threshold", granularity="entiremodel"),
    CompressionConfig(method="thresholdv", threshold=1e-4),
    CompressionConfig(method="powersgd", rank=2, error_feedback=True),
    CompressionConfig(method="powersgd", rank=4, granularity="entiremodel",
                      error_feedback=True),
]


@pytest.mark.parametrize("cfg", CONFIGS, ids=lambda c: f"{c.method}-{c.granularity}-ef{c.error_feedback}")
def test_loss_decreases(mesh8, cfg):
    batch = make_batch()
    state, step, _ = build(mesh8, TinyMLP(), cfg)
    losses = []
    for _ in range(30):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.8, losses[:3] + losses[-3:]
    assert int(state.step) == 30


def test_batchnorm_stats_update(mesh8):
    batch = make_batch()
    cfg = CompressionConfig(method=None)
    state, step, _ = build(mesh8, TinyCNN(), cfg)
    before = jax.tree.map(np.asarray, state.batch_stats)
    state, _ = step(state, batch)
    after = state.batch_stats
    diff = jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))), before, after)
    assert max(jax.tree.leaves(diff)) > 0


def test_ef_state_threads_through(mesh8):
    cfg = CompressionConfig(method="topk", ratio=0.1, error_feedback=True)
    batch = make_batch()
    state, step, _ = build(mesh8, TinyMLP(), cfg, ef=True)
    state, _ = step(state, batch)
    ef_mag = sum(float(jnp.sum(jnp.abs(l))) for l in jax.tree.leaves(state.ef))
    assert ef_mag > 0


def test_comp_state_threads_through(mesh8):
    """The stateful compressor path end-to-end: TrainState.comp leaves
    change across a powersgd step (warm-start Q updated in the jitted step)
    and the transport stats report psum-only traffic."""
    cfg = CompressionConfig(method="powersgd", rank=2, error_feedback=True)
    batch = make_batch()
    state, step, _ = build(mesh8, TinyMLP(), cfg)
    before = {k: np.asarray(v) for k, v in state.comp.items()}
    assert before  # TinyMLP's dense kernels are large enough to compress
    state, metrics = step(state, batch)
    assert set(state.comp) == set(before)
    moved = any(not np.array_equal(np.asarray(state.comp[k]), before[k])
                for k in before)
    assert moved
    assert float(metrics["comm/sent_bits_psum"]) > 0
    assert float(metrics["comm/sent_bits_allgather"]) == 0.0
    # second step must accept the updated state (stable pytree structure)
    state, _ = step(state, batch)
    assert int(state.step) == 2


def test_dense_equals_singlehost_sgd(mesh8):
    """Dense DP over 8 devices == single-device SGD on the full batch."""
    batch = make_batch(n=64)
    cfg = CompressionConfig(method=None)
    module = TinyMLP()
    state, step, _ = build(mesh8, module, cfg, momentum=0.0)
    params0 = state.params

    # manual single-device reference step
    from tpu_compressed_dp.train.step import cross_entropy_sum

    apply_fn = make_apply_fn(module)

    def loss_fn(p):
        logits, _ = apply_fn(p, {}, batch["input"], True, {})
        return cross_entropy_sum(logits, batch["target"]) / batch["input"].shape[0]

    grads = jax.grad(loss_fn)(params0)
    opt = SGD(lr=0.05, momentum=0.0, nesterov=True, weight_decay=1e-4)
    expected, _ = opt.apply(params0, grads, opt.init(params0), jnp.asarray(1))

    state, _ = step(state, batch)
    for a, b in zip(jax.tree.leaves(expected), jax.tree.leaves(state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)


def test_eval_step_counts(mesh8):
    batch = make_batch(n=64)
    state, step, ev = build(mesh8, TinyMLP(), CompressionConfig(method=None))
    m = ev(state, batch)
    assert float(m["count"]) == 64
    assert 0 <= float(m["correct"]) <= 64
    assert float(m["correct5"]) >= float(m["correct"])


def test_eval_mask_excludes_padding(mesh8):
    """Padded examples (mask 0, label -1) contribute to no metric."""
    import jax.numpy as jnp

    state, _, ev = build(mesh8, TinyMLP(), CompressionConfig(method=None))
    real = make_batch(n=40)
    padded = {
        "input": jnp.concatenate([real["input"], jnp.zeros((24, 8, 8, 3))]),
        "target": jnp.concatenate([real["target"], jnp.full((24,), -1, jnp.int32)]),
        "mask": jnp.concatenate([jnp.ones((40,)), jnp.zeros((24,))]),
    }
    m_pad = ev(state, padded)
    assert float(m_pad["count"]) == 40
    # metrics equal a direct (unsharded) computation over the 40 real examples
    from tpu_compressed_dp.train.step import cross_entropy_per_example
    from tpu_compressed_dp.models.common import make_apply_fn

    logits, _ = make_apply_fn(TinyMLP())(state.params, {}, real["input"], False, {})
    np.testing.assert_allclose(
        float(m_pad["loss_sum"]),
        float(jnp.sum(cross_entropy_per_example(logits, real["target"]))),
        rtol=1e-5,
    )
    np.testing.assert_allclose(
        float(m_pad["correct"]),
        float(jnp.sum(jnp.argmax(logits, axis=1) == real["target"])),
    )
    # out-of-range padded labels also produce finite loss contributions (0)
    assert np.isfinite(float(m_pad["loss_sum"]))


def test_lr_schedule_evaluated_per_step(mesh8):
    batch = make_batch()
    sched = piecewise_linear([0, 10, 20], [0.0, 1.0, 0.0])
    module = TinyMLP()
    params, stats = init_model(module, jax.random.key(0), jnp.zeros((1, 8, 8, 3), jnp.float32))
    opt = SGD(lr=lambda s: sched(s / 10.0) * 0.01)
    cfg = CompressionConfig(method=None)
    state = TrainState.create(params, stats, opt.init(params), (), jax.random.key(1))
    step = make_train_step(make_apply_fn(module), opt, cfg, mesh8, donate=False)
    lrs = []
    for _ in range(5):
        state, metrics = step(state, batch)
        lrs.append(float(metrics["lr"]))
    # schedule ramps linearly: lr at step s is s/100
    np.testing.assert_allclose(lrs, [0.0001 * s for s in range(1, 6)], rtol=1e-4)
