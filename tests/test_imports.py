"""Import smoke: every ``tpu_compressed_dp`` submodule must import cleanly.

The seed's single bad ``from jax import shard_map`` surfaced as 20 opaque
pytest collection errors (every test module transitively importing
``train/step.py``).  This file turns the next such regression into one
named failure in seconds: each submodule gets its own test, collected FIRST
in the tier-1 run (``conftest.pytest_collection_modifyitems`` orders the
``imports_smoke`` marker to the front), so the broken import is the first
line of output instead of noise spread over the whole suite.
"""

import importlib
import pkgutil

import pytest

import tpu_compressed_dp


def _submodules():
    names = ["tpu_compressed_dp"]
    for mod in pkgutil.walk_packages(tpu_compressed_dp.__path__,
                                     prefix="tpu_compressed_dp."):
        names.append(mod.name)
    # native holds only the C++ source (no python module); everything else
    # must import
    return [n for n in sorted(set(names)) if not n.endswith(".native")]


@pytest.mark.quick
@pytest.mark.imports_smoke
@pytest.mark.parametrize("module", _submodules())
def test_submodule_imports(module):
    importlib.import_module(module)


def _tool_modules():
    import os
    tools_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    return sorted(f"tools.{f[:-3]}" for f in os.listdir(tools_dir)
                  if f.endswith(".py"))


@pytest.mark.quick
@pytest.mark.imports_smoke
@pytest.mark.parametrize("module", _tool_modules())
def test_tool_imports_side_effect_free(module):
    """Every tool must import without mutating process state (os.environ,
    sys.path, jax platform config) and expose a ``main`` entry point —
    the contract that lets tcdp-lint, the test suite, and other tools
    import them for their helpers without surprise reconfiguration."""
    import os
    import sys

    env_before = dict(os.environ)
    path_before = list(sys.path)
    mod = importlib.import_module(module)
    assert dict(os.environ) == env_before, "import mutated os.environ"
    assert list(sys.path) == path_before, "import mutated sys.path"
    assert callable(getattr(mod, "main", None)), f"{module} has no main()"


@pytest.mark.quick
@pytest.mark.imports_smoke
def test_public_surface():
    # the version-shimmed shard_map and the stateful-compressor entry points
    # must be reachable from the package root / their canonical homes
    assert callable(tpu_compressed_dp.shard_map)
    from tpu_compressed_dp.ops.compressors import REGISTRY, get_compressor
    from tpu_compressed_dp.parallel.dp import init_comp_state  # noqa: F401

    assert "powersgd" in REGISTRY
    assert get_compressor("powersgd").is_stateful
