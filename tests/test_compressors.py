"""Unit tests for the six compression operators (SURVEY.md §4 test pyramid).

Numerics are checked against closed-form properties: exact keep counts for
Top-K/Random-K, threshold semantics, unbiasedness of the stochastic
quantisers, and the reference's tie-keeping Top-K rule.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_compressed_dp.ops import compressors as C

pytestmark = pytest.mark.quick  # fast tier (VERDICT r2 #10)



def rand_grad(n=1000, seed=0):
    return jax.random.normal(jax.random.key(seed), (n,), jnp.float32)


class TestTopK:
    def test_keep_count_matches_reference_rule(self):
        # reference: threshold at kthvalue(ceil(n*(1-K))), keep >= (core.py:181)
        for n, k in [(100, 0.1), (100, 0.5), (97, 0.33), (10, 0.01), (1000, 0.001)]:
            g = np.asarray(rand_grad(n, seed=n))
            out = np.asarray(C.top_k(jnp.asarray(g), ratio=k))
            import math

            m = max(1, math.ceil(n * (1 - k)))
            expected = n - m + 1
            assert np.count_nonzero(out) == expected

    def test_keeps_largest_magnitudes(self):
        g = jnp.asarray([0.1, -5.0, 0.2, 3.0, -0.05, 1.0], jnp.float32)
        out = np.asarray(C.top_k(g, ratio=0.5))
        # n=6, K=0.5 -> m=ceil(3)=3 -> keep 4 largest |g|: -5, 3, 1, 0.2
        np.testing.assert_allclose(out, [0.0, -5.0, 0.2, 3.0, 0.0, 1.0])

    def test_kept_values_unchanged(self):
        g = rand_grad(512)
        out = C.top_k(g, ratio=0.1)
        mask = out != 0
        np.testing.assert_array_equal(np.asarray(out)[np.asarray(mask)], np.asarray(g)[np.asarray(mask)])


class TestRandomK:
    def test_keep_count(self):
        for n, k in [(100, 0.1), (97, 0.33), (1000, 0.5)]:
            g = jnp.ones((n,), jnp.float32)
            out = C.random_k(g, jax.random.key(1), ratio=k)
            assert int(jnp.count_nonzero(out)) == C.randomk_keep_count(n, k)

    def test_uniform_selection(self):
        # every coordinate selected with probability ~k
        n, k, trials = 64, 0.25, 400
        g = jnp.ones((n,), jnp.float32)
        counts = np.zeros(n)
        for t in range(trials):
            counts += np.asarray(C.random_k(g, jax.random.key(t), ratio=k)) != 0
        freq = counts / trials
        assert np.all(np.abs(freq - k) < 0.12)

    def test_same_key_same_mask(self):
        g1, g2 = rand_grad(256, 1), rand_grad(256, 2)
        m1 = np.asarray(C.random_k(g1, jax.random.key(7), ratio=0.1)) != 0
        m2 = np.asarray(C.random_k(g2, jax.random.key(7), ratio=0.1)) != 0
        np.testing.assert_array_equal(m1, m2)


class TestThresholdV:
    def test_semantics(self):
        g = jnp.asarray([0.5, -0.0005, 0.002, -0.7, 0.0], jnp.float32)
        out = np.asarray(C.threshold_v(g, threshold=1e-3))
        np.testing.assert_allclose(out, [0.5, 0.0, 0.002, -0.7, 0.0])


class TestAdaptiveThreshold:
    def test_semantics(self):
        g = jnp.asarray([1.0, 0.49, 0.51, -0.5, -2.0], jnp.float32)
        # max|g| = 2 -> keep where 2|g| >= 2 i.e. |g| >= 1
        out = np.asarray(C.adaptive_threshold(g))
        np.testing.assert_allclose(out, [1.0, 0.0, 0.0, 0.0, -2.0])


class TestTernGrad:
    def test_values_ternary(self):
        g = rand_grad(2048)
        out = np.asarray(C.terngrad(g, jax.random.key(0)))
        gmax = float(jnp.max(jnp.abs(g)))
        nz = np.abs(out[out != 0])
        np.testing.assert_allclose(nz, gmax, rtol=1e-6)

    def test_unbiased(self):
        g = rand_grad(256)
        outs = [np.asarray(C.terngrad(g, jax.random.key(s))) for s in range(600)]
        mean = np.mean(outs, axis=0)
        np.testing.assert_allclose(mean, np.asarray(g), atol=0.25)

    def test_zero_grad_safe(self):
        out = C.terngrad(jnp.zeros((16,), jnp.float32), jax.random.key(0))
        assert np.all(np.isfinite(np.asarray(out)))
        np.testing.assert_allclose(np.asarray(out), 0.0)

    def test_chunked_scales_per_chunk(self):
        # one scale per chunk: nonzeros in chunk c all equal that chunk's max
        g = jnp.concatenate([rand_grad(128, seed=1) * 10.0,
                             rand_grad(128, seed=2) * 0.1])
        levels, scale = C.terngrad_levels(g, jax.random.key(0), chunk=128)
        assert scale.shape == (2,)
        np.testing.assert_allclose(np.asarray(scale),
                                   [float(jnp.max(jnp.abs(g[:128]))),
                                    float(jnp.max(jnp.abs(g[128:])))], rtol=1e-6)
        out = np.asarray(C.terngrad(g, jax.random.key(0), chunk=128))
        for c in range(2):
            nz = np.abs(out[c * 128:(c + 1) * 128])
            nz = nz[nz != 0]
            np.testing.assert_allclose(nz, np.asarray(scale)[c], rtol=1e-6)

    def test_chunked_unbiased_and_denser_than_global(self):
        # a few huge coords + many small: the global max starves small
        # coordinates (keep-prob ~ eps); per-chunk scales keep them alive —
        # the entire-model NaN fix (VERDICT r2 #5)
        small = rand_grad(512, seed=3) * 0.01
        big = rand_grad(512, seed=4) * 100.0
        g = jnp.concatenate([small, big])
        outs = [np.asarray(C.terngrad(g, jax.random.key(s), chunk=512))
                for s in range(600)]
        mean = np.mean(outs, axis=0)
        # per-coordinate estimator std is ~scale_chunk; normalise the error by
        # the chunk scale before comparing (600 trials -> stderr ~ 0.02 scale)
        scales = np.asarray(jnp.stack([jnp.max(jnp.abs(g[:512])),
                                       jnp.max(jnp.abs(g[512:]))]))
        rel_err = np.abs(mean - np.asarray(g)) / np.repeat(scales, 512)
        assert rel_err.max() < 0.15
        keep_small_chunked = np.mean(
            [np.count_nonzero(o[:512]) for o in outs])
        outs_g = [np.asarray(C.terngrad(g, jax.random.key(s)))
                  for s in range(100)]
        keep_small_global = np.mean(
            [np.count_nonzero(o[:512]) for o in outs_g])
        assert keep_small_chunked > 20 * max(keep_small_global, 1e-9)

    def test_chunked_ragged_tail(self):
        # n not a multiple of chunk: padding must not leak into scales/output
        g = rand_grad(300, seed=5)
        levels, scale = C.terngrad_levels(g, jax.random.key(0), chunk=128)
        assert scale.shape == (3,)  # 128 + 128 + 44
        out = C.terngrad_dense(levels, scale, 128)
        assert out.shape == (300,)
        assert np.all(np.isfinite(np.asarray(out)))

    def test_chunk_off_matches_scalar_scale(self):
        g = rand_grad(256, seed=6)
        lv0, s0 = C.terngrad_levels(g, jax.random.key(7))
        lv1, s1 = C.terngrad_levels(g, jax.random.key(7), chunk=1024)
        # n <= chunk: single global scale, identical draw
        assert s1.ndim == 0
        np.testing.assert_allclose(float(s0), float(s1))
        np.testing.assert_array_equal(np.asarray(lv0), np.asarray(lv1))


class TestRandomDithering:
    def test_unbiased(self):
        g = rand_grad(256)
        outs = [np.asarray(C.random_dithering(g, jax.random.key(s), qstates=4)) for s in range(600)]
        np.testing.assert_allclose(np.mean(outs, axis=0), np.asarray(g), atol=0.3)

    def test_quantised_levels(self):
        g = rand_grad(512)
        out = np.asarray(C.random_dithering(g, jax.random.key(1), qstates=8))
        norm = float(jnp.linalg.norm(g))
        levels = np.abs(out) / norm * 8
        np.testing.assert_allclose(levels, np.round(levels), atol=1e-4)

    def test_zero_grad_safe(self):
        out = C.random_dithering(jnp.zeros((16,), jnp.float32), jax.random.key(0))
        assert np.all(np.isfinite(np.asarray(out)))


class TestRegistry:
    @pytest.mark.parametrize(
        "name",
        ["Topk", "Randomk", "Thresholdv", "AdaptiveThreshold", "TernGrad", "RandomDithering",
         "topk", "qsgd", "none", None],
    )
    def test_reference_spellings_resolve(self, name):
        b = C.get_compressor(name, ratio=0.1)
        out = b.fn(rand_grad(64), jax.random.key(0))
        assert out.shape == (64,)

    def test_unknown_method_raises(self):
        with pytest.raises(ValueError):
            C.get_compressor("enitremodel")  # the reference's silent typo (SURVEY §2.3)

    def test_jit_compatible(self):
        for name in C.REGISTRY:
            b = C.get_compressor(name, ratio=0.25)
            f = jax.jit(lambda g, k, b=b: b.fn(g, k))
            out = f(rand_grad(128), jax.random.key(3))
            assert out.shape == (128,)


class TestBlockTopK:
    """Net-new TPU-native operator (no reference equivalent): contiguous
    ``block_size``-element blocks selected by L2 norm."""

    def test_keep_block_count(self):
        for n, k, bs in [(1024, 0.25, 64), (1000, 0.1, 128), (64, 0.5, 16), (100, 0.01, 32)]:
            g = rand_grad(n, seed=n)
            out = np.asarray(C.block_top_k(g, ratio=k, block_size=bs))
            nb = -(-n // bs)
            blocks = np.flatnonzero([np.any(out[i * bs:(i + 1) * bs]) for i in range(nb)])
            assert len(blocks) == C.blocktopk_keep_blocks(n, k, bs)

    def test_keeps_highest_norm_blocks(self):
        bs = 4
        g = jnp.asarray([0.1] * 4 + [5.0] * 4 + [0.2] * 4 + [1.0] * 4, jnp.float32)
        out = np.asarray(C.block_top_k(g, ratio=0.5, block_size=bs))
        np.testing.assert_allclose(out, [0.0] * 4 + [5.0] * 4 + [0.0] * 4 + [1.0] * 4)

    def test_kept_values_unchanged_and_contiguous(self):
        g = rand_grad(512, seed=3)
        out = np.asarray(C.block_top_k(g, ratio=0.1, block_size=32))
        mask = out != 0
        np.testing.assert_array_equal(out[mask], np.asarray(g)[mask])
        # survivors come in whole 32-element blocks
        m2 = mask.reshape(-1, 32)
        assert np.all(m2.all(axis=1) | (~m2).any(axis=1))
        per_block = m2.any(axis=1)
        np.testing.assert_array_equal(m2[per_block], np.ones_like(m2[per_block]))

    def test_ragged_tail_block(self):
        # n not divisible by block_size: the tail block competes with its
        # zero-padding included in the score
        g = jnp.concatenate([jnp.ones((96,)), jnp.full((10,), 10.0)]).astype(jnp.float32)
        out = np.asarray(C.block_top_k(g, ratio=0.3, block_size=32))
        assert np.count_nonzero(out[96:]) == 10  # tail block selected
        assert out.shape == (106,)

    def test_registry_and_payload(self):
        b = C.get_compressor("blocktopk", ratio=0.25, block_size=64)
        assert b.name == "blocktopk" and b.is_sparsifier and not b.needs_rng
        assert C.payload_bits_per_elem("blocktopk", block_size=64) == 32.0 + 0.5
