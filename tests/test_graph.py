"""Graph-spec builder tests: wiring semantics, spec-built nets, training."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_compressed_dp.models import graph as G
from tpu_compressed_dp.models.common import init_model, make_apply_fn

pytestmark = pytest.mark.quick  # fast tier (VERDICT r2 #10)



class TestWiring:
    def test_default_sequential(self):
        # core.py:136-141 default: each node feeds from its predecessor
        spec = {"a": G.Mul(2.0), "b": G.Mul(3.0)}
        out = G.GraphNet(spec).apply({"params": {}}, jnp.ones((1, 2)), train=False)
        np.testing.assert_allclose(np.asarray(out), 6.0)

    def test_explicit_edges_and_cache(self):
        spec = {"a": G.Identity(), "b": G.Mul(2.0),
                "join": (G.Add(), ["a", "b"]),
                "cat": (G.Concat(), ["a", "join"])}
        out = G.GraphNet(spec, outputs=("b", "join", "cat")).apply(
            {"params": {}}, jnp.ones((2, 3)), train=False)
        np.testing.assert_allclose(np.asarray(out["join"]), 3.0)
        assert out["cat"].shape == (2, 6)

    def test_relative_paths(self):
        spec = {"blk": {"in": G.Identity(), "x2": G.Mul(2.0),
                        "add": (G.Add(), ["./in", "./x2"])},
                "deep": {"sub": {"y": (G.Mul(10.0), ["../../blk/add"])}}}
        out = G.GraphNet(spec).apply({"params": {}}, jnp.ones((1, 1)), train=False)
        np.testing.assert_allclose(np.asarray(out), 30.0)

    def test_unknown_input_raises(self):
        with pytest.raises(ValueError, match="unknown input"):
            G.build_graph({"a": G.Identity(), "b": (G.Add(), ["nope", "a"])})

    def test_empty_raises(self):
        with pytest.raises(ValueError, match="empty"):
            G.build_graph({})

    def test_path_iter_matches_reference_semantics(self):
        nested = {"x": {"y": 1, "z": {"w": 2}}, "v": 3}
        assert list(G.path_iter(nested)) == [
            (("x", "y"), 1), (("x", "z", "w"), 2), (("v",), 3)]


class TestSpecNets:
    def test_resnet9_spec_forward(self):
        net = G.GraphNet(G.resnet9_spec())
        params, stats = init_model(net, jax.random.key(0),
                                   jnp.zeros((1, 32, 32, 3), jnp.float32))
        # param layout mirrors the spec paths
        assert "layer1_residual_res1" in params and "linear" in params
        logits, new_stats = make_apply_fn(net)(
            params, stats, jnp.ones((4, 32, 32, 3)), True, {})
        assert logits.shape == (4, 10)
        assert len(new_stats) == 8  # 8 ConvBN nodes carry running stats

    def test_alexnet_spec_forward(self):
        net = G.GraphNet(G.alexnet_spec())
        params, stats = init_model(net, jax.random.key(1),
                                   jnp.zeros((1, 32, 32, 3), jnp.float32))
        logits, _ = make_apply_fn(net)(params, stats,
                                       jnp.ones((2, 32, 32, 3)), False, {})
        assert logits.shape == (2, 10)

    def test_spec_net_trains_on_mesh(self, mesh8):
        from tpu_compressed_dp.parallel.dp import CompressionConfig, init_ef_state
        from tpu_compressed_dp.train.optim import SGD
        from tpu_compressed_dp.train.state import TrainState
        from tpu_compressed_dp.train.step import make_train_step

        ch = {"prep": 8, "layer1": 16, "layer2": 16, "layer3": 16}
        net = G.GraphNet(G.resnet9_spec(channels=ch))
        params, stats = init_model(net, jax.random.key(0),
                                   jnp.zeros((1, 32, 32, 3), jnp.float32))
        opt = SGD(lr=0.05, momentum=0.9)
        comp = CompressionConfig(method="topk", ratio=0.1, error_feedback=True)
        state = TrainState.create(params, stats, opt.init(params),
                                  init_ef_state(params, comp, 8), jax.random.key(1))
        step = make_train_step(make_apply_fn(net), opt, comp, mesh8)
        rng = np.random.default_rng(0)
        batch = {"input": jnp.asarray(rng.standard_normal((16, 32, 32, 3),
                                                          dtype=np.float32)),
                 "target": jnp.asarray(rng.integers(0, 10, (16,), dtype=np.int32))}
        losses = []
        for _ in range(6):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]

    def test_dawn_zoo_graph_variant(self):
        from tpu_compressed_dp.harness.dawn import MODELS

        net = MODELS["resnet9_graph"](0.25)
        params, stats = init_model(net, jax.random.key(0),
                                   jnp.zeros((1, 32, 32, 3), jnp.float32))
        logits, _ = make_apply_fn(net)(params, stats,
                                       jnp.ones((2, 32, 32, 3)), False, {})
        assert logits.shape == (2, 10)
