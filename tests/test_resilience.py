"""Failure detection + crash recovery tests."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_compressed_dp.utils import resilience

pytestmark = pytest.mark.quick  # fast tier (VERDICT r2 #10)



class TestHeartbeat:
    def test_write_read_stale(self, tmp_path):
        p = str(tmp_path / "hb.json")
        hb = resilience.Heartbeat(p, interval_s=0.05, payload={"rank": 0})
        hb.update(step=42, loss=1.5)
        time.sleep(0.15)
        hb.stop()
        rec = resilience.read_heartbeat(p)
        assert rec["step"] == 42 and rec["rank"] == 0 and rec["loss"] == 1.5
        assert not resilience.is_stale(p, max_age_s=10.0)
        assert resilience.is_stale(p, max_age_s=0.0)
        assert resilience.is_stale(str(tmp_path / "missing.json"), 1.0)


class TestRecovery:
    def _tiny_state(self):
        from tpu_compressed_dp.train.optim import SGD
        from tpu_compressed_dp.train.state import TrainState

        params = {"w": jnp.zeros((4,))}
        opt = SGD(lr=0.1)
        return TrainState.create(params, {}, opt.init(params), (),
                                 jax.random.key(0))

    def test_recovers_from_transient_failure(self, tmp_path):
        from tpu_compressed_dp.utils.checkpoint import Checkpointer
        import dataclasses

        ckpt = Checkpointer(str(tmp_path / "ck"))
        state = self._tiny_state()
        crashes = {"left": 2}

        def epoch_fn(state, epoch):
            if epoch == 3 and crashes["left"] > 0:
                crashes["left"] -= 1
                raise RuntimeError("injected device loss")
            state = dataclasses.replace(
                state,
                step=state.step + 1,
                params={"w": state.params["w"] + 1.0},
            )
            ckpt.save(state, {"epoch": epoch})
            return state

        final, info = resilience.run_with_recovery(
            epoch_fn, state, epochs=6, checkpointer=ckpt, max_retries=3)
        ckpt.close()
        assert info["restores"] == 2
        assert int(final.step) == 6
        np.testing.assert_allclose(np.asarray(final.params["w"]), 6.0)

    def test_retry_budget_exhausted(self, tmp_path):
        from tpu_compressed_dp.utils.checkpoint import Checkpointer

        ckpt = Checkpointer(str(tmp_path / "ck"))
        state = self._tiny_state()
        ckpt.save(state, {"epoch": -1})

        def always_fails(state, epoch):
            raise RuntimeError("permanent")

        with pytest.raises(RuntimeError, match="permanent"):
            resilience.run_with_recovery(
                always_fails, state, epochs=2, checkpointer=ckpt, max_retries=2)
        ckpt.close()

    def test_no_checkpointer_reraises(self):
        def fails(state, epoch):
            raise RuntimeError("boom")

        with pytest.raises(RuntimeError, match="boom"):
            resilience.run_with_recovery(fails, self._tiny_state(), epochs=1)
