"""Failure detection + crash recovery tests."""

import os
import signal
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_compressed_dp.utils import resilience

pytestmark = pytest.mark.quick  # fast tier (VERDICT r2 #10)



class TestHeartbeat:
    def test_write_read_stale(self, tmp_path):
        p = str(tmp_path / "hb.json")
        hb = resilience.Heartbeat(p, interval_s=0.05, payload={"rank": 0})
        hb.update(step=42, loss=1.5)
        time.sleep(0.15)
        hb.stop()
        rec = resilience.read_heartbeat(p)
        assert rec["step"] == 42 and rec["rank"] == 0 and rec["loss"] == 1.5
        assert not resilience.is_stale(p, max_age_s=10.0)
        assert resilience.is_stale(p, max_age_s=0.0)
        assert resilience.is_stale(str(tmp_path / "missing.json"), 1.0)

    def test_concurrent_update_hammer(self, tmp_path):
        """Regression (ISSUE 3 satellite): update() mutating the payload
        while the writer thread serialises it raised 'dict changed size
        during iteration' and silently killed the writer.  Hammer the
        payload with growing/shrinking key sets against a hot writer and
        assert the writer survives with valid JSON and no recorded error."""
        p = str(tmp_path / "hb.json")
        hb = resilience.Heartbeat(p, interval_s=0.0005, payload={"rank": 0})
        deadline = time.time() + 0.6
        i = 0
        while time.time() < deadline:
            i += 1
            # churn the key SET (not just values): iteration-order breakage
            # needs insertions/deletions mid-dump
            payload = {f"k{j}_{i % 7}": float(j) for j in range(40)}
            hb.update(step=i, **payload)
            if i % 200 == 0:
                time.sleep(0.001)  # let the writer thread in
        assert hb._thread.is_alive(), "writer thread died mid-run"
        assert hb.last_error is None, hb.last_error
        hb.stop()
        rec = resilience.read_heartbeat(p)
        assert rec is not None and rec["step"] == i

    def test_incarnation_defaults_to_restart_count(self, tmp_path,
                                                   monkeypatch):
        """The heartbeat record carries the incarnation the elastic gossip
        disambiguates stale files with; it seeds from TCDP_RESTART_COUNT,
        which `tools/watchdog.py --relaunch` exports to each child."""
        p = str(tmp_path / "hb.json")
        monkeypatch.setenv("TCDP_RESTART_COUNT", "3")
        hb = resilience.Heartbeat(p, interval_s=10.0)
        hb.update(step=1)
        hb.stop()
        assert resilience.read_heartbeat(p)["incarnation"] == 3
        # explicit argument wins over the environment
        monkeypatch.setenv("TCDP_RESTART_COUNT", "9")
        hb2 = resilience.Heartbeat(p, interval_s=10.0, incarnation=1)
        hb2.update(step=2)
        hb2.stop()
        assert resilience.read_heartbeat(p)["incarnation"] == 1
        # absent/garbage env -> incarnation 0 (first life)
        monkeypatch.delenv("TCDP_RESTART_COUNT")
        hb3 = resilience.Heartbeat(p, interval_s=10.0)
        hb3.update(step=3)
        hb3.stop()
        assert resilience.read_heartbeat(p)["incarnation"] == 0


class TestTornReads:
    """read_heartbeat must answer None — never raise — on the torn/partial
    states a reader can catch a gossip directory in; the writer side is
    atomic (tmp + os.replace), so a COMPLETE read is always valid JSON."""

    def test_truncated_json_reads_none(self, tmp_path):
        p = tmp_path / "hb.json"
        p.write_text('{"ts": 123.0, "st')        # torn mid-record
        assert resilience.read_heartbeat(str(p)) is None
        assert resilience.is_stale(str(p), max_age_s=1e9)

    def test_binary_garbage_reads_none(self, tmp_path):
        p = tmp_path / "hb.json"
        p.write_bytes(b"\xff\xfe\x00garbage\x80")
        assert resilience.read_heartbeat(str(p)) is None

    def test_empty_and_non_dict_read_none(self, tmp_path):
        p = tmp_path / "hb.json"
        p.write_text("")
        assert resilience.read_heartbeat(str(p)) is None
        p.write_text("[1, 2, 3]")                # valid JSON, wrong shape
        assert resilience.read_heartbeat(str(p)) is None
        p.write_text('"ts"')
        assert resilience.read_heartbeat(str(p)) is None

    def test_non_numeric_ts_is_stale(self, tmp_path):
        p = tmp_path / "hb.json"
        p.write_text('{"ts": "soon", "step": 1}')
        assert resilience.is_stale(str(p), max_age_s=1e9)
        p.write_text('{"ts": true, "step": 1}')  # bool is not a timestamp
        assert resilience.is_stale(str(p), max_age_s=1e9)

    def test_writer_replace_is_atomic_under_hammer(self, tmp_path):
        """A hot writer + a hot reader: every read observes either None
        (file not yet created) or a COMPLETE record — no partial JSON ever
        surfaces through the tmp+replace protocol."""
        p = str(tmp_path / "hb.json")
        hb = resilience.Heartbeat(p, interval_s=0.0005)
        deadline = time.time() + 0.5
        reads = 0
        while time.time() < deadline:
            hb.update(step=reads)
            rec = resilience.read_heartbeat(p)
            if rec is not None:
                assert "ts" in rec and "incarnation" in rec, rec
                reads += 1
        hb.stop()
        assert reads > 0, "reader never observed a complete record"


class TestRecovery:
    def _tiny_state(self):
        from tpu_compressed_dp.train.optim import SGD
        from tpu_compressed_dp.train.state import TrainState

        params = {"w": jnp.zeros((4,))}
        opt = SGD(lr=0.1)
        return TrainState.create(params, {}, opt.init(params), (),
                                 jax.random.key(0))

    def test_recovers_from_transient_failure(self, tmp_path):
        from tpu_compressed_dp.utils.checkpoint import Checkpointer
        import dataclasses

        ckpt = Checkpointer(str(tmp_path / "ck"))
        state = self._tiny_state()
        crashes = {"left": 2}

        def epoch_fn(state, epoch):
            if epoch == 3 and crashes["left"] > 0:
                crashes["left"] -= 1
                raise RuntimeError("injected device loss")
            state = dataclasses.replace(
                state,
                step=state.step + 1,
                params={"w": state.params["w"] + 1.0},
            )
            ckpt.save(state, {"epoch": epoch})
            return state

        final, info = resilience.run_with_recovery(
            epoch_fn, state, epochs=6, checkpointer=ckpt, max_retries=3)
        ckpt.close()
        assert info["restores"] == 2
        assert int(final.step) == 6
        np.testing.assert_allclose(np.asarray(final.params["w"]), 6.0)

    def test_retry_budget_exhausted(self, tmp_path):
        from tpu_compressed_dp.utils.checkpoint import Checkpointer

        ckpt = Checkpointer(str(tmp_path / "ck"))
        state = self._tiny_state()
        ckpt.save(state, {"epoch": -1})

        def always_fails(state, epoch):
            raise RuntimeError("permanent")

        with pytest.raises(RuntimeError, match="permanent"):
            resilience.run_with_recovery(
                always_fails, state, epochs=2, checkpointer=ckpt, max_retries=2)
        ckpt.close()

    def test_no_checkpointer_reraises(self):
        def fails(state, epoch):
            raise RuntimeError("boom")

        with pytest.raises(RuntimeError, match="boom"):
            resilience.run_with_recovery(fails, self._tiny_state(), epochs=1)

    def test_failure_before_first_checkpoint_raises_original(self, tmp_path):
        """Satellite fix: a crash before ANY checkpoint exists used to
        surface as the restore's FileNotFoundError, masking the actual
        training failure."""
        from tpu_compressed_dp.utils.checkpoint import Checkpointer

        ckpt = Checkpointer(str(tmp_path / "ck"))  # empty directory

        def fails(state, epoch):
            raise RuntimeError("the real training failure")

        with pytest.raises(RuntimeError, match="the real training failure"):
            resilience.run_with_recovery(
                fails, self._tiny_state(), epochs=2, checkpointer=ckpt,
                max_retries=3)
        ckpt.close()

    def test_replay_epoch_when_meta_lacks_epoch(self, tmp_path):
        """Satellite coverage: checkpoint meta without 'epoch' falls back to
        replaying the FAILED epoch (epoch = (epoch-1) + 1), not skipping
        ahead or rewinding to zero."""
        from tpu_compressed_dp.utils.checkpoint import Checkpointer
        import dataclasses

        ckpt = Checkpointer(str(tmp_path / "ck"))
        state = self._tiny_state()
        calls = []
        crashes = {"left": 1}

        def epoch_fn(state, epoch):
            calls.append(epoch)
            if epoch == 2 and crashes["left"] > 0:
                crashes["left"] -= 1
                raise RuntimeError("transient")
            state = dataclasses.replace(state, step=state.step + 1)
            ckpt.save(state, {})  # meta has NO 'epoch' key
            return state

        final, info = resilience.run_with_recovery(
            epoch_fn, state, epochs=4, checkpointer=ckpt, max_retries=2)
        ckpt.close()
        assert calls == [0, 1, 2, 2, 3]
        assert info["restores"] == 1
        assert int(final.step) == 4

    def test_retry_budget_resets_only_on_progress(self, tmp_path):
        """Satellite coverage: max_retries bounds CONSECUTIVE failures; a
        completed epoch resets the budget, so 4 total failures spread as
        2+2 survive max_retries=2 while 3 consecutive do not."""
        from tpu_compressed_dp.utils.checkpoint import Checkpointer
        import dataclasses

        def run(fail_plan, epochs, max_retries, subdir):
            ckpt = Checkpointer(str(tmp_path / subdir))
            state = self._tiny_state()
            remaining = dict(fail_plan)

            def epoch_fn(state, epoch):
                if remaining.get(epoch, 0) > 0:
                    remaining[epoch] -= 1
                    raise RuntimeError(f"flaky at {epoch}")
                state = dataclasses.replace(state, step=state.step + 1)
                ckpt.save(state, {"epoch": epoch})
                return state

            try:
                return resilience.run_with_recovery(
                    epoch_fn, state, epochs=epochs, checkpointer=ckpt,
                    max_retries=max_retries)
            finally:
                ckpt.close()

        # 2 failures at epoch 1, then 2 at epoch 3: never >2 consecutive
        final, info = run({1: 2, 3: 2}, epochs=5, max_retries=2, subdir="a")
        assert info["restores"] == 4
        assert int(final.step) == 5
        # 3 consecutive failures at epoch 1 exhaust max_retries=2
        with pytest.raises(RuntimeError, match="flaky at 1"):
            run({1: 3}, epochs=3, max_retries=2, subdir="b")

    def test_corrupt_latest_falls_back_without_burning_retries(self, tmp_path):
        """ISSUE 9 satellite: a torn/bit-flipped LATEST checkpoint at
        restore time is handled inside Checkpointer.restore (walk back one
        step, replay), never surfaced as another failure against the retry
        budget — max_retries=1 survives crash + corrupt latest."""
        import dataclasses

        from tpu_compressed_dp.utils.checkpoint import Checkpointer

        def flip(directory, step):
            step_dir = str(tmp_path / "ck" / str(step))
            target, size = None, -1
            for root, _, names in os.walk(step_dir):
                for name in names:
                    fp = os.path.join(root, name)
                    if os.path.getsize(fp) > size:
                        target, size = fp, os.path.getsize(fp)
            with open(target, "r+b") as f:
                f.seek(size // 2)
                b = f.read(1)
                f.seek(size // 2)
                f.write(bytes([b[0] ^ 0xFF]))

        ckpt = Checkpointer(str(tmp_path / "ck"))
        state = self._tiny_state()
        calls = []
        tripped = {"done": False}

        def epoch_fn(state, epoch):
            calls.append(epoch)
            if epoch == 2 and not tripped["done"]:
                tripped["done"] = True
                flip(ckpt.directory, 2)  # corrupt the newest save (step 2)
                raise RuntimeError("device loss over a torn write")
            state = dataclasses.replace(state, step=state.step + 1)
            ckpt.save(state, {"epoch": epoch})
            return state

        final, info = resilience.run_with_recovery(
            epoch_fn, state, epochs=4, checkpointer=ckpt, max_retries=1)
        # restore walked back to step 1 (epoch 0) and replayed epochs 1..3
        assert calls == [0, 1, 2, 1, 2, 3]
        assert info["restores"] == 1
        assert int(final.step) == 4
        assert ckpt.metrics()["ckpt/rollback_steps"] == 1.0
        ckpt.close()


class TestPreemption:
    def test_sigterm_sets_flag_and_check_raises(self):
        h = resilience.PreemptionHandler(log=lambda s: None).install()
        assert h.installed
        try:
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.time() + 5.0
            while not h.triggered and time.time() < deadline:
                time.sleep(0.001)
            assert h.triggered
            with pytest.raises(resilience.Preempted) as ei:
                h.check(7)
            assert ei.value.step == 7
            assert ei.value.signum == signal.SIGTERM
            # the flag is sticky: every later step boundary raises too
            with pytest.raises(resilience.Preempted):
                h.check(8)
        finally:
            h.uninstall()

    def test_uninstall_restores_previous_handlers(self):
        prev_term = signal.getsignal(signal.SIGTERM)
        prev_int = signal.getsignal(signal.SIGINT)
        h = resilience.PreemptionHandler(log=lambda s: None).install()
        assert signal.getsignal(signal.SIGTERM) == h._on_signal
        assert signal.getsignal(signal.SIGINT) == h._on_signal
        h.uninstall()
        assert signal.getsignal(signal.SIGTERM) == prev_term
        assert signal.getsignal(signal.SIGINT) == prev_int
        assert not h.installed
        h.uninstall()  # idempotent

    def test_off_main_thread_degrades_to_inert(self):
        """signal.signal only works on the main thread; a harness driven
        from a worker thread gets an inert handler, not a crash."""
        prev_term = signal.getsignal(signal.SIGTERM)
        out = {}

        def worker():
            h = resilience.PreemptionHandler(log=lambda s: None).install()
            out["installed"] = h.installed
            h.check(1)      # never raises: no signal can reach the flag
            h.uninstall()   # no-op, must not touch the main thread's handlers

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert out["installed"] is False
        assert signal.getsignal(signal.SIGTERM) == prev_term

    def test_run_with_recovery_reraises_preempted(self):
        """Preemption must reach the harness's emergency-save path, not the
        restore-and-replay budget — no restore, no retry."""
        calls = []

        class NeverRestore:
            def restore(self, state, step=None):
                raise AssertionError(
                    "preemption must not trigger a restore")

        def epoch_fn(state, epoch):
            calls.append(epoch)
            raise resilience.Preempted("preempted", step=3,
                                       signum=signal.SIGTERM)

        with pytest.raises(resilience.Preempted):
            resilience.run_with_recovery(
                epoch_fn, object(), epochs=3, checkpointer=NeverRestore(),
                max_retries=5)
        assert calls == [0]


class TestCheckpointStaleCheck:
    def test_ckpt_age_adds_heartbeat_age(self):
        """The watchdog's --max_ckpt_age check: the payload's ckpt_age_s was
        computed at heartbeat-write time, so the heartbeat's own age is
        added — a dying writer cannot freeze the checkpoint clock."""
        now = 1000.0
        hb = {"ts": now - 10.0, "step": 5, "last_ckpt_step": 4,
              "ckpt_age_s": 100.0}
        assert resilience.check_heartbeat(
            "x", max_age_s=60, max_ckpt_age_s=200.0, now=now, hb=hb) == []
        # 100 (payload) + 10 (heartbeat age) = 110 > 105, though the payload
        # value alone would pass
        probs = resilience.check_heartbeat(
            "x", max_age_s=60, max_ckpt_age_s=105.0, now=now, hb=hb)
        assert len(probs) == 1 and "checkpoint stale" in probs[0]
        assert "last_ckpt_step=4" in probs[0]
        # absent field (checkpointing off) skips the check, not fails it
        hb2 = {"ts": now, "step": 5}
        assert resilience.check_heartbeat(
            "x", max_age_s=60, max_ckpt_age_s=1.0, now=now, hb=hb2) == []

    def test_stream_lag_adds_heartbeat_age(self):
        """The watchdog's --max_stream_lag check mirrors the checkpoint
        clock: the payload's stream_lag_s plus the heartbeat's own age,
        so a dying writer cannot freeze the stream clock either."""
        now = 1000.0
        hb = {"ts": now - 10.0, "step": 5, "stream_last_step": 4,
              "stream_lag_s": 100.0}
        assert resilience.check_heartbeat(
            "x", max_age_s=60, max_stream_lag_s=200.0, now=now, hb=hb) == []
        # 100 (payload) + 10 (heartbeat age) = 110 > 105
        probs = resilience.check_heartbeat(
            "x", max_age_s=60, max_stream_lag_s=105.0, now=now, hb=hb)
        assert len(probs) == 1 and "stream stale" in probs[0]
        assert "stream_last_step=4" in probs[0]
        # absent field (streaming off) skips the check, not fails it
        hb2 = {"ts": now, "step": 5}
        assert resilience.check_heartbeat(
            "x", max_age_s=60, max_stream_lag_s=1.0, now=now, hb=hb2) == []
