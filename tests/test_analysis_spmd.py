"""Unit tests for tcdp-lint pass 1 (tpu_compressed_dp/analysis/spmd.py).

Each TCDP00x check must fire on a seeded synthetic jaxpr and stay silent on
the matching clean shape.  The real-tree gate (quick profile at zero
findings) lives in tests/test_lint.py.
"""

import types

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from tpu_compressed_dp.analysis.spmd import (check_barrier_chain,
                                             check_chunk_plan,
                                             check_control_flow,
                                             check_donation,
                                             check_jaxpr_budget,
                                             check_signature_match,
                                             collective_signature,
                                             count_eqns)
from tpu_compressed_dp.compat import shard_map
from tpu_compressed_dp.parallel.mesh import make_data_mesh

pytestmark = pytest.mark.quick


@pytest.fixture(scope="module")
def mesh():
    return make_data_mesh(4)


def _smap(fn, mesh, n_in=1):
    return shard_map(fn, mesh=mesh, in_specs=(P(),) * n_in, out_specs=P())


def _codes(findings):
    return [f.code for f in findings]


class TestControlFlow:
    def test_cond_branch_asymmetry_fires(self, mesh):
        def f(x):
            return jax.lax.cond(x[0] > 0.0,
                                lambda v: jax.lax.psum(v, "data"),
                                lambda v: v, x)

        jx = jax.make_jaxpr(_smap(f, mesh))(jnp.ones((4,)))
        assert _codes(check_control_flow(jx, config="fix")) == ["TCDP001"]

    def test_symmetric_cond_passes(self, mesh):
        def f(x):
            return jax.lax.cond(x[0] > 0.0,
                                lambda v: jax.lax.psum(v, "data"),
                                lambda v: jax.lax.psum(2.0 * v, "data"), x)

        jx = jax.make_jaxpr(_smap(f, mesh))(jnp.ones((4,)))
        assert check_control_flow(jx) == []

    def test_data_predicated_while_fires(self, mesh):
        def f(x):
            def body(v):
                return jax.lax.psum(v, "data") * 0.4

            return jax.lax.while_loop(lambda v: jnp.sum(v) > 1.0, body, x)

        jx = jax.make_jaxpr(_smap(f, mesh))(jnp.ones((4,)))
        assert _codes(check_control_flow(jx)) == ["TCDP001"]

    def test_counter_loop_with_collective_passes(self, mesh):
        def f(x):
            return jax.lax.fori_loop(
                0, 3, lambda i, v: jax.lax.psum(v, "data") * 0.3, x)

        jx = jax.make_jaxpr(_smap(f, mesh))(jnp.ones((4,)))
        assert check_control_flow(jx) == []

    def test_scan_with_collective_passes(self, mesh):
        # static trip count: the pp schedule's ppermute-in-scan shape
        def f(x):
            def body(c, _):
                return jax.lax.psum(c, "data") * 0.25, ()

            out, _ = jax.lax.scan(body, x, None, length=3)
            return out

        jx = jax.make_jaxpr(_smap(f, mesh))(jnp.ones((4,)))
        assert check_control_flow(jx) == []


class TestSignature:
    def _sig(self, fn, mesh, *args):
        return collective_signature(jax.make_jaxpr(_smap(
            fn, mesh, n_in=len(args)))(*args))

    def test_signature_sees_through_containers(self, mesh):
        def f(x):
            return jax.jit(lambda v: jax.lax.psum(v, "data"))(x)

        sig = self._sig(f, mesh, jnp.ones((4,)))
        assert [s[0] for s in sig] == ["psum"]
        assert sig[0][1] == ("data",)

    def test_retrace_match_and_mismatch(self, mesh):
        def f(x):
            return jax.lax.psum(x, "data")

        def g(x):
            return jax.lax.all_gather(x, "data")

        a = self._sig(f, mesh, jnp.ones((4,)))
        b = self._sig(g, mesh, jnp.ones((4,)))
        assert check_signature_match(a, a, "t1", "t2") == []
        assert _codes(check_signature_match(a, b, "t1", "t2")) == ["TCDP002"]

    def test_multiset_mode_ignores_order(self, mesh):
        def f(x):
            return jax.lax.psum(x, "data"), jax.lax.all_gather(x, "data")

        def g(x):
            return jax.lax.all_gather(x, "data"), jax.lax.psum(x, "data")

        a = self._sig(f, mesh, jnp.ones((4,)))
        b = self._sig(g, mesh, jnp.ones((4,)))
        assert check_signature_match(a, b, "f", "g", ordered=False) == []
        assert _codes(check_signature_match(a, b, "f", "g")) == ["TCDP002"]


class TestDonation:
    def test_unmatchable_donation_fires(self):
        def f(x):
            return jnp.sum(x)  # scalar out: nothing to alias f32[8] into

        out = check_donation(f, (jnp.ones((8,)),), (0,))
        assert _codes(out) == ["TCDP003"]

    def test_matching_donation_passes(self):
        def f(x):
            return x * 2.0

        assert check_donation(f, (jnp.ones((8,)),), (0,)) == []

    def test_pytree_donation_multiset(self):
        def f(state):
            return {"a": state["a"] + 1.0}  # drops state["b"]

        state = {"a": jnp.ones((4,)), "b": jnp.ones((3, 2))}
        out = check_donation(f, (state,), (0,))
        assert _codes(out) == ["TCDP003"]
        assert "[3, 2]" in out[0].message


def _plan(index, lo, hi, goff, ng):
    return types.SimpleNamespace(index=index, leaf_lo=lo, leaf_hi=hi,
                                 group_offset=goff, n_groups=ng)


class TestChunkPlan:
    def test_valid_plan_passes(self):
        plans = [_plan(0, 0, 2, 0, 2), _plan(1, 2, 5, 2, 3)]
        assert check_chunk_plan(plans, n_leaves=5, n_groups=5) == []

    def test_duplicate_group_offset_fires(self):
        plans = [_plan(0, 0, 2, 0, 2), _plan(1, 2, 5, 0, 3)]
        out = check_chunk_plan(plans, n_leaves=5, n_groups=5)
        assert "TCDP004" in _codes(out)

    def test_leaf_gap_fires(self):
        plans = [_plan(0, 0, 2, 0, 2), _plan(1, 3, 5, 2, 2)]
        out = check_chunk_plan(plans, n_leaves=5, n_groups=4)
        assert "TCDP004" in _codes(out)


class TestJaxprBudget:
    def test_unrolled_loop_fires(self):
        # the TCDP005 failure shape: a Python loop over "leaves" stamping
        # its body into the trace once per iteration
        def f(x):
            for _ in range(64):
                x = jnp.sin(x) * 2.0 + 1.0
            return x

        jx = jax.make_jaxpr(f)(jnp.ones((4,)))
        out = check_jaxpr_budget(jx, budget=100, config="fix")
        assert _codes(out) == ["TCDP005"]
        assert "budget 100" in out[0].message

    def test_rolled_loop_passes(self):
        # the same computation as a fori_loop counts its body ONCE
        def f(x):
            return jax.lax.fori_loop(
                0, 64, lambda i, v: jnp.sin(v) * 2.0 + 1.0, x)

        jx = jax.make_jaxpr(f)(jnp.ones((4,)))
        assert count_eqns(jx) < 64
        assert check_jaxpr_budget(jx, budget=100) == []

    def test_count_recurses_into_subjaxprs(self):
        def f(x):
            return jax.jit(lambda v: jnp.sin(v) + jnp.cos(v))(x)

        jx = jax.make_jaxpr(f)(jnp.ones((4,)))
        assert count_eqns(jx) >= 3  # pjit eqn + sin + cos + add inside


class TestBarrierChain:
    def test_unchained_chunks_fire(self, mesh):
        def f(x, y):
            return jax.lax.psum(x, "data"), jax.lax.psum(y, "data")

        jx = jax.make_jaxpr(_smap(f, mesh, n_in=2))(jnp.ones((4,)),
                                                    jnp.ones((4,)))
        assert _codes(check_barrier_chain(jx, n_chunks=2)) == ["TCDP004"]

    def test_chained_chunks_pass(self, mesh):
        def f(x, y):
            a = jax.lax.psum(x, "data")
            # the overlap engine's issue-order link: chunk 2's input passes
            # through a barrier fed by chunk 1's collective
            a2, y2 = jax.lax.optimization_barrier((a, y))
            return a2, jax.lax.psum(y2, "data")

        jx = jax.make_jaxpr(_smap(f, mesh, n_in=2))(jnp.ones((4,)),
                                                    jnp.ones((4,)))
        assert check_barrier_chain(jx, n_chunks=2) == []
