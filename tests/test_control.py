"""Closed-loop adaptive compression control plane (tpu_compressed_dp/control/).

The ISSUE 11 acceptance surface: the ladder/config contracts, the decision
rule, window accounting on the applied-update clock, bitwise decision replay
through a ControlState serialisation round trip, rung-target recomputation
through a W-1 elastic remesh, and the dawn harness end to end under
``--adaptive`` with the event stream parsed back by tools/control_report.py.
"""

import dataclasses
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_compressed_dp.control import (
    ControlConfig, Controller, build_ladder, comp_for_rung,
    control_from_dict, control_to_dict, hideable_budget_ms,
    init_control_state, ladder_knob, migrate_comp_state, modeled_comm_ms,
)
from tpu_compressed_dp.parallel.dp import CompressionConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


class _Recorder:
    def __init__(self):
        self.events = []

    def emit(self, kind, **fields):
        self.events.append((kind, fields))


# ------------------------------------------------------------ config/ladder

class TestConfigAndLadder:
    def test_rejects_untunable_method(self):
        with pytest.raises(ValueError, match="tunes"):
            ControlConfig(method="qsgd", rungs=(0.5, 0.25))

    def test_rejects_degenerate_ladders(self):
        with pytest.raises(ValueError, match=">= 2 rungs"):
            ControlConfig(method="topk", rungs=(0.5,))
        with pytest.raises(ValueError, match="descend"):
            ControlConfig(method="topk", rungs=(0.25, 0.5))
        with pytest.raises(ValueError, match=r"\(0, 1\]"):
            ControlConfig(method="topk", rungs=(1.5, 0.5))
        with pytest.raises(ValueError, match="integers"):
            ControlConfig(method="powersgd", rungs=(2.5, 1.0))
        with pytest.raises(ValueError, match="window"):
            ControlConfig(method="topk", rungs=(0.5, 0.25), window=0)
        with pytest.raises(ValueError, match="deadband"):
            ControlConfig(method="topk", rungs=(0.5, 0.25), deadband=1.0)
        with pytest.raises(ValueError, match="signal"):
            ControlConfig(method="topk", rungs=(0.5, 0.25), signal="psychic")
        with pytest.raises(ValueError, match="start_rung"):
            ControlConfig(method="topk", rungs=(0.5, 0.25), start_rung=2)

    def test_default_ladder_anchors_at_static_config(self):
        # rung 0 == the CLI-configured knob: an adaptive run that never
        # acts behaves exactly like the static run
        assert build_ladder("topk", 0.5, 4) == (0.5, 0.25, 0.125, 0.0625,
                                                0.03125)
        assert build_ladder("powersgd", 0.5, 8) == (8.0, 4.0, 2.0, 1.0)
        # the ratio floor: never descend below ~1e-3
        lo = build_ladder("topk", 0.004, 4)
        assert lo[0] == 0.004 and min(lo) >= 1e-3

    def test_knob_and_comp_for_rung(self):
        assert ladder_knob("topk") == "ratio"
        assert ladder_knob("powersgd") == "rank"
        with pytest.raises(ValueError, match="knob"):
            ladder_knob("terngrad")
        cfg = ControlConfig(method="topk", rungs=(0.5, 0.125))
        base = CompressionConfig(method="topk", ratio=0.5,
                                 error_feedback=True)
        assert comp_for_rung(base, cfg, 1).ratio == 0.125
        assert comp_for_rung(base, cfg, 1).error_feedback is True
        rcfg = ControlConfig(method="powersgd", rungs=(4.0, 2.0))
        rbase = CompressionConfig(method="powersgd", rank=4)
        assert comp_for_rung(rbase, rcfg, 1).rank == 2

    def test_migrate_comp_state_keeps_warm_columns(self):
        from tpu_compressed_dp.parallel.dp import init_comp_state

        grads = {"w": jnp.zeros((64, 32), jnp.float32)}
        old = CompressionConfig(method="powersgd", rank=4)
        new = CompressionConfig(method="powersgd", rank=2)
        comp = init_comp_state(grads, old, 4)
        warm = {k: np.asarray(v) + 1.0 for k, v in comp.items()}
        migrated = migrate_comp_state(warm, grads, old, new, 4)
        for k, q in migrated.items():
            assert q.shape[-1] == 2
            # the first min(r_old, r_new) columns carry the learnt subspace
            np.testing.assert_array_equal(np.asarray(q),
                                          warm[k][..., :2])
        # stateless / no-op switches pass through untouched
        assert migrate_comp_state((), grads, old, new, 4) == ()
        assert migrate_comp_state(warm, grads, old, old, 4) is warm


# ------------------------------------------------------------ decision rule

class TestDecisionRule:
    CFG = ControlConfig(method="topk", rungs=(0.5, 0.25, 0.125),
                        deadband=0.25, budget_ms=1.0)

    def test_signal_models(self):
        # 1e6 bits over 100 Mbit/s = 10 ms
        assert modeled_comm_ms(1e6, 100.0) == pytest.approx(10.0)
        assert hideable_budget_ms(self.CFG) == 1.0  # pinned
        free = ControlConfig(method="topk", rungs=(0.5, 0.25))
        assert hideable_budget_ms(free, compute_ms=8.0,
                                  hideable_fraction=0.5) == 4.0
        with pytest.raises(ValueError, match="compute_ms"):
            hideable_budget_ms(free)

    def test_rule_directions(self):
        c = Controller(self.CFG)
        assert c._decide(0, 2.0, 1.0) == (1, "down")      # above the band
        assert c._decide(2, 2.0, 1.0) == (2, "hold")      # floor pins
        assert c._decide(0, 0.1, 1.0) == (0, "hold")      # ceiling pins
        # below the band AND the 2x-projected comm still fits -> up
        assert c._decide(1, 0.6, 1.0) == (0, "up")
        # below the band but the cheaper rung would blow the band -> hold
        # (0.7 * 2 = 1.4 > 1.25): the anti-ping-pong projection
        assert c._decide(1, 0.7, 1.0) == (1, "hold")
        assert c._decide(0, 1.1, 1.0) == (0, "hold")      # inside the band

    def test_window_accounting_on_applied_clock(self):
        cfg = dataclasses.replace(self.CFG, window=4)
        c = Controller(cfg)
        cs = init_control_state(cfg)
        sig = c.window_signals(mean_bits=1e6)  # 10 ms >> 1 ms budget
        cs, decs = c.tick(cs, applied=2, signals=sig)
        assert decs == [] and int(cs.win_updates) == 2
        # a skip-only span (applied clock frozen) is a no-op tick
        cs2, decs = c.tick(cs, applied=2, signals=sig)
        assert decs == [] and cs2 is cs
        cs, (dec,) = c.tick(cs, applied=5, signals=sig)
        assert (dec.index, dec.applied, dec.window_start) == (0, 5, 0)
        assert dec.updates == 5 and dec.direction == "down"
        assert (dec.rung_from, dec.rung_to) == (0, 1)
        assert dec.comm_ms == pytest.approx(10.0)
        # the window closed: accumulators reset, cursor advanced
        assert int(cs.win_updates) == 0 and float(cs.win_bits) == 0.0
        assert int(cs.window_start) == 5 and int(cs.decisions) == 1

    def test_every_close_emits_including_holds(self):
        rec = _Recorder()
        cfg = dataclasses.replace(self.CFG, window=1)
        c = Controller(cfg, events=rec)
        cs = init_control_state(cfg)
        # in-band comm: a hold, but still a decision record
        sig = c.window_signals(mean_bits=1.0e5)  # 1.0 ms == budget
        cs, (dec,) = c.tick(cs, applied=1, signals=sig)
        assert dec.direction == "hold"
        assert [k for k, _ in rec.events] == ["control_decision"]
        assert rec.events[0][1]["knob"] == "ratio"
        assert rec.events[0][1]["direction"] == "hold"

    def test_metrics_and_heartbeat_surfaces(self):
        c = Controller(self.CFG)
        cs = init_control_state(self.CFG)
        m = c.metrics(cs)
        assert set(m) == {"control/rung", "control/value",
                          "control/decisions", "control/window_updates",
                          "control/comm_ms", "control/budget_ms"}
        assert m["control/value"] == 0.5
        assert c.heartbeat_fields(cs) == {"control_rung": 0,
                                          "control_value": 0.5}
        # off state (control == ()) exports nothing
        assert c.metrics(()) == {} and c.heartbeat_fields(()) == {}


# ------------------------------------------------- closed-loop convergence

class TestClosedLoop:
    def test_converges_to_fitting_rung_from_both_sides(self):
        """The acceptance loop: synthetic comm exceeding the hideable
        budget converges DOWN to the rung whose (ratio-proportional) comm
        fits the band, within a handful of windows — and an over-compressed
        start converges UP to the same rung."""
        cfg = ControlConfig(method="topk", rungs=(0.5, 0.25, 0.125),
                            window=2, deadband=0.25, budget_ms=1.0,
                            bandwidth_mbps=100.0)

        def run(start_rung, n_windows=6):
            c = Controller(cfg)
            cs = init_control_state(
                dataclasses.replace(cfg, start_rung=start_rung))
            trail = []
            for w in range(n_windows):
                # billed bits track the live rung's keep ratio: 4e5 * ratio
                # bits/update -> comm 2.0/1.0/0.5 ms at rungs 0/1/2
                bits = 4e5 * cfg.rungs[int(cs.rung)]
                cs, decs = c.tick(cs, applied=2 * (w + 1),
                                  signals=c.window_signals(mean_bits=bits))
                assert len(decs) == 1
                trail.append(int(cs.rung))
            return trail

        down = run(start_rung=0)
        up = run(start_rung=2)
        # rung 1 (comm 1.0 == budget) is the equilibrium from either side,
        # reached within N windows and held thereafter
        assert down[0] == 1 and set(down[1:]) == {1}, down
        assert up[0] == 1 and set(up[1:]) == {1}, up

    def test_decisions_bitwise_through_state_round_trip(self):
        """Crash/resume at the ControlState layer: serialise mid-window
        (the Orbax dict form, through JSON to prove no live-object
        smuggling), resume with a FRESH Controller, and the decision
        stream matches the uninterrupted run field for field."""
        cfg = ControlConfig(method="topk", rungs=(0.5, 0.25, 0.125),
                            window=3, budget_ms=0.5)
        ticks = [(i + 1, 1e6) for i in range(10)]  # applied, bits

        def span(cs, controller, lo, hi):
            out = []
            for applied, bits in ticks[lo:hi]:
                cs, decs = controller.tick(
                    cs, applied=applied,
                    signals=controller.window_signals(mean_bits=bits))
                out += decs
            return cs, out

        clean_cs, clean = span(init_control_state(cfg), Controller(cfg),
                               0, len(ticks))
        # interrupt mid-window (tick 4 of window 2), round-trip the state
        cs, pre = span(init_control_state(cfg), Controller(cfg), 0, 4)
        blob = json.dumps({k: np.asarray(v).tolist()
                           for k, v in control_to_dict(cs).items()})
        cs2 = control_from_dict(json.loads(blob))
        assert int(cs2.win_updates) == 1  # the open window rode the blob
        cs2, post = span(cs2, Controller(cfg), 4, len(ticks))
        assert pre + post == clean
        for a, b in zip(jax.tree.leaves(clean_cs), jax.tree.leaves(cs2)):
            assert np.array_equal(np.asarray(a), np.asarray(b))


# --------------------------------------------------------- harness surface

class TestHarnessWiring:
    def _args(self, extra=()):
        from tpu_compressed_dp.harness import dawn

        return dawn.build_parser().parse_args(
            ["--synthetic", "--method", "Topk", "--compress", "layerwise",
             "--ratio", "0.5", "--error_feedback"] + list(extra))

    def test_build_control_defaults_and_rungs_flag(self):
        from tpu_compressed_dp.harness.loop import (build_control,
                                                    control_summary)

        comp = CompressionConfig(method="topk", ratio=0.5,
                                 error_feedback=True)
        assert build_control(self._args(), comp) is None  # flag off
        cfg = build_control(self._args(["--adaptive"]), comp)
        assert cfg.method == "topk" and cfg.rungs[0] == 0.5
        assert cfg.window == 8 and cfg.signal == "modeled"
        explicit = build_control(
            self._args(["--adaptive", "--adaptive_rungs", "0.5,0.1,0.02",
                        "--adaptive_window", "3"]), comp)
        assert explicit.rungs == (0.5, 0.1, 0.02) and explicit.window == 3
        # summary accounting: live rung + knob value; {} when off
        ctl = Controller(cfg)
        assert control_summary(ctl, init_control_state(cfg)) == {
            "rung": 0.0, "ratio": 0.5}
        assert control_summary(None, ()) == {}

    def test_build_control_refuses_untunable_method(self):
        from tpu_compressed_dp.harness.loop import build_control

        comp = CompressionConfig(method="terngrad")
        with pytest.raises(SystemExit, match="tunable"):
            build_control(self._args(["--adaptive"]), comp)

    def test_dawn_refuses_adaptive_plus_ratio_warmup(self, tmp_path):
        from tpu_compressed_dp.harness import dawn

        args = dawn.build_parser().parse_args(
            ["--synthetic", "--log_dir", str(tmp_path), "--method", "topk",
             "--compress", "layerwise", "--ratio", "0.1", "--adaptive",
             "--ratio_warmup_epochs", "4", "--epochs", "1"])
        with pytest.raises(ValueError, match="pick one"):
            dawn.run(args)

    def test_lm_refuses_pipeline_and_rank_knob(self):
        from tpu_compressed_dp.harness import lm

        with pytest.raises(ValueError, match="pipeline"):
            lm.main(["--preset", "tiny", "--dp", "2", "--pp", "2",
                     "--tp", "1", "--sp", "1", "--seq_len", "64",
                     "--global_batch", "8", "--microbatches", "2",
                     "--steps", "1", "--fp32", "--compress", "entiremodel",
                     "--method", "topk", "--ratio", "0.1", "--adaptive"])
        with pytest.raises(ValueError, match="CNN-harness-only"):
            lm.main(["--preset", "tiny", "--dp", "2", "--tp", "2",
                     "--sp", "2", "--seq_len", "64", "--global_batch", "8",
                     "--steps", "1", "--fp32", "--compress", "entiremodel",
                     "--method", "powersgd", "--rank", "4",
                     "--error_feedback", "--adaptive"])


# ------------------------------------------------------------------ elastic

def test_remesh_recomputes_rung_targets_without_wedging(mesh8):
    """A W-1 elastic remesh mid-adaptive-run: the step variant is rebuilt
    for the CURRENT rung over the survivor mesh, the controller keeps
    deciding on the applied-update clock, and the next rung switch traces
    cleanly at W-1 (no wedge, no stale-mesh step)."""
    from tpu_compressed_dp.models.common import init_model, make_apply_fn
    from tpu_compressed_dp.parallel.dp import init_comp_state, init_ef_state
    from tpu_compressed_dp.train.elastic import (ElasticConfig,
                                                 ElasticRuntime, PeerFailed)
    from tpu_compressed_dp.train.optim import SGD
    from tpu_compressed_dp.train.state import TrainState
    from tpu_compressed_dp.train.step import make_train_step
    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            return nn.Dense(4)(x.reshape((x.shape[0], -1)))

    base = CompressionConfig(method="topk", ratio=0.5, error_feedback=True,
                             granularity="entiremodel")
    cfg = ControlConfig(method="topk", rungs=(0.5, 0.25, 0.125), window=1,
                        budget_ms=0.5)
    module = Tiny()
    params, stats = init_model(module, jax.random.key(0),
                               jnp.zeros((1, 4, 4, 3), jnp.float32))
    opt = SGD(lr=0.05, momentum=0.0)
    W = int(mesh8.shape["data"])
    state = TrainState.create(
        params, stats, opt.init(params), init_ef_state(params, base, W),
        jax.random.key(1), comp=init_comp_state(params, base, W),
        control=init_control_state(cfg))
    controller = Controller(cfg)
    el = ElasticRuntime(ElasticConfig(ef_policy="fold"), mesh8,
                        log=lambda s: None)
    rng = np.random.RandomState(0)
    batch = {"input": jnp.asarray(rng.randn(56, 4, 4, 3).astype(np.float32)),
             "target": jnp.asarray(rng.randint(0, 4, 56).astype(np.int32))}

    def step_for(rung):
        return make_train_step(make_apply_fn(module), opt,
                               comp_for_rung(base, cfg, rung), el.mesh,
                               donate=False)

    def one_step(state):
        state, _ = step_for(int(state.control.rung))(state, batch)
        new_control, decs = controller.tick(
            state.control, applied=int(state.step),
            signals=controller.window_signals(mean_bits=1e6))
        return state.replace(control=new_control), decs

    state, decs = one_step(state)           # window closes: rung 0 -> 1
    assert decs[0].direction == "down" and int(state.control.rung) == 1

    state = el.handle_failure(state, PeerFailed((3,), step=1, reason="t"))
    assert el.world == W - 1
    # the survivor mesh retraces the CURRENT rung's variant and the
    # controller advances to the next rung target — nothing wedges
    state, decs = one_step(state)
    assert int(state.step) == 2
    assert decs[0].direction == "down" and int(state.control.rung) == 2
    for leaf in jax.tree.leaves(state.ef):
        assert np.asarray(leaf).shape[0] == W - 1


# ---------------------------------------------------------------- dawn e2e

@pytest.mark.slow  # ~34 s dawn compile; the in-process closed-loop
# convergence + resume rows keep the control plane in tier-1
def test_dawn_adaptive_e2e_and_control_report(tmp_path, mesh8):
    """The acceptance run: dawn under ``--adaptive`` with comm priced far
    above a pinned budget descends the rung ladder (the per-epoch sent
    fraction PROVES each rung's step variant actually ran), emits
    ``control_decision`` events and per-epoch control metrics, and
    tools/control_report.py + trace_report --control parse it all back."""
    from tpu_compressed_dp.harness import dawn

    ev_path = str(tmp_path / "events.jsonl")
    args = dawn.build_parser().parse_args(
        ["--synthetic", "--synthetic_n", "512", "--channels_scale", "0.125",
         "--log_dir", str(tmp_path), "--batch_size", "64", "--devices", "8",
         "--epochs", "3", "--momentum", "0.9", "--compress", "layerwise",
         "--method", "topk", "--ratio", "0.5", "--error_feedback",
         "--overlap", "2", "--adaptive", "--adaptive_window", "1",
         "--adaptive_budget_ms", "0.001", "--events", ev_path,
         "--prom", str(tmp_path / "m.prom")])
    summary = dawn.run(args)
    # window=1 at epoch cadence: one rung down per epoch, and the billed
    # sent fraction tracks the LIVE rung (0.25 traced for epoch 2's step)
    assert summary["rung"] == 3.0 and summary["ratio"] == 0.0625
    assert summary["sent frac"] == pytest.approx(0.125, rel=0.05)

    from tpu_compressed_dp.obs import export as obs_export

    events = obs_export.read_events(ev_path)
    decs = [e for e in events if e["kind"] == "control_decision"]
    assert [d["rung_to"] for d in decs] == [1, 2, 3]
    assert all(d["direction"] == "down" and d["knob"] == "ratio"
               for d in decs)
    epochs_rec = [e for e in events if e["kind"] == "epoch"]
    assert [e["control"]["control/rung"] for e in epochs_rec] == [1., 2., 3.]
    assert all(e["control"]["control/value"] == pytest.approx(
        0.5 * 2.0 ** -e["control"]["control/rung"]) for e in epochs_rec)

    # the offline reports parse the stream back
    import tools.control_report as cr
    import tools.trace_report as tr

    report = cr.render_report(events)
    assert "rung trajectory" in report and "down (0.5 -> 0.25)" in report
    assert "final rung=3" in report
    s = cr.summarize(cr.decision_rows(events))
    assert s["decisions"] == 3 and s["by_direction"] == {"down": 3}
    assert s["final_value"] == 0.0625 and s["converged"] is False
    assert cr.window_rows(events)[-1]["rung"] == 3.0
    assert tr.main([ev_path, "--control"]) == 0

    # registry-declared control/* gauges land on the Prometheus textfile
    prom = (tmp_path / "m.prom").read_text()
    assert "tcdp_control_rung" in prom and "tcdp_control_value" in prom
