"""LM data pipeline + pretrain harness smoke on the virtual CPU mesh."""

import math

import numpy as np
import pytest

from tpu_compressed_dp.data import lm as lm_data


class TestSyntheticTokens:
    def test_shapes_and_determinism(self):
        ds = lm_data.SyntheticTokens(64, 32, 4, seed=1)
        b0, b0b = ds.batch(0), ds.batch(0)
        np.testing.assert_array_equal(b0["input"], b0b["input"])
        assert b0["input"].shape == (4, 32) and b0["target"].shape == (4, 32)
        assert b0["input"].dtype == np.int32
        # next-token contract: target is input shifted by one
        full0 = np.concatenate([b0["input"], b0["target"][:, -1:]], axis=1)
        np.testing.assert_array_equal(full0[:, 1:], b0["target"])
        assert not np.array_equal(b0["input"], ds.batch(1)["input"])

    def test_process_sharding_differs(self):
        a = lm_data.SyntheticTokens(64, 32, 4, seed=1, process_index=0, process_count=2)
        b = lm_data.SyntheticTokens(64, 32, 4, seed=1, process_index=1, process_count=2)
        assert not np.array_equal(a.batch(0)["input"], b.batch(0)["input"])

    def test_learnable_structure(self):
        # with low noise, motifs repeat: bigram entropy far below uniform
        ds = lm_data.SyntheticTokens(64, 64, 8, seed=2, noise=0.0)
        b = ds.batch(0)
        # each sequence is periodic with period motif_len
        seq = np.concatenate([b["input"], b["target"][:, -1:]], axis=1)
        assert np.array_equal(seq[:, 8:16], seq[:, :8])


class TestByteCorpus:
    def test_crops(self, tmp_path):
        p = tmp_path / "c.txt"
        p.write_bytes(bytes(range(256)) * 8)
        ds = lm_data.ByteCorpus(str(p), 16, 4, seed=0)
        b = ds.batch(0)
        assert b["input"].shape == (4, 16)
        # consecutive bytes in the corpus -> target == input + 1 (mod wrap)
        assert np.array_equal((b["input"][:, 1:]), b["target"][:, :-1])

    def test_too_short_raises(self, tmp_path):
        p = tmp_path / "s.txt"
        p.write_bytes(b"ab")
        with pytest.raises(ValueError, match="shorter"):
            lm_data.ByteCorpus(str(p), 16, 2)


def test_lm_harness_e2e(tmp_path):
    """dp2 x sp2 x tp2 pretrain: converges below the uniform floor, reports
    the compression fraction + throughput telemetry, checkpoints, resumes,
    and emits a parseable JSONL event stream."""
    from tpu_compressed_dp.harness import lm

    ev_path = str(tmp_path / "events.jsonl")
    argv = [
        "--preset", "tiny", "--dp", "2", "--sp", "2", "--tp", "2",
        "--steps", "24", "--seq_len", "64", "--global_batch", "8", "--fp32",
        "--compress", "entiremodel", "--method", "topk", "--ratio", "0.01",
        "--error_feedback", "--log_every", "8", "--events", ev_path,
        "--checkpoint_dir", str(tmp_path / "ck"),
    ]
    s = lm.main(argv)
    assert s["step"] == 24
    assert s["loss"] < math.log(256)
    assert s["sent frac"] == pytest.approx(0.01, rel=0.05)
    assert s["tok/s"] > 0 and s["comm MB/s"] > 0

    # per-log-window step events: schema version, step metrics, timeline;
    # trace_report renders the breakdown/throughput without error
    import tools.trace_report as tr
    from tpu_compressed_dp.obs import export as obs_export

    events = obs_export.read_events(ev_path)
    steps_rec = [e for e in events if e["kind"] == "step"]
    assert len(steps_rec) == 3  # log_every=8 over 24 steps
    assert all(e["v"] == obs_export.SCHEMA_VERSION for e in events)
    assert steps_rec[-1]["metrics"]["loss"] == pytest.approx(s["loss"])
    assert steps_rec[-1]["throughput"]["throughput/tokens_per_sec"] > 0
    assert steps_rec[-1]["comm"]["comm/sent_bits"] > 0
    report = tr.render_report(events)
    assert "per-phase step-time breakdown" in report
    assert "tok/s" in report or "rate" in report

    s2 = lm.main(argv[:-2] + ["--resume", str(tmp_path / "ck"), "--steps", "26"])
    assert s2["step"] == 26


@pytest.mark.slow  # ~12 s; the lm e2e row keeps the harness quick path
def test_lm_harness_clip_stabilisers(tmp_path):
    """randomk + EF + momentum with both clip stabilisers on the 3-D mesh:
    finite loss, training progresses (the EF-momentum protocol the CNN step
    stabilises, now at LM parity)."""
    from tpu_compressed_dp.harness import lm

    s = lm.main([
        "--preset", "tiny", "--dp", "2", "--sp", "2", "--tp", "2",
        "--steps", "16", "--seq_len", "64", "--global_batch", "8", "--fp32",
        "--compress", "entiremodel", "--method", "randomk", "--ratio", "0.05",
        "--error_feedback", "--mode", "wire", "--momentum", "0.9",
        "--clip_norm", "1.0", "--clip_sent_norm", "1.0", "--log_every", "8",
    ])
    assert s["step"] == 16
    assert math.isfinite(s["loss"])
    assert s["loss"] < math.log(256) + 1.0


def test_lm_harness_validates_flags():
    from tpu_compressed_dp.harness import lm

    with pytest.raises(ValueError, match="requires --compress"):
        lm.main(["--preset", "tiny", "--method", "topk", "--steps", "1"])
    with pytest.raises(ValueError, match="divide"):
        lm.main(["--preset", "tiny", "--dp", "2", "--sp", "1", "--tp", "1",
                 "--global_batch", "3", "--steps", "1"])
