"""Data pipeline tests: preprocessing semantics and augmentation invariants."""

import numpy as np

from tpu_compressed_dp.data import cifar10 as D
import pytest

pytestmark = pytest.mark.quick  # fast tier (VERDICT r2 #10)



def test_normalise_matches_reference_formula():
    x = np.random.RandomState(0).randint(0, 255, (4, 32, 32, 3)).astype(np.uint8)
    out = D.normalise(x)
    mean = np.asarray(D.CIFAR10_MEAN, np.float32) * 255
    std = np.asarray(D.CIFAR10_STD, np.float32) * 255
    np.testing.assert_allclose(out, (x.astype(np.float32) - mean) / std, rtol=1e-5)


def test_pad_reflect():
    x = np.arange(2 * 4 * 4 * 1, dtype=np.float32).reshape(2, 4, 4, 1)
    out = D.pad(x, 2)
    assert out.shape == (2, 8, 8, 1)
    np.testing.assert_allclose(out[0, 2:6, 2:6], x[0])
    np.testing.assert_allclose(out[0, 1], out[0, 3])  # reflect row


def test_augment_epoch_shapes_and_crop():
    rng = np.random.RandomState(0)
    x = D.pad(np.ones((16, 32, 32, 3), np.float32), 4)
    out = D.augment_epoch(x, rng)
    assert out.shape == (16, 32, 32, 3)
    # values are only 0 (cutout) or 1 (all-ones input survives crop/flip)
    assert set(np.unique(out)) <= {0.0, 1.0}
    # cutout removes exactly an 8x8 block per sample
    zeros_per_sample = (out == 0).all(axis=3).sum(axis=(1, 2))
    np.testing.assert_array_equal(zeros_per_sample, 64)


def test_augment_is_deterministic_given_rng():
    x = D.pad(np.random.RandomState(1).rand(8, 32, 32, 3).astype(np.float32), 4)
    a = D.augment_epoch(x, np.random.RandomState(7))
    b = D.augment_epoch(x, np.random.RandomState(7))
    np.testing.assert_array_equal(a, b)


def test_crop_actually_crops_window():
    # mark one pixel; crop offsets recoverable
    x = np.zeros((1, 40, 40, 1), np.float32)
    x[0, 20, 20, 0] = 5.0
    rng = np.random.RandomState(3)
    out = D.augment_epoch(x, rng, cutout=None, flip=False)
    assert out.shape == (1, 32, 32, 1)
    assert out.max() == 5.0  # the marked pixel is inside every 32x32 window at (20,20)


def test_batches_iteration():
    data = np.arange(10 * 4, dtype=np.float32).reshape(10, 2, 2, 1)
    labels = np.arange(10, dtype=np.int32)
    b = D.Batches(data, labels, 4, shuffle=False, drop_last=False)
    batches = list(b)
    assert len(b) == 3 and len(batches) == 3
    assert batches[-1]["target"].shape == (2,)
    b2 = D.Batches(data, labels, 4, shuffle=True, drop_last=True, seed=1)
    batches2 = list(b2)
    assert len(b2) == 2 and all(len(x["target"]) == 4 for x in batches2)


def test_synthetic_dataset_learnable_structure():
    ds = D.synthetic_cifar10(n_train=64, n_test=16)
    assert ds["train"]["data"].shape == (64, 32, 32, 3)
    assert ds["train"]["data"].dtype == np.uint8
    # same label -> identical prototype under the noise: class means differ
    labels = ds["train"]["labels"]
    if len(set(labels[:16])) > 1:
        m0 = ds["train"]["data"][labels == labels[0]].mean()
        assert ds["train"]["data"].std() > 0
