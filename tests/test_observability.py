"""Observability: tensorboard/file loggers, meters, profiler wiring."""

import json
import os

import numpy as np
import pytest

from tpu_compressed_dp.utils import meters
from tpu_compressed_dp.utils.loggers import FileLogger, NoOp, TensorboardLogger





@pytest.mark.quick
class TestTensorboardLogger:
    def test_writes_scalars_and_json(self, tmp_path):
        tb = TensorboardLogger(str(tmp_path / "tb"))
        tb.update_examples_count(512)
        tb.log_scalar("losses/train_loss", 1.5)
        tb.update_examples_count(512)
        tb.log_scalar("losses/train_loss", 1.2)
        tb.log_metrics({"net/x": 3.0, "skip": "str"})
        tb.close()
        data = json.load(open(tmp_path / "tb" / "scalars.json"))
        assert data["losses/train_loss"] == [[512, 1.5], [1024, 1.2]]
        assert data["net/x"] == [[1024, 3.0]]
        assert any(f.startswith("events") for f in os.listdir(tmp_path / "tb"))

    def test_non_master_is_noop(self, tmp_path):
        tb = TensorboardLogger(str(tmp_path / "tb2"), is_master=False)
        assert isinstance(tb, NoOp)
        tb.log_scalar("x", 1.0)  # absorbs anything
        tb.close()
        assert not (tmp_path / "tb2").exists()

    def test_disabled_without_dir(self):
        assert isinstance(TensorboardLogger(None), NoOp)


@pytest.mark.quick
class TestFileLogger:
    def test_level_routing(self, tmp_path, capsys):
        log = FileLogger(str(tmp_path), rank=3)
        log.debug("dbg")
        log.info("inf")
        log.event("~~1\t0.1\t90\t95")
        verbose = (tmp_path / "verbose.log").read_text()
        event = (tmp_path / "event.log").read_text()
        debug = (tmp_path / "debug.log").read_text()
        assert "inf" in verbose and "~~1" in verbose and "dbg" not in verbose
        assert "~~1" in event and "inf" not in event
        assert "dbg" in debug and "DEBUG" in debug
        assert "3: inf" in capsys.readouterr().out  # rank-prefixed console

    def test_non_master_console_only(self, tmp_path):
        FileLogger(None, rank=1, is_master=False).info("x")
        assert not os.listdir(tmp_path)


@pytest.mark.quick
class TestMeters:
    def test_network_bytes_reads_proc(self):
        recv, transmit = meters.network_bytes()
        assert recv >= 0 and transmit >= 0

    def test_network_meter_interval(self):
        m = meters.NetworkMeter()
        rg, tg = m.update_bandwidth()
        assert rg >= 0 and tg >= 0

    def test_time_meter(self):
        m = meters.TimeMeter()
        m.batch_loaded()
        m.batch_dispatched()
        s = m.summary()
        assert s["data ms/batch"] >= 0 and s["dispatch ms/batch"] >= 0

    def test_comm_meter(self):
        m = meters.CommMeter(world=8)
        m.update({"comm/sent_bits": 8e6, "comm/dense_elems": 1e6})
        m.update({"comm/sent_bits": 8e6, "comm/dense_elems": 1e6})
        out = m.gbps()
        assert out["net/payload_mb_per_step"] == pytest.approx(1.0)
        assert out["net/compression_frac"] == pytest.approx(0.25)
        assert out["net/allreduce_gbps_per_chip"] > 0


def test_imagenet_harness_tensorboard_integration(tmp_path):
    from tpu_compressed_dp.harness import imagenet as h

    h.main([
        "--synthetic", "--synthetic_n", "64", "--num_classes", "4",
        "--arch", "resnet18", "--width", "8", "--short_epoch", "--workers", "2",
        "--compress", "layerwise", "--method", "randomk", "--ratio", "0.1",
        "--logdir", str(tmp_path), "--tensorboard",
    ])
    scalars = json.load(open(tmp_path / "tb" / "scalars.json"))
    assert "losses/top5" in scalars and "net/payload_mb_per_step" in scalars
    assert len(scalars["losses/train_loss"]) == 3  # smoke schedule: 3 epochs
    # x-axis is cumulative examples
    xs = [p[0] for p in scalars["losses/train_loss"]]
    assert xs == sorted(xs) and xs[0] > 0
    assert "~~0" in (tmp_path / "event.log").read_text()
    assert (tmp_path / "logs.tsv").exists()
