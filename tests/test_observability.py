"""Observability: metric registry conformance, step timeline, event stream,
Prometheus export, watchdog, loggers, meters, profiler wiring."""

import itertools
import json
import os

import numpy as np
import pytest

from tpu_compressed_dp.obs import export as obs_export
from tpu_compressed_dp.obs import registry as obs_registry
from tpu_compressed_dp.obs.trace import StepTimeline
from tpu_compressed_dp.utils import meters
from tpu_compressed_dp.utils.loggers import FileLogger, NoOp, TensorboardLogger


@pytest.mark.quick
class TestMetricRegistry:
    def test_every_spec_is_wellformed(self):
        for name, ms in obs_registry.REGISTRY.items():
            assert ms.name == name
            assert ms.kind in ("counter", "gauge", "timing")
            assert ms.reduction in ("mean", "sum", "min", "max")
            assert ms.emitter in ("engine", "step", "eval", "host")

    def test_canonical_maps_engine_keys(self):
        assert obs_registry.canonical("sent_bits") == "comm/sent_bits"
        assert obs_registry.canonical("comm/sent_bits") == "comm/sent_bits"
        assert obs_registry.canonical("guard/nonfinite") == "guard/nonfinite"
        assert obs_registry.is_declared("sync_agree")
        assert not obs_registry.is_declared("made_up_key")
        assert obs_registry.undeclared(["sent_bits", "nope"]) == ["nope"]

    def test_redeclare_conflict_rejected(self):
        with pytest.raises(ValueError, match="already declared"):
            obs_registry.declare("loss", "counter", "nats", "sum", "step")
        # identical redeclaration is a no-op
        ms = obs_registry.REGISTRY["loss"]
        obs_registry.declare(ms.name, ms.kind, ms.unit, ms.reduction,
                             ms.emitter, ms.help)

    def test_prometheus_name_sanitised(self):
        assert obs_registry.prometheus_name("sent_bits") == \
            "tcdp_comm_sent_bits"
        assert obs_registry.prometheus_name("time/step_p95_ms") == \
            "tcdp_time_step_p95_ms"

    def test_diag_table_derived_from_registry(self):
        """The partitioned engine's diagnostic-reduction table is BUILT from
        the registry declarations — min -> pmin, max -> pmax."""
        import jax

        from tpu_compressed_dp.parallel import dp

        diags = obs_registry.engine_diag_reductions()
        assert diags == {"sync_agree": "min", "guard/nonfinite": "max"}
        assert set(dp._DIAG_STATS) == set(diags)
        assert dp._DIAG_STATS["sync_agree"][0] is jax.lax.pmin
        assert dp._DIAG_STATS["guard/nonfinite"][0] is jax.lax.pmax

    def test_accumulator_sum_keys_derived(self):
        from tpu_compressed_dp.utils.loggers import MetricAccumulator

        assert "correct" in MetricAccumulator.SUM_KEYS
        assert "loss_sum" in MetricAccumulator.SUM_KEYS
        assert "loss" not in MetricAccumulator.SUM_KEYS


CONFORMANCE_METHODS = [None, "topk", "blocktopk", "randomk", "thresholdv",
                       "adaptive_threshold", "terngrad", "qsgd", "powersgd"]


def _sync_stat_keys(cfg, mesh):
    """Trace one sync under shard_map (no compile/run: eval_shape) and
    return the stats keys it emits."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from tpu_compressed_dp.compat import shard_map
    from tpu_compressed_dp.parallel.dp import init_comp_state, make_grad_sync

    grads = {"w": jnp.zeros((64, 8)), "b": jnp.zeros((8,))}
    sync = make_grad_sync(cfg)
    ef = (jax.tree.map(lambda g: jnp.zeros(g.shape, jnp.float32), grads)
          if cfg.error_feedback else ())
    comp = init_comp_state(grads, cfg)

    def f(g, e, c, k):
        # always guard-gated: covers the guard/nonfinite key; the ungated
        # path emits a strict subset
        return sync(g, e, c, k, ok=jnp.asarray(True))[3]

    sm = shard_map(f, mesh=mesh, in_specs=(P(), P(), P(), P()),
                   out_specs=P())
    out = jax.eval_shape(sm, grads, ef, comp, jax.random.key(0))
    return set(out.keys())


class TestRegistryConformance:
    """Every stats key either sync engine can emit — across the FULL
    method x mode x transport x granularity matrix — must be declared in
    the metric registry.  Pure tracing (eval_shape), no compile: the whole
    matrix costs seconds, so tier-1 exercises all of it."""

    def test_all_methods_transports_granularities(self, mesh8):
        from tpu_compressed_dp.parallel.dp import CompressionConfig
        from tpu_compressed_dp.parallel.mesh import make_data_mesh

        mesh = make_data_mesh(4)
        failures = []
        seen = set()
        for m, mode, transport, gran in itertools.product(
                CONFORMANCE_METHODS, ("simulate", "wire"),
                ("allgather", "sharded", "hierarchical"),
                ("layerwise", "entiremodel", "bucketed")):
            # EF composes with everything except the unbiased quantizers
            # (wire mode rejects that combination at build time)
            ef = m not in (None, "terngrad", "qsgd")
            cfg = CompressionConfig(
                method=m, granularity=gran, mode=mode, transport=transport,
                ratio=0.25, error_feedback=ef, check_sync=True,
                dp_pods=2 if transport == "hierarchical" else 1)
            keys = _sync_stat_keys(cfg, mesh)
            seen |= keys
            bad = obs_registry.undeclared(keys)
            if bad:
                failures.append((m, mode, transport, gran, bad))
        assert not failures, f"undeclared stats keys: {failures}"
        # the matrix actually exercised the interesting keys (a silently
        # empty sweep would vacuously pass)
        for expected in ("sent_bits_psum", "sent_bits_alltoall",
                         "sent_bits_ici", "sent_bits_dcn",
                         "sent_bits_dcn_route", "shard_overflow",
                         "threshold_overflow", "sync_agree",
                         "guard/nonfinite"):
            assert expected in seen, f"matrix never emitted {expected}"

    def test_step_metric_keys_declared(self):
        """The step factories' own metric names (loss/correct/count/lr/
        tokens + guard/*) are declared too."""
        from tpu_compressed_dp.train.guard import (GuardConfig,
                                                   guard_metrics,
                                                   init_guard_state)

        gm = guard_metrics(init_guard_state(GuardConfig()))
        step_keys = {"loss", "correct", "count", "lr", "tokens",
                     "loss_sum", "correct5", *gm}
        assert obs_registry.undeclared(step_keys) == []


@pytest.mark.quick
class TestStepTimeline:
    def _clock(self):
        class C:
            t = 0.0

            def __call__(self):
                return self.t

        return C()

    def test_splits_and_percentiles(self):
        clk = self._clock()
        tl = StepTimeline(capacity=8, clock=clk, sync=lambda: None)
        for i in range(4):
            clk.t += 0.25          # data wait
            tl.batch_ready()
            clk.t += 0.75          # dispatch
            tl.step_dispatched()
        p = tl.percentiles()
        assert p["p50"] == pytest.approx(1.0)
        assert p["p95"] == pytest.approx(1.0)
        assert tl.data_wait_frac() == pytest.approx(0.25)
        assert tl.steps_per_sec() == pytest.approx(1.0)
        snap = tl.snapshot()
        assert snap["time/step_p95_ms"] == pytest.approx(1000.0)
        assert snap["time/data_wait_frac"] == pytest.approx(0.25)

    def test_ring_bounds_memory_and_drain(self):
        clk = self._clock()
        tl = StepTimeline(capacity=4, clock=clk, sync=lambda: None)
        for _ in range(10):
            clk.t += 1.0
            tl.batch_ready()
            clk.t += 1.0
            tl.step_dispatched()
        assert len(tl.records) == 4      # ring: most recent only
        assert tl.steps == 10
        drained = tl.drain()
        assert len(drained) <= 4         # pending is capacity-bounded too
        assert tl.drain() == []          # drained once
        assert {"t0", "data", "dispatch", "total"} <= set(drained[0])

    def test_resume_excludes_between_step_work(self):
        """Blocking between-step work (eval, checkpoint saves, a log-window
        device_get drain) must not be billed as the next step's data wait."""
        clk = self._clock()
        tl = StepTimeline(capacity=8, clock=clk, sync=lambda: None)
        clk.t += 0.1
        tl.batch_ready()
        clk.t += 0.9
        tl.step_dispatched()
        clk.t += 100.0          # epoch-end eval + checkpoint
        tl.resume()
        clk.t += 0.1
        tl.batch_ready()
        clk.t += 0.9
        tl.step_dispatched()
        recs = list(tl.records)
        assert recs[1]["data"] == pytest.approx(0.1)
        assert recs[1]["total"] == pytest.approx(1.0)
        assert tl.data_wait_frac() == pytest.approx(0.1)

    def test_device_sync_sampling(self):
        clk = self._clock()
        synced = []

        def sync():
            synced.append(clk.t)
            clk.t += 0.5     # the drain the sample measures

        tl = StepTimeline(capacity=8, device_sync_every=2, clock=clk,
                          sync=sync)
        for _ in range(4):
            clk.t += 0.1
            tl.batch_ready()
            clk.t += 0.1
            tl.step_dispatched()
        assert len(synced) == 2          # steps 2 and 4
        recs = list(tl.records)
        assert "device" not in recs[0] and "device" in recs[1]
        assert recs[1]["device"] == pytest.approx(0.5)
        assert recs[1]["total"] == pytest.approx(0.7)


@pytest.mark.quick
class TestTimerRegression:
    def test_constant_memory_and_split_semantics(self, monkeypatch):
        """utils/timer.Timer kept every split timestamp forever (unbounded
        on long runs); it must keep only the last one, with identical
        split/total semantics."""
        from tpu_compressed_dp.utils import timer as timer_mod

        t = {"now": 100.0}
        monkeypatch.setattr(timer_mod.time, "time", lambda: t["now"])
        tm = timer_mod.Timer()
        assert not hasattr(tm, "times")   # the unbounded list is gone
        t["now"] = 101.5
        assert tm(include_in_total=True) == pytest.approx(1.5)
        t["now"] = 102.0
        assert tm(include_in_total=False) == pytest.approx(0.5)
        t["now"] = 104.0
        assert tm() == pytest.approx(2.0)
        assert tm.total_time == pytest.approx(3.5)  # excluded split stays out
        # a long run's split count leaves no growing state behind
        for _ in range(1000):
            t["now"] += 0.001
            tm()
        assert isinstance(tm.last_time, float)


@pytest.mark.quick
class TestEventStreamAndPrometheus:
    def test_stream_schema_and_roundtrip(self, tmp_path):
        p = str(tmp_path / "ev.jsonl")
        with obs_export.EventStream(p, meta={"harness": "t"}) as es:
            es.emit("step", step=1, metrics={"loss": 1.0})
        events = obs_export.read_events(p)
        assert [e["kind"] for e in events] == ["run_start", "step", "run_end"]
        assert all(e["v"] == obs_export.SCHEMA_VERSION for e in events)
        assert all("ts" in e for e in events)
        assert events[0]["harness"] == "t"
        assert events[1]["metrics"] == {"loss": 1.0}
        # append-only: a resumed run extends the same file
        with obs_export.EventStream(p) as es:
            es.emit("step", step=2)
        assert len(obs_export.read_events(p)) == 6

    def test_prometheus_textfile(self, tmp_path):
        p = str(tmp_path / "m.prom")
        obs_export.write_prometheus(
            {"comm/sent_bits": 1.5e6, "made/up": 2.0, "skipme": "str"},
            p, labels={"harness": "dawn"})
        body = open(p).read()
        # everything exposes as gauge: the harnesses write per-window
        # aggregates, not running totals — a counter TYPE would make
        # Prometheus rate() treat every dip as a reset
        assert "# TYPE tcdp_comm_sent_bits gauge" in body
        assert '# HELP tcdp_comm_sent_bits' in body
        assert 'tcdp_comm_sent_bits{harness="dawn"} 1.5e+06' in body
        assert "# TYPE tcdp_made_up gauge" in body
        assert "skipme" not in body

    def test_telemetry_snapshot(self):
        clk_t = [0.0]

        class TL(StepTimeline):
            pass

        tl = StepTimeline(clock=lambda: clk_t[0], sync=lambda: None)
        clk_t[0] = 1.0
        tl.batch_ready()
        clk_t[0] = 2.0
        tl.step_dispatched()
        snap = obs_export.telemetry_snapshot(tl, step=7, last_good_step=5)
        assert snap["step"] == 7 and snap["last_good_step"] == 5
        assert snap["steps_per_sec"] == pytest.approx(0.5)
        assert snap["step_p95_ms"] == pytest.approx(2000.0)


@pytest.mark.quick
class TestWatchdog:
    def _hb(self, tmp_path, **kw):
        import time as _time

        p = str(tmp_path / "hb.json")
        rec = {"ts": _time.time(), "step": 100, "last_good_step": 100}
        rec.update(kw)
        json.dump(rec, open(p, "w"))
        return p

    def test_healthy(self, tmp_path):
        from tpu_compressed_dp.utils.resilience import check_heartbeat

        p = self._hb(tmp_path, telemetry={"steps_per_sec": 2.0})
        assert check_heartbeat(p, max_age_s=60, max_wedge_steps=10,
                               min_steps_per_sec=0.1) == []

    def test_stale_wedged_stalled_missing(self, tmp_path):
        import time as _time

        from tpu_compressed_dp.utils.resilience import check_heartbeat

        p = self._hb(tmp_path, ts=_time.time() - 999, last_good_step=10,
                     telemetry={"steps_per_sec": 0.001})
        probs = check_heartbeat(p, max_age_s=60, max_wedge_steps=50,
                                min_steps_per_sec=0.1)
        assert len(probs) == 3
        assert any("stale" in x for x in probs)
        assert any("wedged" in x for x in probs)
        assert any("stalled" in x for x in probs)
        missing = check_heartbeat(str(tmp_path / "no.json"))
        assert missing and "missing" in missing[0]
        # absent optional fields skip their checks, not fail them
        q = str(tmp_path / "hb2.json")
        json.dump({"ts": _time.time(), "step": 5}, open(q, "w"))
        assert check_heartbeat(q, max_age_s=60, max_wedge_steps=1,
                               min_steps_per_sec=1.0) == []

    def test_cli_exit_codes(self, tmp_path):
        import time as _time

        import tools.watchdog as wd

        p = self._hb(tmp_path)
        assert wd.main(["--check", "--heartbeat", p]) == 0
        json.dump({"ts": _time.time() - 999, "step": 1}, open(p, "w"))
        assert wd.main(["--check", "--heartbeat", p]) == 1
        assert wd.main(["--check", "--heartbeat",
                        str(tmp_path / "no.json")]) == 2

    def test_max_ckpt_age_cli(self, tmp_path):
        """--max_ckpt_age reads the Checkpointer.heartbeat_fields payload
        the harnesses fold into the heartbeat (ISSUE 9 satellite)."""
        import tools.watchdog as wd

        p = self._hb(tmp_path, last_ckpt_step=50, ckpt_age_s=500.0)
        assert wd.main(["--check", "--heartbeat", p,
                        "--max_ckpt_age", "1000"]) == 0
        assert wd.main(["--check", "--heartbeat", p,
                        "--max_ckpt_age", "60"]) == 1
        # without the flag the checkpoint clock is never consulted
        assert wd.main(["--check", "--heartbeat", p]) == 0

    def test_max_stream_lag_cli(self, tmp_path):
        """--max_stream_lag reads the StreamWriter.heartbeat_fields payload
        the harnesses fold into the heartbeat (delta-stream satellite)."""
        import tools.watchdog as wd

        p = self._hb(tmp_path, stream_last_step=50, stream_lag_s=500.0)
        assert wd.main(["--check", "--heartbeat", p,
                        "--max_stream_lag", "1000"]) == 0
        assert wd.main(["--check", "--heartbeat", p,
                        "--max_stream_lag", "60"]) == 1
        # without the flag the stream clock is never consulted
        assert wd.main(["--check", "--heartbeat", p]) == 0

    def test_max_straggler_skew_cli(self, tmp_path):
        """--max_straggler_skew reads the flight recorder's live
        straggler_skew_s the harnesses fold into the heartbeat."""
        import tools.watchdog as wd

        p = self._hb(tmp_path, straggler_skew_s=2.5, straggler_rank=3)
        assert wd.main(["--check", "--heartbeat", p,
                        "--max_straggler_skew", "5"]) == 0
        assert wd.main(["--check", "--heartbeat", p,
                        "--max_straggler_skew", "1"]) == 1
        # without the flag the skew gauge is never consulted
        assert wd.main(["--check", "--heartbeat", p]) == 0

    def test_max_straggler_skew_unit(self, tmp_path):
        from tpu_compressed_dp.utils.resilience import check_heartbeat

        p = self._hb(tmp_path, straggler_skew_s=2.5, straggler_rank=3)
        probs = check_heartbeat(p, max_straggler_skew_s=1.0)
        assert probs and "straggler" in probs[0]
        assert check_heartbeat(p, max_straggler_skew_s=5.0) == []
        # a heartbeat that never published the gauge skips the check
        q = self._hb(tmp_path)
        assert check_heartbeat(q, max_straggler_skew_s=0.001) == []

    def test_max_step_p95_cli(self, tmp_path):
        """--max_step_p95_ms reads the telemetry snapshot's tail latency —
        the digital twin's modeled budget enforced live (ISSUE 19)."""
        import tools.watchdog as wd

        p = self._hb(tmp_path, telemetry={"step_p95_ms": 1800.0})
        assert wd.main(["--check", "--heartbeat", p,
                        "--max_step_p95_ms", "2000"]) == 0
        assert wd.main(["--check", "--heartbeat", p,
                        "--max_step_p95_ms", "1500"]) == 1
        # without the flag the tail latency is never consulted
        assert wd.main(["--check", "--heartbeat", p]) == 0

    def test_max_step_p95_unit(self, tmp_path):
        from tpu_compressed_dp.utils.resilience import check_heartbeat

        p = self._hb(tmp_path, telemetry={"step_p95_ms": 1800.0})
        probs = check_heartbeat(p, max_step_p95_ms=1500.0)
        assert probs and "slow tail" in probs[0]
        assert check_heartbeat(p, max_step_p95_ms=2000.0) == []
        # a heartbeat whose telemetry never published p95 skips the check
        q = self._hb(tmp_path, telemetry={"steps_per_sec": 2.0})
        assert check_heartbeat(q, max_step_p95_ms=0.001) == []


@pytest.mark.quick
class TestWatchdogRelaunch:
    """The relaunch decision loop (tools/watchdog.py supervise) against a
    fake child and a scripted heartbeat-verdict sequence: restart on
    wedge/death with doubling backoff, budget refilled by a healthy check,
    give-up after max_relaunches CONSECUTIVE restarts, clean exit ends
    supervision."""

    class _Child:
        def __init__(self, rc=None):
            self.rc = rc  # None = still running

        def poll(self):
            return self.rc

        @property
        def returncode(self):
            return self.rc

    def _drive(self, verdicts, *, max_relaunches=2, grace=0.0, interval=1.0,
               child_rcs=(), max_checks=None):
        import tools.watchdog as wd

        spawned, killed, sleeps = [], [], []

        def spawn():
            rc = (child_rcs[len(spawned)] if len(spawned) < len(child_rcs)
                  else None)
            c = self._Child(rc)
            spawned.append(c)
            return c

        it = iter(verdicts)
        rc = wd.supervise(
            spawn, lambda: next(it),
            interval_s=interval, grace_s=grace,
            max_relaunches=max_relaunches, backoff_s=5.0, backoff_cap_s=40.0,
            sleep=sleeps.append, kill=lambda c, **k: killed.append(c),
            log=lambda m: None, max_checks=max_checks)
        return rc, spawned, killed, sleeps

    def test_clean_exit_ends_supervision(self):
        rc, spawned, killed, _ = self._drive([], child_rcs=[0])
        assert rc == 0 and len(spawned) == 1 and killed == []

    def test_wedge_relaunches_with_doubling_backoff_then_gives_up(self):
        rc, spawned, killed, sleeps = self._drive([1, 1, 1], max_relaunches=2)
        assert rc == 1  # wedged (alive) children report generic failure
        assert len(spawned) == 1 + 2  # initial + both budgeted relaunches
        assert len(killed) == 3  # 2 relaunch kills + the give-up kill
        # sleep trace: tick, backoff 5, tick, backoff 10 (doubled), tick
        assert sleeps == [1.0, 5.0, 1.0, 10.0, 1.0]

    def test_dead_childs_exit_code_propagates_on_give_up(self):
        rc, spawned, _, _ = self._drive([1], max_relaunches=0, child_rcs=[7])
        assert rc == 7 and len(spawned) == 1

    def test_preempt_exit_relaunches_immediately_without_backoff(self):
        """PREEMPT_EXIT (emergency checkpoint cut, deliberate exit) respawns
        NOW: no backoff sleep, no kill, no consecutive-budget burn — proven
        by a ZERO relaunch budget and an EMPTY verdict script (a consumed
        health check would raise StopIteration)."""
        from tpu_compressed_dp.utils.resilience import PREEMPT_EXIT

        rc, spawned, killed, sleeps = self._drive(
            [], child_rcs=[PREEMPT_EXIT, 0], max_relaunches=0)
        assert rc == 0
        assert len(spawned) == 2       # respawned despite max_relaunches=0
        assert killed == []
        assert sleeps == [1.0, 1.0]    # two plain ticks, no backoff inserted

    def test_healthy_check_refills_budget_and_resets_backoff(self):
        rc, spawned, _, sleeps = self._drive([1, 0, 1], max_relaunches=2,
                                             max_checks=3)
        assert rc == 0  # bounded by max_checks, never gave up
        assert len(spawned) == 3
        backoffs = [s for s in sleeps if s != 1.0]
        assert backoffs == [5.0, 5.0]  # second wedge backs off from the base

    def test_grace_period_suppresses_checks_after_each_launch(self):
        rc, _, _, sleeps = self._drive([0, 0], grace=2.5, max_checks=2)
        assert rc == 0
        # 2 silent warm-up ticks before the 1st check, then 2 checked ticks
        assert sleeps == [1.0, 1.0, 1.0, 1.0]

    def test_cli_requires_command(self, capsys):
        import tools.watchdog as wd

        assert wd.main(["--relaunch", "--heartbeat", "hb.json"]) == 2
        assert "training command" in capsys.readouterr().out

    def test_exception_kills_child_not_orphans(self):
        """Ctrl-C (or a check() crash) mid-supervision must kill the child
        on the way out — a detached run would keep refreshing the
        heartbeat under a restarted watchdog's feet."""
        import tools.watchdog as wd

        spawned, killed = [], []

        def spawn():
            c = self._Child(None)
            spawned.append(c)
            return c

        def check():
            raise KeyboardInterrupt

        with pytest.raises(KeyboardInterrupt):
            wd.supervise(spawn, check, interval_s=1.0, grace_s=0.0,
                         max_relaunches=2, sleep=lambda s: None,
                         kill=lambda c, **k: killed.append(c),
                         log=lambda m: None)
        assert killed == spawned  # the (only) child was cleaned up


class TestPreemptStorm:
    """The preempt-storm guard: free PREEMPT_EXIT respawns are rate-capped
    — more than ``max_preempts`` inside the sliding window falls through
    to the unhealthy path (budget, backoff, give-up) instead of respawning
    forever on the supervisor's dime."""

    def _drive(self, child_rcs, *, max_preempts, preempt_window_s=600.0,
               max_relaunches=0, verdicts=()):
        import tools.watchdog as wd

        spawned, killed, sleeps = [], [], []

        def spawn():
            rc = (child_rcs[len(spawned)] if len(spawned) < len(child_rcs)
                  else None)
            c = TestWatchdogRelaunch._Child(rc)
            spawned.append(c)
            return c

        it = iter(verdicts)
        rc = wd.supervise(
            spawn, lambda: next(it), interval_s=1.0, grace_s=0.0,
            max_relaunches=max_relaunches, backoff_s=5.0,
            backoff_cap_s=40.0, sleep=sleeps.append,
            kill=lambda c, **k: killed.append(c), log=lambda m: None,
            max_preempts=max_preempts, preempt_window_s=preempt_window_s)
        return rc, spawned, killed, sleeps

    def test_storm_gives_up_with_childs_exit_code(self):
        from tpu_compressed_dp.utils.resilience import PREEMPT_EXIT

        rc, spawned, killed, sleeps = self._drive(
            [PREEMPT_EXIT] * 3, max_preempts=2)
        # two free respawns, the third preempt in the window is the storm:
        # zero budget left => give up, propagating the child's exit 75
        assert rc == PREEMPT_EXIT
        assert len(spawned) == 3
        assert len(killed) == 1
        assert sleeps == [1.0, 1.0, 1.0]  # never a backoff, never a check

    def test_storm_spends_the_budget_before_giving_up(self):
        from tpu_compressed_dp.utils.resilience import PREEMPT_EXIT

        rc, spawned, killed, sleeps = self._drive(
            [PREEMPT_EXIT, PREEMPT_EXIT, 0], max_preempts=1,
            max_relaunches=1)
        # preempt #2 is the storm, but one budgeted relaunch remains: kill,
        # back off, respawn — and that child exits cleanly
        assert rc == 0
        assert len(spawned) == 3 and len(killed) == 1
        assert sleeps == [1.0, 1.0, 5.0, 1.0]

    def test_preempts_outside_the_window_never_storm(self):
        from tpu_compressed_dp.utils.resilience import PREEMPT_EXIT

        # window shorter than the tick spacing: each preempt evicts the
        # previous from the deque — five in a row stay "free" even at cap 1
        rc, spawned, killed, sleeps = self._drive(
            [PREEMPT_EXIT] * 5 + [0], max_preempts=1, preempt_window_s=0.5)
        assert rc == 0
        assert len(spawned) == 6 and killed == []
        assert sleeps == [1.0] * 6

    def test_cap_none_disables_the_guard(self):
        from tpu_compressed_dp.utils.resilience import PREEMPT_EXIT

        rc, spawned, killed, _ = self._drive(
            [PREEMPT_EXIT] * 9 + [0], max_preempts=None)
        assert rc == 0 and len(spawned) == 10 and killed == []

class TestJobNamespacing:
    """Per-job telemetry namespacing (--job_id / $TCDP_JOB_ID): two jobs
    sharing one textfile-collector or heartbeat dir must never clobber
    each other's files, and the exposition carries a job label."""

    def test_job_scoped_path(self):
        assert obs_export.job_scoped_path("/x/hb.json", "jobA") \
            == "/x/jobA.hb.json"
        assert obs_export.job_scoped_path("hb.json", "jobA") == "jobA.hb.json"
        assert obs_export.job_scoped_path("/x/hb.json", None) == "/x/hb.json"
        assert obs_export.job_scoped_path(None, "jobA") is None

    def test_prom_labels_and_job_scoped_args(self):
        import argparse

        from tpu_compressed_dp.harness import loop

        args = argparse.Namespace(job_id="lm-a")
        assert loop.job_scoped(args, "/m/metrics.prom") \
            == "/m/lm-a.metrics.prom"
        assert loop.prom_labels(args, harness="lm") \
            == {"harness": "lm", "job": "lm-a"}
        solo = argparse.Namespace(job_id=None)
        assert loop.job_scoped(solo, "/m/metrics.prom") == "/m/metrics.prom"
        assert loop.prom_labels(solo, harness="lm") == {"harness": "lm"}

    def test_job_id_defaults_from_fleet_env(self, monkeypatch):
        import argparse

        from tpu_compressed_dp.harness import loop

        monkeypatch.setenv("TCDP_JOB_ID", "from-env")
        p = argparse.ArgumentParser()
        loop.add_telemetry_args(p)
        assert p.parse_args([]).job_id == "from-env"
        assert p.parse_args(["--job_id", "cli-wins"]).job_id == "cli-wins"

    def test_two_jobs_share_a_prom_dir_without_clobbering(self, tmp_path):
        base = str(tmp_path / "metrics.prom")
        for job in ("jobA", "jobB"):
            obs_export.write_prometheus(
                {"fleet/world": 4.0}, obs_export.job_scoped_path(base, job),
                labels={"job": job})
        a = (tmp_path / "jobA.metrics.prom").read_text()
        b = (tmp_path / "jobB.metrics.prom").read_text()
        assert 'job="jobA"' in a and 'job="jobB"' in b
        assert not (tmp_path / "metrics.prom").exists()

    def test_heartbeat_is_job_scoped_and_labelled(self, tmp_path):
        import argparse

        from tpu_compressed_dp.harness import loop
        from tpu_compressed_dp.utils.resilience import read_heartbeat

        args = argparse.Namespace(job_id="lm-a",
                                  heartbeat=str(tmp_path / "hb.json"),
                                  heartbeat_interval=30.0)
        hb = loop.make_heartbeat(args)
        try:
            hb.update(step=3)
        finally:
            hb.stop()
        rec = read_heartbeat(str(tmp_path / "lm-a.hb.json"))
        assert rec is not None and rec["job"] == "lm-a"
        assert not (tmp_path / "hb.json").exists()

    def test_fleet_metrics_declared_in_registry(self):
        from tpu_compressed_dp.obs import registry

        for name in ("fleet/world", "fleet/applied_updates",
                     "fleet/jobs_running", "fleet/devices_free",
                     "fleet/evictions", "fleet/shrinks", "fleet/readmits"):
            assert registry.is_declared(name), name
            assert registry.spec(name).emitter == "host", name


@pytest.mark.quick
class TestTraceReport:
    def _events(self, tmp_path):
        p = str(tmp_path / "ev.jsonl")
        with obs_export.EventStream(p, meta={"harness": "dawn"}) as es:
            spans = [{"t0": 10.0 + i, "data": 0.2, "dispatch": 0.8,
                      "total": 1.0} for i in range(4)]
            spans[1]["device"] = 0.5
            es.emit("epoch", epoch=1, step=4,
                    metrics={"train loss": 2.0, "comm MB/s": 3.25},
                    throughput={"throughput/examples_per_sec": 512.0,
                                "throughput/mfu": 0.5},
                    guard={"guard/skipped": 1.0},
                    timeline={}, step_spans=spans)
            es.emit("guard", epoch=1, step=4, **{"guard/skipped": 1.0})
        return p

    def test_render_and_chrome(self, tmp_path):
        import tools.trace_report as tr

        events = obs_export.read_events(self._events(tmp_path))
        bd = tr.phase_breakdown(events)
        assert bd["data"]["mean_ms"] == pytest.approx(200.0)
        assert bd["data"]["share"] == pytest.approx(0.2)
        assert bd["device"]["mean_ms"] == pytest.approx(500.0)
        rows = tr.throughput_rows(events)
        assert rows[0]["rate"] == 512.0 and rows[0]["mfu"] == 0.5
        report = tr.render_report(events)
        assert "per-phase step-time breakdown" in report
        assert "MFU" in report and "guard events: 1" in report
        ch = tr.chrome_trace_events(events)
        # 4 steps x (data + dispatch) + 1 device span
        assert len(ch) == 9
        assert all(e["ph"] == "X" and e["dur"] > 0 for e in ch)
        out = str(tmp_path / "chrome.json")
        assert tr.main([self._events(tmp_path), "--chrome", out]) == 0
        assert json.load(open(out))["traceEvents"]

    def test_schema_guard(self, tmp_path):
        import tools.trace_report as tr

        with pytest.raises(ValueError, match="schema version"):
            tr.check_schema([{"v": 999, "kind": "epoch"}])

    def test_schedule_section(self, tmp_path, capsys):
        """--schedule folds the overlap_evidence per-chunk placement table
        into the report (the device-side overlap view the host timeline
        cannot carry)."""
        import tools.trace_report as tr

        sched = tmp_path / "overlap.txt"
        sched.write_text(
            "# header comment\n"
            "== topk1%-EF-bucketed4MB-overlap4: 4 collective instr ==\n"
            "   all-reduce     chunk=c00  operands=  1 ~    9.44 MB  "
            "compute_after=  70 ( 60.0%)\n"
            "   summary: first=60.0% mean=45.0% last=20.0%\n")
        out = tr.render_schedule(str(sched))
        assert "chunk=c00" in out and "summary: first=60.0%" in out
        assert "# header comment" not in out
        assert tr.main([self._events(tmp_path),
                        "--schedule", str(sched)]) == 0
        assert "compiled-schedule overlap" in capsys.readouterr().out
        # --json must carry the schedule too, not silently drop the flag
        assert tr.main([self._events(tmp_path), "--json",
                        "--schedule", str(sched)]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert any("chunk=c00" in ln for ln in payload["schedule"])
        missing = tr.render_schedule(str(tmp_path / "nope.txt"))
        assert "unreadable" in missing


@pytest.mark.quick
class TestProfileTraceContext:
    def test_stops_on_exception(self, monkeypatch):
        """The hoisted profiler context must stop the trace when the epoch
        raises (the leak the copy-pasted start/stop pairs had)."""
        import jax

        from tpu_compressed_dp.harness.loop import profile_trace

        calls = []
        monkeypatch.setattr(jax.profiler, "start_trace",
                            lambda d: calls.append(("start", d)))
        monkeypatch.setattr(jax.profiler, "stop_trace",
                            lambda: calls.append(("stop", None)))
        with pytest.raises(RuntimeError):
            with profile_trace("/tmp/x") as active:
                assert active
                raise RuntimeError("mid-epoch failure")
        assert calls == [("start", "/tmp/x"), ("stop", None)]
        # falsy dir: no-op, nothing started
        with profile_trace(None) as active:
            assert not active
        assert len(calls) == 2


@pytest.mark.quick
class TestTensorboardLogger:
    @pytest.mark.slow  # ~9 s TF import; events/prom/heartbeat are the
    # primary telemetry surfaces and stay tier-1
    def test_writes_scalars_and_json(self, tmp_path):
        tb = TensorboardLogger(str(tmp_path / "tb"))
        tb.update_examples_count(512)
        tb.log_scalar("losses/train_loss", 1.5)
        tb.update_examples_count(512)
        tb.log_scalar("losses/train_loss", 1.2)
        tb.log_metrics({"net/x": 3.0, "skip": "str"})
        tb.close()
        data = json.load(open(tmp_path / "tb" / "scalars.json"))
        assert data["losses/train_loss"] == [[512, 1.5], [1024, 1.2]]
        assert data["net/x"] == [[1024, 3.0]]
        assert any(f.startswith("events") for f in os.listdir(tmp_path / "tb"))

    def test_non_master_is_noop(self, tmp_path):
        tb = TensorboardLogger(str(tmp_path / "tb2"), is_master=False)
        assert isinstance(tb, NoOp)
        tb.log_scalar("x", 1.0)  # absorbs anything
        tb.close()
        assert not (tmp_path / "tb2").exists()

    def test_disabled_without_dir(self):
        assert isinstance(TensorboardLogger(None), NoOp)


@pytest.mark.quick
class TestFileLogger:
    def test_level_routing(self, tmp_path, capsys):
        log = FileLogger(str(tmp_path), rank=3)
        log.debug("dbg")
        log.info("inf")
        log.event("~~1\t0.1\t90\t95")
        verbose = (tmp_path / "verbose.log").read_text()
        event = (tmp_path / "event.log").read_text()
        debug = (tmp_path / "debug.log").read_text()
        assert "inf" in verbose and "~~1" in verbose and "dbg" not in verbose
        assert "~~1" in event and "inf" not in event
        assert "dbg" in debug and "DEBUG" in debug
        assert "3: inf" in capsys.readouterr().out  # rank-prefixed console

    def test_non_master_console_only(self, tmp_path):
        FileLogger(None, rank=1, is_master=False).info("x")
        assert not os.listdir(tmp_path)


@pytest.mark.quick
class TestMeters:
    def test_network_bytes_reads_proc(self):
        recv, transmit = meters.network_bytes()
        assert recv >= 0 and transmit >= 0

    def test_network_meter_interval(self):
        m = meters.NetworkMeter()
        rg, tg = m.update_bandwidth()
        assert rg >= 0 and tg >= 0

    def test_time_meter(self):
        m = meters.TimeMeter()
        m.batch_loaded()
        m.batch_dispatched()
        s = m.summary()
        assert s["data ms/batch"] >= 0 and s["dispatch ms/batch"] >= 0

    def test_comm_meter(self):
        m = meters.CommMeter(world=8)
        m.update({"comm/sent_bits": 8e6, "comm/dense_elems": 1e6})
        m.update({"comm/sent_bits": 8e6, "comm/dense_elems": 1e6})
        out = m.gbps()
        assert out["net/payload_mb_per_step"] == pytest.approx(1.0)
        assert out["net/compression_frac"] == pytest.approx(0.25)
        assert out["net/allreduce_gbps_per_chip"] > 0


@pytest.mark.slow
def test_imagenet_harness_tensorboard_integration(tmp_path):
    # full imagenet-harness run (~60 s CPU): the tensorboard/event-stream
    # surface it exercises end-to-end stays tier-1-covered by the dawn/LM
    # e2e runs and the TestTraceReport/TestEventStream units; slow-marked
    # so tier-1 keeps headroom under its 870 s budget
    from tpu_compressed_dp.harness import imagenet as h

    ev_path = str(tmp_path / "events.jsonl")
    summary = h.main([
        "--synthetic", "--synthetic_n", "64", "--num_classes", "4",
        "--arch", "resnet18", "--width", "8", "--short_epoch", "--workers", "2",
        "--compress", "layerwise", "--method", "randomk", "--ratio", "0.1",
        "--logdir", str(tmp_path), "--tensorboard", "--events", ev_path,
    ])
    scalars = json.load(open(tmp_path / "tb" / "scalars.json"))
    assert "losses/top5" in scalars and "net/payload_mb_per_step" in scalars
    assert len(scalars["losses/train_loss"]) == 3  # smoke schedule: 3 epochs
    # x-axis is cumulative examples
    xs = [p[0] for p in scalars["losses/train_loss"]]
    assert xs == sorted(xs) and xs[0] > 0
    assert "~~0" in (tmp_path / "event.log").read_text()
    assert (tmp_path / "logs.tsv").exists()
    # throughput + comm-rate columns reach the epoch summary
    assert summary["img/s"] > 0
    assert summary["comm MB/s"] > 0
    # the JSONL event stream parses, is schema-versioned, and feeds
    # trace_report's breakdown + throughput tables without error
    import tools.trace_report as tr

    events = obs_export.read_events(ev_path)
    kinds = [e["kind"] for e in events]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    assert kinds.count("epoch") == 3
    assert all(e["v"] == obs_export.SCHEMA_VERSION for e in events)
    ep = next(e for e in events if e["kind"] == "epoch")
    assert ep["throughput"]["throughput/examples_per_sec"] > 0
    assert ep["step_spans"] and ep["timeline"]["time/steps_per_sec"] > 0
    report = tr.render_report(events)
    assert "per-phase step-time breakdown" in report and "MFU" in report


@pytest.mark.quick
class TestEventStreamRotation:
    """--events_max_mb size-capped streams: rotation is atomic, every
    record carries its segment index, and the reader stitches segments
    back into one ordered stream (ISSUE 15 satellite)."""

    def test_rotate_and_stitch(self, tmp_path):
        p = str(tmp_path / "ev.jsonl")
        with obs_export.EventStream(p, meta={"harness": "t"},
                                    max_bytes=256) as es:
            for i in range(20):
                es.emit("step", step=i, metrics={"loss": 1.0})
        segs = obs_export.list_segments(p)
        assert segs, "256-byte cap over 20 records must rotate"
        # live file still parses on its own; stitched view sees everything
        assert os.path.exists(p)
        events = obs_export.read_all_events(p)
        kinds = [e["kind"] for e in events]
        assert kinds[0] == "run_start" and kinds[-1] == "run_end"
        assert [e["step"] for e in events if e["kind"] == "step"] \
            == list(range(20))
        # every record names its segment; indices ascend across the stitch
        seg_ids = [e["seg"] for e in events]
        assert seg_ids == sorted(seg_ids)
        assert seg_ids[-1] == len(segs)
        # no torn tmp files left behind by the atomic replace
        assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]

    def test_resume_continues_numbering(self, tmp_path):
        p = str(tmp_path / "ev.jsonl")
        with obs_export.EventStream(p, max_bytes=200) as es:
            for i in range(10):
                es.emit("step", step=i)
        n_segs = len(obs_export.list_segments(p))
        assert n_segs >= 1
        with obs_export.EventStream(p, max_bytes=200) as es:
            for i in range(10, 20):
                es.emit("step", step=i)
        assert len(obs_export.list_segments(p)) > n_segs
        steps = [e["step"] for e in obs_export.read_all_events(p)
                 if e["kind"] == "step"]
        assert steps == list(range(20))

    def test_unbounded_stays_single_file(self, tmp_path):
        p = str(tmp_path / "ev.jsonl")
        with obs_export.EventStream(p) as es:
            for i in range(50):
                es.emit("step", step=i)
        assert obs_export.list_segments(p) == []
        assert len(obs_export.read_all_events(p)) \
            == len(obs_export.read_events(p)) == 52


@pytest.mark.quick
class TestFlightRecorder:
    def _fl(self, tmp_path=None, **kw):
        from tpu_compressed_dp.obs.flight import FlightRecorder

        kw.setdefault("rank", 0)
        kw.setdefault("capacity", 8)
        if tmp_path is not None:
            kw.setdefault("directory", str(tmp_path))
        return FlightRecorder(**kw)

    def test_rings_bounded_under_hammer(self):
        """O(capacity) memory: 10k notes never grow any ring past the
        cap, while the counters keep exact totals (ISSUE 15 acceptance)."""
        fl = self._fl(capacity=8)
        for i in range(10_000):
            fl.note_step(i, {"loss": 1.0, "guard/skipped": 0.0})
        snap = fl.snapshot()
        assert all(len(ring) <= 8 for ring in snap["rings"].values())
        # note_step with a guard/ key writes two records (step + guard)
        assert snap["records"] == 20_000
        m = fl.metrics()
        assert m["flight/records"] == 20_000.0
        assert m["flight/dumps"] == 0.0 and m["flight/last_dump_step"] == -1.0
        # newest records win: the step ring holds the tail of the run
        assert [r["step"] for r in snap["rings"]["step"]] \
            == list(range(9_992, 10_000))

    def test_unknown_channel_and_bad_capacity(self):
        from tpu_compressed_dp.obs.flight import FlightRecorder

        fl = self._fl()
        with pytest.raises(ValueError, match="unknown flight channel"):
            fl.record("typo", "oops")
        with pytest.raises(ValueError, match="capacity"):
            FlightRecorder(capacity=0)

    def test_observe_dump_roundtrip(self, tmp_path):
        from tpu_compressed_dp.obs import flight as fli
        from tpu_compressed_dp.train.elastic import PeerFailed

        fl = self._fl(tmp_path, meta={"harness": "t"})
        fl.note_step(5, {"loss": 2.0})
        err = PeerFailed((3, 1), step=5, reason="gossip stale")
        path = fl.observe(err)
        assert path == fli.bundle_path(str(tmp_path), 0)
        bundles = fli.read_bundles(str(tmp_path))
        assert set(bundles) == {0}
        b = bundles[0]
        assert fli.validate_bundle(b) == []
        assert b["reason"] == "peer_failed" and b["step"] == 5
        assert b["error"]["failed"] == [1, 3]  # ctor sorts the tuple
        assert b["rings"]["fault"][-1]["kind"] == "peer_failed"
        assert b["rings"]["step"][-1]["metrics"] == {"loss": 2.0}
        assert fl.metrics()["flight/dumps"] == 1.0
        assert fl.metrics()["flight/last_dump_step"] == 5.0

    def test_observe_without_directory_is_noop_dump(self):
        fl = self._fl()
        assert fl.observe(RuntimeError("boom"), step=1) is None
        assert fl.metrics()["flight/dumps"] == 0.0
        assert fl.snapshot()["rings"]["fault"]  # evidence still recorded

    def test_classify_failure_mapping(self):
        from tpu_compressed_dp.obs.flight import classify_failure
        from tpu_compressed_dp.train.elastic import PeerFailed
        from tpu_compressed_dp.train.guard import GuardExceeded
        from tpu_compressed_dp.utils import chaos, resilience
        from tpu_compressed_dp.utils.checkpoint import CheckpointCorrupt

        assert classify_failure(GuardExceeded("wedged")) == "guard_exceeded"
        assert classify_failure(PeerFailed((1,))) == "peer_failed"
        assert classify_failure(resilience.Preempted("sig")) == "preempt"
        assert classify_failure(CheckpointCorrupt("bad")) == "ckpt_corrupt"
        assert classify_failure(chaos.ChaosCrash("kill")) == "chaos_crash"
        assert classify_failure(RuntimeError("?")) == "error"

    def test_note_chaos_uses_fault_kind(self):
        from tpu_compressed_dp.utils.chaos import ChaosConfig

        fl = self._fl()
        fl.note_chaos(ChaosConfig(kind="nan", target="grads", every=1,
                                  worker=1))
        fl.note_chaos("nan:grads")  # spec-string form
        fl.note_chaos(None)  # disarmed: no record
        ring = fl.snapshot()["rings"]["chaos"]
        assert len(ring) == 2
        assert ring[0]["kind"] == "nan" and ring[0]["worker"] == 1
        assert ring[1]["kind"] == "armed" and ring[1]["spec"] == "nan:grads"

    def test_publish_single_rank_degrades(self, tmp_path):
        fl = self._fl(tmp_path)
        fl.note_spans([{"t0": 1.0, "data": 0.1, "total": 1.0}])
        g = fl.publish()
        assert g == {"straggler/skew_s": 0.0, "straggler/rank": -1.0,
                     "straggler/frac": 0.0}

    def test_registry_conformance(self):
        """Every gauge the recorder exports (counters + live straggler
        family) is registry-declared with a host emitter (TCDP103)."""
        from tpu_compressed_dp.obs.flight import straggler_gauges

        fl = self._fl()
        names = set(fl.metrics()) | set(straggler_gauges({}))
        assert names == {"flight/records", "flight/dumps",
                         "flight/last_dump_step", "straggler/skew_s",
                         "straggler/rank", "straggler/frac"}
        for name in names:
            assert obs_registry.is_declared(name), name
            assert obs_registry.spec(name).emitter == "host", name


@pytest.mark.quick
class TestStragglerEndToEnd:
    """Scripted skewed timelines -> shared phase profiles -> live
    straggler_* gauges -> heartbeat -> watchdog exit 1 (ISSUE 15
    acceptance: the whole live path, no training loop required)."""

    def _publish(self, tmp_path):
        from tpu_compressed_dp.obs.flight import FlightRecorder

        gauges = {}
        for rank, step_s in ((0, 0.10), (1, 0.10), (2, 0.25)):
            fl = FlightRecorder(rank=rank, capacity=16,
                                directory=str(tmp_path))
            fl.note_spans([{"t0": float(i), "data": step_s / 2,
                            "dispatch": step_s / 2, "total": step_s}
                           for i in range(4)])
            gauges = fl.publish()
        return gauges

    def test_gauges_to_watchdog(self, tmp_path):
        import time as _time

        import tools.watchdog as wd

        g = self._publish(tmp_path)
        assert g["straggler/rank"] == 2.0
        assert g["straggler/skew_s"] == pytest.approx(0.15)
        assert g["straggler/frac"] == pytest.approx(1.5)
        # the harness folds the gauges into the heartbeat top level...
        hb = str(tmp_path / "hb.json")
        json.dump({"ts": _time.time(), "step": 10, "last_good_step": 10,
                   "straggler_skew_s": g["straggler/skew_s"],
                   "straggler_rank": g["straggler/rank"]}, open(hb, "w"))
        # ...and the watchdog turns a breach into exit 1
        assert wd.main(["--check", "--heartbeat", hb,
                        "--max_straggler_skew", "0.05"]) == 1
        assert wd.main(["--check", "--heartbeat", hb,
                        "--max_straggler_skew", "0.5"]) == 0

    def test_prometheus_export(self, tmp_path):
        g = self._publish(tmp_path)
        prom = str(tmp_path / "m.prom")
        obs_export.write_prometheus(g, prom, labels={"harness": "t"})
        body = open(prom).read()
        assert "# TYPE tcdp_straggler_skew_s gauge" in body
        assert 'tcdp_straggler_rank{harness="t"} 2' in body

    def test_offline_matches_live(self, tmp_path):
        """postmortem's straggler_from_bundles recomputes the SAME gauges
        from dumped timing rings — one skew definition, two surfaces."""
        from tpu_compressed_dp.obs.flight import FlightRecorder, read_bundles
        from tools.postmortem import straggler_from_bundles

        live = self._publish(tmp_path)
        for rank, step_s in ((0, 0.10), (1, 0.10), (2, 0.25)):
            fl = FlightRecorder(rank=rank, capacity=16,
                                directory=str(tmp_path))
            fl.note_spans([{"t0": float(i), "data": step_s / 2,
                            "dispatch": step_s / 2, "total": step_s}
                           for i in range(4)])
            fl.dump("error")
        offline = straggler_from_bundles(read_bundles(str(tmp_path)))
        assert offline == pytest.approx(live)


@pytest.mark.quick
class TestTraceReportMerge:
    def _rank_events(self, tmp_path, rank, lag=0.0):
        p = str(tmp_path / f"ev.rank{rank}.jsonl")
        with obs_export.EventStream(p, meta={"harness": "t"}) as es:
            spans = [{"t0": 100.0 * rank + i, "data": 0.2,
                      "dispatch": 0.8 + lag, "total": 1.0 + lag}
                     for i in range(3)]
            es.emit("epoch", epoch=1, step=3, metrics={},
                    throughput={}, guard={}, timeline={}, step_spans=spans)
        return p

    def test_merge_cli(self, tmp_path):
        import tools.trace_report as tr

        p0 = self._rank_events(tmp_path, 0)
        p1 = self._rank_events(tmp_path, 1, lag=0.5)
        out = str(tmp_path / "merged.json")
        assert tr.main([p0, p1, "--merge", "--chrome", out]) == 0
        trace = json.load(open(out))
        evs = trace["traceEvents"]
        # one process lane per rank, named via metadata events
        meta = [e for e in evs if e["ph"] == "M"]
        assert {(e["pid"], e["args"]["name"]) for e in meta} \
            == {(0, "rank 0"), (1, "rank 1")}
        by_pid = {pid: [e for e in evs if e["ph"] == "X" and e["pid"] == pid]
                  for pid in (0, 1)}
        assert len(by_pid[0]) == 6 and len(by_pid[1]) == 6  # 3 steps x 2 ph
        # spans align on each rank's own first t0 (host clocks are
        # per-process): both lanes start at ts 0
        assert min(e["ts"] for e in by_pid[0]) == 0.0
        assert min(e["ts"] for e in by_pid[1]) == 0.0
        # the lagging rank's dispatch spans are visibly longer
        d0 = [e for e in by_pid[0] if e["name"] == "dispatch"][0]["dur"]
        d1 = [e for e in by_pid[1] if e["name"] == "dispatch"][0]["dur"]
        assert d1 == pytest.approx(d0 + 0.5e6)

    def test_merge_flag_errors(self, tmp_path):
        import tools.trace_report as tr

        p0 = self._rank_events(tmp_path, 0)
        p1 = self._rank_events(tmp_path, 1)
        with pytest.raises(SystemExit):  # multi-file needs --merge
            tr.main([p0, p1, "--chrome", str(tmp_path / "x.json")])
        with pytest.raises(SystemExit):  # --merge needs --chrome
            tr.main([p0, p1, "--merge"])

    def test_merge_reads_rotated_streams(self, tmp_path):
        """A size-capped (--events_max_mb) per-rank stream merges whole:
        the stitcher feeds the lane builder, not just the live file."""
        import tools.trace_report as tr

        p0 = str(tmp_path / "r0.jsonl")
        with obs_export.EventStream(p0, max_bytes=200) as es:
            for i in range(3):
                es.emit("epoch", epoch=i, step=i + 1, metrics={},
                        throughput={}, guard={}, timeline={},
                        step_spans=[{"t0": float(i), "data": 0.1,
                                     "dispatch": 0.2, "total": 0.3}])
        assert obs_export.list_segments(p0)
        p1 = self._rank_events(tmp_path, 1)
        out = str(tmp_path / "merged.json")
        assert tr.main([p0, p1, "--merge", "--chrome", out]) == 0
        evs = json.load(open(out))["traceEvents"]
        lane0 = [e for e in evs if e["ph"] == "X" and e["pid"] == 0]
        assert len(lane0) == 6  # all 3 rotated-away steps x 2 phases


@pytest.mark.quick
class TestPostmortemClassify:
    """Verdict taxonomy priority order on synthetic bundles (the chaos
    drill covers the real failure paths; these pin the tie-breaks)."""

    def _bundle(self, rank, reason, *, step=None, error=None, rings=None):
        from tpu_compressed_dp.obs.flight import CHANNELS, FLIGHT_SCHEMA

        base = {ch: [] for ch in CHANNELS}
        base.update(rings or {})
        return {"v": FLIGHT_SCHEMA, "kind": "blackbox", "rank": rank,
                "reason": reason, "step": step, "seq": 1, "capacity": 8,
                "meta": {}, "error": error, "extra": None,
                "counts": {"records": 1, "dumps": 1}, "rings": base}

    def test_priority_order(self):
        from tools.postmortem import classify

        corrupt = self._bundle(1, "ckpt_corrupt", step=7,
                               error={"message": "manifest sha mismatch"})
        preempt = self._bundle(0, "preempt", step=7, error={"signum": 15})
        peer = self._bundle(2, "peer_failed", step=7,
                            error={"failed": [0]})
        guard = self._bundle(3, "guard_exceeded", step=7, error={})
        v = classify({0: preempt, 1: corrupt, 2: peer, 3: guard})
        assert (v["kind"], v["rank"]) == ("corruption", 1)
        v = classify({0: preempt, 2: peer, 3: guard})
        assert (v["kind"], v["rank"]) == ("preempt", 0)
        v = classify({2: peer, 3: guard})
        assert (v["kind"], v["rank"]) == ("dead_peer", 0)
        v = classify({3: guard})
        assert (v["kind"], v["rank"]) == ("guard", -1)

    def test_nan_names_injected_worker(self):
        from tools.postmortem import classify

        chaos_rec = {"kind": "nan", "seq": 0, "t": 0.0, "target": "grads",
                     "every": 1, "worker": 2, "crash_at_step": -1}
        b = self._bundle(0, "guard_exceeded", step=4, error={},
                         rings={"chaos": [chaos_rec]})
        v = classify({0: b})
        assert (v["kind"], v["rank"], v["step"]) == ("nan", 2, 4)
        assert "grads" in v["detail"]

    def test_dead_peer_chaos_fallback_requires_armed_crash(self):
        from tools.postmortem import classify

        # survivors raised a bare PeerFailed with no .failed evidence
        def peer(rings=None):
            return self._bundle(0, "peer_failed", step=3, error={},
                                rings=rings)

        armed = {"kind": "crash", "seq": 0, "t": 0.0, "worker": 1,
                 "crash_at_step": 3}
        v = classify({0: peer({"chaos": [armed]})})
        assert (v["kind"], v["rank"]) == ("dead_peer", 1)
        # an unarmed config (crash_at_step=-1) must NOT name a scapegoat
        unarmed = dict(armed, crash_at_step=-1)
        v = classify({0: peer({"chaos": [unarmed]})})
        assert (v["kind"], v["rank"]) == ("dead_peer", -1)

    def test_straggler_fallback_and_unknown(self):
        from tools.postmortem import STRAGGLER_FRAC, classify

        def timing(step_s):
            return {"timing": [{"kind": "span", "seq": i, "t": 0.0,
                                "data": step_s / 2, "total": step_s}
                               for i in range(4)]}

        slow = self._bundle(1, "error", rings=timing(0.4))
        fast = self._bundle(0, "error", rings=timing(0.1))
        v = classify({0: fast, 1: slow})
        assert (v["kind"], v["rank"]) == ("straggler", 1)
        # under the skew floor the verdict stays unknown, not straggler
        near = self._bundle(1, "error",
                            rings=timing(0.1 * (1 + STRAGGLER_FRAC / 2)))
        v = classify({0: fast, 1: near})
        assert v["kind"] == "unknown"
        assert classify({})["kind"] == "unknown"
        assert classify({})["rank"] == -1

    def test_merge_timeline_order_and_report(self):
        from tools import postmortem as pm

        b0 = self._bundle(
            0, "peer_failed", step=2, error={"failed": [1]},
            rings={"step": [{"kind": "metrics", "seq": 0, "t": 0.1,
                             "step": 1},
                            {"kind": "metrics", "seq": 1, "t": 0.2,
                             "step": 2}],
                   "fault": [{"kind": "peer_failed", "seq": 2, "t": 0.3}]})
        b1 = self._bundle(
            1, "chaos_crash", step=2, error={},
            rings={"step": [{"kind": "metrics", "seq": 0, "t": 0.1,
                             "step": 2}]})
        merged = pm.merge_timeline({0: b0, 1: b1})
        # stepped records first (step, rank, seq); step-less sort last
        assert [(r["rank"], r.get("step")) for r in merged] \
            == [(0, 1), (0, 2), (1, 2), (0, None)]
        report = pm.render_report({0: b0, 1: b1})
        assert report.splitlines()[0].startswith("postmortem: dead_peer")
        assert "cross-rank timeline" in report
        assert pm.verdict_line(pm.classify({0: b0, 1: b1})) \
            == report.splitlines()[0]

    def test_cli_json_and_missing_dir(self, tmp_path, capsys):
        from tools import postmortem as pm
        from tpu_compressed_dp.obs.flight import FlightRecorder
        from tpu_compressed_dp.train.guard import GuardExceeded

        assert pm.main([str(tmp_path / "empty")]) == 2
        fl = FlightRecorder(rank=0, capacity=8, directory=str(tmp_path))
        fl.note_step(3, {"loss": float("nan")})
        fl.observe(GuardExceeded("skip streak 2 exceeded"), step=3)
        capsys.readouterr()
        assert pm.main([str(tmp_path), "--json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["verdict"]["kind"] == "guard"
        assert payload["ranks"]["0"]["reason"] == "guard_exceeded"
        assert payload["ranks"]["0"]["problems"] == []
        assert payload["timeline"]
