"""PowerSGD low-rank compression: math, stateful sync engine, checkpointing.

The properties pinned here are the ones the subsystem's design rests on
(ops/lowrank.py):

  * psum-linearity — every nonlinear step happens AFTER a psum, so the
    2-worker sync equals the same compression applied to the worker-mean
    gradient;
  * transport — the P/Q factors ride the psum ring and nothing else
    (``sent_bits_psum > 0``, ``sent_bits_allgather == 0``), at fewer bits
    than dense;
  * state — the warm-start Q threads through the sync and survives an Orbax
    checkpoint round-trip bitwise, and warm-starting actually helps (the
    reconstruction error of a repeated gradient decreases across steps).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_compressed_dp.compat import shard_map
from tpu_compressed_dp.ops import compressors, lowrank
from tpu_compressed_dp.parallel.dp import (
    CompressionConfig,
    init_comp_state,
    init_comp_state_grouped,
    init_ef_state,
    make_grad_sync,
    make_grouped_grad_sync,
)


@pytest.fixture(scope="module")
def mesh2():
    from tpu_compressed_dp.parallel.mesh import make_data_mesh

    return make_data_mesh(2)


def run_sync(mesh, cfg, grads_per_dev, comp, ef=None, seed=0):
    """grads_per_dev leaves have leading dim == mesh size; returns
    (synced, new_ef, new_comp, stats) with comp threaded through."""
    sync = make_grad_sync(cfg, "data")
    if ef is None:
        ef = init_ef_state(jax.tree.map(lambda g: g[0], grads_per_dev), cfg)

    def f(g, e, c):
        return sync(jax.tree.map(lambda x: x[0], g), e, c, jax.random.key(seed))

    shard_spec = jax.tree.map(lambda _: P("data"), grads_per_dev)
    fn = shard_map(
        f, mesh=mesh,
        in_specs=(shard_spec, P(), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    return fn(grads_per_dev, ef, comp)


@pytest.mark.quick
class TestDims:
    def test_near_square_and_rank_clamp(self):
        m, n2, r = lowrank.powersgd_dims(10000, 4)
        assert m * n2 >= 10000 and abs(m - n2) <= 1
        assert r == 4
        # rank ~ m means the factors cost ~2n — always the dense fallback
        # (the clamp to min(m, n2) can never beat it at near-square shapes)
        assert lowrank.powersgd_dims(10000, 1000) is None
        assert lowrank.powersgd_dims(256, 64) is None

    def test_dense_fallback_for_tiny_groups(self):
        # factors r*(m+n2) >= n: biases / norm scales send dense
        assert lowrank.powersgd_dims(32, 4) is None
        assert lowrank.powersgd_dims(1, 1) is None
        assert lowrank.powersgd_group_bits(32, 4) == 32.0 * 32

    def test_payload_bits_per_elem(self):
        n = 1 << 20
        m, n2, r = lowrank.powersgd_dims(n, 2)
        got = compressors.payload_bits_per_elem("powersgd", rank=2, n=n)
        assert got == pytest.approx(32.0 * r * (m + n2) / n)
        assert got < 1.0  # ~0.25% of dense at 1M elements, r=2
        with pytest.raises(ValueError, match="shape-dependent"):
            compressors.payload_bits_per_elem("powersgd", rank=2)

    def test_registry(self):
        assert "powersgd" in compressors.REGISTRY
        assert compressors.canonical_name("power_sgd") == "powersgd"
        bound = compressors.get_compressor("powersgd", rank=2)
        assert bound.is_stateful and bound.needs_rng
        g = jax.random.normal(jax.random.key(0), (4096,))
        out = bound.fn(g, jax.random.key(1))
        assert out.shape == g.shape
        # a single-shot rank-2 approximation is not the identity but keeps
        # a nontrivial fraction of the energy
        err = float(jnp.linalg.norm(out - g) / jnp.linalg.norm(g))
        assert 0.0 < err < 1.0


@pytest.mark.quick
class TestGramSchmidt:
    def test_orthonormal_columns(self):
        p = jax.random.normal(jax.random.key(3), (50, 4))
        q = lowrank.gram_schmidt(p)
        np.testing.assert_allclose(np.asarray(q.T @ q), np.eye(4), atol=1e-5)

    def test_batched(self):
        p = jax.random.normal(jax.random.key(4), (3, 50, 2))
        q = lowrank.gram_schmidt(p)
        for b in range(3):
            np.testing.assert_allclose(
                np.asarray(q[b].T @ q[b]), np.eye(2), atol=1e-5)

    def test_zero_and_deficient_columns_stay_finite(self):
        q = lowrank.gram_schmidt(jnp.zeros((10, 3)))
        assert np.all(np.isfinite(np.asarray(q)))
        # duplicated column: the second projects to ~0, must not NaN
        col = jax.random.normal(jax.random.key(5), (10, 1))
        q = lowrank.gram_schmidt(jnp.concatenate([col, col], axis=1))
        assert np.all(np.isfinite(np.asarray(q)))


def _local_reference(mean_flat, q0, rank):
    """The engine's math on a single (already-averaged) gradient."""
    n = mean_flat.shape[0]
    m, n2, r = lowrank.powersgd_dims(n, rank)
    mat = lowrank._as_matrix(mean_flat, m, n2)
    p_hat = lowrank.gram_schmidt(lowrank._dot(mat, q0))
    q1 = lowrank._dot(mat.T, p_hat)
    recon = lowrank._dot(p_hat, q1.T).reshape(-1)[:n]
    return recon, q1


class TestTwoWorkerSync:
    """The acceptance-criteria tests: psum-linearity and transport split."""

    def make(self, n=4096, rank=2):
        cfg = CompressionConfig(method="powersgd", rank=rank,
                                granularity="entiremodel")
        grads = {"w": jax.random.normal(jax.random.key(11), (2, n))}
        comp = init_comp_state({"w": grads["w"][0]}, cfg)
        return cfg, grads, comp

    def test_psum_linearity(self, mesh2):
        """2-worker PowerSGD sync == the same compression applied to the
        mean of the per-worker gradients (every nonlinear step runs after
        a psum, so the collective IS a mean over low-rank factor payloads)."""
        cfg, grads, comp = self.make()
        out, _, new_comp, _ = run_sync(mesh2, cfg, grads, comp)
        mean = jnp.mean(grads["w"], axis=0)
        exp, q1 = _local_reference(mean, comp["q0"], cfg.rank)
        np.testing.assert_allclose(np.asarray(out["w"]), np.asarray(exp),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(new_comp["q0"]), np.asarray(q1),
                                   rtol=1e-5, atol=1e-6)

    def test_factors_ride_psum_only(self, mesh2):
        cfg, grads, comp = self.make()
        _, _, _, stats = run_sync(mesh2, cfg, grads, comp)
        assert float(stats["sent_bits_psum"]) > 0
        assert float(stats["sent_bits_allgather"]) == 0.0
        assert float(stats["sent_bits"]) == float(stats["sent_bits_psum"])
        # compressed: far below the 32 bits/elem dense wire
        assert float(stats["sent_bits"]) < 32.0 * float(stats["dense_elems"])
        m, n2, r = lowrank.powersgd_dims(4096, cfg.rank)
        assert float(stats["sent_elems"]) == r * (m + n2)
        assert float(stats["num_collectives"]) == 2.0  # P-psum + Q-psum

    def test_ef_residual_identity(self, mesh2):
        """Per worker: transmitted reconstruction + residual == gradient
        (Stich-style memory, here against the worker-mean reconstruction)."""
        cfg = CompressionConfig(method="powersgd", rank=2,
                                granularity="entiremodel", error_feedback=True)
        grads = {"w": jax.random.normal(jax.random.key(12), (2, 2048))}
        comp = init_comp_state({"w": grads["w"][0]}, cfg)
        out, new_ef, _, _ = run_sync(mesh2, cfg, grads, comp)
        # run_sync returns device-0's residual slice (out_specs P())
        np.testing.assert_allclose(
            np.asarray(new_ef["w"]),
            np.asarray(grads["w"][0] - out["w"]), rtol=1e-5, atol=1e-6)

    def test_layerwise_mixes_compressed_and_dense_groups(self, mesh2):
        cfg = CompressionConfig(method="powersgd", rank=4,
                                granularity="layerwise")
        grads = {
            "w": jax.random.normal(jax.random.key(13), (2, 4096)),
            "b": jax.random.normal(jax.random.key(14), (2, 8)),
        }
        comp = init_comp_state(
            jax.tree.map(lambda g: g[0], grads), cfg)
        # leaves sort by key: 'b' is group 0 (dense fallback, no state),
        # 'w' is group 1 (compressed)
        assert set(comp) == {"q1"}
        out, _, new_comp, stats = run_sync(mesh2, cfg, grads, comp)
        # dense-fallback group is exactly the mean
        np.testing.assert_allclose(np.asarray(out["b"]),
                                   np.asarray(grads["b"].mean(0)), rtol=1e-6)
        assert set(new_comp) == {"q1"}
        # dense group bills 32 bits/elem, still on the psum ring
        assert float(stats["sent_bits_allgather"]) == 0.0

    def test_missing_state_raises(self, mesh2):
        cfg, grads, _ = self.make()
        with pytest.raises(ValueError, match="init_comp_state"):
            run_sync(mesh2, cfg, grads, ())

    def test_check_sync_reports_warm_start_agreement(self, mesh2):
        """check_sync (the check_reduction analog): agreeing warm starts
        report sync_agree == 1.0 — the factor psums are only meaningful in a
        shared basis, so divergence here is the powersgd equivalent of
        misaligned Random-K indices."""
        cfg = CompressionConfig(method="powersgd", rank=2,
                                granularity="entiremodel", check_sync=True)
        grads = {"w": jax.random.normal(jax.random.key(11), (2, 4096))}
        comp = init_comp_state({"w": grads["w"][0]}, cfg)
        _, _, _, stats = run_sync(mesh2, cfg, grads, comp)
        assert float(stats["sync_agree"]) == 1.0

    def test_warm_start_converges_on_repeated_gradient(self, mesh2):
        """Power iteration with a persistent Q: reconstruction error of a
        FIXED gradient strictly improves over fresh-random single shots
        within a few steps (the whole point of warm-starting)."""
        cfg, grads, comp = self.make(n=2048, rank=2)
        mean = np.asarray(jnp.mean(grads["w"], axis=0))
        errs = []
        for _ in range(6):
            out, _, comp, _ = run_sync(mesh2, cfg, grads, comp)
            errs.append(float(np.linalg.norm(np.asarray(out["w"]) - mean)))
        assert errs[-1] <= errs[0] * (1 + 1e-6)
        assert errs[-1] == min(errs)


class TestGroupedSync:
    def test_comp_threads_through_signature_groups(self, mesh2):
        cfg = CompressionConfig(method="powersgd", rank=2,
                                granularity="layerwise")
        grads = {"a": jax.random.normal(jax.random.key(21), (2, 1024)),
                 "b": jax.random.normal(jax.random.key(22), (2, 900))}
        local = jax.tree.map(lambda g: g[0], grads)
        is_sharded = [False, False]
        comp = init_comp_state_grouped(local, cfg, is_sharded, "data")
        assert set(comp) == {"sig0"} and set(comp["sig0"]) == {"q0", "q1"}
        sync = make_grouped_grad_sync(cfg, "data", is_sharded, "data")

        def f(g, c):
            return sync(jax.tree.map(lambda x: x[0], g), (), c,
                        jax.random.key(0))

        out, _, new_comp, stats = shard_map(
            f, mesh=mesh2,
            in_specs=(jax.tree.map(lambda _: P("data"), grads), P()),
            out_specs=(P(), P(), P(), P()), check_vma=False,
        )(grads, comp)
        assert set(new_comp) == {"sig0"}
        for k in ("q0", "q1"):
            assert new_comp["sig0"][k].shape == comp["sig0"][k].shape
        assert float(stats["sent_bits_allgather"]) == 0.0


class TestCheckpointRoundTrip:
    def test_warm_start_q_survives_orbax_bitwise(self, tmp_path):
        """Acceptance criterion: TrainState.comp round-trips through Orbax
        exactly — a resumed run continues the power iteration from the
        converged subspace, not from random."""
        from tpu_compressed_dp.train.state import TrainState
        from tpu_compressed_dp.utils.checkpoint import (
            restore_checkpoint, save_checkpoint)

        cfg = CompressionConfig(method="powersgd", rank=4,
                                granularity="layerwise", error_feedback=True)
        params = {"w": jnp.zeros((4096,)), "b": jnp.zeros((8,))}
        comp = init_comp_state(params, cfg, num_devices=2)
        ef = init_ef_state(params, cfg, num_devices=2)
        # make the state visibly non-fresh so the round-trip is meaningful
        comp = jax.tree.map(lambda q: q + 0.123, comp)
        state = TrainState.create(params, {}, {"momentum": params}, ef,
                                  jax.random.key(7), comp=comp)
        save_checkpoint(str(tmp_path / "ckpt"), state)

        target = TrainState.create(
            params, {}, {"momentum": params},
            jax.tree.map(jnp.zeros_like, ef), jax.random.key(0),
            comp=jax.tree.map(jnp.zeros_like, comp))
        restored, _ = restore_checkpoint(str(tmp_path / "ckpt"), target)
        assert set(restored.comp) == set(comp)
        for k in comp:
            assert np.array_equal(np.asarray(restored.comp[k]),
                                  np.asarray(comp[k]))  # bitwise
            assert restored.comp[k].dtype == comp[k].dtype

    def test_stateless_comp_roundtrips_as_empty(self, tmp_path):
        from tpu_compressed_dp.train.state import TrainState
        from tpu_compressed_dp.utils.checkpoint import (
            restore_checkpoint, save_checkpoint)

        params = {"w": jnp.ones((16,))}
        state = TrainState.create(params, {}, {"momentum": params}, (),
                                  jax.random.key(1))
        save_checkpoint(str(tmp_path / "ckpt"), state)
        restored, _ = restore_checkpoint(str(tmp_path / "ckpt"), state)
        assert restored.comp == ()

    def test_pre_comp_checkpoint_still_restores(self, tmp_path, monkeypatch):
        """Back-compat: checkpoints written before TrainState grew `comp`
        have no such key on disk; restore must fall back instead of failing
        Orbax's structure check, keeping the caller's comp — () normally, a
        freshly-built warm start when resuming an old run with powersgd
        newly enabled."""
        from tpu_compressed_dp.train.state import TrainState
        from tpu_compressed_dp.utils import checkpoint as ck

        params = {"w": jnp.arange(4096, dtype=jnp.float32)}
        state = TrainState.create(params, {}, {"momentum": params}, (),
                                  jax.random.key(1))
        orig = ck._to_saveable

        def legacy_saveable(s):
            d = orig(s)
            d.pop("comp")  # what an old writer produced
            return d

        monkeypatch.setattr(ck, "_to_saveable", legacy_saveable)
        ck.save_checkpoint(str(tmp_path / "ckpt"), state)
        monkeypatch.setattr(ck, "_to_saveable", orig)
        restored, _ = ck.restore_checkpoint(str(tmp_path / "ckpt"), state)
        assert restored.comp == ()
        np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                      np.asarray(params["w"]))
        # resuming that same old checkpoint with powersgd newly ON: the
        # freshly-built warm start must survive the fallback restore
        cfg = CompressionConfig(method="powersgd", rank=2)
        comp = init_comp_state(params, cfg)
        target = TrainState.create(params, {}, {"momentum": params}, (),
                                   jax.random.key(0), comp=comp)
        restored2, _ = ck.restore_checkpoint(str(tmp_path / "ckpt"), target)
        assert set(restored2.comp) == set(comp)
        for k in comp:
            np.testing.assert_array_equal(np.asarray(restored2.comp[k]),
                                          np.asarray(comp[k]))

    def test_powersgd_rejected_with_pipeline_parallelism(self):
        from tpu_compressed_dp.models.transformer import LlamaConfig
        from tpu_compressed_dp.train.optim import SGD
        from tpu_compressed_dp.train.pp_step import make_pp_train_step

        cfg = LlamaConfig(dim=32, n_layers=2, n_heads=2, n_kv_heads=2,
                          vocab_size=64)
        with pytest.raises(NotImplementedError, match="pipeline"):
            make_pp_train_step(
                cfg, SGD(lr=0.1),
                CompressionConfig(method="powersgd", rank=2),
                mesh=None, microbatches=2)
