"""Dataset-tools command assembly (the `IMAGENET/tools/` parity surface:
EBS replication -> per-worker GCS staging, snapshot -> bucket upload,
remote tensorboard -> SSH port-forward).  Print-mode only: CI has no gcloud."""

import os
import subprocess
import sys
import pytest

pytestmark = pytest.mark.quick  # fast tier (VERDICT r2 #10)


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(REPO, "tools", "dataset_tools.py")


def run(*argv):
    out = subprocess.run([sys.executable, TOOL, *argv],
                         capture_output=True, text=True, cwd=REPO)
    return out


def test_stage_fans_rsync_to_all_workers():
    out = run("stage", "gs://b/imagenet", "/mnt/disks/ssd/imagenet",
              "--tpu", "pod", "--zone", "us-east5-a")
    assert out.returncode == 0
    assert "--worker=all" in out.stdout
    assert "gcloud storage rsync -r gs://b/imagenet /mnt/disks/ssd/imagenet" in out.stdout
    assert "mkdir -p" in out.stdout


def test_snapshot_is_one_upload():
    out = run("snapshot", "/data/imagenet", "gs://b/imagenet")
    assert out.returncode == 0
    assert out.stdout.strip() == "gcloud storage rsync -r /data/imagenet gs://b/imagenet"


def test_tensorboard_port_forwards_worker0():
    out = run("tensorboard", "logs/tb", "--tpu", "pod", "--zone", "us-east5-a")
    assert out.returncode == 0
    assert "--worker=0" in out.stdout
    assert "-L 6006:localhost:6006" in out.stdout


def test_tensorboard_local_without_tpu():
    out = run("tensorboard", "logs/tb")
    assert out.returncode == 0
    assert out.stdout.strip().startswith("tensorboard --logdir=logs/tb")


def test_stage_requires_tpu():
    out = run("stage", "gs://b/x", "/y")
    assert out.returncode != 0
