"""Tier-1 static-analysis gate: the real tree must be tcdp-lint clean.

tools/tcdp_lint.py is the developer entry point (full matrix, --json,
--diff); this file is what makes the analyzer a GATE rather than advice —
a new undeclared stat key, a wall-clock read in a replay module, or an
asymmetric collective in a step factory turns into a named test failure
here.  The ruff gate runs the [tool.ruff] config from pyproject.toml when
ruff is installed and skips otherwise (the CI image does not bake it in).
"""

import json
import os
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fmt(findings):
    return "\n".join(f"{f.location()}: {f.code}: {f.message}"
                     for f in findings)


@pytest.mark.quick
def test_host_pass_clean():
    """Pass 2 (AST rules TCDP101-105 + pragma hygiene TCDP100) at zero
    active findings over the package and tools/."""
    from tpu_compressed_dp.analysis.hostlint import run_host_pass

    active, _suppressed = run_host_pass(REPO)
    assert active == [], f"tcdp-lint pass 2 findings:\n{_fmt(active)}"


def test_spmd_pass_quick_clean():
    """Pass 1 (jaxpr checks TCDP001-004) at zero findings over the quick
    engine/step-factory matrix — every method traced on the wire path plus
    the transport/granularity/overlap variants (~14s on CPU; the full
    9x2x2x3 matrix is `tools/tcdp_lint.py --spmd --profile full`)."""
    from tpu_compressed_dp.analysis.spmd import run_spmd_pass

    findings, stats = run_spmd_pass("quick")
    assert findings == [], f"tcdp-lint pass 1 findings:\n{_fmt(findings)}"
    assert stats["configs_traced"] >= 30


@pytest.mark.quick
def test_cli_host_json(capsys):
    """CLI smoke: --host --json emits a versioned payload and exits 0."""
    from tools import tcdp_lint

    rc = tcdp_lint.main(["--host", "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert payload["version"] == 1
    assert payload["counts"]["active"] == 0


@pytest.mark.quick
def test_cli_diff_mode(capsys):
    """--diff HEAD restricts pass 2 to changed files (the pre-commit path)
    and still exits 0 on a clean tree."""
    from tools import tcdp_lint

    rc = tcdp_lint.main(["--host", "--diff", "HEAD", "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert payload["counts"]["active"] == 0


@pytest.mark.quick
def test_readme_rule_table_in_sync():
    """Every rule code in CODES has a row in the README 'Static analysis'
    table, and the README names no codes the analyzer doesn't have."""
    from tpu_compressed_dp.analysis.report import CODES

    with open(os.path.join(REPO, "README.md"), "r", encoding="utf-8") as f:
        readme = f.read()
    section = readme.split("## Static analysis", 1)[1].split("\n## ", 1)[0]
    import re
    in_readme = set(re.findall(r"\bTCDP\d{3}\b", section))
    assert in_readme == set(CODES), (
        f"README table drift: missing {set(CODES) - in_readme}, "
        f"stale {in_readme - set(CODES)}")


def test_ruff_gate():
    """The [tool.ruff] correctness subset must pass whenever the `dev`
    extra is installed (`pip install -e .[dev]`).  Only a genuinely
    ruff-less image skips; an installed-but-unrunnable ruff (module present
    without a PATH entry point, a broken wheel) is a LOUD failure — the
    gate sat dormant for exactly that silent-skip reason."""
    import importlib.util
    import sys

    ruff = shutil.which("ruff")
    installed = importlib.util.find_spec("ruff") is not None
    if ruff is None and not installed:
        pytest.skip("ruff not installed (pip install -e '.[dev]' arms this "
                    "gate)")
    cmd = [ruff] if ruff else [sys.executable, "-m", "ruff"]
    try:
        proc = subprocess.run(cmd + ["check", "."], cwd=REPO,
                              capture_output=True, text=True, timeout=300)
    except OSError as err:
        pytest.fail(f"ruff is installed but not runnable: {err}")
    assert proc.returncode == 0, proc.stdout + proc.stderr
