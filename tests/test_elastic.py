"""Elastic training unit tests (train/elastic.py + the chaos surface).

The end-to-end invariants (mid-collective kill -> W-1 remesh -> bitwise EF
migration -> run completes) live in tools/chaos_drill.py and
tests/test_chaos_drill.py; this module covers the pieces host-side:
failure detection (gossip incarnations, bounded fetch), the chaos spec
round-trips, the state-migration arithmetic, and the runtime's conversion
and refusal rules.
"""

import threading
import time

import jax
import numpy as np
import pytest

from tpu_compressed_dp.train import elastic
from tpu_compressed_dp.utils.chaos import (ChaosConfig, ChaosCrash,
                                           CrashInjector)

pytestmark = pytest.mark.quick


# ----------------------------------------------------------- chaos surface

class TestChaosSpec:
    def test_parse_mid_collective(self):
        c = ChaosConfig.parse("crash=mid_collective,crash_at_step=12,worker=3")
        assert c.crash_mode == "mid_collective"
        assert c.crash_at_step == 12 and c.worker == 3
        assert not c.injects_in_graph  # crash-only chaos stays host-side

    def test_parse_peer_timeout(self):
        c = ChaosConfig.parse("crash=mid_collective,crash_at_step=1,"
                              "peer_timeout=0.5")
        assert c.peer_timeout == 0.5

    def test_parse_during_remesh(self):
        c = ChaosConfig.parse("crash=during_remesh,crash_at_step=12,worker=3")
        assert c.crash_mode == "during_remesh"
        assert c.crash_at_step == 12 and c.worker == 3
        assert not c.injects_in_graph

    def test_to_spec_round_trips(self):
        for spec in (
            "crash=mid_collective,crash_at_step=12,worker=3,peer_timeout=0.5",
            "crash=7",
            "nan,target=grads,steps=3,worker=1",
            "inf,target=loss,every=2,crash_at_step=9",
        ):
            c = ChaosConfig.parse(spec)
            assert ChaosConfig.parse(c.to_spec()) == c, spec

    def test_every_documented_spec_rearms_identically(self):
        """The utils/chaos.py docstring's CLI examples (plus the
        during_remesh mode), round-tripped through ``to_spec`` — the
        string a relaunched process re-arms from.  The config AND the
        armed CrashInjector must come back identical, or a watchdog
        relaunch would replay a different fault scenario than the one
        that killed the previous life."""
        from tpu_compressed_dp.utils.chaos import maybe_crash_injector

        documented = (
            "nan,target=grads,steps=3+7,worker=1",
            "inf,target=loss,every=50",
            "crash=120",
            "crash=mid_collective,crash_at_step=12,worker=3",
            "crash=during_remesh,crash_at_step=12,worker=3",
            "peer_timeout=0.5",
            "nan",
            "inf",
        )
        for spec in documented:
            c = ChaosConfig.parse(spec)
            c2 = ChaosConfig.parse(c.to_spec())
            assert c2 == c, spec
            assert c2.to_spec() == c.to_spec(), spec
            inj, inj2 = maybe_crash_injector(c), maybe_crash_injector(c2)
            assert (inj is None) == (inj2 is None), spec
            if inj is not None:
                assert (inj.crash_at_step, inj.mode, inj.worker) == \
                    (inj2.crash_at_step, inj2.mode, inj2.worker), spec
                assert not inj2.fired  # re-armed, not already spent

    def test_bad_crash_mode_rejected(self):
        with pytest.raises(ValueError, match="crash_mode"):
            ChaosConfig(crash_at_step=1, crash_mode="sideways")
        with pytest.raises(ValueError):
            ChaosConfig.parse("crash=mid_collective,peer_timeout=-1")

    def test_injector_fires_only_in_its_phase(self):
        inj = CrashInjector(3, mode="mid_collective", worker=5)
        for i in range(5):
            inj.check(i)  # the pre-dispatch phase never fires this mode
        inj2 = CrashInjector(3, mode="mid_collective", worker=5)
        inj2.check(3)
        with pytest.raises(ChaosCrash) as ei:
            inj2.check(3, phase="mid_collective")
        assert ei.value.step == 3 and ei.value.worker == 5
        assert ei.value.mode == "mid_collective"
        # step-mode injectors keep the legacy behavior (fire pre-dispatch)
        inj3 = CrashInjector(2)
        inj3.check(1)
        with pytest.raises(ChaosCrash):
            inj3.check(2)


# ----------------------------------------------------------------- config

class TestElasticConfig:
    def test_validation(self):
        with pytest.raises(ValueError, match="ef_policy"):
            elastic.ElasticConfig(ef_policy="average")
        with pytest.raises(ValueError, match="peer_timeout_s"):
            elastic.ElasticConfig(peer_timeout_s=0.0)
        with pytest.raises(ValueError, match="min_world"):
            elastic.ElasticConfig(min_world=0)
        c = elastic.ElasticConfig(ef_policy="drop", peer_timeout_s=1.0)
        assert c.ef_policy == "drop"


# ----------------------------------------------------------------- gossip

class TestPeerGossip:
    def _gossip(self, td, clock, world=3, timeout=5.0, rank=0):
        return elastic.PeerGossip(str(td), rank, world,
                                  peer_timeout_s=timeout,
                                  now=lambda: clock["t"])

    def test_silent_peer_declared_dead_after_timeout(self, tmp_path):
        clock = {"t": 100.0}
        g = self._gossip(tmp_path, clock)
        elastic.write_peer_heartbeat(str(tmp_path), 1, 0, ts=clock["t"])
        # peer 2 never writes at all; cold-start grace covers both at first
        assert g.check() == {}
        clock["t"] += 6.0
        elastic.write_peer_heartbeat(str(tmp_path), 1, 1, ts=clock["t"])
        newly = g.check()
        assert list(newly) == [2]
        assert g.dead == (2,)
        # already-dead peers are not re-reported as newly dead
        clock["t"] += 6.0
        elastic.write_peer_heartbeat(str(tmp_path), 1, 2, ts=clock["t"])
        assert g.check() == {}

    def test_beat_writes_own_file_rate_limited(self, tmp_path, monkeypatch):
        from tpu_compressed_dp.utils.resilience import read_heartbeat

        monkeypatch.setenv("TCDP_RESTART_COUNT", "2")
        clock = {"t": 100.0}
        g = self._gossip(tmp_path, clock, world=2)       # timeout 5s
        g.beat(step=3)
        own = elastic.heartbeat_path(str(tmp_path), 0)
        rec = read_heartbeat(own)
        assert rec["step"] == 3 and rec["incarnation"] == 2
        clock["t"] += 1.0                                # < timeout/4
        g.beat(step=4)
        assert read_heartbeat(own)["step"] == 3          # rate-limited
        clock["t"] += 0.5                                # crosses 1.25s
        g.beat(step=5)
        assert read_heartbeat(own)["step"] == 5
        # the written file closes the loop: a peer's gossip sees us alive
        g2 = self._gossip(tmp_path, clock, world=2, rank=1)
        clock["t"] += 4.0
        g.beat(step=6)
        clock["t"] += 2.0                                # beat is 2s old: fresh
        assert g2.check() == {}

    def test_raise_if_dead_carries_step_and_ranks(self, tmp_path):
        clock = {"t": 100.0}
        g = self._gossip(tmp_path, clock)
        clock["t"] += 6.0
        with pytest.raises(elastic.PeerFailed) as ei:
            g.raise_if_dead(step=17)
        assert ei.value.failed == (1, 2) and ei.value.step == 17

    def test_stale_lower_incarnation_never_refreshes(self, tmp_path):
        """A dead prior life's file (lower incarnation) reappearing with a
        fresh ts must NOT read as liveness of the tracked peer."""
        clock = {"t": 100.0}
        g = self._gossip(tmp_path, clock, world=2)
        elastic.write_peer_heartbeat(str(tmp_path), 1, 0, incarnation=2,
                                     ts=clock["t"])
        assert g.check() == {}          # admits incarnation 2
        clock["t"] += 4.0
        # an NFS-delayed write of incarnation 1 lands with a FRESH ts
        elastic.write_peer_heartbeat(str(tmp_path), 1, 9, incarnation=1,
                                     ts=clock["t"])
        clock["t"] += 3.0               # 7s since the last REAL beat
        newly = g.check()
        assert list(newly) == [1], "stale incarnation refreshed liveness"

    def test_incarnation_advance_means_peer_restarted(self, tmp_path):
        clock = {"t": 100.0}
        g = self._gossip(tmp_path, clock, world=2)
        elastic.write_peer_heartbeat(str(tmp_path), 1, 5, incarnation=0,
                                     ts=clock["t"])
        assert g.check() == {}
        clock["t"] += 1.0               # well within the timeout…
        elastic.write_peer_heartbeat(str(tmp_path), 1, 0, incarnation=1,
                                     ts=clock["t"])
        newly = g.check()               # …but the tracked life is gone
        assert list(newly) == [1] and "incarnation" in newly[1]
        assert g.rejoin_candidates() == {1: 1}
        g.readmit(1)
        assert g.dead == () and g.check() == {}

    def test_ntp_step_cannot_mass_declare_peers_dead(self, tmp_path):
        """Staleness runs on the LOCAL monotonic clock and record-change
        detection; the writers' wall-clock ``ts`` is never compared to
        local time.  A cluster-wide NTP step (every peer's ts jumps
        backward) therefore keeps everyone alive as long as they keep
        writing — and real silence still detects on schedule."""
        clock = {"t": 100.0}
        g = self._gossip(tmp_path, clock, world=3)          # timeout 5s
        # wildly skewed writer clocks on admission: irrelevant
        elastic.write_peer_heartbeat(str(tmp_path), 1, 0, ts=999999.0)
        elastic.write_peer_heartbeat(str(tmp_path), 2, 0, ts=-500.0)
        assert g.check() == {}
        # the NTP step: both writers' ts jump far BACKWARD, records keep
        # changing -> alive
        clock["t"] += 4.0
        elastic.write_peer_heartbeat(str(tmp_path), 1, 1, ts=42.0)
        elastic.write_peer_heartbeat(str(tmp_path), 2, 1, ts=-501.0)
        assert g.check() == {}, "NTP step mass-declared live peers dead"
        clock["t"] += 4.0
        elastic.write_peer_heartbeat(str(tmp_path), 1, 2, ts=41.0)
        elastic.write_peer_heartbeat(str(tmp_path), 2, 2, ts=-502.0)
        assert g.check() == {}
        # genuine silence: both die within one local timeout window
        clock["t"] += 6.0
        assert set(g.check()) == {1, 2}

    def test_unchanged_record_goes_stale_on_local_clock(self, tmp_path):
        """A record that stops CHANGING is silence, even if its wall ts
        looks perpetually 'fresh' relative to a skewed local clock."""
        clock = {"t": 100.0}
        g = self._gossip(tmp_path, clock, world=2)
        # writer's wall clock is far in our future; file never changes
        elastic.write_peer_heartbeat(str(tmp_path), 1, 0, ts=1e9)
        assert g.check() == {}
        clock["t"] += 4.0
        assert g.check() == {}          # within the timeout window
        clock["t"] += 2.0               # 6s of local silence
        assert list(g.check()) == [1]

    def test_dead_peer_rejoins_on_fresh_higher_incarnation(self, tmp_path):
        clock = {"t": 100.0}
        g = self._gossip(tmp_path, clock, world=2)
        clock["t"] += 6.0               # silence -> dead
        assert list(g.check()) == [1]
        assert g.rejoin_candidates() == {}
        elastic.write_peer_heartbeat(str(tmp_path), 1, 0, incarnation=1,
                                     ts=clock["t"])
        assert g.rejoin_candidates() == {1: 1}
        # …but a fresh file of the SAME (dead) incarnation is not a rejoin:
        # the paused process's in-memory state is stale relative to the
        # remeshed run; it must restart (bump incarnation) to come back
        elastic.write_peer_heartbeat(str(tmp_path), 0, 0, incarnation=0,
                                     ts=clock["t"])
        g2 = self._gossip(tmp_path, clock, world=2, rank=1)
        assert g2.check() == {}         # admits rank 0 at incarnation 0
        g2.note_dead([0])
        clock["t"] += 1.0
        elastic.write_peer_heartbeat(str(tmp_path), 0, 1, incarnation=0,
                                     ts=clock["t"])
        assert g2.rejoin_candidates() == {}


# ------------------------------------------------------------ bounded fetch

class TestFetchWithTimeout:
    def test_value_passes_through(self):
        assert elastic.fetch_with_timeout(lambda: 42, 5.0) == 42

    def test_timeout_raises_peer_failed(self):
        ev = threading.Event()
        with pytest.raises(elastic.PeerFailed) as ei:
            elastic.fetch_with_timeout(lambda: ev.wait(30.0), 0.05, step=7,
                                       what="drill fetch")
        ev.set()
        assert ei.value.failed == () and ei.value.step == 7
        assert "drill fetch" in str(ei.value)

    def test_thunk_exception_re_raised(self):
        def boom():
            raise KeyError("inner")

        with pytest.raises(KeyError, match="inner"):
            elastic.fetch_with_timeout(boom, 5.0)

    def test_timeout_hammer_leaks_no_threads(self):
        """Repeated timeouts must not accumulate runner threads: each
        abandoned runner is tracked while its fetch is still blocked and
        reaped the moment it drains."""
        baseline = elastic.abandoned_fetch_count()
        release = threading.Event()
        n = 8
        for i in range(n):
            with pytest.raises(elastic.PeerFailed):
                elastic.fetch_with_timeout(lambda: release.wait(30.0), 0.02,
                                           what=f"hammer {i}")
        assert elastic.abandoned_fetch_count() <= baseline + n
        assert elastic.abandoned_fetch_count() >= 1  # tracked, not lost
        release.set()                   # the blocked fetches all drain now
        deadline = time.time() + 10.0
        while (elastic.abandoned_fetch_count() > baseline
               and time.time() < deadline):
            time.sleep(0.01)
        assert elastic.abandoned_fetch_count() <= baseline, \
            "abandoned fetch threads leaked after their fetches drained"

    def test_timed_out_fetch_discards_late_buffer(self):
        """A fetch that completes AFTER its deadline must drop the fetched
        buffer (the discard flag), not pin a dead world's arrays in a
        result box nobody reads."""
        import gc
        import weakref

        release = threading.Event()
        refs = []

        def slow_fetch():
            buf = np.ones((256,), np.float32)
            refs.append(weakref.ref(buf))
            release.wait(30.0)
            return buf

        with pytest.raises(elastic.PeerFailed):
            elastic.fetch_with_timeout(slow_fetch, 0.02, what="late buffer")
        release.set()
        deadline = time.time() + 10.0
        while refs[0]() is not None and time.time() < deadline:
            gc.collect()
            time.sleep(0.01)
        assert refs[0]() is None, "late fetch result pinned after discard"


# ---------------------------------------------------------- state migration

def _tree(w=4, seed=0):
    rng = np.random.RandomState(seed)
    return {"a": rng.randn(w, 8).astype(np.float32),
            "b": rng.randn(w, 3, 2).astype(np.float32)}


class TestMigration:
    def test_fold_conserves_mass_bitwise(self):
        ef = _tree()
        out, dropped = elastic.migrate_ef(ef, [2], policy="fold")
        assert dropped == 0.0
        for k in ef:
            expect = np.delete(ef[k], [2], axis=0)
            expect[0] = expect[0] + ef[k][2]
            assert np.array_equal(out[k], expect)
            # exact fp32 conservation: the summed mass is unchanged up to
            # the one add per leaf the fold performs
            assert out[k].shape[0] == 3

    def test_fold_into_other_survivor(self):
        ef = _tree()
        out, _ = elastic.migrate_ef(ef, [0], policy="fold", fold_into=1)
        expect = np.delete(ef["a"], [0], axis=0)
        expect[1] = expect[1] + ef["a"][0]
        assert np.array_equal(out["a"], expect)

    def test_drop_accounts_l2_norm(self):
        ef = _tree()
        out, dropped = elastic.migrate_ef(ef, [1, 3], policy="drop")
        sq = sum(float(np.sum(ef[k][[1, 3]].astype(np.float64) ** 2))
                 for k in ef)
        assert dropped == pytest.approx(np.sqrt(sq), rel=0, abs=0)
        for k in ef:
            assert np.array_equal(out[k], np.delete(ef[k], [1, 3], axis=0))

    def test_multi_failure_fold_sums_all_lost_rows(self):
        ef = _tree()
        out, _ = elastic.migrate_ef(ef, [1, 2], policy="fold")
        expect = np.delete(ef["a"], [1, 2], axis=0)
        expect[0] = expect[0] + (ef["a"][1] + ef["a"][2])
        assert np.array_equal(out["a"], expect)

    def test_empty_ef_passes_through(self):
        assert elastic.migrate_ef((), [1]) == ((), 0.0)
        assert elastic.migrate_comp((), [1]) == ()

    def test_bad_worker_index_raises(self):
        with pytest.raises(ValueError):
            elastic.migrate_ef(_tree(w=2), [5])
        with pytest.raises(ValueError):
            elastic.migrate_ef(_tree(), [1], policy="average")

    def test_comp_rows_deleted(self):
        comp = _tree(seed=1)
        out = elastic.migrate_comp(comp, [0])
        for k in comp:
            assert np.array_equal(out[k], comp[k][1:])

    def test_expand_ef_appends_zero_rows(self):
        ef = _tree(w=3)
        out = elastic.expand_ef(ef, 2)
        for k in ef:
            assert out[k].shape[0] == 5
            assert np.array_equal(out[k][:3], ef[k])
            assert not np.any(out[k][3:])

    def test_expand_comp_broadcasts_row0(self):
        comp = _tree(w=3, seed=2)
        out = elastic.expand_comp(comp, 2)
        for k in comp:
            assert out[k].shape[0] == 5
            assert np.array_equal(out[k][3], comp[k][0])
            assert np.array_equal(out[k][4], comp[k][0])


import dataclasses as _dc


@_dc.dataclass
class _FakeState:
    """Bare dataclass standing in for TrainState in migration/runtime
    tests — the shrink/expand helpers only touch ``ef``/``comp`` and go
    through ``dataclasses.replace``."""

    ef: object = ()
    comp: object = ()


class TestRowGroupMigration:
    """dp x sp (LM) row-group arithmetic: the EF leading dim is the SYNC
    world (dp*sp), data-major — data row d owns leading rows
    [d*m, (d+1)*m).  Losing a data row must take its whole row GROUP."""

    def test_rows_per_data_row(self):
        ef = {"a": np.zeros((8, 4), np.float32)}
        assert elastic._rows_per_data_row(ef, 4) == 2      # dp=4, sp=2
        assert elastic._rows_per_data_row(ef, 8) == 1      # pure dp
        assert elastic._rows_per_data_row((), 4) == 1
        with pytest.raises(ValueError):
            elastic._rows_per_data_row(ef, 3)              # 8 % 3 != 0

    def test_shrink_folds_the_whole_row_group(self):
        rng = np.random.RandomState(0)
        ef = {"a": rng.randn(8, 4).astype(np.float32)}
        state = _FakeState(ef=ef, comp={"q": rng.randn(8, 3).astype(np.float32)})
        # dp=4: data row 1 owns leading rows 2 and 3
        out, dropped = elastic.shrink_state(state, [1], policy="fold",
                                            data_world=4)
        assert dropped == 0.0
        expect = np.delete(ef["a"], [2, 3], axis=0)
        expect[0] = expect[0] + ef["a"][[2, 3]].sum(axis=0)
        assert np.array_equal(out.ef["a"], expect)
        assert out.comp["q"].shape[0] == 6
        # total EF mass conserved through the fold (the fold/drop invariant:
        # what was withheld stays accounted — folded back or norm-counted)
        assert np.allclose(out.ef["a"].sum(axis=0), ef["a"].sum(axis=0),
                           atol=1e-5)

    def test_shrink_drop_accounts_the_row_group_norm(self):
        rng = np.random.RandomState(1)
        ef = {"a": rng.randn(8, 4).astype(np.float32)}
        state = _FakeState(ef=ef)
        out, dropped = elastic.shrink_state(state, [3], policy="drop",
                                            data_world=4)
        lost = ef["a"][[6, 7]]
        assert dropped == pytest.approx(
            float(np.sqrt(np.sum(lost.astype(np.float64) ** 2))), abs=0)
        assert np.array_equal(out.ef["a"], ef["a"][:6])

    def test_expand_appends_row_groups(self):
        rng = np.random.RandomState(2)
        state = _FakeState(ef={"a": rng.randn(6, 4).astype(np.float32)},
                           comp={"q": rng.randn(6, 3).astype(np.float32)})
        # current dp=3 (m=2); one rejoining data row appends 2 leading rows
        out = elastic.expand_state(state, n_new=1, data_world=3)
        assert out.ef["a"].shape[0] == 8
        assert not np.any(out.ef["a"][6:])                 # zero EF rows
        assert np.array_equal(out.comp["q"][6], out.comp["q"][0])
        assert np.array_equal(out.comp["q"][7], out.comp["q"][0])


class TestTrimBatches:
    def test_trims_rows_and_keeps_len(self):
        inner = [{"x": np.arange(8), "y": np.arange(8) * 2} for _ in range(3)]
        view = elastic.TrimBatches(inner, 6)
        assert len(view) == 3
        for b in view:
            assert b["x"].shape[0] == 6 and b["y"].shape[0] == 6
        # short batches pass through untouched
        short = elastic.TrimBatches([{"x": np.arange(4)}], 6)
        assert next(iter(short))["x"].shape[0] == 4


# ------------------------------------------------------------- mesh surgery

class TestMeshSurgery:
    def test_surviving_mesh_drops_workers_in_order(self, mesh8):
        new_mesh, removed = elastic.surviving_mesh(mesh8, [2, 5])
        devices = list(mesh8.devices.reshape(-1))
        assert list(new_mesh.devices.reshape(-1)) == [
            d for i, d in enumerate(devices) if i not in (2, 5)]
        assert removed == [devices[2], devices[5]]
        assert tuple(new_mesh.axis_names) == ("data",)
        assert new_mesh.shape["data"] == 6

    def test_extended_mesh_appends_at_tail(self, mesh8):
        new_mesh, removed = elastic.surviving_mesh(mesh8, [0])
        back = elastic.extended_mesh(new_mesh, removed)
        devices = list(mesh8.devices.reshape(-1))
        assert list(back.devices.reshape(-1)) == devices[1:] + [devices[0]]

    def test_model_parallel_mesh_loses_full_data_row(self):
        """Losing data row i of a dp x tp mesh removes ALL of that row's
        model-axis devices (the model shards are replicated across data
        rows, so the survivors keep a complete copy)."""
        from jax.sharding import Mesh

        devs = np.array(jax.devices()[:8]).reshape(4, 2)
        mesh = Mesh(devs, ("data", "tensor"))
        new_mesh, removed = elastic.surviving_mesh(mesh, [1])
        assert tuple(new_mesh.axis_names) == ("data", "tensor")
        assert new_mesh.shape["data"] == 3
        assert new_mesh.shape["tensor"] == 2
        assert removed == [list(devs[1])]
        assert new_mesh.devices.tolist() == np.delete(devs, 1, 0).tolist()
        # the parked row readmits at the mesh tail, model axis intact
        back = elastic.extended_mesh(new_mesh, removed)
        assert back.shape["data"] == 4 and back.shape["tensor"] == 2
        assert list(back.devices[-1]) == list(devs[1])

    def test_non_leading_data_axis_round_trips(self):
        """Axis order is preserved when the data axis is not axis 0 (the
        LM harness's dp x sp layouts put it wherever the step factory
        wants it)."""
        from jax.sharding import Mesh

        devs = np.array(jax.devices()[:8]).reshape(2, 4)
        mesh = Mesh(devs, ("tensor", "data"))
        new_mesh, removed = elastic.surviving_mesh(mesh, [0])
        assert tuple(new_mesh.axis_names) == ("tensor", "data")
        assert new_mesh.shape["tensor"] == 2 and new_mesh.shape["data"] == 3
        assert removed == [list(devs[:, 0])]
        assert new_mesh.devices.tolist() == devs[:, 1:].tolist()
        back = elastic.extended_mesh(new_mesh, removed)
        assert back.shape["data"] == 4
        assert back.devices[:, -1].tolist() == devs[:, 0].tolist()

    def test_unit_model_axes_accepted(self):
        from jax.sharding import Mesh

        devs = np.array(jax.devices()[:4]).reshape(4, 1, 1)
        mesh = Mesh(devs, ("data", "seq", "tensor"))
        new_mesh, _ = elastic.surviving_mesh(mesh, [3])
        assert tuple(new_mesh.axis_names) == ("data", "seq", "tensor")
        assert new_mesh.shape["data"] == 3

    def test_out_of_range_failure_rejected(self, mesh8):
        with pytest.raises(ValueError, match="outside world"):
            elastic.surviving_mesh(mesh8, [8])


# ----------------------------------------------------------------- runtime

class TestElasticRuntime:
    def _runtime(self, mesh, **cfg_kw):
        return elastic.ElasticRuntime(
            elastic.ElasticConfig(**cfg_kw), mesh, log=lambda s: None)

    def test_failure_from_conversions(self, mesh8):
        el = self._runtime(mesh8)
        # PeerFailed passes through untouched
        pf = elastic.PeerFailed((3,), step=5)
        assert el.failure_from(pf) is pf
        # mid-collective chaos converts to the dying worker
        crash = ChaosCrash("boom")
        crash.step, crash.mode, crash.worker = 4, "mid_collective", 6
        out = el.failure_from(crash)
        assert out.failed == (6,) and out.step == 4
        # step-mode crashes (watchdog territory) and unrelated faults do not
        crash2 = ChaosCrash("boom")
        crash2.step, crash2.mode, crash2.worker = 4, "step", 6
        assert el.failure_from(crash2) is None
        assert el.failure_from(RuntimeError("x")) is None

    def test_empty_culprit_filled_from_gossip(self, mesh8, tmp_path):
        clock = {"t": 100.0}
        gossip = elastic.PeerGossip(str(tmp_path), 0, 8, peer_timeout_s=5.0,
                                    now=lambda: clock["t"])
        el = elastic.ElasticRuntime(elastic.ElasticConfig(), mesh8,
                                    gossip=gossip, log=lambda s: None)
        clock["t"] += 6.0               # every peer silent past the timeout
        out = el.failure_from(elastic.PeerFailed((), step=3,
                                                 reason="fetch timeout"))
        assert out.failed == tuple(range(1, 8)) and out.step == 3

    def test_min_world_refusal(self, mesh8):
        el = self._runtime(mesh8, min_world=8)

        class FakeState:
            ef = ()
            comp = ()

        with pytest.raises(elastic.PeerFailed, match="min_world"):
            el.handle_failure(FakeState(), elastic.PeerFailed((1,), step=0))
        assert el.remesh_count == 0

    def test_culpritless_failure_re_raised(self, mesh8):
        el = self._runtime(mesh8)
        with pytest.raises(elastic.PeerFailed):
            el.handle_failure(object(), elastic.PeerFailed((), step=0))

    def test_metrics_are_declared(self, mesh8):
        from tpu_compressed_dp.obs import registry

        el = self._runtime(mesh8)
        for key in el.metrics():
            assert registry.is_declared(key), key

    def test_remesh_ms_accumulates_downtime(self, mesh8):
        rng = np.random.RandomState(0)
        el = elastic.ElasticRuntime(elastic.ElasticConfig(), mesh8,
                                    place=lambda s, m: s, log=lambda s: None)
        state = _FakeState(ef={"a": rng.randn(8, 4).astype(np.float32)})
        assert el.metrics()["elastic/remesh_ms"] == 0.0
        state = el.handle_failure(state, elastic.PeerFailed((2,), step=1))
        after_shrink = el.remesh_ms
        assert after_shrink >= el.remesh_latency_ms > 0.0
        el.readmit(state)
        assert el.remesh_ms > after_shrink     # readmission downtime counts
        assert el.metrics()["elastic/remesh_ms"] == el.remesh_ms

    def test_handle_failure_on_dp_tp_mesh(self):
        """The tentpole's model-axis remesh: a dp x tp virtual mesh loses
        a data row and RE-SHARDS instead of refusing; the EF fold/drop
        invariant (withheld mass folded back or norm-accounted) holds with
        one EF row per data row (m = lead // dp = 1)."""
        from jax.sharding import Mesh

        devs = np.array(jax.devices()[:8]).reshape(4, 2)
        mesh = Mesh(devs, ("data", "tensor"))
        rng = np.random.RandomState(3)
        for policy in ("fold", "drop"):
            el = elastic.ElasticRuntime(
                elastic.ElasticConfig(ef_policy=policy), mesh,
                place=lambda s, m: s, log=lambda s: None)
            ef = {"a": rng.randn(4, 6).astype(np.float32)}
            state = _FakeState(ef=ef)
            out = el.handle_failure(state, elastic.PeerFailed((1,), step=2))
            assert el.world == 3 and el.mesh.shape["tensor"] == 2
            assert el.parked == (1,)
            if policy == "fold":
                expect = np.delete(ef["a"], 1, axis=0)
                expect[0] = expect[0] + ef["a"][1]
                assert np.array_equal(out.ef["a"], expect)
                assert el.dropped_ef_norm == 0.0
            else:
                assert np.array_equal(out.ef["a"],
                                      np.delete(ef["a"], 1, axis=0))
                assert el.dropped_ef_norm == pytest.approx(float(
                    np.sqrt(np.sum(ef["a"][1].astype(np.float64) ** 2))),
                    abs=0)
            # readmit restores the full dp x tp grid at the tail
            back = el.readmit(out)
            assert el.world == 4 and el.mesh.shape["tensor"] == 2
            assert back.ef["a"].shape[0] == 4
            assert not np.any(back.ef["a"][-1])

    def test_handle_failure_on_dp_sp_mesh_row_groups(self):
        """dp x sp (the LM layout): the EF lead is dp*sp and losing data
        row d takes its whole row group [d*m, (d+1)*m)."""
        from jax.sharding import Mesh

        devs = np.array(jax.devices()[:8]).reshape(4, 2)
        mesh = Mesh(devs, ("data", "seq"))
        rng = np.random.RandomState(4)
        el = elastic.ElasticRuntime(
            elastic.ElasticConfig(ef_policy="fold"), mesh,
            place=lambda s, m: s, ef_axes=("data", "seq"),
            log=lambda s: None)
        ef = {"a": rng.randn(8, 5).astype(np.float32)}     # dp*sp = 8 rows
        state = _FakeState(ef=ef)
        out = el.handle_failure(state, elastic.PeerFailed((2,), step=1))
        assert el.world == 3
        expect = np.delete(ef["a"], [4, 5], axis=0)        # row group of d=2
        expect[0] = expect[0] + ef["a"][[4, 5]].sum(axis=0)
        assert np.array_equal(out.ef["a"], expect)
        assert np.allclose(out.ef["a"].sum(axis=0), ef["a"].sum(axis=0),
                           atol=1e-5)                      # mass conserved
        back = el.readmit(out)
        assert el.world == 4 and back.ef["a"].shape[0] == 8
        assert not np.any(back.ef["a"][6:])

    def test_cascade_unions_dead_set(self, mesh8):
        """``crash=during_remesh``: the injector fires while the runtime
        is inside ``handle_failure`` — the dead set is unioned and the
        shrink restarts from the uncommitted mesh (one committed remesh,
        both ranks parked)."""
        rng = np.random.RandomState(5)
        crash = CrashInjector(0, mode="during_remesh", worker=5)
        el = elastic.ElasticRuntime(
            elastic.ElasticConfig(), mesh8, crash=crash,
            place=lambda s, m: s, log=lambda s: None)
        ef = {"a": rng.randn(8, 4).astype(np.float32)}
        out = el.handle_failure(_FakeState(ef=ef),
                                elastic.PeerFailed((3,), step=0))
        assert el.world == 6 and el.parked == (3, 5)
        assert el.cascade_count == 1 and el.remesh_count == 1
        assert el.peer_failures == 2
        expect = np.delete(ef["a"], [3, 5], axis=0)
        expect[0] = expect[0] + ef["a"][[3, 5]].sum(axis=0)
        assert np.array_equal(out.ef["a"], expect)

    def test_cascade_below_min_world_raises_cleanly(self, mesh8):
        """A cascade whose union would shrink below min_world raises a
        PeerFailed naming EVERY dead rank — nothing committed, no wedge."""
        crash = CrashInjector(0, mode="during_remesh", worker=5)
        el = elastic.ElasticRuntime(
            elastic.ElasticConfig(min_world=7), mesh8, crash=crash,
            place=lambda s, m: s, log=lambda s: None)
        with pytest.raises(elastic.PeerFailed, match="min_world") as ei:
            el.handle_failure(_FakeState(), elastic.PeerFailed((3,), step=0))
        assert ei.value.failed == (3, 5)
        assert el.world == 8 and el.remesh_count == 0
