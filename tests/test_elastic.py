"""Elastic training unit tests (train/elastic.py + the chaos surface).

The end-to-end invariants (mid-collective kill -> W-1 remesh -> bitwise EF
migration -> run completes) live in tools/chaos_drill.py and
tests/test_chaos_drill.py; this module covers the pieces host-side:
failure detection (gossip incarnations, bounded fetch), the chaos spec
round-trips, the state-migration arithmetic, and the runtime's conversion
and refusal rules.
"""

import threading
import time

import jax
import numpy as np
import pytest

from tpu_compressed_dp.train import elastic
from tpu_compressed_dp.utils.chaos import (ChaosConfig, ChaosCrash,
                                           CrashInjector)

pytestmark = pytest.mark.quick


# ----------------------------------------------------------- chaos surface

class TestChaosSpec:
    def test_parse_mid_collective(self):
        c = ChaosConfig.parse("crash=mid_collective,crash_at_step=12,worker=3")
        assert c.crash_mode == "mid_collective"
        assert c.crash_at_step == 12 and c.worker == 3
        assert not c.injects_in_graph  # crash-only chaos stays host-side

    def test_parse_peer_timeout(self):
        c = ChaosConfig.parse("crash=mid_collective,crash_at_step=1,"
                              "peer_timeout=0.5")
        assert c.peer_timeout == 0.5

    def test_to_spec_round_trips(self):
        for spec in (
            "crash=mid_collective,crash_at_step=12,worker=3,peer_timeout=0.5",
            "crash=7",
            "nan,target=grads,steps=3,worker=1",
            "inf,target=loss,every=2,crash_at_step=9",
        ):
            c = ChaosConfig.parse(spec)
            assert ChaosConfig.parse(c.to_spec()) == c, spec

    def test_bad_crash_mode_rejected(self):
        with pytest.raises(ValueError, match="crash_mode"):
            ChaosConfig(crash_at_step=1, crash_mode="sideways")
        with pytest.raises(ValueError):
            ChaosConfig.parse("crash=mid_collective,peer_timeout=-1")

    def test_injector_fires_only_in_its_phase(self):
        inj = CrashInjector(3, mode="mid_collective", worker=5)
        for i in range(5):
            inj.check(i)  # the pre-dispatch phase never fires this mode
        inj2 = CrashInjector(3, mode="mid_collective", worker=5)
        inj2.check(3)
        with pytest.raises(ChaosCrash) as ei:
            inj2.check(3, phase="mid_collective")
        assert ei.value.step == 3 and ei.value.worker == 5
        assert ei.value.mode == "mid_collective"
        # step-mode injectors keep the legacy behavior (fire pre-dispatch)
        inj3 = CrashInjector(2)
        inj3.check(1)
        with pytest.raises(ChaosCrash):
            inj3.check(2)


# ----------------------------------------------------------------- config

class TestElasticConfig:
    def test_validation(self):
        with pytest.raises(ValueError, match="ef_policy"):
            elastic.ElasticConfig(ef_policy="average")
        with pytest.raises(ValueError, match="peer_timeout_s"):
            elastic.ElasticConfig(peer_timeout_s=0.0)
        with pytest.raises(ValueError, match="min_world"):
            elastic.ElasticConfig(min_world=0)
        c = elastic.ElasticConfig(ef_policy="drop", peer_timeout_s=1.0)
        assert c.ef_policy == "drop"


# ----------------------------------------------------------------- gossip

class TestPeerGossip:
    def _gossip(self, td, clock, world=3, timeout=5.0, rank=0):
        return elastic.PeerGossip(str(td), rank, world,
                                  peer_timeout_s=timeout,
                                  now=lambda: clock["t"])

    def test_silent_peer_declared_dead_after_timeout(self, tmp_path):
        clock = {"t": 100.0}
        g = self._gossip(tmp_path, clock)
        elastic.write_peer_heartbeat(str(tmp_path), 1, 0, ts=clock["t"])
        # peer 2 never writes at all; cold-start grace covers both at first
        assert g.check() == {}
        clock["t"] += 6.0
        elastic.write_peer_heartbeat(str(tmp_path), 1, 1, ts=clock["t"])
        newly = g.check()
        assert list(newly) == [2]
        assert g.dead == (2,)
        # already-dead peers are not re-reported as newly dead
        clock["t"] += 6.0
        elastic.write_peer_heartbeat(str(tmp_path), 1, 2, ts=clock["t"])
        assert g.check() == {}

    def test_beat_writes_own_file_rate_limited(self, tmp_path, monkeypatch):
        from tpu_compressed_dp.utils.resilience import read_heartbeat

        monkeypatch.setenv("TCDP_RESTART_COUNT", "2")
        clock = {"t": 100.0}
        g = self._gossip(tmp_path, clock, world=2)       # timeout 5s
        g.beat(step=3)
        own = elastic.heartbeat_path(str(tmp_path), 0)
        rec = read_heartbeat(own)
        assert rec["step"] == 3 and rec["incarnation"] == 2
        clock["t"] += 1.0                                # < timeout/4
        g.beat(step=4)
        assert read_heartbeat(own)["step"] == 3          # rate-limited
        clock["t"] += 0.5                                # crosses 1.25s
        g.beat(step=5)
        assert read_heartbeat(own)["step"] == 5
        # the written file closes the loop: a peer's gossip sees us alive
        g2 = self._gossip(tmp_path, clock, world=2, rank=1)
        clock["t"] += 4.0
        g.beat(step=6)
        clock["t"] += 2.0                                # beat is 2s old: fresh
        assert g2.check() == {}

    def test_raise_if_dead_carries_step_and_ranks(self, tmp_path):
        clock = {"t": 100.0}
        g = self._gossip(tmp_path, clock)
        clock["t"] += 6.0
        with pytest.raises(elastic.PeerFailed) as ei:
            g.raise_if_dead(step=17)
        assert ei.value.failed == (1, 2) and ei.value.step == 17

    def test_stale_lower_incarnation_never_refreshes(self, tmp_path):
        """A dead prior life's file (lower incarnation) reappearing with a
        fresh ts must NOT read as liveness of the tracked peer."""
        clock = {"t": 100.0}
        g = self._gossip(tmp_path, clock, world=2)
        elastic.write_peer_heartbeat(str(tmp_path), 1, 0, incarnation=2,
                                     ts=clock["t"])
        assert g.check() == {}          # admits incarnation 2
        clock["t"] += 4.0
        # an NFS-delayed write of incarnation 1 lands with a FRESH ts
        elastic.write_peer_heartbeat(str(tmp_path), 1, 9, incarnation=1,
                                     ts=clock["t"])
        clock["t"] += 3.0               # 7s since the last REAL beat
        newly = g.check()
        assert list(newly) == [1], "stale incarnation refreshed liveness"

    def test_incarnation_advance_means_peer_restarted(self, tmp_path):
        clock = {"t": 100.0}
        g = self._gossip(tmp_path, clock, world=2)
        elastic.write_peer_heartbeat(str(tmp_path), 1, 5, incarnation=0,
                                     ts=clock["t"])
        assert g.check() == {}
        clock["t"] += 1.0               # well within the timeout…
        elastic.write_peer_heartbeat(str(tmp_path), 1, 0, incarnation=1,
                                     ts=clock["t"])
        newly = g.check()               # …but the tracked life is gone
        assert list(newly) == [1] and "incarnation" in newly[1]
        assert g.rejoin_candidates() == {1: 1}
        g.readmit(1)
        assert g.dead == () and g.check() == {}

    def test_dead_peer_rejoins_on_fresh_higher_incarnation(self, tmp_path):
        clock = {"t": 100.0}
        g = self._gossip(tmp_path, clock, world=2)
        clock["t"] += 6.0               # silence -> dead
        assert list(g.check()) == [1]
        assert g.rejoin_candidates() == {}
        elastic.write_peer_heartbeat(str(tmp_path), 1, 0, incarnation=1,
                                     ts=clock["t"])
        assert g.rejoin_candidates() == {1: 1}
        # …but a fresh file of the SAME (dead) incarnation is not a rejoin:
        # the paused process's in-memory state is stale relative to the
        # remeshed run; it must restart (bump incarnation) to come back
        elastic.write_peer_heartbeat(str(tmp_path), 0, 0, incarnation=0,
                                     ts=clock["t"])
        g2 = self._gossip(tmp_path, clock, world=2, rank=1)
        assert g2.check() == {}         # admits rank 0 at incarnation 0
        g2.note_dead([0])
        clock["t"] += 1.0
        elastic.write_peer_heartbeat(str(tmp_path), 0, 1, incarnation=0,
                                     ts=clock["t"])
        assert g2.rejoin_candidates() == {}


# ------------------------------------------------------------ bounded fetch

class TestFetchWithTimeout:
    def test_value_passes_through(self):
        assert elastic.fetch_with_timeout(lambda: 42, 5.0) == 42

    def test_timeout_raises_peer_failed(self):
        ev = threading.Event()
        with pytest.raises(elastic.PeerFailed) as ei:
            elastic.fetch_with_timeout(lambda: ev.wait(30.0), 0.05, step=7,
                                       what="drill fetch")
        ev.set()
        assert ei.value.failed == () and ei.value.step == 7
        assert "drill fetch" in str(ei.value)

    def test_thunk_exception_re_raised(self):
        def boom():
            raise KeyError("inner")

        with pytest.raises(KeyError, match="inner"):
            elastic.fetch_with_timeout(boom, 5.0)


# ---------------------------------------------------------- state migration

def _tree(w=4, seed=0):
    rng = np.random.RandomState(seed)
    return {"a": rng.randn(w, 8).astype(np.float32),
            "b": rng.randn(w, 3, 2).astype(np.float32)}


class TestMigration:
    def test_fold_conserves_mass_bitwise(self):
        ef = _tree()
        out, dropped = elastic.migrate_ef(ef, [2], policy="fold")
        assert dropped == 0.0
        for k in ef:
            expect = np.delete(ef[k], [2], axis=0)
            expect[0] = expect[0] + ef[k][2]
            assert np.array_equal(out[k], expect)
            # exact fp32 conservation: the summed mass is unchanged up to
            # the one add per leaf the fold performs
            assert out[k].shape[0] == 3

    def test_fold_into_other_survivor(self):
        ef = _tree()
        out, _ = elastic.migrate_ef(ef, [0], policy="fold", fold_into=1)
        expect = np.delete(ef["a"], [0], axis=0)
        expect[1] = expect[1] + ef["a"][0]
        assert np.array_equal(out["a"], expect)

    def test_drop_accounts_l2_norm(self):
        ef = _tree()
        out, dropped = elastic.migrate_ef(ef, [1, 3], policy="drop")
        sq = sum(float(np.sum(ef[k][[1, 3]].astype(np.float64) ** 2))
                 for k in ef)
        assert dropped == pytest.approx(np.sqrt(sq), rel=0, abs=0)
        for k in ef:
            assert np.array_equal(out[k], np.delete(ef[k], [1, 3], axis=0))

    def test_multi_failure_fold_sums_all_lost_rows(self):
        ef = _tree()
        out, _ = elastic.migrate_ef(ef, [1, 2], policy="fold")
        expect = np.delete(ef["a"], [1, 2], axis=0)
        expect[0] = expect[0] + (ef["a"][1] + ef["a"][2])
        assert np.array_equal(out["a"], expect)

    def test_empty_ef_passes_through(self):
        assert elastic.migrate_ef((), [1]) == ((), 0.0)
        assert elastic.migrate_comp((), [1]) == ()

    def test_bad_worker_index_raises(self):
        with pytest.raises(ValueError):
            elastic.migrate_ef(_tree(w=2), [5])
        with pytest.raises(ValueError):
            elastic.migrate_ef(_tree(), [1], policy="average")

    def test_comp_rows_deleted(self):
        comp = _tree(seed=1)
        out = elastic.migrate_comp(comp, [0])
        for k in comp:
            assert np.array_equal(out[k], comp[k][1:])

    def test_expand_ef_appends_zero_rows(self):
        ef = _tree(w=3)
        out = elastic.expand_ef(ef, 2)
        for k in ef:
            assert out[k].shape[0] == 5
            assert np.array_equal(out[k][:3], ef[k])
            assert not np.any(out[k][3:])

    def test_expand_comp_broadcasts_row0(self):
        comp = _tree(w=3, seed=2)
        out = elastic.expand_comp(comp, 2)
        for k in comp:
            assert out[k].shape[0] == 5
            assert np.array_equal(out[k][3], comp[k][0])
            assert np.array_equal(out[k][4], comp[k][0])


class TestTrimBatches:
    def test_trims_rows_and_keeps_len(self):
        inner = [{"x": np.arange(8), "y": np.arange(8) * 2} for _ in range(3)]
        view = elastic.TrimBatches(inner, 6)
        assert len(view) == 3
        for b in view:
            assert b["x"].shape[0] == 6 and b["y"].shape[0] == 6
        # short batches pass through untouched
        short = elastic.TrimBatches([{"x": np.arange(4)}], 6)
        assert next(iter(short))["x"].shape[0] == 4


# ------------------------------------------------------------- mesh surgery

class TestMeshSurgery:
    def test_surviving_mesh_drops_workers_in_order(self, mesh8):
        new_mesh, removed = elastic.surviving_mesh(mesh8, [2, 5])
        devices = list(mesh8.devices.reshape(-1))
        assert list(new_mesh.devices.reshape(-1)) == [
            d for i, d in enumerate(devices) if i not in (2, 5)]
        assert removed == [devices[2], devices[5]]
        assert tuple(new_mesh.axis_names) == ("data",)
        assert new_mesh.shape["data"] == 6

    def test_extended_mesh_appends_at_tail(self, mesh8):
        new_mesh, removed = elastic.surviving_mesh(mesh8, [0])
        back = elastic.extended_mesh(new_mesh, removed)
        devices = list(mesh8.devices.reshape(-1))
        assert list(back.devices.reshape(-1)) == devices[1:] + [devices[0]]

    def test_rejects_model_parallel_mesh(self):
        from jax.sharding import Mesh

        devs = np.array(jax.devices()[:8]).reshape(4, 2)
        mesh = Mesh(devs, ("data", "tensor"))
        with pytest.raises(ValueError, match="model axes"):
            elastic.surviving_mesh(mesh, [1])

    def test_unit_model_axes_accepted(self):
        from jax.sharding import Mesh

        devs = np.array(jax.devices()[:4]).reshape(4, 1, 1)
        mesh = Mesh(devs, ("data", "seq", "tensor"))
        new_mesh, _ = elastic.surviving_mesh(mesh, [3])
        assert tuple(new_mesh.axis_names) == ("data", "seq", "tensor")
        assert new_mesh.shape["data"] == 3

    def test_out_of_range_failure_rejected(self, mesh8):
        with pytest.raises(ValueError, match="outside world"):
            elastic.surviving_mesh(mesh8, [8])


# ----------------------------------------------------------------- runtime

class TestElasticRuntime:
    def _runtime(self, mesh, **cfg_kw):
        return elastic.ElasticRuntime(
            elastic.ElasticConfig(**cfg_kw), mesh, log=lambda s: None)

    def test_failure_from_conversions(self, mesh8):
        el = self._runtime(mesh8)
        # PeerFailed passes through untouched
        pf = elastic.PeerFailed((3,), step=5)
        assert el.failure_from(pf) is pf
        # mid-collective chaos converts to the dying worker
        crash = ChaosCrash("boom")
        crash.step, crash.mode, crash.worker = 4, "mid_collective", 6
        out = el.failure_from(crash)
        assert out.failed == (6,) and out.step == 4
        # step-mode crashes (watchdog territory) and unrelated faults do not
        crash2 = ChaosCrash("boom")
        crash2.step, crash2.mode, crash2.worker = 4, "step", 6
        assert el.failure_from(crash2) is None
        assert el.failure_from(RuntimeError("x")) is None

    def test_empty_culprit_filled_from_gossip(self, mesh8, tmp_path):
        clock = {"t": 100.0}
        gossip = elastic.PeerGossip(str(tmp_path), 0, 8, peer_timeout_s=5.0,
                                    now=lambda: clock["t"])
        el = elastic.ElasticRuntime(elastic.ElasticConfig(), mesh8,
                                    gossip=gossip, log=lambda s: None)
        clock["t"] += 6.0               # every peer silent past the timeout
        out = el.failure_from(elastic.PeerFailed((), step=3,
                                                 reason="fetch timeout"))
        assert out.failed == tuple(range(1, 8)) and out.step == 3

    def test_min_world_refusal(self, mesh8):
        el = self._runtime(mesh8, min_world=8)

        class FakeState:
            ef = ()
            comp = ()

        with pytest.raises(elastic.PeerFailed, match="min_world"):
            el.handle_failure(FakeState(), elastic.PeerFailed((1,), step=0))
        assert el.remesh_count == 0

    def test_culpritless_failure_re_raised(self, mesh8):
        el = self._runtime(mesh8)
        with pytest.raises(elastic.PeerFailed):
            el.handle_failure(object(), elastic.PeerFailed((), step=0))

    def test_metrics_are_declared(self, mesh8):
        from tpu_compressed_dp.obs import registry

        el = self._runtime(mesh8)
        for key in el.metrics():
            assert registry.is_declared(key), key
