"""MFU / analytic-FLOPs tests (VERDICT r2 #3)."""

import jax
import jax.numpy as jnp

from tpu_compressed_dp.utils import flops as F
import pytest

pytestmark = pytest.mark.quick  # fast tier (VERDICT r2 #10)



class _FakeDev:
    def __init__(self, kind):
        self.device_kind = kind


def test_transformer_formula():
    # 6N dominates at seq << d; attention term = 12*L*d*s
    n_params, L, d, s = 1_000_000, 4, 256, 128
    got = F.transformer_train_flops_per_token(n_params, L, d, s)
    assert got == 6.0 * n_params + 12.0 * L * d * s


def test_peak_prefix_match():
    assert F.chip_peak_flops(_FakeDev("TPU v5 lite")) == 197e12  # not v5p
    assert F.chip_peak_flops(_FakeDev("TPU v5p")) == 459e12
    assert F.chip_peak_flops(_FakeDev("TPU v4")) == 275e12
    assert F.chip_peak_flops(_FakeDev("Graphcore IPU")) is None


def test_mfu_none_off_tpu():
    assert F.mfu(1e12, _FakeDev("weird")) is None
    assert F.mfu(98.5e12, _FakeDev("TPU v5 lite")) == 0.5


def test_fwd_flops_xla_matmul():
    # 2*M*N*K FLOPs for a matmul, per XLA's own cost model; abstract args
    def f(a, b):
        return a @ b

    a = jnp.zeros((64, 32), jnp.float32)
    b = jnp.zeros((32, 16), jnp.float32)
    got = F.fwd_flops_xla(f, a, b)
    if got is not None:  # backend exposes a cost model
        assert got == 2 * 64 * 32 * 16
