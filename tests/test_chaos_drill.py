"""Chaos drill (tools/chaos_drill.py): the injection matrix that proves the
step guard's acceptance invariants.  The quick subset runs in tier-1; the
full matrix (kind x target x worker cross, PowerSGD hold, EF identity incl.
the sharded wire transport, poison control arm) is ``slow``."""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools import chaos_drill  # noqa: E402


@pytest.mark.quick
def test_quick_drill(mesh8):
    """tier-1 smoke: skip consistency, loss-scale dynamics, the wedge
    raise, bitwise crash recovery through run_with_recovery, and the
    elastic invariants (gossip detection + one mid-collective kill ->
    W-1 remesh with bitwise EF fold)."""
    results = chaos_drill.run_drills(chaos_drill.QUICK, mesh=mesh8)
    assert results["skip_consistency"]["nonfinite"] == [0.0, 0.0, 1.0, 0.0, 0.0]
    assert results["loss_scale"]["scales"][:2] == [1024.0, 512.0]
    assert results["max_skips"]["raised_at_step"] == 3
    assert results["crash_recovery"]["restores"] == 1
    assert results["elastic_gossip"]["detected"] == [2]
    assert results["elastic_remesh"]["world"] == 7
    assert results["elastic_remesh"]["dropped_ef_norm"] == 0.0  # fold policy
    # ISSUE 9 acceptance rows: preempt -> emergency save -> bitwise resume;
    # corrupt latest -> one-step rollback to the last verifiable save
    assert results["ckpt_preempt"]["resumed_from"] == 3
    assert results["ckpt_preempt"]["bitwise"] is True
    assert results["ckpt_corrupt"]["rollback_steps"] == 1
    # stream acceptance rows: torn delta -> walk back to the keyframe and
    # re-converge bitwise; torn keyframe with no later anchor -> warm
    # rejoin refuses the stream (full-restore fallback)
    assert results["stream_corrupt"]["corrupt_segments"] == 1
    assert results["stream_corrupt"]["walkback_seq"] == 0
    assert results["stream_corrupt"]["reconverged"] is True
    assert results["stream_corrupt"]["keyframe_fallback"] is True
    # ISSUE 11 acceptance row: crash-relaunch mid-decision-window replays
    # the same rung schedule and the same control_decision events
    assert results["control_resume"]["rungs"] == [1, 2, 2]
    assert results["control_resume"]["resumed_mid_window"] is True
    # ISSUE 12 acceptance row: high-priority arrival evicts one job and
    # shrinks another through the readmit barrier; every job finishes
    # bitwise-equal to its solo run
    assert results["fleet"]["evictions"] == 1
    assert results["fleet"]["shrinks"] == 1
    assert results["fleet"]["readmits"] == 1
    assert results["fleet"]["bitwise"] is True


@pytest.mark.quick
def test_every_quick_row_registered_and_collectible(capsys):
    """CI discovery contract: every quick row expands to a concrete drill
    (a ``drill_*`` function exists for it), and ``--list`` prints the full
    quick/slow row matrix — so a row can neither silently vanish from the
    tier-1 gate nor run unlisted."""
    # matrix groups expand inline; aliased rows re-parameterise another drill
    matrix = ("skip_matrix", "elastic_matrix", "fleet_matrix")
    alias = {"ef_identity_sharded": "ef_identity"}

    def resolves(name):
        return callable(
            getattr(chaos_drill, f"drill_{alias.get(name, name)}", None))

    quick_rows = chaos_drill.expand_rows(chaos_drill.QUICK)
    assert quick_rows, "quick tier is empty"
    for name in chaos_drill.QUICK:
        assert name in chaos_drill.FULL, f"{name} missing from FULL"
        if name not in matrix:
            assert resolves(name), f"quick row {name} has no drill function"
    rc = chaos_drill.main(["--list"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "quick:" in out and "slow:" in out
    listed = [ln.strip() for ln in out.splitlines()
              if ln.startswith("  ")]
    for row in quick_rows:
        assert row in listed, f"quick row {row} missing from --list"
    for row in chaos_drill.expand_rows(
            [n for n in chaos_drill.FULL if n not in chaos_drill.QUICK]):
        assert row in listed, f"slow row {row} missing from --list"
    # every FULL name resolves too (the slow tier is equally collectible)
    for name in chaos_drill.FULL:
        if name not in matrix:
            assert resolves(name), name


@pytest.mark.slow
def test_full_drill_matrix(mesh8):
    results = chaos_drill.run_drills(
        [n for n in chaos_drill.FULL if n not in chaos_drill.QUICK],
        mesh=mesh8)
    assert results["ef_identity"]["max_gap"] < 1e-5
    assert results["ef_identity_sharded"]["max_gap"] < 1e-5
    # elastic matrix: every kill-step x worker x EF-policy cell remeshed to
    # W-1; drop cells with a warm EF account a positive abandoned norm
    assert results["elastic_readmit"] == {"world": 8, "readmits": 1}
    for policy in ("fold", "drop"):
        for worker in (0, 7):
            for kill_step in (0, 3):
                cell = results[f"elastic[{policy},w{worker},s{kill_step}]"]
                assert cell["world"] == 7
                if policy == "fold":
                    assert cell["dropped_ef_norm"] == 0.0
                elif kill_step > 0:
                    assert cell["dropped_ef_norm"] > 0.0
    assert results["elastic[sharded-wire]"]["world"] == 7
    # cascade: during_remesh second death -> one committed remesh at W-2
    assert results["elastic_cascade"] == {"world": 6, "cascades": 1}
    # fleet matrix: both EF policies shrink+readmit bitwise; the rigid
    # cell has no shrink candidate, so preemption is evict-only
    for cell in ("fleet[fold]", "fleet[drop]"):
        assert results[cell]["bitwise"] is True
        assert (results[cell]["shrinks"], results[cell]["readmits"]) == (1, 1)
    assert results["fleet[rigid]"]["bitwise"] is True
    assert (results["fleet[rigid]"]["shrinks"],
            results["fleet[rigid]"]["readmits"],
            results["fleet[rigid]"]["evictions"]) == (0, 0, 1)


@pytest.mark.slow
def test_crash_recovery_replays_in_graph_faults(mesh8):
    """Crash + restore replays through a step where in-graph chaos fires:
    the injection is step-counter driven, so the replayed run skips the
    same step and lands bitwise on the uncrashed run."""
    out = chaos_drill.drill_crash_recovery(
        mesh8, crash_at_step=4, chaos_spec="nan,target=grads,steps=5,worker=3")
    assert out["restores"] == 1
