"""In-graph step guard: finiteness vote, skip/hold semantics, dynamic loss
scaling, chaos injection, and the Orbax round-trip of the new guard state
(ISSUE 3 tentpole)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_compressed_dp.parallel.dp import (CompressionConfig, init_comp_state,
                                           init_ef_state)
from tpu_compressed_dp.train import guard as guard_mod
from tpu_compressed_dp.train.guard import (GuardConfig, GuardExceeded,
                                           GuardState, check_guard_metrics,
                                           init_guard_state, update_guard)
from tpu_compressed_dp.train.optim import SGD
from tpu_compressed_dp.train.state import TrainState
from tpu_compressed_dp.train.step import make_train_step
from tpu_compressed_dp.utils.chaos import ChaosConfig

pytestmark = pytest.mark.quick


# ------------------------------------------------------------- pure units

class TestGuardConfig:
    def test_for_dtype_activates_scaling_on_16bit(self):
        assert GuardConfig.for_dtype(jnp.bfloat16).loss_scaling
        assert GuardConfig.for_dtype(jnp.float16).loss_scaling
        assert not GuardConfig.for_dtype(jnp.float32).loss_scaling

    def test_validation(self):
        with pytest.raises(ValueError, match="backoff"):
            GuardConfig(backoff=1.5)
        with pytest.raises(ValueError, match="growth "):
            GuardConfig(growth=0.5)
        with pytest.raises(ValueError, match="init_scale"):
            GuardConfig(init_scale=0.25)

    def test_init_state_identity_scale_when_scaling_off(self):
        gs = init_guard_state(GuardConfig(loss_scaling=False))
        assert float(gs.loss_scale) == 1.0
        assert init_guard_state(None) == ()


class TestUpdateGuard:
    def _gs(self, **kw):
        base = dict(loss_scale=jnp.asarray(512.0), good_steps=jnp.asarray(0),
                    skips=jnp.asarray(0), total_skipped=jnp.asarray(0),
                    last_good_step=jnp.asarray(0))
        base.update({k: jnp.asarray(v) for k, v in kw.items()})
        return GuardState(**base)

    def test_backoff_clamps_at_one(self):
        cfg = GuardConfig(backoff=0.5, loss_scaling=True)
        gs = self._gs(loss_scale=1.5)
        gs = update_guard(cfg, gs, jnp.asarray(False), jnp.asarray(1))
        assert float(gs.loss_scale) == 1.0
        gs = update_guard(cfg, gs, jnp.asarray(False), jnp.asarray(2))
        assert float(gs.loss_scale) == 1.0  # never below 1
        assert int(gs.skips) == 2 and int(gs.total_skipped) == 2

    def test_growth_after_interval_and_counter_reset(self):
        cfg = GuardConfig(growth_interval=2, growth=2.0, loss_scaling=True)
        gs = self._gs()
        gs = update_guard(cfg, gs, jnp.asarray(True), jnp.asarray(1))
        assert float(gs.loss_scale) == 512.0 and int(gs.good_steps) == 1
        gs = update_guard(cfg, gs, jnp.asarray(True), jnp.asarray(2))
        assert float(gs.loss_scale) == 1024.0 and int(gs.good_steps) == 0
        assert int(gs.last_good_step) == 2

    def test_bad_step_resets_growth_progress(self):
        cfg = GuardConfig(growth_interval=2, loss_scaling=True)
        gs = self._gs(good_steps=1)
        gs = update_guard(cfg, gs, jnp.asarray(False), jnp.asarray(5))
        assert int(gs.good_steps) == 0
        assert int(gs.last_good_step) == 0  # unchanged

    def test_pinned_scale_when_scaling_off(self):
        cfg = GuardConfig(loss_scaling=False)
        gs = self._gs(loss_scale=1.0)
        for ok in (False, True, True, True):
            gs = update_guard(cfg, gs, jnp.asarray(ok), jnp.asarray(1))
        assert float(gs.loss_scale) == 1.0


class TestHostCheck:
    def test_raises_past_max(self):
        cfg = GuardConfig(max_consecutive_skips=3)
        check_guard_metrics({"guard/skip_streak": 3.0}, cfg)  # at the limit: ok
        with pytest.raises(GuardExceeded, match="4 consecutive"):
            check_guard_metrics(
                {"guard/skip_streak": 4.0, "guard/loss_scale": 8.0,
                 "guard/last_good_step": 11.0}, cfg)

    def test_noop_without_guard_metrics(self):
        check_guard_metrics({"loss": 1.0}, GuardConfig())


class TestChaosParse:
    def test_full_spec(self):
        c = ChaosConfig.parse("inf,target=loss,steps=3+7,worker=2,crash=40")
        assert c.kind == "inf" and c.target == "loss"
        assert c.steps == (3, 7) and c.worker == 2 and c.crash_at_step == 40
        assert c.injects_in_graph

    def test_crash_only(self):
        c = ChaosConfig.parse("crash=10")
        assert not c.injects_in_graph and c.crash_at_step == 10

    def test_rejects_unknown(self):
        with pytest.raises(ValueError, match="unknown --chaos key"):
            ChaosConfig.parse("bogus=1")
        with pytest.raises(ValueError, match="nan|inf"):
            ChaosConfig.parse("jitter")


class TestGuardMeter:
    def test_delta_based_rate_survives_sparse_sampling(self):
        """The skip rate comes from cumulative-counter deltas, so observing
        only every 10th step still reads the true rate (per-step sampling
        would alias a periodic fault to 0% or 100%)."""
        from tpu_compressed_dp.utils.meters import GuardMeter

        gm = GuardMeter()
        assert gm.summary() == {}  # guard off
        # 10% true skip rate, observed at steps 10 and 20 only
        gm.update({"guard/skipped": 1.0, "guard/loss_scale": 64.0}, step=10)
        gm.update({"guard/skipped": 2.0, "guard/loss_scale": 64.0}, step=20)
        s = gm.summary()
        assert s["guard/skip_rate"] == pytest.approx(0.1)
        assert s["guard/skipped"] == 2.0
        gm.update({"loss": 1.0}, step=30)  # no guard metrics: ignored
        assert gm.summary()["guard/skipped"] == 2.0


# -------------------------------------------------- jitted-step integration

def _build(mesh, comp_cfg, guard_cfg, chaos, *, momentum=0.9,
           dtype=jnp.float32, lr=0.05):
    import flax.linen as nn

    from tpu_compressed_dp.models.common import init_model, make_apply_fn

    class TinyMLP(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            x = x.reshape((x.shape[0], -1)).astype(dtype)
            x = nn.relu(nn.Dense(16, dtype=dtype)(x))
            return nn.Dense(4, dtype=dtype)(x)

    module = TinyMLP()
    params, stats = init_model(module, jax.random.key(0),
                               jnp.zeros((1, 4, 4, 3), jnp.float32))
    opt = SGD(lr=lr, momentum=momentum, nesterov=momentum > 0)
    n = mesh.shape["data"]
    state = TrainState.create(
        params, stats, opt.init(params), init_ef_state(params, comp_cfg, n),
        jax.random.key(1), comp=init_comp_state(params, comp_cfg, n),
        guard=init_guard_state(guard_cfg))
    step = make_train_step(make_apply_fn(module), opt, comp_cfg, mesh,
                           guard_cfg=guard_cfg, chaos=chaos, donate=False)
    return state, step


def _batch(n=32, seed=0):
    rng = np.random.RandomState(seed)
    return {"input": jnp.asarray(rng.randn(n, 4, 4, 3).astype(np.float32)),
            "target": jnp.asarray(rng.randint(0, 4, n).astype(np.int32))}


def test_single_worker_nan_vetoes_globally_and_holds_state(mesh8):
    """The acceptance core: NaN on ONE worker at step k => the identical
    skip decision everywhere, with ef (and params/opt/bn) bitwise held."""
    comp = CompressionConfig(method="topk", ratio=0.25, error_feedback=True)
    gcfg = GuardConfig(loss_scaling=False)
    chaos = ChaosConfig(kind="nan", target="grads", steps=(1,), worker=5)
    state, step = _build(mesh8, comp, gcfg, chaos)
    batch = _batch()
    state, m = step(state, batch)
    assert float(m["guard/nonfinite"]) == 0.0
    pre = jax.tree.map(np.asarray, (state.params, state.opt_state,
                                    state.batch_stats, state.ef))
    state, m = step(state, batch)
    assert float(m["guard/nonfinite"]) == 1.0
    assert float(m["guard/skipped"]) == 1.0
    assert float(m["guard/last_good_step"]) == 1.0
    post = jax.tree.map(np.asarray, (state.params, state.opt_state,
                                     state.batch_stats, state.ef))
    for a, b in zip(jax.tree.leaves(pre), jax.tree.leaves(post)):
        assert np.array_equal(a, b)
    # the run recovers: next step applies
    state, m = step(state, batch)
    assert float(m["guard/nonfinite"]) == 0.0
    assert float(m["guard/skip_streak"]) == 0.0
    assert int(state.step) == 3


def test_schedule_step_unit():
    """schedule_step: applied-update count under rewind; raw passthrough
    when the guard is off or the knob is."""
    from tpu_compressed_dp.train.guard import schedule_step

    cfg = GuardConfig(loss_scaling=False)
    gs = init_guard_state(cfg)
    gs = dataclasses.replace(gs, total_skipped=jnp.asarray(4, jnp.int32))
    assert int(schedule_step(cfg, gs, jnp.asarray(10, jnp.int32))) == 6
    off = GuardConfig(loss_scaling=False, lr_rewind=False)
    assert int(schedule_step(off, gs, jnp.asarray(10, jnp.int32))) == 10
    assert int(schedule_step(None, (), jnp.asarray(10, jnp.int32))) == 10
    assert int(schedule_step(cfg, (), jnp.asarray(10, jnp.int32))) == 10


def test_lr_rewind_skips_dont_advance_schedule(mesh8):
    """The ROADMAP item's acceptance: N injected-NaN skips leave the LR —
    and, with a deterministic compressor + fixed batch, the ENTIRE applied
    trajectory (params/opt/ef) — exactly where an unskipped run of the same
    good-step count puts them.  Without rewind the vetoed attempts
    fast-forward the schedule clock and the LRs diverge."""
    lr_sched = lambda s: 0.05 / (1.0 + 0.5 * s.astype(jnp.float32))  # noqa: E731
    comp = CompressionConfig(method="topk", ratio=0.25, error_feedback=True)
    gcfg = GuardConfig(loss_scaling=False)
    batch = _batch()

    # run A: 5 attempts, NaN injected at raw steps 1 and 3 -> 3 applied
    chaos = ChaosConfig(kind="nan", target="grads", steps=(1, 3), worker=2)
    sA, stepA = _build(mesh8, comp, gcfg, chaos, lr=lr_sched)
    for _ in range(5):
        sA, mA = stepA(sA, batch)
    assert float(mA["guard/skipped"]) == 2.0
    assert int(sA.step) == 5

    # run B: 3 clean attempts -> the same 3 applied updates
    sB, stepB = _build(mesh8, comp, gcfg, None, lr=lr_sched)
    for _ in range(3):
        sB, mB = stepB(sB, batch)

    assert float(mA["lr"]) == float(mB["lr"])  # schedule clock identical
    for a, b in zip(jax.tree.leaves((sA.params, sA.opt_state, sA.ef)),
                    jax.tree.leaves((sB.params, sB.opt_state, sB.ef))):
        assert np.array_equal(np.asarray(a), np.asarray(b))

    # control arm: rewind off -> the 2 vetoed attempts advance the clock
    sC, stepC = _build(mesh8, comp,
                       GuardConfig(loss_scaling=False, lr_rewind=False),
                       chaos, lr=lr_sched)
    for _ in range(5):
        sC, mC = stepC(sC, batch)
    assert float(mC["lr"]) == pytest.approx(float(lr_sched(jnp.asarray(5.0))))
    assert float(mC["lr"]) != float(mB["lr"])


def test_guard_off_matches_guard_on_fp32(mesh8):
    """With no faults and the fp32 identity scale, the guarded step computes
    the same update as the unguarded one.  Not asserted bitwise: the guarded
    program compiles separately and XLA may lower its psum with a different
    reduction tree (fp add is non-associative — observed 1-ulp diffs on the
    CPU backend), so the bound here is a tight ulp-scale tolerance; the
    guard's *within-program* holds ARE bitwise (tested above)."""
    comp = CompressionConfig(method="topk", ratio=0.5, error_feedback=True)
    chaos = None
    s0, step0 = _build(mesh8, comp, None, chaos)
    gcfg = GuardConfig(loss_scaling=False)
    s1, step1 = _build(mesh8, comp, gcfg, chaos)
    batch = _batch()
    for _ in range(3):
        s0, _ = step0(s0, batch)
        s1, _ = step1(s1, batch)
    for a, b in zip(jax.tree.leaves(s0.params), jax.tree.leaves(s1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


@pytest.mark.slow  # two extra whole-step compiles; property also implied by
                   # test_guard_off_matches_guard_on_fp32 + the bf16 dynamics
def test_pow2_loss_scale_is_exact_on_fp32(mesh8):
    """A power-of-two scale multiplies out exactly in fp32: scaled-loss
    backprop + unscale == the unscaled gradient path, bitwise."""
    comp = CompressionConfig(method=None)
    s0, step0 = _build(mesh8, comp, GuardConfig(loss_scaling=False),
                       None, momentum=0.0)
    s1, step1 = _build(mesh8, comp,
                       GuardConfig(init_scale=2.0 ** 12, growth_interval=10 ** 6,
                                   loss_scaling=True),
                       None, momentum=0.0)
    batch = _batch()
    for _ in range(2):
        s0, _ = step0(s0, batch)
        s1, _ = step1(s1, batch)
    for a, b in zip(jax.tree.leaves(s0.params), jax.tree.leaves(s1.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_loss_scale_backoff_and_regrowth_bf16(mesh8):
    """bf16 compute path: the dynamic scale halves on the injected overflow
    and regrows after growth_interval good steps."""
    comp = CompressionConfig(method=None)
    gcfg = GuardConfig.for_dtype(jnp.bfloat16, init_scale=256.0,
                                 growth_interval=2)
    assert gcfg.loss_scaling
    chaos = ChaosConfig(kind="inf", target="grads", steps=(0,), worker=3)
    state, step = _build(mesh8, comp, gcfg, chaos, momentum=0.0,
                         dtype=jnp.bfloat16)
    batch = _batch()
    scales = []
    for _ in range(4):
        state, m = step(state, batch)
        scales.append(float(m["guard/loss_scale"]))
    assert scales == [128.0, 128.0, 256.0, 256.0], scales
    assert float(m["guard/skipped"]) == 1.0


def test_guard_requires_state(mesh8):
    comp = CompressionConfig(method=None)
    gcfg = GuardConfig()
    state, step = _build(mesh8, comp, gcfg, None)
    state = dataclasses.replace(state, guard=())
    with pytest.raises(ValueError, match="state.guard is empty"):
        step(state, _batch())


def test_wire_mode_guard_holds_ef(mesh8):
    """The wire engine path (packed sparse payloads) is guarded too: EF held
    bitwise on the vetoed step, finite throughout."""
    comp = CompressionConfig(method="topk", ratio=0.25, error_feedback=True,
                             mode="wire", granularity="entiremodel")
    gcfg = GuardConfig(loss_scaling=False)
    chaos = ChaosConfig(kind="nan", target="grads", steps=(1,), worker=0)
    state, step = _build(mesh8, comp, gcfg, chaos)
    batch = _batch()
    state, _ = step(state, batch)
    pre_ef = jax.tree.map(np.asarray, state.ef)
    state, m = step(state, batch)
    assert float(m["guard/nonfinite"]) == 1.0
    for a, b in zip(jax.tree.leaves(pre_ef), jax.tree.leaves(state.ef)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------ lm-step path

@pytest.mark.slow  # (2,2,2)-mesh LM compile; the vote/hold mechanics are
                   # tier-1-covered on the DP step + quick drill
def test_lm_step_guard_votes_across_full_mesh(mesh8):
    """(data, seq, tensor) mesh: one poisoned (data, seq) worker's NaN must
    veto the update on every tensor shard too (params held bitwise)."""
    from tpu_compressed_dp.models import transformer as tf
    from tpu_compressed_dp.train.lm_step import (init_lm_ef_state,
                                                 make_lm_mesh,
                                                 make_lm_train_step)

    cfg = dataclasses.replace(tf.tiny_llama(vocab=64, dim=32, layers=1),
                              n_heads=2, n_kv_heads=2, ffn_hidden=64)
    mesh = make_lm_mesh(2, 2, 2)
    comp = CompressionConfig(method="topk", ratio=0.25, error_feedback=True,
                             granularity="entiremodel")
    gcfg = GuardConfig.for_dtype(cfg.dtype, init_scale=256.0)
    chaos = ChaosConfig(kind="nan", target="grads", steps=(0,), worker=2)
    params = tf.init_llama(cfg, jax.random.key(0))
    opt = SGD(lr=1e-2, momentum=0.9)
    state = TrainState.create(
        params, {}, opt.init(params),
        init_lm_ef_state(cfg, params, comp, mesh), jax.random.key(1),
        guard=init_guard_state(gcfg))
    step = make_lm_train_step(cfg, opt, comp, mesh, guard_cfg=gcfg,
                              chaos=chaos, donate=False)
    rng = np.random.RandomState(0)
    batch = {"input": jnp.asarray(rng.randint(0, 64, (4, 32)).astype(np.int32)),
             "target": jnp.asarray(rng.randint(0, 64, (4, 32)).astype(np.int32))}
    pre = jax.tree.map(np.asarray, (state.params, state.ef))
    state, m = step(state, batch)
    assert float(m["guard/nonfinite"]) == 1.0
    assert float(m["guard/loss_scale"]) == 128.0  # bf16 path backed off
    post = jax.tree.map(np.asarray, (state.params, state.ef))
    for a, b in zip(jax.tree.leaves(pre), jax.tree.leaves(post)):
        assert np.array_equal(a, b)
    state, m = step(state, batch)
    assert float(m["guard/nonfinite"]) == 0.0
    assert np.isfinite(float(m["loss"]))


# ------------------------------------------------------ checkpoint plumbing

class TestGuardCheckpoint:
    def _state(self, guard):
        params = {"w": jnp.arange(64, dtype=jnp.float32)}
        return TrainState.create(params, {}, {"momentum": params}, (),
                                 jax.random.key(1), guard=guard)

    def test_guard_roundtrips_bitwise(self, tmp_path):
        from tpu_compressed_dp.utils.checkpoint import (restore_checkpoint,
                                                        save_checkpoint)

        gs = GuardState(loss_scale=jnp.asarray(384.0),
                        good_steps=jnp.asarray(7, jnp.int32),
                        skips=jnp.asarray(2, jnp.int32),
                        total_skipped=jnp.asarray(5, jnp.int32),
                        last_good_step=jnp.asarray(123, jnp.int32))
        save_checkpoint(str(tmp_path / "ck"), self._state(gs))
        target = self._state(init_guard_state(GuardConfig()))
        restored, _ = restore_checkpoint(str(tmp_path / "ck"), target)
        for f in ("loss_scale", "good_steps", "skips", "total_skipped",
                  "last_good_step"):
            np.testing.assert_array_equal(
                np.asarray(getattr(restored.guard, f)),
                np.asarray(getattr(gs, f)))

    def test_guard_off_roundtrips_as_empty(self, tmp_path):
        from tpu_compressed_dp.utils.checkpoint import (restore_checkpoint,
                                                        save_checkpoint)

        save_checkpoint(str(tmp_path / "ck"), self._state(()))
        restored, _ = restore_checkpoint(str(tmp_path / "ck"),
                                         self._state(()))
        assert restored.guard == ()

    def test_guard_armed_after_guardless_save(self, tmp_path):
        """Toggle regression (review finding): a checkpoint saved with the
        guard OFF (on-disk marker ``guard: {}``) must restore into a
        guard-armed target, keeping the target's fresh GuardState — Orbax
        raises KeyError (not ValueError) for this marker-vs-template
        mismatch, which the original fallback missed."""
        from tpu_compressed_dp.utils.checkpoint import (restore_checkpoint,
                                                        save_checkpoint)

        save_checkpoint(str(tmp_path / "ck"), self._state(()))
        fresh = init_guard_state(GuardConfig(init_scale=128.0))
        restored, _ = restore_checkpoint(str(tmp_path / "ck"),
                                         self._state(fresh))
        assert float(restored.guard.loss_scale) == 128.0
        np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                      np.arange(64, dtype=np.float32))

    def test_guard_disarmed_after_guarded_save(self, tmp_path):
        """Reverse toggle: a guard-on checkpoint restores into a guard-off
        target — the saved GuardState wins (harmless to an unguarded step,
        preserved for a later re-arm)."""
        from tpu_compressed_dp.utils.checkpoint import (restore_checkpoint,
                                                        save_checkpoint)

        gs = init_guard_state(GuardConfig(init_scale=512.0))
        save_checkpoint(str(tmp_path / "ck"), self._state(gs))
        restored, _ = restore_checkpoint(str(tmp_path / "ck"),
                                         self._state(()))
        assert float(restored.guard.loss_scale) == 512.0

    def test_genuine_mismatch_still_raises(self, tmp_path):
        """The template-free fallback must NOT mask real structure drift:
        resized params raise instead of restoring garbage."""
        from tpu_compressed_dp.utils.checkpoint import (restore_checkpoint,
                                                        save_checkpoint)

        save_checkpoint(str(tmp_path / "ck"), self._state(()))
        bad_params = {"w": jnp.zeros((65,), jnp.float32)}  # 64 -> 65
        target = TrainState.create(bad_params, {}, {"momentum": bad_params},
                                   (), jax.random.key(0),
                                   guard=init_guard_state(GuardConfig()))
        with pytest.raises((ValueError, KeyError)):
            restore_checkpoint(str(tmp_path / "ck"), target)

    def test_pre_guard_checkpoint_keeps_callers_guard(self, tmp_path,
                                                      monkeypatch):
        """Legacy fallback (mirrors the `comp` fallback): a checkpoint
        written before TrainState grew `guard` restores into a guard-armed
        target, keeping the target's fresh GuardState."""
        from tpu_compressed_dp.utils import checkpoint as ck

        orig = ck._to_saveable

        def legacy(s):
            d = orig(s)
            d.pop("guard")  # what a pre-guard writer produced
            return d

        monkeypatch.setattr(ck, "_to_saveable", legacy)
        ck.save_checkpoint(str(tmp_path / "ck"), self._state(()))
        monkeypatch.setattr(ck, "_to_saveable", orig)
        fresh = init_guard_state(GuardConfig(init_scale=64.0))
        restored, _ = ck.restore_checkpoint(str(tmp_path / "ck"),
                                            self._state(fresh))
        assert float(restored.guard.loss_scale) == 64.0
        # guard-off target restores too
        restored2, _ = ck.restore_checkpoint(str(tmp_path / "ck"),
                                             self._state(()))
        assert restored2.guard == ()

    def test_pre_comp_and_pre_guard_checkpoint(self, tmp_path, monkeypatch):
        """The double-legacy case: a pre-PowerSGD checkpoint (no comp AND no
        guard on disk) restores into a target that has both."""
        from tpu_compressed_dp.utils import checkpoint as ck

        orig = ck._to_saveable

        def ancient(s):
            d = orig(s)
            d.pop("guard")
            d.pop("comp")
            return d

        monkeypatch.setattr(ck, "_to_saveable", ancient)
        ck.save_checkpoint(str(tmp_path / "ck"), self._state(()))
        monkeypatch.setattr(ck, "_to_saveable", orig)
        params = {"w": jnp.arange(64, dtype=jnp.float32)}
        target = TrainState.create(
            params, {}, {"momentum": params}, (), jax.random.key(0),
            comp=(), guard=init_guard_state(GuardConfig(init_scale=32.0)))
        restored, _ = ck.restore_checkpoint(str(tmp_path / "ck"), target)
        assert float(restored.guard.loss_scale) == 32.0
        assert restored.comp == ()
        np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                      np.arange(64, dtype=np.float32))
