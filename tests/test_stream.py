"""Delta state streaming (tpu_compressed_dp/stream/): the lossless window
invariant, the store's manifest discipline, corruption walk-back, warm
rejoin end-to-end against the full-restore path, the fsck/serve tooling,
and the harness plumbing.

The core contract under test: segments carry CURRENT VALUES at selected
coordinates (set semantics, never additive), every window closes with a
bit-exact flush, so ``keyframe + deltas of one window`` reconstructs the
producer's fp32 params *bitwise* — what lets a warm joiner skip the params
broadcast and a serving replica trust its snapshots.
"""

import argparse
import copy
import dataclasses
import json
import os
import sys

import jax
import numpy as np
import pytest

from tpu_compressed_dp.stream import delta as sdelta
from tpu_compressed_dp.stream.reader import StreamReader
from tpu_compressed_dp.stream.rejoin import warm_rejoin
from tpu_compressed_dp.stream.store import (StreamCorrupt, head_path,
                                            is_stream_dir, list_segments,
                                            prune_segments, read_head,
                                            read_segment_manifest,
                                            segment_payload_path,
                                            verify_stream)
from tpu_compressed_dp.stream.writer import StreamWriter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

pytestmark = pytest.mark.quick


def _quiet(*a, **k):
    pass


def _params(rng, scale=1.0):
    return {"dense": {"kernel": (rng.randn(24, 8) * scale).astype(np.float32)},
            "bias": (rng.randn(32) * scale).astype(np.float32)}


def _advance(params, rng, scale=0.01):
    return {"dense": {"kernel": (params["dense"]["kernel"]
                                 + (rng.randn(24, 8) * scale
                                    ).astype(np.float32))},
            "bias": (params["bias"]
                     + (rng.randn(32) * scale).astype(np.float32))}


def _assert_bitwise(a, b, what):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), (
            f"{what}: leaf not bitwise equal")


def _flip_payload(directory, seq):
    path = segment_payload_path(directory, seq)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))


# ------------------------------------------------------------- delta codec

class TestDeltaCodec:
    def test_select_pack_topk_matches_numpy(self):
        """The reused wire compress step (threshold + select + pack) picks
        exactly the numpy argsort top-k by magnitude, payload gathered in
        ascending-index order."""
        from tpu_compressed_dp.ops import wire

        rng = np.random.RandomState(0)
        n, keep = 512, 37
        # distinct magnitudes => a unique top-k set, no tie ambiguity
        mags = rng.permutation(np.arange(1, n + 1)).astype(np.float32)
        vec = mags * np.where(rng.rand(n) < 0.5, -1.0, 1.0).astype(np.float32)
        payload, idx, count = jax.jit(
            lambda v: wire.select_pack_topk(v, keep))(vec)
        k = int(count)
        assert k == keep
        want = np.sort(np.argsort(np.abs(vec))[-keep:])
        np.testing.assert_array_equal(np.asarray(idx)[:k], want)
        np.testing.assert_array_equal(np.asarray(payload)[:k], vec[want])

    def test_flatten_round_trip_and_respec_guard(self):
        rng = np.random.RandomState(1)
        params = _params(rng)
        vec, spec = sdelta.flatten_params(params)
        assert vec.dtype == np.float32 and vec.ndim == 1
        back = sdelta.unflatten_like(params, vec, spec)
        _assert_bitwise(params, back, "flatten round trip")
        # template-free reconstruction agrees leaf for leaf
        d = sdelta.unflatten_dict(vec, spec)
        assert len(d) == len(spec)
        for ent in spec:
            assert d[ent["path"]].shape == tuple(ent["shape"])
        # a different model must fail loudly, not half-apply
        other = {"dense": {"kernel": np.zeros((3, 3), np.float32)},
                 "bias": np.zeros(32, np.float32)}
        with pytest.raises(ValueError):
            sdelta.unflatten_like(other, vec, spec)

    def test_keep_for_ratio_bounds(self):
        assert sdelta.keep_for_ratio(1000, 0.01) == 10
        assert sdelta.keep_for_ratio(10, 0.0) == 1      # never zero
        assert sdelta.keep_for_ratio(10, 5.0) == 10     # never past n

    def test_topk_delta_set_semantics_and_early_exact(self):
        """Payloads carry current VALUES at the selected coordinates; when
        fewer coordinates changed than the budget, the delta is exact
        without running the packer."""
        rng = np.random.RandomState(2)
        last = rng.randn(256).astype(np.float32)
        vec = last.copy()
        touched = np.array([3, 77, 200])
        vec[touched] += 1.5
        idx, vals = sdelta.topk_delta(vec, last, keep=16)
        np.testing.assert_array_equal(np.sort(idx), touched)
        np.testing.assert_array_equal(vals, vec[np.sort(idx)])
        recon = last.copy()
        sdelta.apply_delta(recon, idx, vals)
        np.testing.assert_array_equal(recon, vec)   # bitwise: set, not add

    def test_residual_identity(self):
        """Transmitted coordinates zero their residual; untransmitted ones
        carry the full remaining drift — transmitted + residual accounts
        for the cumulative drift bitwise."""
        rng = np.random.RandomState(3)
        last = rng.randn(512).astype(np.float32)
        vec = (last + rng.randn(512).astype(np.float32) * 0.1).astype(
            np.float32)
        idx, vals = sdelta.topk_delta(vec, last, keep=32)
        after = last.copy()
        sdelta.apply_delta(after, idx, vals)
        res = sdelta.residual_of(vec, after)
        assert np.all(res[idx] == 0.0)
        mask = np.ones(512, bool)
        mask[idx] = False
        np.testing.assert_array_equal(res[mask], (vec - last)[mask])

    def test_flush_covers_every_bitwise_change(self):
        """The window-closing flush compares bit patterns, not values —
        -0.0 vs 0.0 and changed NaN payloads are transmitted too."""
        last = np.array([0.0, 1.0, np.nan, 2.0], np.float32)
        vec = np.array([-0.0, 1.0, np.nan, 3.0], np.float32)
        vec[2] = np.float32(np.frombuffer(
            np.array([0x7fc00001], np.uint32).tobytes(), np.float32)[0])
        idx, vals = sdelta.flush_delta(vec, last)
        assert 0 in idx and 3 in idx and 2 in idx and 1 not in idx
        recon = last.copy()
        sdelta.apply_delta(recon, idx, vals)
        assert np.array_equal(recon.view(np.int32), vec.view(np.int32))


# --------------------------------------------------------- window invariant

class TestWindowInvariant:
    def test_keyframe_plus_deltas_reconstruct_bitwise(self, tmp_path):
        """Tier-1 pin of the lossless invariant: at every window close,
        ``keyframe + deltas`` == the producer's params, bitwise; mid-window
        the reconstruction differs ONLY at untransmitted coordinates."""
        sd = str(tmp_path / "stream")
        rng = np.random.RandomState(4)
        params = _params(rng)
        w = StreamWriter(sd, ratio=0.05, keyframe_every=4, log=_quiet)
        r = StreamReader(sd, log=_quiet)
        closes = 0
        for step in range(1, 10):
            w.append(params, step=step)
            r.catch_up()
            man = read_segment_manifest(sd, w.head_seq)
            pvec, _ = sdelta.flatten_params(params)
            rvec, _ = sdelta.flatten_params(r.params_like(params))
            if man["window_close"]:
                closes += 1
                assert r.exact
                assert np.array_equal(pvec.view(np.int32),
                                      rvec.view(np.int32)), (
                    f"window close at seq {w.head_seq} not bitwise")
            else:
                # mid-window: residual_norm tracks what was withheld, and
                # any mismatch is confined to untransmitted coordinates
                diff = pvec.view(np.int32) != rvec.view(np.int32)
                payload = np.load(segment_payload_path(sd, w.head_seq))
                sent = set(np.asarray(payload["idx"]).tolist())
                assert sent.isdisjoint(np.flatnonzero(diff).tolist())
            params = _advance(params, rng)
        assert closes >= 2, "expected at least two window closes"
        # pattern: K D D F repeating for keyframe_every=4
        kinds = [read_segment_manifest(sd, q)["kind"]
                 for q in list_segments(sd)]
        assert kinds[:8] == ["keyframe", "delta", "delta", "delta",
                             "keyframe", "delta", "delta", "delta"]

    def test_sync_pins_bitwise_mid_window(self, tmp_path):
        sd = str(tmp_path / "stream")
        rng = np.random.RandomState(5)
        params = _params(rng)
        w = StreamWriter(sd, ratio=0.02, keyframe_every=8, log=_quiet)
        for step in range(1, 4):
            w.append(params, step=step)
            params = _advance(params, rng)
        w.sync(params, step=4)       # forced window-closing flush
        r = StreamReader(sd, log=_quiet)
        r.catch_up()
        assert r.exact and r.applied_step == 4
        _assert_bitwise(params, r.params_like(params), "sync pin")
        assert w.metrics()["stream/residual_norm"] == 0.0

    def test_async_appends_commit_in_order(self, tmp_path):
        sd = str(tmp_path / "stream")
        rng = np.random.RandomState(6)
        params = _params(rng)
        w = StreamWriter(sd, ratio=0.05, keyframe_every=4, log=_quiet)
        for step in range(1, 7):
            w.append_async(params, step=step)
            params = _advance(params, rng)
        w.drain()
        assert list_segments(sd) == list(range(6))
        assert read_head(sd)["seq"] == 5
        assert w.last_append_error is None
        w.close()

    def test_reopen_resumes_seq_and_forces_keyframe(self, tmp_path):
        """A relaunched producer continues the seq space and re-anchors
        with a keyframe — consumers never need the dead writer's window."""
        sd = str(tmp_path / "stream")
        rng = np.random.RandomState(7)
        params = _params(rng)
        w = StreamWriter(sd, ratio=0.05, keyframe_every=4, log=_quiet)
        for step in (1, 2):
            w.append(params, step=step)
            params = _advance(params, rng)
        w.close()
        w2 = StreamWriter(sd, ratio=0.05, keyframe_every=4, log=_quiet)
        seq = w2.append(params, step=3)
        assert seq == 2
        assert read_segment_manifest(sd, 2)["kind"] == "keyframe"
        r = StreamReader(sd, log=_quiet)
        r.catch_up()
        _assert_bitwise(params, r.params_like(params), "resume keyframe")
        w2.close()

    def test_reopen_never_overwrites_committed_but_unheaded_segment(
            self, tmp_path):
        """write_segment commits payload -> manifest -> head; a crash
        between the last two leaves a committed segment the head pointer
        never saw.  A restarted writer must continue PAST it — overwriting
        it would make a tailing reader (which already scanned that seq)
        skip the replacement keyframe and apply later deltas onto a wrong
        base while still reporting exact."""
        sd = str(tmp_path / "stream")
        rng = np.random.RandomState(17)
        params = _params(rng)
        w = StreamWriter(sd, ratio=0.05, keyframe_every=4, log=_quiet)
        for step in (1, 2, 3):
            w.append(params, step=step)
            params = _advance(params, rng)
        w.close()
        # roll the head pointer one seq back: the on-disk picture a crash
        # between the manifest and head commits leaves behind
        head = read_head(sd)
        with open(head_path(sd), "w") as f:
            json.dump({**head, "seq": head["seq"] - 1}, f)
        # a long-lived tailing reader has already scanned seq 2
        r = StreamReader(sd, log=_quiet)
        r.catch_up()
        assert r.applied_seq == 2
        w2 = StreamWriter(sd, ratio=0.05, keyframe_every=4, log=_quiet)
        seq = w2.append(params, step=4)
        assert seq == 3, "restart must not reuse the unheaded seq 2"
        assert read_segment_manifest(sd, 3)["kind"] == "keyframe"
        r.catch_up()
        _assert_bitwise(params, r.params_like(params),
                        "tailing reader across a torn-head restart")
        assert r.exact
        w2.close()

    def test_request_keyframe_re_anchors(self, tmp_path):
        sd = str(tmp_path / "stream")
        rng = np.random.RandomState(8)
        params = _params(rng)
        w = StreamWriter(sd, ratio=0.05, keyframe_every=32, log=_quiet)
        w.append(params, step=1)
        params = _advance(params, rng)
        w.request_keyframe()        # the Checkpointer tee calls this
        w.append(params, step=2)
        assert read_segment_manifest(sd, 1)["kind"] == "keyframe"
        w.close()


# --------------------------------------------------- store / fsck / prune

class TestStoreAndFsck:
    def _stream(self, tmp_path, n=9, keyframe_every=4, seed=9):
        sd = str(tmp_path / "stream")
        rng = np.random.RandomState(seed)
        params = _params(rng)
        w = StreamWriter(sd, ratio=0.05, keyframe_every=keyframe_every,
                         log=_quiet)
        for step in range(1, n + 1):
            w.append(params, step=step)
            params = _advance(params, rng)
        w.close()
        return sd, params

    def test_verify_stream_clean_and_corrupt(self, tmp_path):
        sd, _ = self._stream(tmp_path)
        problems, seqs = verify_stream(sd)
        assert problems == [] and seqs == list(range(9))
        _flip_payload(sd, 5)
        problems, _ = verify_stream(sd)
        assert any("segment 5" in p for p in problems)

    def test_reader_walks_back_and_recovers(self, tmp_path):
        """Torn mid-window delta: the consumer reverts to its stored
        keyframe bitwise and re-anchors at the next keyframe."""
        sd = str(tmp_path / "stream")
        rng = np.random.RandomState(10)
        params = _params(rng)
        w = StreamWriter(sd, ratio=0.05, keyframe_every=4, log=_quiet)
        w.append(params, step=1)                     # seq 0 keyframe
        kf = copy.deepcopy(params)
        params = _advance(params, rng)
        w.append(params, step=2)                     # seq 1 delta
        params = _advance(params, rng)
        w.append(params, step=3)                     # seq 2 delta
        _flip_payload(sd, 2)
        r = StreamReader(sd, log=_quiet)
        r.catch_up()
        assert r.metrics()["stream/corrupt_segments"] == 1.0
        assert r.applied_seq == 0
        _assert_bitwise(kf, r.params_like(kf), "walk-back")
        # next keyframe re-anchors; sync closes the window bitwise
        params = _advance(params, rng)
        w.append(params, step=4)                     # seq 3 flush (skipped)
        params = _advance(params, rng)
        w.append(params, step=5)                     # seq 4 keyframe
        w.sync(params, step=5)
        r.catch_up()
        assert r.exact
        _assert_bitwise(params, r.params_like(params), "re-anchor")
        w.close()

    def test_fresh_reader_seeks_past_dead_history(self, tmp_path):
        """A fresh consumer (rejoin, relaunched server) anchors at the
        newest verifiable keyframe — older windows are never read — and
        a corrupt head keyframe falls back to the previous verifiable
        one, scanning forward from there."""
        sd, _ = self._stream(tmp_path, n=9, keyframe_every=3)
        # seqs 0..8, keyframes at 0 / 3 / 6
        r = StreamReader(sd, log=_quiet)
        r.catch_up()
        assert r.segments_applied == 3       # the last window only: 6 7 8
        assert r.applied_seq == 8 and r.exact
        total = sum(read_segment_manifest(sd, q)["bytes"]
                    for q in list_segments(sd))
        assert 0 < r.bytes_read < total
        _flip_payload(sd, 6)
        r2 = StreamReader(sd, log=_quiet)
        r2.catch_up()
        assert r2.corrupt_segments == 1      # met seq 6 scanning forward
        assert r2.applied_seq == 3 and not r2.exact

    def test_torn_head_never_claims_exact_while_behind(self, tmp_path):
        """``exact`` on an unreadable head pointer falls back to the
        committed-segment listing: a reader a window behind must not
        label its snapshot bitwise-at-head just because the head tore."""
        sd = str(tmp_path / "stream")
        rng = np.random.RandomState(19)
        params = _params(rng)
        w = StreamWriter(sd, ratio=0.05, keyframe_every=4, log=_quiet)
        w.append(params, step=1)
        p2 = _advance(params, rng)
        w.sync(p2, step=2)
        r = StreamReader(sd, log=_quiet)
        r.catch_up()
        assert r.exact
        p3 = _advance(p2, rng)
        w.sync(p3, step=3)               # reader now one flush behind
        with open(head_path(sd), "w") as f:
            f.write("{torn")
        assert not r.exact               # behind + torn head != exact
        r.catch_up()
        assert r.exact                   # caught up: listing fallback
        _assert_bitwise(p3, r.params_like(p3), "post-tear catch-up")
        w.close()

    def test_no_verifiable_keyframe_raises(self, tmp_path):
        sd, _ = self._stream(tmp_path, n=2, keyframe_every=4)
        _flip_payload(sd, 0)     # the only keyframe
        with pytest.raises(StreamCorrupt):
            StreamReader(sd, log=_quiet).catch_up()
        # ...and warm rejoin degrades to the full-restore path

        @dataclasses.dataclass
        class Joiner:
            params: dict

        j = Joiner(params=_params(np.random.RandomState(9)))
        out, info = warm_rejoin(j, sd, log=_quiet)
        assert out is j and info is None

    def test_empty_dir_is_not_corrupt(self, tmp_path):
        sd = str(tmp_path / "empty")
        os.makedirs(sd)
        r = StreamReader(sd, log=_quiet)
        assert r.catch_up() == 0     # a polling consumer just waits
        assert not is_stream_dir(sd)

    def test_fsck_cli_on_streams(self, tmp_path):
        from tools import ckpt_fsck as fsck

        sd, _ = self._stream(tmp_path)
        assert fsck.main([sd]) == 0
        assert fsck.main([sd, "--list"]) == 0
        _flip_payload(sd, 5)
        assert fsck.main([sd]) == 1          # detected offline
        empty = str(tmp_path / "none")
        os.makedirs(empty)
        assert fsck.main([empty]) == 2

    def test_fsck_finds_stream_next_to_checkpoints(self, tmp_path):
        from tools import ckpt_fsck as fsck

        self._stream(tmp_path)               # <tmp>/stream
        assert fsck.main([str(tmp_path)]) == 0
        _flip_payload(str(tmp_path / "stream"), 3)
        assert fsck.main([str(tmp_path)]) == 1

    def test_prune_keeps_trailing_windows(self, tmp_path):
        from tools import ckpt_fsck as fsck

        sd, params = self._stream(tmp_path, n=12, keyframe_every=3)
        before = list_segments(sd)
        assert fsck.main([sd, "--prune", "--keep_windows", "1"]) == 0
        after = list_segments(sd)
        assert after and after[0] > before[0]
        assert read_segment_manifest(sd, after[0])["kind"] == "keyframe"
        # the surviving tail still reconstructs the producer bitwise
        problems, _ = verify_stream(sd)
        assert problems == []
        r = StreamReader(sd, log=_quiet)
        r.catch_up()
        rvec, _ = sdelta.flatten_params(r.params_like(params))

    def test_stat_keys_declared(self):
        from tpu_compressed_dp.obs import registry

        rng = np.random.RandomState(11)
        w = StreamWriter("/tmp/_unused_stream_dir_decl", log=_quiet)
        for k in list(w.metrics()) + ["stream/lag_s",
                                      "stream/corrupt_segments",
                                      "stream/rejoin_bytes"]:
            assert registry.is_declared(k), k


# --------------------------------------------------------- checkpoint tee

class TestCheckpointTee:
    def test_committed_save_requests_keyframe(self, tmp_path):
        """A committed full checkpoint re-anchors the delta window, so
        delta history never needs to span past the newest restore point."""
        import dataclasses as dc

        import jax.numpy as jnp

        from tpu_compressed_dp.train.optim import SGD
        from tpu_compressed_dp.train.state import TrainState
        from tpu_compressed_dp.utils.checkpoint import Checkpointer

        params = {"w": jnp.zeros((4,))}
        opt = SGD(lr=0.1)
        state = TrainState.create(params, {}, opt.init(params), (),
                                  jax.random.key(0))

        class StubStream:
            calls = 0

            def request_keyframe(self):
                StubStream.calls += 1

        ckpt = Checkpointer(str(tmp_path / "ck"))
        ckpt.stream = StubStream()
        ckpt.save(state, {"step": 1})
        state = dc.replace(state, step=state.step + 1)
        ckpt.save(state, {"step": 2})
        ckpt.close()
        assert StubStream.calls == 2

    def test_stream_failure_never_fails_a_save(self, tmp_path):
        import jax.numpy as jnp

        from tpu_compressed_dp.train.optim import SGD
        from tpu_compressed_dp.train.state import TrainState
        from tpu_compressed_dp.utils.checkpoint import Checkpointer

        params = {"w": jnp.zeros((4,))}
        opt = SGD(lr=0.1)
        state = TrainState.create(params, {}, opt.init(params), (),
                                  jax.random.key(0))

        class BadStream:
            def request_keyframe(self):
                raise RuntimeError("disk full")

        ckpt = Checkpointer(str(tmp_path / "ck"))
        ckpt.stream = BadStream()
        ckpt.save(state, {"step": 1})    # must not raise
        ckpt.close()
        assert os.path.isdir(str(tmp_path / "ck" / str(int(state.step))))


# ------------------------------------------------------- warm rejoin e2e

class TestWarmRejoinEndToEnd:
    def test_joiner_adopts_from_stream_bitwise(self, tmp_path, mesh8,
                                               monkeypatch):
        """The acceptance row: a joiner catches up from the delta stream
        (no full Orbax read on the warm path), announces the ``stream``
        flag through the rendezvous join record, adopts through
        ``join_world`` — and lands bitwise identical to a joiner that took
        the full-restore path."""
        from tools import chaos_drill

        from tpu_compressed_dp.parallel.dp import CompressionConfig
        from tpu_compressed_dp.train.elastic import (ElasticConfig,
                                                     ElasticRuntime)
        from tpu_compressed_dp.train.rendezvous import Rendezvous
        from tpu_compressed_dp.utils import checkpoint as ck

        comp = CompressionConfig(method="topk", ratio=0.25,
                                 error_feedback=True)
        state, step = chaos_drill._tiny_setup(mesh8, comp, None, None)
        batch = chaos_drill._batch()
        sd = str(tmp_path / "stream")
        cd = str(tmp_path / "ckpt")
        w = StreamWriter(sd, ratio=0.05, keyframe_every=8, log=_quiet)
        ckpt = ck.Checkpointer(cd)
        ckpt.stream = w
        for _ in range(3):
            state, _ = step(state, batch)
            w.append(jax.device_get(state.params), step=int(state.step))
        ckpt.save(state, {"step": int(state.step)})
        ckpt.close()
        # the survivor side of the barrier protocol: flush so the stream
        # head reconstructs the live params bitwise
        live_params = jax.device_get(state.params)
        w.sync(live_params, step=int(state.step))

        # scripted single-process rendezvous: the survivor (rank 1)
        # admits the joiner (rank 0) as soon as its join record — with
        # the stream flag — appears
        class Clock:
            t = 0.0

            def now(self):
                return Clock.t

            def sleep(self, s):
                Clock.t += s
                survivor_turn()

        clock = Clock()
        rd = str(tmp_path / "rdzv")
        surv = Rendezvous(rd, 1, now=clock.now, sleep=clock.sleep)
        joiner_rdzv = Rendezvous(rd, 0, now=clock.now, sleep=clock.sleep)
        committed = {}

        def survivor_turn():
            joins = surv.pending_joins()
            if 0 in joins and "d" not in committed:
                assert joins[0]["stream"] == w.head_seq
                # the survivors derive warm from the immutable join
                # records (+ the fleet-wide armed flag) and PUBLISH the
                # bit in the commit — both sides of the admission
                # broadcast pick their layout from the committed record
                committed["d"] = surv.propose(
                    [0, 1], voters=[1],
                    warm=joins[0].get("stream") is not None)

        # -- warm joiner: adopt from the stream; Orbax must not be read
        fresh, _ = chaos_drill._tiny_setup(mesh8, comp, None, None)
        host_fresh = jax.device_get(fresh.params)

        @dataclasses.dataclass
        class Probe:
            params: dict

        adopted, info = warm_rejoin(Probe(params=host_fresh), sd, log=_quiet)
        assert info is not None and info["exact"]
        # the fresh reader seeks to the newest verifiable keyframe: the
        # joiner pays for one window's tail, never the whole history
        assert info["bytes"] > 0
        assert 1 <= info["segments"] < len(list_segments(sd))
        assert info["seq"] == w.head_seq
        decision = joiner_rdzv.join(incarnation=1, stream_seq=info["seq"],
                                    deadline_s=30.0)
        assert decision is not None and decision.ranks == (0, 1)
        assert decision.warm, "commit must carry the warm layout bit"
        monkeypatch.setattr(
            ck.Checkpointer, "restore",
            lambda *a, **k: (_ for _ in ()).throw(
                AssertionError("warm path read Orbax")))

        # the single-process broadcast shortcut np.asarray's every leaf,
        # which typed PRNG keys refuse — fold the key to its raw data for
        # the scripted barrier (the real multi-process path ships buffers)
        def raw_rng(st):
            return dataclasses.replace(st, rng=jax.random.key_data(st.rng))

        el = ElasticRuntime(ElasticConfig(), mesh8, log=_quiet)
        warm_state = el.join_world(raw_rng(fresh), decision,
                                   adopted_params=adopted.params,
                                   adopted_info=info)
        assert el.metrics()["stream/rejoin_bytes"] == float(info["bytes"])
        monkeypatch.undo()
        _assert_bitwise(live_params, jax.device_get(warm_state.params),
                        "warm joiner vs survivor")

        # -- control joiner: full Orbax restore under a COLD commit (the
        # layout a fleet without unanimous stream flags agrees on)
        fresh2, _ = chaos_drill._tiny_setup(mesh8, comp, None, None)
        restore = ck.Checkpointer(cd)
        cold, _meta = restore.restore(fresh2)
        restore.close()
        el2 = ElasticRuntime(ElasticConfig(), mesh8, log=_quiet)
        cold_state = el2.join_world(
            raw_rng(cold), dataclasses.replace(decision, warm=False))
        _assert_bitwise(jax.device_get(cold_state.params),
                        jax.device_get(warm_state.params),
                        "warm joiner vs full-restore joiner")

        # a warm commit with no adoption in hand must refuse to join the
        # params-skipping collective (fresh-init params would be garbage)
        from tpu_compressed_dp.train.rendezvous import RendezvousError
        el3 = ElasticRuntime(ElasticConfig(), mesh8, log=_quiet)
        with pytest.raises(RendezvousError):
            el3.join_world(raw_rng(fresh2), decision)
        w.close()


# ------------------------------------------------------- harness plumbing

class TestHarnessPlumbing:
    def _args(self, extra=()):
        from tpu_compressed_dp.harness import loop

        p = argparse.ArgumentParser()
        loop.add_stream_args(p, cadence_help="test cadence")
        return p.parse_args(list(extra))

    def test_stream_args_defaults(self):
        a = self._args()
        assert a.stream_dir is None and a.stream_every == 1
        assert a.stream_keyframe_every == 8 and a.stream_ratio == 0.01
        assert a.stream_rejoin is False

    def test_make_stream_gating(self, tmp_path):
        from tpu_compressed_dp.harness import loop

        assert loop.make_stream(self._args()) is None
        a = self._args(["--stream_dir", str(tmp_path / "s")])
        w = loop.make_stream(a, log=_quiet)
        assert isinstance(w, StreamWriter)
        w.close()

    def test_stream_join_seq_probe(self, tmp_path):
        from tpu_compressed_dp.harness import loop

        sd = str(tmp_path / "s")
        rng = np.random.RandomState(12)
        params = _params(rng)
        w = StreamWriter(sd, ratio=0.05, keyframe_every=4, log=_quiet)
        w.sync(params, step=1)
        w.close()
        # no --stream_rejoin => no probe
        assert loop.stream_join_seq(
            self._args(["--stream_dir", sd])) is None
        a = self._args(["--stream_dir", sd, "--stream_rejoin"])
        assert loop.stream_join_seq(a) == 0
        # an unusable stream degrades to a cold join, not a crash
        _flip_payload(sd, 0)
        assert loop.stream_join_seq(a) is None

    def test_rejoin_params_respects_cold_commit(self, tmp_path):
        """The joiner's catch-up obeys the COMMITTED warm bit: a cold
        admission skips the stream outright (the survivors take the full
        broadcast layout, so an adoption would be discarded anyway)."""
        from tpu_compressed_dp.harness.loop import stream_rejoin_params
        from tpu_compressed_dp.train.rendezvous import EpochDecision

        sd = str(tmp_path / "s")
        rng = np.random.RandomState(21)
        w = StreamWriter(sd, ratio=0.05, keyframe_every=4, log=_quiet)
        w.sync(_params(rng), step=1)
        w.close()
        a = self._args(["--stream_dir", sd, "--stream_rejoin"])
        cold = EpochDecision(epoch=1, ranks=(0, 1), coordinator=1,
                             address="h:1", process_id=0, warm=False)
        assert stream_rejoin_params(a, None, cold, log=_quiet) == (None,
                                                                   None)

    def test_elastic_runtime_warm_layout_is_fleet_shared(self, mesh8):
        """The barrier layout keys on ``stream_armed`` (a fleet-wide
        fact), never on holding the writer: a survivor WITHOUT the
        process-0 StreamWriter must still compute the warm layout."""
        from tpu_compressed_dp.train.elastic import (ElasticConfig,
                                                     ElasticRuntime)

        el = ElasticRuntime(ElasticConfig(), mesh8, log=_quiet,
                            stream=None, stream_armed=True)
        assert el.stream_armed and el.stream is None
        # directly-constructed runtimes (drills) follow the writer
        assert not ElasticRuntime(ElasticConfig(), mesh8,
                                  log=_quiet).stream_armed

    def test_all_harnesses_expose_stream_flags(self):
        for mod in ("dawn", "imagenet", "lm"):
            h = __import__(f"tpu_compressed_dp.harness.{mod}",
                           fromlist=[mod])
            p = h.build_parser()
            a = p.parse_args(["--stream_dir", "/tmp/x", "--stream_rejoin"])
            assert a.stream_dir == "/tmp/x" and a.stream_rejoin


# -------------------------------------------------------------- serve CLI

class TestServeCLI:
    def test_once_snapshot_and_heartbeat(self, tmp_path):
        from tools import stream_serve

        sd = str(tmp_path / "stream")
        rng = np.random.RandomState(13)
        params = _params(rng)
        w = StreamWriter(sd, ratio=0.05, keyframe_every=4, log=_quiet)
        for s in (1, 2):
            w.append(params, step=s)
            params = _advance(params, rng)
        w.sync(params, step=3)
        w.close()
        snap = str(tmp_path / "snap")
        hb = str(tmp_path / "hb.json")
        rc = stream_serve.main([sd, "--once", "--snapshot_dir", snap,
                                "--heartbeat", hb])
        assert rc == 0
        with np.load(os.path.join(snap, "snapshot-3.npz")) as z:
            got = {k: z[k] for k in z.files}
        vec, spec = sdelta.flatten_params(params)
        want = sdelta.unflatten_dict(vec, spec)
        assert set(got) == set(want)
        for k in want:
            assert np.array_equal(got[k], want[k]), k
        rec = json.load(open(hb))
        assert rec["exact"] is True and rec["applied_step"] == 3
        assert rec["stream_lag_s"] >= 0.0

    def test_exit_codes(self, tmp_path):
        from tools import stream_serve

        assert stream_serve.main([str(tmp_path / "nope"), "--once"]) == 2
        empty = str(tmp_path / "empty")
        os.makedirs(empty)
        assert stream_serve.main([empty, "--once"]) == 2
        sd = str(tmp_path / "stream")
        w = StreamWriter(sd, keyframe_every=4, log=_quiet)
        w.sync(_params(np.random.RandomState(14)), step=1)
        w.close()
        assert stream_serve.main([sd, "--once"]) == 0
        _flip_payload(sd, 0)
        assert stream_serve.main([sd, "--once"]) == 1
