"""Test fixture: an 8-device virtual CPU mesh (SURVEY.md §4).

Environment must be set before the first `import jax` anywhere in the test
process, hence module scope here.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_backend_optimization_level" not in _flags:
    # tests are compile-time dominated on the CPU backend; O0 keeps XLA
    # semantics while cutting suite wall time ~2.5x (VERDICT r1 weak #5).
    # NB the CI host has ONE cpu core (nproc=1): every compile serializes,
    # xdist can't help, and the persistent compilation cache doesn't engage
    # on the CPU backend — full-suite wall time is bounded by total compile
    # work (~15 min here; minutes on a normal multi-core host).
    _flags = (_flags + " --xla_backend_optimization_level=0").strip()
os.environ["XLA_FLAGS"] = _flags

import jax  # noqa: E402
import pytest  # noqa: E402

# The environment may have imported jax at interpreter startup (sitecustomize
# PJRT plugins), capturing JAX_PLATFORMS before this module ran — force the
# platform through the config as well, which works until first backend use.
jax.config.update("jax_platforms", "cpu")


def pytest_collection_modifyitems(config, items):
    """Run the ``imports_smoke`` tests first: a broken import then fails in
    seconds as one named test instead of as 20 opaque collection errors at
    the end of the run."""
    items.sort(key=lambda it: 0 if it.get_closest_marker("imports_smoke")
               else 1)


@pytest.fixture(scope="session")
def mesh8():
    from tpu_compressed_dp.parallel.mesh import make_data_mesh

    assert len(jax.devices()) >= 8, "expected 8 virtual CPU devices"
    return make_data_mesh(8)
