"""Tests for the compressed-DP gradient sync engine on an 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from tpu_compressed_dp.compat import shard_map

from tpu_compressed_dp.parallel.dp import CompressionConfig, init_ef_state, make_grad_sync


def run_sync(mesh, cfg, grads_per_dev, ef=None, seed=0, comp=None):
    """grads_per_dev: pytree whose leaves have leading dim 8 (one slice per device)."""
    from tpu_compressed_dp.parallel.dp import init_comp_state

    sync = make_grad_sync(cfg, "data")
    if ef is None:
        ef = init_ef_state(jax.tree.map(lambda g: g[0], grads_per_dev), cfg)
    if comp is None:
        comp = init_comp_state(jax.tree.map(lambda g: g[0], grads_per_dev), cfg)

    def f(g, e, c):
        out, new_ef, new_comp, stats = sync(g, e, c, jax.random.key(seed))
        return out, new_ef, new_comp, stats

    shard_spec = jax.tree.map(lambda _: P("data"), grads_per_dev)
    # one slice per device in, replicated grads out
    fn = shard_map(
        lambda g, e, c: f(jax.tree.map(lambda x: x[0], g), e, c),
        mesh=mesh,
        in_specs=(shard_spec, P(), P()),
        out_specs=(P(), P(), P(), P()),
        check_vma=False,
    )
    out, new_ef, new_comp, stats = fn(grads_per_dev, ef, comp)
    return out, new_ef, stats


def make_grads(shape_leading=8, n=64, seed=0):
    k = jax.random.key(seed)
    return {
        "w": jax.random.normal(k, (shape_leading, n), jnp.float32),
        "b": jax.random.normal(jax.random.fold_in(k, 1), (shape_leading, 8), jnp.float32),
    }


class TestDense:
    def test_dense_sync_is_mean(self, mesh8):
        cfg = CompressionConfig(method=None)
        grads = make_grads()
        out, _, stats = run_sync(mesh8, cfg, grads)
        np.testing.assert_allclose(np.asarray(out["w"]), np.asarray(grads["w"]).mean(0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(out["b"]), np.asarray(grads["b"]).mean(0), rtol=1e-5)
        assert float(stats["sent_elems"]) >= 0

    def test_entiremodel_dense_matches_layerwise(self, mesh8):
        grads = make_grads()
        out_l, _, _ = run_sync(mesh8, CompressionConfig(method=None, granularity="layerwise"), grads)
        out_e, _, _ = run_sync(mesh8, CompressionConfig(method=None, granularity="entiremodel"), grads)
        for k in out_l:
            np.testing.assert_allclose(np.asarray(out_l[k]), np.asarray(out_e[k]), rtol=1e-5)


class TestCompressed:
    @pytest.mark.parametrize("gran", ["layerwise", "entiremodel"])
    def test_topk_sync(self, mesh8, gran):
        cfg = CompressionConfig(method="topk", ratio=0.25, granularity=gran)
        grads = make_grads()
        out, _, stats = run_sync(mesh8, cfg, grads)
        # Every device compresses its own slice then the results are averaged:
        # reconstruct expected value with the numpy reference.
        from tpu_compressed_dp.ops import compressors as C

        if gran == "layerwise":
            exp_w = np.mean(
                [np.asarray(C.top_k(grads["w"][d], ratio=0.25)) for d in range(8)], axis=0
            )
            np.testing.assert_allclose(np.asarray(out["w"]), exp_w, rtol=1e-5)
        assert float(stats["sent_elems"]) < float(stats["dense_elems"])

    def test_randomk_per_worker_masks_differ_in_simulate(self, mesh8):
        # simulate mode folds the worker index into the key (unseeded CIFAR
        # harness analog): per-device masks differ, so the averaged result has
        # more nonzeros than one mask's worth.
        cfg = CompressionConfig(method="randomk", ratio=0.25, granularity="layerwise")
        grads = {"w": jnp.ones((8, 256), jnp.float32)}
        out, _, _ = run_sync(mesh8, cfg, grads)
        nnz = int(jnp.count_nonzero(out["w"]))
        assert nnz > 64  # > one mask's keep count => masks differed across devices

    def test_randomk_shared_mask(self, mesh8):
        cfg = CompressionConfig(method="randomk", ratio=0.25, shared_mask=True)
        grads = {"w": jnp.ones((8, 256), jnp.float32)}
        out, _, _ = run_sync(mesh8, cfg, grads)
        nnz = int(jnp.count_nonzero(out["w"]))
        assert nnz == 64  # identical masks across devices

    def test_num_collectives(self, mesh8):
        grads = make_grads()
        _, _, s_l = run_sync(mesh8, CompressionConfig(method="topk", ratio=0.5), grads)
        _, _, s_e = run_sync(
            mesh8, CompressionConfig(method="topk", ratio=0.5, granularity="entiremodel"), grads
        )
        assert float(s_l["num_collectives"]) == 2.0  # one per parameter tensor
        assert float(s_e["num_collectives"]) == 1.0  # one for the whole model


class TestErrorFeedback:
    def test_residual_property(self, mesh8):
        # compressed + residual == accumulated gradient, per leaf per device.
        cfg = CompressionConfig(method="topk", ratio=0.25, error_feedback=True, shared_mask=True)
        grads = make_grads()
        out, new_ef, _ = run_sync(mesh8, cfg, grads)
        assert set(new_ef.keys()) == {"w", "b"}
        # After one step from zero EF: residual = g_local - compress(g_local).
        # Top-K is deterministic, so recompute device 0's compression directly.
        from tpu_compressed_dp.ops import compressors as C

        for leaf in ("w", "b"):
            g0 = np.asarray(grads[leaf])[0]
            res0 = np.asarray(new_ef[leaf])
            comp0 = np.asarray(C.top_k(jnp.asarray(g0), ratio=0.25))
            np.testing.assert_allclose(res0, g0 - comp0, rtol=1e-6)

    def test_ef_accumulates_small_grads(self, mesh8):
        # A coordinate never selected by Top-K accumulates in the residual so
        # it is eventually sent (the EF convergence mechanism).
        cfg = CompressionConfig(method="topk", ratio=0.05, error_feedback=True)
        g = jnp.concatenate([jnp.full((5,), 10.0), jnp.linspace(0.01, 0.1, 95)])
        grads = {"w": jnp.tile(g[None, :], (8, 1))}
        ef = {"w": jnp.zeros((100,), jnp.float32)}
        out, ef1, _ = run_sync(mesh8, cfg, grads, ef=ef)
        # small coords went to residual
        assert float(jnp.sum(jnp.abs(ef1["w"]))) > 0
        out2, ef2, _ = run_sync(mesh8, cfg, grads, ef=ef1, seed=1)
        # residual keeps growing for untransmitted coords
        assert float(jnp.max(ef2["w"])) >= float(jnp.max(ef1["w"]))


class TestBucketedGranularity:
    """granularity='bucketed': the reference DDP's static 25MB bucketing
    (`ddp.py:188,238-241`) — contiguous leaves concatenated into capped
    groups, one operator + one collective per bucket."""

    def test_make_leaf_groups(self):
        from tpu_compressed_dp.parallel.dp import make_leaf_groups

        # byte sizes (size * itemsize), ADVICE r1: bf16 leaves pack at their
        # real density, not a hardcoded 4 bytes/elem
        sizes = [400, 400, 1200, 200, 2400, 40]
        groups = make_leaf_groups(sizes, "bucketed", 800.0)
        assert groups == [[0, 1], [2], [3], [4], [5]]
        assert make_leaf_groups(sizes, "layerwise", 800.0) == [[i] for i in range(6)]
        assert make_leaf_groups(sizes, "entiremodel", 800.0) == [list(range(6))]
        assert make_leaf_groups([], "entiremodel", 800.0) == []
        # oversized single leaf still gets its own bucket
        assert make_leaf_groups([10**9], "bucketed", 800.0) == [[0]]
        # half-width leaves fill a bucket at twice the element count
        assert make_leaf_groups([400, 400, 400, 400], "bucketed", 800.0) == [
            [0, 1], [2, 3]]

    def test_mixed_dtype_group_keeps_leaf_dtypes_and_fp32_ef(self, mesh8):
        # ADVICE r1: concatenating bf16+fp32 leaves promotes; the synced
        # grads must come back at each leaf's dtype while the EF residual
        # stays fp32 (sub-bf16-epsilon dropped mass must accumulate).
        k = jax.random.key(3)
        grads = {
            "a": jax.random.normal(k, (8, 48), jnp.float32).astype(jnp.bfloat16),
            "b": jax.random.normal(jax.random.fold_in(k, 1), (8, 32), jnp.float32),
        }
        cfg = CompressionConfig(method="topk", ratio=0.25, granularity="bucketed",
                                bucket_mb=1e-3, error_feedback=True)
        out, new_ef, _ = run_sync(mesh8, cfg, grads)
        assert out["a"].dtype == jnp.bfloat16 and out["b"].dtype == jnp.float32
        assert new_ef["a"].dtype == jnp.float32 and new_ef["b"].dtype == jnp.float32

    def test_dense_bucketed_equals_layerwise(self, mesh8):
        grads = make_grads()
        cfg_b = CompressionConfig(method=None, granularity="bucketed", bucket_mb=1e-4)
        cfg_l = CompressionConfig(method=None, granularity="layerwise")
        out_b, _, stats_b = run_sync(mesh8, cfg_b, grads)
        out_l, _, _ = run_sync(mesh8, cfg_l, grads)
        for leaf in out_b:
            np.testing.assert_allclose(
                np.asarray(out_b[leaf]), np.asarray(out_l[leaf]), rtol=1e-6)

    def test_bucket_count_and_collectives(self, mesh8):
        # leaves: w 64 elems (256B), b 8 elems (32B); capacity 256B -> 2 buckets
        grads = make_grads()
        cfg = CompressionConfig(method="topk", ratio=0.25, granularity="bucketed",
                                bucket_mb=256 / 1e6, shared_mask=True)
        _, _, stats = run_sync(mesh8, cfg, grads)
        assert float(stats["num_collectives"]) == 2.0
        # huge capacity -> one bucket, entiremodel-equivalent selection
        cfg1 = CompressionConfig(method="topk", ratio=0.25, granularity="bucketed",
                                 bucket_mb=25.0, shared_mask=True)
        out1, _, stats1 = run_sync(mesh8, cfg1, grads)
        cfg_e = CompressionConfig(method="topk", ratio=0.25, granularity="entiremodel",
                                  shared_mask=True)
        out_e, _, _ = run_sync(mesh8, cfg_e, grads)
        assert float(stats1["num_collectives"]) == 1.0
        for leaf in out1:
            np.testing.assert_allclose(
                np.asarray(out1[leaf]), np.asarray(out_e[leaf]), rtol=1e-6)

    def test_ef_residual_identity_bucketed(self, mesh8):
        # residual + transmitted == accumulated gradient, per worker
        grads = make_grads()
        cfg = CompressionConfig(method="topk", ratio=0.25, granularity="bucketed",
                                bucket_mb=256 / 1e6, error_feedback=True)
        out, ef1, _ = run_sync(mesh8, cfg, grads)
        from tpu_compressed_dp.ops.compressors import topk_keep_count

        g0 = np.asarray(grads["w"])[0]
        k = topk_keep_count(64, 0.25)
        idx = np.argsort(-np.abs(g0))[:k]
        exp_res = g0.copy()
        exp_res[idx] = 0.0
        np.testing.assert_allclose(np.asarray(ef1["w"]), exp_res, rtol=1e-5)

    def test_rejects_bad_bucket_mb(self):
        with pytest.raises(ValueError, match="bucket_mb"):
            CompressionConfig(method="topk", granularity="bucketed", bucket_mb=0.0)


class TestFusedSimulateEpilogue:
    def test_fused_topk_path_matches_unfused(self, mesh8, monkeypatch):
        """The TPU-only fused sparsify epilogue must produce identical synced
        grads, EF residuals, and comm stats to the unfused chain (forced on
        via interpret-mode here; CPU CI never dispatches it otherwise)."""
        import functools
        from tpu_compressed_dp.ops import kernels

        grads = make_grads(n=700)
        cfg = CompressionConfig(method="topk", ratio=0.1,
                                granularity="entiremodel", error_feedback=True)
        out_ref, ef_ref, stats_ref = run_sync(mesh8, cfg, grads)

        monkeypatch.setattr(kernels, "use_fused_sparsify", lambda n: True)
        monkeypatch.setattr(kernels, "fused_sparsify",
                            functools.partial(kernels.fused_sparsify,
                                              interpret=True))
        out_f, ef_f, stats_f = run_sync(mesh8, cfg, grads)
        for k in out_ref:
            np.testing.assert_allclose(np.asarray(out_ref[k]),
                                       np.asarray(out_f[k]), rtol=1e-6)
            np.testing.assert_allclose(np.asarray(ef_ref[k]),
                                       np.asarray(ef_f[k]), rtol=1e-6)
        assert float(stats_f["sent_elems"]) == float(stats_ref["sent_elems"])
        assert float(stats_f["sent_bits"]) == float(stats_ref["sent_bits"])


@pytest.mark.quick
class TestTerngradChunkResolution:
    """terngrad_chunk=-1 (auto, ADVICE r3): layerwise keeps the reference's
    exact per-tensor global-max semantics on every leaf size; chunked scales
    apply only where the reference has no working behavior to match."""

    def test_auto_layerwise_is_per_tensor_max(self):
        assert CompressionConfig(method="terngrad",
                                 granularity="layerwise").resolved_terngrad_chunk == 0

    def test_auto_entiremodel_and_bucketed_chunk(self):
        for gran in ("entiremodel", "bucketed"):
            assert CompressionConfig(
                method="terngrad",
                granularity=gran).resolved_terngrad_chunk == 1 << 21

    def test_explicit_value_wins(self):
        for gran in ("layerwise", "entiremodel"):
            cfg = CompressionConfig(method="terngrad", granularity=gran,
                                    terngrad_chunk=4096)
            assert cfg.resolved_terngrad_chunk == 4096
        assert CompressionConfig(
            method="terngrad", granularity="entiremodel",
            terngrad_chunk=0).resolved_terngrad_chunk == 0
