"""In-repo flash attention kernel (ops/flash_attention.py): interpret-mode
parity of forward AND backward against the exact online-softmax reference —
the kernel is the dispatched single-block attention path of the LM step, so
a sign/transpose slip in the hand-written VJP would corrupt training
gradients silently."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import tpu_compressed_dp.ops.ring_attention as ra_mod
from tpu_compressed_dp.ops.flash_attention import flash_causal_attention


def exact(q, k, v):
    old = ra_mod._FUSED_ATTN
    ra_mod._FUSED_ATTN = False
    try:
        return ra_mod.ring_attention(q, k, v)
    finally:
        ra_mod._FUSED_ATTN = old


@pytest.mark.parametrize(
    "shape",
    [
        (1, 2, 128, 64),    # padded head_dim (lse rides the pad lanes)
        (2, 1, 256, 128),   # unpadded head_dim (lse gets its own tile)
        (1, 1, 384, 64),    # seq needs the reduced 128 block
    ],
)
def test_forward_and_grads_match_exact(shape):
    B, H, T, D = shape
    ks = jax.random.split(jax.random.key(0), 4)
    q, k, v = (jax.random.normal(kk, shape, jnp.float32) * 0.5
               for kk in ks[:3])
    o_f = flash_causal_attention(q, k, v, None, True)
    o_e = exact(q, k, v)
    np.testing.assert_allclose(np.asarray(o_f), np.asarray(o_e), atol=1e-5)

    tgt = jax.random.normal(ks[3], shape)
    lf = lambda q, k, v: jnp.mean(
        (flash_causal_attention(q, k, v, None, True) - tgt) ** 2)
    le = lambda q, k, v: jnp.mean((exact(q, k, v) - tgt) ** 2)
    gf = jax.grad(lf, (0, 1, 2))(q, k, v)
    ge = jax.grad(le, (0, 1, 2))(q, k, v)
    for a, b, nm in zip(gf, ge, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5,
                                   err_msg=f"d{nm}")


def test_streamed_dkv_matches_resident(monkeypatch):
    """The DMA/double-buffered dkv kernel (`_dkv_kernel_streamed`) against
    the VMEM-resident form, both under interpret: the streamed path is the
    only one real TPU runs take for the backward, but interpret mode (the
    only CI-runnable path) defaulted to the resident kernel — so the
    explicit-DMA machinery had zero off-chip coverage (ADVICE r5).
    `TPU_CDP_FORCE_STREAMED_DKV=1` runs it under the Pallas interpreter;
    the two must agree to fp32 roundoff (identical math via
    `_dkv_block_math`, different operand staging)."""
    shape = (1, 2, 256, 64)
    ks = jax.random.split(jax.random.key(3), 4)
    q, k, v = (jax.random.normal(kk, shape, jnp.float32) * 0.5
               for kk in ks[:3])
    tgt = jax.random.normal(ks[3], shape)

    def loss(q, k, v):
        return jnp.mean((flash_causal_attention(q, k, v, None, True) - tgt) ** 2)

    monkeypatch.delenv("TPU_CDP_FORCE_STREAMED_DKV", raising=False)
    g_resident = jax.grad(loss, (0, 1, 2))(q, k, v)
    monkeypatch.setenv("TPU_CDP_FORCE_STREAMED_DKV", "1")
    g_streamed = jax.grad(loss, (0, 1, 2))(q, k, v)
    for a, b, nm in zip(g_streamed, g_resident, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6,
                                   err_msg=f"d{nm} streamed vs resident")
