"""Transformer / ring-attention / LM-step tests on the virtual 8-CPU mesh.

The load-bearing property: the sharded model (tensor-parallel layers, ring
attention over the sequence axis, vocab-parallel loss) computes the SAME
function as the plain single-device forward — parallelism must be a layout
choice, not a semantics change.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from tpu_compressed_dp import compat
from tpu_compressed_dp.compat import shard_map

# compile-dominated on the 1-core CI host (~7 min alone vs the 870 s tier-1
# budget for the whole suite): excluded from `-m 'not slow'`, runs in the
# unfiltered suite on real hardware
pytestmark = pytest.mark.slow

from tpu_compressed_dp.models import transformer as tf
from tpu_compressed_dp.ops.ring_attention import dense_causal_attention, ring_attention


def _mesh(d, s, t):
    from tpu_compressed_dp.train.lm_step import make_lm_mesh

    return make_lm_mesh(d, s, t)


class TestRingAttention:
    def test_single_block_matches_naive(self):
        k1, k2, k3 = jax.random.split(jax.random.key(0), 3)
        q = jax.random.normal(k1, (2, 4, 16, 8))
        k = jax.random.normal(k2, (2, 4, 16, 8))
        v = jax.random.normal(k3, (2, 4, 16, 8))
        out = dense_causal_attention(q, k, v)
        # naive reference
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(8)
        mask = jnp.tril(jnp.ones((16, 16), bool))
        s = jnp.where(mask, s, -jnp.inf)
        ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_gqa_head_repeat(self):
        k1, k2, k3 = jax.random.split(jax.random.key(1), 3)
        q = jax.random.normal(k1, (1, 4, 8, 8))
        k = jax.random.normal(k2, (1, 2, 8, 8))
        v = jax.random.normal(k3, (1, 2, 8, 8))
        out = dense_causal_attention(q, k, v)
        ref = dense_causal_attention(q, jnp.repeat(k, 2, 1), jnp.repeat(v, 2, 1))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)

    def test_fused_gate_is_shape_and_backend_aware(self):
        """The fused flash path engages only on TPU at lane-multiple seq and
        MXU-friendly head_dim; on the CPU test backend it must stay off so
        dense_causal_attention remains the independent reference."""
        from tpu_compressed_dp.ops.ring_attention import use_fused_attention

        on_tpu = jax.default_backend() == "tpu"
        assert use_fused_attention((8, 12, 1024, 64), (8, 12, 1024, 64)) == on_tpu
        # never at these shapes, regardless of backend:
        assert not use_fused_attention((8, 12, 1000, 64), (8, 12, 1000, 64))
        # in-repo kernel tiles any 128-multiple seq (768 -> block 128)
        assert use_fused_attention((8, 12, 768, 64), (8, 12, 768, 64)) == on_tpu
        # VMEM gate: the dkv backward holds full Q + packed cotangent
        assert not use_fused_attention((1, 1, 1 << 15, 128),
                                       (1, 1, 1 << 15, 128))
        assert not use_fused_attention((8, 12, 64, 64), (8, 12, 64, 64))
        assert not use_fused_attention((8, 12, 1024, 80), (8, 12, 1024, 80))
        assert not use_fused_attention((8, 12, 1024, 64), (8, 12, 512, 64))

    @pytest.mark.skipif(jax.default_backend() != "tpu",
                        reason="fused flash path engages on TPU only")
    def test_fused_matches_exact_on_tpu(self):  # pragma: no cover - TPU-only
        import tpu_compressed_dp.ops.ring_attention as mod

        keys = jax.random.split(jax.random.key(5), 3)
        q = jax.random.normal(keys[0], (2, 4, 256, 64))
        k = jax.random.normal(keys[1], (2, 4, 256, 64))
        v = jax.random.normal(keys[2], (2, 4, 256, 64))
        fused = ring_attention(q, k, v)
        old = mod._FUSED_ATTN
        mod._FUSED_ATTN = False
        try:
            exact = ring_attention(q, k, v)
        finally:
            mod._FUSED_ATTN = old
        np.testing.assert_allclose(np.asarray(fused), np.asarray(exact),
                                   atol=5e-5)

    @pytest.mark.parametrize("ring", [2, 4])
    def test_ring_matches_dense(self, ring):
        mesh = jax.make_mesh((ring,), ("seq",))
        keys = jax.random.split(jax.random.key(2), 3)
        T = 32
        q = jax.random.normal(keys[0], (2, 4, T, 8))
        k = jax.random.normal(keys[1], (2, 4, T, 8))
        v = jax.random.normal(keys[2], (2, 4, T, 8))
        ref = dense_causal_attention(q, k, v)
        ringed = shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis_name="seq"),
            mesh=mesh,
            in_specs=(P(None, None, "seq"), P(None, None, "seq"), P(None, None, "seq")),
            out_specs=P(None, None, "seq"),
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(ringed), np.asarray(ref), atol=1e-5)


class TestVocabParallelXent:
    def test_matches_dense(self):
        mesh = jax.make_mesh((4,), ("tensor",))
        logits = jax.random.normal(jax.random.key(3), (2, 8, 64))
        targets = jax.random.randint(jax.random.key(4), (2, 8), 0, 64)
        ref = float(tf.vocab_parallel_xent(logits, targets))
        # dense softmax cross-check
        logz = jax.nn.log_softmax(logits)
        want = float(-jnp.mean(jnp.take_along_axis(logz, targets[..., None], -1)))
        assert ref == pytest.approx(want, rel=1e-5)
        sharded = shard_map(
            lambda z, t: tf.vocab_parallel_xent(z, t, tensor_axis="tensor"),
            mesh=mesh,
            in_specs=(P(None, None, "tensor"), P()),
            out_specs=P(),
        )(logits, targets)
        assert float(sharded) == pytest.approx(want, rel=1e-5)


class TestLlamaParity:
    def setup_method(self):
        # fp32 everywhere so the sharded/unsharded comparison is tight
        self.cfg = tf.LlamaConfig(vocab_size=64, dim=32, n_layers=2, n_heads=4,
                                  n_kv_heads=2, ffn_hidden=64, dtype=jnp.float32)
        self.params = tf.init_llama(self.cfg, jax.random.key(0))
        self.tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, 64)

    def test_sharded_forward_matches_single_device(self):
        ref = tf.apply_llama(self.cfg, self.params, self.tokens)
        mesh = _mesh(2, 2, 2)
        sharded = shard_map(
            lambda p, t: tf.apply_llama(self.cfg, p, t, tensor_axis="tensor",
                                        seq_axis="seq"),
            mesh=mesh,
            in_specs=(tf.param_specs(self.cfg), P("data", "seq")),
            out_specs=P("data", "seq", "tensor"),
        )(self.params, self.tokens)
        np.testing.assert_allclose(np.asarray(sharded), np.asarray(ref),
                                   atol=2e-4, rtol=2e-4)

    def test_sharded_loss_matches_single_device(self):
        # 17 tokens -> (x, y) shifted pairs of length 16 (divisible by seq=2)
        toks = jax.random.randint(jax.random.key(5), (4, 17), 0, 64)
        x, y = toks[:, :-1], toks[:, 1:]
        ref = float(tf.vocab_parallel_xent(
            tf.apply_llama(self.cfg, self.params, x), y))
        mesh = _mesh(2, 2, 2)

        def f(p, x, y):
            z = tf.apply_llama(self.cfg, p, x, tensor_axis="tensor", seq_axis="seq")
            loss = tf.vocab_parallel_xent(z, y, tensor_axis="tensor")
            # equal per-worker token counts -> pmean of local means == global mean
            return jax.lax.pmean(loss, ("data", "seq"))

        got = float(shard_map(
            f, mesh=mesh,
            in_specs=(tf.param_specs(self.cfg), P("data", "seq"), P("data", "seq")),
            out_specs=P(),
        )(self.params, x, y))
        assert got == pytest.approx(ref, rel=1e-4)


class TestLMTrainStep:
    def _setup(self, comp_kwargs, d=2, s=2, t=2):
        from tpu_compressed_dp.parallel.dp import CompressionConfig
        from tpu_compressed_dp.train.lm_step import (
            init_lm_ef_state, make_lm_train_step,
        )
        from tpu_compressed_dp.train.optim import SGD
        from tpu_compressed_dp.train.state import TrainState

        cfg = tf.LlamaConfig(vocab_size=64, dim=32, n_layers=2, n_heads=4,
                             n_kv_heads=2, ffn_hidden=64, dtype=jnp.float32)
        mesh = _mesh(d, s, t)
        params = tf.init_llama(cfg, jax.random.key(0))
        opt = SGD(lr=0.1, momentum=0.9)
        comp = CompressionConfig(**comp_kwargs)
        state = TrainState.create(
            params, {}, opt.init(params),
            init_lm_ef_state(cfg, params, comp, mesh), jax.random.key(1),
        )
        step = make_lm_train_step(cfg, opt, comp, mesh)
        batch = {
            "input": jax.random.randint(jax.random.key(2), (4, 16), 0, 64),
            "target": jax.random.randint(jax.random.key(3), (4, 16), 0, 64),
        }
        return cfg, state, step, batch

    def test_dense_step_learns(self):
        cfg, state, step, batch = self._setup({"method": None})
        losses = []
        for _ in range(8):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        assert int(state.step) == 8
        assert losses[-1] < losses[0]  # memorises the fixed batch
        assert float(m["tokens"]) == 4 * 16

    def test_entiremodel_topk_ef_step(self):
        cfg, state, step, batch = self._setup({
            "method": "topk", "granularity": "entiremodel", "ratio": 0.01,
            "error_feedback": True,
        })
        state, m = step(state, batch)
        assert np.isfinite(float(m["loss"]))
        assert float(m["comm/sent_elems"]) / float(m["comm/dense_elems"]) == \
            pytest.approx(0.01, rel=0.05)
        # EF residual became nonzero (dropped coordinates stored)
        ef_norm = sum(float(jnp.sum(jnp.abs(e))) for e in jax.tree.leaves(state.ef))
        assert ef_norm > 0

    def test_wire_randomk_step(self):
        cfg, state, step, batch = self._setup({
            "method": "randomk", "granularity": "entiremodel", "ratio": 0.05,
            "mode": "wire", "error_feedback": True,
        })
        state, m = step(state, batch)
        assert np.isfinite(float(m["loss"]))
        assert float(m["comm/sent_elems"]) / float(m["comm/dense_elems"]) == \
            pytest.approx(0.05, rel=0.05)

    def test_tensor_axis_divisibility_validated(self):
        from tpu_compressed_dp.parallel.dp import CompressionConfig
        from tpu_compressed_dp.train.lm_step import make_lm_train_step
        from tpu_compressed_dp.train.optim import SGD

        cfg = tf.LlamaConfig(vocab_size=64, dim=32, n_layers=1, n_heads=3,
                             n_kv_heads=3, ffn_hidden=64, dtype=jnp.float32)
        with pytest.raises(ValueError, match="divide"):
            make_lm_train_step(cfg, SGD(lr=0.1), CompressionConfig(), _mesh(2, 2, 2))


class TestRemat:
    def test_remat_identical_forward_and_grads(self):
        import dataclasses

        cfg = tf.LlamaConfig(vocab_size=64, dim=32, n_layers=2, n_heads=4,
                             n_kv_heads=2, ffn_hidden=64, dtype=jnp.float32)
        cfg_r = dataclasses.replace(cfg, remat=True)
        params = tf.init_llama(cfg, jax.random.key(0))
        toks = jax.random.randint(jax.random.key(1), (2, 16), 0, 64)
        tgts = jax.random.randint(jax.random.key(2), (2, 16), 0, 64)

        def loss(c):
            return lambda p: tf.vocab_parallel_xent(tf.apply_llama(c, p, toks), tgts)

        l0, g0 = jax.value_and_grad(loss(cfg))(params)
        l1, g1 = jax.value_and_grad(loss(cfg_r))(params)
        assert float(l0) == pytest.approx(float(l1), rel=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                    atol=1e-5, rtol=1e-5),
            g0, g1)

    def test_remat_in_sharded_step(self):
        import dataclasses
        from tpu_compressed_dp.parallel.dp import CompressionConfig
        from tpu_compressed_dp.train.lm_step import (
            init_lm_ef_state, make_lm_mesh, make_lm_train_step,
        )
        from tpu_compressed_dp.train.optim import SGD
        from tpu_compressed_dp.train.state import TrainState

        cfg = tf.LlamaConfig(vocab_size=64, dim=32, n_layers=2, n_heads=4,
                             n_kv_heads=2, ffn_hidden=64, dtype=jnp.float32,
                             remat=True)
        mesh = make_lm_mesh(2, 2, 2)
        params = tf.init_llama(cfg, jax.random.key(0))
        opt = SGD(lr=0.1, momentum=0.9)
        comp = CompressionConfig(method="topk", granularity="entiremodel",
                                 ratio=0.05, error_feedback=True)
        state = TrainState.create(params, {}, opt.init(params),
                                  init_lm_ef_state(cfg, params, comp, mesh),
                                  jax.random.key(1))
        step = make_lm_train_step(cfg, opt, comp, mesh)
        batch = {"input": jax.random.randint(jax.random.key(2), (4, 16), 0, 64),
                 "target": jax.random.randint(jax.random.key(3), (4, 16), 0, 64)}
        losses = []
        for _ in range(5):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]


@pytest.mark.quick
@pytest.mark.skipif(
    not compat.HAS_VMA,
    reason="fused_head_xent's custom VJP places cross-shard cotangent psums "
           "by diffing VMA types; without VMA typing they vanish and tp>1 "
           "grads are per-shard partials — use_fused_head_xent gates the "
           "path off on old JAX, so only the correct unfused path runs there")
class TestFusedHeadXent:
    """fused_head_xent == vocab_parallel_xent(h @ w) — value AND grads —
    including the vocab-sharded (tensor-parallel) form and non-dividing
    chunk sizes (vocab padding)."""

    def _mk(self, n=12, d=16, v=50, seed=0):
        ks = jax.random.split(jax.random.key(seed), 3)
        h = jax.random.normal(ks[0], (3, n // 3, d), jnp.float32) * 0.5
        w = jax.random.normal(ks[1], (d, v), jnp.float32) * 0.2
        y = jax.random.randint(ks[2], (3, n // 3), 0, v)
        return h, w, y

    @pytest.mark.parametrize("chunk", [8, 16, 64])
    def test_matches_unfused_value_and_grads(self, chunk):
        from tpu_compressed_dp.models.transformer import (fused_head_xent,
                                                          vocab_parallel_xent)

        h, w, y = self._mk()
        ref_fn = lambda h, w: vocab_parallel_xent(h @ w, y)
        fused_fn = lambda h, w: fused_head_xent(h, w, y, None, chunk)
        ref, (dh_r, dw_r) = jax.value_and_grad(ref_fn, (0, 1))(h, w)
        got, (dh_f, dw_f) = jax.value_and_grad(fused_fn, (0, 1))(h, w)
        np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(dh_f), np.asarray(dh_r),
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(dw_f), np.asarray(dw_r),
                                   atol=1e-6)

    def test_vocab_parallel_matches(self):
        # v=50 over 2 shards: v_local=25 does NOT divide chunk=8 -> each
        # shard has a 7-column pad window that aliases the NEXT shard's
        # first target ids (the inf-loss bug class: a target in a foreign
        # pad window must not gather the -inf masked logit)
        from tpu_compressed_dp.models.transformer import (fused_head_xent,
                                                          vocab_parallel_xent)

        h, w, y = self._mk(v=50)
        y = y.at[0, 0].set(25)  # shard 1's first id == shard 0's pad alias
        y = y.at[0, 1].set(3)   # in-shard-0 control
        from tpu_compressed_dp.parallel.mesh import make_mesh as _mm
        mesh = _mm((2,), ("tensor",))
        ref = float(vocab_parallel_xent(h @ w, y))

        def local(h, w, y):
            return fused_head_xent(h, w, y, "tensor", 8)

        got = shard_map(local, mesh=mesh,
                        in_specs=(P(), P(None, "tensor"), P()),
                        out_specs=P())(h, w, y)
        np.testing.assert_allclose(float(got), ref, rtol=1e-6)

        # grads through the sharded form: dw shards concatenate to the
        # unfused dw; dh (cotangent of the REPLICATED h) must come back
        # psum'd across shards — the custom VJP owns that psum
        dw_r = jax.grad(lambda w: vocab_parallel_xent(h @ w, y))(w)
        dh_r = jax.grad(lambda h: vocab_parallel_xent(h @ w, y))(h)
        dh_f, dw_f = shard_map(
            lambda h, w, y: jax.grad(
                lambda hw: fused_head_xent(hw[0], hw[1], y, "tensor", 8)
            )((h, w)),
            mesh=mesh, in_specs=(P(), P(None, "tensor"), P()),
            out_specs=(P(), P(None, "tensor")))(h, w, y)
        np.testing.assert_allclose(np.asarray(dw_f), np.asarray(dw_r),
                                   atol=1e-6)
        np.testing.assert_allclose(np.asarray(dh_f), np.asarray(dh_r),
                                   atol=1e-6)


def test_fused_xent_auto_uses_logits_itemsize(monkeypatch):
    """ADVICE r5: the auto heuristic must size the logits buffer at the
    CONFIG's dtype width, not hardcoded bf16 — an fp32 config crosses the
    1 GiB auto-on threshold at half the token*vocab product."""
    from tpu_compressed_dp import compat
    from tpu_compressed_dp.models import transformer as tf_mod

    # exercise the size heuristic itself even where the VMA gate would
    # force the unfused path (old jax)
    monkeypatch.setattr(compat, "HAS_VMA", True)
    monkeypatch.setattr(tf_mod, "_FUSED_XENT", "")
    elems = (1 << 28) + 1  # > 1 GiB at fp32, exactly half that at bf16
    assert tf_mod.use_fused_head_xent(elems, 1, itemsize=4)
    assert not tf_mod.use_fused_head_xent(elems, 1, itemsize=2)
    # the default preserves the r5 bf16 behaviour
    assert not tf_mod.use_fused_head_xent(elems, 1)
    assert tf_mod.use_fused_head_xent((1 << 29) + 1, 1)
