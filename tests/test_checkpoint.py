"""Async checkpointing (ISSUE 9): non-blocking saves, checksummed manifests,
walk-back restore, best-step GC pinning, and the offline fsck tool.

The drills in tools/chaos_drill.py prove the end-to-end invariants (bitwise
preempt-resume, corrupt-latest rollback); these units pin the Checkpointer's
mechanics against its injectable write seam (``_write_payload``)."""

import dataclasses
import os
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_compressed_dp.utils import checkpoint as ck
from tpu_compressed_dp.utils.checkpoint import Checkpointer, CheckpointCorrupt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

pytestmark = pytest.mark.quick


def _tiny_state():
    from tpu_compressed_dp.train.optim import SGD
    from tpu_compressed_dp.train.state import TrainState

    params = {"w": jnp.zeros((4,))}
    opt = SGD(lr=0.1)
    return TrainState.create(params, {}, opt.init(params), (),
                             jax.random.key(0))


def _bump(state, n=1):
    return dataclasses.replace(
        state, step=state.step + n,
        params={"w": state.params["w"] + float(n)})


def _flip_byte(directory, step):
    """Corrupt a committed step: XOR the middle byte of its largest file
    (size-preserving, so only the digest check can catch it)."""
    step_dir = os.path.join(directory, str(step))
    target, size = None, -1
    for root, _, names in os.walk(step_dir):
        for name in names:
            fp = os.path.join(root, name)
            sz = os.path.getsize(fp)
            if sz > size:
                target, size = fp, sz
    assert target is not None and size > 0, f"no payload file under {step_dir}"
    with open(target, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1)
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))


class _Recorder:
    def __init__(self):
        self.events = []

    def emit(self, kind, **fields):
        self.events.append((kind, fields))


class TestAsyncSaves:
    def test_save_async_nonblocking_blocked_ms_only_on_overlap(self, tmp_path):
        """The acceptance timing test: with a fake-slow write seam,
        save_async returns while the write is still in flight (inflight=1,
        nothing committed), blocked_ms stays zero without overlap, and only
        a second save arriving DURING the write accrues barrier time."""
        ckpt = Checkpointer(str(tmp_path / "ck"))
        entered, release = threading.Event(), threading.Event()
        orig = ckpt._write_payload

        def slow(step, payload, meta):
            entered.set()
            assert release.wait(timeout=10.0)
            orig(step, payload, meta)

        ckpt._write_payload = slow
        s = _tiny_state()
        ckpt.save_async(s, {"i": 0})
        # returned while the writer is still parked in the seam
        assert entered.wait(timeout=10.0)
        m = ckpt.metrics()
        assert m["ckpt/inflight"] == 1.0
        assert m["ckpt/blocked_ms"] == 0.0   # no overlap -> no stall billed
        assert m["ckpt/last_step"] == -1.0   # nothing durable yet
        threading.Timer(0.15, release.set).start()
        ckpt.save_async(_bump(s), {"i": 1})  # overlaps -> barriers on write 1
        ckpt.drain()
        m = ckpt.metrics()
        assert m["ckpt/blocked_ms"] > 0.0
        assert m["ckpt/inflight"] == 0.0
        assert m["ckpt/last_step"] == 1.0
        assert m["ckpt/save_ms"] > 0.0
        assert ck.list_step_dirs(ckpt.directory) == [0, 1]
        ckpt.close()

    def test_overlapping_async_saves_serialize(self, tmp_path):
        """Back-to-back save_asyncs never run their writes concurrently:
        each spawn barriers on the previous thread, so the write spans are
        strictly ordered (one Checkpointer owns the directory)."""
        ckpt = Checkpointer(str(tmp_path / "ck"))
        spans = []
        orig = ckpt._write_payload

        def tracked(step, payload, meta):
            t0 = time.monotonic()
            time.sleep(0.05)
            orig(step, payload, meta)
            spans.append((step, t0, time.monotonic()))

        ckpt._write_payload = tracked
        s = _tiny_state()
        for n in range(3):
            ckpt.save_async(_bump(s, n), {"i": n})
        ckpt.close()  # drains the last write
        assert [sp[0] for sp in spans] == [0, 1, 2]
        for (_, _, end), (_, start, _) in zip(spans, spans[1:]):
            assert start >= end, "async writes overlapped"
        assert ck.list_step_dirs(ckpt.directory) == [0, 1, 2]

    def test_close_never_strands_background_thread(self, tmp_path):
        for i in range(3):
            ckpt = Checkpointer(str(tmp_path / f"ck{i}"))
            ckpt.save_async(_bump(_tiny_state(), i), {})
            th = ckpt._thread
            ckpt.close()
            assert ckpt._thread is None
            assert th is None or not th.is_alive()
            # the drained write actually committed before close returned
            assert ck.list_step_dirs(ckpt.directory) == [i]
            assert ck.verify_step_dir(ckpt.directory, i) == []

    def test_sync_save_after_async_drains_first(self, tmp_path):
        """The emergency-save ordering: a sync save arriving during an
        in-flight async write waits for it (accruing blocked_ms), then
        commits its own step — both end up durable, in order."""
        ckpt = Checkpointer(str(tmp_path / "ck"))
        release = threading.Event()
        calls = {"n": 0}
        orig = ckpt._write_payload

        def slow_first(step, payload, meta):
            calls["n"] += 1
            if calls["n"] == 1:
                assert release.wait(timeout=10.0)
            orig(step, payload, meta)

        ckpt._write_payload = slow_first
        s = _tiny_state()
        ckpt.save_async(s, {})
        threading.Timer(0.1, release.set).start()
        ckpt.save(_bump(s), {"emergency": True})
        assert ck.list_step_dirs(ckpt.directory) == [0, 1]
        assert ckpt.metrics()["ckpt/blocked_ms"] > 0.0
        ckpt.close()

    def test_async_write_error_surfaces_at_next_barrier(self, tmp_path):
        ckpt = Checkpointer(str(tmp_path / "ck"))

        def boom(step, payload, meta):
            raise RuntimeError("disk full")

        ckpt._write_payload = boom
        s = _tiny_state()
        ckpt.save_async(s, {})
        with pytest.raises(RuntimeError, match="disk full"):
            ckpt.drain()
        # the emergency path drains non-raising and records the failure
        ckpt.save_async(s, {})
        ckpt.drain(raise_error=False)
        assert isinstance(ckpt.last_save_error, RuntimeError)
        assert ckpt.metrics()["ckpt/last_step"] == -1.0  # nothing committed
        del ckpt._write_payload  # back to the real writer
        ckpt.save(s, {"emergency": True})
        assert ck.list_step_dirs(ckpt.directory) == [0]
        assert ck.verify_step_dir(ckpt.directory, 0) == []
        ckpt.close()

    def test_metric_and_heartbeat_keys_declared(self, tmp_path):
        from tpu_compressed_dp.obs import registry as obs_registry

        ckpt = Checkpointer(str(tmp_path / "ck"))
        m = ckpt.metrics()
        assert set(m) == {"ckpt/save_ms", "ckpt/blocked_ms", "ckpt/inflight",
                          "ckpt/last_step", "ckpt/age_s",
                          "ckpt/rollback_steps"}
        assert obs_registry.undeclared(m.keys()) == []
        hb = ckpt.heartbeat_fields()
        assert hb["last_ckpt_step"] == -1
        assert hb["ckpt_age_s"] >= 0.0
        ckpt.close()


class TestManifests:
    def test_manifest_commit_and_verify(self, tmp_path):
        d = str(tmp_path / "ck")
        ckpt = Checkpointer(d)
        ckpt.save(_tiny_state(), {"epoch": 7})
        ckpt.close()
        man = ck.read_manifest(d, 0)
        assert man["v"] == ck.MANIFEST_SCHEMA
        assert man["step"] == 0
        assert man["files"]  # per-file sha256 + bytes
        assert all({"sha256", "bytes"} <= set(e) for e in man["files"].values())
        assert man["meta"]["epoch"] == 7
        assert ck.verify_step_dir(d, 0) == []
        _flip_byte(d, 0)
        problems = ck.verify_step_dir(d, 0)
        assert problems and any("digest mismatch" in p for p in problems)

    def test_torn_manifest_is_a_problem_but_absent_is_legacy(self, tmp_path):
        d = str(tmp_path / "ck")
        ckpt = Checkpointer(d)
        ckpt.save(_tiny_state(), {})
        ckpt.close()
        mp = ck.manifest_path(d, 0)
        with open(mp, "w") as f:
            f.write('{"v": 1, "ste')  # torn manifest commit
        assert any("unreadable" in p for p in ck.verify_step_dir(d, 0))
        os.remove(mp)  # pre-manifest directory: tolerated as legacy
        assert ck.verify_step_dir(d, 0) == []
        ckpt2 = Checkpointer(d)
        restored, _ = ckpt2.restore(_tiny_state())
        assert int(restored.step) == 0
        assert ckpt2.metrics()["ckpt/rollback_steps"] == 0.0
        ckpt2.close()

    def test_restore_walks_back_past_corrupt_latest(self, tmp_path):
        """Last-known-good fallback: a bit-flipped newest step rolls the
        restore back one step (metric + event), while an EXPLICIT request
        for the corrupt step raises CheckpointCorrupt."""
        d = str(tmp_path / "ck")
        ckpt = Checkpointer(d)
        s = _tiny_state()
        for n in (1, 2, 3):
            ckpt.save(_bump(s, n), {"epoch": n})
        ckpt.close()
        _flip_byte(d, 3)
        ckpt2 = Checkpointer(d)
        rec = _Recorder()
        ckpt2.events = rec
        restored, meta = ckpt2.restore(_tiny_state())
        assert int(restored.step) == 2
        np.testing.assert_allclose(np.asarray(restored.params["w"]), 2.0)
        assert meta["epoch"] == 2
        assert ckpt2.metrics()["ckpt/rollback_steps"] == 1.0
        rb = next(f for k, f in rec.events if k == "ckpt_rollback")
        assert rb["from_step"] == 3 and rb["to_step"] == 2
        assert rb["skipped"] and rb["skipped"][0]["step"] == 3
        with pytest.raises(CheckpointCorrupt, match="step 3"):
            ckpt2.restore(_tiny_state(), step=3)
        ckpt2.close()


class TestBestPin:
    def test_best_step_survives_max_to_keep_gc(self, tmp_path):
        """Satellite regression: the raw Orbax max_to_keep would evict the
        best checkpoint after enough periodic saves; our GC pins it, and a
        fresh process restoring LATEST re-adopts the improve-only gate."""
        d = str(tmp_path / "ck")
        ckpt = Checkpointer(d, max_to_keep=2)
        s = _tiny_state()
        assert ckpt.save_if_best(_bump(s, 1), 0.9)
        for n in (2, 3, 4, 5):
            ckpt.save(_bump(s, n), {"epoch": n})
        assert ck.list_step_dirs(d) == [1, 4, 5]  # pinned best + newest 2
        assert not os.path.exists(ck.manifest_path(d, 2))  # GC'd with its step
        _, meta_best = ckpt.restore(_tiny_state(), step=1)
        assert meta_best["best_metric"] == 0.9
        ckpt.close()
        ckpt2 = Checkpointer(d, max_to_keep=2)
        _, meta = ckpt2.restore(_tiny_state())  # latest = 5, NOT the best
        assert meta["best_metric"] == 0.9 and meta["best_step"] == 1
        assert ckpt2.best_metric == 0.9 and ckpt2.best_step == 1
        assert not ckpt2.save_if_best(_bump(s, 6), 0.5)  # not an improvement
        ckpt2.close()


class TestCkptFsck:
    def _make(self, tmp_path, n=2):
        d = str(tmp_path / "ck")
        ckpt = Checkpointer(d)
        s = _tiny_state()
        for i in range(1, n + 1):
            ckpt.save(_bump(s, i), {"epoch": i})
        ckpt.close()
        return d

    def test_verify_list_prune_cycle(self, tmp_path, capsys):
        from tools import ckpt_fsck as fsck

        d = self._make(tmp_path)
        assert fsck.main([d]) == 0
        out = capsys.readouterr().out
        assert "step 1: OK" in out and "step 2: OK" in out
        assert fsck.main([d, "--list"]) == 0
        out = capsys.readouterr().out
        assert "files" in out and "meta[epoch" in out
        # a flipped byte + an orphaned manifest (step dir gone)
        _flip_byte(d, 2)
        with open(os.path.join(d, "manifest-9.json"), "w") as f:
            f.write("{}")
        assert fsck.main([d]) == 1
        out = capsys.readouterr().out
        assert "step 2: CORRUPT" in out and "digest mismatch" in out
        assert "orphaned manifest" in out
        assert fsck.main([d, "--prune"]) == 0
        assert ck.list_step_dirs(d) == [1]
        assert not os.path.exists(ck.manifest_path(d, 2))
        assert not os.path.exists(os.path.join(d, "manifest-9.json"))
        assert fsck.main([d]) == 0  # clean after prune

    def test_missing_or_empty_directory_exit_2(self, tmp_path, capsys):
        from tools import ckpt_fsck as fsck

        assert fsck.main([str(tmp_path / "nope")]) == 2
        empty = tmp_path / "empty"
        empty.mkdir()
        assert fsck.main([str(empty)]) == 2
        out = capsys.readouterr().out
        assert "no such directory" in out and "no checkpoints" in out
