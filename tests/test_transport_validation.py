"""Measured-vs-analytic transport parity, end-to-end (VERDICT r4 #4).

Runs the real 2-process CPU rendezvous from tools/validate_transport.py as a
subprocess sweep and asserts the loopback-measured bytes per step track the
analytic ``per_chip_traffic_bytes`` model.  The r5 chip-adjacent run
(benchmarks/transport_validation_r5.tsv) measured ratios 0.999 (dense),
1.018 (wire topk 1%), 1.033 (wire blocktopk 1%), 1.006 (terngrad) at 8 MB
dense payloads; the test tolerates more slack because CI payloads are
smaller (framing overhead amortises less) and the host is 1-core.
"""

import json
import os
import subprocess
import sys

import pytest

from tpu_compressed_dp import compat

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOL = os.path.join(ROOT, "tools", "validate_transport.py")


@pytest.mark.timeout(600)
@pytest.mark.skipif(
    not compat.HAS_CPU_MULTIPROCESS,
    reason="this jax's CPU backend has no cross-process collectives "
           "('Multiprocess computations aren't implemented on the CPU "
           "backend') — the 2-process rendezvous cannot run")
def test_measured_lo_bytes_track_analytic(tmp_path):
    out = tmp_path / "transport.tsv"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers want 1 local device each
    r = subprocess.run(
        [sys.executable, TOOL, "--out", str(out), "--steps", "10",
         "--port", "12489"],
        capture_output=True, text=True, timeout=570, env=env, cwd=ROOT)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    rows = [ln.split("\t") for ln in out.read_text().splitlines()
            if ln and not ln.startswith("#")]
    header, data = rows[0], rows[1:]
    assert len(data) >= 2, out.read_text()
    by_case = {d[header.index("case")]: d for d in data}
    ratios = {}
    for case, d in by_case.items():
        ratio = float(d[header.index("ratio_measured_over_analytic")])
        ratios[case] = ratio
        # the analytic model must be the right SCALE at the NIC: payload
        # dominated, bounded framing overhead
        assert 0.85 < ratio < 1.6, (case, ratio, out.read_text())
    # method ordering must survive measurement: dense > terngrad > topk-1%
    meas = {c: float(d[header.index("measured_lo_tx_bytes_per_step")])
            for c, d in by_case.items()}
    assert meas["dense"] > meas["terngrad-wire"] > meas["topk-1%-wire-EF"], meas
