"""Unit tests for tcdp-lint pass 2 (tpu_compressed_dp/analysis/hostlint.py).

Each TCDP10x rule must fire on its seeded fixture (tests/fixtures/lint/),
stay silent on the clean fixture, and honour the disable pragma round trip
(justified -> suppressed; bare -> suppressed + TCDP100).
"""

import os

import pytest

from tpu_compressed_dp.analysis.hostlint import lint_source, roles_for_path
from tpu_compressed_dp.analysis.report import (CODES, filter_suppressed,
                                               findings_to_json,
                                               parse_disables)

pytestmark = pytest.mark.quick

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures", "lint")


def _lint_fixture(name):
    with open(os.path.join(FIXTURES, name), "r", encoding="utf-8") as f:
        source = f.read()
    return lint_source(source, f"tests/fixtures/lint/{name}"), source


class TestRulesFire:
    def test_tcdp101_wallclock(self):
        findings, _ = _lint_fixture("tcdp101_wallclock.py")
        assert [f.code for f in findings] == ["TCDP101", "TCDP101"]
        assert "time.time" in findings[0].message

    def test_tcdp102_nonatomic_write(self):
        findings, _ = _lint_fixture("tcdp102_nonatomic.py")
        assert [f.code for f in findings] == ["TCDP102"]
        assert "os.replace" in findings[0].message

    def test_tcdp103_undeclared_stat_key(self):
        findings, _ = _lint_fixture("tcdp103_statkey.py")
        assert [f.code for f in findings] == ["TCDP103"]
        assert "comm/undeclared_fixture_key" in findings[0].message

    def test_tcdp104_scope_taxonomy(self):
        findings, _ = _lint_fixture("tcdp104_scope.py")
        assert [f.code for f in findings] == ["TCDP104"] * 3

    def test_tcdp105_unguarded_thread_write(self):
        findings, _ = _lint_fixture("tcdp105_thread.py")
        assert [f.code for f in findings] == ["TCDP105"]
        assert "self.count" in findings[0].message


class TestCleanAndSuppression:
    def test_clean_fixture_zero_findings(self):
        findings, _ = _lint_fixture("clean.py")
        assert findings == []

    def test_disable_round_trip(self):
        raw, source = _lint_fixture("disabled.py")
        assert [f.code for f in raw] == ["TCDP101", "TCDP101"]
        active, suppressed = filter_suppressed(
            raw, {"tests/fixtures/lint/disabled.py": source})
        # both wall-clock findings suppressed; the bare pragma earns a
        # TCDP100 so silent waivers cannot accumulate
        assert [f.code for f in suppressed] == ["TCDP101", "TCDP101"]
        assert [f.code for f in active] == ["TCDP100"]
        assert suppressed[0].justification.startswith("operator-facing")

    def test_parse_disables_forms(self):
        src = ("x = 1  # tcdp-lint: disable=TCDP101 -- why\n"
               "# tcdp-lint: disable=TCDP102,TCDP103\n"
               "y = 2\n")
        d = parse_disables(src)
        assert d[1] == (("TCDP101",), "why")
        # own-line comment guards the following line too
        assert d[3][0] == ("TCDP102", "TCDP103")


class TestDrivers:
    def test_roles_from_path(self):
        assert roles_for_path("tpu_compressed_dp/train/rendezvous.py") == {
            "replay", "shared_dir"}
        assert roles_for_path("tpu_compressed_dp/parallel/dp.py") == set()

    def test_json_payload_shape(self):
        findings, _ = _lint_fixture("tcdp103_statkey.py")
        payload = findings_to_json(findings)
        assert payload["counts"]["active"] == 1
        f = payload["active"][0]
        assert f["code"] == "TCDP103"
        assert f["description"] == CODES["TCDP103"]
