"""Real multi-process elastic drills (train/rendezvous.py end to end).

Everything in tests/test_elastic.py and tests/test_rendezvous.py runs the
protocol single-process with injected seams; this module is the other
half: actual ``jax.distributed`` worlds of 2 OS processes on the CPU
backend, where a peer's death really wedges the collectives and the
survivor must rendezvous, re-init, and remesh to keep training.

Both drills are gated on ``HAS_CPU_MULTIPROCESS`` (jax < 0.5 has no
cross-process CPU collectives) and live in the slow tier: they burn
wall-clock on real peer-timeout windows.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from tpu_compressed_dp import compat

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WATCHDOG = os.path.join(REPO, "tools", "watchdog.py")

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        not compat.HAS_CPU_MULTIPROCESS,
        reason="this jax's CPU backend has no cross-process collectives — "
               "a 2-process elastic world cannot form"),
]


def _free_port() -> int:
    # OS-assigned: a hardcoded port collides with concurrent pytest
    # sessions or a leftover child from a timed-out run
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env(devices_per_proc: int = 2, **extra) -> dict:
    env = dict(os.environ)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(
        f"--xla_force_host_platform_device_count={devices_per_proc}")
    env.update({"JAX_PLATFORMS": "cpu", "XLA_FLAGS": " ".join(flags)})
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _dawn_cmd(rank: int, port: int, elastic_dir: str, log_dir: str, *,
              epochs: int, peer_timeout: float = 4.0,
              heartbeat: str = None) -> list:
    cmd = [sys.executable, "-m", "tpu_compressed_dp.harness.dawn",
           "--synthetic", "--synthetic_n", "512", "--epochs", str(epochs),
           "--batch_size", "64", "--channels_scale", "0.125",
           "--compress", "entiremodel", "--method", "topk", "--ratio", "0.1",
           "--error_feedback",
           "--elastic", "--elastic_dir", elastic_dir,
           "--elastic_min_world", "2",
           "--peer_timeout", str(peer_timeout),
           "--coordinator", f"127.0.0.1:{port}",
           "--num_processes", "2", "--process_id", str(rank),
           "--log_dir", log_dir]
    if heartbeat:
        cmd += ["--heartbeat", heartbeat, "--heartbeat_interval", "1"]
    return cmd


def _wait_for(predicate, deadline_s: float, what: str):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.25)
    raise AssertionError(f"timed out after {deadline_s:g}s waiting for {what}")


def _gossip_step(elastic_dir, rank):
    try:
        with open(os.path.join(elastic_dir, f"rank{rank}.json")) as f:
            return json.load(f).get("step", -1)
    except (OSError, ValueError):
        return -1


def _read_epoch(elastic_dir):
    try:
        with open(os.path.join(elastic_dir, "epoch.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


@pytest.mark.timeout(420)
def test_kill_one_process_survivor_remeshes(tmp_path):
    """SIGKILL one of two dawn processes mid-training: the survivor's
    bounded fetch / gossip staleness converts the wedge into PeerFailed,
    the rendezvous commits epoch 1 over the survivor alone, jax.distributed
    re-initialises at num_processes=1, and training completes at W-1
    (2 of 4 data rows) with exit 0."""
    port = _free_port()
    elastic_dir = str(tmp_path / "elastic")
    procs = [
        subprocess.Popen(
            _dawn_cmd(r, port, elastic_dir, str(tmp_path / f"log{r}"),
                      epochs=8),
            env=_env(), cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in (0, 1)]
    try:
        # let the world form and take a few real steps (both ranks beating)
        _wait_for(lambda: _gossip_step(elastic_dir, 0) >= 2
                  and _gossip_step(elastic_dir, 1) >= 2,
                  180, "both ranks to start stepping")
        procs[1].send_signal(signal.SIGKILL)
        procs[1].wait(timeout=30)
        out0, _ = procs[0].communicate(timeout=300)
        assert procs[0].returncode == 0, out0[-3000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    rec = _read_epoch(elastic_dir)
    assert rec is not None, "no epoch was ever committed"
    assert rec["epoch"] >= 1 and [int(r) for r in rec["ranks"]] == [0]
    assert out0.count("re-initialised") >= 1, out0[-3000:]


@pytest.mark.timeout(540)
def test_watchdog_relaunch_rejoins_running_world(tmp_path):
    """The full readmission loop: kill rank 1, wait for the survivor to
    commit the shrunken epoch, then hand rank 1 to ``tools/watchdog.py
    --relaunch --elastic_dir`` — its spawn exports the committed epoch, the
    child parks in the join barrier, the survivor's epoch-boundary
    ``rejoin_barrier`` readmits it, and BOTH sides exit 0 with a final
    epoch naming ranks [0, 1] again."""
    port = _free_port()
    elastic_dir = str(tmp_path / "elastic")
    hb1 = str(tmp_path / "hb1.json")
    p0 = subprocess.Popen(
        _dawn_cmd(0, port, elastic_dir, str(tmp_path / "log0"), epochs=24),
        env=_env(), cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    p1 = subprocess.Popen(
        _dawn_cmd(1, port, elastic_dir, str(tmp_path / "log1"), epochs=24,
                  heartbeat=hb1),
        env=_env(), cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    wd = None
    try:
        _wait_for(lambda: _gossip_step(elastic_dir, 0) >= 2
                  and _gossip_step(elastic_dir, 1) >= 2,
                  180, "both ranks to start stepping")
        p1.send_signal(signal.SIGKILL)
        p1.wait(timeout=30)
        # survivor detects, votes alone, commits the shrunken world
        _wait_for(lambda: (_read_epoch(elastic_dir) or {}).get("ranks")
                  == [0], 120, "the survivor to commit the W-1 epoch")
        shrunk = _read_epoch(elastic_dir)["epoch"]
        # the watchdog's spawn reads epoch.json and exports the rejoin
        # hint; the child lands in the running world's join barrier
        wd = subprocess.Popen(
            [sys.executable, WATCHDOG, "--relaunch",
             "--heartbeat", hb1, "--interval", "5", "--grace", "120",
             "--max_relaunches", "3", "--backoff", "2",
             "--elastic_dir", elastic_dir, "--"]
            + _dawn_cmd(1, port, elastic_dir, str(tmp_path / "log1b"),
                        epochs=24, heartbeat=hb1),
            env=_env(TCDP_RESTART_COUNT="1"), cwd=REPO,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        _wait_for(lambda: (_read_epoch(elastic_dir) or {}).get("ranks")
                  == [0, 1], 240, "the readmit barrier to re-commit [0, 1]")
        out0, _ = p0.communicate(timeout=300)
        assert p0.returncode == 0, out0[-3000:]
        outw, _ = wd.communicate(timeout=300)
        assert wd.returncode == 0, outw[-3000:]
        assert "rejoin hint" in outw
    finally:
        for p in (p0, p1, wd):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
    rec = _read_epoch(elastic_dir)
    assert rec["epoch"] > shrunk  # readmission is a NEW epoch, not a rewind
    assert [int(r) for r in rec["ranks"]] == [0, 1]
