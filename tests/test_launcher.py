"""Launcher coverage (VERDICT r1 missing #3 / weak #7).

The reference's cluster entry is `IMAGENET/train.py` (ncluster + NCCL ring
strings + torch.distributed.launch); ours is `tools/launch_tpu.py` with a
gcloud fan-out mode and a local multi-process mode.  The local mode is the
real test: it spawns N processes with an explicit 127.0.0.1 rendezvous —
the same multi-process path a TPU pod runs, minus the hardware — and the
dawn harness trains across them (the `CIFAR10/core.py:334` Gloo-over-TCP
equivalent).
"""

import subprocess
import sys
import os

import pytest

from tpu_compressed_dp import compat

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LAUNCHER = os.path.join(REPO, "tools", "launch_tpu.py")


class TestGcloudMode:
    def test_dry_run_prints_command(self):
        out = subprocess.run(
            [sys.executable, LAUNCHER, "--tpu", "my-pod", "--zone", "us-east5-a",
             "--", "python", "-m", "tpu_compressed_dp.harness.imagenet", "/data"],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0
        assert "gcloud compute tpus tpu-vm ssh my-pod" in out.stdout
        assert "--worker=all" in out.stdout
        assert "--zone=us-east5-a" in out.stdout
        assert "harness.imagenet" in out.stdout

    def test_requires_train_cmd(self):
        out = subprocess.run([sys.executable, LAUNCHER, "--tpu", "x"],
                             capture_output=True, text=True, cwd=REPO)
        assert out.returncode != 0

    def test_requires_tpu_or_local(self):
        out = subprocess.run([sys.executable, LAUNCHER, "--", "python", "x.py"],
                             capture_output=True, text=True, cwd=REPO)
        assert out.returncode != 0


class TestLocalMode:
    @pytest.mark.timeout(300)
    @pytest.mark.skipif(
        not compat.HAS_CPU_MULTIPROCESS,
        reason="this jax's CPU backend has no cross-process collectives — "
               "the 2-process local launch cannot sync gradients")
    def test_two_process_dawn_trains(self, tmp_path):
        """2 processes x 2 virtual CPU devices: the dawn harness shards the
        global batch per process (`ShardedBatches`), syncs compressed
        gradients across the 4-device mesh, and both ranks exit 0."""
        import socket

        # OS-assigned free port: a hardcoded one collides with concurrent
        # pytest sessions or a leftover child from a timed-out run
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        out = subprocess.run(
            [sys.executable, LAUNCHER, "--local_procs", "2",
             "--devices_per_proc", "2", "--port", str(port), "--",
             sys.executable, "-m", "tpu_compressed_dp.harness.dawn",
             "--synthetic", "--synthetic_n", "256", "--epochs", "2",
             "--batch_size", "64", "--channels_scale", "0.125",
             "--compress", "entiremodel", "--method", "topk", "--ratio", "0.1",
             "--error_feedback", "--log_dir", str(tmp_path)],
            capture_output=True, text=True, cwd=REPO, timeout=280)
        assert out.returncode == 0, out.stderr[-2000:]
        # rank-0-only logging: exactly one epoch table in the combined output
        assert out.stdout.count("train loss") == 1, out.stdout
        # the TSV lands with one row per epoch
        tsv = (tmp_path / "logs.tsv").read_text().strip().splitlines()
        assert len(tsv) == 3  # header + 2 epochs
