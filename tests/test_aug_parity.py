"""Statistical + positional parity of the CIFAR augmentation pipeline vs the
reference semantics (VERDICT r4 missing #1, third bullet).

The reference augments per sample in ``__getitem__`` with choices drawn once
per epoch (`CIFAR10/core.py:62-114`): Crop(32,32) from the reflect-pad-4
40x40 image (offsets uniform over {0..8}^2), FlipLR with p=0.5, Cutout(8,8)
(offsets uniform over {0..24}^2, applied to the cropped image), in that
order.  This repo vectorises the same distribution over the whole epoch
(`data/cifar10.py`).  These tests pin both halves of the claim:

  * positional: each transform moves exactly the pixels the reference's
    would, verified on coordinate-encoded images;
  * distributional: the drawn choices match the reference's uniform/bernoulli
    laws, verified on 200k draws with ~4-sigma bounds (false-failure
    probability < 1e-4 per run).
"""

import numpy as np
import pytest

from tpu_compressed_dp.data import cifar10 as C

pytestmark = pytest.mark.quick


def coord_image(n=1, h=40, w=40):
    """Images whose pixel values encode (row, col): value = row * 64 + col.
    Channels carry row in ch0, col in ch1 (uint8-safe for h, w <= 64)."""
    r = np.arange(h, dtype=np.uint8)[:, None] * np.ones((1, w), np.uint8)
    c = np.ones((h, 1), np.uint8) * np.arange(w, dtype=np.uint8)[None, :]
    img = np.stack([r, c, np.zeros_like(r)], axis=-1)
    return np.repeat(img[None], n, axis=0)


class TestPositional:
    def test_crop_extracts_expected_window(self):
        x = coord_image(4)
        choices = {"crop": (32, 32), "cutout": None,
                   "y0": np.array([0, 3, 8, 5]), "x0": np.array([8, 0, 2, 5]),
                   "flip": None}
        out = C.apply_augment(x, choices)
        for i, (y0, x0) in enumerate(zip(choices["y0"], choices["x0"])):
            assert (out[i, :, :, 0] == coord_image(1)[0, y0:y0 + 32, x0:x0 + 32, 0]).all()
            assert (out[i, :, :, 1] == coord_image(1)[0, y0:y0 + 32, x0:x0 + 32, 1]).all()

    def test_flip_reverses_cols_of_flagged_rows_only(self):
        x = coord_image(2)
        choices = {"crop": (32, 32), "cutout": None,
                   "y0": np.zeros(2, int), "x0": np.zeros(2, int),
                   "flip": np.array([True, False])}
        out = C.apply_augment(x, choices)
        assert (out[0, :, :, 1] == out[1, :, ::-1, 1]).all()
        assert (out[1, 0, :, 1] == np.arange(32)).all()

    def test_cutout_zeroes_exact_patch_after_crop_and_flip(self):
        x = coord_image(1) + 1  # no natural zeros
        choices = {"crop": (32, 32), "cutout": (8, 8),
                   "y0": np.array([4]), "x0": np.array([4]),
                   "flip": np.array([True]), "cy": np.array([10]),
                   "cx": np.array([20])}
        out = C.apply_augment(x, choices)
        patch = out[0, 10:18, 20:28]
        assert (patch == 0).all()
        mask = np.ones((32, 32), bool)
        mask[10:18, 20:28] = False
        assert (out[0][mask] != 0).all()

    def test_order_is_crop_flip_cutout(self):
        # cutout coordinates index the CROPPED+FLIPPED image (reference list
        # order, core.py Transform chain): with flip on, the zero patch must
        # sit at cx in the flipped frame, not mirrored
        x = coord_image(1) + 1
        base = {"crop": (32, 32), "cutout": (8, 8),
                "y0": np.array([0]), "x0": np.array([0]),
                "cy": np.array([0]), "cx": np.array([0])}
        flipped = C.apply_augment(x, {**base, "flip": np.array([True])})
        plain = C.apply_augment(x, {**base, "flip": np.array([False])})
        assert (flipped[0, :8, :8] == 0).all()
        assert (plain[0, :8, :8] == 0).all()

    def test_normalise_and_pad_match_reference_constants(self):
        x = np.full((1, 2, 2, 3), 128, np.uint8)
        z = C.normalise(x)
        want = (128.0 - 255.0 * np.array(C.CIFAR10_MEAN)) / (
            255.0 * np.array(C.CIFAR10_STD))
        assert np.allclose(z[0, 0, 0], want, atol=1e-6)
        p = C.pad(coord_image(1), border=4)
        assert p.shape == (1, 48, 48, 3)
        # reflect: row -1 mirrors row 1
        assert (p[0, 3, 4:-4, 0] == coord_image(1)[0, 1, :, 0]).all()


class TestDistributional:
    N = 200_000

    def draws(self):
        rng = np.random.RandomState(123)
        return C.draw_augment_choices(self.N, (40, 40), rng)

    def test_crop_offsets_uniform_over_0_8(self):
        ch = self.draws()
        for key in ("y0", "x0"):
            v = ch[key]
            assert v.min() == 0 and v.max() == 8
            counts = np.bincount(v, minlength=9)
            expect = self.N / 9
            # 4-sigma binomial bound per cell
            tol = 4 * np.sqrt(expect * (1 - 1 / 9))
            assert (np.abs(counts - expect) < tol).all(), counts

    def test_flip_rate_half(self):
        f = self.draws()["flip"]
        tol = 4 * np.sqrt(self.N * 0.25)
        assert abs(f.sum() - self.N / 2) < tol

    def test_cutout_offsets_uniform_over_0_24(self):
        ch = self.draws()
        for key in ("cy", "cx"):
            v = ch[key]
            assert v.min() == 0 and v.max() == 24
            counts = np.bincount(v, minlength=25)
            expect = self.N / 25
            tol = 4 * np.sqrt(expect * (1 - 1 / 25))
            assert (np.abs(counts - expect) < tol).all(), counts

    def test_independence_epoch_to_epoch(self):
        # fresh draws each epoch (set_random_choices per epoch): correlation
        # between consecutive epochs' offsets ~ 0
        rng = np.random.RandomState(7)
        a = C.draw_augment_choices(self.N, (40, 40), rng)
        b = C.draw_augment_choices(self.N, (40, 40), rng)
        r = np.corrcoef(a["y0"], b["y0"])[0, 1]
        assert abs(r) < 4 / np.sqrt(self.N)
