"""Fleet control plane: spec validation, the pure planner, the shared-dir
protocol under torn reads and concurrent writers, the scheduler's
preemption interleavings against a scripted controller, the shared
supervised-spawn environment composition, and the tools/fleet.py CLI."""

import json
import os
import sys
import threading
import time

import pytest

from tpu_compressed_dp.fleet import (DevicePool, Evict, FleetScheduler, Grow,
                                     JobController, JobSpec, Place, Shrink,
                                     Slot, SpecError, Waiting, plan)
from tpu_compressed_dp.fleet import state as fstate
from tpu_compressed_dp.utils.resilience import PREEMPT_EXIT, spawn_supervised


def _spec(job_id="j", command=("run",), **kw):
    return JobSpec(job_id, command, **kw)


@pytest.mark.quick
class TestJobSpec:
    def test_roundtrip(self):
        s = _spec("lm-a", ("python", "-m", "x"), priority=2, min_world=2,
                  max_world=4, target_updates=100, checkpoint_dir="ck")
        assert JobSpec.from_json(s.to_json()) == s
        assert JobSpec.parse(json.dumps(s.to_json())) == s
        assert s.elastic

    def test_pinned_world_is_not_elastic(self):
        assert not _spec(min_world=3, max_world=3).elastic

    def test_bad_job_ids_rejected(self):
        for bad in ("", "a/b", ".hidden", "a b", "x" * 65, "spéc"):
            with pytest.raises(SpecError):
                _spec(job_id=bad)

    def test_empty_command_rejected(self):
        with pytest.raises(SpecError):
            _spec(command=())

    def test_world_range_validated(self):
        with pytest.raises(SpecError):
            _spec(min_world=0)
        with pytest.raises(SpecError):
            _spec(min_world=3, max_world=2)

    def test_target_updates_validated(self):
        with pytest.raises(SpecError):
            _spec(target_updates=0)

    def test_unknown_fields_rejected(self):
        with pytest.raises(SpecError, match="unknown"):
            JobSpec.from_json({"job_id": "j", "command": ["run"],
                               "prio": 3})

    def test_command_must_be_argv_list(self):
        with pytest.raises(SpecError, match="argv"):
            JobSpec.from_json({"job_id": "j", "command": "python -m x"})

    def test_parse_rejects_non_json(self):
        with pytest.raises(SpecError, match="JSON"):
            JobSpec.parse("{not json")

    def test_command_coerced_to_strings(self):
        assert _spec(command=("python", 3)).command == ("python", "3")


@pytest.mark.quick
class TestPlan:
    def _slot(self, job_id, world, *, priority=0, min_world=None,
              max_world=None, seq=0, elastic=True):
        return Slot(job_id, priority, world,
                    min_world if min_world is not None else world,
                    max_world if max_world is not None else world,
                    seq, elastic=elastic)

    def test_places_at_max_world_when_room(self):
        acts = plan(8, [], [Waiting("a", 0, 2, 4, 0)])
        assert acts == [Place("a", 4)]

    def test_bin_packs_leftover_capacity(self):
        acts = plan(8, [], [Waiting("a", 0, 2, 6, 0),
                            Waiting("b", 0, 2, 6, 1)])
        assert acts == [Place("a", 6), Place("b", 2)]

    def test_priority_orders_the_queue(self):
        acts = plan(4, [], [Waiting("low", 0, 4, 4, 0),
                            Waiting("high", 5, 4, 4, 1)])
        assert acts == [Place("high", 4)]

    def test_resume_keeps_original_seq_rank(self):
        # the evictee (seq 0) outranks a later equal-priority arrival
        acts = plan(4, [], [Waiting("late", 0, 4, 4, 7),
                            Waiting("back", 0, 4, 4, 0, resume=True)])
        assert acts == [Place("back", 4, resume=True)]

    def test_shrink_before_evict(self):
        # the drill scenario: elastic a gives one device, rigid b evicts
        running = [self._slot("a", 4, min_world=3, max_world=4, seq=0),
                   self._slot("b", 3, seq=1, elastic=False)]
        acts = plan(8, running, [Waiting("c", 10, 4, 4, 2)])
        assert acts == [Shrink("a", 3), Evict("b"), Place("c", 4)]

    def test_shrink_alone_when_it_suffices(self):
        running = [self._slot("a", 6, min_world=2, max_world=6, seq=0)]
        acts = plan(8, running, [Waiting("c", 10, 4, 4, 1)])
        assert acts == [Shrink("a", 4), Place("c", 4)]

    def test_equal_priority_never_preempts(self):
        running = [self._slot("a", 4, min_world=2, max_world=4, seq=0)]
        acts = plan(4, running, [Waiting("b", 0, 2, 4, 1)])
        assert acts == []

    def test_eviction_order_latest_admitted_first(self):
        running = [self._slot("a", 4, seq=0, elastic=False),
                   self._slot("b", 4, seq=1, elastic=False)]
        acts = plan(8, running, [Waiting("c", 10, 4, 4, 2)])
        assert acts == [Evict("b"), Place("c", 4)]

    def test_no_growth_while_anyone_waits(self):
        running = [self._slot("a", 2, min_world=2, max_world=8, seq=0)]
        acts = plan(8, running, [Waiting("big", 0, 7, 7, 1)])
        assert acts == []  # capacity is spoken for, even if unplaced yet

    def test_no_growth_on_an_evicting_tick(self):
        running = [self._slot("a", 2, min_world=2, max_world=8,
                              seq=0, priority=5),
                   self._slot("b", 6, seq=1, elastic=False)]
        acts = plan(8, running, [Waiting("c", 10, 6, 6, 2)])
        assert acts == [Evict("b"), Place("c", 6)]  # no Grow("a") rider

    def test_growth_toward_max_world_when_queue_empty(self):
        running = [self._slot("a", 2, min_world=2, max_world=4, seq=1),
                   self._slot("b", 2, min_world=2, max_world=4, seq=0)]
        acts = plan(8, running, [])
        # priority tie -> earliest admitted grows first, then the rest
        assert acts == [Grow("b", 4), Grow("a", 4)]
        # a lone grower takes everything up to its max_world
        acts = plan(8, [self._slot("b", 2, min_world=2, max_world=8,
                                   seq=0)], [])
        assert acts == [Grow("b", 8)]

    def test_rigid_slot_never_shrinks(self):
        running = [self._slot("a", 4, min_world=2, max_world=4, seq=0,
                              elastic=False)]
        acts = plan(8, running, [Waiting("c", 10, 6, 6, 1)])
        assert acts == [Evict("a"), Place("c", 6)]

    def test_impossible_spec_does_not_wedge_the_queue(self):
        acts = plan(4, [], [Waiting("huge", 9, 5, 5, 0),
                            Waiting("ok", 0, 2, 2, 1)])
        assert acts == [Place("ok", 2)]


@pytest.mark.quick
class TestDevicePool:
    def test_contiguous_first_fit(self):
        pool = DevicePool(8)
        assert pool.allocate(4) == (0, 1, 2, 3)
        assert pool.allocate(3) == (4, 5, 6)
        pool.release((4, 5, 6))
        assert pool.allocate(4) == (4, 5, 6, 7)

    def test_fragmented_falls_back_to_lowest_ids(self):
        pool = DevicePool(6)
        a = pool.allocate(2)            # (0, 1)
        b = pool.allocate(2)            # (2, 3)
        pool.allocate(2)                # (4, 5)
        pool.release(a)
        pool.release(b[1:])             # free = {0, 1, 3}: no run of 3
        assert pool.allocate(3) == (0, 1, 3)

    def test_over_allocation_raises(self):
        pool = DevicePool(2)
        with pytest.raises(ValueError):
            pool.allocate(3)
        with pytest.raises(ValueError):
            pool.allocate(0)

    def test_double_release_and_range_checked(self):
        pool = DevicePool(2)
        ids = pool.allocate(2)
        pool.release(ids)
        with pytest.raises(ValueError):
            pool.release((0,))
        with pytest.raises(ValueError):
            pool.release((9,))


@pytest.mark.quick
class TestFleetStateTornReads:
    """Every shared-dir read must answer None (or skip the file) on
    torn/partial/foreign content — never raise out of the decision loop
    (style of tests/test_resilience.py::TestTornReads)."""

    def test_torn_job_record_reads_none(self, tmp_path):
        d = str(tmp_path)
        fstate.write_job_record(d, {"job_id": "a", "status": "running"})
        path = os.path.join(fstate.jobs_dir(d), "job.a.json")
        with open(path, "w") as f:
            f.write('{"job_id": "a", "sta')      # torn mid-record
        assert fstate.read_job_record(d, "a") is None
        assert fstate.list_job_records(d) == []

    def test_garbage_and_wrong_shape_read_none(self, tmp_path):
        d = str(tmp_path)
        os.makedirs(fstate.jobs_dir(d))
        path = os.path.join(fstate.jobs_dir(d), "job.a.json")
        with open(path, "wb") as f:
            f.write(b"\xff\xfe\x00garbage\x80")
        assert fstate.read_job_record(d, "a") is None
        with open(path, "w") as f:
            f.write("[1, 2]")                    # valid JSON, not a record
        assert fstate.read_job_record(d, "a") is None
        with open(path, "w") as f:
            f.write('{"status": "running"}')     # missing job_id
        assert fstate.read_job_record(d, "a") is None

    def test_torn_pool_record_reads_none(self, tmp_path):
        d = str(tmp_path)
        fstate.write_pool_record(d, {"pool_size": 8})
        with open(fstate.pool_path(d), "w") as f:
            f.write('{"pool_si')
        assert fstate.read_pool_record(d) is None

    def test_torn_submission_skipped_not_rejected(self, tmp_path):
        # an in-flight write is picked up next tick, not bounced
        d = str(tmp_path)
        os.makedirs(fstate.queue_dir(d))
        with open(os.path.join(fstate.queue_dir(d), "submit.a.json"),
                  "w") as f:
            f.write('{"spec": {"job_')
        assert fstate.pending_submissions(d) == []

    def test_malformed_spec_surfaces_with_error(self, tmp_path):
        d = str(tmp_path)
        os.makedirs(fstate.queue_dir(d))
        with open(os.path.join(fstate.queue_dir(d), "submit.a.json"),
                  "w") as f:
            json.dump({"spec": {"job_id": "a", "command": []}, "ts": 1.0}, f)
        [(spec, rec)] = fstate.pending_submissions(d)
        assert spec is None and rec["job_id"] == "a"
        assert "command" in rec["error"]

    def test_queue_file_naming_a_different_job_is_rejected(self, tmp_path):
        d = str(tmp_path)
        fstate.submit_job(d, _spec("real"), ts=1.0)
        os.rename(os.path.join(fstate.queue_dir(d), "submit.real.json"),
                  os.path.join(fstate.queue_dir(d), "submit.fake.json"))
        [(spec, rec)] = fstate.pending_submissions(d)
        assert spec is None and rec["job_id"] == "fake"

    def test_stray_tmp_files_are_invisible(self, tmp_path):
        d = str(tmp_path)
        fstate.write_job_record(d, {"job_id": "a", "status": "done"})
        with open(os.path.join(fstate.jobs_dir(d),
                               "job.a.json.999.tmp"), "w") as f:
            f.write("{")
        assert [r["job_id"] for r in fstate.list_job_records(d)] == ["a"]

    def test_submission_order_replays_from_record_ts(self, tmp_path):
        d = str(tmp_path)
        fstate.submit_job(d, _spec("later"), ts=2.0)
        fstate.submit_job(d, _spec("earlier"), ts=1.0)
        ids = [s.job_id for s, _ in fstate.pending_submissions(d)]
        assert ids == ["earlier", "later"]
        fstate.clear_submission(d, "earlier")
        fstate.clear_submission(d, "missing")    # idempotent
        assert [s.job_id for s, _ in fstate.pending_submissions(d)] \
            == ["later"]

    def test_writer_replace_is_atomic_under_hammer(self, tmp_path):
        """A hot writer thread + a hot reader: every read observes either
        None or a COMPLETE record through the tmp+replace protocol."""
        d = str(tmp_path)
        stop = threading.Event()

        def writer():
            i = 0
            while not stop.is_set():
                fstate.write_job_record(
                    d, {"job_id": "a", "status": "running", "seq": i,
                        "devices": list(range(8))})
                i += 1

        t = threading.Thread(target=writer)
        t.start()
        try:
            deadline, reads = time.time() + 0.5, 0
            while time.time() < deadline:
                rec = fstate.read_job_record(d, "a")
                if rec is not None:
                    assert set(rec) == {"job_id", "status", "seq",
                                        "devices"}, rec
                    reads += 1
        finally:
            stop.set()
            t.join()
        assert reads > 0, "reader never observed a complete record"


class _Recorder:
    """events= stand-in: collects (kind, fields)."""

    def __init__(self):
        self.events = []

    def emit(self, kind, **fields):
        self.events.append((kind, fields))

    def kinds(self):
        return [k for k, _ in self.events]


class _ScriptedController(JobController):
    """One fake 'update' per poll; eviction checkpoints the applied count
    and returns PREEMPT_EXIT; resume restores it.  ``script[job_id]`` can
    override poll results to drive crash/unhealthy paths."""

    resizable = True

    def __init__(self, targets, script=None):
        self.targets = targets
        self.script = dict(script or {})
        self.live = {}            # job_id -> {"applied": int, "world": int}
        self.saved = {}           # job_id -> applied at eviction
        self.calls = []

    def start(self, spec, world, devices, *, resume):
        applied = self.saved.pop(spec.job_id, 0) if resume else 0
        self.live[spec.job_id] = {"applied": applied, "world": world}
        self.calls.append(("start", spec.job_id, world, tuple(devices),
                           resume))

    def evict(self, job_id):
        j = self.live.pop(job_id)
        self.saved[job_id] = j["applied"]
        self.calls.append(("evict", job_id))
        return PREEMPT_EXIT

    def shrink(self, job_id, world):
        self.live[job_id]["world"] = world
        self.calls.append(("shrink", job_id, world))

    def grow(self, job_id, world, new_devices):
        self.live[job_id]["world"] = world
        self.calls.append(("grow", job_id, world, tuple(new_devices)))

    def poll(self, job_id):
        if self.script.get(job_id):
            return self.script[job_id].pop(0)
        j = self.live[job_id]
        j["applied"] += 1
        if j["applied"] >= self.targets.get(job_id, 1 << 30):
            self.live.pop(job_id)
            return {"exit_code": 0, "applied_updates": j["applied"]}
        return {"exit_code": None, "applied_updates": j["applied"]}


def _sched(tmp_path, ctrl, pool=8, **kw):
    rec = _Recorder()
    wall_state = [0.0]

    def wall():
        wall_state[0] += 1.0
        return wall_state[0]

    kw.setdefault("log", lambda s: None)
    return FleetScheduler(str(tmp_path), pool, ctrl, events=rec, wall=wall,
                          **kw), rec


@pytest.mark.quick
class TestFleetScheduler:
    def test_three_job_preemption_scenario(self, tmp_path):
        """The drill timeline without JAX: high-priority jobC shrinks
        elastic jobA through the readmit barrier, evicts rigid jobB
        (emergency save -> resume), frees bin-pack back, everyone
        finishes at its target applied-update count."""
        targets = {"jobA": 8, "jobB": 5, "jobC": 3}
        ctrl = _ScriptedController(targets)
        sched, rec = _sched(tmp_path, ctrl)
        sched.submit(_spec("jobA", min_world=3, max_world=4,
                           target_updates=8))
        sched.submit(_spec("jobB", min_world=3, max_world=3,
                           target_updates=5))
        for t in range(32):
            if t == 3:
                sched.submit(_spec("jobC", priority=10, min_world=4,
                                   max_world=4, target_updates=3))
            sched.tick()
            if sched.idle():
                break
        assert sched.idle()
        for job_id, tgt in targets.items():
            job = sched.jobs[job_id]
            assert (job.status, job.applied) == ("done", tgt), job_id
        c = sched.counters
        assert (c["evictions"], c["shrinks"], c["readmits"]) == (1, 1, 1)
        assert c["preemptions"] == 0 and c["failures"] == 0
        assert c["finishes"] == 3 and c["restarts"] == 0
        # the evictee resumed from its emergency save, not from scratch
        assert ("start", "jobB", 3, (3, 4, 5), True) in ctrl.calls
        assert ("shrink", "jobA", 3) in ctrl.calls
        assert ("grow", "jobA", 4, (6,)) in ctrl.calls
        for kind in ("fleet_submit", "fleet_admit", "fleet_place",
                     "fleet_shrink", "fleet_evict", "fleet_readmit",
                     "fleet_finish"):
            assert kind in rec.kinds(), kind
        # shared-dir exports: job + pool records readable mid-flight
        assert fstate.read_job_record(str(tmp_path), "jobA")["status"] \
            == "done"
        pool = fstate.read_pool_record(str(tmp_path))
        assert pool["pool_size"] == 8 and pool["devices_free"] == 8
        prom = open(os.path.join(fstate.prom_dir(str(tmp_path)),
                                 "jobA.fleet.prom")).read()
        assert 'job="jobA"' in prom and "fleet_applied_updates" in prom
        assert "fleet_devices_free" in open(os.path.join(
            fstate.prom_dir(str(tmp_path)), "fleet.prom")).read()

    def test_external_preemption_requeues_without_budget_burn(self, tmp_path):
        ctrl = _ScriptedController(
            {"j": 2}, script={"j": [{"exit_code": PREEMPT_EXIT}]})
        sched, rec = _sched(tmp_path, ctrl, pool=2, max_restarts=0)
        sched.submit(_spec("j", min_world=2, max_world=2, target_updates=2))
        for _ in range(8):
            sched.tick()
            if sched.idle():
                break
        job = sched.jobs["j"]
        assert job.status == "done" and job.restarts == 0
        assert sched.counters["preemptions"] == 1
        assert "fleet_preempt" in rec.kinds()
        # requeued with resume: the second start restores
        starts = [c for c in ctrl.calls if c[0] == "start"]
        assert [s[4] for s in starts] == [False, True]

    def test_crash_burns_budget_then_fails(self, tmp_path):
        ctrl = _ScriptedController(
            {}, script={"j": [{"exit_code": 3}, {"exit_code": 3}]})
        sched, rec = _sched(tmp_path, ctrl, pool=1, max_restarts=1)
        sched.submit(_spec("j", target_updates=5))
        for _ in range(6):
            sched.tick()
        job = sched.jobs["j"]
        assert job.status == "failed" and job.restarts == 1
        assert job.exit_code == 3
        assert sched.counters["restarts"] == 1
        assert sched.counters["failures"] == 1
        assert rec.kinds().count("fleet_restart") == 1
        assert "fleet_fail" in rec.kinds()
        assert sched.pool.free_count == 1     # devices came back

    def test_unhealthy_verdict_evicts_and_restarts(self, tmp_path):
        ctrl = _ScriptedController(
            {"j": 3}, script={"j": [{"exit_code": None, "healthy": False}]})
        sched, rec = _sched(tmp_path, ctrl, pool=1, max_restarts=1)
        sched.submit(_spec("j", target_updates=3))
        for _ in range(10):
            sched.tick()
            if sched.idle():
                break
        assert ("evict", "j") in ctrl.calls   # killed, not abandoned
        job = sched.jobs["j"]
        assert job.status == "done" and job.restarts == 1
        assert "fleet_restart" in rec.kinds()

    def test_rejections(self, tmp_path):
        ctrl = _ScriptedController({"ok": 1})
        sched, rec = _sched(tmp_path, ctrl, pool=4)
        sched.submit(_spec("ok", target_updates=1))
        sched.submit(_spec("huge", min_world=5, max_world=5))
        sched.tick()
        sched.submit(_spec("ok", target_updates=1))   # duplicate job_id
        with open(os.path.join(fstate.queue_dir(str(tmp_path)),
                               "submit.bad.json"), "w") as f:
            json.dump({"spec": {"job_id": "bad", "command": []}}, f)
        sched.tick()
        assert sched.counters["rejects"] == 3
        rejected = {f["job"] for k, f in rec.events if k == "fleet_reject"}
        assert rejected == {"huge", "ok", "bad"}
        assert list(sched.jobs) == ["ok"]             # admitted exactly once
        assert fstate.pending_submissions(str(tmp_path)) == []

    def test_run_until_idle_ticks_and_sleeps(self, tmp_path):
        ctrl = _ScriptedController({"j": 2})
        sched, _ = _sched(tmp_path, ctrl, pool=1)
        sched.submit(_spec("j", target_updates=2))
        sleeps = []
        ticks = sched.run(interval_s=0.5, sleep=sleeps.append,
                          max_ticks=50, until_idle=True)
        assert sched.idle() and ticks == 3
        assert sleeps == [0.5, 0.5]           # no sleep after the idle tick


@pytest.mark.quick
class TestSpawnSupervised:
    def _capture(self):
        captured = {}

        def popen(cmd, env):
            captured["cmd"], captured["env"] = cmd, env
            return "child"

        return captured, popen

    def test_env_composition_preserves_operator_vars(self):
        captured, popen = self._capture()
        child = spawn_supervised(
            ("python", "-m", "x"), restart_count=4,
            env={"OPERATOR_VAR": "kept", "PATH": "/bin"},
            popen=popen, log=lambda s: None)
        assert child == "child"
        assert captured["cmd"] == ["python", "-m", "x"]
        env = captured["env"]
        assert env["OPERATOR_VAR"] == "kept" and env["PATH"] == "/bin"
        assert env["TCDP_RESTART_COUNT"] == "4"
        assert "TCDP_ELASTIC_DIR" not in env

    def test_extra_env_wins_and_is_str_coerced(self):
        captured, popen = self._capture()
        spawn_supervised(
            ("run",), restart_count=0, env={"TCDP_JOB_ID": "old"},
            extra_env={"TCDP_JOB_ID": "new", "TCDP_FLEET_WORLD": 4},
            popen=popen, log=lambda s: None)
        env = captured["env"]
        assert env["TCDP_JOB_ID"] == "new"
        assert env["TCDP_FLEET_WORLD"] == "4"

    def test_restart_count_is_supervisor_owned(self):
        # unlike operator vars, the incarnation is always overwritten
        captured, popen = self._capture()
        spawn_supervised(("run",), restart_count=2,
                         env={"TCDP_RESTART_COUNT": "99"},
                         popen=popen, log=lambda s: None)
        assert captured["env"]["TCDP_RESTART_COUNT"] == "2"

    def test_elastic_dir_without_epoch_leaves_rejoin_keys_alone(self,
                                                                tmp_path):
        from tpu_compressed_dp.train.rendezvous import DIR_ENV, EPOCH_ENV

        captured, popen = self._capture()
        spawn_supervised(("run",), restart_count=0,
                         elastic_dir=str(tmp_path),
                         env={EPOCH_ENV: "operator-set"},
                         popen=popen, log=lambda s: None)
        env = captured["env"]
        assert env[DIR_ENV] == str(tmp_path)
        assert env[EPOCH_ENV] == "operator-set"   # no committed epoch: kept

    def test_committed_epoch_exports_rejoin_hint(self, tmp_path):
        from tpu_compressed_dp.train.rendezvous import (ADDR_ENV, DIR_ENV,
                                                        EPOCH_ENV,
                                                        write_epoch)

        write_epoch(str(tmp_path), {"epoch": 3, "ranks": [0, 1],
                                    "address": "host:1234"})
        captured, popen = self._capture()
        logs = []
        spawn_supervised(("run",), restart_count=1,
                         elastic_dir=str(tmp_path), env={},
                         popen=popen, log=logs.append)
        env = captured["env"]
        assert env[DIR_ENV] == str(tmp_path)
        assert env[EPOCH_ENV] == "3" and env[ADDR_ENV] == "host:1234"
        assert any("world epoch 3" in m for m in logs)


class TestFleetCLI:
    def _submit(self, tmp_path, spec_dict, name="spec.json"):
        import tools.fleet as fleet_cli

        p = tmp_path / name
        p.write_text(json.dumps(spec_dict))
        return fleet_cli.main(["submit", "--fleet_dir",
                               str(tmp_path / "fleet"), "--spec", str(p)])

    def test_submit_queues_a_valid_spec(self, tmp_path, capsys):
        rc = self._submit(tmp_path, {"job_id": "a", "command": ["true"],
                                     "min_world": 1, "max_world": 2})
        assert rc == 0
        assert "queued a" in capsys.readouterr().out
        [(spec, _)] = fstate.pending_submissions(str(tmp_path / "fleet"))
        assert spec.job_id == "a" and spec.elastic

    def test_submit_bounces_a_malformed_spec(self, tmp_path, capsys):
        rc = self._submit(tmp_path, {"job_id": "a", "command": []})
        assert rc == 2
        assert "invalid spec" in capsys.readouterr().out
        assert not os.path.isdir(fstate.queue_dir(str(tmp_path / "fleet")))

    def test_status_without_a_pool_record(self, tmp_path, capsys):
        import tools.fleet as fleet_cli

        assert fleet_cli.main(["status", "--fleet_dir", str(tmp_path)]) == 2
        assert "no pool record" in capsys.readouterr().out

    def test_run_executes_real_subprocess_jobs(self, tmp_path, capsys):
        """End-to-end over real children: two trivial jobs share a
        2-device pool, finish, and land in the shared-dir records."""
        import tools.fleet as fleet_cli

        fleet_dir = str(tmp_path / "fleet")
        for job_id in ("a", "b"):
            assert self._submit(
                tmp_path,
                {"job_id": job_id,
                 "command": [sys.executable, "-c", "pass"]},
                name=f"{job_id}.json") == 0
        rc = fleet_cli.main(["run", "--fleet_dir", fleet_dir,
                             "--devices", "2", "--interval", "0.05",
                             "--max_ticks", "200", "--until_idle"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "2 finished" in out
        recs = {r["job_id"]: r for r in fstate.list_job_records(fleet_dir)}
        assert {j: r["status"] for j, r in recs.items()} \
            == {"a": "done", "b": "done"}
        pool = fstate.read_pool_record(fleet_dir)
        assert pool["devices_free"] == 2
        # fleet_* events landed in the JSONL stream
        from tpu_compressed_dp.obs.export import read_events

        kinds = {e["kind"] for e in read_events(fstate.events_path(fleet_dir))}
        assert {"fleet_admit", "fleet_place", "fleet_finish"} <= kinds

    def test_run_reports_failed_jobs_nonzero(self, tmp_path):
        import tools.fleet as fleet_cli

        fleet_dir = str(tmp_path / "fleet")
        assert self._submit(
            tmp_path,
            {"job_id": "crash",
             "command": [sys.executable, "-c", "raise SystemExit(3)"]}) == 0
        rc = fleet_cli.main(["run", "--fleet_dir", fleet_dir,
                             "--devices", "1", "--interval", "0.05",
                             "--max_ticks", "200", "--until_idle",
                             "--max_restarts", "0"])
        assert rc == 1
        [rec] = fstate.list_job_records(fleet_dir)
        assert rec["status"] == "failed" and rec["exit_code"] == 3
