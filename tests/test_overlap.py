"""Chunk-pipelined gradient sync (parallel/overlap.py): schedule-only —
``sync_overlap=K`` must be BITWISE ``sync_overlap=1`` across method ×
mode/transport × EF, through the bare engines and the fused train step,
guard included.  The AOT schedule shape (K separate collective
instructions) is pinned by the slow-marked topology test."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_compressed_dp.compat import shard_map
from tpu_compressed_dp.parallel.dp import (CompressionConfig, init_comp_state,
                                           init_ef_state, make_grad_sync,
                                           make_leaf_groups)
from tpu_compressed_dp.parallel.overlap import plan_chunks


class TestPlanChunks:
    BYTES = [512, 512, 294912, 512, 512, 589824, 1024, 1179648, 2048,
             4718592, 20480, 256, 6912]

    def test_boundaries_align_with_groups(self):
        cfg = CompressionConfig(granularity="bucketed", bucket_mb=1.0,
                                sync_overlap=3)
        plans = plan_chunks(self.BYTES, cfg)
        groups = make_leaf_groups(self.BYTES, "bucketed", 1.0 * 1024 * 1024)
        starts = {g[0] for g in groups}
        assert 1 < len(plans) <= 3
        # contiguous, exhaustive, group-aligned
        assert plans[0].leaf_lo == 0 and plans[-1].leaf_hi == len(self.BYTES)
        for a, b in zip(plans, plans[1:]):
            assert a.leaf_hi == b.leaf_lo
            assert b.leaf_lo in starts
        # global group offsets partition the group list
        assert plans[0].group_offset == 0
        assert sum(p.n_groups for p in plans) == len(groups)

    def test_clamps_to_group_count(self):
        cfg = CompressionConfig(granularity="layerwise", sync_overlap=64)
        plans = plan_chunks(self.BYTES, cfg)
        assert len(plans) == len(self.BYTES)  # one leaf per group

    def test_entiremodel_degrades_to_one_chunk(self):
        cfg = CompressionConfig(granularity="entiremodel", sync_overlap=8)
        plans = plan_chunks(self.BYTES, cfg)
        assert len(plans) == 1

    def test_rejects_bad_overlap(self):
        with pytest.raises(ValueError, match="sync_overlap"):
            CompressionConfig(sync_overlap=0)


def _grads(n_leaves=5, seed=0):
    k = jax.random.key(seed)
    sizes = [3000, 50, 2000, 700, 1200][:n_leaves]
    return {f"p{i:02d}": jax.random.normal(jax.random.fold_in(k, i), (8, n))
            for i, n in enumerate(sizes)}


def _run_sync(mesh, cfg, grads, seed=0):
    sync = make_grad_sync(cfg, "data")
    g0 = jax.tree.map(lambda g: g[0], grads)
    ef = init_ef_state(g0, cfg)
    comp = init_comp_state(g0, cfg)
    fn = shard_map(
        lambda g, e, c: sync(jax.tree.map(lambda x: x[0], g), e, c,
                             jax.random.key(seed)),
        mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P("data"), grads), P(), P()),
        out_specs=(P(), P(), P(), P()), check_vma=False)
    return fn(grads, ef, comp)


def _assert_bitwise(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert np.array_equal(np.asarray(x), np.asarray(y))


# Tier-1 keeps one simulate and one wire representative; the heavy-compile
# transports (sharded unrolls its full route/reduce/return machinery per
# group: ~30-85 s CPU compile) and the rest of the method matrix run in the
# slow-marked full cross-product below, keeping tier-1 inside its 870 s
# budget.
QUICK_CASES = [
    dict(method="topk", ratio=0.25, granularity="bucketed", bucket_mb=0.05,
         mode="wire", transport="allgather", error_feedback=True),
]
SLOW_CASES = [
    # the simulate-mode row mirrors the wire row above (~26 s of the
    # tier-1 budget); the wire transport is the shipped hot path
    dict(method="topk", ratio=0.25, granularity="layerwise",
         error_feedback=True),
    dict(method=None, granularity="bucketed", bucket_mb=0.01),
    dict(method="topk", ratio=0.25, granularity="bucketed", bucket_mb=0.1,
         mode="wire", transport="sharded", error_feedback=True),
    dict(method="powersgd", rank=2, granularity="bucketed", bucket_mb=0.01,
         error_feedback=True),
    dict(method="topk", ratio=0.25, granularity="bucketed", bucket_mb=0.01,
         mode="wire", transport="allgather", error_feedback=True),
    dict(method="randomk", ratio=0.25, granularity="bucketed",
         bucket_mb=0.01, mode="wire", error_feedback=True),
    dict(method="randomk", ratio=0.25, granularity="layerwise",
         shared_mask=False),
    dict(method="blocktopk", ratio=0.25, block_size=64,
         granularity="bucketed", bucket_mb=0.01, mode="wire",
         error_feedback=True),
    dict(method="thresholdv", threshold=0.5, granularity="bucketed",
         bucket_mb=0.01, mode="wire", error_feedback=True),
    dict(method="qsgd", granularity="layerwise"),
    dict(method="terngrad", granularity="bucketed", bucket_mb=0.01),
    dict(method="topk", ratio=0.25, granularity="entiremodel",
         error_feedback=True),
]


class TestChunkedSyncBitwise:
    """sync_overlap=K vs =1 through the real engines on the 8-dev mesh."""

    def _check(self, mesh8, case, k=3):
        base = CompressionConfig(sync_overlap=1, **case)
        chunked = CompressionConfig(sync_overlap=k, **case)
        grads = _grads()
        o1, e1, c1, s1 = _run_sync(mesh8, base, grads)
        oK, eK, cK, sK = _run_sync(mesh8, chunked, grads)
        _assert_bitwise((o1, e1, c1), (oK, eK, cK))
        # collective count is granularity's, not K's: chunking must not
        # add or drop reduction groups
        assert float(s1["num_collectives"]) == float(sK["num_collectives"])

    @pytest.mark.parametrize("case", QUICK_CASES,
                             ids=lambda c: f"{c.get('method')}-"
                                           f"{c.get('mode', 'sim')}")
    def test_quick_matrix(self, mesh8, case):
        self._check(mesh8, case)

    @pytest.mark.slow
    @pytest.mark.parametrize("case", SLOW_CASES,
                             ids=lambda c: f"{c.get('method')}-"
                                           f"{c.get('mode', 'sim')}-"
                                           f"{c.get('granularity')}")
    def test_full_matrix(self, mesh8, case):
        self._check(mesh8, case)

    @pytest.mark.slow
    def test_many_chunks(self, mesh8):
        self._check(mesh8, QUICK_CASES[0], k=5)  # k == n_leaves (layerwise)


def _build_step(mesh, cfg, *, guard_cfg=None, chaos=None, clip_sent=0.0):
    import flax.linen as nn

    from tpu_compressed_dp.models.common import init_model, make_apply_fn
    from tpu_compressed_dp.train.guard import init_guard_state
    from tpu_compressed_dp.train.optim import SGD
    from tpu_compressed_dp.train.state import TrainState
    from tpu_compressed_dp.train.step import make_train_step

    class TinyMLP(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(16)(x))
            x = nn.relu(nn.Dense(16)(x))
            return nn.Dense(4)(x)

    module = TinyMLP()
    params, stats = init_model(module, jax.random.key(0),
                               jnp.zeros((1, 4, 4, 3), jnp.float32))
    opt = SGD(lr=lambda s: 0.05 / (1.0 + 0.1 * s.astype(jnp.float32)),
              momentum=0.9, nesterov=True, weight_decay=5e-4)
    n = mesh.shape["data"]
    state = TrainState.create(
        params, stats, opt.init(params), init_ef_state(params, cfg, n),
        jax.random.key(1), comp=init_comp_state(params, cfg, n),
        guard=init_guard_state(guard_cfg))
    step = make_train_step(make_apply_fn(module), opt, cfg, mesh,
                           guard_cfg=guard_cfg, chaos=chaos,
                           clip_sent_norm=clip_sent, donate=False)
    return state, step


def _batch(n=32, seed=0):
    rng = np.random.RandomState(seed)
    return {"input": jnp.asarray(rng.randn(n, 4, 4, 3).astype(np.float32)),
            "target": jnp.asarray(rng.randint(0, 4, n).astype(np.int32))}


def _run_steps(mesh, cfg, steps=3, **kw):
    state, step = _build_step(mesh, cfg, **kw)
    batch = _batch()
    metrics = None
    for _ in range(steps):
        state, metrics = step(state, batch)
    return state, metrics


class TestFusedStepBitwise:
    """The per-chunk optimizer interleave (make_overlap_sync_apply) against
    the single-dispatch step: whole TrainState bitwise after 3 steps."""

    def test_fused_step_matches(self, mesh8):
        case = dict(method="topk", ratio=0.25, granularity="layerwise",
                    error_feedback=True)
        s1, m1 = _run_steps(mesh8, CompressionConfig(sync_overlap=1, **case))
        sK, mK = _run_steps(mesh8, CompressionConfig(sync_overlap=3, **case))
        _assert_bitwise(
            (s1.params, s1.opt_state, s1.ef, s1.comp, s1.batch_stats),
            (sK.params, sK.opt_state, sK.ef, sK.comp, sK.batch_stats))
        assert float(m1["loss"]) == float(mK["loss"])
        assert float(m1["lr"]) == float(mK["lr"])

    def test_guarded_chaos_step_matches_and_holds(self, mesh8):
        """Vote-once-then-chunk: a vetoed step under sync_overlap=K holds
        params/opt/ef bitwise exactly like K=1, and the two guarded runs
        stay bitwise equal through the veto."""
        from tpu_compressed_dp.train.guard import GuardConfig
        from tpu_compressed_dp.utils.chaos import ChaosConfig

        case = dict(method="topk", ratio=0.25, granularity="layerwise",
                    error_feedback=True)
        gcfg = GuardConfig(loss_scaling=False)
        chaos = ChaosConfig(kind="nan", target="grads", steps=(1,), worker=3)
        s1, m1 = _run_steps(mesh8, CompressionConfig(sync_overlap=1, **case),
                            guard_cfg=gcfg, chaos=chaos)
        sK, mK = _run_steps(mesh8, CompressionConfig(sync_overlap=3, **case),
                            guard_cfg=gcfg, chaos=chaos)
        assert float(m1["guard/skipped"]) == float(mK["guard/skipped"]) == 1.0
        _assert_bitwise(
            (s1.params, s1.opt_state, s1.ef, s1.guard),
            (sK.params, sK.opt_state, sK.ef, sK.guard))

    @pytest.mark.slow
    def test_clip_sent_falls_back_and_matches(self, mesh8):
        """clip_sent_norm needs the global synced norm: the step keeps the
        chunked sync but applies the whole-tree update — still bitwise."""
        case = dict(method="topk", ratio=0.25, granularity="layerwise",
                    error_feedback=True)
        s1, _ = _run_steps(mesh8, CompressionConfig(sync_overlap=1, **case),
                           clip_sent=0.5)
        sK, _ = _run_steps(mesh8, CompressionConfig(sync_overlap=3, **case),
                           clip_sent=0.5)
        _assert_bitwise((s1.params, s1.opt_state, s1.ef),
                        (sK.params, sK.opt_state, sK.ef))


@pytest.mark.slow
class TestAOTSchedule:
    """The schedule-shape acceptance: sync_overlap=K emits K separate chunk
    collectives in the production-TPU AOT schedule (the combiner merged
    them to ONE before — benchmarks/overlap_hlo_r5.txt)."""

    def test_chunk_collectives_stay_separate(self):
        pytest.importorskip("jax.experimental.topologies")
        from jax.experimental import topologies

        import tools.overlap_evidence as ev

        try:
            topo = topologies.get_topology_desc(platform="tpu",
                                                topology_name="v5e:2x4")
        except Exception as e:  # no TPU compiler support in this build
            pytest.skip(f"AOT TPU topology unavailable: {e}")
        mesh = topologies.make_mesh(topo, (8,), ("data",))
        step, state_s, batch_s = ev.build_step("bucketed", None, mesh,
                                               overlap=4, bucket_mb=4.0)
        txt = ev.compile_text(jax.jit(step).lower(state_s, batch_s))
        rows, total_c, _ = ev.schedule_stats(txt)
        chunks = {r["chunk"] for r in rows if r["chunk"] != "-"}
        # at least two distinct chunk-scoped collective instructions
        # survived scheduling un-merged
        assert len(chunks) >= 2, rows
