"""Model zoo shape/param tests (architecture parity with the reference)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_compressed_dp.models import alexnet, resnet, resnet9, vgg
from tpu_compressed_dp.models.common import init_model, make_apply_fn


def n_params(tree):
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(tree))


@pytest.mark.parametrize(
    "module,img,ncls",
    [
        (resnet9.ResNet9(), 32, 10),
        (resnet9.AlexNetGraph(), 32, 10),
        (alexnet.AlexNet(), 32, 10),
        pytest.param(vgg.vgg16(), 32, 10, marks=pytest.mark.slow),
        # vgg16 forward is ~25 s of conv compile on the 1-core CPU host;
        # its construction/param-count contract stays tier-1 below
    ],
    ids=["resnet9", "alexnet_graph", "alexnet_module", "vgg16"],
)
def test_cifar_models_forward(module, img, ncls):
    params, stats = init_model(module, jax.random.key(0), jnp.zeros((1, img, img, 3)))
    apply_fn = make_apply_fn(module)
    x = jax.random.normal(jax.random.key(1), (4, img, img, 3))
    logits, _ = apply_fn(params, stats, x, False, {})
    assert logits.shape == (4, ncls)
    logits_t, new_stats = apply_fn(params, stats, x, True, {"dropout": jax.random.key(2)})
    assert logits_t.shape == (4, ncls)


def test_resnet9_param_count():
    """DAWNBench ResNet-9 has ~6.57M params (reference architecture)."""
    params, _ = init_model(resnet9.ResNet9(), jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    n = n_params(params)
    assert 6.4e6 < n < 6.8e6, n


@pytest.mark.slow
def test_vgg16_matches_torchvision_param_count():
    """VGG-16 (no BN), 10 classes, 7x7 adaptive pool: same layer dims as
    torchvision => 134.3M params (1000-class version also checked).
    Slow-marked: building the 134M-param tree costs ~15 s of the tier-1
    budget for a pure count check."""
    params, _ = init_model(vgg.vgg16(), jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    n = n_params(params)
    # torchvision vgg16 w/ 1000 classes = 138_357_544; with 10 classes:
    expected = 138_357_544 - (4096 * 1000 + 1000) + (4096 * 10 + 10)
    assert n == expected, (n, expected)


@pytest.mark.slow  # ~8 s build; forward-shape row keeps resnet50 quick coverage
def test_resnet50_param_count():
    params, _ = init_model(
        resnet.resnet50(num_classes=1000), jax.random.key(0), jnp.zeros((1, 64, 64, 3))
    )
    n = n_params(params)
    assert n == 25_557_032, n  # torchvision resnet50 reference count


def test_resnet50_bn0_init():
    """--init-bn0: last BN gamma of every block zero (`resnet.py:154-160`)."""
    params, _ = init_model(
        resnet.resnet50(num_classes=10, bn0=True), jax.random.key(0), jnp.zeros((1, 64, 64, 3))
    )
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    zeroed = [
        p for path, p in flat
        if any(getattr(k, "key", "") == "bn3" for k in path)
        and any(getattr(k, "key", "") == "scale" for k in path)
    ]
    assert len(zeroed) == 16  # 3+4+6+3 bottleneck blocks
    for z in zeroed:
        np.testing.assert_allclose(np.asarray(z), 0.0)


def test_resnet50_forward_shape():
    m = resnet.resnet50(num_classes=7)
    params, stats = init_model(m, jax.random.key(0), jnp.zeros((1, 64, 64, 3)))
    logits, _ = make_apply_fn(m)(params, stats, jnp.zeros((2, 64, 64, 3)), False, {})
    assert logits.shape == (2, 7)


def test_adaptive_avg_pool_torch_semantics():
    # tiling when input < output (1x1 -> 7x7) and identity at equal size
    x = jnp.arange(4.0).reshape(1, 1, 1, 4)
    out = vgg.adaptive_avg_pool(x, 7)
    assert out.shape == (1, 7, 7, 4)
    np.testing.assert_allclose(np.asarray(out[0, 3, 3]), np.arange(4.0))
    x2 = jax.random.normal(jax.random.key(0), (2, 7, 7, 3))
    np.testing.assert_allclose(np.asarray(vgg.adaptive_avg_pool(x2, 7)), np.asarray(x2), rtol=1e-6)


def test_resnet9_classifier_scale():
    """Logits are scaled by 0.125 (`Mul(weight)`, `dawn.py:54,70`)."""
    m1 = resnet9.ResNet9(classifier_weight=0.125)
    m2 = resnet9.ResNet9(classifier_weight=1.0)
    params, stats = init_model(m1, jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    l1, _ = make_apply_fn(m1)(params, stats, x, False, {})
    l2, _ = make_apply_fn(m2)(params, stats, x, False, {})
    np.testing.assert_allclose(np.asarray(l1) * 8.0, np.asarray(l2), rtol=1e-5)
