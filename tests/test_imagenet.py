"""ImageNet-side tests: data pipeline, phase schedule, checkpoint, harness e2e.

The reference had no tests (SURVEY.md §4); these cover the behaviors its
manual protocol relied on: DistValSampler equal-batch-count, rect-val AR
bucketing, progressive-resize phase swaps, Scheduler LR values, and
checkpoint/resume (including the EF residual the reference failed to save).
"""

import numpy as np
import pytest

# ~2 min of ResNet compiles on the 1-core CI host: excluded from the 870 s
# tier-1 budget (`-m 'not slow'`), runs in the unfiltered suite
pytestmark = pytest.mark.slow

import jax
import jax.numpy as jnp

from tpu_compressed_dp.data import imagenet as inet
from tpu_compressed_dp.train import schedules


def test_synthetic_images_interface():
    ds = inet.SyntheticImages(16, num_classes=10, seed=0)
    assert len(ds) == 16
    w, h = ds.size(3)
    img = ds.load(3)
    assert img.size == (w, h)
    assert 0 <= ds.label(3) < 10


def test_train_loader_shapes_and_determinism():
    ds = inet.SyntheticImages(64, num_classes=10)
    dl = inet.TrainLoader(ds, 16, 32, seed=3, workers=2)
    batches = list(dl)
    assert len(batches) == len(dl) == 4
    for b in batches:
        assert b["input"].shape == (16, 32, 32, 3)
        assert b["input"].dtype == np.uint8
        assert b["target"].shape == (16,)
    # same epoch -> same batches; next epoch -> reshuffled
    again = list(dl)
    np.testing.assert_array_equal(batches[0]["input"], again[0]["input"])
    dl.set_epoch(1)
    assert not np.array_equal(batches[0]["target"], list(dl)[0]["target"])


def test_val_loader_equal_batch_count_across_processes():
    # DistValSampler contract (`dataloader.py:133-161`): every process yields
    # the same number of batches even when it runs out of images.
    ds = inet.SyntheticImages(50, num_classes=10)
    loaders = [
        inet.ValLoader(ds, 8, 32, process_index=i, process_count=4, workers=2)
        for i in range(4)
    ]
    counts = [len(list(l)) for l in loaders]
    assert counts == [loaders[0].expected_num_batches] * 4
    total = sum(len(b["target"]) for l in loaders for b in l)
    assert total == 50  # every image seen exactly once


def test_rect_val_falls_back_to_square_multihost(monkeypatch):
    # Rect-val hands each process differently-shaped local batches — fine
    # under the reference's per-process NCCL (`dataloader.py:133-175`),
    # incompatible with one global SPMD array.  Pin the documented fallback
    # (VERDICT r1 weak #8): multi-process phases silently request square val.
    from tpu_compressed_dp.harness.imagenet import PhaseData

    ds_t = inet.SyntheticImages(64, num_classes=10)
    ds_v = inet.SyntheticImages(32, num_classes=10)
    phases = [{"ep": 0, "sz": 32, "bs": 16, "rect_val": True}]

    pd = PhaseData(ds_t, ds_v, phases, workers=1)
    pd.set_epoch(0)
    assert pd.val_loader.rect_val  # single-process: rect honoured

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    pd2 = PhaseData(ds_t, ds_v, phases, workers=1)
    pd2.set_epoch(0)
    assert not pd2.val_loader.rect_val  # multi-host: square fallback


def test_val_loader_rect_shapes_bounded():
    ds = inet.SyntheticImages(64, num_classes=10)
    dl = inet.ValLoader(ds, 8, 32, rect_val=True, ar_buckets=4, workers=2)
    shapes = set()
    ars = []
    for b in dl:
        if len(b["target"]):
            shapes.add(b["input"].shape[1:3])
            ars.append(b["input"].shape[2] / b["input"].shape[1])
    assert len(shapes) <= 4  # palette bounds compile count
    assert ars == sorted(ars)  # AR-ascending batch order (sort_ar semantics)


def test_val_batch_size_rule():
    # `train_imagenet_nv.py:592-597`
    assert inet.val_batch_size(128, 512) == 512
    assert inet.val_batch_size(128, 64) == 512
    assert inet.val_batch_size(224, 224) == 256
    assert inet.val_batch_size(288, 128) == 128
    assert inet.val_batch_size(288, 512) == 512


def test_epoch_from_steps_and_variable_bs_lr():
    # 2 epochs at 10 steps, then 2 at 5 (bs doubled): LR-vs-epoch must not care
    to_epoch = schedules.epoch_from_steps([10, 10, 5, 5])
    assert float(to_epoch(0.0)) == 0.0
    assert float(to_epoch(10.0)) == 1.0
    assert float(to_epoch(25.0)) == 3.0
    assert float(to_epoch(27.5)) == pytest.approx(3.5)
    phases = [{"ep": (0, 2), "lr": (0.0, 1.0)}, {"ep": 2, "lr": 0.5},
              {"ep": (3, 4), "lr": (0.5, 0.0)}]
    lr = schedules.phase_lr_schedule_variable_bs(phases, [10, 10, 5, 5])
    assert float(lr(10.0)) == pytest.approx(0.5)   # epoch 1 of the ramp
    assert float(lr(22.0)) == pytest.approx(0.5)   # constant phase
    assert float(lr(30.0)) == pytest.approx(0.0)   # annealed to zero


class TestCheckpoint:
    def _tiny_state(self, ef=True):
        from tpu_compressed_dp.parallel.dp import CompressionConfig, init_ef_state
        from tpu_compressed_dp.train.optim import SGD
        from tpu_compressed_dp.train.state import TrainState

        params = {"w": jnp.arange(6.0).reshape(2, 3), "b": jnp.ones((3,))}
        opt = SGD(lr=0.1, momentum=0.9)
        cfg = CompressionConfig(method="randomk", ratio=0.5, error_feedback=ef)
        return TrainState.create(
            params, {}, opt.init(params), init_ef_state(params, cfg, 2),
            jax.random.key(5),
        )

    def test_roundtrip_with_ef(self, tmp_path):
        from tpu_compressed_dp.utils.checkpoint import restore_checkpoint, save_checkpoint
        import dataclasses

        state = self._tiny_state()
        state = dataclasses.replace(
            state,
            step=jnp.asarray(17, jnp.int32),
            ef=jax.tree.map(lambda e: e + 2.5, state.ef),
        )
        save_checkpoint(str(tmp_path / "ck"), state, {"epoch": 3})
        blank = self._tiny_state()
        restored, meta = restore_checkpoint(str(tmp_path / "ck"), blank)
        assert int(restored.step) == 17
        assert meta["epoch"] == 3
        jax.tree.map(np.testing.assert_allclose, restored.params, state.params)
        jax.tree.map(np.testing.assert_allclose, restored.ef, state.ef)  # EF saved!
        np.testing.assert_array_equal(
            jax.random.key_data(restored.rng), jax.random.key_data(state.rng)
        )

    def test_roundtrip_no_ef(self, tmp_path):
        from tpu_compressed_dp.utils.checkpoint import restore_checkpoint, save_checkpoint

        state = self._tiny_state(ef=False)
        assert state.ef == ()
        save_checkpoint(str(tmp_path / "ck"), state)
        restored, _ = restore_checkpoint(str(tmp_path / "ck"), self._tiny_state(ef=False))
        assert restored.ef == ()

    def test_save_if_best_gating(self, tmp_path):
        from tpu_compressed_dp.utils.checkpoint import Checkpointer

        ckpt = Checkpointer(str(tmp_path / "ck"))
        s = self._tiny_state()
        assert ckpt.save_if_best(s, 50.0)
        assert not ckpt.save_if_best(s, 49.0)   # not an improvement
        assert not ckpt.save_if_best(s, 50.0)   # ties don't save
        assert ckpt.save_if_best(s, 60.0)
        assert not ckpt.save_if_best(s, 93.0, floor=94.0)  # below floor
        ckpt.close()


def test_imagenet_harness_e2e(tmp_path):
    """Full smoke: synthetic data, progressive resize (64->96 px with rect
    val), bf16 resnet18, layer-wise Top-K + EF, checkpoint every improvement,
    then resume for the last epoch."""
    from tpu_compressed_dp.harness import imagenet as h

    argv = [
        "--synthetic", "--synthetic_n", "96", "--num_classes", "8",
        "--arch", "resnet18", "--width", "16",
        "--compress", "layerwise", "--method", "topk", "--ratio", "0.1",
        "--error_feedback", "--no_bn_wd", "--init_bn0",
        "--short_epoch", "--workers", "2", "--seed", "11",
        "--checkpoint_dir", str(tmp_path / "ck"),
    ]
    summary = h.main(argv)
    assert summary["epoch"] == 2  # smoke schedule runs epochs 0..2
    assert np.isfinite(summary["train loss"])
    assert 0 < summary["sent frac"] < 0.12  # topk k=0.1 (+ tiny-tensor rounding)

    # resume from the stored checkpoint and run evaluate-only
    stats = h.main(argv + ["--resume", str(tmp_path / "ck"), "--evaluate"])
    assert stats["count"] > 0


def _make_image_tree(root, n_classes=3, per_class=8, seed=0):
    """Write a torchvision-layout tree with varied sizes/ARs to disk."""
    import os

    from PIL import Image as PILImage

    rng = np.random.default_rng(seed)
    for ci in range(n_classes):
        cdir = root / f"class_{ci:02d}"
        os.makedirs(cdir, exist_ok=True)
        for j in range(per_class):
            w = int(rng.integers(24, 72))
            h = int(rng.integers(24, 72))
            arr = np.full((h, w, 3), 40 * ci + 20, np.uint8)
            arr += rng.integers(0, 20, arr.shape).astype(np.uint8)
            PILImage.fromarray(arr).save(cdir / f"img_{j:03d}.png")


class TestImageFolderSizeCache:
    def test_cold_scan_then_warm_load(self, tmp_path, monkeypatch):
        """VERDICT r2 #7: the AR index persists; a warm start opens ZERO
        image files for size planning."""
        from tpu_compressed_dp.data import imagenet as inet

        _make_image_tree(tmp_path / "train")
        ds = inet.ImageFolder(str(tmp_path / "train"))
        wh = ds.sizes_bulk()
        assert wh.shape == (24, 2)
        cache = tmp_path / "train" / inet.ImageFolder.SIZE_CACHE
        assert cache.exists()

        # warm: a fresh instance must satisfy sizes_bulk from the cache only
        ds2 = inet.ImageFolder(str(tmp_path / "train"))
        opens = []
        real_open = inet.Image.open
        monkeypatch.setattr(inet.Image, "open",
                            lambda *a, **k: opens.append(a) or real_open(*a, **k))
        wh2 = ds2.sizes_bulk()
        assert opens == []
        np.testing.assert_array_equal(np.asarray(wh), np.asarray(wh2))
        # and size(i) agrees with a direct header read
        with real_open(ds2.samples[5][0]) as im:
            assert ds2.size(5) == im.size

    def test_stale_cache_rescans(self, tmp_path):
        from PIL import Image as PILImage

        from tpu_compressed_dp.data import imagenet as inet

        _make_image_tree(tmp_path / "train")
        ds = inet.ImageFolder(str(tmp_path / "train"))
        ds.sizes_bulk()
        # add a file: the sample list changes, cache must be ignored
        extra = tmp_path / "train" / "class_00" / "img_zzz.png"
        PILImage.fromarray(np.zeros((10, 30, 3), np.uint8)).save(extra)
        ds2 = inet.ImageFolder(str(tmp_path / "train"))
        wh = ds2.sizes_bulk()
        assert wh.shape == (25, 2)
        idx = [i for i, (p, _) in enumerate(ds2.samples)
               if p.endswith("img_zzz.png")][0]
        assert ds2.size(idx) == (30, 10)

    def test_readonly_root_falls_back_to_home_cache(self, tmp_path, monkeypatch):
        # chmod can't model a read-only mount when tests run as root (root
        # bypasses permission bits) — fail the in-tree write directly
        from tpu_compressed_dp.data import imagenet as inet

        _make_image_tree(tmp_path / "train")
        monkeypatch.setenv("HOME", str(tmp_path / "home"))
        root = str(tmp_path / "train")
        ds = inet.ImageFolder(root)
        real_savez = np.savez_compressed

        def savez(path, **kw):
            if str(path).startswith(root):
                raise OSError(30, "Read-only file system", str(path))
            return real_savez(path, **kw)

        monkeypatch.setattr(np, "savez_compressed", savez)
        ds.sizes_bulk()
        home_caches = list((tmp_path / "home").rglob("sizes-*.npz"))
        assert len(home_caches) == 1
        ds2 = inet.ImageFolder(root)
        assert ds2._load_size_cache() is not None


def test_imagenet_harness_e2e_imagefolder(tmp_path):
    """On-disk ImageFolder end-to-end (VERDICT r2 #7): train + rect-val
    through the smoke schedule's two image sizes, driven by real files."""
    from tpu_compressed_dp.harness import imagenet as h

    _make_image_tree(tmp_path / "data" / "train", per_class=32)
    _make_image_tree(tmp_path / "data" / "validation", per_class=8, seed=5)
    import json

    phases = [
        {"ep": 0, "sz": 64, "bs": 32},
        {"ep": [0, 1], "lr": [0.1, 0.2]},
        {"ep": 1, "lr": 0.1},
        {"ep": 2, "sz": 96, "bs": 16, "rect_val": True},
        {"ep": [2, 3], "lr": [0.01, 0.001]},
    ]
    argv = [
        str(tmp_path / "data"),
        "--phases", json.dumps(phases),
        "--num_classes", "3", "--arch", "resnet18", "--width", "16",
        "--short_epoch", "--workers", "2", "--seed", "3",
    ]
    summary = h.main(argv)
    assert summary["epoch"] == 2  # smoke schedule: 64px then 96px rect-val
    assert np.isfinite(summary["train loss"])
    assert summary["top5"] >= 0.0
    # the rect-val planning persisted its AR index next to the data
    from tpu_compressed_dp.data.imagenet import ImageFolder

    assert (tmp_path / "data" / "validation" / ImageFolder.SIZE_CACHE).exists()
